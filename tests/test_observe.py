"""Fleet observability plane (ISSUE 16): cross-process tracing, live
metrics, SLO burn rates, crash flight recorder.

The contracts pinned here:

- a trace context attached to a request survives the wire roundtrip
  (``pack_request`` → ``unpack_request_ex``) bit-exactly;
- the deterministic sampler honors its rate exactly (no RNG — sampling is
  a property of the rate, not of luck);
- mergeable histograms merge cross-process snapshots by addition and keep
  sane quantiles;
- a traced request through a live fleet yields ONE merged trace whose
  critical-path stage sum reconciles with the measured end-to-end latency
  by construction, stamped with the served model version;
- a shed request's trace carries the shed-decision event;
- a replica killed mid-replay yields a single merged trace showing the
  reroute — no orphan spans;
- a SUBPROCESS fleet merges client + router + child-replica spans into
  one trace spanning >= 3 processes, and a SIGKILL'd child leaves a
  flight-recorder dump (collected by the supervisor, persisted to disk,
  unfinished child spans adopted as "lost" stubs);
- the multiwindow SLO burn-rate monitor alerts only when BOTH windows
  burn, fires on entering alert state only, and notifies subscribers;
- per-bucket admission-error histograms break the projection error down
  by bucket;
- the HTTP metrics plane serves Prometheus text and the JSON snapshot the
  ``python -m photon_tpu.telemetry.live`` console renders;
- a rollout under an ambient trace (the online publish path) links
  publish → rollout → probe spans into one trace;
- the report renderer draws the "Fleet traces / SLOs" section.
"""

from __future__ import annotations

import json
import os
import signal
import time
import types

import numpy as np
import pytest

from photon_tpu.data.synthetic import make_game_dataset
from photon_tpu.fault.injection import FaultPlan, set_plan
from photon_tpu.game.model import (
    FixedEffectModel,
    GameModel,
    RandomEffectModel,
)
from photon_tpu.models.glm import Coefficients, model_for_task
from photon_tpu.serving import (
    AsyncScoringClient,
    FleetObserver,
    ObservePolicy,
    RequestShedError,
    ServingFleet,
    Slo,
    SloMonitor,
    SupervisorPolicy,
    build_requests,
    host_score_request,
    request_spec_for_dataset,
)
from photon_tpu.serving.transport import pack_request, unpack_request_ex
from photon_tpu.telemetry import TelemetrySession
from photon_tpu.telemetry.distributed import (
    FlightRecorder,
    MergeableHistogram,
    SpanRecord,
    TraceContext,
    TraceSampler,
    activate_trace,
    attach_trace,
    new_trace_id,
    trace_of,
)


@pytest.fixture(autouse=True)
def _no_fault_plan():
    yield
    set_plan(None)


def _fixture(seed=3, n_entities=40, fixed_dim=6, random_dim=4):
    data, _ = make_game_dataset(
        n_entities, 4, fixed_dim, random_dim, seed=seed
    )
    rng = np.random.default_rng(seed)
    keys = np.unique(data.id_columns["re0"])
    model = GameModel(
        coordinates={
            "fixed": FixedEffectModel(
                model_for_task("logistic_regression", Coefficients(
                    rng.standard_normal(fixed_dim).astype(np.float32)
                )),
                "global",
            ),
            "per_entity": RandomEffectModel(
                table=rng.standard_normal(
                    (len(keys), random_dim)
                ).astype(np.float32),
                keys=keys, entity_column="re0", shard_name="re0",
                task_type="logistic_regression",
            ),
        },
        task_type="logistic_regression",
    )
    return model, data


def _retrained(model: GameModel, seed: int) -> GameModel:
    rng = np.random.default_rng(seed)
    fixed = model.coordinates["fixed"]
    per_entity = model.coordinates["per_entity"]
    means = np.asarray(fixed.coefficients.means)
    return GameModel(
        coordinates={
            "fixed": FixedEffectModel(
                model_for_task(model.task_type, Coefficients(
                    (means + rng.standard_normal(means.shape)).astype(
                        np.float32
                    )
                )),
                fixed.shard_name,
            ),
            "per_entity": RandomEffectModel(
                table=rng.standard_normal(
                    (per_entity.num_entities, per_entity.dim)
                ).astype(np.float32),
                keys=per_entity.keys,
                entity_column=per_entity.entity_column,
                shard_name=per_entity.shard_name,
                task_type=model.task_type,
            ),
        },
        task_type=model.task_type,
    )


def _observed_fleet(model, data, session, replicas=2, **kwargs):
    fleet = ServingFleet(
        model, replicas=replicas,
        request_spec=request_spec_for_dataset(model, data),
        max_batch=16, max_delay_s=0.001, telemetry=session,
        **kwargs,
    ).warmup()
    observer = fleet.observe(start=False)
    return fleet, observer


def _trace_with_span(collector, name):
    for tid in reversed(collector.trace_ids()):
        if any(d.get("name") == name for d in collector.trace(tid)):
            return tid
    return None


# -- wire + primitives --------------------------------------------------------

def test_trace_context_rides_the_wire():
    (req,) = build_requests(*(_fixture(seed=5)[::-1]), [3])
    ctx = TraceContext(new_trace_id(), "abcd1234", True)
    attach_trace(req, ctx)
    got, deadline, seq = unpack_request_ex(pack_request(req, seq=7))
    assert seq == 7
    got_ctx = trace_of(got)
    assert got_ctx is not None
    assert got_ctx.trace_id == ctx.trace_id
    assert got_ctx.span_id == ctx.span_id
    # An untraced request stays untraced across the wire.
    (bare,) = build_requests(*(_fixture(seed=5)[::-1]), [3])
    got2, _, _ = unpack_request_ex(pack_request(bare))
    assert trace_of(got2) is None


def test_sampler_is_deterministic_and_exact():
    sampler = TraceSampler(0.25)
    picks = [sampler.should_sample() for _ in range(100)]
    assert picks[0] is True  # the first request always samples
    # The accumulator crosses 1.0 every 4th request thereafter: the count
    # is exact, not probabilistic.
    assert sum(picks) == 26
    twin = TraceSampler(0.25)
    assert picks == [twin.should_sample() for _ in range(100)]
    assert all(TraceSampler(1.0).should_sample() for _ in range(10))
    assert not any(TraceSampler(0.0).should_sample() for _ in range(10))


def test_mergeable_histogram_merges_across_snapshots():
    a, b = MergeableHistogram(), MergeableHistogram()
    for v in (0.001, 0.002, 0.004):
        a.observe(v)
    for v in (0.1, 0.2):
        b.observe(v)
    merged = MergeableHistogram.merged([a.snapshot(), b.snapshot()])
    assert merged.count == 5
    assert 0.0005 <= merged.quantile(0.5) <= 0.02
    assert merged.quantile(0.99) >= 0.05


def test_flight_recorder_ring_bounded_and_dump_roundtrip(tmp_path):
    ring = FlightRecorder("r0", capacity=4)
    for i in range(10):
        ring.record("event", i=i)
    snap = ring.snapshot()
    assert len(snap["records"]) == 4
    assert snap["records_total"] == 10
    assert [r["i"] for r in snap["records"]] == [6, 7, 8, 9]
    path = str(tmp_path / "r0.flight.json")
    ring.dump(path)
    loaded = FlightRecorder.load(path)
    assert loaded["owner"] == "r0"
    assert [r["i"] for r in loaded["records"]] == [6, 7, 8, 9]
    assert FlightRecorder.load(str(tmp_path / "missing.json")) is None


# -- SLO burn rates -----------------------------------------------------------

def test_slo_multiwindow_burn_alerts_once_and_notifies():
    clock = types.SimpleNamespace(t=1000.0)
    session = TelemetrySession("test-slo")
    monitor = SloMonitor(
        [Slo("p99_latency", "latency", objective=0.1, budget=0.01,
             fast_window_s=5.0, slow_window_s=60.0,
             fast_burn=14.0, slow_burn=2.0)],
        telemetry=session, clock=lambda: clock.t,
    )
    seen = []
    monitor.subscribe(seen.append)
    # Healthy traffic: no alert even after many evaluations.
    for _ in range(50):
        monitor.observe_request("ok", 0.01)
        clock.t += 0.05
    assert monitor.evaluate() == []
    # A latency cliff: every request blows the objective — both windows
    # burn and the alert fires exactly once while the state persists.
    for _ in range(50):
        monitor.observe_request("ok", 0.5)
        clock.t += 0.05
    fired = monitor.evaluate()
    assert len(fired) == 1 and fired[0]["slo"] == "p99_latency"
    assert monitor.evaluate() == []  # still alerting — not re-fired
    assert seen == fired
    gauges = {
        (m["labels"]["slo"], m["labels"]["window"]): m["value"]
        for m in session.registry.snapshot()["gauges"]
        if m["name"] == "slo.burn_rate"
    }
    assert gauges[("p99_latency", "fast")] >= 14.0
    # Recovery clears the alert state, so a second cliff re-fires.
    for _ in range(200):
        monitor.observe_request("ok", 0.01)
        clock.t += 0.5
    assert monitor.evaluate() == []
    for _ in range(50):
        monitor.observe_request("ok", 0.5)
        clock.t += 0.05
    assert len(monitor.evaluate()) == 1


def test_slo_shed_fraction_kind_counts_sheds():
    clock = types.SimpleNamespace(t=0.0)
    monitor = SloMonitor(
        [Slo("shed_fraction", "shed_fraction", objective=0.0, budget=0.05,
             fast_burn=2.0, slow_burn=1.0)],
        clock=lambda: clock.t,
    )
    for i in range(40):
        monitor.observe_request("shed" if i % 2 else "ok", 0.01)
        clock.t += 0.1
    monitor.evaluate()
    state = monitor.export()["slos"][0]
    assert state["alerting"]  # 50% shed against a 5% budget
    assert state["fast_burn"] == pytest.approx(10.0, rel=0.3)


# -- traced serving -----------------------------------------------------------

def test_traced_request_critical_path_reconciles_with_latency():
    model, data = _fixture(seed=7)
    session = TelemetrySession("test-trace")
    fleet, observer = _observed_fleet(model, data, session, replicas=1)
    with fleet:
        (req,) = build_requests(data, model, [4])
        t0 = time.monotonic()
        got = fleet.score(req, deadline_s=30.0)
        wall = time.monotonic() - t0
        np.testing.assert_allclose(
            got, host_score_request(model, req), rtol=1e-4, atol=1e-4
        )
    tids = observer.collector.trace_ids()
    assert len(tids) == 1
    spans = observer.collector.trace(tids[0])
    (root,) = [d for d in spans if d.get("parent_id") is None]
    assert root["name"] == "serving.request"
    assert root["status"] == "ok"
    events = {e["name"] for e in root["events"]}
    assert {"enqueue", "admit", "dispatch", "batch_close",
            "score_begin", "score_end"} <= events
    # The served model version is stamped into the response span.
    assert root["attrs"]["version"] == 0
    cp = observer.collector.critical_path(tids[0])
    assert cp["stage_sum_s"] == pytest.approx(cp["total_s"], abs=1e-6)
    assert cp["total_s"] <= wall + 0.05
    assert [s["stage"] for s in cp["stages"]] == [
        "queue", "batch_wait", "transport", "compute", "child_other",
        "resolve",
    ]
    # The live plane aggregated the request under its version.
    snap = observer.fleet_snapshot()
    assert snap["versions"]["0"]["requests"] == 1
    assert snap["versions"]["0"]["p99_s"] is not None


def test_shed_request_trace_carries_shed_decision_event():
    model, data = _fixture(seed=11)
    session = TelemetrySession("test-shed-trace")
    fleet, observer = _observed_fleet(model, data, session, replicas=1)
    with fleet:
        (req,) = build_requests(data, model, [4])
        fleet.score(req, deadline_s=30.0)
        with pytest.raises(RequestShedError):
            fleet.submit(req, deadline_s=0.0)
    shed_spans = [
        d for tid in observer.collector.trace_ids()
        for d in observer.collector.trace(tid)
        if d.get("status") == "shed"
    ]
    assert len(shed_spans) == 1
    (shed_event,) = [
        e for e in shed_spans[0]["events"] if e["name"] == "shed"
    ]
    assert shed_event["reason"] == "deadline"
    snap = observer.fleet_snapshot()
    assert sum(v["requests"] for v in snap["versions"].values()) == 2
    assert any(v["shed_rate"] > 0 for v in snap["versions"].values())


def test_replica_kill_yields_single_merged_trace_with_reroute():
    model, data = _fixture(seed=13)
    session = TelemetrySession("test-kill-trace")
    fleet, observer = _observed_fleet(model, data, session, replicas=2)
    with fleet:
        requests = build_requests(data, model, [4] * 10)
        set_plan(FaultPlan.parse("serve:replica_kill:replica=r0:times=1"))
        futures = [fleet.submit(r) for r in requests]
        results = [f.result(timeout=60) for f in futures]
        set_plan(None)
        assert len(results) == len(requests)
    rerouted = [
        tid for tid in observer.collector.trace_ids()
        if any(e["name"] == "reroute"
               for d in observer.collector.trace(tid)
               for e in d.get("events", ()))
    ]
    assert rerouted  # the kill landed inside a traced request
    for tid in rerouted:
        spans = observer.collector.trace(tid)
        # ONE merged trace: a single root, every span finished (the
        # rerouted request resolved ok through the survivor — no orphans).
        roots = [d for d in spans if d.get("parent_id") is None]
        assert len(roots) == 1
        assert roots[0]["status"] == "ok"
        assert all(d.get("duration_s") is not None for d in spans)
        (reroute_event,) = [
            e for e in roots[0]["events"] if e["name"] == "reroute"
        ]
        assert reroute_event["from_replica"] == "r0"


def test_per_bucket_admission_error_histograms():
    model, data = _fixture(seed=17)
    session = TelemetrySession("test-bucket-hist")
    fleet, observer = _observed_fleet(model, data, session, replicas=1)
    with fleet:
        for req in build_requests(data, model, [1, 3, 9, 16]):
            fleet.score(req, deadline_s=30.0)
    hists = {
        tuple(sorted((m.get("labels") or {}).items())): m
        for m in session.registry.snapshot()["histograms"]
        if m["name"] == "serving.admission_error_s"
    }
    buckets = {
        dict(labels).get("bucket")
        for labels in hists if dict(labels).get("bucket")
    }
    # Rows 1 and 3 pad into small buckets, 9 and 16 into 16 — at least
    # two distinct per-bucket series, next to the unlabeled aggregate.
    assert len(buckets) >= 2
    assert () in hists  # the unlabeled twin keeps its historic shape
    # Every projection-error sample lands in BOTH the aggregate and its
    # bucket series (the first request has no pace EWMA yet, so no
    # projection — both sides skip it identically).
    assert sum(
        m["count"] for labels, m in hists.items() if labels
    ) == hists[()]["count"] >= 3


# -- subprocess fleet: 3-process traces + flight recorder ---------------------

def test_subprocess_trace_spans_three_processes_and_flight_dump(tmp_path):
    """ISSUE 16 acceptance: one scoring request through client → router →
    subprocess replica produces a single merged trace spanning >= 3
    processes whose critical path reconciles; a SIGKILL'd child leaves a
    flight dump the supervisor collects, with unfinished child spans
    adopted as "lost" stubs."""
    model, data = _fixture(seed=19)
    session = TelemetrySession("test-subprocess-trace")
    spec = request_spec_for_dataset(model, data)
    fleet = ServingFleet(
        model, replicas=1, backend="subprocess", request_spec=spec,
        max_batch=16, max_delay_s=0.001, telemetry=session,
    ).warmup()
    observer = fleet.observe(start=False, flight_dir=str(tmp_path))
    try:
        server = fleet.serve()
        (req,) = build_requests(data, model, [4])
        client = AsyncScoringClient(
            server.address, connections=1, telemetry=session,
            observer=observer,
        )
        try:
            got = client.submit(req).result(timeout=60)
        finally:
            client.close()
        np.testing.assert_allclose(
            got, host_score_request(model, req), rtol=1e-4, atol=1e-4
        )
        observer.poll_once()
        tid = _trace_with_span(observer.collector, "client.request")
        assert tid is not None
        spans = observer.collector.trace(tid)
        names = {d["name"] for d in spans}
        assert {"client.request", "serving.request",
                "replica.score"} <= names
        processes = observer.collector.processes(tid)
        assert len(processes) >= 3
        # The child hop ran in a DIFFERENT OS process.
        child_pids = {
            p.rsplit(":", 1)[-1] for p in processes
            if p.startswith("replica-")
        }
        assert child_pids and str(os.getpid()) not in child_pids
        (child,) = [d for d in spans if d["name"] == "replica.score"]
        child_events = {e["name"] for e in child["events"]}
        assert {"ingress", "compute_begin", "compute_end",
                "egress"} <= child_events
        assert child["attrs"]["version"] == 0
        cp = observer.collector.critical_path(tid)
        assert cp["stage_sum_s"] == pytest.approx(cp["total_s"], abs=1e-6)
        stage = {s["stage"]: s["duration_s"] for s in cp["stages"]}
        assert stage["compute"] > 0.0  # the child's own clock contributed
        # The merged tree has one root (the client span) and no orphans.
        tree = observer.collector.tree(tid)
        assert tree["name"] == "client.request"

        # -- the crash: SIGKILL the child mid-life, supervisor collects.
        sup = fleet.supervise(
            SupervisorPolicy(probe_interval_s=0.05, probe_deadline_s=30.0,
                             resurrect=False),  # postmortem only
            start=False,
        )
        r0 = fleet.replicas[0]
        os.kill(r0.child_pid, signal.SIGKILL)
        deadline = time.monotonic() + 60.0
        while r0.alive and time.monotonic() < deadline:
            sup.check_once()
            time.sleep(0.05)
        assert not r0.alive
        assert observer.flight_dumps, "the death produced no flight dump"
        dump_meta = observer.flight_dumps[0]
        assert dump_meta["replica"] == "r0"
        assert dump_meta["path"] and os.path.exists(dump_meta["path"])
        with open(dump_meta["path"]) as f:
            dump = json.load(f)
        assert dump["cause"] and dump["cause"] == dump_meta["cause"]
        # The child's pre-scoring flush left the traced batch's ingress.
        child_kinds = {r["kind"] for r in dump["child"]["records"]}
        assert {"frame", "span"} <= child_kinds
        assert dump["parent"] is not None  # parent-side ring collected too
    finally:
        fleet.close()


def test_collect_flight_adopts_unshipped_spans_as_lost(tmp_path):
    """Span-stream loss recovery: a span the victim opened but never
    shipped is adopted as a terminal "lost" stub — the trace keeps the
    hop instead of orphaning it."""
    observer = FleetObserver(telemetry=TelemetrySession("test-lost"),
                             flight_dir=str(tmp_path))
    tid = new_trace_id()
    root = SpanRecord(tid, "serving.request", "router:1")
    root.finish()
    observer.collector.add(root)
    # The victim's ring: one span opened, never closed, never shipped.
    ring = FlightRecorder("r9")
    orphan = SpanRecord(tid, "replica.score", "replica-r9:4242",
                        parent_id=root.span_id)
    ring.note_span(orphan, "open")
    flight_path = str(tmp_path / "r9.flight.json")
    ring.dump(flight_path)
    victim = types.SimpleNamespace(
        replica_id="r9", generation=2, flight_path=flight_path
    )
    path = observer.collect_flight(victim, "crash")
    assert path and os.path.exists(path)
    spans = observer.collector.trace(tid)
    assert len(spans) == 2
    (lost,) = [d for d in spans if d["name"] == "replica.score"]
    assert lost["status"] == "lost"
    assert lost["attrs"]["lost_reason"] == "crash"
    assert observer.flight_dumps[0]["lost_spans_recovered"] == 1
    # A shipped span is NOT duplicated by a later dump collection.
    observer.collect_flight(victim, "crash")
    assert len(observer.collector.trace(tid)) == 2


# -- live metrics plane -------------------------------------------------------

def test_http_metrics_plane_and_live_console(capsys):
    import urllib.request

    from photon_tpu.telemetry import live as live_console

    model, data = _fixture(seed=23)
    session = TelemetrySession("test-http-plane")
    fleet = ServingFleet(
        model, replicas=1,
        request_spec=request_spec_for_dataset(model, data),
        max_batch=16, max_delay_s=0.001, telemetry=session,
    ).warmup()
    observer = fleet.observe(
        policy=ObservePolicy(http_port=0, poll_interval_s=0.05)
    )
    with fleet:
        for req in build_requests(data, model, [2, 5]):
            fleet.score(req, deadline_s=30.0)
        host, port = observer.http_address
        base = f"http://{host}:{port}"
        with urllib.request.urlopen(f"{base}/metrics", timeout=10) as r:
            prom = r.read().decode()
        assert "serving_requests" in prom
        with urllib.request.urlopen(f"{base}/fleet.json", timeout=10) as r:
            snap = json.loads(r.read().decode())
        assert snap["versions"]["0"]["requests"] == 2
        assert "slo" in snap
        # The console view renders one frame from the same endpoint.
        rc = live_console.main(["--url", base, "--once"])
        assert rc == 0
        out = capsys.readouterr().out
        assert "fleet @" in out
        assert "qps" in out
    assert observer.http_address is None  # close() tore the server down


# -- linked publish trace + report --------------------------------------------

def test_rollout_under_ambient_trace_links_publish_and_probes():
    """The online publish path (``RefreshService._publish``): the rollout
    and its canary probes parent under the ambient publish span — one
    linked trace for refresh → canary → swap."""
    from photon_tpu.online.service import OnlineLearningService

    model, data = _fixture(seed=29)
    retrained = _retrained(model, seed=31)
    session = TelemetrySession("test-publish-trace")
    fleet, observer = _observed_fleet(model, data, session, replicas=2)
    with fleet:
        for req in build_requests(data, model, [4, 4]):
            fleet.score(req, deadline_s=30.0)  # seeds the probe mirror
        svc = types.SimpleNamespace(
            fleet=fleet,
            policy=types.SimpleNamespace(rollout_parity_tol=1e-3),
        )
        OnlineLearningService._publish(svc, retrained)
        # Served version advanced — new responses stamp version 1.
        (req,) = build_requests(data, model, [4])
        fleet.score(req, deadline_s=30.0)
    tid = _trace_with_span(observer.collector, "online.publish")
    assert tid is not None
    spans = observer.collector.trace(tid)
    (publish,) = [d for d in spans if d["name"] == "online.publish"]
    (rollout,) = [d for d in spans if d["name"] == "serving.rollout"]
    assert publish["parent_id"] is None
    assert rollout["parent_id"] == publish["span_id"]
    assert publish["status"] == "ok" and rollout["status"] == "ok"
    phases = [e["name"] for e in rollout["events"]]
    assert "canary" in phases and "promoted" in phases
    # Probe requests rode the same trace through the router.
    probe_roots = [d for d in spans if d["name"] == "serving.request"]
    assert probe_roots
    assert all(d["parent_id"] == rollout["span_id"] for d in probe_roots)
    # Post-swap responses carry the new version.
    v1 = [
        d for t in observer.collector.trace_ids()
        for d in observer.collector.trace(t)
        if d["name"] == "serving.request"
        and (d.get("attrs") or {}).get("version") == 1
    ]
    assert v1


def test_rollout_without_ambient_trace_still_traced():
    model, data = _fixture(seed=37)
    retrained = _retrained(model, seed=41)
    session = TelemetrySession("test-rollout-trace")
    fleet, observer = _observed_fleet(model, data, session, replicas=2)
    with fleet:
        for req in build_requests(data, model, [4, 4]):
            fleet.score(req, deadline_s=30.0)
        fleet.rollout(retrained)
    tid = _trace_with_span(observer.collector, "serving.rollout")
    assert tid is not None
    (rollout,) = [
        d for d in observer.collector.trace(tid)
        if d["name"] == "serving.rollout"
    ]
    assert rollout["parent_id"] is None  # fresh trace, no ambient parent


def test_ambient_trace_context_manager_restores():
    assert __import__(
        "photon_tpu.telemetry.distributed", fromlist=["current_trace"]
    ).current_trace() is None
    ctx = TraceContext(new_trace_id(), "feed1234", True)
    from photon_tpu.telemetry.distributed import current_trace

    with activate_trace(ctx):
        assert current_trace() is ctx
        inner = TraceContext(new_trace_id(), "beef5678", True)
        with activate_trace(inner):
            assert current_trace() is inner
        assert current_trace() is ctx
    assert current_trace() is None


def test_report_renders_fleet_traces_slos_section():
    from photon_tpu.telemetry.report import render_markdown

    model, data = _fixture(seed=43)
    session = TelemetrySession("test-observe-report")
    fleet, observer = _observed_fleet(model, data, session, replicas=1)
    with fleet:
        for req in build_requests(data, model, [4, 8]):
            fleet.score(req, deadline_s=30.0)
        with pytest.raises(RequestShedError):
            fleet.submit(req, deadline_s=0.0)
    observer.flight_dumps.append({
        "replica": "r0", "cause": "crash", "path": None, "generation": 1,
        "child_records": 7, "lost_spans_recovered": 1,
        "collected_at": time.time(),
    })
    report = session.build_report(extra={"observe": observer.export()})
    text = render_markdown(report)
    assert "## Fleet traces / SLOs" in text
    assert "queue (s)" in text and "compute (s)" in text
    assert "p99_latency" in text and "shed_fraction" in text
    assert "### Flight dumps" in text
    assert "1 lost span(s) recovered" in text.replace("**", "") or (
        "lost span(s)" in text
    )
    # A report without the payload renders no section.
    assert "Fleet traces" not in render_markdown(session.build_report())


# -- SLO-driven admission guard (ISSUE 19 satellite) ---------------------------

def _counter_total(session, name, **labels):
    total = 0
    for m in session.registry.snapshot()["counters"]:
        if m["name"] != name:
            continue
        if labels and any(
            str(m["labels"].get(k)) != str(v) for k, v in labels.items()
        ):
            continue
        total += m["value"]
    return total


def test_admission_guard_tightens_on_alert_and_relaxes_on_clear():
    """The guard closes the SLO→admission loop: a burn-rate alert raises
    the router's ``burn_safety`` multiplier to ``admission_tighten``; the
    CLEAR edge — and only with no other SLO still alerting — relaxes it
    back to 1.0.  Exactly one counter tick per edge."""
    clock = types.SimpleNamespace(t=0.0)
    session = TelemetrySession("test-guard")
    model, data = _fixture(seed=7)
    fleet, observer = _observed_fleet(model, data, session, replicas=1)
    try:
        monitor = SloMonitor(
            [Slo("p99_latency", "latency", objective=0.1, budget=0.01,
                 fast_window_s=5.0, slow_window_s=60.0,
                 fast_burn=14.0, slow_burn=2.0)],
            telemetry=session, clock=lambda: clock.t,
        )
        observer.slo_monitor = monitor
        observer.attach_admission_guard(fleet.router, tighten=8.0)
        assert fleet.router.burn_safety == 1.0
        # Injected latency cliff: every request blows the objective.
        for _ in range(50):
            monitor.observe_request("ok", 0.5)
            clock.t += 0.05
        monitor.evaluate()
        assert fleet.router.burn_safety == 8.0
        assert _counter_total(session, "serving.admission_tightened") == 1
        monitor.evaluate()  # continuing alert: no re-tighten tick
        assert _counter_total(session, "serving.admission_tightened") == 1
        # Heal: healthy traffic drains both windows → CLEAR → relax.
        for _ in range(200):
            monitor.observe_request("ok", 0.01)
            clock.t += 0.5
        monitor.evaluate()
        assert fleet.router.burn_safety == 1.0
        assert _counter_total(session, "serving.admission_relaxed") == 1
    finally:
        fleet.close()


def test_admission_guard_shed_rises_under_alert_and_recovers():
    """Behavioral half of the guard: while tightened, the overload
    projection sheds a deadline that sails through at safety 1; after the
    relax edge the same request is admitted again."""
    model, data = _fixture(seed=7)
    session = TelemetrySession("test-guard-shed")
    fleet, observer = _observed_fleet(model, data, session, replicas=1)
    try:
        reqs = build_requests(data, model, [3, 5, 8])
        for r in reqs:
            fleet.score(r)  # measure per-row service time
        shed0 = _counter_total(session, "serving.shed", reason="overload")
        fleet.router.burn_safety = 1e9  # what a fired alert installs
        with pytest.raises(RequestShedError):
            fleet.score(reqs[0], deadline_s=0.25)
        assert _counter_total(
            session, "serving.shed", reason="overload"
        ) > shed0
        fleet.router.burn_safety = 1.0  # the clear edge relaxes
        got = np.asarray(fleet.score(reqs[0], deadline_s=0.25), np.float64)
        want = host_score_request(model, reqs[0])
        assert np.abs(got - want).max() < 1e-3
    finally:
        fleet.close()
