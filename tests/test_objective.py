"""GLM objective: dense vs sparse equivalence, gradient/Hv checks,
normalization round trip (SURVEY.md §4 'aggregator math ... cross-check')."""

import jax
import jax.numpy as jnp
import numpy as np

from photon_tpu.core.normalization import NormalizationContext
from photon_tpu.core.objective import GlmObjective, RegularizationContext
from photon_tpu.core.stats import BasicStatisticalSummary
from photon_tpu.data.batch import dense_batch, sparse_batch_from_rows

DIM = 12
N = 50


def _make_data(seed=0, density=0.4):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(N, DIM)).astype(np.float32)
    mask = rng.random((N, DIM)) < density
    mask[:, 0] = True  # keep feature 0 present so padding id 0 is exercised
    x = x * mask
    y = (rng.random(N) < 0.5).astype(np.float32)
    offset = rng.normal(size=N).astype(np.float32) * 0.1
    weight = rng.uniform(0.5, 2.0, N).astype(np.float32)
    return x, y, offset, weight


def _sparse_rows(x):
    rows = []
    for i in range(x.shape[0]):
        ids = np.nonzero(x[i])[0].astype(np.int32)
        rows.append((ids, x[i][ids].astype(np.float32)))
    return rows


def test_dense_sparse_equivalence():
    x, y, offset, weight = _make_data()
    dense = dense_batch(x, y, offset, weight)
    sparse = sparse_batch_from_rows(_sparse_rows(x), y, offset, weight)
    obj = GlmObjective.create("logistic", RegularizationContext("l2", 0.5))
    rng = np.random.default_rng(1)
    w = jnp.asarray(rng.normal(size=DIM).astype(np.float32))
    vd, gd = obj.value_and_grad(w, dense)
    vs, gs = obj.value_and_grad(w, sparse)
    np.testing.assert_allclose(vd, vs, rtol=1e-5)
    np.testing.assert_allclose(gd, gs, rtol=1e-4, atol=1e-5)


def test_gradient_matches_numeric():
    x, y, offset, weight = _make_data(2)
    batch = dense_batch(x, y, offset, weight)
    obj = GlmObjective.create("poisson", RegularizationContext("l2", 0.1))
    w = jnp.zeros(DIM)
    _, g = obj.value_and_grad(w, batch)
    eps = 1e-3
    for j in range(0, DIM, 3):
        e = jnp.zeros(DIM).at[j].set(eps)
        num = (obj.value(w + e, batch) - obj.value(w - e, batch)) / (2 * eps)
        np.testing.assert_allclose(g[j], num, rtol=1e-2, atol=1e-2)


def test_hessian_vector_matches_full_hessian():
    x, y, offset, weight = _make_data(3)
    batch = dense_batch(x, y, offset, weight)
    obj = GlmObjective.create("logistic", RegularizationContext("l2", 0.3))
    rng = np.random.default_rng(4)
    w = jnp.asarray(rng.normal(size=DIM).astype(np.float32))
    v = jnp.asarray(rng.normal(size=DIM).astype(np.float32))
    h = jax.hessian(obj.value)(w, batch)
    np.testing.assert_allclose(
        obj.hessian_vector(w, v, batch), h @ v, rtol=1e-4, atol=1e-4
    )


def test_hessian_diagonal_matches_full_hessian():
    x, y, offset, weight = _make_data(5)
    dense = dense_batch(x, y, offset, weight)
    sparse = sparse_batch_from_rows(_sparse_rows(x), y, offset, weight)
    obj = GlmObjective.create("logistic", RegularizationContext("l2", 0.2))
    rng = np.random.default_rng(6)
    w = jnp.asarray(rng.normal(size=DIM).astype(np.float32))
    h = jax.hessian(obj.value)(w, dense)
    np.testing.assert_allclose(
        obj.hessian_diagonal(w, dense), jnp.diag(h), rtol=1e-4, atol=1e-4
    )
    np.testing.assert_allclose(
        obj.hessian_diagonal(w, sparse), jnp.diag(h), rtol=1e-4, atol=1e-4
    )


def test_normalization_equals_materialized_scaling():
    x, y, offset, weight = _make_data(7)
    batch = dense_batch(x, y, offset, weight)
    summary = BasicStatisticalSummary.from_batch(batch, DIM)
    norm = NormalizationContext.build("scale_with_standard_deviation", summary)
    obj_norm = GlmObjective.create("logistic", normalization=norm)
    # Materialize the scaled features and compare objectives.
    factors = np.asarray(norm.factors)
    batch_scaled = dense_batch(x * factors, y, offset, weight)
    obj_plain = GlmObjective.create("logistic")
    rng = np.random.default_rng(8)
    w = jnp.asarray(rng.normal(size=DIM).astype(np.float32))
    np.testing.assert_allclose(
        obj_norm.value(w, batch), obj_plain.value(w, batch_scaled), rtol=1e-5
    )


def test_standardization_model_space_round_trip():
    x, y, offset, weight = _make_data(9)
    # Append an intercept column.
    xi = np.concatenate([x, np.ones((N, 1), np.float32)], axis=1)
    dim = DIM + 1
    batch = dense_batch(xi, y, offset, weight)
    summary = BasicStatisticalSummary.from_batch(batch, dim)
    norm = NormalizationContext.build("standardization", summary, intercept_id=DIM)
    obj_norm = GlmObjective.create("logistic", normalization=norm)
    rng = np.random.default_rng(10)
    w = jnp.asarray(rng.normal(size=dim).astype(np.float32))
    # Margins under normalized objective == margins of denormalized model on raw data.
    w_orig = norm.model_to_original_space(w)
    obj_plain = GlmObjective.create("logistic")
    np.testing.assert_allclose(
        obj_norm._margins(w, batch),
        obj_plain._margins(w_orig, batch),
        rtol=1e-4, atol=1e-4,
    )


def test_hessian_diagonal_under_standardization():
    # Regression: the diagonal must account for shift terms, not just factors.
    x, y, offset, weight = _make_data(12)
    xi = np.concatenate([x, np.ones((N, 1), np.float32)], axis=1)
    dim = DIM + 1
    dense = dense_batch(xi, y, offset, weight)
    sparse = sparse_batch_from_rows(_sparse_rows(xi), y, offset, weight)
    summary = BasicStatisticalSummary.from_batch(dense, dim)
    norm = NormalizationContext.build("standardization", summary, intercept_id=DIM)
    obj = GlmObjective.create("logistic", RegularizationContext("l2", 0.4),
                              normalization=norm)
    rng = np.random.default_rng(13)
    w = jnp.asarray(rng.normal(size=dim).astype(np.float32))
    h = jax.hessian(obj.value)(w, dense)
    np.testing.assert_allclose(
        obj.hessian_diagonal(w, dense), jnp.diag(h), rtol=1e-3, atol=1e-3
    )
    np.testing.assert_allclose(
        obj.hessian_diagonal(w, sparse), jnp.diag(h), rtol=1e-3, atol=1e-3
    )


def test_sparse_batch_overflow_raises():
    rows = [(np.array([1, 2, 3], np.int32), np.ones(3, np.float32))]
    import pytest as _pytest
    with _pytest.raises(ValueError, match="exceeds capacity"):
        sparse_batch_from_rows(rows, np.ones(1, np.float32), capacity=2)


def test_sparse_stats_match_dense():
    x, y, offset, weight = _make_data(11)
    dense = dense_batch(x, y, offset, weight)
    sparse = sparse_batch_from_rows(_sparse_rows(x), y, offset, weight)
    sd = BasicStatisticalSummary.from_batch(dense, DIM)
    ss = BasicStatisticalSummary.from_batch(sparse, DIM)
    np.testing.assert_allclose(sd.mean, ss.mean, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(sd.variance, ss.variance, rtol=1e-3, atol=1e-5)
    np.testing.assert_allclose(sd.min, ss.min, rtol=1e-5)
    np.testing.assert_allclose(sd.max, ss.max, rtol=1e-5)
    np.testing.assert_allclose(sd.num_nonzeros, ss.num_nonzeros)


def test_variances_follow_model_to_original_space():
    # Monte-carlo check of the diagonal-posterior variance transform: sample
    # w ~ N(mean, diag(var)) in normalized space, map each sample through
    # model_to_original_space, and compare empirical variances.
    rng = np.random.default_rng(0)
    d = 5
    factors = jnp.asarray([2.0, 0.5, 1.5, 3.0, 1.0])
    shifts = jnp.asarray([0.3, -1.0, 0.0, 2.0, 0.0])
    norm = NormalizationContext(factors=factors, shifts=shifts, intercept_id=4)
    var = jnp.asarray([0.4, 0.1, 0.2, 0.3, 0.5])
    samples = rng.standard_normal((200_000, d)) * np.sqrt(np.asarray(var))
    # Vectorized replica of model_to_original_space for the sample cloud:
    w_eff = samples * np.asarray(factors)
    corr = w_eff @ np.asarray(shifts)
    w_eff[:, 4] -= corr
    empirical = w_eff.var(axis=0)
    predicted = np.asarray(norm.variances_to_original_space(var))
    np.testing.assert_allclose(empirical, predicted, rtol=0.05)
