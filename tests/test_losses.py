"""Loss derivative checks vs jax.grad (the reference checks its pointwise
losses against numeric differentiation — SURVEY.md §4 'unit tests')."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from photon_tpu.core.losses import LOSSES, get_loss


def _labels_for(name, n, rng):
    if name in ("logistic", "smoothed_hinge"):
        return rng.integers(0, 2, n).astype(np.float32)
    if name == "poisson":
        return rng.poisson(2.0, n).astype(np.float32)
    return rng.normal(size=n).astype(np.float32)


@pytest.mark.parametrize("name", sorted(LOSSES))
def test_first_derivative_matches_autodiff(name):
    loss = get_loss(name)
    rng = np.random.default_rng(0)
    z = jnp.asarray(rng.normal(scale=2.0, size=64).astype(np.float32))
    y = jnp.asarray(_labels_for(name, 64, rng))
    d1_auto = jax.vmap(jax.grad(lambda zz, yy: loss.value(zz, yy)))(z, y)
    np.testing.assert_allclose(loss.d1(z, y), d1_auto, rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("name", sorted(LOSSES))
def test_second_derivative_matches_autodiff(name):
    loss = get_loss(name)
    rng = np.random.default_rng(1)
    z = jnp.asarray(rng.normal(scale=2.0, size=64).astype(np.float32))
    # Avoid the smoothed hinge's kink points where d2 is undefined.
    if name == "smoothed_hinge":
        z = z + 0.123
    y = jnp.asarray(_labels_for(name, 64, rng))
    d2_auto = jax.vmap(jax.grad(jax.grad(lambda zz, yy: loss.value(zz, yy))))(z, y)
    np.testing.assert_allclose(loss.d2(z, y), d2_auto, rtol=1e-5, atol=1e-5)


def test_logistic_known_values():
    loss = get_loss("logistic")
    # At margin 0: loss = log 2 regardless of label.
    np.testing.assert_allclose(
        loss.value(jnp.asarray(0.0), jnp.asarray(1.0)), np.log(2.0), rtol=1e-6
    )
    np.testing.assert_allclose(
        loss.d2(jnp.asarray(0.0), jnp.asarray(0.0)), 0.25, rtol=1e-6
    )


def test_squared_known_values():
    loss = get_loss("squared")
    np.testing.assert_allclose(
        loss.value(jnp.asarray(3.0), jnp.asarray(1.0)), 2.0, rtol=1e-6
    )


def test_task_type_aliases():
    assert get_loss("logistic_regression").name == "logistic"
    assert get_loss("linear_regression").name == "squared"
    assert get_loss("poisson_regression").name == "poisson"


def test_autodiff_matches_d1_at_exact_zero_margin():
    """Regression: the stable logistic value's kinks all sit at EXACTLY z=0
    (the first evaluation from w0=0 with zero offsets); autodiff's
    subgradient choice there used to yield -y instead of sigmoid(0)-y,
    which could stall L-BFGS at the start point.  Every loss's autodiff
    derivative must equal its analytic d1 at z=0."""
    import jax

    from photon_tpu.core.losses import LOSSES

    for name, loss in LOSSES.items():
        for y in (0.0, 1.0):
            g_auto = jax.grad(lambda z: loss.value(z, jnp.asarray(y)))(
                jnp.asarray(0.0)
            )
            g_true = loss.d1(jnp.asarray(0.0), jnp.asarray(y))
            np.testing.assert_allclose(
                g_auto, g_true, rtol=1e-6,
                err_msg=f"{name} autodiff != d1 at z=0, y={y}",
            )


@pytest.mark.parametrize("name", sorted(LOSSES))
def test_losses_finite_at_extreme_margins(name):
    """Every loss must stay finite across margins a line search can probe
    (f32 exp overflows at ~88; the Poisson NLL is linearized past the
    exponent cap with analytic d1/d2 as its exact derivatives — losses.py)."""
    z = jnp.asarray([-200.0, -100.0, -30.0, 0.0, 30.0, 100.0, 200.0])
    loss = get_loss(name)
    y = jnp.asarray([0.0, 1.0, 1.0, 0.0, 1.0, 0.0,
                     3.0 if name in ("poisson", "squared") else 1.0])
    for fn in (loss.value, loss.d1, loss.d2):
        out = np.asarray(fn(z, y))
        assert np.isfinite(out).all(), (name, fn, out)
    assert np.isfinite(np.asarray(loss.mean(z))).all(), name
    # Autodiff through the value must agree with the analytic d1 even in
    # the clamped region (a naive clamp autodiffs to a WRONG -y slope).
    g = np.asarray(jax.vmap(jax.grad(loss.value))(z, y))
    np.testing.assert_allclose(g, np.asarray(loss.d1(z, y)), rtol=1e-5)
