"""The `benes` static-permutation kernel (ops/clos.py + ops/benes.py).

The kernel rewrites the row-order <-> feature-order exchange — the random
E-element access that pins every other kernel to ~0.1% of TPU HBM
roofline (ops/KERNEL_NOTES.md round-4 hardware verdicts) — as a 3-stage
Clos factorization: row-local shuffles + transposes, routed host-side by
bipartite edge-coloring (native/src/clos_route.cpp).  These tests pin

- the routing itself (native and pure-Python colorings) against plain
  ``x[perm]``,
- the route inversion (one coloring serves both directions),
- the end-to-end objective: value/grad/Hv through
  ``PHOTON_SPARSE_GRAD=benes`` must match the autodiff reference exactly
  like the fm/pallas paths do (interpret-mode reduce on CPU).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from photon_tpu.core.objective import GlmObjective, RegularizationContext
from photon_tpu.data.batch import attach_feature_major
from photon_tpu.ops.clos import (
    apply_clos,
    invert_route,
    route_permutation,
)

from tests.test_fast_sparse import _random_batch


@pytest.mark.parametrize("n,a,b", [
    (16, 4, 4), (100, None, None), (4096, 64, 64), (5000, None, None),
])
def test_route_matches_flat_gather(n, a, b):
    rng = np.random.default_rng(0)
    perm = rng.permutation(n)
    route = route_permutation(perm, a, b)
    x = jnp.asarray(rng.standard_normal(n).astype(np.float32))
    np.testing.assert_array_equal(
        np.asarray(apply_clos(x, route)), np.asarray(x)[perm]
    )


def test_route_python_fallback_matches_native():
    rng = np.random.default_rng(1)
    perm = rng.permutation(512)
    x = jnp.asarray(rng.standard_normal(512).astype(np.float32))
    r_native = route_permutation(perm, 32, 16, use_native=True)
    r_py = route_permutation(perm, 32, 16, use_native=False)
    ref = np.asarray(x)[perm]
    np.testing.assert_array_equal(np.asarray(apply_clos(x, r_native)), ref)
    np.testing.assert_array_equal(np.asarray(apply_clos(x, r_py)), ref)


def test_route_inversion_round_trips():
    rng = np.random.default_rng(2)
    n = 2048
    perm = rng.permutation(n)
    route = route_permutation(perm, 64, 32)
    inv = invert_route(route)
    x = jnp.asarray(rng.standard_normal(n).astype(np.float32))
    # inv applies perm^-1: y[perm[i]] = x[perm[i]] pulled back => identity.
    y = apply_clos(apply_clos(x, route), inv)
    np.testing.assert_array_equal(np.asarray(y), np.asarray(x))
    # And inv alone equals gathering by the inverse permutation.
    inv_perm = np.empty_like(perm)
    inv_perm[perm] = np.arange(n)
    np.testing.assert_array_equal(
        np.asarray(apply_clos(x, inv)), np.asarray(x)[inv_perm]
    )


def test_route_rejects_non_permutation():
    with pytest.raises(ValueError):
        route_permutation(np.array([0, 0, 2, 3]), 2, 2)


@pytest.mark.parametrize("loss", ["logistic", "squared", "poisson"])
@pytest.mark.parametrize("zipf", [False, True])
def test_benes_kernel_matches_autodiff(monkeypatch, loss, zipf):
    """PHOTON_SPARSE_GRAD=benes routes value+grad AND Hv through the
    static-permutation pipeline — must match autodiff like fm/pallas."""
    monkeypatch.setenv("PHOTON_SPARSE_GRAD", "benes")
    n, k, d = 256, 6, 48
    batch = _random_batch(n, k, d, seed=90, zipf=zipf)
    fast = attach_feature_major(batch, aligned_dim=d)
    assert fast.al is not None and fast.benes is not None
    obj = GlmObjective.create(loss, RegularizationContext("l2", 0.6))
    rng = np.random.default_rng(91)
    w = jnp.asarray(rng.standard_normal(d), jnp.float32) * 0.1

    assert obj._sparse_kernel(fast, d) == "benes"
    v_ref, g_ref = jax.value_and_grad(obj.value)(w, batch)
    v_b, g_b = obj.value_and_grad(w, fast)
    np.testing.assert_allclose(v_b, v_ref, rtol=1e-5)
    np.testing.assert_allclose(g_b, g_ref, rtol=2e-4, atol=1e-5)
    # Under jit (optimizers always call it jitted).
    v_j, g_j = jax.jit(obj.value_and_grad)(w, fast)
    np.testing.assert_allclose(g_j, g_ref, rtol=2e-4, atol=1e-5)

    vec = jnp.asarray(rng.standard_normal(d), jnp.float32)
    hv_ref = jax.jvp(lambda u: jax.grad(obj.value)(u, batch), (w,), (vec,))[1]
    hv = obj.hessian_vector(w, vec, fast)
    np.testing.assert_allclose(hv, hv_ref, rtol=2e-4, atol=1e-5)


def test_benes_kernel_under_normalization(monkeypatch):
    from photon_tpu.core.normalization import NormalizationContext
    from photon_tpu.core.stats import BasicStatisticalSummary

    monkeypatch.setenv("PHOTON_SPARSE_GRAD", "benes")
    n, k, d = 192, 5, 40
    batch = _random_batch(n, k, d, seed=92)
    fast = attach_feature_major(batch, aligned_dim=d)
    summary = BasicStatisticalSummary.from_batch(batch, d)
    norm = NormalizationContext.build(
        "standardization", summary, intercept_id=0
    )
    obj = GlmObjective.create(
        "logistic", RegularizationContext("l2", 0.4), normalization=norm
    )
    w = jnp.asarray(
        np.random.default_rng(93).standard_normal(d), jnp.float32
    ) * 0.1
    v_ref, g_ref = jax.value_and_grad(obj.value)(w, batch)
    v_b, g_b = obj.value_and_grad(w, fast)
    np.testing.assert_allclose(v_b, v_ref, rtol=1e-5)
    np.testing.assert_allclose(g_b, g_ref, rtol=2e-4, atol=1e-5)


def test_benes_aux_not_built_without_optin(monkeypatch):
    """Auto mode must never pay the routing cost speculatively."""
    monkeypatch.setenv("PHOTON_SPARSE_GRAD", "auto")
    batch = _random_batch(64, 4, 32, seed=94)
    fast = attach_feature_major(batch, aligned_dim=32)
    assert fast.benes is None


def test_benes_lbfgs_training_converges(monkeypatch):
    """A full L-BFGS solve through the benes kernel reaches the same
    optimum as autodiff (end-to-end: optimizer loop, jit, line search)."""
    from photon_tpu.core.optimizers import lbfgs

    n, k, d = 256, 5, 32
    monkeypatch.setenv("PHOTON_SPARSE_GRAD", "benes")
    batch = _random_batch(n, k, d, seed=95)
    fast = attach_feature_major(batch, aligned_dim=d)
    obj = GlmObjective.create("logistic", RegularizationContext("l2", 1.0))
    w0 = jnp.zeros(d, jnp.float32)
    res_b = lbfgs(lambda w: obj.value_and_grad(w, fast), w0)

    monkeypatch.setenv("PHOTON_SPARSE_GRAD", "autodiff")
    res_a = lbfgs(lambda w: obj.value_and_grad(w, batch), w0)
    np.testing.assert_allclose(
        np.asarray(res_b.w), np.asarray(res_a.w), rtol=1e-3, atol=1e-4
    )
