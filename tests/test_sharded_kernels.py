"""Sharded fast-kernel equivalence (VERDICT r5 item 2): the pallas and
xchg gradient kernels must produce the SAME numbers under the sharded
objective (8-virtual-device mesh, per-shard layouts + psum) as plain
single-device autodiff.

This is the reference's distributed-vs-local cross-check (SURVEY.md §4)
applied to the round-4/5 hardware kernels: before this round the fast
kernels required ``shards == 1`` and silently fell back on any mesh, so
no kernel win could reach the multi-chip north star.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from photon_tpu.core.objective import GlmObjective, RegularizationContext
from photon_tpu.data.batch import SparseBatch, attach_feature_major
from photon_tpu.parallel import DistributedGlmObjective, create_mesh, shard_batch

N, K, D = 160, 5, 64  # N not a multiple of 8 after padding? 160 = 8*20


def _batch(seed=0, n=N):
    rng = np.random.default_rng(seed)
    ids = rng.integers(1, D, size=(n, K)).astype(np.int32)
    vals = rng.standard_normal((n, K)).astype(np.float32)
    vals[rng.random((n, K)) < 0.1] = 0.0
    label = (rng.random(n) < 0.5).astype(np.float32)
    offset = (rng.standard_normal(n) * 0.1).astype(np.float32)
    weight = rng.uniform(0.5, 2.0, n).astype(np.float32)
    return SparseBatch(
        ids=jnp.asarray(ids), vals=jnp.asarray(vals),
        label=jnp.asarray(label), offset=jnp.asarray(offset),
        weight=jnp.asarray(weight),
    )


def _autodiff_reference(obj, w, batch, monkeypatch):
    monkeypatch.setenv("PHOTON_SPARSE_GRAD", "autodiff")
    v, g = obj.value_and_grad(w, batch)
    return np.asarray(v), np.asarray(g)


def _check_sharded(monkeypatch, kernel, reduce_mode=None, loss="logistic",
                   reg=None, n=N, check_hv=True):
    monkeypatch.setenv("PHOTON_ROUTE_CACHE", "0")
    if reduce_mode is not None:
        monkeypatch.setenv("PHOTON_XCHG_REDUCE", reduce_mode)
    batch = _batch(n=n)
    obj = GlmObjective.create(
        loss, reg or RegularizationContext("l2", 0.3)
    )
    rng = np.random.default_rng(1)
    w = jnp.asarray(rng.standard_normal(D).astype(np.float32) * 0.1)
    v_ref, g_ref = _autodiff_reference(obj, w, batch, monkeypatch)

    monkeypatch.setenv("PHOTON_SPARSE_GRAD", kernel)
    mesh = create_mesh()
    sharded = shard_batch(batch, mesh, aligned_dim=D)
    assert sharded.al is not None
    dist = DistributedGlmObjective(obj, mesh)
    assert dist._sparse_kernel(w, sharded) == kernel
    v_d, g_d = dist.value_and_grad(w, sharded)
    np.testing.assert_allclose(v_d, v_ref, rtol=2e-5)
    scale = max(float(np.abs(g_ref).max()), 1.0)
    np.testing.assert_allclose(
        np.asarray(g_d), g_ref, rtol=2e-4, atol=2e-4 * scale
    )
    # Hv through the same sharded kernel vs autodiff jvp.
    if not check_hv:
        return
    u = jnp.asarray(rng.standard_normal(D).astype(np.float32))
    monkeypatch.setenv("PHOTON_SPARSE_GRAD", "autodiff")
    hv_ref = np.asarray(obj.hessian_vector(w, u, batch))
    monkeypatch.setenv("PHOTON_SPARSE_GRAD", kernel)
    hv_d = np.asarray(dist.hessian_vector(w, u, sharded))
    hs = max(float(np.abs(hv_ref).max()), 1.0)
    np.testing.assert_allclose(hv_d, hv_ref, rtol=2e-4, atol=2e-4 * hs)


def test_sharded_pallas_grad_matches_autodiff(monkeypatch):
    _check_sharded(monkeypatch, "pallas")


def test_sharded_xchg_cumsum_matches_autodiff(monkeypatch):
    _check_sharded(monkeypatch, "xchg", reduce_mode="cumsum")


def test_sharded_xchg_aligned_matches_autodiff(monkeypatch):
    # Hv covered by the cumsum variant (same exchange machinery); skipped
    # here to keep the suite under its wall-clock bar.
    _check_sharded(monkeypatch, "xchg", reduce_mode="aligned",
                   check_hv=False)


def test_sharded_xchg_poisson_unpadded_rows(monkeypatch):
    """Different loss + a row count that needs zero-weight padding (101
    rows over 8 shards): the pad rows must contribute exactly nothing
    through the exchange.  (Hv covered by the logistic cumsum test.)"""
    _check_sharded(
        monkeypatch, "xchg", reduce_mode="cumsum", loss="poisson", n=101,
        check_hv=False,
    )


def test_sharded_pallas_normalized_grad(monkeypatch):
    """Normalization algebra through the sharded pallas kernel, and the
    normalized Hv fallback (jvp through the fm layout — pallas_call has
    no JVP rule)."""
    from photon_tpu.core.normalization import NormalizationContext

    monkeypatch.setenv("PHOTON_ROUTE_CACHE", "0")
    batch = _batch(seed=7)
    rng = np.random.default_rng(8)
    factors = rng.uniform(0.5, 2.0, D).astype(np.float32)
    shifts = (rng.standard_normal(D) * 0.01).astype(np.float32)
    norm = NormalizationContext(factors=jnp.asarray(factors),
                                shifts=jnp.asarray(shifts))
    obj = GlmObjective.create(
        "logistic", RegularizationContext("l2", 0.2), normalization=norm
    )
    w = jnp.asarray(rng.standard_normal(D).astype(np.float32) * 0.1)
    v_ref, g_ref = _autodiff_reference(obj, w, batch, monkeypatch)

    monkeypatch.setenv("PHOTON_SPARSE_GRAD", "pallas")
    mesh = create_mesh()
    sharded = shard_batch(batch, mesh, aligned_dim=D)
    dist = DistributedGlmObjective(obj, mesh)
    v_d, g_d = dist.value_and_grad(w, sharded)
    np.testing.assert_allclose(v_d, v_ref, rtol=2e-5)
    scale = max(float(np.abs(g_ref).max()), 1.0)
    np.testing.assert_allclose(
        np.asarray(g_d), g_ref, rtol=2e-4, atol=2e-4 * scale
    )
    u = jnp.asarray(rng.standard_normal(D).astype(np.float32))
    monkeypatch.setenv("PHOTON_SPARSE_GRAD", "autodiff")
    hv_ref = np.asarray(obj.hessian_vector(w, u, batch))
    monkeypatch.setenv("PHOTON_SPARSE_GRAD", "pallas")
    hv_d = np.asarray(dist.hessian_vector(w, u, sharded))
    hs = max(float(np.abs(hv_ref).max()), 1.0)
    np.testing.assert_allclose(hv_d, hv_ref, rtol=2e-4, atol=2e-4 * hs)


def test_sharded_attach_stacks_uniform_geometry(monkeypatch):
    """The per-shard aux must stack: aligned layouts share one padded
    geometry; xchg routes share one treedef (shared blk census or a
    collective colored fallback)."""
    monkeypatch.setenv("PHOTON_SPARSE_GRAD", "xchg")
    monkeypatch.setenv("PHOTON_XCHG_REDUCE", "cumsum")
    monkeypatch.setenv("PHOTON_ROUTE_CACHE", "0")
    # Skewed ids so per-shard block censuses genuinely differ.
    rng = np.random.default_rng(3)
    n = 8 * 24
    ids = (1 + (rng.zipf(1.5, size=(n, K)) - 1) % (D - 1)).astype(np.int32)
    batch = SparseBatch(
        ids=jnp.asarray(ids),
        vals=jnp.asarray(rng.standard_normal((n, K)).astype(np.float32)),
        label=jnp.asarray((rng.random(n) < 0.5).astype(np.float32)),
        offset=jnp.zeros(n, jnp.float32),
        weight=jnp.ones(n, jnp.float32),
    )
    out = attach_feature_major(batch, shards=8, aligned_dim=D)
    assert out.al is not None and out.xchg is not None
    assert int(out.al.lo.shape[0]) == 8
    assert int(out.al.dup_map.shape[0]) == 8
    # One treedef means uniform meta (n_in/n_out/nc/ch/... are static).
    leaves = jax.tree.leaves(out.xchg)
    assert all(int(leaf.shape[0]) == 8 for leaf in leaves)


def test_sharded_lbfgs_convergence_xchg(monkeypatch):
    """A full sharded L-BFGS fit with the xchg kernel forced converges to
    the same optimum as single-device autodiff.  Iteration cap keeps the
    interpret-mode run inside the suite's wall-clock bar (converges in
    ~15 iterations at this shape)."""
    from photon_tpu.core.optimizers import OptimizerConfig, lbfgs

    cfg = OptimizerConfig(max_iterations=30)
    monkeypatch.setenv("PHOTON_ROUTE_CACHE", "0")
    monkeypatch.setenv("PHOTON_XCHG_REDUCE", "cumsum")
    batch = _batch(seed=11)
    obj = GlmObjective.create("logistic", RegularizationContext("l2", 1.0))
    w0 = jnp.zeros(D, jnp.float32)

    monkeypatch.setenv("PHOTON_SPARSE_GRAD", "autodiff")
    res_ref = lbfgs(lambda w: obj.value_and_grad(w, batch), w0, cfg)

    monkeypatch.setenv("PHOTON_SPARSE_GRAD", "xchg")
    mesh = create_mesh()
    sharded = shard_batch(batch, mesh, aligned_dim=D)
    dist = DistributedGlmObjective(obj, mesh)
    res_d = lbfgs(lambda w: dist.value_and_grad(w, sharded), w0, cfg)
    assert bool(res_d.converged)
    np.testing.assert_allclose(
        float(res_d.value), float(res_ref.value), rtol=1e-4
    )
    np.testing.assert_allclose(
        np.asarray(res_d.w), np.asarray(res_ref.w), atol=5e-2
    )


def test_bf16_storage_keeps_xchg_grad_consistent(monkeypatch):
    """batch_astype(bf16) after an xchg attach must keep the gradient
    consistent with the (converted) values the margins read: the baked
    vals_dest converts IN PLACE (elementwise casts commute with the
    static permutation), so both directions see one value stream and
    the fused path survives.  Checked sharded AND single-device against
    autodiff on the SAME converted batch (tight tolerance — same
    values, different reduction order)."""
    from photon_tpu.data.batch import batch_astype

    monkeypatch.setenv("PHOTON_ROUTE_CACHE", "0")
    monkeypatch.setenv("PHOTON_XCHG_REDUCE", "cumsum")
    monkeypatch.setenv("PHOTON_SPARSE_GRAD", "xchg")
    batch = _batch(seed=17)
    obj = GlmObjective.create("logistic", RegularizationContext("l2", 0.3))
    rng = np.random.default_rng(18)
    w = jnp.asarray(rng.standard_normal(D).astype(np.float32) * 0.1)

    fast = attach_feature_major(batch, aligned_dim=D)
    assert fast.xchg is not None and fast.xchg.vals_dest is not None
    fast16 = batch_astype(fast, jnp.bfloat16)
    # The baked stream converts IN PLACE (elementwise casts commute with
    # the static permutation), so the fused path survives bf16 storage.
    assert fast16.xchg.vals_dest.dtype == jnp.bfloat16
    v_x, g_x = obj.value_and_grad(w, fast16)

    monkeypatch.setenv("PHOTON_SPARSE_GRAD", "autodiff")
    b16 = batch_astype(batch, jnp.bfloat16)
    v_a, g_a = obj.value_and_grad(w, b16)
    np.testing.assert_allclose(float(v_x), float(v_a), rtol=2e-5)
    scale = max(float(np.abs(np.asarray(g_a)).max()), 1.0)
    np.testing.assert_allclose(
        np.asarray(g_x), np.asarray(g_a), rtol=2e-4, atol=2e-4 * scale
    )

    # Sharded: the STACKED baked stream converts in place the same way —
    # assert the aux actually survived (shard_batch can drop it on route
    # mismatch, which would let fallback kernels pass this vacuously)
    # and that xchg is what dispatches.
    monkeypatch.setenv("PHOTON_SPARSE_GRAD", "xchg")
    mesh = create_mesh()
    sharded16 = batch_astype(
        shard_batch(batch, mesh, aligned_dim=D), jnp.bfloat16
    )
    assert sharded16.xchg is not None
    assert sharded16.xchg.vals_dest.dtype == jnp.bfloat16
    dist = DistributedGlmObjective(obj, mesh)
    assert dist._sparse_kernel(w, sharded16) == "xchg"
    v_d, g_d = dist.value_and_grad(w, sharded16)
    np.testing.assert_allclose(float(v_d), float(v_a), rtol=2e-5)
    np.testing.assert_allclose(
        np.asarray(g_d), np.asarray(g_a), rtol=2e-4, atol=2e-4 * scale
    )
