"""Self-healing serving fleet (ISSUE 13): process-backed replicas,
health-checked supervision, canary-gated resurrection.

The contracts pinned here:

- crash mid-batch (``replica:crash``) reroutes in-flight work exactly once
  (no lost, no duplicated responses), the supervisor resurrects the
  replica, and its return to the dispatch set is gated on a mirrored-
  traffic parity probe ≤ 1e-3 vs the host oracle;
- a hang (``replica:hang`` — probe timeout / stale heartbeat) is treated
  the same as a crash: declared, torn down, rerouted, resurrected;
- a flapping replica (N deaths inside the window) is quarantined
  PERMANENTLY (``serving.replica_quarantined``) and never respawned;
- a replica resurrected across an active rollout rejoins on the CURRENT
  model, never the one it died on;
- a kill→resurrect cycle triggers ZERO jax compile events after warmup
  (thread replicas re-warm against cached programs);
- a failed spawn (``replica:spawn``, retriable) backs off exponentially
  and eventually rejoins;
- a SUBPROCESS replica (own Python/jax runtime, frame protocol over
  loopback) scores identically to the thread-backed scorer ≤ 1e-6,
  hot-swaps over the wire, and survives a real SIGKILL through the same
  supervision loop;
- the admission projection charges PADDED rows and the projection error
  is measurable (``serving.admission_error_s``);
- the pipelined ``AsyncScoringClient`` drives open-loop load through the
  socket itself;
- the telemetry report renders the supervisor timeline.
"""

from __future__ import annotations

import os
import signal
import time

import numpy as np
import pytest

from photon_tpu.data.synthetic import make_game_dataset
from photon_tpu.fault.injection import FaultPlan, set_plan
from photon_tpu.game.model import (
    FixedEffectModel,
    GameModel,
    RandomEffectModel,
)
from photon_tpu.models.glm import Coefficients, model_for_task
from photon_tpu.serving import (
    AsyncScoringClient,
    RequestShedError,
    ServingFleet,
    SupervisorPolicy,
    TrafficSpec,
    build_requests,
    generate_traffic,
    host_score_request,
    replay_open_loop,
    request_spec_for_dataset,
)
from photon_tpu.telemetry import TelemetrySession


@pytest.fixture(autouse=True)
def _no_fault_plan():
    yield
    set_plan(None)


def _fixture(seed=3, n_entities=40, fixed_dim=6, random_dim=4):
    data, _ = make_game_dataset(
        n_entities, 4, fixed_dim, random_dim, seed=seed
    )
    rng = np.random.default_rng(seed)
    keys = np.unique(data.id_columns["re0"])
    model = GameModel(
        coordinates={
            "fixed": FixedEffectModel(
                model_for_task("logistic_regression", Coefficients(
                    rng.standard_normal(fixed_dim).astype(np.float32)
                )),
                "global",
            ),
            "per_entity": RandomEffectModel(
                table=rng.standard_normal(
                    (len(keys), random_dim)
                ).astype(np.float32),
                keys=keys, entity_column="re0", shard_name="re0",
                task_type="logistic_regression",
            ),
        },
        task_type="logistic_regression",
    )
    return model, data


def _retrained(model: GameModel, seed: int) -> GameModel:
    rng = np.random.default_rng(seed)
    fixed = model.coordinates["fixed"]
    per_entity = model.coordinates["per_entity"]
    means = np.asarray(fixed.coefficients.means)
    return GameModel(
        coordinates={
            "fixed": FixedEffectModel(
                model_for_task(model.task_type, Coefficients(
                    (means + rng.standard_normal(means.shape)).astype(
                        np.float32
                    )
                )),
                fixed.shard_name,
            ),
            "per_entity": RandomEffectModel(
                table=rng.standard_normal(
                    (per_entity.num_entities, per_entity.dim)
                ).astype(np.float32),
                keys=per_entity.keys,
                entity_column=per_entity.entity_column,
                shard_name=per_entity.shard_name,
                task_type=model.task_type,
            ),
        },
        task_type=model.task_type,
    )


def _counter_total(session, name, **labels):
    total = 0
    for m in session.registry.snapshot()["counters"]:
        if m["name"] != name:
            continue
        if labels and any(
            str(m["labels"].get(k)) != str(v) for k, v in labels.items()
        ):
            continue
        total += m["value"]
    return total


def _fleet(model, data, session, replicas=2, max_batch=16, **kwargs):
    return ServingFleet(
        model, replicas=replicas,
        request_spec=request_spec_for_dataset(model, data),
        max_batch=max_batch, max_delay_s=0.001, telemetry=session,
        **kwargs,
    ).warmup()


def _supervisor(fleet, **overrides):
    defaults = dict(probe_interval_s=0.05, probe_deadline_s=10.0,
                    respawn_base_s=0.0, respawn_jitter=0.0)
    defaults.update(overrides)
    return fleet.supervise(SupervisorPolicy(**defaults), start=False)


def _resurrect(sup, replica, rounds=30, sleep_s=0.05) -> bool:
    for _ in range(rounds):
        sup.check_once()
        if replica.alive:
            return True
        time.sleep(sleep_s)
    return replica.alive


def _timeline(session, name="serving.supervisor_step"):
    steps = [
        (m["value"], m["labels"].get("replica"), m["labels"].get("phase"))
        for m in session.registry.snapshot()["gauges"]
        if m["name"] == name
    ]
    return [(rid, phase) for _, rid, phase in sorted(steps)]


# -- model wire artifact -------------------------------------------------------

def test_model_artifact_roundtrip_bit_exact(tmp_path):
    """The shared serving artifact (the frame-format model file every
    subprocess child loads) roundtrips bit-exactly — tables, coefficient
    vectors, and string/int key vocabularies alike."""
    from photon_tpu.serving.replica_proc import (
        load_model_artifact,
        save_model_artifact,
    )

    model, _ = _fixture(seed=5)
    # String keys exercise the <U* wire buffers.
    per = model.coordinates["per_entity"]
    import dataclasses

    string_model = GameModel(
        coordinates={
            "fixed": model.coordinates["fixed"],
            "per_entity": dataclasses.replace(
                per, keys=np.asarray([f"user-{k}" for k in per.keys])
            ),
        },
        task_type=model.task_type,
    )
    path = str(tmp_path / "model.bin")
    save_model_artifact(path, string_model, version=7)
    got, version = load_model_artifact(path)
    assert version == 7
    assert got.task_type == string_model.task_type
    assert list(got.coordinates) == list(string_model.coordinates)
    np.testing.assert_array_equal(
        np.asarray(got.coordinates["fixed"].coefficients.means),
        np.asarray(string_model.coordinates["fixed"].coefficients.means),
    )
    np.testing.assert_array_equal(
        np.asarray(got.coordinates["per_entity"].table),
        np.asarray(string_model.coordinates["per_entity"].table),
    )
    np.testing.assert_array_equal(
        got.coordinates["per_entity"].keys,
        string_model.coordinates["per_entity"].keys,
    )
    assert got.coordinates["per_entity"].keys.dtype.kind == "U"


# -- padded admission projection (ISSUE 13 satellite) --------------------------

def test_admission_projection_charges_padded_rows():
    """The per-replica wait projection folds bucket padding in (padded
    rows cost compute too) and the projection error lands in
    ``serving.admission_error_s``."""
    model, data = _fixture(seed=7)
    session = TelemetrySession("test-padded-admission")
    with _fleet(model, data, session, replicas=1) as fleet:
        replica = fleet.replicas[0]
        # Ladder is 8/16 for max_batch=16: 3 rows pad to 8, 20 rows chunk
        # into 16 + 8.
        assert replica.padded_rows(3) == 8
        assert replica.padded_rows(16) == 16
        assert replica.padded_rows(20) == 24
        replica.row_seconds = 0.5
        assert replica.projected_wait_s(3) == pytest.approx(
            (replica.pending_padded_rows() + 8) * 0.5
        )
        # Serve enough traffic that at least one dispatch runs with a live
        # pace estimate — that dispatch's projection error is recorded.
        replica.row_seconds = None
        for req in build_requests(data, model, [3] * 8):
            fleet.score(req)
    hists = {
        h["name"]: h for h in session.registry.snapshot()["histograms"]
    }
    assert "serving.admission_error_s" in hists
    assert hists["serving.admission_error_s"]["count"] >= 1


# -- open-loop load through the socket (ISSUE 13 satellite) --------------------

def test_async_client_drives_open_loop_through_socket():
    """The pipelined AsyncScoringClient: seq-tagged frames over a couple
    of connections, futures resolve out of submission order, admission
    sheds come back as typed frames, and ``replay_open_loop`` drives the
    TCP transport itself."""
    model, data = _fixture(seed=11)
    session = TelemetrySession("test-async-client")
    with _fleet(model, data, session, replicas=2) as fleet:
        server = fleet.serve()
        want = model.score(data)
        with AsyncScoringClient(server.address, connections=2,
                                telemetry=session) as client:
            requests = build_requests(data, model, [4] * 24)
            futures = [client.submit(r) for r in requests]
            pos = 0
            for fut in futures:
                rows = np.arange(pos, pos + 4) % data.num_examples
                np.testing.assert_allclose(
                    fut.result(timeout=30), want[rows],
                    rtol=1e-4, atol=1e-4,
                )
                pos = (pos + 4) % data.num_examples
            # A zero deadline sheds remotely; the shed rides back as a
            # typed frame and surfaces through the future.
            with pytest.raises(RequestShedError) as e:
                client.submit(requests[0], deadline_s=0.0).result(timeout=30)
            assert e.value.reason == "deadline"
            # The open-loop replay drives the socket directly.
            traffic = generate_traffic(
                data, model,
                TrafficSpec(requests=30, mean_rows=4, max_rows=16,
                            target_qps=400.0, seed=2),
            )
            outcomes = replay_open_loop(client.submit, traffic,
                                        timeout_s=60.0)
        assert all(o.status == "ok" for o in outcomes)
        for out in outcomes:
            np.testing.assert_allclose(
                out.scores, host_score_request(model, out.item.request),
                rtol=1e-4, atol=1e-4,
            )
            assert out.finished_at_s is not None


# -- crash: exactly-once reroute + resurrection --------------------------------

def test_crash_mid_stream_reroutes_exactly_once_then_resurrects():
    """ISSUE 13 acceptance: ``replica:crash`` mid-traffic yields
    exactly-once responses (none lost, none duplicated), then the
    supervisor re-spawns, re-warms, and rejoins the replica through the
    canary parity gate ≤ 1e-3 vs the host oracle."""
    model, data = _fixture(seed=13)
    session = TelemetrySession("test-crash-resurrect")
    with _fleet(model, data, session, replicas=2) as fleet:
        sup = _supervisor(fleet)
        requests = build_requests(data, model, [4] * 30)
        want = model.score(data)
        set_plan(FaultPlan.parse("replica:crash:replica=r0:times=1"))
        futures = [fleet.submit(r) for r in requests]
        results = [f.result(timeout=60) for f in futures]
        set_plan(None)
        pos = 0
        for got in results:  # every future resolved with its OWN scores
            rows = np.arange(pos, pos + 4) % data.num_examples
            np.testing.assert_allclose(got, want[rows], rtol=1e-4,
                                       atol=1e-4)
            pos = (pos + 4) % data.num_examples
        r0 = fleet.replicas[0]
        assert not r0.alive and r0.death_cause == "crash"
        assert _resurrect(sup, r0)
        # Post-rejoin: the resurrected replica serves its own correct
        # scores again (direct submit — dispatch-set membership is
        # asserted via alive + generation).
        assert r0.generation == 1
        got = r0.submit(requests[0]).result(timeout=30)
        np.testing.assert_allclose(got, want[np.arange(4)], rtol=1e-3,
                                   atol=1e-3)
    assert _counter_total(
        session, "serving.replica_deaths", replica="r0", cause="crash"
    ) == 1
    assert _counter_total(
        session, "serving.replica_resurrections", replica="r0"
    ) == 1
    phases = [p for rid, p in _timeline(session) if rid == "r0"]
    assert phases == ["died-crash", "respawn", "rejoin-probe", "rejoined"]


def test_hang_probe_timeout_treated_like_crash():
    """ISSUE 13 satellite: a wedged replica (``replica:hang`` — no
    failure, just no progress) is detected by the supervisor's deadline,
    declared dead like a crash, its in-flight futures reroute
    exactly-once, and it resurrects the same way."""
    model, data = _fixture(seed=17)
    session = TelemetrySession("test-hang")
    with _fleet(model, data, session, replicas=2) as fleet:
        sup = _supervisor(fleet, probe_deadline_s=0.5, hang_timeout_s=0.2)
        requests = build_requests(data, model, [4] * 20)
        want = model.score(data)
        set_plan(FaultPlan.parse("replica:hang:replica=r0:times=1"))
        futures = [fleet.submit(r) for r in requests]
        # Give the wedge time to latch (r0's batcher thread is stuck in
        # the injected hang; its heartbeat goes stale with work pending).
        time.sleep(0.4)
        sup.check_once()  # declares the hang, abandons, reroutes
        results = [f.result(timeout=60) for f in futures]
        set_plan(None)
        pos = 0
        for got in results:
            rows = np.arange(pos, pos + 4) % data.num_examples
            np.testing.assert_allclose(got, want[rows], rtol=1e-4,
                                       atol=1e-4)
            pos = (pos + 4) % data.num_examples
        r0 = fleet.replicas[0]
        assert _counter_total(
            session, "serving.replica_deaths", replica="r0", cause="hang"
        ) == 1
        assert _resurrect(sup, r0)
    assert _counter_total(
        session, "serving.replica_resurrections", replica="r0"
    ) == 1
    phases = [p for rid, p in _timeline(session) if rid == "r0"]
    assert phases[0] == "died-hang" and phases[-1] == "rejoined"


def test_flapping_replica_quarantined_permanently():
    """ISSUE 13 satellite: N deaths inside the flap window quarantine the
    replica permanently — no further respawn attempts, the fleet keeps
    serving on the survivor."""
    model, data = _fixture(seed=19)
    session = TelemetrySession("test-flap")
    with _fleet(model, data, session, replicas=2) as fleet:
        sup = _supervisor(fleet, max_deaths=2, flap_window_s=60.0)
        (req,) = build_requests(data, model, [4])
        want = host_score_request(model, req)
        r0 = fleet.replicas[0]
        # Death #1 -> resurrected.
        set_plan(FaultPlan.parse("replica:crash:replica=r0:times=1"))
        fleet.submit(req).result(timeout=30)
        set_plan(None)
        assert not r0.alive
        assert _resurrect(sup, r0)
        # Death #2 inside the window -> quarantined, never respawned.
        set_plan(FaultPlan.parse("replica:crash:replica=r0:times=1"))
        fleet.submit(req).result(timeout=30)
        set_plan(None)
        assert not r0.alive
        for _ in range(5):
            sup.check_once()
        assert r0.quarantined and not r0.alive
        assert _counter_total(
            session, "serving.replica_quarantined", replica="r0"
        ) == 1
        assert _counter_total(
            session, "serving.replica_resurrections", replica="r0"
        ) == 1
        assert _counter_total(
            session, "serving.replica_deaths", replica="r0"
        ) == 2
        # The fleet still serves (through the survivor).
        np.testing.assert_allclose(
            fleet.score(req), want, rtol=1e-4, atol=1e-4
        )
        assert ("r0", "quarantined") in _timeline(session)


def test_resurrection_during_rollout_rejoins_on_new_model():
    """ISSUE 13 satellite: a replica that dies before/through a rollout
    comes back on the CURRENT model — the supervisor re-syncs the model
    version at rejoin, so the fleet is never split across versions."""
    model, data = _fixture(seed=23)
    retrained = _retrained(model, seed=29)
    session = TelemetrySession("test-rollout-resurrect")
    with _fleet(model, data, session, replicas=2) as fleet:
        sup = _supervisor(fleet)
        requests = build_requests(data, model, [4] * 6)
        for req in requests:
            fleet.score(req)
        set_plan(FaultPlan.parse("replica:crash:replica=r0:times=1"))
        futs = [fleet.submit(r) for r in requests]
        [f.result(timeout=30) for f in futs]
        set_plan(None)
        r0 = fleet.replicas[0]
        assert not r0.alive
        # The rollout lands while r0 is dead: the canary is the survivor.
        fleet.rollout(retrained, probe_requests=requests[:2])
        assert fleet.current_model()[1] == 1
        assert _resurrect(sup, r0)
        # r0 rejoined on the NEW model.
        want_new = retrained.score(data)
        got = r0.submit(requests[0]).result(timeout=30)
        np.testing.assert_allclose(
            got, want_new[np.arange(4)], rtol=1e-3, atol=1e-3
        )


def test_kill_resurrect_cycle_zero_recompiles():
    """ISSUE 13 acceptance: a full kill→resurrect cycle triggers ZERO jax
    compile events after warmup — the thread replica's re-warm hits the
    cached bucket programs, and the rejoin probes ride them."""
    import jax.monitoring
    from jax._src import monitoring as monitoring_src

    model, data = _fixture(seed=31)
    session = TelemetrySession("test-zero-recompile")
    compile_events = []

    def listener(event, **kwargs):
        if "compile" in event:
            compile_events.append(event)

    with _fleet(model, data, session, replicas=2) as fleet:
        sup = _supervisor(fleet)
        compiled = fleet.compilations
        requests = build_requests(data, model, [4] * 12)
        want = model.score(data)
        jax.monitoring.register_event_listener(listener)
        try:
            set_plan(FaultPlan.parse("replica:crash:replica=r0:times=1"))
            futs = [fleet.submit(r) for r in requests]
            [f.result(timeout=30) for f in futs]
            set_plan(None)
            assert _resurrect(sup, fleet.replicas[0])
            pos = 0
            for req in requests:  # post-rejoin traffic across the fleet
                rows = np.arange(pos, pos + 4) % data.num_examples
                np.testing.assert_allclose(
                    fleet.score(req), want[rows], rtol=1e-4, atol=1e-4
                )
                pos = (pos + 4) % data.num_examples
        finally:
            monitoring_src._unregister_event_listener_by_callback(listener)
        assert fleet.compilations == compiled
    assert compile_events == []


def test_spawn_failure_backs_off_and_eventually_rejoins():
    """``replica:spawn`` (retriable): failed respawn attempts count as
    ``serving.respawn_failures``, back off with the capped exponential
    policy, and a later attempt completes the resurrection."""
    model, data = _fixture(seed=37)
    session = TelemetrySession("test-spawn-backoff")
    with _fleet(model, data, session, replicas=2) as fleet:
        sup = _supervisor(fleet, respawn_base_s=0.05)
        (req,) = build_requests(data, model, [4])
        set_plan(FaultPlan.parse(
            "replica:crash:replica=r0:times=1,"
            "replica:spawn:replica=r0:times=2"
        ))
        fleet.submit(req).result(timeout=30)
        r0 = fleet.replicas[0]
        assert not r0.alive
        sup.check_once()  # death noted; respawn attempt 1 hits the fault
        assert _counter_total(
            session, "serving.respawn_failures", replica="r0"
        ) == 1
        sup.check_once()  # still inside the backoff window: no attempt
        assert _counter_total(
            session, "serving.respawn_failures", replica="r0"
        ) == 1
        assert _resurrect(sup, r0, rounds=40, sleep_s=0.05)
        set_plan(None)
        assert _counter_total(
            session, "serving.respawn_failures", replica="r0"
        ) == 2
        assert _counter_total(
            session, "serving.replica_resurrections", replica="r0"
        ) == 1
        # The timeline keeps one gauge per (replica, phase) — the failure
        # COUNT is the respawn_failures counter above; the timeline pins
        # the order: the last failure precedes the successful rejoin.
        phases = [p for rid, p in _timeline(session) if rid == "r0"]
        assert "respawn-failed" in phases
        assert phases.index("respawn-failed") < phases.index("rejoined")
        assert phases[-1] == "rejoined"


def test_probe_timeout_on_busy_replica_is_not_a_hang():
    """A saturated-but-PROGRESSING replica that misses the probe deadline
    by queueing is busy, not hung: only a stale heartbeat alongside the
    missed probe declares — otherwise a load spike would cascade into a
    mass abandon and, repeated, a permanent quarantine of a healthy
    fleet."""
    from concurrent.futures import Future

    from photon_tpu.fault.watchdog import heartbeat

    model, data = _fixture(seed=53)
    session = TelemetrySession("test-busy-not-hung")
    with _fleet(model, data, session, replicas=2) as fleet:
        sup = _supervisor(fleet, probe_deadline_s=0.1, hang_timeout_s=0.5)
        r0 = fleet.replicas[0]
        r0.submit = lambda request: Future()  # the probe never resolves
        heartbeat(r0.heartbeat_site)  # fresh scoring progress
        sup._health_check(r0)
        assert r0.alive  # busy, not hung
        time.sleep(0.6)  # now the progress mark is stale too
        sup._health_check(r0)
        assert not r0.alive and r0.death_cause == "hang"


def test_parity_gate_rejects_nan_and_shape_mismatch():
    """The probe/rejoin/rollout parity gate fails loudly on non-finite or
    misshapen served answers — ``np.abs(nan) > tol`` is False, so a
    NaN-scoring replica (or canary!) would otherwise slide through the
    gate and be promoted fleet-wide."""
    from photon_tpu.serving import router, supervisor
    from photon_tpu.serving.supervisor import parity_worst

    # The ONE comparison: the rollout canary gate and the supervision
    # probes must share this exact function, or their NaN semantics can
    # silently diverge.
    assert supervisor.parity_worst is router.parity_worst
    assert parity_worst([1.0, 2.0], np.asarray([1.0, 2.0])) == 0.0
    assert parity_worst([1.0, 2.5], [1.0, 2.0]) == pytest.approx(0.5)
    assert parity_worst([1.0, np.nan], [1.0, 2.0]) == float("inf")
    assert parity_worst([1.0], [1.0, 2.0]) == float("inf")
    assert parity_worst([], []) == 0.0


def test_failed_rollout_keeps_model_version_monotonic():
    """A failed rollout restores the MODEL but never the version number:
    reusing a version would let a probe that captured the failed
    rollout's (model, version) pass the supervisor's stale-oracle check
    against a later rollout's different model."""
    model, data = _fixture(seed=59)
    retrained = _retrained(model, seed=61)
    session = TelemetrySession("test-rollout-version")
    with _fleet(model, data, session, replicas=2) as fleet:
        probes = build_requests(data, model, [4])
        assert fleet.current_model() == (model, 0)

        def bad_oracle(req):
            return np.full(req.num_rows, 1e6, np.float32)

        with pytest.raises(Exception):
            fleet.rollout(retrained, probe_requests=probes,
                          probe_oracle=bad_oracle)
        m, v = fleet.current_model()
        assert m is model and v == 2  # bump + rollback-bump: monotonic
        assert not fleet.rollout_in_progress()
        fleet.rollout(retrained, probe_requests=probes)
        m2, v2 = fleet.current_model()
        assert m2 is retrained and v2 == 3


# -- subprocess backend --------------------------------------------------------

def test_subprocess_replicas_end_to_end():
    """ISSUE 13 acceptance (subprocess backend): children with their own
    Python/jax runtimes serve over the frame protocol — scores match the
    thread-backed scorer ≤ 1e-6 on identical requests; a model hot-swaps
    over the wire (canary rollout); a real SIGKILL mid-stream reroutes
    exactly-once, the supervisor detects the exit code, re-spawns a fresh
    child from the CURRENT model artifact, and gates its rejoin on the
    parity probe."""
    from photon_tpu.serving.scorer import GameScorer

    model, data = _fixture(seed=41)
    retrained = _retrained(model, seed=43)
    session = TelemetrySession("test-subprocess")
    spec = request_spec_for_dataset(model, data)
    fleet = ServingFleet(
        model, replicas=2, backend="subprocess", request_spec=spec,
        max_batch=16, max_delay_s=0.001, telemetry=session,
    ).warmup()
    try:
        requests = build_requests(data, model, [1, 5, 16, 4, 4, 4])
        # Parity vs the thread-backed scorer on identical requests.
        reference = GameScorer(model, request_spec=spec,
                               max_batch=16).warmup()
        for req in requests:
            got = fleet.score(req)
            want = reference.score_batch(req)
            np.testing.assert_allclose(got, want, rtol=0, atol=1e-6)
        # Liveness ping frame reports the child's state.
        pong = fleet.replicas[0].ping(10.0)
        assert pong["kind"] == "pong" and pong["compilations"] >= 1
        # Canary rollout over the wire: children swap from the shared
        # artifact with zero parent-side compiles.
        compiled = fleet.compilations
        fleet.rollout(retrained, probe_requests=requests[:2])
        assert fleet.compilations == compiled
        want_new = retrained.score(data)
        got = fleet.score(requests[3])
        np.testing.assert_allclose(
            got, want_new[np.arange(22, 26) % data.num_examples],
            rtol=1e-4, atol=1e-4,
        )
        # A REAL crash: SIGKILL the child mid-stream.
        sup = fleet.supervise(
            SupervisorPolicy(probe_interval_s=0.05, probe_deadline_s=30.0,
                             respawn_base_s=0.0, respawn_jitter=0.0),
            start=False,
        )
        r0 = fleet.replicas[0]
        os.kill(r0.child_pid, signal.SIGKILL)
        time.sleep(0.2)
        futs = [fleet.submit(r) for r in requests]
        results = [f.result(timeout=60) for f in futs]  # exactly-once
        for req, got in zip(requests, results):
            np.testing.assert_allclose(
                got, host_score_request(retrained, req),
                rtol=1e-4, atol=1e-4,
            )
        assert _resurrect(sup, r0, rounds=60, sleep_s=0.2)
        assert r0.poll_exit() is None  # a fresh child is running
        got = r0.submit(requests[1]).result(timeout=30)
        np.testing.assert_allclose(
            got, host_score_request(retrained, requests[1]),
            rtol=1e-3, atol=1e-3,
        )
    finally:
        fleet.close()
    assert _counter_total(
        session, "serving.replica_deaths", replica="r0", cause="crash"
    ) == 1
    assert _counter_total(
        session, "serving.replica_resurrections", replica="r0"
    ) == 1


def test_child_stats_frame_merges_into_parent_report():
    """ISSUE 14 satellite (ROADMAP fleet edge (e)): a subprocess replica's
    scorer-level ``serving.*`` counters accrue in the CHILD process; the
    ``stats`` control frame pulls them and merges deltas into the parent's
    registry under the same names + a replica label — idempotent across
    repeated pulls — and the fleet report renders the child-scorer row."""
    from photon_tpu.telemetry.report import render_markdown

    model, data = _fixture(seed=51)
    session = TelemetrySession("test-child-stats")
    spec = request_spec_for_dataset(model, data)
    fleet = ServingFleet(
        model, replicas=1, backend="subprocess", request_spec=spec,
        max_batch=16, max_delay_s=0.001, telemetry=session,
    ).warmup()
    try:
        requests = build_requests(data, model, [4, 9, 2])
        for req in requests:
            fleet.score(req)
        r0 = fleet.replicas[0]
        merged = r0.pull_stats()
        assert merged  # counters crossed the wire
        # Delta merge: a second pull with no new traffic adds nothing.
        assert r0.pull_stats() == {}
        syncs_after_first = _counter_total(
            session, "serving.host_syncs", replica="r0"
        )
        assert syncs_after_first == len(requests)  # 1 host sync per batch
        # The supervisor's health pass pulls too (new traffic arrives, the
        # next check_once folds it in — plus its own probe batch).
        for req in requests:
            fleet.score(req)
        sup = fleet.supervise(
            SupervisorPolicy(probe_interval_s=10.0, probe_deadline_s=30.0),
            start=False,
        )
        sup.check_once()
        syncs = _counter_total(session, "serving.host_syncs", replica="r0")
        batches = _counter_total(session, "serving.batches", replica="r0")
        assert syncs >= 2 * len(requests)
        assert syncs == batches  # the child's one-sync-per-batch contract
    finally:
        fleet.close()
    report = session.build_report()
    text = render_markdown(report)
    assert "child scorers" in text
    assert "r0: host_syncs=" in text


# -- report renderer -----------------------------------------------------------

def test_report_renders_supervisor_timeline():
    """ISSUE 13 satellite: the "Serving fleet" report section grows the
    supervisor block — deaths/resurrections/quarantine summary plus the
    event timeline."""
    from photon_tpu.telemetry.report import render_markdown

    model, data = _fixture(seed=47)
    session = TelemetrySession("test-supervisor-report")
    with _fleet(model, data, session, replicas=2) as fleet:
        sup = _supervisor(fleet, max_deaths=2)
        (req,) = build_requests(data, model, [4])
        for _ in range(2):
            set_plan(FaultPlan.parse("replica:crash:replica=r0:times=1"))
            fleet.submit(req).result(timeout=30)
            set_plan(None)
            _resurrect(sup, fleet.replicas[0])
        for _ in range(3):
            sup.check_once()
    report = {
        "driver": "test", "run_id": "x", "status": "ok",
        "metrics": session.registry.snapshot(),
    }
    md = render_markdown(report)
    assert "## Serving fleet" in md
    assert "**supervisor**" in md
    assert "resurrections=1" in md
    assert "quarantined=1 (r0)" in md
    assert "**supervisor timeline**" in md
    assert "r0:died-crash" in md and "r0:rejoined" in md
    assert "r0:quarantined" in md


# -- fleet-wide rollback (ISSUE 15 satellite / ROADMAP fleet edge (d)) --------

def test_fleet_wide_parity_regression_rolls_back_not_quarantines():
    """EVERY replica failing its known-answer probe right after a swap is
    a fleet-wide regression: the supervisor triggers ONE rollout rollback
    to the predecessor artifact — zero deaths, zero quarantines, every
    replica stays in the dispatch set serving the restored model."""
    model, data = _fixture(seed=61)
    model2 = _retrained(model, seed=62)
    skewed = _retrained(model, seed=63)  # what the replicas "really" serve
    session = TelemetrySession("t-fleet-rollback")
    fleet = _fleet(model, data, session)
    sup = _supervisor(fleet)
    probes = build_requests(data, model2, [4, 4])
    fleet.rollout(model2, probe_requests=probes)
    assert fleet.current_model()[0] is model2
    # Simulate post-swap fleet-wide artifact skew: every replica silently
    # serves a model that disagrees with the published one's oracle.
    for replica in fleet.replicas:
        replica.scorer.swap_model(skewed)
    sup.check_once()
    assert _counter_total(session, "serving.rollout_rollbacks") == 1
    assert _counter_total(session, "serving.replica_deaths") == 0
    assert _counter_total(session, "serving.replica_quarantined") == 0
    assert all(r.alive for r in fleet.replicas)
    # Rolled back to the PREDECESSOR (version monotonic), serving parity
    # restored end to end.
    current, version = fleet.current_model()
    assert current is model
    assert version == 2
    req = build_requests(data, model, [6])[0]
    got = fleet.score(req)
    np.testing.assert_allclose(
        got, host_score_request(model, req), atol=1e-5
    )
    # The next pass is clean (no lingering suspicion), and the timeline
    # carries the fleet-rollback marks.
    sup.check_once()
    assert _counter_total(session, "serving.rollout_rollbacks") == 1
    phases = [phase for _rid, phase in _timeline(session)]
    assert phases.count("fleet-rollback") == len(fleet.replicas)
    fleet.close()


def test_partial_parity_failure_still_declares_per_replica():
    """One replica wrong, the rest fine: NOT a fleet regression — the
    existing per-replica parity declaration (death + resurrection path)
    applies, and no rollback fires."""
    model, data = _fixture(seed=67)
    model2 = _retrained(model, seed=68)
    skewed = _retrained(model, seed=69)
    session = TelemetrySession("t-partial-parity")
    fleet = _fleet(model, data, session)
    sup = _supervisor(fleet, resurrect=False)
    fleet.rollout(model2, probe_requests=build_requests(data, model2, [4]))
    fleet.replicas[0].scorer.swap_model(skewed)
    sup.check_once()
    assert _counter_total(session, "serving.rollout_rollbacks") == 0
    assert _counter_total(
        session, "serving.replica_deaths", cause="parity"
    ) == 1
    assert not fleet.replicas[0].alive
    assert fleet.replicas[1].alive
    assert fleet.current_model()[0] is model2
    fleet.close()


def test_rollback_without_predecessor_falls_back_to_declarations():
    """A fleet that never completed a rollout has no predecessor: the
    all-replica parity failure declares per-replica exactly as before
    (rollback_to_previous returns False)."""
    model, data = _fixture(seed=71)
    skewed = _retrained(model, seed=72)
    session = TelemetrySession("t-no-predecessor")
    fleet = _fleet(model, data, session)
    sup = _supervisor(fleet, resurrect=False)
    assert fleet.rollback_to_previous() is False
    for replica in fleet.replicas:
        replica.scorer.swap_model(skewed)
    sup.check_once()
    assert _counter_total(session, "serving.rollout_rollbacks") == 0
    assert _counter_total(
        session, "serving.replica_deaths", cause="parity"
    ) == len(fleet.replicas)
    fleet.close()
