"""GAME data IO, model IO, and end-to-end GAME driver tests.

Mirrors the reference's driver integration tests (SURVEY.md §4: full
GameTrainingDriver runs on small resource fixtures asserting output model
files + metric thresholds, and train→save→load→score round-trips)."""

import json
import os

import numpy as np
import pytest

from photon_tpu.data.game_io import read_game_avro, write_game_avro
from photon_tpu.data.synthetic import make_game_dataset
from photon_tpu.game.model_io import load_game_model, save_game_model


def small_game_data():
    return make_game_dataset(
        n_entities=25, rows_per_entity_mean=4, fixed_dim=6, random_dim=4, seed=3
    )


def test_game_avro_round_trip(tmp_path):
    data, index_maps = small_game_data()
    path = str(tmp_path / "train.avro")
    write_game_avro(path, data, index_maps)

    bags = {name: name for name in data.shards}
    loaded, loaded_maps = read_game_avro(path, bags, ["re0"])

    assert loaded.num_examples == data.num_examples
    np.testing.assert_allclose(loaded.label, data.label)
    np.testing.assert_allclose(loaded.weight, data.weight)
    # Entity ids come back as strings of the original ints.
    assert [int(x) for x in loaded.id_columns["re0"]] == list(
        data.id_columns["re0"]
    )
    # Margins must agree under each side's own indexing: compare via a
    # fixed coefficient vector keyed by feature name.
    for shard_name in data.shards:
        imap, lmap = index_maps[shard_name], loaded_maps[shard_name]
        rng = np.random.default_rng(1)
        w_by_key = {k: rng.standard_normal() for k in imap.keys()}
        dense = data.shards[shard_name].x
        w_orig = np.array([w_by_key[imap.get_key(i)] for i in range(len(imap))])
        sp = loaded.shards[shard_name]
        w_load = np.array(
            [w_by_key.get(lmap.get_key(i), 0.0) for i in range(len(lmap))]
        )
        np.testing.assert_allclose(
            dense @ w_orig,
            (w_load[sp.ids] * sp.vals).sum(axis=1),
            rtol=1e-5, atol=1e-5,
        )


def test_read_with_fixed_maps_drops_unknown_features(tmp_path):
    data, index_maps = small_game_data()
    path = str(tmp_path / "train.avro")
    write_game_avro(path, data, index_maps)
    bags = {name: name for name in data.shards}
    # Re-read with the ORIGINAL maps: dims must match the training dims.
    loaded, maps = read_game_avro(path, bags, ["re0"], index_maps=index_maps)
    assert maps is index_maps
    for name in data.shards:
        assert loaded.shards[name].dim == data.shards[name].dim


def test_game_model_io_round_trip(tmp_path, game_model_fixture):
    model, index_maps, data = game_model_fixture
    save_game_model(str(tmp_path / "m"), model, index_maps)
    loaded, _ = load_game_model(str(tmp_path / "m"))
    assert set(loaded.coordinates) == set(model.coordinates)
    np.testing.assert_allclose(
        loaded.score(data), model.score(data), rtol=1e-5, atol=1e-5
    )


def test_game_model_io_json_round_trip(tmp_path, game_model_fixture):
    model, index_maps, data = game_model_fixture
    save_game_model(str(tmp_path / "mj"), model, index_maps, fmt="json")
    loaded, _ = load_game_model(str(tmp_path / "mj"))
    np.testing.assert_allclose(
        loaded.score(data), model.score(data), rtol=1e-5, atol=1e-5
    )


@pytest.fixture(scope="module")
def game_model_fixture():
    """A trained small GAME model (fixed + one random effect)."""
    from photon_tpu.core.objective import RegularizationContext
    from photon_tpu.core.optimizers import OptimizerConfig
    from photon_tpu.core.problem import ProblemConfig
    from photon_tpu.game.coordinate import (
        FixedEffectCoordinateConfig,
        RandomEffectCoordinateConfig,
    )
    from photon_tpu.game.estimator import GameEstimator, GameOptimizationConfiguration

    data, index_maps = small_game_data()
    problem = ProblemConfig(
        regularization=RegularizationContext("l2", 1.0),
        optimizer_config=OptimizerConfig(max_iterations=10),
        variance_computation="simple",
    )
    config = GameOptimizationConfiguration(
        coordinates={
            "fixed": FixedEffectCoordinateConfig("global", problem),
            "per_entity": RandomEffectCoordinateConfig("re0", "re0", problem),
        },
        descent_iterations=1,
    )
    estimator = GameEstimator("logistic_regression", data)
    result = estimator.fit([config])[0]
    return result.model, index_maps, data


def test_train_and_score_game_drivers_synthetic(tmp_path):
    from photon_tpu.drivers import score_game, train_game

    out = str(tmp_path / "out")
    spec = "synthetic-game:32:4:8:4:1:7"
    summary = train_game.run(train_game.build_parser().parse_args([
        "--backend", "cpu",
        "--input", spec,
        "--coordinate", "fixed:type=fixed,shard=global,reg_weights=0.1+1,max_iters=10",
        "--coordinate", "per_user:type=random,shard=re0,entity=re0,reg_weights=1,max_iters=8",
        "--descent-iterations", "2",
        "--validation-split", "0.25",
        "--output-dir", out,
    ]))
    assert os.path.isdir(os.path.join(out, "best_model", "fixed-effect", "fixed"))
    assert os.path.isdir(
        os.path.join(out, "best_model", "random-effect", "per_user")
    )
    assert len(summary["sweep"]) == 2  # reg sweep: 0.1 and 1 on the fixed coord
    assert summary["best_metrics"]["AUC"] > 0.6

    score_out = str(tmp_path / "scored")
    result = score_game.run(score_game.build_parser().parse_args([
        "--backend", "cpu",
        "--input", spec,
        "--model", os.path.join(out, "best_model"),
        "--evaluators", "AUC,SHARDED_AUC:re0",
        "--output-dir", score_out,
    ]))
    assert result["metrics"]["AUC"] > 0.6
    assert os.path.exists(os.path.join(score_out, "scores.txt"))
    with open(os.path.join(score_out, "metrics.json")) as f:
        assert "SHARDED_AUC:re0" in json.load(f)


def test_index_features_driver_and_fixed_index_training(tmp_path):
    """index_features builds per-shard maps; train_game consumes them via
    --index-maps (the reference's FeatureIndexingJob -> training flow)."""
    from photon_tpu.drivers import index_features, train_game

    data, index_maps = small_game_data()
    avro_path = str(tmp_path / "train.avro")
    write_game_avro(avro_path, data, index_maps)

    maps_dir = str(tmp_path / "maps")
    summary = index_features.run(index_features.build_parser().parse_args([
        "--input", avro_path,
        "--feature-bags", "global=global,re0=re0",
        "--output-dir", maps_dir,
    ]))
    assert summary["num_records"] == data.num_examples
    # Feature counts match the original maps (intercept included).
    for shard in ("global", "re0"):
        assert summary["shards"][shard]["num_features"] == len(index_maps[shard])
        assert os.path.exists(
            os.path.join(maps_dir, f"feature_index_{shard}.json")
        )

    out = str(tmp_path / "out")
    train_summary = train_game.run(train_game.build_parser().parse_args([
        "--backend", "cpu",
        "--input", avro_path,
        "--feature-bags", "global=global,re0=re0",
        "--id-columns", "re0",
        "--index-maps", maps_dir,
        "--coordinate", "fixed:type=fixed,shard=global,max_iters=8",
        "--coordinate", "per_user:type=random,shard=re0,entity=re0,max_iters=6",
        "--validation-split", "0.25",
        "--output-dir", out,
    ]))
    assert train_summary["best_metrics"]["AUC"] > 0.55


def test_train_game_checkpoint_and_resume(tmp_path):
    """--checkpoint writes a per-iteration model; a resumed run warm-starts
    from it (SURVEY.md §5 restart-from-checkpoint)."""
    from photon_tpu.drivers import train_game

    out = str(tmp_path / "out")
    # Same shapes/iteration counts as the synthetic train+score test above so
    # the persistent compilation cache shares the compiled GAME programs.
    spec = "synthetic-game:32:4:8:4:1:11"
    base = [
        "--backend", "cpu",
        "--input", spec,
        "--coordinate", "fixed:type=fixed,shard=global,max_iters=10",
        "--coordinate", "per_user:type=random,shard=re0,entity=re0,max_iters=8",
        "--descent-iterations", "2",
        "--validation-split", "0.25",
    ]
    train_game.run(train_game.build_parser().parse_args(
        base + ["--checkpoint", "--output-dir", out]
    ))
    ckpt = os.path.join(out, "checkpoint", "latest")
    assert os.path.exists(os.path.join(ckpt, "metadata.json"))

    out2 = str(tmp_path / "resumed")
    summary = train_game.run(train_game.build_parser().parse_args(
        base + ["--output-dir", out2, "--initial-model", ckpt]
    ))
    assert summary["best_metrics"]["AUC"] > 0.55


def test_train_game_driver_avro_end_to_end(tmp_path):
    """Full Avro path: synthetic -> Avro file -> train -> warm-start retrain."""
    from photon_tpu.drivers import train_game

    data, index_maps = small_game_data()
    avro_path = str(tmp_path / "train.avro")
    write_game_avro(avro_path, data, index_maps)

    out = str(tmp_path / "out")
    common_args = [
        "--backend", "cpu",
        "--input", avro_path,
        "--feature-bags", "global=global,re0=re0",
        "--id-columns", "re0",
        "--coordinate", "fixed:type=fixed,shard=global,max_iters=10",
        "--coordinate", "per_user:type=random,shard=re0,entity=re0,max_iters=8",
        "--validation-split", "0.25",
    ]
    summary = train_game.run(train_game.build_parser().parse_args(
        common_args + ["--output-dir", out]
    ))
    assert summary["best_metrics"]["AUC"] > 0.55

    # Warm start with the fixed coordinate locked (partial retraining).
    out2 = str(tmp_path / "out2")
    summary2 = train_game.run(train_game.build_parser().parse_args(
        common_args + [
            "--output-dir", out2,
            "--initial-model", os.path.join(out, "best_model"),
            "--locked-coordinates", "fixed",
        ]
    ))
    assert summary2["best_metrics"]["AUC"] > 0.55


def test_streamed_scoring_matches_whole(tmp_path, monkeypatch):
    """score_game --stream over part files must reproduce the whole-set
    HOST-path scores and metrics exactly (chunk boundaries cannot change
    results); the default whole-set route — the serving gather tables
    (ISSUE 9) — must agree with both to f32 tolerance."""
    import numpy as np

    from photon_tpu.drivers import score_game, train_game
    from photon_tpu.game.data import take_rows

    data, index_maps = small_game_data()
    avro_path = str(tmp_path / "train.avro")
    write_game_avro(avro_path, data, index_maps)
    out = str(tmp_path / "out")
    train_game.run(train_game.build_parser().parse_args([
        "--backend", "cpu",
        "--input", avro_path,
        "--feature-bags", "global=global,re0=re0",
        "--id-columns", "re0",
        "--coordinate", "fixed:type=fixed,shard=global,max_iters=8",
        "--coordinate", "per_user:type=random,shard=re0,entity=re0,max_iters=6",
        "--validation-split", "0.25",
        "--output-dir", out,
    ]))

    parts = tmp_path / "parts"
    parts.mkdir()
    n = data.num_examples
    for pi, (lo, hi) in enumerate([(0, n // 2), (n // 2, n)]):
        write_game_avro(
            str(parts / f"part-{pi}.avro"),
            take_rows(data, np.arange(lo, hi)), index_maps,
        )

    common_args = [
        "--backend", "cpu",
        "--model", os.path.join(out, "best_model"),
        "--feature-bags", "global=global,re0=re0",
        "--id-columns", "re0",
        "--evaluators", "AUC,SHARDED_AUC:re0",
    ]
    device_whole = score_game.run(score_game.build_parser().parse_args(
        common_args + ["--input", avro_path,
                       "--output-dir", str(tmp_path / "s_device")]))
    monkeypatch.setenv("PHOTON_BATCH_SCORER", "host")
    whole = score_game.run(score_game.build_parser().parse_args(
        common_args + ["--input", avro_path,
                       "--output-dir", str(tmp_path / "s_whole")]))
    monkeypatch.delenv("PHOTON_BATCH_SCORER")
    streamed = score_game.run(score_game.build_parser().parse_args(
        common_args + ["--input", str(parts / "*.avro"), "--stream",
                       "--output-dir", str(tmp_path / "s_stream")]))

    assert streamed["streamed"] and streamed["num_scored"] == n
    s_whole = np.loadtxt(tmp_path / "s_whole" / "scores.txt")
    s_stream = np.loadtxt(tmp_path / "s_stream" / "scores.txt")
    np.testing.assert_array_equal(s_whole, s_stream)
    for name, value in whole["metrics"].items():
        assert streamed["metrics"][name] == pytest.approx(value, rel=1e-6)
    # The default (device gather-table) whole-set route agrees with the
    # host oracle to f32 accumulation tolerance.
    s_device = np.loadtxt(tmp_path / "s_device" / "scores.txt")
    np.testing.assert_allclose(s_device, s_whole, rtol=1e-4, atol=1e-4)
    for name, value in whole["metrics"].items():
        assert device_whole["metrics"][name] == pytest.approx(value, rel=1e-3)
