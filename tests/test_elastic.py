"""ISSUE 7 (elastic resume): mesh-shape-portable checkpoints,
preemption-aware shutdown, and the run watchdog.

Acceptance pins:

- A checkpoint written under a 1-device placement resumes under a forced
  multi-device CPU mesh (and vice versa) with EXACT fit parity — the
  fingerprint carries the logical layout, never the mesh shape.
- SIGTERM / the injected ``preempt`` fault site stop the loops at an
  iteration boundary with a PUBLISHED checkpoint and the distinct
  preemption exit code (75); resume matches the uninterrupted run exactly
  in both residual modes.
- The watchdog turns silent heartbeats into ``watchdog.stalled``
  telemetry and escalates hung guarded-IO calls to retriable timeouts.
- The async publisher's staged host copies are gauged and bounded
  (``--checkpoint-max-staged-mb`` falls back to blocking saves).
- The resident GLM driver rebuilds finished sweep weights from
  checkpoints without re-fitting.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from photon_tpu.core.objective import RegularizationContext
from photon_tpu.core.optimizers import OptimizerConfig
from photon_tpu.core.problem import ProblemConfig
from photon_tpu.data.synthetic import make_game_dataset
from photon_tpu.fault.checkpoint import DescentCheckpointer
from photon_tpu.fault.injection import FaultPlan, set_plan
from photon_tpu.fault.preemption import (
    PREEMPTED_EXIT_CODE,
    PreemptedError,
    PreemptionHandler,
    clear_preemption,
    preemption_requested,
)
from photon_tpu.game.coordinate import (
    FixedEffectCoordinateConfig,
    RandomEffectCoordinateConfig,
)
from photon_tpu.game.data import split_game_dataset
from photon_tpu.game.estimator import GameEstimator, GameOptimizationConfiguration
from photon_tpu.parallel.mesh import create_mesh
from photon_tpu.telemetry import TelemetrySession

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _elastic_hygiene(monkeypatch):
    """No test leaks a fault plan, a preemption flag, stall heartbeats, or
    pays real backoff sleeps."""
    from photon_tpu.fault.watchdog import clear_heartbeats, set_stall_timeout

    monkeypatch.setenv("PHOTON_IO_RETRY_BASE_S", "0")
    set_plan(None)
    clear_preemption()
    clear_heartbeats()
    set_stall_timeout(None)
    yield
    set_plan(None)
    clear_preemption()
    clear_heartbeats()
    set_stall_timeout(None)


def _problem(lam: float, iters: int) -> ProblemConfig:
    return ProblemConfig(
        regularization=RegularizationContext("l2", lam),
        optimizer_config=OptimizerConfig(max_iterations=iters),
    )


def _game_fixture(seed: int = 7):
    data, _ = make_game_dataset(40, 5, 6, 3, seed=seed)
    train, val = split_game_dataset(data, 0.25)
    config = GameOptimizationConfiguration(
        coordinates={
            "fixed": FixedEffectCoordinateConfig("global", _problem(0.01, 8)),
            "re0": RandomEffectCoordinateConfig("re0", "re0", _problem(1.0, 6)),
        },
        descent_iterations=3,
        name="elastic",
    )
    return train, val, config


def _coordinate_arrays(model):
    out = {}
    for name, coord in model.coordinates.items():
        if hasattr(coord, "table"):
            out[name] = np.asarray(coord.table)
        else:
            out[name] = np.asarray(coord.coefficients.means)
    return out


def _assert_models_equal(a_model, b_model):
    a, b = _coordinate_arrays(a_model), _coordinate_arrays(b_model)
    assert sorted(a) == sorted(b)
    for name in a:
        np.testing.assert_array_equal(a[name], b[name])


# -- mesh-shape-portable checkpoints (tentpole acceptance) -------------------
#
# The test process runs under a forced 8-device CPU platform (conftest), so
# "a different device count" is exercised in-process: a mesh over k of the
# virtual devices vs single-device placement (mesh=None).


@pytest.mark.parametrize("write_devices,resume_devices", [(None, 4), (4, None)])
def test_kill_resume_across_mesh_shapes_exact(
    tmp_path, write_devices, resume_devices
):
    """A fit killed mid-sweep under one mesh shape resumes under ANOTHER
    device count with EXACT parity vs the uninterrupted fit — the
    checkpoint is mesh-shape portable (score rows re-padded/re-sharded,
    model tables re-placed, fingerprint pinning only the logical
    layout)."""
    train, val, config = _game_fixture()

    def mesh_for(devices):
        return None if devices is None else create_mesh(devices)

    baseline = GameEstimator(
        "logistic_regression", train, val, mesh=mesh_for(write_devices)
    ).fit([config])[0]

    ckpt = str(tmp_path / "ckpt")
    set_plan(FaultPlan.parse("descent:kill:iter=2"))
    from photon_tpu.fault.injection import InjectedKillError

    with pytest.raises(InjectedKillError):
        GameEstimator(
            "logistic_regression", train, val, mesh=mesh_for(write_devices)
        ).fit([config], checkpoint_dir=ckpt)
    set_plan(None)

    resumed = GameEstimator(
        "logistic_regression", train, val, mesh=mesh_for(resume_devices)
    ).fit([config], checkpoint_dir=ckpt, resume="auto")[0]

    _assert_models_equal(baseline.model, resumed.model)
    assert baseline.metrics == resumed.metrics
    assert [h["iteration"] for h in resumed.descent.history] == [0, 1, 2]


def test_completed_restore_across_mesh_shape_exact(tmp_path):
    """A COMPLETED checkpoint written single-device restores under a
    2-device mesh without re-running a single solve, bit-identical."""
    train, val, config = _game_fixture()
    ckpt = str(tmp_path / "ckpt")
    session = TelemetrySession("t")
    first = GameEstimator("logistic_regression", train, val).fit(
        [config], checkpoint_dir=ckpt
    )[0]

    restored = GameEstimator(
        "logistic_regression", train, val, mesh=create_mesh(2),
        telemetry=session,
    ).fit([config], checkpoint_dir=ckpt, resume="auto")[0]

    counters = {
        c["name"]: c["value"] for c in session.registry.snapshot()["counters"]
        if c["name"].startswith("estimator.")
    }
    assert counters.get("estimator.configurations_resumed") == 1
    assert "estimator.configurations" not in counters  # zero re-fits
    _assert_models_equal(first.model, restored.model)
    assert first.metrics == restored.metrics


def test_checkpoint_records_logical_layout_not_mesh(tmp_path):
    """The payload carries the logical layout, the manifest its digest,
    and the fingerprint has NO device/process/mesh component — the
    portability contract, checkable without deserializing arrays."""
    train, val, config = _game_fixture()
    ckpt = str(tmp_path / "ckpt")
    GameEstimator(
        "logistic_regression", train, val, mesh=create_mesh(4)
    ).fit([config], checkpoint_dir=ckpt)

    latest = DescentCheckpointer(os.path.join(ckpt, "cfg-000")).latest_path()
    with open(os.path.join(latest, "state.json")) as f:
        payload = json.load(f)
    layout = payload["layout"]
    # Score-row lengths are the LOGICAL (unpadded) row count, even though
    # the writing run padded them to a 4-device multiple on device.
    assert set(layout["rows"].values()) == {train.num_examples}
    assert layout["coordinates"]["re0"]["kind"] == "random"
    assert layout["coordinates"]["re0"]["entities"] > 0
    assert layout["coordinates"]["fixed"]["kind"] == "fixed"

    fp = payload["fingerprint"]
    assert fp["layout"]["rows"] == train.num_examples
    assert fp["layout"]["coordinates"] == {"fixed": "fixed", "re0": "random"}
    # The exact compatibility surface: logical identity only.  No device-,
    # process-, or mesh-shape component may ever join it (that is what
    # makes checkpoints elastic) — a new key fails this assertion and must
    # justify itself against the portability contract.
    assert set(fp) == {
        "task_type", "coordinates", "layout", "residual_mode",
        "validation", "locked", "warm_start", "config",
    }

    from photon_tpu.fault.checkpoint import layout_digest

    with open(os.path.join(latest, "manifest.json")) as f:
        manifest = json.load(f)
    assert manifest["extra"]["layout_digest"] == layout_digest(layout)


def test_inconsistent_layout_digest_refused(tmp_path):
    """The manifest's advertised layout digest is cross-checked against
    the payload at load: an artifact whose two halves disagree (writer
    bug, mixed-version tamper — file hashes alone cannot catch an edited
    manifest `extra`) is refused."""
    train, val, config = _game_fixture()
    ckpt = str(tmp_path / "ckpt")
    GameEstimator("logistic_regression", train, val).fit(
        [config], checkpoint_dir=ckpt
    )
    latest = DescentCheckpointer(os.path.join(ckpt, "cfg-000")).latest_path()
    DescentCheckpointer.load_path(latest)  # consistent: loads fine
    manifest_path = os.path.join(latest, "manifest.json")
    manifest = json.load(open(manifest_path))
    manifest["extra"]["layout_digest"] = "deadbeefdeadbeef"
    with open(manifest_path, "w") as f:
        json.dump(manifest, f)
    from photon_tpu.fault.checkpoint import CheckpointError

    with pytest.raises(CheckpointError, match="layout digest"):
        DescentCheckpointer.load_path(latest)


def test_resume_refuses_different_logical_layout(tmp_path):
    """A checkpoint from a different row count (the same sweep re-pointed
    at different data) must refuse — the layout is identity, the mesh is
    not."""
    train, val, config = _game_fixture()
    ckpt = str(tmp_path / "ckpt")
    GameEstimator("logistic_regression", train, val).fit(
        [config], checkpoint_dir=ckpt
    )
    other_train, other_val = split_game_dataset(
        make_game_dataset(44, 5, 6, 3, seed=9)[0], 0.25
    )
    from photon_tpu.fault.checkpoint import CheckpointError

    with pytest.raises(CheckpointError):
        GameEstimator("logistic_regression", other_train, other_val).fit(
            [config], checkpoint_dir=ckpt, resume="auto"
        )


def test_checkpoint_read_faults_retry_on_resume(tmp_path):
    """The checkpoint:read fault site: injected transient IO errors inside
    the checkpoint load recover through the retry layer (io.retries > 0)
    and the resumed state is unaffected."""
    train, val, config = _game_fixture()
    ckpt = str(tmp_path / "ckpt")
    GameEstimator("logistic_regression", train, val).fit(
        [config], checkpoint_dir=ckpt
    )
    latest = DescentCheckpointer(os.path.join(ckpt, "cfg-000")).latest_path()
    clean = DescentCheckpointer.load_path(latest)

    set_plan(FaultPlan.parse("checkpoint:read:times=2"))
    faulted = DescentCheckpointer.load_path(latest)
    set_plan(None)

    from photon_tpu.fault.retry import RETRY_TOTALS

    assert RETRY_TOTALS["checkpoint:io"] > 0
    assert faulted.iteration == clean.iteration
    for name, row in clean.residual_rows.items():
        np.testing.assert_array_equal(row, faulted.residual_rows[name])


# -- preemption-aware shutdown (tentpole acceptance) -------------------------


def test_sigterm_handler_sets_flag_and_restores():
    import signal

    previous = signal.getsignal(signal.SIGTERM)
    with PreemptionHandler("checkpoint"):
        assert not preemption_requested()
        signal.raise_signal(signal.SIGTERM)
        assert preemption_requested()
    # Handler restored, flag cleared.
    assert signal.getsignal(signal.SIGTERM) is previous
    assert not preemption_requested()

    # mode=ignore installs nothing.
    with PreemptionHandler("ignore"):
        assert signal.getsignal(signal.SIGTERM) is previous
    with pytest.raises(ValueError):
        PreemptionHandler("maybe")


def test_second_signal_escalates_to_default_behavior():
    """A second signal is the operator insisting: the handler restores the
    previous behavior and delivers it — so a double Ctrl-C interrupts even
    before the first iteration boundary would have honored the flag."""
    import signal

    with PreemptionHandler("checkpoint"):
        signal.raise_signal(signal.SIGINT)
        assert preemption_requested()  # first signal: flag only
        with pytest.raises(KeyboardInterrupt):
            signal.raise_signal(signal.SIGINT)  # second: stock behavior
    clear_preemption()


def test_non_training_drivers_keep_stock_signals(tmp_path):
    """telemetry_run installs the flag-setting handler only for drivers
    whose loops POLL the flag (preemptible=True) — a scoring driver whose
    code never checks it must keep stock Ctrl-C behavior."""
    import argparse
    import signal

    from photon_tpu.drivers import common
    from photon_tpu.utils import PhotonLogger

    logger = PhotonLogger("t")
    args = argparse.Namespace(
        output_dir=str(tmp_path), telemetry=False, faults=None,
        on_preempt="checkpoint", stall_timeout=None,
    )
    previous = signal.getsignal(signal.SIGINT)
    with common.telemetry_run(args, "score", logger):
        assert signal.getsignal(signal.SIGINT) is previous  # untouched
    with common.telemetry_run(args, "train", logger, preemptible=True):
        assert signal.getsignal(signal.SIGINT) is not previous
    assert signal.getsignal(signal.SIGINT) is previous  # restored


@pytest.mark.parametrize("mode", ["device", "host"])
def test_preempt_checkpoints_and_resume_matches_exactly(tmp_path, mode):
    """`--faults preempt:iter=k`: the descent stops at the iteration-k
    boundary with iteration k-1's checkpoint PUBLISHED, and the resumed
    fit matches the uninterrupted one exactly — in both residual modes."""
    train, val, config = _game_fixture()

    def fit(**kw):
        return GameEstimator(
            "logistic_regression", train, val, residual_mode=mode
        ).fit([config], **kw)[0]

    baseline = fit()

    ckpt = str(tmp_path / "ckpt")
    session = TelemetrySession("t")
    set_plan(FaultPlan.parse("preempt:iter=2"))
    with pytest.raises(PreemptedError):
        GameEstimator(
            "logistic_regression", train, val, residual_mode=mode,
            telemetry=session,
        ).fit([config], checkpoint_dir=ckpt)
    set_plan(None)
    clear_preemption()

    assert session.counter("descent.preempted").value == 1
    # The preemption drained the publisher: iteration 1's checkpoint is
    # the published LATEST (not in-flight, not torn).
    latest = DescentCheckpointer(os.path.join(ckpt, "cfg-000")).latest_path()
    assert latest is not None and latest.endswith("ckpt-000001")

    resumed = fit(checkpoint_dir=ckpt, resume="latest")
    _assert_models_equal(baseline.model, resumed.model)
    assert baseline.metrics == resumed.metrics


def test_preempt_without_checkpointer_still_stops():
    train, val, config = _game_fixture()
    set_plan(FaultPlan.parse("preempt:iter=1"))
    with pytest.raises(PreemptedError):
        GameEstimator("logistic_regression", train, val).fit([config])


def test_streamed_preempt_snapshots_and_resumes_exactly(tmp_path):
    """The streamed L-BFGS loop honors preemption at its host-iteration
    boundary: the mid-fit state is snapshotted IMMEDIATELY (off the
    checkpoint_every cadence), and the resumed trajectory is exactly the
    uninterrupted one."""
    from photon_tpu.drivers import train as train_driver

    from test_fault_injection import _stream_files

    glob_spec = _stream_files(tmp_path)

    def stream_args(out, extra=()):
        return train_driver.build_parser().parse_args([
            "--backend", "cpu", "--stream", "--input", glob_spec,
            "--task", "logistic_regression", "--reg-weights", "0.5,2.0",
            "--max-iterations", "12",
            "--output-dir", str(tmp_path / out), *extra,
        ])

    baseline = train_driver.run(stream_args("base"))

    ckpt = str(tmp_path / "ckpt")
    with pytest.raises(PreemptedError):
        train_driver.run(stream_args("preempted", [
            "--checkpoint-dir", ckpt,
            # checkpoint-every 100 would never snapshot on cadence: the
            # published mid-fit state can only come from the preemption
            # path's forced save.
            "--checkpoint-every", "100",
            "--faults", "preempt:iter=4",
        ]))
    set_plan(None)
    clear_preemption()

    report = json.load(open(
        tmp_path / "preempted" / "telemetry" / "run_report.json"
    ))
    assert report["status"] == "preempted"

    resumed = train_driver.run(stream_args("resumed", [
        "--checkpoint-dir", ckpt, "--resume", "latest",
    ]))
    for ea, eb in zip(baseline["sweep"], resumed["sweep"]):
        assert ea["final_value"] == eb["final_value"]
        assert ea["iterations"] == eb["iterations"]
        assert ea["convergence_reason"] == eb["convergence_reason"]


def test_run_cli_maps_preemption_to_exit_code():
    from photon_tpu.drivers import common

    def preempted_run(args):
        raise PreemptedError("boundary stop")

    with pytest.raises(SystemExit) as exc:
        common.run_cli(preempted_run, None)
    assert exc.value.code == PREEMPTED_EXIT_CODE

    # Everything else propagates unchanged.
    def crashed_run(args):
        raise RuntimeError("real crash")

    with pytest.raises(RuntimeError, match="real crash"):
        common.run_cli(crashed_run, None)


@pytest.mark.slow
def test_cli_preemption_exit_code(tmp_path):
    """End to end through the real CLI: an injected preemption exits with
    the distinct code 75 (EX_TEMPFAIL), leaves a published checkpoint, and
    the resumed run matches an uninterrupted one."""
    out = str(tmp_path / "out")
    ckpt = str(tmp_path / "ckpt")
    argv = [
        sys.executable, "-m", "photon_tpu.drivers.train_game",
        "--backend", "cpu",
        "--input", "synthetic-game:30:4:6:3",
        "--task", "logistic_regression",
        "--coordinate", "fixed:type=fixed,shard=global,max_iters=6",
        "--coordinate", "re0:type=random,shard=re0,entity=re0,max_iters=5",
        "--descent-iterations", "2",
        "--validation-split", "0.25",
        "--output-dir", out,
        "--checkpoint-dir", ckpt,
        "--faults", "preempt:iter=1",
    ]
    env = {k: v for k, v in os.environ.items()}
    proc = subprocess.run(
        argv, capture_output=True, text=True, timeout=600, env=env, cwd=REPO,
    )
    assert proc.returncode == PREEMPTED_EXIT_CODE, (
        proc.returncode, proc.stderr[-2000:]
    )
    assert "preempted" in (proc.stderr or "")
    report = json.load(open(os.path.join(out, "telemetry", "run_report.json")))
    assert report["status"] == "preempted"
    from photon_tpu.fault.checkpoint import has_published_checkpoint

    assert has_published_checkpoint(ckpt)


# -- run watchdog (tentpole) -------------------------------------------------


def test_watchdog_detects_stall_and_recovery():
    from photon_tpu.fault.watchdog import Watchdog, heartbeat

    session = TelemetrySession("t")
    heartbeat("descent.iteration")
    wd = Watchdog(0.05, telemetry=session)
    time.sleep(0.12)
    assert wd.check_once() == ["descent.iteration"]
    # Counted once per stall episode, gauge carries the age.
    assert wd.check_once() == []
    counters = {
        (c["name"], c["labels"].get("site")): c["value"]
        for c in session.registry.snapshot()["counters"]
    }
    assert counters[("watchdog.stalled", "descent.iteration")] == 1
    gauges = {
        (g["name"], g["labels"].get("site")): g["value"]
        for g in session.registry.snapshot()["gauges"]
    }
    assert gauges[("watchdog.stall_age_seconds", "descent.iteration")] > 0.05
    # Progress resets the episode; a NEW stall counts again.
    heartbeat("descent.iteration")
    assert wd.check_once() == []
    time.sleep(0.12)
    assert wd.check_once() == ["descent.iteration"]

    with pytest.raises(ValueError):
        Watchdog(0.0)


def test_watchdog_thread_emits_on_real_stall():
    from photon_tpu.fault.watchdog import Watchdog, heartbeat

    session = TelemetrySession("t")
    heartbeat("io.unit")
    wd = Watchdog(0.05, telemetry=session, poll_interval_s=0.02).start()
    try:
        deadline = time.monotonic() + 2.0
        while time.monotonic() < deadline:
            counters = {
                (c["name"], c["labels"].get("site")): c["value"]
                for c in session.registry.snapshot()["counters"]
            }
            if counters.get(("watchdog.stalled", "io.unit")):
                break
            time.sleep(0.02)
        else:
            pytest.fail("watchdog thread never flagged the stalled site")
    finally:
        wd.stop()


def test_hung_io_escalates_to_retriable_timeout():
    """The retry/timeout/backoff triangle: a guarded-IO call hung past the
    stall timeout raises a retriable timeout, the retry layer backs off,
    and a healthy later attempt succeeds — with both escalation and
    recovery counted."""
    from photon_tpu.fault.retry import RetryPolicy, retry_call

    session = TelemetrySession("t")
    calls = {"n": 0}

    def hangs_once():
        calls["n"] += 1
        if calls["n"] == 1:
            time.sleep(5.0)  # "hung" well past the 0.1s stall timeout
        return "recovered"

    t0 = time.monotonic()
    out = retry_call(
        hangs_once, site="unit", telemetry=session,
        policy=RetryPolicy(
            attempts=3, base_delay_s=0.0, stall_timeout_s=0.1
        ),
        sleep=lambda s: None,
    )
    assert out == "recovered" and calls["n"] == 2
    assert time.monotonic() - t0 < 4.0  # did NOT wait out the hang
    counters = {
        (c["name"], c["labels"].get("site")): c["value"]
        for c in session.registry.snapshot()["counters"]
    }
    assert counters[("io.stall_timeouts", "unit")] == 1
    assert counters[("io.retries", "unit")] == 1

    # Exhausted stalls surface as the timeout error itself.
    from photon_tpu.fault.watchdog import IOStallTimeoutError

    with pytest.raises(IOStallTimeoutError):
        retry_call(
            lambda: time.sleep(5.0), site="unit",
            policy=RetryPolicy(
                attempts=1, base_delay_s=0.0, stall_timeout_s=0.05
            ),
            sleep=lambda s: None,
        )


def test_slow_but_healthy_io_survives_escalating_timeout():
    """The per-attempt timeout DOUBLES each retry, so IO legitimately
    slower than the configured timeout still completes within the attempt
    budget instead of being starved to failure."""
    from photon_tpu.fault.retry import RetryPolicy, retry_call

    calls = {"n": 0}

    def consistently_slow():
        calls["n"] += 1
        time.sleep(0.25)  # slower than the 0.1s base timeout, every time
        return "slow-ok"

    out = retry_call(
        consistently_slow, site="unit",
        policy=RetryPolicy(attempts=4, base_delay_s=0.0, stall_timeout_s=0.1),
        sleep=lambda s: None,
    )
    # Budget per attempt: 0.1, 0.2, 0.4 — the third attempt fits.
    assert out == "slow-ok" and calls["n"] == 3


def test_finished_activity_retires_its_heartbeat():
    """Silence from FINISHED work is not a stall: retry_call retires its
    site mark when the call sequence ends, and a completed descent retires
    the iteration mark — a healthy run's later phases cannot trip
    watchdog.stalled on a site that simply finished."""
    from photon_tpu.fault.retry import RetryPolicy, retry_call
    from photon_tpu.fault.watchdog import progress_ages

    def io_sites():
        return [k for k in progress_ages() if k.startswith("io.unit")]

    def tracked_while_running():
        assert io_sites()  # marked during the call (per-call key)
        return "ok"

    retry_call(
        tracked_while_running, site="unit",
        policy=RetryPolicy(attempts=2, base_delay_s=0.0),
        sleep=lambda s: None,
    )
    assert not io_sites()  # ...and retired on success

    # Retired on NON-retriable failure too (no stale mark after the call).
    with pytest.raises(ValueError):
        retry_call(
            lambda: (_ for _ in ()).throw(ValueError("not an OSError")),
            site="unit",
            policy=RetryPolicy(attempts=2, base_delay_s=0.0),
            sleep=lambda s: None,
        )
    assert not io_sites()

    train, val, config = _game_fixture()
    GameEstimator("logistic_regression", train, val).fit([config])
    assert "descent.iteration" not in progress_ages()


def test_stall_timeout_resolution(monkeypatch):
    from photon_tpu.fault.retry import default_policy
    from photon_tpu.fault.watchdog import set_stall_timeout, stall_timeout

    assert stall_timeout() == 0.0
    monkeypatch.setenv("PHOTON_STALL_TIMEOUT_S", "7.5")
    assert stall_timeout() == 7.5
    assert default_policy().stall_timeout_s == 7.5
    set_stall_timeout(2.0)  # driver flag wins over env
    assert stall_timeout() == 2.0
    set_stall_timeout(None)
    assert stall_timeout() == 7.5
    monkeypatch.setenv("PHOTON_STALL_TIMEOUT_S", "junk")
    assert stall_timeout() == 0.0


def test_resilience_report_section():
    from photon_tpu.telemetry.report import render_markdown

    report = {
        "driver": "t", "run_id": "r", "status": "preempted",
        "metrics": {
            "counters": [
                {"name": "watchdog.stalled", "labels": {"site": "a"},
                 "value": 2},
                {"name": "io.stall_timeouts", "labels": {"site": "b"},
                 "value": 1},
                {"name": "descent.preempted", "labels": {}, "value": 1},
            ],
            "gauges": [], "histograms": [],
        },
        "spans": [],
    }
    text = render_markdown(report)
    assert "Resilience events" in text
    assert "watchdog.stalled" in text and "descent.preempted" in text


# -- bounded staged host copies (satellite) ----------------------------------


def test_staged_bytes_gauge_and_cap_fallback(tmp_path):
    train, val, config = _game_fixture()

    # Unbounded async run: gauge populated, no fallback.
    s1 = TelemetrySession("t1")
    GameEstimator(
        "logistic_regression", train, val, telemetry=s1
    ).fit([config], checkpoint_dir=str(tmp_path / "c1"), checkpoint_async="on")
    assert s1.gauge("checkpoint.staged_bytes").value > 0
    assert s1.counter("checkpoint.staged_fallback_sync").value == 0

    # A cap below any real snapshot: every save publishes blocking.
    s2 = TelemetrySession("t2")
    result = GameEstimator(
        "logistic_regression", train, val, telemetry=s2
    ).fit(
        [config], checkpoint_dir=str(tmp_path / "c2"), checkpoint_async="on",
        checkpoint_max_staged_mb=0.0001,
    )[0]
    saves = s2.counter("checkpoint.saves").value
    assert saves == config.descent_iterations
    assert s2.counter("checkpoint.staged_fallback_sync").value == saves

    # The blocking fallback still produces a loadable, resumable chain.
    restored = GameEstimator("logistic_regression", train, val).fit(
        [config], checkpoint_dir=str(tmp_path / "c2"), resume="latest"
    )[0]
    _assert_models_equal(result.model, restored.model)


def test_max_staged_env_resolution(tmp_path, monkeypatch):
    from photon_tpu.fault.checkpoint import CheckpointPublisherBase

    base = CheckpointPublisherBase(str(tmp_path))
    assert base.max_staged_bytes is None
    monkeypatch.setenv("PHOTON_CHECKPOINT_MAX_STAGED_MB", "2")
    assert CheckpointPublisherBase(
        str(tmp_path)
    ).max_staged_bytes == 2 * (1 << 20)
    # Explicit argument wins; negative means unbounded.
    assert CheckpointPublisherBase(
        str(tmp_path), max_staged_mb=1
    ).max_staged_bytes == 1 << 20
    assert CheckpointPublisherBase(
        str(tmp_path), max_staged_mb=-1
    ).max_staged_bytes is None


# -- resident GLM driver checkpoint/resume (satellite) -----------------------


def test_resident_driver_checkpoint_resume_skips_refits(tmp_path):
    from photon_tpu.drivers import train as train_driver

    def args(out, extra=()):
        return train_driver.build_parser().parse_args([
            "--backend", "cpu",
            "--input", "synthetic:logistic_regression:120:10:3:5",
            "--task", "logistic_regression", "--reg-weights", "0.5,2.0",
            "--max-iterations", "15",
            "--output-dir", str(tmp_path / out), *extra,
        ])

    baseline = train_driver.run(args("base"))

    ckpt = str(tmp_path / "ckpt")
    checkpointed = train_driver.run(args("ckpt-run", [
        "--checkpoint-dir", ckpt,
    ]))
    # Checkpointing must not perturb the sweep.
    for ea, eb in zip(baseline["sweep"], checkpointed["sweep"]):
        assert ea["final_value"] == eb["final_value"]

    # Wipe the SECOND lambda's chain: resume rebuilds lambda 0 from its
    # snapshot (zero solves) and re-fits only lambda 1 — from the restored
    # solver-space warm start, so the result is the uninterrupted sweep's.
    import shutil

    shutil.rmtree(os.path.join(ckpt, "lam-001"))
    resumed = train_driver.run(args("resumed", [
        "--checkpoint-dir", ckpt, "--resume", "auto",
    ]))
    for ea, eb in zip(baseline["sweep"], resumed["sweep"]):
        assert ea["final_value"] == eb["final_value"]
        assert ea["iterations"] == eb["iterations"]
        assert ea["convergence_reason"] == eb["convergence_reason"]
    assert resumed["sweep"][0]["wall_time_s"] == 0.0  # rebuilt, not refit
    assert resumed["best_lambda"] == baseline["best_lambda"]

    report = json.load(open(
        tmp_path / "resumed" / "telemetry" / "run_report.json"
    ))
    resumed_counter = [
        c for c in report["metrics"]["counters"]
        if c["name"] == "train.lambdas_resumed"
    ]
    assert resumed_counter and resumed_counter[0]["value"] == 1


def test_resident_resume_refuses_mismatched_settings(tmp_path):
    from photon_tpu.drivers import train as train_driver
    from photon_tpu.fault.checkpoint import CheckpointError

    ckpt = str(tmp_path / "ckpt")

    def args(out, extra=()):
        return train_driver.build_parser().parse_args([
            "--backend", "cpu",
            "--input", "synthetic:logistic_regression:120:10:3:5",
            "--task", "logistic_regression", "--reg-weights", "0.5",
            "--max-iterations", "15",
            "--output-dir", str(tmp_path / out),
            "--checkpoint-dir", ckpt, *extra,
        ])

    train_driver.run(args("first"))
    # Only the FINAL state is snapshotted, so a different iteration budget
    # cannot continue a completed resident fit — it must refuse.
    with pytest.raises(CheckpointError):
        train_driver.run(args("more-iters", [
            "--resume", "auto", "--max-iterations", "30",
        ]))
