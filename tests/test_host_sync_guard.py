"""tools/check_host_sync.py: the GAME hot loop stays free of unsanctioned
host syncs, and the checker actually catches one when introduced."""

from __future__ import annotations

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

from check_host_sync import check_file, main  # noqa: E402


def test_hot_loop_is_clean():
    assert main([]) == 0


def test_checker_flags_unsanctioned_sync(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(
        "import numpy as np\n"
        "def hot(x):\n"
        "    return np.asarray(x)\n"
    )
    assert check_file(bad) == [(3, "return np.asarray(x)")]
    assert main([str(bad)]) == 1


def test_checker_accepts_marker_within_window(tmp_path):
    ok = tmp_path / "ok.py"
    ok.write_text(
        "import numpy as np\n"
        "def hot(x):\n"
        "    # host-sync: the one sanctioned scalar fetch\n"
        "    return np.asarray(x)\n"
    )
    assert check_file(ok) == []
    far = tmp_path / "far.py"
    far.write_text(
        "import numpy as np\n"
        "# host-sync: too far above to sanction the call\n"
        "a = 1\nb = 2\nc = 3\nd = 4\n"
        "x = np.asarray(a)\n"
    )
    assert len(check_file(far)) == 1


def test_serving_hot_path_is_guarded():
    """The online scoring service rides the default guard set (ISSUE 9
    satellite): its one response-egress fetch and ingest coercions carry
    markers, and adding an unmarked sync to serving code must fail CI."""
    from check_host_sync import DEFAULT_FILES

    guarded = set(DEFAULT_FILES)
    assert "photon_tpu/serving/scorer.py" in guarded
    assert "photon_tpu/serving/batcher.py" in guarded


def test_fleet_serving_is_guarded():
    """The fleet tier rides the default guard set (ISSUE 12 satellite):
    the router moves requests between host queues (its only sanctioned
    fetches are the explicit parity-oracle markers), the transport is
    pure wire IO, and the fleet assembly never touches device data — an
    unmarked sync in any of them must fail CI."""
    from check_host_sync import DEFAULT_FILES

    guarded = set(DEFAULT_FILES)
    assert "photon_tpu/serving/router.py" in guarded
    assert "photon_tpu/serving/transport.py" in guarded
    assert "photon_tpu/serving/fleet.py" in guarded


def test_tile_store_is_guarded():
    """The disk tier of out-of-core GAME rides the default guard set
    (ISSUE 11 satellite): the store is pure host IO by design — a device
    fetch inside a part-file read/write would serialize the disk edge
    against the device stream it exists to overlap."""
    from check_host_sync import DEFAULT_FILES

    assert "photon_tpu/game/tile_store.py" in set(DEFAULT_FILES)


def test_self_healing_tier_is_guarded():
    """The self-healing tier rides the default guard set (ISSUE 13
    satellite): the supervisor's only sanctioned fetches are its
    probe-oracle parity comparisons, and the subprocess-replica parent
    side is frames + numpy with the one sanctioned fetch at artifact
    publish — an unmarked sync in either must fail CI."""
    from check_host_sync import DEFAULT_FILES

    guarded = set(DEFAULT_FILES)
    assert "photon_tpu/serving/supervisor.py" in guarded
    assert "photon_tpu/serving/replica_proc.py" in guarded


def test_newton_cg_solver_is_guarded():
    """The matrix-free Newton-CG solver rides the default guard set
    (ISSUE 14 satellite): it runs inside the bin loop of every large-dim
    random-effect train, where an unmarked host fetch would repeal the
    one-sync-per-iteration contract."""
    from check_host_sync import DEFAULT_FILES

    assert "photon_tpu/core/optimizers/newton_cg.py" in set(DEFAULT_FILES)


def test_checker_ignores_jnp_and_comments(tmp_path):
    f = tmp_path / "f.py"
    f.write_text(
        "import jax.numpy as jnp\n"
        "# np.asarray(commented out)\n"
        "y = jnp.asarray([1.0])\n"
    )
    assert check_file(f) == []


def test_online_loop_is_guarded():
    """The online-learning loop rides the default guard set (ISSUE 15
    satellite): ingest/delta/service are pure host control — a device
    fetch added to any of them must fail CI."""
    from check_host_sync import DEFAULT_FILES

    guarded = set(DEFAULT_FILES)
    assert "photon_tpu/online/feed.py" in guarded
    assert "photon_tpu/online/delta.py" in guarded
    assert "photon_tpu/online/service.py" in guarded
