"""Size-binned batched Cholesky/Newton random-effect solves (ISSUE 8).

Parity strategy (see game/batched_solve.py + README "Batched entity
solver"): the f32 objective's value-criterion stall basin is ~1e-4 wide, so
two DIFFERENT f32 solvers run independently cannot agree to 1e-5 — what is
pinned at ≤1e-5 is (a) the batched restructuring itself (size-binned block
vs per-capacity bucket loop under the SAME solver — means AND variances),
and (b) the batched Newton path against an f64 ground-truth optimum (it
polishes past the value stall, landing ~1e-7 from the true optimum — closer
than the seed's L-BFGS ever got).  Cross-solver agreement with the seed's
vmapped iterative path is pinned at the f32 floor (≤5e-3, the tolerance the
suite always used for cross-solver comparisons).
"""

import contextlib
import os
import types

import numpy as np
import pytest

from photon_tpu.core.objective import RegularizationContext
from photon_tpu.core.optimizers import OptimizerConfig
from photon_tpu.core.problem import ProblemConfig
from photon_tpu.data.synthetic import make_game_data
from photon_tpu.game.batched_solve import bin_layout, solver_route
from photon_tpu.game.coordinate import (
    RandomEffectCoordinate,
    RandomEffectCoordinateConfig,
    RandomEffectDeviceData,
    _accumulate_solve_stats,
)
from photon_tpu.game.data import (
    DenseShard,
    GameDataset,
    build_random_effect_dataset,
    merge_buckets,
    plan_size_bins,
)
from photon_tpu.telemetry import TelemetrySession


def _dataset(n_entities=50, rows_mean=6, dim=4, seed=3):
    raw = make_game_data(
        n_entities=n_entities, rows_per_entity_mean=rows_mean,
        fixed_dim=5, random_dim=dim, seed=seed,
    )
    return GameDataset.create(
        label=raw["label"],
        shards={"per_entity": DenseShard(raw["x_random"]["re0"])},
        id_columns={"userId": raw["entity_ids"]["re0"]},
    )


def _problem(optimizer="lbfgs", reg=("l2", 1.0), variance="none",
             max_iterations=100):
    return ProblemConfig(
        optimizer=optimizer,
        regularization=RegularizationContext(*reg),
        optimizer_config=OptimizerConfig(
            max_iterations=max_iterations, tolerance=0.0,
            gradient_tolerance=1e-8,
        ),
        variance_computation=variance,
    )


def _config(problem=None, **kw):
    return RandomEffectCoordinateConfig(
        shard_name="per_entity", entity_column="userId",
        problem=problem or _problem(), **kw,
    )


@contextlib.contextmanager
def _solve_env(binning: str, newton: str):
    saved = {
        k: os.environ.get(k)
        for k in ("PHOTON_SOLVE_BINNING", "PHOTON_SOLVE_NEWTON")
    }
    os.environ["PHOTON_SOLVE_BINNING"] = binning
    os.environ["PHOTON_SOLVE_NEWTON"] = newton
    try:
        yield
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _train(data, config, task="logistic_regression", binning="on",
           newton="on", mesh=None, initial_model=None, telemetry=None):
    with _solve_env(binning, newton):
        coord = RandomEffectCoordinate(data, config, task, mesh=mesh)
        if telemetry is not None:
            coord.telemetry = telemetry
        model, stats = coord.train(
            np.zeros(data.num_examples, np.float32),
            initial_model=initial_model,
        )
    return coord, model, stats


# ---------------------------------------------------------------------------
# Bin policy
# ---------------------------------------------------------------------------


def _fake_buckets(caps_and_counts):
    return [
        types.SimpleNamespace(row_capacity=c, num_entities=n)
        for c, n in caps_and_counts
    ]


def test_plan_size_bins_respects_max_bins_and_waste():
    buckets = _fake_buckets(
        [(1, 1000), (2, 800), (4, 500), (8, 200), (16, 50), (32, 10)]
    )
    groups = plan_size_bins(buckets, max_bins=3, waste_cap=2.0)
    assert len(groups) <= 3
    # Every bucket appears exactly once, groups ascend in capacity.
    flat = [i for g in groups for i in g]
    assert sorted(flat) == list(range(6))
    assert [max(g) for g in groups] == sorted(max(g) for g in groups)
    # Deterministic.
    assert groups == plan_size_bins(buckets, max_bins=3, waste_cap=2.0)


def test_plan_size_bins_waste_cap_limits_greedy_merge():
    # A huge cap-1 cohort must NOT be padded 32x into the cap-32 bin when
    # the waste budget says no.
    buckets = _fake_buckets([(1, 100_000), (32, 10)])
    groups = plan_size_bins(buckets, max_bins=4, waste_cap=2.0)
    assert groups == [[0], [1]]
    # With max_bins=1 the merge is forced regardless of waste.
    assert plan_size_bins(buckets, max_bins=1, waste_cap=2.0) == [[0, 1]]


def test_merge_buckets_preserves_rows_and_weights():
    data = _dataset()
    ds = build_random_effect_dataset(data, "userId", "per_entity")
    merged = merge_buckets(list(ds.buckets))
    assert merged.row_capacity == max(b.row_capacity for b in ds.buckets)
    assert merged.num_entities == sum(b.num_entities for b in ds.buckets)
    # Same live rows, same total weight mass, per entity.
    mask = merged.row_weight > 0
    seen = np.sort(merged.row_index[mask])
    assert seen.tolist() == sorted(
        np.concatenate([
            b.row_index[b.row_weight > 0] for b in ds.buckets
        ]).tolist()
    )
    np.testing.assert_allclose(
        np.sort(merged.row_weight.sum(axis=1)),
        np.sort(np.concatenate([b.row_weight.sum(axis=1) for b in ds.buckets])),
        rtol=1e-6,
    )


def test_bin_layout_off_is_one_bucket_per_bin():
    data = _dataset()
    ds = build_random_effect_dataset(data, "userId", "per_entity")
    with _solve_env("off", "off"):
        assert bin_layout(ds.buckets) == [[i] for i in range(len(ds.buckets))]
    with _solve_env("on", "on"):
        assert len(bin_layout(ds.buckets)) <= 4


def test_solver_route_selection():
    smooth = _problem()
    assert solver_route(smooth, 8) == "newton"
    assert solver_route(smooth, 8, row_split=True) == "row_split"
    assert solver_route(smooth, 10_000) == "vmapped"  # over the dim cap
    l1 = _problem(optimizer="owlqn", reg=("l1", 0.5))
    assert solver_route(l1, 8) == "vmapped"
    with _solve_env("on", "off"):
        assert solver_route(smooth, 8) == "vmapped"


# ---------------------------------------------------------------------------
# Solver parity
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("task", [
    "logistic_regression", "linear_regression", "poisson_regression",
])
@pytest.mark.parametrize("optimizer", ["lbfgs", "tron"])
def test_batched_parity_across_tasks(task, optimizer):
    data = _dataset()
    config = _config(_problem(optimizer=optimizer))
    _, batched, stats = _train(data, config, task)
    _, loop_newton, _ = _train(data, config, task, binning="off")
    _, loop_seed, _ = _train(data, config, task, binning="off", newton="off")
    b, ln, ls = (np.asarray(m.table) for m in (batched, loop_newton, loop_seed))
    # The batched restructuring is exact: same solver, ≤1e-5.
    np.testing.assert_allclose(b, ln, atol=1e-5, rtol=0)
    # Cross-solver agreement with the seed's iterative path: f32 floor.
    np.testing.assert_allclose(b, ls, atol=5e-3, rtol=0)
    assert stats["entities"] == 50 and stats["quarantined"] == 0


def test_newton_matches_f64_ground_truth():
    """The batched path's accuracy claim: within 1e-5 of the TRUE optimum
    (f64 numpy Newton run to 1e-14), past the f32 value-stall basin the
    seed's L-BFGS parks in."""
    data = _dataset()
    raw_x = data.shards["per_entity"].x.astype(np.float64)
    ids = data.id_columns["userId"]
    _, model, _ = _train(data, _config(), "logistic_regression")
    table = np.asarray(model.table)
    d = raw_x.shape[1]
    for e in range(model.num_entities):
        rows = ids == model.keys[e]
        xe = raw_x[rows]
        ye = data.label[rows].astype(np.float64)
        w = np.zeros(d)
        for _ in range(200):
            p = 1.0 / (1.0 + np.exp(-(xe @ w)))
            g = xe.T @ (p - ye) + w
            h = (xe * (p * (1 - p))[:, None]).T @ xe + np.eye(d)
            step = np.linalg.solve(h, -g)
            w += step
            if np.abs(step).max() < 1e-14:
                break
        np.testing.assert_allclose(table[e], w, atol=1e-5, rtol=0)


@pytest.mark.parametrize("variance", ["simple", "full"])
def test_variance_parity(variance):
    data = _dataset()
    config = _config(_problem(variance=variance))
    _, batched, _ = _train(data, config)
    _, loop, _ = _train(data, config, binning="off")
    assert batched.variances is not None
    np.testing.assert_allclose(
        np.asarray(batched.table), np.asarray(loop.table), atol=1e-5, rtol=0
    )
    np.testing.assert_allclose(
        np.asarray(batched.variances), np.asarray(loop.variances),
        atol=1e-5, rtol=0,
    )


@pytest.mark.parametrize("projection,kw", [
    ("index_map", {}),
    ("random", {"projected_dim": 3}),
])
def test_projection_parity(projection, kw):
    data = _dataset(dim=6)
    config = _config(projection=projection, **kw)
    _, batched, _ = _train(data, config)
    _, loop, _ = _train(data, config, binning="off")
    np.testing.assert_allclose(
        np.asarray(batched.table), np.asarray(loop.table), atol=1e-5, rtol=0
    )


def test_l1_bin_routes_through_vmapped_and_solves():
    data = _dataset()
    config = _config(_problem(optimizer="owlqn", reg=("l1", 0.3)))
    coord, batched, stats = _train(data, config)
    assert set(coord._bin_routes()) == {"vmapped"}
    assert stats["entities"] == 50
    # Same (OWL-QN) solver both sides; only the batched restructuring
    # differs.  L1 solutions are sparse: the zero pattern must survive.
    _, loop, _ = _train(data, config, binning="off")
    np.testing.assert_allclose(
        np.asarray(batched.table), np.asarray(loop.table), atol=1e-4, rtol=0
    )
    assert (np.asarray(batched.table) == 0.0).any()


def test_row_split_composes_with_binning():
    from photon_tpu.parallel.mesh import create_mesh

    data = _dataset(n_entities=24, rows_mean=8)
    config = _config(row_split=True)
    mesh = create_mesh()
    coord, batched, _ = _train(data, config, mesh=mesh)
    assert set(coord._bin_routes()) == {"row_split"}
    assert len(coord.device_data.buckets) <= 4
    _, loop, _ = _train(data, config, mesh=mesh, binning="off")
    # Row-split solves psum per-entity data terms across the mesh; bin
    # merging changes the padded-row layout and with it the psum reduction
    # order, which the iterative trajectory amplifies — same tolerance
    # class as tests/test_row_split.py's colocated-vs-split comparison.
    np.testing.assert_allclose(
        np.asarray(batched.table), np.asarray(loop.table), atol=2e-3, rtol=2e-2
    )


def test_warm_start_parity_and_join_cache():
    data = _dataset()
    config = _config()
    session = TelemetrySession("t-warm")
    _, first, _ = _train(data, config)
    # FOREIGN vocabulary warm start (fresh keys array -> host key join).
    from photon_tpu.game.model import RandomEffectModel
    import dataclasses

    # Shift the vocabulary so only part of it overlaps: a genuinely FOREIGN
    # warm start (a value-equal copy would pass keys_match and skip the
    # join entirely).
    foreign = dataclasses.replace(first, keys=first.keys + 6)
    assert isinstance(foreign, RandomEffectModel)
    with _solve_env("on", "on"):
        coord = RandomEffectCoordinate(data, config, "logistic_regression")
        coord.telemetry = session
        coord.train(np.zeros(data.num_examples, np.float32),
                    initial_model=foreign)
        assert len(coord.device_data._warm_join_cache) == 1
        cached = next(iter(coord.device_data._warm_join_cache.values()))
        assert cached[0] is foreign.keys
        # Second warm start with the SAME keys object: cache hit, no growth.
        coord.train(np.zeros(data.num_examples, np.float32),
                    initial_model=foreign)
        assert len(coord.device_data._warm_join_cache) == 1
    joins = [
        c for c in session.registry.snapshot()["counters"]
        if c["name"] == "descent.host_transfer_bytes"
        and c["labels"].get("path") == "warm_start"
    ]
    assert joins and all(c["value"] > 0 for c in joins)


# ---------------------------------------------------------------------------
# Quarantine + stats accounting
# ---------------------------------------------------------------------------


def test_nan_quarantine_stays_per_entity_in_batched_solve():
    from photon_tpu.fault.injection import FaultPlan, set_plan

    data = _dataset()
    config = _config()
    with _solve_env("on", "on"):
        coord = RandomEffectCoordinate(data, config, "logistic_regression")
        coord.fault_name = "re0"
        set_plan(FaultPlan.parse("solve:nan:coord=re0"))
        try:
            model, stats = coord.train(
                np.zeros(data.num_examples, np.float32)
            )
        finally:
            set_plan(None)
    table = np.asarray(model.table)
    assert np.isfinite(table).all()
    assert stats["quarantined"] == 1
    # The poisoned entity cold-starts at zero; its bin-mates are solved.
    poisoned = int(coord.device_data.device_buckets[0]["entity_index"][0])
    assert np.all(table[poisoned] == 0.0)
    assert np.abs(table).sum() > 0
    # A quarantined entity is NOT counted converged (the accumulator fix).
    assert stats["converged"] <= stats["entities"] - 1


def test_accumulate_stats_masks_padded_and_quarantined():
    import jax.numpy as jnp

    acc = jnp.zeros(6, jnp.int32)
    # 3 real entities + 2 bin-padding slots (index == num_entities == 3).
    entity_index = jnp.asarray([0, 1, 2, 3, 3])
    converged = jnp.asarray([True, True, False, True, True])
    iterations = jnp.asarray([2, 5, 9, 99, 99])
    good = jnp.asarray([True, False, True, True, True])
    out = np.asarray(
        _accumulate_solve_stats(acc, entity_index, 3, converged, iterations, good)
    )
    # entities: only real; converged: real AND good AND converged;
    # iterations_max: padded slots' 99 masked out; quarantined: real ~good;
    # cg_iters/cg_entities: no per-entity CG counts supplied -> 0.
    assert out.tolist() == [3, 1, 9, 1, 0, 0]
    # Newton-CG bins supply per-entity inner-iteration totals: summed over
    # REAL entities only (padded slots' counts masked out), and the same
    # bins' real entities land in cg_entities (the per-entity-mean
    # denominator for mixed-route coordinates).
    cg = jnp.asarray([7, 11, 2, 50, 50])
    out = np.asarray(
        _accumulate_solve_stats(
            acc, entity_index, 3, converged, iterations, good,
            cg_iterations=cg,
        )
    )
    assert out.tolist() == [3, 1, 9, 1, 20, 3]


# ---------------------------------------------------------------------------
# Incremental entity onboarding
# ---------------------------------------------------------------------------


def _grown_datasets(seed=11):
    """(base, grown): ``grown`` appends rows for 12 NEW entities (keys
    offset past the base vocabulary) to the base dataset."""
    base = _dataset(n_entities=30, seed=seed)
    extra_raw = make_game_data(
        n_entities=12, rows_per_entity_mean=5, fixed_dim=5, random_dim=4,
        seed=seed + 1,
    )
    new_ids = extra_raw["entity_ids"]["re0"] + 10_000
    grown = GameDataset.create(
        label=np.concatenate([base.label, extra_raw["label"]]),
        shards={
            "per_entity": DenseShard(np.concatenate([
                base.shards["per_entity"].x,
                extra_raw["x_random"]["re0"],
            ])),
        },
        id_columns={
            "userId": np.concatenate([base.id_columns["userId"], new_ids]),
        },
    )
    return base, grown


def test_onboarding_matches_full_rebuild():
    base, grown = _grown_datasets()
    config = _config()
    with _solve_env("on", "on"):
        dd = RandomEffectDeviceData(base, config)
        n_bins_before = len(dd.buckets)
        dd.onboard(grown)
        assert dd.dataset.num_entities == 42
        assert len(dd.buckets) > n_bins_before  # layout EXTENDED, not rebuilt
        coord = RandomEffectCoordinate(
            grown, config, "logistic_regression", device_data=dd
        )
        onboarded, stats = coord.train(
            np.zeros(grown.num_examples, np.float32)
        )
        rebuilt_coord = RandomEffectCoordinate(
            grown, config, "logistic_regression"
        )
        rebuilt, _ = rebuilt_coord.train(
            np.zeros(grown.num_examples, np.float32)
        )
    assert stats["entities"] == 42
    np.testing.assert_array_equal(onboarded.keys, rebuilt.keys)
    np.testing.assert_allclose(
        np.asarray(onboarded.table), np.asarray(rebuilt.table),
        atol=1e-5, rtol=0,
    )


def test_onboarding_rejects_shrunk_data_and_grows_existing_rows():
    base, _ = _grown_datasets()
    config = _config()
    dd = RandomEffectDeviceData(base, config)
    from photon_tpu.game.data import take_rows

    with pytest.raises(ValueError, match="append-only|GROWN"):
        dd.onboard(take_rows(base, np.arange(base.num_examples - 5)))
    # Appending rows that reference an EXISTING entity GROWS the layout in
    # place (ISSUE 15 blocker fix — tests/test_online_growth.py pins the
    # fit parity; here: the vocabulary is unchanged and the rows landed).
    dup = GameDataset.create(
        label=np.concatenate([base.label, base.label[:3]]),
        shards={
            "per_entity": DenseShard(np.concatenate([
                base.shards["per_entity"].x, base.shards["per_entity"].x[:3],
            ])),
        },
        id_columns={
            "userId": np.concatenate([
                base.id_columns["userId"], base.id_columns["userId"][:3],
            ]),
        },
    )
    dd.onboard(dup)
    assert dd.dataset.num_entities == 30
    assert len(dd.dataset.entity_idx_per_row) == dup.num_examples
    live_rows = sum(st["live_rows"] for st in dd.bin_stats)
    assert live_rows == dup.num_examples


def test_estimator_onboarding_is_atomic_across_coordinates():
    """A per-user + per-item estimator onboarding a batch that one
    coordinate must reject (its feature shard has the wrong dim in the
    grown data) rejects up front and leaves EVERY cached layout untouched
    — not grow the per-user layout and then throw on the per-item one (a
    half-onboarded cache would mix grown row indices with old-length
    offset vectors)."""
    from photon_tpu.game.estimator import (
        GameEstimator,
        GameOptimizationConfiguration,
    )

    raw = make_game_data(
        n_entities=20, rows_per_entity_mean=4, fixed_dim=5, random_dim=4,
        seed=5, n_random_coords=2,
    )
    base = GameDataset.create(
        label=raw["label"],
        shards={
            "re0": DenseShard(raw["x_random"]["re0"]),
            "re1": DenseShard(raw["x_random"]["re1"]),
        },
        id_columns={
            "re0": raw["entity_ids"]["re0"],
            "re1": raw["entity_ids"]["re1"],
        },
    )
    n_new = 6
    grown = GameDataset.create(
        label=np.concatenate([base.label, base.label[:n_new]]),
        shards={
            # per-user's shard grows correctly; per-item's shard comes
            # back at the WRONG dim — its layout must reject.
            "re0": DenseShard(np.concatenate([
                base.shards["re0"].x, base.shards["re0"].x[:n_new]
            ])),
            "re1": DenseShard(np.concatenate([
                base.shards["re1"].x, base.shards["re1"].x[:n_new]
            ], axis=0)[:, :3]),
        },
        id_columns={
            "re0": np.concatenate(
                [base.id_columns["re0"],
                 np.arange(10_000, 10_000 + n_new, dtype=np.int64)]
            ),
            "re1": np.concatenate(
                [base.id_columns["re1"], base.id_columns["re1"][:n_new]]
            ),
        },
    )
    config = GameOptimizationConfiguration(
        coordinates={
            "per_user": RandomEffectCoordinateConfig(
                "re0", "re0", problem=_problem(max_iterations=5)
            ),
            "per_item": RandomEffectCoordinateConfig(
                "re1", "re1", problem=_problem(max_iterations=5)
            ),
        },
        descent_iterations=1,
    )
    estimator = GameEstimator("logistic_regression", base)
    estimator.fit([config])
    with pytest.raises(ValueError, match="dim"):
        estimator.onboard_training_data(grown)
    # NOTHING mutated: every cached layout still holds the base vocabulary
    # and the base row count, and another fit on the base data still runs.
    for dd in estimator._device_data_cache.values():
        assert dd.dataset.num_entities == 20
        assert len(dd.dataset.entity_idx_per_row) == base.num_examples
    assert estimator.training_data is base
    estimator.fit([config])


def test_model_with_entities_grows_on_device():
    base, grown = _grown_datasets()
    config = _config()
    _, model, _ = _train(base, config)
    dd = RandomEffectDeviceData(grown, config)
    bigger = model.with_entities(dd.dataset.keys)
    assert bigger.num_entities == 42
    # Existing entities keep their rows at the new sorted positions.
    from photon_tpu.game.data import entity_index_for

    idx = entity_index_for(model.keys, bigger.keys)
    np.testing.assert_array_equal(
        np.asarray(bigger.table)[idx], np.asarray(model.table)
    )
    # New entities start at zero.
    new_mask = np.ones(42, bool)
    new_mask[idx] = False
    assert np.all(np.asarray(bigger.table)[new_mask] == 0.0)
    with pytest.raises(ValueError, match="merged keys"):
        model.with_entities(model.keys[:5])


def test_estimator_onboarding_end_to_end():
    from photon_tpu.game.estimator import (
        GameEstimator,
        GameOptimizationConfiguration,
    )

    base, grown = _grown_datasets()
    config = GameOptimizationConfiguration(
        coordinates={"per_entity": _config()}, descent_iterations=1
    )
    session = TelemetrySession("t-onboard")
    with _solve_env("on", "on"):
        estimator = GameEstimator(
            "logistic_regression", base, telemetry=session
        )
        first = estimator.fit([config])[0]
        estimator.onboard_training_data(grown)
        dd = estimator._device_data_cache[
            config.coordinates["per_entity"].data_key
        ]
        warm = first.model.coordinate("per_entity").with_entities(
            dd.dataset.keys
        )
        from photon_tpu.game.model import GameModel

        second = estimator.fit(
            [config],
            initial_model=GameModel(
                {"per_entity": warm}, "logistic_regression"
            ),
        )[0]
        fresh = GameEstimator("logistic_regression", grown).fit(
            [config],
            initial_model=GameModel(
                {"per_entity": warm}, "logistic_regression"
            ),
        )[0]
    got = second.model.coordinate("per_entity")
    want = fresh.model.coordinate("per_entity")
    assert got.num_entities == 42
    np.testing.assert_allclose(
        np.asarray(got.table), np.asarray(want.table), atol=1e-5, rtol=0
    )
    onboarded = session.counter("estimator.entities_onboarded").value
    assert onboarded == 12


def test_residual_engine_grow_preserves_rows():
    from photon_tpu.game.residuals import HostResiduals, ResidualEngine

    rng = np.random.default_rng(0)
    base_offset = rng.standard_normal(20).astype(np.float32)
    rows = {
        "a": rng.standard_normal(20).astype(np.float32),
        "b": rng.standard_normal(20).astype(np.float32),
    }
    grown_offset = np.concatenate(
        [base_offset, rng.standard_normal(8).astype(np.float32)]
    )
    for cls in (ResidualEngine, HostResiduals):
        engine = cls(base_offset, names=["a", "b"])
        for name, row in rows.items():
            engine.update(name, row.copy())
        engine.grow(grown_offset)
        got = np.asarray(engine.offsets_for("a"), np.float32)[:28]
        # Fresh engine over the grown rows (appended scores zero) is the
        # reference the grown engine must match.
        fresh = cls(grown_offset, names=["a", "b"])
        for name, row in rows.items():
            fresh.update(name, np.pad(row, (0, 8)))
        want = np.asarray(fresh.offsets_for("a"), np.float32)[:28]
        np.testing.assert_allclose(got, want, atol=1e-6)
        with pytest.raises(ValueError, match="appends"):
            engine.grow(base_offset)


# ---------------------------------------------------------------------------
# Telemetry + report
# ---------------------------------------------------------------------------


def test_bin_telemetry_gauges():
    data = _dataset()
    session = TelemetrySession("t-bins")
    coord, _, _ = _train(data, _config(), telemetry=session)
    gauges = {
        (g["name"], g["labels"]["bin"]): g
        for g in session.registry.snapshot()["gauges"]
        if g["name"].startswith("solves.")
    }
    assert gauges
    occupancy = sum(
        g["value"] for (name, _), g in gauges.items()
        if name == "solves.bin_occupancy"
    )
    assert occupancy == coord.dataset.num_entities
    for (name, _), g in gauges.items():
        if name == "solves.padded_fraction":
            assert 0.0 <= g["value"] < 1.0
        assert g["labels"]["route"] == "newton"


def test_report_renders_entity_solves_section():
    from photon_tpu.telemetry.report import render_markdown

    report = {
        "driver": "t", "run_id": "r", "status": "ok", "duration_s": 1.0,
        "metrics": {
            "counters": [],
            "gauges": [
                {"name": "solves.bin_occupancy", "value": 90,
                 "labels": {"coordinate": "per_user", "bin": "0",
                            "capacity": "8", "route": "newton"}},
                {"name": "solves.padded_fraction", "value": 0.31,
                 "labels": {"coordinate": "per_user", "bin": "0",
                            "capacity": "8", "route": "newton"}},
            ],
            "histograms": [],
        },
    }
    text = render_markdown(report)
    assert "## Entity solves" in text
    assert "per_user" in text and "newton" in text and "0.31" in text


# ---------------------------------------------------------------------------
# Bench integration (the 1M curve point is slow-marked; tier-1 runs a
# small-capped smoke of the same code path, assertions included)
# ---------------------------------------------------------------------------


def test_bench_entities_smoke(capsys):
    import bench

    bench._bench_entities(max_entities=3000)
    out = capsys.readouterr().out
    line = [ln for ln in out.splitlines()
            if '"game_entity_solves_per_sec"' in ln]
    assert line, out
    import json

    payload = json.loads(line[-1])
    detail = payload["detail"]
    assert detail["descent_parity"]["host_syncs_per_iteration"] == 1.0
    assert all(p["max_same_solver_diff"] <= 1e-5 for p in detail["curve"])
    # The high-dim Newton-CG leg (ISSUE 14) rides the same mode: its
    # ≥1×-the-L-BFGS-rate bar at d=256 is asserted inside the bench.
    hidim = [ln for ln in out.splitlines()
             if "game_entity_solves_per_sec_hidim" in ln]
    assert hidim, out
    hdetail = json.loads(hidim[-1])["detail"]
    assert hdetail["dim"] == 256
    assert hdetail["speedup_vs_vmapped_lbfgs"] >= 1.0
    assert [p["dim"] for p in hdetail["curve"]] == [64, 256, 1024]


@pytest.mark.slow
def test_bench_entities_full_curve(capsys):
    """The full 10k -> 1M CPU scaling curve (the ISSUE 8 acceptance run):
    asserts internally that the batched path beats the bucket loop at
    >=100k entities, parity <=1e-5, and host_syncs == 1/iter."""
    import bench

    bench._bench_entities(max_entities=1_000_000)
    out = capsys.readouterr().out
    assert "game_entity_solves_per_sec" in out
