"""Slab-aligned Pallas gather: layout correctness, skew-robust padding, and
kernel (interpret-mode) equivalence — VERDICT r2 item 3.

The kernel itself only lowers on real TPU hardware; here it runs in Pallas
interpret mode, which exercises the same index math.  The layout builder is
pure NumPy and is tested directly.
"""

import numpy as np
import pytest

from photon_tpu.ops.pallas_gather import (
    LANES,
    SLAB_POSITIONS,
    AlignedLayout,
    build_aligned_layout,
    gather_products,
    gather_products_reference,
)


def _coo(n, k, d, seed=0, dist="uniform"):
    rng = np.random.default_rng(seed)
    if dist == "zipf":
        ids = ((rng.zipf(1.3, size=(n, k)) - 1) % d).astype(np.int32)
    else:
        ids = rng.integers(0, d, size=(n, k), dtype=np.int32)
    vals = rng.standard_normal((n, k)).astype(np.float32)
    # Pad a random suffix of each row (the SparseBatch convention).
    cut = rng.integers(1, k + 1, size=n)
    mask = np.arange(k)[None, :] < cut[:, None]
    return np.where(mask, ids, 0), np.where(mask, vals, 0.0).astype(np.float32)


def _feature_sums(products: np.ndarray, layout: AlignedLayout, d: int) -> np.ndarray:
    """Aggregate per-slot products back to features via dup_map (test-side)."""
    n_sub = layout.lo.shape[0]
    tile = np.arange(n_sub) // (layout.lo.shape[0] // layout.n_tiles)
    s = layout.slab_of_tile[tile]
    f = layout.dup_map[
        s[:, None] * SLAB_POSITIONS + layout.lo * LANES + np.arange(LANES)[None, :]
    ]
    out = np.zeros(d, np.float64)
    np.add.at(out, f.reshape(-1), products.reshape(-1).astype(np.float64))
    return out


@pytest.mark.parametrize("dist", ["uniform", "zipf"])
def test_layout_preserves_entries(dist):
    n, k, d = 2048, 12, 4096
    ids, vals = _coo(n, k, d, seed=1, dist=dist)
    lay = build_aligned_layout(ids, vals, d)
    assert lay.n_entries == int((vals != 0).sum())
    # Reference products through the layout == direct per-feature sums.
    rng = np.random.default_rng(2)
    w = rng.standard_normal(d).astype(np.float32)
    ref = gather_products_reference(w, lay)
    got = _feature_sums(ref, lay, d)
    want = np.zeros(d, np.float64)
    np.add.at(want, ids.reshape(-1), (w[ids] * vals).reshape(-1).astype(np.float64))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("dist,limit", [("uniform", 1.35), ("zipf", 1.5)])
def test_padding_factor_bounded(dist, limit):
    # The round-2 layout measured 34.7x padding on zipf(1.3) (VERDICT r2
    # weak #3); the bin-packed slab layout must stay near 1.
    n, k, d = 65536, 32, 262144 // 16  # scaled-down bench shape, same regime
    ids, vals = _coo(n, k, d, seed=3, dist=dist)
    lay = build_aligned_layout(ids, vals, d)
    assert lay.padding_factor <= limit, (
        f"{dist}: padding {lay.padding_factor:.2f}x > {limit}"
    )


def test_pad_slots_are_zero():
    ids, vals = _coo(512, 8, 1024, seed=4)
    lay = build_aligned_layout(ids, vals, 1024)
    w = np.random.default_rng(5).standard_normal(1024).astype(np.float32)
    ref = gather_products_reference(w, lay)
    # All slots with val==0 must produce exactly 0 (no pad contamination).
    assert (ref[lay.vals == 0.0] == 0.0).all()


def test_kernel_interpret_matches_reference():
    ids, vals = _coo(1024, 8, 2048, seed=6, dist="zipf")
    lay = build_aligned_layout(ids, vals, 2048)
    w = np.random.default_rng(7).standard_normal(2048).astype(np.float32)
    out = np.asarray(gather_products(w, lay, interpret=True))
    ref = gather_products_reference(w, lay)
    np.testing.assert_allclose(out, ref, rtol=1e-6, atol=1e-7)


def test_odd_dim_supported():
    # The slab dictionary decouples the layout from the feature space: no
    # dim % 1024 restriction (round-2 layout required it).
    ids, vals = _coo(256, 4, 1000, seed=8)
    lay = build_aligned_layout(ids, vals, 1000)
    w = np.random.default_rng(9).standard_normal(1000).astype(np.float32)
    got = _feature_sums(gather_products_reference(w, lay), lay, 1000)
    want = np.zeros(1000, np.float64)
    np.add.at(want, ids.reshape(-1), (w[ids] * vals).reshape(-1).astype(np.float64))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_out_of_range_ids_rejected():
    ids = np.array([[0, 5]], np.int32)
    vals = np.ones((1, 2), np.float32)
    with pytest.raises(ValueError, match="out of range"):
        build_aligned_layout(ids, vals, 4)


def test_empty_batch():
    lay = build_aligned_layout(
        np.zeros((4, 3), np.int32), np.zeros((4, 3), np.float32), 64
    )
    assert lay.n_entries == 0 and lay.padding_factor >= 1.0


def test_row_aligned_layout_edge_cases():
    """Transposed (row-dictionary) layout edge cases: n=1, k=1, rows that
    are entirely padding, and a single hot feature shared by every row."""
    import jax.numpy as jnp

    from photon_tpu.ops.pallas_gather import (
        aligned_segment_grad,
        build_row_aligned_layout,
        device_layout,
    )

    rng = np.random.default_rng(9)
    cases = []
    # n=1, k=1
    cases.append((np.array([[3]], np.int32), np.array([[2.0]], np.float32), 8))
    # k=1 column, several rows
    cases.append((
        rng.integers(0, 5, (6, 1)).astype(np.int32),
        rng.standard_normal((6, 1)).astype(np.float32), 5,
    ))
    # middle row entirely padding; one hot feature everywhere else
    ids = np.full((5, 3), 2, np.int32)
    vals = rng.standard_normal((5, 3)).astype(np.float32)
    ids[2] = 0
    vals[2] = 0.0
    cases.append((ids, vals, 7))
    for ids, vals, d in cases:
        n = ids.shape[0]
        al_t = device_layout(build_row_aligned_layout(ids, vals))
        w = jnp.asarray(rng.standard_normal(d), jnp.float32)
        z = np.asarray(aligned_segment_grad(w, al_t, n, interpret=True))
        z_ref = (np.asarray(w)[ids] * vals).sum(axis=1)
        np.testing.assert_allclose(z, z_ref, rtol=2e-5, atol=1e-6)


def test_layout_cache_round_trip(monkeypatch, tmp_path):
    """The content-keyed aligned-layout disk cache must reproduce the
    built layout exactly (both directions), miss on changed values, and
    stay inert below the size floor."""
    import numpy as np

    from photon_tpu.ops.pallas_gather import (
        AlignedLayout,
        load_or_build_aligned_layout,
    )

    monkeypatch.setenv("PHOTON_LAYOUT_CACHE", str(tmp_path))
    monkeypatch.setenv("PHOTON_LAYOUT_CACHE_FLOOR", "1")
    rng = np.random.default_rng(5)
    n, k, dim = 512, 8, 256
    ids = rng.integers(1, dim, size=(n, k)).astype(np.int32)
    vals = rng.standard_normal((n, k)).astype(np.float32)
    for transposed in (False, True):
        first = load_or_build_aligned_layout(ids, vals, dim,
                                             transposed=transposed)
        second = load_or_build_aligned_layout(ids, vals, dim,
                                              transposed=transposed)
        for field in AlignedLayout.__dataclass_fields__:
            np.testing.assert_array_equal(
                np.asarray(getattr(first, field)),
                np.asarray(getattr(second, field)),
            )
    import os

    n_files = len(os.listdir(tmp_path))
    assert n_files == 2  # one per direction
    # Different values -> different key (the layout drops val==0 slots).
    load_or_build_aligned_layout(ids, 2.0 * vals, dim)
    assert len(os.listdir(tmp_path)) == 3
    # Floor: small layouts skip the cache entirely.
    monkeypatch.setenv("PHOTON_LAYOUT_CACHE_FLOOR", str(1 << 22))
    load_or_build_aligned_layout(ids, 3.0 * vals, dim)
    assert len(os.listdir(tmp_path)) == 3


def test_layout_cache_hit_skips_builder(monkeypatch, tmp_path):
    """A cache HIT must not invoke the builder — a broken load silently
    falling back to rebuild would keep every equality test green while
    the cache is permanently dead."""
    import numpy as np

    import photon_tpu.ops.pallas_gather as pg

    monkeypatch.setenv("PHOTON_LAYOUT_CACHE", str(tmp_path))
    monkeypatch.setenv("PHOTON_LAYOUT_CACHE_FLOOR", "1")
    rng = np.random.default_rng(6)
    ids = rng.integers(1, 128, size=(256, 4)).astype(np.int32)
    vals = rng.standard_normal((256, 4)).astype(np.float32)
    pg.load_or_build_aligned_layout(ids, vals, 128)

    def boom(*a, **k):
        raise AssertionError("builder invoked on a cache hit")

    monkeypatch.setattr(pg, "build_aligned_layout", boom)
    pg.load_or_build_aligned_layout(ids, vals, 128)
