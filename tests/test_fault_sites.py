"""Fault-site hygiene: every site consumed in code is registered,
documented, and tested.

``photon_tpu.fault.injection.KNOWN_FAULT_SITES`` is the one registry.
This module scans the source tree for the site literals actually consumed
(``fault_point("...")`` and ``.consume("...")`` call sites) and enforces
three invariants, so a new fault site cannot land silently:

1. every consumed site is registered (and nothing registered is dead);
2. every registered site appears in README's fault-tolerance docs
   (the fault-site table / failure-mode matrix);
3. every registered site is exercised by at least one test.
"""

import os
import re

from photon_tpu.fault.injection import KNOWN_FAULT_SITES

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# fault_point("site", ...) and plan.consume("site", ...) — the only two
# shapes through which code consumes a site by literal name.  \s* spans
# newlines, so wrapped call sites match too.
_SITE_CALL = re.compile(
    r"""(?:fault_point|\.consume)\(\s*["']([^"']+)["']"""
)


def _python_files(root: str):
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if not d.startswith((".", "__"))]
        for name in filenames:
            if name.endswith(".py"):
                yield os.path.join(dirpath, name)


def _consumed_sites() -> dict:
    """{site: [files]} for every site literal consumed in photon_tpu/."""
    sites: dict = {}
    for path in _python_files(os.path.join(REPO, "photon_tpu")):
        text = open(path).read()
        for site in _SITE_CALL.findall(text):
            sites.setdefault(site, []).append(os.path.relpath(path, REPO))
    return sites


def test_every_consumed_site_is_registered():
    consumed = _consumed_sites()
    unregistered = {
        site: files for site, files in consumed.items()
        if site not in KNOWN_FAULT_SITES
    }
    assert not unregistered, (
        f"fault sites consumed in code but missing from "
        f"KNOWN_FAULT_SITES (register them in "
        f"photon_tpu/fault/injection.py): {unregistered}"
    )
    dead = set(KNOWN_FAULT_SITES) - set(consumed)
    assert not dead, (
        f"KNOWN_FAULT_SITES entries no code consumes (stale registry "
        f"rows): {sorted(dead)}"
    )


def test_every_site_is_documented_in_readme():
    readme = open(os.path.join(REPO, "README.md")).read()
    undocumented = [
        site for site in KNOWN_FAULT_SITES if f"`{site}`" not in readme
    ]
    assert not undocumented, (
        f"fault sites missing from README's fault-site table "
        f"(document the failure mode): {undocumented}"
    )


def test_every_site_is_exercised_by_a_test():
    this_file = os.path.abspath(__file__)
    coverage = {site: [] for site in KNOWN_FAULT_SITES}
    for path in _python_files(os.path.dirname(this_file)):
        if os.path.abspath(path) == this_file:
            continue  # the registry scan itself is not coverage
        text = open(path).read()
        for site in KNOWN_FAULT_SITES:
            if site in text:
                coverage[site].append(os.path.basename(path))
    untested = sorted(site for site, files in coverage.items() if not files)
    assert not untested, (
        f"fault sites with no test exercising them (inject them in a "
        f"recovery test before shipping): {untested}"
    )
