"""Native-component tests: C++ LIBSVM parser and mmap index store
(SURVEY.md §2.4 native inventory — the rebuild's host-side native layer).

Every test skips cleanly when the toolchain is unavailable; a separate test
asserts the pure-Python fallback engages under PHOTON_TPU_NO_NATIVE=1.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

from photon_tpu.data.libsvm import _parse_libsvm_py, parse_libsvm
from photon_tpu.native.build import get_lib

needs_native = pytest.mark.skipif(
    get_lib() is None, reason="native toolchain unavailable"
)


def _write_libsvm(path, n=500, dim=100, seed=0, comments=True):
    rng = np.random.default_rng(seed)
    with open(path, "w") as f:
        for i in range(n):
            k = int(rng.integers(1, 12))
            ids = np.sort(rng.choice(np.arange(1, dim), size=k, replace=False))
            lab = int(rng.choice([-1, 1]))
            f.write(
                f"{lab} " + " ".join(
                    f"{j}:{rng.standard_normal():.6g}" for j in ids
                )
            )
            if comments and i % 5 == 0:
                f.write(" # trailing comment")
            f.write("\n")
        if comments:
            f.write("\n# whole-line comment\n")


@needs_native
def test_native_parser_matches_python(tmp_path):
    path = str(tmp_path / "data.libsvm")
    _write_libsvm(path)
    from photon_tpu.native import libsvm_native

    nat = libsvm_native.parse_file(path, False)
    assert nat is not None
    rows_n, labels_n, dim_n = nat
    py = _parse_libsvm_py(path, False)
    assert dim_n == py.dim
    np.testing.assert_allclose(labels_n, py.labels)
    assert len(rows_n) == len(py.rows)
    for (i1, v1), (i2, v2) in zip(rows_n, py.rows):
        np.testing.assert_array_equal(i1, i2)
        np.testing.assert_allclose(v1, v2)


@needs_native
def test_native_parser_zero_based_and_empty_rows(tmp_path):
    path = str(tmp_path / "zb.libsvm")
    with open(path, "w") as f:
        f.write("1 0:1.5 3:2.5\n")
        f.write("0\n")  # label-only row (no features)
        f.write("-1 7:0.25\n")
    from photon_tpu.native import libsvm_native

    rows, labels, dim = libsvm_native.parse_file(path, True)
    assert dim == 8
    np.testing.assert_allclose(labels, [1.0, 0.0, -1.0])
    assert len(rows[1][0]) == 0
    np.testing.assert_array_equal(rows[0][0], [0, 3])


@needs_native
def test_native_parser_malformed_raises(tmp_path):
    path = str(tmp_path / "bad.libsvm")
    with open(path, "w") as f:
        f.write("1 3:not_a_number\n")
    from photon_tpu.native import libsvm_native

    with pytest.raises(ValueError):
        libsvm_native.parse_file(path, False)


def test_parse_libsvm_fallback_env(tmp_path):
    """PHOTON_TPU_NO_NATIVE forces the Python path (subprocess: the flag is
    read at library-load time)."""
    path = str(tmp_path / "data.libsvm")
    _write_libsvm(path, n=50)
    code = (
        "import sys; sys.path.insert(0, %r); "
        "from photon_tpu.native.build import get_lib; "
        "assert get_lib() is None; "
        "from photon_tpu.data.libsvm import parse_libsvm; "
        "d = parse_libsvm(%r); print(d.num_examples, d.dim)"
        % (os.path.dirname(os.path.dirname(os.path.abspath(__file__))), path)
    )
    env = dict(os.environ, PHOTON_TPU_NO_NATIVE="1")
    out = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True, env=env,
        timeout=120,
    )
    assert out.returncode == 0, out.stderr
    n, dim = out.stdout.split()
    assert int(n) == 50


@needs_native
def test_native_parser_page_boundary_no_trailing_newline(tmp_path):
    """A file sized to an exact page multiple with no final newline must not
    read past the buffer (heap-copy guard in svm_open)."""
    path = str(tmp_path / "page.libsvm")
    tail = "1 7:2.5"
    page = os.sysconf("SC_PAGESIZE")
    # One comment line padding to exactly (page - len(tail)) bytes + tail.
    content = "#" + "x" * (page - len(tail) - 2) + "\n" + tail
    assert len(content) == page and not content.endswith("\n")
    with open(path, "w") as f:
        f.write(content)
    from photon_tpu.native import libsvm_native

    rows, labels, dim = libsvm_native.parse_file(path, False)
    assert len(rows) == 1 and dim == 7
    np.testing.assert_allclose(labels, [1.0])
    np.testing.assert_allclose(rows[0][1], [2.5])


@needs_native
def test_native_parser_rejects_space_after_colon(tmp_path):
    """'id: val' must fail in the native path exactly as in Python."""
    path = str(tmp_path / "gap.libsvm")
    with open(path, "w") as f:
        f.write("1 2: 3\n")
    from photon_tpu.native import libsvm_native

    with pytest.raises(ValueError):
        libsvm_native.parse_file(path, False)
    with pytest.raises(ValueError):
        _parse_libsvm_py(path, False)


@needs_native
def test_native_parser_rejects_bare_colon_at_eol(tmp_path):
    """'id:' at end of line must error, not steal the next line's label."""
    path = str(tmp_path / "steal.libsvm")
    with open(path, "w") as f:
        f.write("1 2:\n3 1:1\n")
    from photon_tpu.native import libsvm_native

    with pytest.raises(ValueError):
        libsvm_native.parse_file(path, False)
    with pytest.raises(ValueError):
        _parse_libsvm_py(path, False)


@needs_native
def test_index_store_rejects_overflowing_header(tmp_path):
    """A corrupt header with n_buckets ~ 2^61 must fail open (the size
    check divides instead of multiplying, so it cannot overflow)."""
    import struct

    from photon_tpu.data.index_map import OffHeapIndexMap

    path = str(tmp_path / "o.pixs")
    OffHeapIndexMap.build_file(path, ["a", "b"]).close()
    data = bytearray(open(path, "rb").read())
    # Header: magic(4) version(4) n_keys(8) n_buckets(8) blob_bytes(8).
    data[16:24] = struct.pack("<q", 1 << 61)
    bad = str(tmp_path / "bad.pixs")
    open(bad, "wb").write(bytes(data))
    with pytest.raises(OSError):
        OffHeapIndexMap.open(bad)


@needs_native
def test_index_store_rejects_truncated_file(tmp_path):
    from photon_tpu.data.index_map import OffHeapIndexMap

    path = str(tmp_path / "t.pixs")
    OffHeapIndexMap.build_file(path, [f"k{i}" for i in range(100)]).close()
    data = open(path, "rb").read()
    trunc = str(tmp_path / "trunc.pixs")
    with open(trunc, "wb") as f:
        f.write(data[: len(data) // 2])
    with pytest.raises(OSError):
        OffHeapIndexMap.open(trunc)


@needs_native
def test_index_store_round_trip(tmp_path):
    from photon_tpu.data.index_map import IndexMap, OffHeapIndexMap
    from photon_tpu.data.index_map import feature_key

    path = str(tmp_path / "features.pixs")
    keys = [feature_key(f"f{i}", f"t{i % 3}") for i in range(5000)]
    m = OffHeapIndexMap.build_file(path, keys, intercept=True)
    assert len(m) == 5001
    assert m.intercept_id == 5000
    for i in (0, 1234, 4999):
        assert m.get_id(keys[i]) == i
        assert m.get_key(i) == keys[i]
    assert m.get_id("nope") == -1
    assert keys[17] in m and "nope" not in m
    # Reopen from disk.
    m2 = OffHeapIndexMap.open(path)
    assert m2.get_id(keys[42]) == 42
    # JSON export interops with the in-memory map.
    jpath = str(tmp_path / "features.json")
    m.save(jpath)
    m3 = IndexMap.load(jpath)
    assert m3.get_id(keys[42]) == 42
    assert m3.intercept_id == m.intercept_id
    m.close() if hasattr(m, "close") else None


@needs_native
def test_index_store_duplicate_keys_deduped(tmp_path):
    from photon_tpu.data.index_map import OffHeapIndexMap

    path = str(tmp_path / "dup.pixs")
    m = OffHeapIndexMap.build_file(path, ["a", "b", "a", "c"], intercept=False)
    assert len(m) == 3
    assert [m.get_key(i) for i in range(3)] == ["a", "b", "c"]


@needs_native
def test_train_driver_uses_native_parser(tmp_path):
    """End-to-end: the train driver parses LIBSVM through the native path
    (implicitly — parse_libsvm prefers it) and converges."""
    from photon_tpu.data.synthetic import make_glm_data, write_libsvm
    from photon_tpu.drivers import train

    batch, _ = make_glm_data(400, 10, seed=0)
    path = str(tmp_path / "train.libsvm")
    write_libsvm(path, np.asarray(batch.x)[:, :-1], np.asarray(batch.label))
    out = train.run(train.build_parser().parse_args([
        "--backend", "cpu",
        "--input", path,
        "--task", "logistic_regression",
        "--max-iterations", "30",
        "--output-dir", str(tmp_path / "out"),
    ]))
    assert out["sweep"][0]["convergence_reason"] in (
        "GRADIENT_CONVERGED", "FUNCTION_VALUES_TOLERANCE", "MAX_ITERATIONS"
    )


def test_native_parser_sign_parity(tmp_path):
    """'+1' labels/values parse (the common a1a convention) but double
    signs like '+-2.5' are rejected — in BOTH parsers (the from_chars
    '+'-shim must not be laxer than strtof/Python)."""
    import numpy as np
    import pytest

    from photon_tpu.data.libsvm import _parse_libsvm_py
    from photon_tpu.native import libsvm_native

    good = tmp_path / "plus.libsvm"
    good.write_text("+1 1:+2.5 3:-1.5\n-1 2:+0.5\n")
    parsed = libsvm_native.parse_file(str(good))
    if parsed is None:
        pytest.skip("native library unavailable")
    rows, labels, dim = parsed
    py = _parse_libsvm_py(str(good), False)
    np.testing.assert_array_equal(labels, py.labels)
    assert dim == py.dim
    for (i1, v1), (i2, v2) in zip(rows, py.rows):
        np.testing.assert_array_equal(i1, i2)
        np.testing.assert_array_equal(v1, v2)

    bad = tmp_path / "doublesign.libsvm"
    bad.write_text("1 3:+-2.5\n")
    with pytest.raises(ValueError):
        libsvm_native.parse_file(str(bad))
    with pytest.raises(ValueError):
        _parse_libsvm_py(str(bad), False)


def test_native_parser_rejects_out_of_range_ids(tmp_path):
    # int32-overflowing and sub-minimum feature ids must be parse errors in
    # BOTH parsers, never a silent wraparound (ADVICE r1).
    import pytest

    from photon_tpu.data.libsvm import _parse_libsvm_py
    from photon_tpu.native import libsvm_native

    good = tmp_path / "good.libsvm"
    good.write_text("1 1:1.0\n")
    native_ok = libsvm_native.parse_file(str(good)) is not None

    for bad in ["1 3000000000:1.0\n", "1 0:1.0\n"]:
        p = tmp_path / "bad.libsvm"
        p.write_text(bad)
        with pytest.raises(ValueError):
            _parse_libsvm_py(str(p), zero_based=False)
        if native_ok:
            with pytest.raises(ValueError):
                libsvm_native.parse_file(str(p), zero_based=False)


def test_native_avro_reader_matches_python(tmp_path, monkeypatch):
    """The native columnar GAME Avro decoder must be byte-exact with the
    pure-Python reader in BOTH modes (first-seen map building and
    fixed-map scoring), including intercept placement and id columns."""
    import numpy as np

    from photon_tpu.data.fixtures import make_movielens_like
    from photon_tpu.data.game_io import read_game_avro, write_game_avro

    data, maps = make_movielens_like(n_users=60, n_items=50, mean_ratings=8)
    path = str(tmp_path / "ml.avro")
    write_game_avro(path, data, maps)
    bags = {"global": "global", "per_user": "per_user"}
    cols = ["userId", "itemId"]

    monkeypatch.setenv("PHOTON_TPU_NO_NATIVE_AVRO", "1")
    ds_py, maps_py = read_game_avro(path, bags, cols)
    monkeypatch.setenv("PHOTON_TPU_NO_NATIVE_AVRO", "0")

    # The comparison is only meaningful if the native decoder actually ran:
    # spy on decode_file (a silent fallback would compare python-vs-python).
    from photon_tpu.native import avro_native

    calls = []
    real_decode = avro_native.decode_file

    def spy(*a, **kw):
        out = real_decode(*a, **kw)
        calls.append(out is not None)
        return out

    monkeypatch.setattr(avro_native, "decode_file", spy)
    ds_nat, maps_nat = read_game_avro(path, bags, cols)
    assert calls == [True], f"native decoder did not run: {calls}"

    np.testing.assert_array_equal(ds_py.label, ds_nat.label)
    np.testing.assert_array_equal(ds_py.offset, ds_nat.offset)
    np.testing.assert_array_equal(ds_py.weight, ds_nat.weight)
    for c in cols:
        assert list(ds_py.id_columns[c]) == list(ds_nat.id_columns[c])
    for s in bags:
        assert list(maps_py[s].keys()) == list(maps_nat[s].keys())
        assert maps_py[s].intercept_id == maps_nat[s].intercept_id
        np.testing.assert_array_equal(ds_py.shard(s).ids, ds_nat.shard(s).ids)
        np.testing.assert_array_equal(ds_py.shard(s).vals, ds_nat.shard(s).vals)

    # Fixed-map mode (scoring path: absent features dropped, intercept kept).
    ds_nat2, _ = read_game_avro(path, bags, cols, index_maps=maps_py)
    monkeypatch.setenv("PHOTON_TPU_NO_NATIVE_AVRO", "1")
    ds_py2, _ = read_game_avro(path, bags, cols, index_maps=maps_py)
    for s in bags:
        np.testing.assert_array_equal(ds_py2.shard(s).ids, ds_nat2.shard(s).ids)
        np.testing.assert_array_equal(ds_py2.shard(s).vals, ds_nat2.shard(s).vals)


def test_native_avro_skips_unwanted_double_fields(tmp_path, monkeypatch):
    """Extra plain-double fields (e.g. a timestamp) are SKIPPED by the
    native decoder — OP_SKIP_DOUBLE, no decoded storage — while response/
    offset/weight and the bags stay byte-exact with the Python reader."""
    import numpy as np

    from photon_tpu.data import avro_codec
    from photon_tpu.data.fixtures import make_movielens_like
    from photon_tpu.data.game_io import read_game_avro, write_game_avro

    data, maps = make_movielens_like(n_users=20, n_items=15, mean_ratings=5)
    base = str(tmp_path / "base.avro")
    write_game_avro(base, data, maps)
    schema, records = avro_codec.read_container(base)
    schema["fields"].insert(1, {"name": "ts", "type": "double"})
    for i, rec in enumerate(records):
        rec["ts"] = 1e9 + i
    path = str(tmp_path / "with_ts.avro")
    avro_codec.write_container(path, schema, records)

    bags = {"global": "global", "per_user": "per_user"}
    cols = ["userId", "itemId"]
    monkeypatch.setenv("PHOTON_TPU_NO_NATIVE_AVRO", "1")
    ds_py, _ = read_game_avro(path, bags, cols)
    monkeypatch.setenv("PHOTON_TPU_NO_NATIVE_AVRO", "0")

    from photon_tpu.native import avro_native

    calls = []
    real_decode = avro_native.decode_file

    def spy(fp, data_offset, sync, compiled, *a, **kw):
        # The skipped field must not occupy a decoded double slot.
        assert "ts" not in compiled.dbl_slots
        out = real_decode(fp, data_offset, sync, compiled, *a, **kw)
        calls.append(out is not None)
        return out

    monkeypatch.setattr(avro_native, "decode_file", spy)
    ds_nat, _ = read_game_avro(path, bags, cols)
    assert calls == [True], f"native decoder did not run: {calls}"
    np.testing.assert_array_equal(ds_py.label, ds_nat.label)
    np.testing.assert_array_equal(ds_py.offset, ds_nat.offset)
    np.testing.assert_array_equal(ds_py.weight, ds_nat.weight)
    for s in bags:
        np.testing.assert_array_equal(ds_py.shard(s).ids, ds_nat.shard(s).ids)
        np.testing.assert_array_equal(ds_py.shard(s).vals, ds_nat.shard(s).vals)


def test_native_avro_schema_compiler_rejects_unsupported():
    """Schemas outside the native subset compile to None (Python fallback):
    map fields, non-null unions, int id columns."""
    from photon_tpu.native.avro_native import compile_schema

    base = {
        "type": "record", "name": "T",
        "fields": [
            {"name": "response", "type": "double"},
            {"name": "bag", "type": {"type": "array", "items": {
                "type": "record", "name": "FeatureAvro",
                "fields": [
                    {"name": "name", "type": "string"},
                    {"name": "term", "type": "string"},
                    {"name": "value", "type": "double"},
                ]}}},
            {"name": "uid", "type": "string"},
        ],
    }
    ok = compile_schema(base, {"bag"}, {"uid"})
    assert ok is not None and "response" in ok.dbl_slots

    import copy

    bad = copy.deepcopy(base)
    bad["fields"].append({"name": "meta", "type": {"type": "map", "values": "string"}})
    assert compile_schema(bad, {"bag"}, {"uid"}) is None

    bad2 = copy.deepcopy(base)
    bad2["fields"][2]["type"] = ["null", "string"]  # id col must be plain
    assert compile_schema(bad2, {"bag"}, {"uid"}) is None

    bad3 = copy.deepcopy(base)
    bad3["fields"][1]["type"]["items"]["fields"][2]["type"] = "float"
    assert compile_schema(bad3, {"bag"}, {"uid"}) is None
