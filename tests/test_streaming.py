"""Large-scale input pipeline tests (SURVEY.md §7 step 7): chunked in-HBM
folds, host streaming with prefetch, file sharding, multi-host assembly."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from photon_tpu.core.objective import GlmObjective, RegularizationContext
from photon_tpu.core.optimizers import OptimizerConfig
from photon_tpu.core.optimizers.lbfgs import lbfgs
from photon_tpu.data.batch import SparseBatch
from photon_tpu.data.streaming import (
    ChunkedGlmObjective,
    LibsvmFileSource,
    StreamingObjective,
    chunk_batch,
    make_global_batch,
    shard_files_for_process,
    stream_chunks,
    streaming_lbfgs,
)


def _sparse_data(n=900, k=5, d=64, seed=0):
    rng = np.random.default_rng(seed)
    ids = rng.integers(1, d, size=(n, k)).astype(np.int32)
    vals = rng.standard_normal((n, k)).astype(np.float32)
    w_true = (rng.standard_normal(d) * 0.4).astype(np.float32)
    m = (w_true[ids] * vals).sum(1)
    y = (rng.random(n) < 1.0 / (1.0 + np.exp(-m))).astype(np.float32)
    return SparseBatch(
        jnp.asarray(ids), jnp.asarray(vals), jnp.asarray(y),
        jnp.zeros(n, jnp.float32), jnp.ones(n, jnp.float32),
    )


def test_chunked_objective_matches_flat():
    batch = _sparse_data()
    chunks = chunk_batch(batch, rows_per_chunk=128)
    assert chunks.num_chunks == 8  # ceil(900/128), padded
    obj = GlmObjective.create("logistic", RegularizationContext("l2", 0.5))
    cobj = ChunkedGlmObjective(obj)
    w = jnp.asarray(np.random.default_rng(1).standard_normal(64), jnp.float32)
    v1, g1 = obj.value_and_grad(w, batch)
    v2, g2 = cobj.value_and_grad(w, chunks)
    np.testing.assert_allclose(float(v1), float(v2), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(float(obj.value(w, batch)), float(cobj.value(w, chunks)), rtol=1e-5)
    v = jnp.ones(64, jnp.float32)
    np.testing.assert_allclose(
        np.asarray(obj.hessian_vector(w, v, batch)),
        np.asarray(cobj.hessian_vector(w, v, chunks)),
        rtol=1e-4, atol=1e-4,
    )
    np.testing.assert_allclose(
        np.asarray(obj.hessian_diagonal(w, batch)),
        np.asarray(cobj.hessian_diagonal(w, chunks)),
        rtol=1e-4, atol=1e-4,
    )


def test_chunked_objective_full_fit_matches():
    """The chunked objective slots into the jitted L-BFGS unchanged."""
    batch = _sparse_data(seed=2)
    chunks = chunk_batch(batch, rows_per_chunk=256)
    obj = GlmObjective.create("logistic", RegularizationContext("l2", 1.0))
    cobj = ChunkedGlmObjective(obj)
    config = OptimizerConfig(max_iterations=40)
    w0 = jnp.zeros(64, jnp.float32)
    r1 = lbfgs(lambda w: obj.value_and_grad(w, batch), w0, config)
    r2 = lbfgs(lambda w: cobj.value_and_grad(w, chunks), w0, config)
    np.testing.assert_allclose(float(r1.value), float(r2.value), rtol=1e-4)
    np.testing.assert_allclose(np.asarray(r1.w), np.asarray(r2.w), rtol=1e-2, atol=1e-3)


def test_stream_chunks_order_and_prefetch(monkeypatch):
    # Pin to the single-worker prefetch path: its contract includes strict
    # LOAD order (pooled delivery order is covered by test_io_pool).
    monkeypatch.setenv("PHOTON_IO_THREADS", "1")
    seen = []

    def load(i):
        seen.append(i)
        return jnp.full((2,), float(i))

    out = list(stream_chunks(load, 5, prefetch=2))
    assert [int(o[0]) for o in out] == [0, 1, 2, 3, 4]
    assert seen == [0, 1, 2, 3, 4]


def test_stream_chunks_pooled_delivery_order(monkeypatch):
    # Pooled path (multi-core hosts): DELIVERY stays strictly ordered even
    # when loads finish out of order; chunk residency stays bounded by
    # prefetch — loads STARTED may never exceed chunks consumed + prefetch,
    # even with a slow consumer (unbounded submission would race ahead).
    monkeypatch.setenv("PHOTON_IO_THREADS", "4")
    import time as _time

    started = []

    def load(i):
        started.append(i)
        _time.sleep(0.002 * ((i * 3) % 4))
        return jnp.full((2,), float(i))

    out = []
    for c in stream_chunks(load, 8, prefetch=2):
        out.append(c)
        _time.sleep(0.005)
        assert len(started) <= len(out) + 2, (
            f"{len(started)} loads started, {len(out)} consumed"
        )
    assert [int(o[0]) for o in out] == list(range(8))


def _run_stream_scale_bench(tmp_path, flag, rows):
    """Run ``bench.py <flag>`` in a subprocess at toy size and return the
    parsed final JSON line.  Shared scaffold of the two stream-scale bench
    tests; isolating TMPDIR keeps the test's 5s-probe cpu-fallback verdict
    out of the shared backend-probe cache, where a real bench run within
    the TTL would silently skip the TPU probe."""
    import json
    import subprocess
    import sys as _sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(
        os.environ,
        PHOTON_STREAM_SCALE_ROWS=str(rows),
        PHOTON_STREAM_SCALE_DIR=str(tmp_path / "data"),
        PHOTON_BENCH_PROBE_TIMEOUT="5",
        TMPDIR=str(tmp_path),
        PHOTON_BENCH_COMPILATION_CACHE=os.environ.get(
            "JAX_COMPILATION_CACHE_DIR", str(tmp_path / "cache")
        ),
    )
    out = subprocess.run(
        [_sys.executable, os.path.join(repo, "bench.py"), flag],
        capture_output=True, text=True, timeout=500, env=env, cwd=repo,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    return json.loads(out.stdout.strip().splitlines()[-1])


def test_stream_scale_mp_bench_mode(tmp_path):
    """bench.py --stream-scale-mp at toy size: the 2-process distributed
    pass runs, the JSON line parses, and the (value, |grad|) cross-check
    against the single-process pass holds (both CPU-pinned workers)."""
    line = _run_stream_scale_bench(tmp_path, "--stream-scale-mp", 2000)
    if line["metric"] == "bench_error":
        # Some jaxlibs cannot run cross-process collectives on the CPU
        # backend at all; that is a platform limitation, not a bench bug
        # (same signatures test_multiprocess skips on).
        from bench import MP_UNSUPPORTED_MARKERS

        err = str(line["detail"].get("error", ""))
        if any(marker in err for marker in MP_UNSUPPORTED_MARKERS):
            pytest.skip(f"platform cannot run multi-process JAX: {err[:200]}")
    assert line["metric"] == "config5_stream_mp_rows_per_sec"
    assert line["detail"]["processes"] == 2
    assert line["detail"]["rows"] == 2000
    assert line["detail"]["value_match"] is True
    assert line["detail"]["grad_l1_match"] is True


def test_csr_chunk_path_matches_rows_path(tmp_path):
    """The flat-CSR fast chunk loader must produce byte-identical batches
    to the rows-based builder (same padding, intercept column, label
    normalization), and reject malformed input with the same error."""
    import numpy as np

    from photon_tpu.data.libsvm import (
        csr_to_sparse_batch,
        parse_libsvm,
        to_sparse_batch,
    )
    from photon_tpu.native import libsvm_native

    p = str(tmp_path / "part.libsvm")
    with open(p, "w") as f:
        f.write("1 3:0.5 7:-1.25\n")
        f.write("-1 1:2.0\n")
        f.write("0\n")  # label-only row: only the intercept column
        f.write("1 2:1.0 4:4.0 9:0.125\n")

    csr = libsvm_native.parse_file_csr(p)
    if csr is None:
        pytest.skip("native library unavailable (source-only checkout)")
    labels, row_ptr, ids, vals, dim = csr
    b_csr, d_csr = csr_to_sparse_batch(
        labels, row_ptr, ids, vals, dim=dim, intercept=True, capacity=8
    )
    b_rows, d_rows = to_sparse_batch(
        parse_libsvm(p), dim=dim, intercept=True, capacity=8
    )
    assert d_csr == d_rows
    np.testing.assert_array_equal(b_csr.ids, b_rows.ids)
    np.testing.assert_array_equal(b_csr.vals, b_rows.vals)
    np.testing.assert_array_equal(b_csr.label, b_rows.label)
    np.testing.assert_array_equal(b_csr.weight, b_rows.weight)

    bad = str(tmp_path / "bad.libsvm")
    with open(bad, "w") as f:
        f.write("1 3:\n")
    with pytest.raises(ValueError):
        libsvm_native.parse_file_csr(bad)


def test_stream_chunks_propagates_worker_error():
    def load(i):
        if i == 2:
            raise RuntimeError("disk error")
        return jnp.zeros(1)

    with pytest.raises(RuntimeError, match="disk error"):
        list(stream_chunks(load, 4))


def test_shard_files_for_process():
    files = [f"part-{i:03d}" for i in range(10)]
    shards = [shard_files_for_process(files, p, 3) for p in range(3)]
    assert sorted(sum(shards, [])) == files
    assert abs(len(shards[0]) - len(shards[2])) <= 1
    assert shard_files_for_process(files, 0, 1) == files


def _write_files(tmp_path, n_files=3, rows=120, d=40, seed=0):
    from photon_tpu.data.synthetic import make_glm_data, write_libsvm

    paths = []
    full_x, full_y = [], []
    for i in range(n_files):
        b, _ = make_glm_data(rows, d, seed=seed + i, weight_seed=7)
        x = np.asarray(b.x)[:, :-1]
        y = np.asarray(b.label)
        p = str(tmp_path / f"part-{i}.libsvm")
        write_libsvm(p, x, y)
        paths.append(p)
        full_x.append(x)
        full_y.append(y)
    return paths, np.concatenate(full_x), np.concatenate(full_y)


def test_streaming_lbfgs_matches_in_memory(tmp_path):
    paths, x, y = _write_files(tmp_path)
    source = LibsvmFileSource(paths)
    assert source.num_examples == len(y)
    obj = GlmObjective.create("logistic", RegularizationContext("l2", 1.0))
    sobj = StreamingObjective(obj, source.chunk_iter_factory)
    config = OptimizerConfig(max_iterations=40)
    result = streaming_lbfgs(sobj, jnp.zeros(source.dim, jnp.float32), config)
    assert bool(result.converged)

    # In-memory reference on the concatenated data.
    from photon_tpu.data.libsvm import parse_libsvm, to_sparse_batch

    batches = [parse_libsvm(p) for p in paths]
    rows = [r for b in batches for r in b.rows]
    labels = np.concatenate([b.labels for b in batches])
    from photon_tpu.data.libsvm import LibsvmData

    flat, dim = to_sparse_batch(
        LibsvmData(rows, labels, max(b.dim for b in batches)),
        capacity=source.capacity,
    )
    r_ref = lbfgs(lambda w: obj.value_and_grad(w, flat),
                  jnp.zeros(dim, jnp.float32), config)
    np.testing.assert_allclose(float(result.value), float(r_ref.value), rtol=1e-4)
    np.testing.assert_allclose(
        np.asarray(result.w), np.asarray(r_ref.w), rtol=5e-2, atol=5e-3
    )


def test_streaming_lbfgs_kill_and_resume_exact(tmp_path):
    """A streamed fit killed mid-loop and resumed from its mid-fit L-BFGS
    snapshot matches the uninterrupted fit EXACTLY (ISSUE 5 satellite: the
    ROADMAP's streamed-GLM checkpoint edge)."""
    from photon_tpu.fault.checkpoint import StreamCheckpointer
    from photon_tpu.fault.injection import (
        FaultPlan,
        InjectedKillError,
        set_plan,
    )

    paths, _, _ = _write_files(tmp_path)
    source = LibsvmFileSource(paths)
    obj = GlmObjective.create("logistic", RegularizationContext("l2", 1.0))
    config = OptimizerConfig(max_iterations=25)

    def objective():
        return StreamingObjective(obj, source.chunk_iter_factory)

    w0 = jnp.zeros(source.dim, jnp.float32)
    baseline = streaming_lbfgs(objective(), w0, config)

    ckpt = StreamCheckpointer(str(tmp_path / "ckpt"))
    set_plan(FaultPlan.parse("stream:kill:iter=3"))
    try:
        with pytest.raises(InjectedKillError):
            streaming_lbfgs(objective(), w0, config, checkpointer=ckpt)
    finally:
        set_plan(None)

    state = ckpt.load("latest")
    assert state is not None and not state.completed
    assert state.iteration <= 3
    resumed = streaming_lbfgs(
        objective(), w0, config, checkpointer=ckpt, resume_state=state
    )
    np.testing.assert_array_equal(np.asarray(baseline.w), np.asarray(resumed.w))
    assert int(baseline.iterations) == int(resumed.iterations)
    assert int(baseline.reason) == int(resumed.reason)
    np.testing.assert_array_equal(
        np.asarray(baseline.history_value), np.asarray(resumed.history_value)
    )


def test_streaming_completed_checkpoint_rebuilds_without_passes(tmp_path):
    """Resuming a COMPLETED streamed fit rebuilds the result from the final
    snapshot with zero streamed passes."""
    from photon_tpu.fault.checkpoint import StreamCheckpointer

    paths, _, _ = _write_files(tmp_path)
    source = LibsvmFileSource(paths)
    obj = GlmObjective.create("logistic", RegularizationContext("l2", 1.0))
    config = OptimizerConfig(max_iterations=25)
    passes = {"n": 0}

    def counting_factory():
        passes["n"] += 1
        return source.chunk_iter_factory()

    ckpt = StreamCheckpointer(str(tmp_path / "ckpt"))
    w0 = jnp.zeros(source.dim, jnp.float32)
    fitted = streaming_lbfgs(
        StreamingObjective(obj, counting_factory), w0, config,
        checkpointer=ckpt,
    )
    state = ckpt.load("latest")
    assert state is not None and state.completed

    passes["n"] = 0
    rebuilt = streaming_lbfgs(
        StreamingObjective(obj, counting_factory), w0, config,
        checkpointer=ckpt, resume_state=state,
    )
    assert passes["n"] == 0  # not a single streamed pass
    np.testing.assert_array_equal(np.asarray(fitted.w), np.asarray(rebuilt.w))
    assert float(fitted.value) == float(rebuilt.value)
    assert bool(fitted.converged) == bool(rebuilt.converged)


def test_streaming_max_iterations_checkpoint_continues_with_larger_budget(
    tmp_path,
):
    """A streamed fit that stopped on MAX_ITERATIONS is 'completed' for its
    own budget, but resuming with a LARGER budget continues the loop (same
    rule as descent checkpoints) instead of short-circuiting stale."""
    from photon_tpu.fault.checkpoint import StreamCheckpointer

    paths, _, _ = _write_files(tmp_path)
    source = LibsvmFileSource(paths)
    obj = GlmObjective.create("logistic", RegularizationContext("l2", 1.0))

    def objective():
        return StreamingObjective(obj, source.chunk_iter_factory)

    w0 = jnp.zeros(source.dim, jnp.float32)
    small = OptimizerConfig(max_iterations=3)
    ckpt = StreamCheckpointer(str(tmp_path / "ckpt"))
    capped = streaming_lbfgs(objective(), w0, small, checkpointer=ckpt)
    assert int(capped.iterations) == 3 and not bool(capped.converged)

    state = ckpt.load("latest")
    assert state is not None and state.completed

    # Same budget: rebuilt without passes (stale short-circuit is correct).
    same = streaming_lbfgs(
        objective(), w0, small, checkpointer=ckpt, resume_state=state
    )
    assert int(same.iterations) == 3

    # Larger budget: the loop CONTINUES past the snapshot.
    grown = streaming_lbfgs(
        objective(), w0, OptimizerConfig(max_iterations=25),
        checkpointer=ckpt, resume_state=state,
    )
    assert int(grown.iterations) > 3
    assert float(grown.value) < float(capped.value)  # it kept optimizing


def test_source_with_files_and_known_dim(tmp_path):
    """Global metadata + per-process file restriction; known feature_dim
    skips the full parse but yields identical layout."""
    paths, _, _ = _write_files(tmp_path)
    full = LibsvmFileSource(paths)
    fast = LibsvmFileSource(paths, feature_dim=full.feature_dim)
    assert fast.dim == full.dim
    assert fast.capacity == full.capacity
    assert fast.num_examples == full.num_examples
    shard = full.with_files(paths[:1])
    assert shard.dim == full.dim  # metadata survives restriction
    chunks = list(shard.chunk_iter_factory())
    assert len(chunks) == 1
    assert chunks[0].ids.shape[1] == full.capacity


def test_streaming_train_driver(tmp_path):
    paths, _, _ = _write_files(tmp_path, n_files=2, rows=150)
    from photon_tpu.drivers import train

    out = str(tmp_path / "out")
    summary = train.run(train.build_parser().parse_args([
        "--backend", "cpu",
        "--input", str(tmp_path / "part-*.libsvm"),
        "--stream",
        "--validation-input", "synthetic:logistic_regression:200:40:5:7",
        "--max-iterations", "30",
        "--output-dir", out,
    ]))
    assert summary["streaming"] is True
    assert os.path.exists(os.path.join(out, "best_model.avro"))
    assert summary["sweep"][0]["metrics"]["AUC"] > 0.6


def test_make_global_batch_single_process():
    from photon_tpu.parallel import create_mesh

    batch = _sparse_data(n=64)
    mesh = create_mesh()
    global_batch = make_global_batch(batch, mesh)
    np.testing.assert_array_equal(np.asarray(global_batch.ids), np.asarray(batch.ids))
    obj = GlmObjective.create("logistic")
    w = jnp.zeros(64, jnp.float32)
    v1, _ = obj.value_and_grad(w, batch)
    v2, _ = obj.value_and_grad(w, global_batch)
    np.testing.assert_allclose(float(v1), float(v2), rtol=1e-6)


def test_make_global_batch_aligned_single_process(monkeypatch):
    """make_global_batch(aligned_dim=...) attaches per-local-shard
    aligned/xchg aux (8 local devices here) and the sharded objective
    matches single-device autodiff — the single-process degenerate of
    the multi-process leg (tests/test_multiprocess.py part 1b)."""
    from photon_tpu.parallel import DistributedGlmObjective, create_mesh

    monkeypatch.setenv("PHOTON_SPARSE_GRAD", "xchg")
    monkeypatch.setenv("PHOTON_XCHG_REDUCE", "cumsum")
    monkeypatch.setenv("PHOTON_ROUTE_CACHE", "0")
    batch = _sparse_data(n=64)
    mesh = create_mesh()
    global_batch = make_global_batch(batch, mesh, aligned_dim=64)
    assert global_batch.xchg is not None
    obj = GlmObjective.create("logistic", RegularizationContext("l2", 0.4))
    w = jnp.asarray(
        np.random.default_rng(3).standard_normal(64).astype(np.float32) * 0.1
    )
    monkeypatch.setenv("PHOTON_SPARSE_GRAD", "autodiff")
    v_ref, g_ref = obj.value_and_grad(w, batch)
    monkeypatch.setenv("PHOTON_SPARSE_GRAD", "xchg")
    dist = DistributedGlmObjective(obj, mesh)
    assert dist._sparse_kernel(w, global_batch) == "xchg"
    v, g = dist.value_and_grad(w, global_batch)
    np.testing.assert_allclose(float(v), float(v_ref), rtol=2e-5)
    np.testing.assert_allclose(
        np.asarray(g), np.asarray(g_ref), rtol=2e-4, atol=1e-4
    )


def test_streaming_path_validates_data(tmp_path):
    # ADVICE r1: --stream used to skip data validation entirely.
    import pytest

    from photon_tpu.data.validation import DataValidationError
    from photon_tpu.drivers import train

    bad = tmp_path / "bad.libsvm"
    bad.write_text("nan 1:1.0\n1 2:1.0\n-1 1:0.5\n")
    args = [
        "--input", str(bad), "--task", "logistic_regression",
        "--stream", "--max-iterations", "3",
        "--output-dir", str(tmp_path / "out"),
    ]
    with pytest.raises(DataValidationError):
        train.run(train.build_parser().parse_args(
            args + ["--data-validation", "error"]))
    # off -> trains (NaN label flows into the data; run must still finish)
    summary = train.run(train.build_parser().parse_args(
        args + ["--data-validation", "off"]))
    assert summary is not None


def test_stream_scale_bench_mode(tmp_path):
    """bench.py --stream-scale at toy size: generated part files stream
    through the production path, the JSON line parses, RSS bound holds, and
    the generator's manifest cache skips regeneration (VERDICT r3 item 3;
    full-scale 10M-row runs are recorded in BASELINE.md)."""
    import sys as _sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    line = _run_stream_scale_bench(tmp_path, "--stream-scale", 3000)
    assert line["metric"] == "config5_stream_rows_per_sec"
    assert line["detail"]["rows"] == 3000
    assert line["detail"]["rss_bounded"] is True
    assert line["detail"]["kernel"] == "fm"

    # Manifest cache: a repeat call with the same spec returns the same
    # files without rewriting; a changed spec regenerates (in-process — the
    # generator is pure numpy).
    _sys.path.insert(0, repo)
    import bench

    files = sorted(os.listdir(tmp_path / "data"))
    mtimes = [os.path.getmtime(tmp_path / "data" / f) for f in files]
    again = bench._generate_stream_files(str(tmp_path / "data"), 3000, 64, 16, 1 << 17)
    assert len(again) == 64
    assert [os.path.getmtime(tmp_path / "data" / f) for f in files] == mtimes
    smaller = bench._generate_stream_files(str(tmp_path / "data"), 640, 4, 8, 1 << 10)
    assert len(smaller) == 4
