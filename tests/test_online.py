"""Online learning service (ISSUE 15): feeds, delta, the end-to-end
refresh loop under live fleet traffic, and the kill→resume contracts."""

from __future__ import annotations

import os
import threading

import numpy as np
import pytest

from photon_tpu.core.objective import RegularizationContext
from photon_tpu.core.optimizers import OptimizerConfig
from photon_tpu.core.problem import ProblemConfig
from photon_tpu.data.synthetic import make_game_data
from photon_tpu.fault.injection import FaultPlan, InjectedKillError, set_plan
from photon_tpu.game.coordinate import (
    FixedEffectCoordinateConfig,
    RandomEffectCoordinateConfig,
)
from photon_tpu.game.data import DenseShard, GameDataset, SparseShard
from photon_tpu.game.estimator import (
    GameEstimator,
    GameOptimizationConfiguration,
)
from photon_tpu.game.model import GameModel
from photon_tpu.online import (
    DirectoryFeed,
    OnlineLearningService,
    QueueFeed,
    RefreshPolicy,
    compute_delta,
    merge_append,
    merge_deltas,
    missing_key,
)
from photon_tpu.telemetry import TelemetrySession

TASK = "linear_regression"


@pytest.fixture(autouse=True)
def _clear_fault_plan():
    yield
    set_plan(None)


def _problem(lam=1.0):
    return ProblemConfig(
        regularization=RegularizationContext("l2", lam),
        optimizer_config=OptimizerConfig(
            max_iterations=50, tolerance=1e-9
        ),
    )


def _config(iters=2, coords=2):
    coordinates = {
        "fixed": FixedEffectCoordinateConfig("global", _problem(0.01)),
        "per_user": RandomEffectCoordinateConfig("re0", "re0", _problem()),
    }
    if coords >= 2:
        coordinates["per_item"] = RandomEffectCoordinateConfig(
            "re1", "re1", _problem()
        )
    return GameOptimizationConfiguration(
        coordinates=coordinates, descent_iterations=iters
    )


def _cut(n_ent, seed, keep=None, columns=("re0", "re1")):
    raw = make_game_data(n_ent, 4, 6, 4, seed=seed, n_random_coords=2)
    sel = slice(None) if keep is None else keep(raw["entity_ids"]["re0"])
    return GameDataset.create(
        raw["label"][sel],
        {
            "global": DenseShard(raw["x_fixed"][sel]),
            "re0": DenseShard(raw["x_random"]["re0"][sel]),
            "re1": DenseShard(raw["x_random"]["re1"][sel]),
        },
        id_columns={
            c: raw["entity_ids"][c][sel] for c in columns
        },
    )


def _counter(session, name, **labels):
    return sum(
        m["value"] for m in session.registry.snapshot()["counters"]
        if m["name"] == name
        and all(
            (m.get("labels") or {}).get(k) == v for k, v in labels.items()
        )
    )


# ---------------------------------------------------------------------------
# Feeds
# ---------------------------------------------------------------------------


def test_queue_feed_peek_commit():
    feed = QueueFeed()
    a = feed.append(_cut(5, 0))
    b = feed.append(_cut(5, 1))
    assert len(feed) == 2
    assert feed.poll() == [a, b]
    assert feed.poll() == [a, b]  # peek, not consume
    feed.mark_consumed([a])
    assert feed.poll() == [b]
    assert feed.pending_rows() == b.data.num_examples


def test_directory_feed_durable_cursor_and_retry(tmp_path):
    """Part files load in sorted order under retry (`online:ingest`
    faults retried to a clean read), and the consumed cursor survives a
    feed restart — only unconsumed parts re-ingest."""
    loads = []

    def loader(path):
        loads.append(os.path.basename(path))
        return _cut(5, len(loads))

    d = tmp_path / "parts"
    d.mkdir()
    (d / "part-001.avro").write_bytes(b"x")
    (d / "part-000.avro").write_bytes(b"x")
    session = TelemetrySession("t-feed")
    set_plan(FaultPlan.parse("online:ingest:times=2"))
    feed = DirectoryFeed(str(d), loader, telemetry=session)
    pending = feed.poll()
    set_plan(None)
    assert [b.source for b in pending] == ["part-000.avro", "part-001.avro"]
    assert loads == ["part-000.avro", "part-001.avro"]
    assert _counter(session, "io.retries", site="online:ingest") == 2
    feed.mark_consumed(pending[:1])
    assert (d / "_consumed.txt").exists()
    # Restarted feed (fresh instance): the consumed part never re-reads.
    loads.clear()
    feed2 = DirectoryFeed(str(d), loader, telemetry=session)
    pending2 = feed2.poll()
    assert [b.source for b in pending2] == ["part-001.avro"]
    assert loads == ["part-001.avro"]


def test_directory_feed_exhausts_retries_loudly(tmp_path):
    d = tmp_path / "parts"
    d.mkdir()
    (d / "part-000.avro").write_bytes(b"x")
    set_plan(FaultPlan.parse("online:ingest:p=1.0"))
    feed = DirectoryFeed(str(d), lambda p: _cut(3, 0))
    with pytest.raises(OSError, match="online:ingest"):
        feed.poll()


# ---------------------------------------------------------------------------
# Merge + delta
# ---------------------------------------------------------------------------


def test_merge_append_fills_missing_columns():
    base = _cut(10, 0)
    batch = _cut(12, 1, keep=lambda ids: ids < 6, columns=("re0",))
    merged, absent = merge_append(base, batch)
    n_tail = batch.num_examples
    assert merged.num_examples == base.num_examples + n_tail
    assert not absent["re0"].any()
    assert absent["re1"].all()
    tail_re1 = merged.id_columns["re1"][base.num_examples:]
    assert (tail_re1 == missing_key(np.int64)).all()


def test_merge_append_refuses_unknown_and_missing_shards():
    base = _cut(8, 0)
    batch = _cut(8, 1)
    bad = GameDataset.create(
        batch.label,
        {**batch.shards, "mystery": DenseShard(
            np.zeros((batch.num_examples, 3), np.float32))},
        id_columns=dict(batch.id_columns),
    )
    with pytest.raises(ValueError, match="unknown feature shard"):
        merge_append(base, bad)
    lacking = GameDataset.create(
        batch.label,
        {"re0": batch.shards["re0"]},
        id_columns=dict(batch.id_columns),
    )
    with pytest.raises(ValueError, match="every feature shard"):
        merge_append(base, lacking)
    alien = GameDataset.create(
        batch.label, dict(batch.shards),
        id_columns={**batch.id_columns,
                    "alien": batch.id_columns["re0"]},
    )
    with pytest.raises(ValueError, match="unknown id column"):
        merge_append(base, alien)


def test_merge_append_coerces_sparse_to_dense_layout():
    """An Avro append (padded-COO sparse) merges onto a dense base with
    identical margins — the conversion is lossless."""
    base = _cut(8, 0)
    dense_batch = _cut(8, 1, keep=lambda ids: ids < 4)
    x = dense_batch.shards["global"].x
    n = x.shape[0]
    k = max(int((x != 0).sum(axis=1).max()), 1)
    ids = np.zeros((n, k), np.int32)
    vals = np.zeros((n, k), np.float32)
    for i in range(n):
        nz = np.nonzero(x[i])[0]
        ids[i, : len(nz)] = nz
        vals[i, : len(nz)] = x[i][nz]
    sparse_batch = GameDataset.create(
        dense_batch.label,
        {**dense_batch.shards,
         "global": SparseShard(ids, vals, x.shape[1])},
        id_columns=dict(dense_batch.id_columns),
    )
    merged, _ = merge_append(base, sparse_batch)
    assert isinstance(merged.shards["global"], DenseShard)
    np.testing.assert_allclose(
        merged.shards["global"].x[base.num_examples:], x, atol=0
    )


def test_compute_delta_classifies_coordinates():
    config = _config()
    base = _cut(20, 0)
    vocabs = {
        "re0": np.unique(base.id_columns["re0"]),
        "re1": np.unique(base.id_columns["re1"]),
    }
    batch = _cut(30, 1, keep=lambda ids: (ids < 5) | (ids >= 25),
                 columns=("re0",))
    _, absent = merge_append(base, batch)
    delta = compute_delta(
        config.coordinates, vocabs, batch, absent_tail=absent
    )
    assert delta.coordinates["fixed"].touched
    cu = delta.coordinates["per_user"]
    assert cu.touched
    assert set(cu.existing_keys) <= set(vocabs["re0"])
    assert (cu.new_keys >= 25).all() and len(cu.new_keys)
    assert not delta.coordinates["per_item"].touched
    assert delta.untouched == ["per_item"]
    merged_delta = merge_deltas([delta, delta])
    assert merged_delta.rows == 2 * delta.rows
    assert merged_delta.untouched == ["per_item"]


# ---------------------------------------------------------------------------
# End-to-end: live traffic, locked coordinates, parity, zero recompiles
# ---------------------------------------------------------------------------


def test_online_service_end_to_end_under_live_traffic(tmp_path):
    """The tier-1 loop: append batches with BOTH new and existing
    entities under live fleet traffic → ingest, in-place growth (zero
    full random-layout rebuilds, counter-asserted), partial refresh
    (locked-coordinate count asserted per round), canary publish with
    zero dropped/mixed-model responses and zero serving-side compiles;
    refreshed model parity ≤1e-5 vs a full offline retrain on the merged
    dataset (rebuilt-from-scratch layouts, same warm start)."""
    from photon_tpu.serving.fleet import ServingFleet
    from photon_tpu.serving.router import host_score_request
    from photon_tpu.serving.scorer import (
        build_requests,
        request_spec_for_dataset,
    )

    config = _config(iters=6)
    base = _cut(60, 0)
    session = TelemetrySession("t-online-e2e")
    estimator = GameEstimator(TASK, base, telemetry=session)
    model0 = estimator.fit([config])[0].model
    fleet = ServingFleet(
        model0, replicas=2,
        request_spec=request_spec_for_dataset(model0, base),
        telemetry=session, table_capacity_factor=2,
    ).warmup()
    compiles0 = fleet.compilations

    # Live traffic: closed-loop clients scoring through the fleet for the
    # whole refresh+rollout window; every (request, response) is captured
    # for the dropped/mixed-model audit.
    requests = build_requests(base, model0, [6, 9, 4, 8] * 2)
    stop = threading.Event()
    responses: list = []
    errors: list = []

    def client(tid):
        import time as _time

        i = tid
        while not stop.is_set():
            req = requests[i % len(requests)]
            try:
                responses.append((req, fleet.score(req)))
            except Exception as e:  # noqa: BLE001 — audited below
                errors.append(e)
            i += 1
            # Gentle closed loop: the 1-core fixture shares this CPU with
            # the refresh train — the audit needs coverage, not load.
            _time.sleep(0.02)

    threads = [
        threading.Thread(target=client, args=(t,), daemon=True)
        for t in range(2)
    ]
    for t in threads:
        t.start()

    try:
        feed = QueueFeed()
        service = OnlineLearningService(
            estimator, config, feed, model=model0, fleet=fleet,
            checkpoint_dir=str(tmp_path / "ckpt"),
            policy=RefreshPolicy(refresh_iterations=6),
            telemetry=session,
        )
        # Round 1: BOTH new and existing entities, all coordinates
        # touched -> zero locked.
        feed.append(_cut(70, 1, keep=lambda ids: (ids < 20) | (ids >= 62)))
        result = service.refresh_once()
        assert result is not None and result.published
        assert result.locked == []
        merged1 = estimator.training_data
        # Round 2: the batch omits per_item's id column -> per_item is
        # locked, and its model survives the refresh bit-identical.
        feed.append(_cut(
            70, 2, keep=lambda ids: ids < 10, columns=("re0",)
        ))
        result2 = service.refresh_once()
        assert result2 is not None and result2.locked == ["per_item"]
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=30)
        fleet.close()

    # Zero dropped requests, zero serving-side compiles across BOTH
    # publishes (the capacity-headroom hot swap).
    assert not errors, errors[:3]
    assert fleet.compilations == compiles0
    assert responses

    # No mixed-model response: every captured response equals ONE of the
    # three published models' oracles end to end.
    models = [model0, result.model, result2.model]
    for req, scores in responses[:: max(1, len(responses) // 64)]:
        worst = min(
            float(np.abs(scores - host_score_request(m, req)).max())
            for m in models
        )
        assert worst <= 1e-4, worst

    # Locked coordinate kept its model exactly.
    np.testing.assert_array_equal(
        np.asarray(result.model.coordinates["per_item"].table),
        np.asarray(result2.model.coordinates["per_item"].table),
    )

    # Parity ≤1e-5 vs the full offline retrain on merged1 (rebuilt
    # layouts, same grown warm start, same iterations, no locks).
    fresh = GameEstimator(TASK, merged1)
    warm = {}
    for name, m in model0.coordinates.items():
        cc = config.coordinates[name]
        if hasattr(m, "with_entities"):
            warm[name] = m.with_entities(
                fresh.device_layout(cc).dataset.keys
            )
        else:
            warm[name] = m
    full = fresh.fit(
        [config], initial_model=GameModel(warm, TASK)
    )[0].model
    parity = float(np.abs(
        result.model.score(merged1) - full.score(merged1)
    ).max())
    assert parity <= 1e-5, parity

    # Growth/zero-rebuild counters: rows landed in place, new entities
    # appended, and NO random-effect layout was ever rebuilt.
    assert _counter(session, "onboard.rows_in_place") > 0
    assert _counter(session, "onboard.entities_new") > 0
    assert _counter(
        session, "estimator.device_data_rebuilds", kind="random"
    ) == 0
    assert _counter(session, "online.refreshes") == 2
    assert _counter(session, "online.publishes") == 2
    assert _counter(session, "online.coordinates_locked") == 1
    assert _counter(session, "online.coordinates_refreshed") == 3 + 2
    assert _counter(session, "online.rows_ingested") == (
        estimator.training_data.num_examples - base.num_examples
    )
    # Staleness returns to 0 after the backlog drains.
    gauges = {
        m["name"]: m["value"]
        for m in session.registry.snapshot()["gauges"]
        if not m.get("labels")
    }
    assert gauges.get("online.staleness_s") == 0.0


def test_refresh_kill_and_resume_exact(tmp_path):
    """`descent:kill` mid-refresh → the restarted service (same data,
    same pending batch, same checkpoint dir) resumes the round's fit and
    lands EXACTLY where an uninterrupted control run does."""
    config = _config(iters=3)
    base = _cut(40, 0)
    batch = _cut(50, 1, keep=lambda ids: (ids < 12) | (ids >= 44))

    def build(ckpt_dir):
        estimator = GameEstimator(TASK, base)
        model0 = estimator.fit([config])[0].model
        feed = QueueFeed()
        feed.append(batch)
        return OnlineLearningService(
            estimator, config, feed, model=model0, fleet=None,
            checkpoint_dir=ckpt_dir,
            policy=RefreshPolicy(refresh_iterations=3),
        )

    # Control: uninterrupted refresh.
    control = build(str(tmp_path / "control"))
    want = control.refresh_once().model

    # Killed: descent:kill at iteration 1 of the refresh fit.
    victim = build(str(tmp_path / "killed"))
    set_plan(FaultPlan.parse("descent:kill:iter=1"))
    with pytest.raises(InjectedKillError):
        victim.refresh_once()
    set_plan(None)
    # The batch stays PENDING (consumed only after publish) and the round
    # counter unmoved — the restart replays the same round.
    assert len(victim.feed) == 1
    restarted = build(str(tmp_path / "killed"))
    got = restarted.refresh_once().model
    for name in config.coordinates:
        g, w = got.coordinates[name], want.coordinates[name]
        g_t = getattr(g, "table", None)
        w_t = getattr(w, "table", None)
        if g_t is None:
            g_t, w_t = g.coefficients.means, w.coefficients.means
        np.testing.assert_allclose(
            np.asarray(g_t), np.asarray(w_t), atol=1e-6, rtol=0
        )


def test_refresh_kill_between_train_and_publish(tmp_path):
    """`online:refresh:kill` (between train and publish) → the restarted
    service restores the round's COMPLETED fit from its checkpoint
    (zero retraining — `estimator.configurations_resumed`) and
    publishes it."""
    from photon_tpu.serving.fleet import ServingFleet
    from photon_tpu.serving.scorer import request_spec_for_dataset

    config = _config(iters=2)
    base = _cut(40, 0)
    batch = _cut(50, 1, keep=lambda ids: (ids < 12) | (ids >= 44))
    ckpt = str(tmp_path / "ckpt")

    session = TelemetrySession("t-pubkill")
    estimator = GameEstimator(TASK, base, telemetry=session)
    model0 = estimator.fit([config])[0].model
    feed = QueueFeed()
    feed.append(batch)
    service = OnlineLearningService(
        estimator, config, feed, model=model0, fleet=None,
        checkpoint_dir=ckpt, telemetry=session,
        policy=RefreshPolicy(refresh_iterations=2),
    )
    set_plan(FaultPlan.parse("online:refresh:kill:iter=0"))
    with pytest.raises(InjectedKillError):
        service.refresh_once()
    set_plan(None)
    assert len(feed) == 1  # unpublished -> still pending

    # Restart with a FLEET attached: the completed fit republishes.
    session2 = TelemetrySession("t-pubkill-2")
    estimator2 = GameEstimator(TASK, base, telemetry=session2)
    model0b = estimator2.fit([config])[0].model
    fleet = ServingFleet(
        model0b, replicas=1,
        request_spec=request_spec_for_dataset(model0b, base),
        telemetry=session2, table_capacity_factor=2,
    ).warmup()
    feed2 = QueueFeed()
    feed2.append(batch)
    service2 = OnlineLearningService(
        estimator2, config, feed2, model=model0b, fleet=fleet,
        checkpoint_dir=ckpt, telemetry=session2,
        policy=RefreshPolicy(refresh_iterations=2),
    )
    try:
        result = service2.refresh_once()
        assert result is not None and result.published
        # The round's fit was restored from its checkpoint, not re-run.
        assert _counter(
            session2, "estimator.configurations_resumed"
        ) == 1
        assert len(feed2) == 0
    finally:
        fleet.close()


def test_refresh_failure_keeps_backlog_and_counts(tmp_path):
    """A failed publish (canary parity gate) leaves the batches pending
    and counts `online.refresh_failures` through the background loop."""
    import time as _time

    from photon_tpu.serving.fleet import ServingFleet
    from photon_tpu.serving.scorer import request_spec_for_dataset

    config = _config(iters=1, coords=1)
    base = _cut(30, 0)
    session = TelemetrySession("t-fail")
    estimator = GameEstimator(TASK, base, telemetry=session)
    model0 = estimator.fit([config])[0].model
    fleet = ServingFleet(
        model0, replicas=1,
        request_spec=request_spec_for_dataset(model0, base),
        telemetry=session, table_capacity_factor=2,
    ).warmup()
    feed = QueueFeed()
    feed.append(_cut(30, 1, keep=lambda ids: ids < 10))
    service = OnlineLearningService(
        estimator, config, feed, model=model0, fleet=fleet,
        telemetry=session,
        policy=RefreshPolicy(
            refresh_iterations=1, poll_interval_s=0.05,
            rollout_parity_tol=-1.0,  # every publish fails its gate
        ),
    )
    try:
        with service.start():
            deadline = _time.monotonic() + 30
            while (_time.monotonic() < deadline
                   and _counter(session, "online.refresh_failures") == 0):
                _time.sleep(0.05)
        assert _counter(session, "online.refresh_failures") >= 1
        assert len(feed) == 1  # backlog intact for the next attempt
        assert _counter(session, "online.publishes") == 0
    finally:
        fleet.close()


# ---------------------------------------------------------------------------
# Driver
# ---------------------------------------------------------------------------


def test_online_game_driver_end_to_end(tmp_path):
    """`python -m photon_tpu.drivers.online_game`: initial fit → fleet →
    directory feed drain → publish → model export + summary, with the
    durable consumed cursor written."""
    from photon_tpu.data.game_io import write_game_avro
    from photon_tpu.data.synthetic import make_game_dataset
    from photon_tpu.drivers import online_game
    from photon_tpu.game.data import take_rows
    from photon_tpu.game.model_io import load_game_model

    data, maps = make_game_dataset(40, 4, 6, 4, seed=1, n_random_coords=1)
    ids = data.id_columns["re0"]
    appends = tmp_path / "appends"
    appends.mkdir()
    write_game_avro(
        str(appends / "part-000.avro"),
        take_rows(data, np.nonzero(ids < 8)[0]), maps,
    )
    write_game_avro(
        str(appends / "part-001.avro"),
        take_rows(data, np.nonzero(ids >= 34)[0]), maps,
    )
    out = tmp_path / "out"
    args = online_game.build_parser().parse_args([
        "--input", "synthetic-game:32:4:6:4:1:0",
        "--append-dir", str(appends),
        "--feature-bags", "global=global,re0=re0",
        "--id-columns", "re0",
        "--coordinate", "fixed:type=fixed,shard=global",
        "--coordinate", "per_user:type=random,shard=re0,entity=re0",
        "--task", "logistic_regression",
        "--initial-iterations", "1", "--refresh-iterations", "1",
        "--checkpoint-dir", str(tmp_path / "ckpt"),
        "--output-dir", str(out),
    ])
    summary = online_game.run(args)
    assert summary["rounds"] == 1
    assert summary["published"] == 1
    assert summary["rows_ingested"] > 0
    assert (appends / "_consumed.txt").exists()
    model, _maps = load_game_model(str(out / "model"))
    assert set(model.coordinates) == {"fixed", "per_user"}
    import json as _json

    body = _json.load(open(out / "online_summary.json"))
    assert body["rounds"] == 1


# ---------------------------------------------------------------------------
# Review-hardening regressions
# ---------------------------------------------------------------------------


def test_failed_publish_retry_does_not_duplicate_rows(tmp_path):
    """A refresh that fails AFTER onboarding (the canary gate trips) must
    not re-merge the same pending batches on retry: rows enter the
    training data exactly once, and the successful retry publishes the
    same model a never-failed run would."""
    from photon_tpu.serving.fleet import ServingFleet
    from photon_tpu.serving.scorer import request_spec_for_dataset

    config = _config(iters=2, coords=1)
    base = _cut(30, 0)
    batch = _cut(36, 1, keep=lambda ids: (ids < 8) | (ids >= 32))
    session = TelemetrySession("t-noduplicate")
    estimator = GameEstimator(TASK, base, telemetry=session)
    model0 = estimator.fit([config])[0].model
    fleet = ServingFleet(
        model0, replicas=1,
        request_spec=request_spec_for_dataset(model0, base),
        telemetry=session, table_capacity_factor=2,
    ).warmup()
    feed = QueueFeed()
    feed.append(batch)
    service = OnlineLearningService(
        estimator, config, feed, model=model0, fleet=fleet,
        telemetry=session,
        policy=RefreshPolicy(
            refresh_iterations=2, rollout_parity_tol=-1.0
        ),
    )
    try:
        with pytest.raises(Exception, match="parity|Rollout"):
            service.refresh_once()
        expected_rows = base.num_examples + batch.num_examples
        assert estimator.training_data.num_examples == expected_rows
        assert len(feed) == 1  # still pending
        # Retry with a sane gate: publishes, and the data did NOT double.
        service.policy = RefreshPolicy(refresh_iterations=2)
        result = service.refresh_once()
        assert result is not None and result.published
        assert estimator.training_data.num_examples == expected_rows
        assert _counter(session, "online.rows_ingested") == (
            batch.num_examples
        )
        assert len(feed) == 0
    finally:
        fleet.close()
    # The retried refresh equals a never-failed control run exactly.
    control_est = GameEstimator(TASK, base)
    control_model0 = control_est.fit([config])[0].model
    control_feed = QueueFeed()
    control_feed.append(batch)
    control = OnlineLearningService(
        control_est, config, control_feed, model=control_model0,
        fleet=None, policy=RefreshPolicy(refresh_iterations=2),
    ).refresh_once()
    for name in config.coordinates:
        g, w = result.model.coordinates[name], control.model.coordinates[name]
        g_t = getattr(g, "table", None)
        w_t = getattr(w, "table", None)
        if g_t is None:
            g_t, w_t = g.coefficients.means, w.coefficients.means
        np.testing.assert_allclose(
            np.asarray(g_t), np.asarray(w_t), atol=1e-6, rtol=0
        )


def test_sparse_width_growth_routes_wide_rows_to_migration():
    """A merged append can WIDEN a sparse shard's padded-COO nonzero
    width past an existing bin block's: those entities migrate (the plan
    phase gates on width), narrower rows pad up in place, and the fit
    matches a full rebuild — no mid-apply shape crash, no half-mutated
    layout."""
    from photon_tpu.game.coordinate import (
        RandomEffectCoordinate,
        RandomEffectCoordinateConfig,
        RandomEffectDeviceData,
    )
    from photon_tpu.online.delta import merge_append

    rng = np.random.default_rng(3)
    dim = 12

    def sparse(n, k, seed):
        r = np.random.default_rng(seed)
        return SparseShard(
            r.integers(0, dim, (n, k)).astype(np.int32),
            r.standard_normal((n, k)).astype(np.float32),
            dim,
        )

    n_base = 60
    base = GameDataset.create(
        (rng.random(n_base) < 0.5).astype(np.float32),
        {"pe": sparse(n_base, 3, 1)},
        id_columns={"uid": np.repeat(np.arange(15, dtype=np.int64), 4)},
    )
    n_tail = 20
    batch = GameDataset.create(
        (rng.random(n_tail) < 0.5).astype(np.float32),
        {"pe": sparse(n_tail, 5, 2)},  # WIDER than the base's k=3
        id_columns={"uid": np.concatenate([
            np.arange(8, dtype=np.int64),          # existing entities
            np.arange(20, 32, dtype=np.int64),     # new entities
        ])},
    )
    merged, _absent = merge_append(base, batch)
    assert merged.shards["pe"].ids.shape[1] == 5
    cfg = RandomEffectCoordinateConfig("pe", "uid", _problem())
    session = TelemetrySession("t-width")
    dd = RandomEffectDeviceData(base, cfg)
    dd.onboard(merged, telemetry=session)
    # Wider rows could not land in the k=3 blocks: they migrated.
    assert _counter(session, "onboard.rows_in_place") == 0
    assert _counter(session, "onboard.entities_migrated") == 8
    coord = RandomEffectCoordinate(
        merged, cfg, "logistic_regression", device_data=dd
    )
    got, _ = coord.train(np.zeros(merged.num_examples, np.float32))
    want, _ = RandomEffectCoordinate(
        merged, cfg, "logistic_regression"
    ).train(np.zeros(merged.num_examples, np.float32))
    np.testing.assert_allclose(
        np.asarray(got.table), np.asarray(want.table), atol=1e-5, rtol=0
    )
    # Narrower append onto the now-wide layout pads up IN PLACE: target
    # the migrated entities — their new blocks sit at width 5 with
    # pow2(5)=8 row capacity, i.e. 3 free slots each.
    batch2 = GameDataset.create(
        (rng.random(6) < 0.5).astype(np.float32),
        {"pe": sparse(6, 2, 4)},
        id_columns={"uid": np.arange(0, 6, dtype=np.int64)},
    )
    merged2, _ = merge_append(merged, batch2)
    session2 = TelemetrySession("t-width-2")
    dd.onboard(merged2, telemetry=session2)
    assert _counter(session2, "onboard.rows_in_place") == 6
    got2, _ = RandomEffectCoordinate(
        merged2, cfg, "logistic_regression", device_data=dd
    ).train(np.zeros(merged2.num_examples, np.float32))
    want2, _ = RandomEffectCoordinate(
        merged2, cfg, "logistic_regression"
    ).train(np.zeros(merged2.num_examples, np.float32))
    np.testing.assert_allclose(
        np.asarray(got2.table), np.asarray(want2.table), atol=1e-5, rtol=0
    )


def test_missing_marker_never_wraps_on_narrow_int_columns():
    """The missing-id fill is dtype-relative: an int32 id column fills
    with int32-min (not int64-min wrapped to 0 — entity 0 is real), and
    the mask detects it after the round trip."""
    from photon_tpu.online.delta import missing_mask

    assert missing_key(np.int32) == np.iinfo(np.int32).min
    assert missing_key(np.uint32) == np.iinfo(np.uint32).max
    assert missing_key(np.int64) == np.iinfo(np.int64).min
    base = _cut(10, 0)
    base32 = GameDataset.create(
        base.label, dict(base.shards),
        id_columns={
            "re0": base.id_columns["re0"].astype(np.int32),
            "re1": base.id_columns["re1"].astype(np.int32),
        },
    )
    batch = _cut(10, 1, keep=lambda ids: ids < 5, columns=("re0",))
    merged, absent = merge_append(base32, batch)
    tail = merged.id_columns["re1"][base32.num_examples:]
    assert tail.dtype == np.int32
    assert (tail == np.iinfo(np.int32).min).all()
    assert (tail != 0).all()
    np.testing.assert_array_equal(missing_mask(tail), absent["re1"])


def test_failed_round_retry_excludes_new_arrivals(tmp_path):
    """A retry of a failed round replays EXACTLY its batch set: parts
    arriving between the failure and the retry wait for the next round
    (the round checkpoint's fingerprint pins the row count), and both
    rounds publish."""
    from photon_tpu.serving.fleet import ServingFleet
    from photon_tpu.serving.scorer import request_spec_for_dataset

    config = _config(iters=1, coords=1)
    base = _cut(30, 0)
    batch1 = _cut(34, 1, keep=lambda ids: (ids < 6) | (ids >= 31))
    batch2 = _cut(34, 2, keep=lambda ids: ids < 4)
    session = TelemetrySession("t-round-snapshot")
    estimator = GameEstimator(TASK, base, telemetry=session)
    model0 = estimator.fit([config])[0].model
    fleet = ServingFleet(
        model0, replicas=1,
        request_spec=request_spec_for_dataset(model0, base),
        telemetry=session, table_capacity_factor=2,
    ).warmup()
    feed = QueueFeed()
    feed.append(batch1)
    service = OnlineLearningService(
        estimator, config, feed, model=model0, fleet=fleet,
        checkpoint_dir=str(tmp_path / "ckpt"), telemetry=session,
        policy=RefreshPolicy(refresh_iterations=1,
                             rollout_parity_tol=-1.0),
    )
    try:
        with pytest.raises(Exception, match="parity|Rollout"):
            service.refresh_once()
        feed.append(batch2)  # arrives mid-round
        service.policy = RefreshPolicy(refresh_iterations=1)
        r0 = service.refresh_once()
        # Round 0 published with ONLY batch1 (the snapshot), resuming its
        # own checkpoint; batch2 waits.
        assert r0 is not None and r0.published and r0.round == 0
        assert r0.rows == batch1.num_examples
        assert len(feed) == 1
        assert estimator.training_data.num_examples == (
            base.num_examples + batch1.num_examples
        )
        r1 = service.refresh_once()
        assert r1 is not None and r1.published and r1.round == 1
        assert len(feed) == 0
        assert estimator.training_data.num_examples == (
            base.num_examples + batch1.num_examples + batch2.num_examples
        )
        assert _counter(session, "online.checkpoint_refused") == 0
    finally:
        fleet.close()


def test_restart_with_extra_batch_survives_checkpoint_refusal(tmp_path):
    """A RESTARTED service whose backlog differs from the killed
    attempt's (a part arrived in between) cannot resume the stale round
    checkpoint — it must train the round fresh (counted as
    `online.checkpoint_refused`) instead of wedging on the fingerprint
    refusal forever."""
    config = _config(iters=2, coords=1)
    base = _cut(30, 0)
    batch1 = _cut(34, 1, keep=lambda ids: ids < 6)
    batch2 = _cut(34, 2, keep=lambda ids: (ids >= 3) & (ids < 9))
    ckpt = str(tmp_path / "ckpt")

    estimator = GameEstimator(TASK, base)
    model0 = estimator.fit([config])[0].model
    feed = QueueFeed()
    feed.append(batch1)
    service = OnlineLearningService(
        estimator, config, feed, model=model0, fleet=None,
        checkpoint_dir=ckpt,
        policy=RefreshPolicy(refresh_iterations=2),
    )
    set_plan(FaultPlan.parse("online:refresh:kill:iter=0"))
    with pytest.raises(InjectedKillError):
        service.refresh_once()
    set_plan(None)

    # Restart with a BIGGER backlog: batch2 landed before the restart.
    session2 = TelemetrySession("t-refused")
    estimator2 = GameEstimator(TASK, base, telemetry=session2)
    model0b = estimator2.fit([config])[0].model
    feed2 = QueueFeed()
    feed2.append(batch1)
    feed2.append(batch2)
    service2 = OnlineLearningService(
        estimator2, config, feed2, model=model0b, fleet=None,
        checkpoint_dir=ckpt, telemetry=session2,
        policy=RefreshPolicy(refresh_iterations=2),
    )
    result = service2.refresh_once()
    assert result is not None
    assert result.rows == batch1.num_examples + batch2.num_examples
    assert _counter(session2, "online.checkpoint_refused") == 1
    assert len(feed2) == 0


def test_driver_restart_reingests_published_parts(tmp_path):
    """A RESTARTED driver reconstructs the full training data: parts a
    previous run already published (consumed cursor) re-merge into the
    base before the initial fit, so their entities stay in the model —
    published rows never silently drop from training."""
    from photon_tpu.data.game_io import write_game_avro
    from photon_tpu.data.synthetic import make_game_dataset
    from photon_tpu.drivers import online_game
    from photon_tpu.game.data import take_rows
    from photon_tpu.game.model_io import load_game_model

    data, maps = make_game_dataset(44, 4, 6, 4, seed=1, n_random_coords=1)
    ids = data.id_columns["re0"]
    appends = tmp_path / "appends"
    appends.mkdir()
    # part-000 carries entities 34..43 — NEW relative to the 32-entity base.
    write_game_avro(
        str(appends / "part-000.avro"),
        take_rows(data, np.nonzero(ids >= 34)[0]), maps,
    )

    def args_for(out):
        return online_game.build_parser().parse_args([
            "--input", "synthetic-game:32:4:6:4:1:0",
            "--append-dir", str(appends),
            "--feature-bags", "global=global,re0=re0",
            "--id-columns", "re0",
            "--coordinate", "fixed:type=fixed,shard=global",
            "--coordinate", "per_user:type=random,shard=re0,entity=re0",
            "--task", "logistic_regression",
            "--initial-iterations", "1", "--refresh-iterations", "1",
            "--checkpoint-dir", str(tmp_path / "ckpt"),
            "--output-dir", str(out),
        ])

    first = online_game.run(args_for(tmp_path / "out1"))
    assert first["rounds"] == 1 and first["published"] == 1

    # "Restart": a second run over the same append dir.  part-000 is
    # consumed (no new rounds), but its entities must STILL be in the
    # final model via the consumed-part replay.
    write_game_avro(
        str(appends / "part-001.avro"),
        take_rows(data, np.nonzero(ids < 6)[0]), maps,
    )
    second = online_game.run(args_for(tmp_path / "out2"))
    assert second["rounds"] == 1  # only part-001 is a new round
    model, _ = load_game_model(str(tmp_path / "out2" / "model"))
    keys = np.asarray(model.coordinates["per_user"].keys)
    # Entities from the ALREADY-PUBLISHED part-000 survive the restart.
    assert (keys >= 34).sum() == 10
