"""The shared disk-cache root contract (utils/caches.py): one precedence
rule for the route, stream-layout, and aligned-layout caches."""

import os

from photon_tpu.utils.caches import resolve_cache_dir


def test_explicit_override_wins(monkeypatch):
    monkeypatch.setenv("PHOTON_LAYOUT_CACHE", "/tmp/somewhere")
    monkeypatch.setenv("PHOTON_ROUTE_CACHE", "/tmp/elsewhere")
    assert resolve_cache_dir("PHOTON_LAYOUT_CACHE", "layouts") == "/tmp/somewhere"


def test_zero_disables(monkeypatch):
    monkeypatch.setenv("PHOTON_LAYOUT_CACHE", "0")
    monkeypatch.setenv("PHOTON_ROUTE_CACHE", "/tmp/elsewhere")
    assert resolve_cache_dir("PHOTON_LAYOUT_CACHE", "layouts") is None


def test_follows_route_cache(monkeypatch):
    monkeypatch.delenv("PHOTON_LAYOUT_CACHE", raising=False)
    monkeypatch.setenv("PHOTON_ROUTE_CACHE", "/tmp/routes")
    assert resolve_cache_dir("PHOTON_LAYOUT_CACHE", "layouts") == os.path.join(
        "/tmp/routes", "layouts"
    )


def test_route_zero_disables_followers(monkeypatch):
    monkeypatch.delenv("PHOTON_STREAM_LAYOUT_CACHE", raising=False)
    monkeypatch.setenv("PHOTON_ROUTE_CACHE", "0")
    assert resolve_cache_dir("PHOTON_STREAM_LAYOUT_CACHE", "stream") is None


def test_route_cache_resolves_own_root(monkeypatch):
    monkeypatch.setenv("PHOTON_ROUTE_CACHE", "/tmp/routes")
    assert resolve_cache_dir("PHOTON_ROUTE_CACHE", "") == "/tmp/routes"
    monkeypatch.delenv("PHOTON_ROUTE_CACHE", raising=False)
    root = resolve_cache_dir("PHOTON_ROUTE_CACHE", "")
    assert root is not None  # default root (memoized per process)


def test_override_wins_even_when_route_cache_disabled(monkeypatch):
    """Precedence order regression guard: a follower's explicit override
    must win even with PHOTON_ROUTE_CACHE=0 (the suite's own global
    default) — checking the route sentinel first would wrongly disable
    an explicitly enabled cache."""
    monkeypatch.setenv("PHOTON_ROUTE_CACHE", "0")
    monkeypatch.setenv("PHOTON_LAYOUT_CACHE", "/tmp/explicit")
    assert resolve_cache_dir("PHOTON_LAYOUT_CACHE", "layouts") == "/tmp/explicit"


def test_default_root_location(monkeypatch, tmp_path):
    """The default root must honor an existing CWD legacy cache, else
    fall under ~/.cache (the ADVICE-r4 no-CWD-pollution contract) —
    'is not None' alone would let a wrong location regress silently."""
    from photon_tpu.utils import caches

    monkeypatch.delenv("PHOTON_ROUTE_CACHE", raising=False)
    caches.default_route_cache_root.cache_clear()
    monkeypatch.chdir(tmp_path)  # no legacy dir here
    try:
        assert caches.default_route_cache_root() == os.path.join(
            os.path.expanduser("~"), ".cache", "photon_tpu", "routes"
        )
        caches.default_route_cache_root.cache_clear()
        os.makedirs(tmp_path / ".photon_route_cache")
        assert caches.default_route_cache_root() == str(
            tmp_path / ".photon_route_cache"
        )
    finally:
        caches.default_route_cache_root.cache_clear()
