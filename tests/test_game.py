"""GAME engine tests: bucketing, batched solves, coordinate descent.

Mirrors the reference's integration-test strategy (SURVEY.md §4): the
batched/vmapped random-effect solver is cross-checked against independent
sequential per-entity solves (the distributed-vs-local trick), and full
GameEstimator fits on tiny synthetic GAME data must converge with improving
validation metrics.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from photon_tpu.core.objective import GlmObjective, RegularizationContext
from photon_tpu.core.optimizers import OptimizerConfig
from photon_tpu.core.problem import GlmOptimizationProblem, ProblemConfig
from photon_tpu.data.batch import dense_batch
from photon_tpu.data.synthetic import make_game_data
from photon_tpu.evaluation.evaluators import MultiEvaluator, get_evaluator
from photon_tpu.game import (
    CoordinateDescent,
    DenseShard,
    FixedEffectCoordinate,
    FixedEffectCoordinateConfig,
    GameDataset,
    GameEstimator,
    GameOptimizationConfiguration,
    RandomEffectCoordinate,
    RandomEffectCoordinateConfig,
    build_random_effect_dataset,
)
from photon_tpu.parallel import create_mesh


def _game_dataset(seed=0, n_entities=40, rows_mean=6, fixed_dim=5, random_dim=3):
    raw = make_game_data(
        n_entities=n_entities,
        rows_per_entity_mean=rows_mean,
        fixed_dim=fixed_dim,
        random_dim=random_dim,
        seed=seed,
    )
    return GameDataset.create(
        label=raw["label"],
        shards={
            "global": DenseShard(raw["x_fixed"]),
            "per_entity": DenseShard(raw["x_random"]["re0"]),
        },
        id_columns={"userId": raw["entity_ids"]["re0"]},
        weight=raw["weight"],
    )


# ---------------------------------------------------------------------------
# Random-effect dataset bucketing
# ---------------------------------------------------------------------------


def test_bucketing_partitions_all_rows_once():
    data = _game_dataset()
    ds = build_random_effect_dataset(data, "userId", "per_entity")
    seen = []
    for bucket in ds.buckets:
        mask = bucket.row_weight > 0
        assert bucket.row_capacity >= mask.sum(axis=1).max()
        # power-of-two capacities
        assert bucket.row_capacity & (bucket.row_capacity - 1) == 0
        seen.append(bucket.row_index[mask])
    seen = np.concatenate(seen)
    assert sorted(seen.tolist()) == list(range(data.num_examples))
    # every entity present exactly once across buckets
    all_entities = np.concatenate([b.entity_index for b in ds.buckets])
    assert sorted(all_entities.tolist()) == list(range(ds.num_entities))


def test_bucketing_respects_active_row_cap_with_weight_correction():
    data = _game_dataset(rows_mean=10)
    cap = 4
    ds = build_random_effect_dataset(data, "userId", "per_entity", active_row_cap=cap)
    raw_counts = np.bincount(
        ds.entity_idx_per_row[ds.entity_idx_per_row >= 0], minlength=ds.num_entities
    )
    for bucket in ds.buckets:
        assert bucket.row_capacity <= cap
        mask = bucket.row_weight > 0
        for i, e in enumerate(bucket.entity_index):
            rows_kept = int(mask[i].sum())
            assert rows_kept == min(raw_counts[e], cap)
            # weight mass is preserved in expectation: kept rows upweighted
            expected_mass = data.weight[
                ds.entity_idx_per_row == e
            ].sum()
            np.testing.assert_allclose(
                bucket.row_weight[i].sum(), expected_mass, rtol=1e-5
            )


def test_entity_index_for_unseen_keys():
    data = _game_dataset()
    ds = build_random_effect_dataset(data, "userId", "per_entity")
    idx = ds.entity_index_for(np.array([0, 10**9, 1]))
    assert idx[0] >= 0 and idx[2] >= 0
    assert idx[1] == -1


def test_missing_marker_rows_stay_out_of_cold_rebuild_vocab():
    """A cold rebuild over a merged dataset must reproduce the incremental
    path's missing-id semantics (ISSUE 19 satellite): rows whose id column
    carries the dtype-relative missing marker map to per-row entity index
    -1 — zero margin, no bin membership — instead of materializing a
    marker "entity" that trains its own random effect."""
    from photon_tpu.game.data import missing_key

    data = _game_dataset()
    raw = data.id_columns["userId"].copy()
    marker = missing_key(raw.dtype)
    absent = np.zeros(len(raw), bool)
    absent[::7] = True
    raw[absent] = marker
    marked = GameDataset.create(
        label=data.label,
        shards=dict(data.shards),
        id_columns={"userId": raw},
        weight=data.weight,
    )
    ds = build_random_effect_dataset(marked, "userId", "per_entity")
    assert marker not in ds.keys
    assert (ds.entity_idx_per_row[absent] == -1).all()
    assert (ds.entity_idx_per_row[~absent] >= 0).all()
    # Every bucket row belongs to a REAL entity: the marked rows carry no
    # bin membership anywhere.
    covered = np.concatenate([
        b.row_index[b.row_weight > 0] for b in ds.buckets
    ])
    assert not np.intersect1d(covered, np.nonzero(absent)[0]).size
    # An explicit vocabulary is the caller's verbatim choice: not filtered.
    pinned = build_random_effect_dataset(
        marked, "userId", "per_entity",
        vocab=np.concatenate([np.unique(raw)]),
    )
    assert marker in pinned.keys
    # Disabling the hook restores the historical behavior (the marker
    # becomes an ordinary entity).
    legacy = build_random_effect_dataset(
        marked, "userId", "per_entity", missing_marker=None,
    )
    assert marker in legacy.keys
    assert (legacy.entity_idx_per_row >= 0).all()


# ---------------------------------------------------------------------------
# Batched (vmapped) random-effect solves vs sequential per-entity solves
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("optimizer", ["lbfgs", "tron"])
def test_vmapped_solves_match_sequential(optimizer):
    data = _game_dataset(seed=3, n_entities=12, rows_mean=5)
    config = RandomEffectCoordinateConfig(
        shard_name="per_entity",
        entity_column="userId",
        problem=ProblemConfig(
            optimizer=optimizer,
            regularization=RegularizationContext("l2", 0.5),
            optimizer_config=OptimizerConfig(max_iterations=50),
        ),
    )
    coord = RandomEffectCoordinate(data, config, "logistic_regression")
    offsets = np.zeros(data.num_examples, np.float32)
    model, stats = coord.train(offsets)
    assert stats["entities"] == coord.dataset.num_entities

    # Sequential reference: solve each entity's rows independently.
    obj = GlmObjective.create("logistic", RegularizationContext("l2", 0.5))
    problem = GlmOptimizationProblem(obj, config.problem)
    shard = data.shards["per_entity"]
    for e in range(coord.dataset.num_entities):
        rows = np.nonzero(coord.dataset.entity_idx_per_row == e)[0]
        batch = dense_batch(
            shard.x[rows], data.label[rows], weight=data.weight[rows]
        )
        coefficients, _ = problem.run(batch, jnp.zeros(shard.dim, jnp.float32))
        np.testing.assert_allclose(
            model.table[e], coefficients.means, rtol=5e-3, atol=5e-3
        )


def test_random_effect_scores_zero_for_unseen_entities():
    train = _game_dataset(seed=1, n_entities=10)
    config = RandomEffectCoordinateConfig(
        shard_name="per_entity", entity_column="userId",
        problem=ProblemConfig(
            regularization=RegularizationContext("l2", 1.0),
            optimizer_config=OptimizerConfig(max_iterations=20),
        ),
    )
    coord = RandomEffectCoordinate(train, config, "logistic_regression")
    model, _ = coord.train(np.zeros(train.num_examples, np.float32))
    # Score a dataset containing unseen entity keys.
    other = GameDataset.create(
        label=train.label[:4],
        shards={"per_entity": DenseShard(train.shards["per_entity"].x[:4])},
        id_columns={"userId": np.array([10**6, 10**6 + 1, 0, 1], np.int64)},
    )
    scores = model.score(other)
    assert scores[0] == 0.0 and scores[1] == 0.0
    assert scores[2] != 0.0 or scores[3] != 0.0


# ---------------------------------------------------------------------------
# Coordinate descent / estimator
# ---------------------------------------------------------------------------


def _configs(descent_iterations=2, lam_fixed=0.01, lam_re=1.0):
    return GameOptimizationConfiguration(
        coordinates={
            "fixed": FixedEffectCoordinateConfig(
                shard_name="global",
                problem=ProblemConfig(
                    regularization=RegularizationContext("l2", lam_fixed),
                    optimizer_config=OptimizerConfig(max_iterations=60),
                ),
            ),
            "per-user": RandomEffectCoordinateConfig(
                shard_name="per_entity",
                entity_column="userId",
                problem=ProblemConfig(
                    regularization=RegularizationContext("l2", lam_re),
                    optimizer_config=OptimizerConfig(max_iterations=30),
                ),
            ),
        },
        descent_iterations=descent_iterations,
    )


def _split_rows(data: GameDataset, frac=0.25, seed=0):
    """Row-wise train/validation split of one GameDataset (same ground-truth
    model on both sides — the valid way to test generalization here)."""
    rng = np.random.default_rng(seed)
    val_mask = rng.random(data.num_examples) < frac

    def subset(mask):
        rows = np.nonzero(mask)[0]
        from photon_tpu.game.data import _gather_shard_rows

        return GameDataset(
            label=data.label[rows],
            offset=data.offset[rows],
            weight=data.weight[rows],
            shards={k: _gather_shard_rows(s, rows) for k, s in data.shards.items()},
            id_columns={k: v[rows] for k, v in data.id_columns.items()},
        )

    return subset(~val_mask), subset(val_mask)


def test_game_estimator_beats_fixed_effect_alone():
    full = _game_dataset(seed=7, n_entities=60, rows_mean=20)
    train, val = _split_rows(full)
    evaluators = MultiEvaluator([get_evaluator("auc"), get_evaluator("logistic_loss")])

    estimator = GameEstimator(
        "logistic_regression", train, val, evaluators=evaluators
    )
    game_results = estimator.fit([_configs()])
    best = estimator.select_best(game_results)

    # Fixed-effect-only baseline on the same data.
    fixed_only = GameEstimator(
        "logistic_regression", train, val, evaluators=evaluators
    ).fit(
        [
            GameOptimizationConfiguration(
                coordinates={
                    "fixed": _configs().coordinates["fixed"],
                },
                descent_iterations=1,
            )
        ]
    )[0]
    assert best.metrics["AUC"] > fixed_only.metrics["AUC"]
    assert best.metrics["LOGISTIC_LOSS"] < fixed_only.metrics["LOGISTIC_LOSS"]


def test_game_model_score_is_offset_plus_coordinate_sum():
    train = _game_dataset(seed=2, n_entities=20)
    result = GameEstimator("logistic_regression", train).fit(
        [_configs(descent_iterations=1)]
    )[0]
    model = result.model
    total = model.score(train)
    parts = sum(np.asarray(m.score(train)) for m in model.coordinates.values())
    np.testing.assert_allclose(total, train.offset + parts, rtol=1e-5, atol=1e-5)


def test_sweep_selects_best_configuration():
    train, val = _split_rows(_game_dataset(seed=4, n_entities=40, rows_mean=16))
    estimator = GameEstimator("logistic_regression", train, val)
    results = estimator.fit(
        [_configs(lam_re=1000.0), _configs(lam_re=1.0)]
    )
    best = estimator.select_best(results)
    assert best is results[int(np.argmax([r.metrics["AUC"] for r in results]))]


def test_warm_start_and_locked_coordinates():
    train, val = _split_rows(_game_dataset(seed=9, n_entities=25, rows_mean=12))
    estimator = GameEstimator("logistic_regression", train, val)
    first = estimator.fit([_configs(descent_iterations=1)])[0]

    # Retrain with the fixed effect locked: its coefficients must not move.
    second = estimator.fit(
        [_configs(descent_iterations=1)],
        initial_model=first.model,
        locked_coordinates=["fixed"],
    )[0]
    np.testing.assert_array_equal(
        np.asarray(second.model.coordinate("fixed").coefficients.means),
        np.asarray(first.model.coordinate("fixed").coefficients.means),
    )
    # The unlocked coordinate was retrained from the warm start.
    assert "per-user" in second.model.coordinates


def test_warm_start_aligns_entity_vocabularies_by_key():
    """A warm-start model trained on a different entity set must be joined
    by key, not by index (review finding: silent index misalignment)."""
    train = _game_dataset(seed=13, n_entities=12)
    config = RandomEffectCoordinateConfig(
        shard_name="per_entity", entity_column="userId",
        problem=ProblemConfig(
            regularization=RegularizationContext("l2", 1.0),
            optimizer_config=OptimizerConfig(max_iterations=5),
        ),
    )
    coord = RandomEffectCoordinate(train, config, "logistic_regression")
    model, _ = coord.train(np.zeros(train.num_examples, np.float32))
    # Shift the model's keys so only some overlap with the dataset's vocab.
    from photon_tpu.game.model import RandomEffectModel

    shifted = RandomEffectModel(
        table=model.table,
        keys=model.keys + 6,  # keys 6..17 vs dataset keys 0..11
        entity_column=model.entity_column,
        shard_name=model.shard_name,
        task_type=model.task_type,
    )
    init_table = np.asarray(coord._initial_table(shifted))
    for e, key in enumerate(coord.dataset.keys):
        src = np.searchsorted(shifted.keys, key)
        if src < len(shifted.keys) and shifted.keys[src] == key:
            np.testing.assert_array_equal(init_table[e], np.asarray(model.table)[src])
        else:
            np.testing.assert_array_equal(init_table[e], 0.0)


def test_locked_coordinate_without_initial_model_raises():
    train = _game_dataset(seed=11, n_entities=10)
    estimator = GameEstimator("logistic_regression", train)
    with pytest.raises(ValueError):
        estimator.fit([_configs(descent_iterations=1)], locked_coordinates=["fixed"])


# ---------------------------------------------------------------------------
# Mesh-sharded GAME training (8 virtual devices)
# ---------------------------------------------------------------------------


def test_game_training_on_mesh_matches_single_device():
    train = _game_dataset(seed=12, n_entities=30, rows_mean=5)
    config = _configs(descent_iterations=1)
    single = GameEstimator("logistic_regression", train).fit([config])[0]
    mesh = create_mesh()
    sharded = GameEstimator("logistic_regression", train, mesh=mesh).fit([config])[0]
    np.testing.assert_allclose(
        np.asarray(single.model.coordinate("fixed").coefficients.means),
        np.asarray(sharded.model.coordinate("fixed").coefficients.means),
        rtol=1e-3, atol=1e-3,
    )
    np.testing.assert_allclose(
        np.asarray(single.model.coordinate("per-user").table),
        np.asarray(sharded.model.coordinate("per-user").table),
        rtol=1e-3, atol=1e-3,
    )


def test_factored_random_effect_coordinate():
    """FactoredRandomEffectCoordinate (SURVEY.md §2.2 [K?]): when the true
    per-entity effects share a low-rank subspace and rows are scarce, the
    rank-constrained fit w_e = L z_e must generalize BETTER than the free
    per-entity fit (that sharing is the component's entire point)."""
    import numpy as np

    from photon_tpu.core.objective import RegularizationContext
    from photon_tpu.core.optimizers import OptimizerConfig
    from photon_tpu.core.problem import ProblemConfig
    from photon_tpu.data.index_map import IndexMap, feature_key
    from photon_tpu.evaluation.evaluators import get_evaluator
    from photon_tpu.game.coordinate import (
        FactoredRandomEffectCoordinate,
        FactoredRandomEffectCoordinateConfig,
        RandomEffectCoordinate,
        RandomEffectCoordinateConfig,
    )
    from photon_tpu.game.data import DenseShard, GameDataset

    rng = np.random.default_rng(17)
    n_entities, rows_tr, rows_va, d, true_rank = 60, 6, 8, 10, 2
    u_true = rng.standard_normal((d, true_rank)) * 1.6
    z_true = rng.standard_normal((n_entities, true_rank))
    w_true = z_true @ u_true.T  # [entities, d] — rank-2 effects

    def make(rows_per):
        n = n_entities * rows_per
        ent = np.repeat(np.arange(n_entities), rows_per)
        x = rng.standard_normal((n, d)).astype(np.float32)
        margin = np.einsum("nd,nd->n", x, w_true[ent])
        label = (rng.random(n) < 1 / (1 + np.exp(-margin))).astype(np.float32)
        return GameDataset(
            shards={"re0": DenseShard(x)},
            label=label,
            offset=np.zeros(n, np.float32),
            weight=np.ones(n, np.float32),
            id_columns={"re0": ent},
        )

    train_ds, val_ds = make(rows_tr), make(rows_va)
    prob = ProblemConfig(
        regularization=RegularizationContext("l2", 1.0),
        optimizer_config=OptimizerConfig(max_iterations=10),
    )
    offsets = np.zeros(train_ds.num_examples, np.float32)
    auc = get_evaluator("AUC")

    fc = FactoredRandomEffectCoordinate(
        train_ds,
        FactoredRandomEffectCoordinateConfig(
            "re0", "re0", latent_dim=2, latent_iterations=4, problem=prob
        ),
        "logistic_regression",
    )
    m_fact, stats = fc.train(offsets)
    assert stats["entities"] == n_entities
    val_fact = auc.evaluate(
        np.asarray(m_fact.score(val_ds)), val_ds.label, val_ds.weight
    )

    rc = RandomEffectCoordinate(
        train_ds, RandomEffectCoordinateConfig("re0", "re0", problem=prob),
        "logistic_regression",
    )
    m_free, _ = rc.train(offsets)
    val_free = auc.evaluate(
        np.asarray(m_free.score(val_ds)), val_ds.label, val_ds.weight
    )
    assert val_fact > 0.78, f"factored val AUC too low: {val_fact}"
    assert val_fact > val_free + 0.03, (val_fact, val_free)


def test_factored_random_effect_driver_spec(tmp_path):
    """type=factored_random parses and trains end-to-end in train_game."""
    from photon_tpu.drivers import train_game

    summary = train_game.run(train_game.build_parser().parse_args([
        "--backend", "cpu",
        "--input", "synthetic-game:24:4:8:4:1:7",
        "--coordinate", "fixed:type=fixed,shard=global,max_iters=8",
        "--coordinate",
        "per_user:type=factored_random,shard=re0,entity=re0,"
        "latent_dim=2,latent_iterations=2,max_iters=6",
        "--descent-iterations", "2",  # iteration 2 exercises the SVD warm start
        "--validation-split", "0.25",
        "--output-dir", str(tmp_path / "out"),
    ]))
    assert summary["best_metrics"]["AUC"] > 0.5
    import os
    assert os.path.isdir(
        os.path.join(tmp_path, "out", "best_model", "random-effect", "per_user")
    )


def test_factored_random_effect_on_mesh_matches_single():
    """The pooled projection solve partitions over the mesh via GSPMD; an
    8-virtual-device run must match single-device results."""
    import numpy as np

    from photon_tpu.core.objective import RegularizationContext
    from photon_tpu.core.optimizers import OptimizerConfig
    from photon_tpu.core.problem import ProblemConfig
    from photon_tpu.game.coordinate import (
        FactoredRandomEffectCoordinate,
        FactoredRandomEffectCoordinateConfig,
    )
    from photon_tpu.game.data import DenseShard, GameDataset
    from photon_tpu.parallel.mesh import create_mesh

    rng = np.random.default_rng(23)
    n_entities, rows, d = 24, 5, 8
    n = n_entities * rows
    ent = np.repeat(np.arange(n_entities), rows)
    x = rng.standard_normal((n, d)).astype(np.float32)
    label = (rng.random(n) < 0.5).astype(np.float32)
    data = GameDataset(
        shards={"re0": DenseShard(x)}, label=label,
        offset=np.zeros(n, np.float32), weight=np.ones(n, np.float32),
        id_columns={"re0": ent},
    )
    cfg = FactoredRandomEffectCoordinateConfig(
        "re0", "re0", latent_dim=2, latent_iterations=2,
        problem=ProblemConfig(
            regularization=RegularizationContext("l2", 1.0),
            optimizer_config=OptimizerConfig(max_iterations=6),
        ),
    )
    offsets = np.zeros(n, np.float32)
    m_single, _ = FactoredRandomEffectCoordinate(
        data, cfg, "logistic_regression"
    ).train(offsets)
    m_mesh, _ = FactoredRandomEffectCoordinate(
        data, cfg, "logistic_regression", mesh=create_mesh(8)
    ).train(offsets)
    np.testing.assert_allclose(
        np.asarray(m_mesh.table), np.asarray(m_single.table),
        rtol=5e-3, atol=5e-4,
    )


def test_fixed_effect_pallas_kernel_on_sparse_shard(monkeypatch):
    """A sparse-shard GAME fixed effect under PHOTON_SPARSE_GRAD=pallas
    attaches the aligned layout and trains to the same optimum as the fm
    path (the coordinate-level wiring of the third kernel)."""
    rng = np.random.default_rng(44)
    n, k, d = 160, 4, 40
    ids = np.sort(
        rng.integers(0, d, size=(n, k)).astype(np.int32), axis=1
    )
    vals = rng.standard_normal((n, k)).astype(np.float32)
    y = (rng.random(n) < 0.5).astype(np.float32)
    from photon_tpu.game.data import GameDataset, SparseShard

    data = GameDataset.create(y, {"global": SparseShard(ids, vals, d)})
    problem = ProblemConfig(
        regularization=RegularizationContext("l2", 1.0),
        optimizer_config=OptimizerConfig(max_iterations=10),
    )
    results = {}
    for kernel in ("pallas", "fm"):
        monkeypatch.setenv("PHOTON_SPARSE_GRAD", kernel)
        coord = FixedEffectCoordinate(
            data, FixedEffectCoordinateConfig("global", problem),
            "logistic_regression",
        )
        if kernel == "pallas":
            assert coord.device_data.batch.al is not None
        else:
            assert coord.device_data.batch.al is None
        model, tracker = coord.train(np.zeros(data.num_examples, np.float32))
        results[kernel] = (tracker.iterations, np.asarray(model.coefficients.means))
    assert results["pallas"][0] == results["fm"][0], "iteration paths diverged"
    np.testing.assert_allclose(
        results["pallas"][1], results["fm"][1], rtol=1e-3, atol=1e-4
    )
