"""Solver trace-sharing: one compiled program per static configuration.

The round-3 refactor makes objectives jit pytrees (reg weights are dynamic
leaves) and routes every GLM fit through module-level cached solvers
(core/problem.py::cached_solver), so a lambda sweep or hyperparameter search
traces its optimizer loop ONCE.  The reference pays a JVM-warmup/classload
analog once per driver run; retracing per sweep point was this rebuild's
equivalent regression and is pinned here.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from photon_tpu.core.objective import GlmObjective, RegularizationContext
from photon_tpu.core.optimizers import OptimizerConfig
from photon_tpu.core.problem import (
    GlmOptimizationProblem,
    ProblemConfig,
    cached_solver,
)
from photon_tpu.data.batch import SparseBatch, attach_feature_major


def _batch(n=256, k=5, d=24, seed=0):
    rng = np.random.default_rng(seed)
    ids = rng.integers(1, d, size=(n, k), dtype=np.int32)
    vals = rng.standard_normal((n, k)).astype(np.float32)
    label = (rng.random(n) < 0.5).astype(np.float32)
    return attach_feature_major(SparseBatch(
        jnp.asarray(ids), jnp.asarray(vals), jnp.asarray(label),
        jnp.zeros(n, jnp.float32), jnp.ones(n, jnp.float32),
    ))


@pytest.mark.parametrize("optimizer,reg_type", [
    ("lbfgs", "l2"), ("owlqn", "elastic_net"), ("tron", "l2"),
])
def test_lambda_sweep_traces_once(optimizer, reg_type):
    batch = _batch()
    ocfg = OptimizerConfig(max_iterations=12)
    solver = cached_solver(optimizer, ocfg, "none", False)
    start = solver._cache_size()
    results = []
    for lam in (0.05, 0.5, 5.0):
        reg = RegularizationContext(reg_type, lam)
        cfg = ProblemConfig(optimizer=optimizer, regularization=reg,
                            optimizer_config=ocfg)
        obj = GlmObjective.create("logistic", reg)
        coeffs, res = GlmOptimizationProblem(obj, cfg).run(batch, dim=24)
        assert np.isfinite(np.asarray(coeffs.means)).all()
        results.append(np.asarray(coeffs.means))
    # The three lambdas produced genuinely different fits from ONE trace.
    assert solver._cache_size() - start <= 1
    assert not np.allclose(results[0], results[2])


def test_dynamic_weights_match_eager_objective():
    """A traced (tracer-reg-weight) solve must equal the eager evaluation
    of the same objective — the pytree refactor cannot change numerics."""
    batch = _batch(seed=3)
    reg = RegularizationContext("l2", 1.3)
    obj = GlmObjective.create("logistic", reg)
    cfg = ProblemConfig(optimizer="lbfgs", regularization=reg,
                        optimizer_config=OptimizerConfig(max_iterations=25))
    coeffs, _ = GlmOptimizationProblem(obj, cfg).run(batch, dim=24)
    w = coeffs.means
    # Eager value/grad at the optimum: gradient must vanish.
    v, g = obj.value_and_grad(w, batch)
    assert float(jnp.linalg.norm(g)) < 1e-2 * max(1.0, float(jnp.abs(v)))


def test_vmapped_solver_shared_across_instances():
    """Two coordinate-style vmapped solvers with the same static config are
    the same object (module cache), not per-instance jits."""
    reg = RegularizationContext("l2", 1.0)
    cfg = ProblemConfig(optimizer="lbfgs", regularization=reg)
    p1 = GlmOptimizationProblem(GlmObjective.create("logistic", reg), cfg)
    p2 = GlmOptimizationProblem(
        GlmObjective.create("logistic", reg.replace(reg_weight=9.0)), cfg
    )
    assert p1.solver(vmapped=True) is p2.solver(vmapped=True)
