"""End-to-end coverage for the `xchg` kernel (ops/vperm exchange).

Same contract as the benes/pallas kernel tests: with
PHOTON_SPARSE_GRAD=xchg the objective's value+grad, normalized
gradient, Hv, and a full L-BFGS solve must match autodiff.  Kernels run
in interpret mode off-TPU (the identical code lowers on hardware).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from photon_tpu.core.objective import GlmObjective, RegularizationContext
from photon_tpu.data.batch import SparseBatch, attach_feature_major


def _random_batch(n, k, d, seed=0, zipf=False):
    rng = np.random.default_rng(seed)
    if zipf:
        ranks = rng.zipf(1.3, size=(n, k)).astype(np.int64)
        ids = np.minimum(ranks - 1, d - 1).astype(np.int32)
    else:
        ids = rng.integers(0, d, size=(n, k)).astype(np.int32)
    vals = rng.standard_normal((n, k)).astype(np.float32)
    vals[rng.random((n, k)) < 0.15] = 0.0
    return SparseBatch(
        ids=jnp.asarray(ids),
        vals=jnp.asarray(vals),
        label=jnp.asarray((rng.random(n) < 0.4).astype(np.float32)),
        offset=jnp.asarray(rng.standard_normal(n).astype(np.float32) * 0.1),
        weight=jnp.asarray(rng.uniform(0.5, 2.0, n).astype(np.float32)),
    )


@pytest.mark.parametrize("loss", ["logistic", "squared"])
@pytest.mark.parametrize("zipf", [False, True])
@pytest.mark.parametrize("reduce", ["aligned", "cumsum"])
def test_xchg_kernel_matches_autodiff(monkeypatch, loss, zipf, reduce):
    monkeypatch.setenv("PHOTON_SPARSE_GRAD", "xchg")
    monkeypatch.setenv("PHOTON_XCHG_REDUCE", reduce)
    n, k, d = 256, 6, 48
    batch = _random_batch(n, k, d, seed=80, zipf=zipf)
    fast = attach_feature_major(batch, aligned_dim=d)
    assert fast.al is not None and fast.xchg is not None
    assert fast.al_t is not None  # xchg implies the pallas forward
    assert (fast.xchg.bounds is not None) == (reduce == "cumsum")
    # Both reduce modes ride the balanced exchange with the pre-permuted
    # static value stream at these sizes; pin that the vals_dest fast
    # path is what's under test.
    assert fast.xchg.vals_dest is not None
    obj = GlmObjective.create(loss, RegularizationContext("l2", 0.6))
    rng = np.random.default_rng(81)
    w = jnp.asarray(rng.standard_normal(d), jnp.float32) * 0.1

    assert obj._sparse_kernel(fast, d) == "xchg"
    v_ref, g_ref = jax.value_and_grad(obj.value)(w, batch)
    v_x, g_x = obj.value_and_grad(w, fast)
    np.testing.assert_allclose(v_x, v_ref, rtol=1e-5)
    np.testing.assert_allclose(g_x, g_ref, rtol=2e-4, atol=1e-5)
    v_j, g_j = jax.jit(obj.value_and_grad)(w, fast)
    np.testing.assert_allclose(g_j, g_ref, rtol=2e-4, atol=1e-5)

    vec = jnp.asarray(rng.standard_normal(d), jnp.float32)
    hv_ref = jax.jvp(lambda u: jax.grad(obj.value)(u, batch), (w,), (vec,))[1]
    hv = obj.hessian_vector(w, vec, fast)
    np.testing.assert_allclose(hv, hv_ref, rtol=2e-4, atol=1e-5)


def test_xchg_kernel_under_normalization(monkeypatch):
    from photon_tpu.core.normalization import NormalizationContext
    from photon_tpu.core.stats import BasicStatisticalSummary

    monkeypatch.setenv("PHOTON_SPARSE_GRAD", "xchg")
    n, k, d = 192, 5, 40
    batch = _random_batch(n, k, d, seed=82)
    fast = attach_feature_major(batch, aligned_dim=d)
    summary = BasicStatisticalSummary.from_batch(batch, d)
    norm = NormalizationContext.build(
        "standardization", summary, intercept_id=0
    )
    obj = GlmObjective.create(
        "logistic", RegularizationContext("l2", 0.4), normalization=norm
    )
    w = jnp.asarray(
        np.random.default_rng(83).standard_normal(d), jnp.float32
    ) * 0.1
    v_ref, g_ref = jax.value_and_grad(obj.value)(w, batch)
    v_x, g_x = obj.value_and_grad(w, fast)
    np.testing.assert_allclose(v_x, v_ref, rtol=1e-5)
    np.testing.assert_allclose(g_x, g_ref, rtol=2e-4, atol=1e-5)


def test_xchg_route_not_built_in_auto_below_floor(monkeypatch):
    """Auto mode must not pay the edge-coloring for small problems (and
    never on a CPU backend)."""
    monkeypatch.setenv("PHOTON_SPARSE_GRAD", "auto")
    batch = _random_batch(64, 4, 32, seed=84)
    fast = attach_feature_major(batch, aligned_dim=32)
    assert fast.xchg is None


def test_game_fixed_effect_with_xchg_forced(monkeypatch, tmp_path):
    """The GAME training driver end-to-end with the xchg kernel forced:
    the fixed-effect coordinate's attach builds the routes and training
    converges to finite metrics (route plumbing inside coordinates)."""
    from photon_tpu.drivers import train_game

    monkeypatch.setenv("PHOTON_SPARSE_GRAD", "xchg")
    monkeypatch.setenv("PHOTON_XCHG_REDUCE", "cumsum")
    monkeypatch.setenv("PHOTON_ROUTE_CACHE", "0")
    out = train_game.run(train_game.build_parser().parse_args([
        "--backend", "cpu",
        "--input", "synthetic-game:32:4:8:4:1:7",
        "--coordinate", "fixed:type=fixed,shard=global,max_iters=4",
        "--coordinate", "per_user:type=random,shard=re0,entity=re0,max_iters=3",
        "--descent-iterations", "1",
        "--validation-split", "0.25",
        "--output-dir", str(tmp_path / "out"),
    ]))
    for v in out["best_metrics"].values():
        assert np.isfinite(v)


def test_xchg_lbfgs_training_converges(monkeypatch):
    from photon_tpu.core.optimizers import lbfgs

    n, k, d = 256, 5, 32
    monkeypatch.setenv("PHOTON_SPARSE_GRAD", "xchg")
    batch = _random_batch(n, k, d, seed=85)
    fast = attach_feature_major(batch, aligned_dim=d)
    obj = GlmObjective.create("logistic", RegularizationContext("l2", 1.0))
    w0 = jnp.zeros(d, jnp.float32)
    res_x = lbfgs(lambda w: obj.value_and_grad(w, fast), w0)

    monkeypatch.setenv("PHOTON_SPARSE_GRAD", "autodiff")
    res_a = lbfgs(lambda w: obj.value_and_grad(w, batch), w0)
    # Different reduction orders walk slightly different line-search paths;
    # the optima must agree tightly in objective value and loosely in w.
    np.testing.assert_allclose(
        np.asarray(res_x.w), np.asarray(res_a.w), rtol=1e-2, atol=1e-3
    )
    np.testing.assert_allclose(
        float(obj.value(res_x.w, batch)), float(obj.value(res_a.w, batch)),
        rtol=1e-6,
    )
