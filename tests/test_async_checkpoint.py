"""ISSUE 5: async checkpoint publisher + one-sync-per-iteration descent.

Pins the two tentpole contracts: (1) the async publisher stages d2h on the
loop thread and publishes in the background with bounded depth 1, surfacing
failures on the next save/drain and keeping kill-window atomicity; (2) the
descent loop performs exactly ONE stats/quarantine host sync per outer
iteration (``descent.host_syncs``) — the per-coordinate train() stats drain
is gone."""

import os
import time

import numpy as np
import pytest

from photon_tpu.core.objective import RegularizationContext
from photon_tpu.core.optimizers import OptimizerConfig
from photon_tpu.core.problem import ProblemConfig
from photon_tpu.data.synthetic import make_game_dataset
from photon_tpu.fault.checkpoint import (
    AsyncPublisher,
    DescentCheckpointer,
    resolve_checkpoint_async,
)
from photon_tpu.fault.injection import (
    FaultPlan,
    InjectedKillError,
    set_plan,
)
from photon_tpu.game.coordinate import (
    DeferredSolveStats,
    FixedEffectCoordinateConfig,
    RandomEffectCoordinateConfig,
)
from photon_tpu.game.data import split_game_dataset
from photon_tpu.game.estimator import GameEstimator, GameOptimizationConfiguration
from photon_tpu.telemetry import TelemetrySession


@pytest.fixture(autouse=True)
def _fault_hygiene(monkeypatch):
    monkeypatch.setenv("PHOTON_IO_RETRY_BASE_S", "0")
    set_plan(None)
    yield
    set_plan(None)


def _problem(lam: float, iters: int) -> ProblemConfig:
    return ProblemConfig(
        regularization=RegularizationContext("l2", lam),
        optimizer_config=OptimizerConfig(max_iterations=iters),
    )


def _game_fixture(seed: int = 7, iters: int = 3):
    data, _ = make_game_dataset(40, 5, 6, 3, seed=seed)
    train, val = split_game_dataset(data, 0.25)
    config = GameOptimizationConfiguration(
        coordinates={
            "fixed": FixedEffectCoordinateConfig("global", _problem(0.01, 8)),
            "re0": RandomEffectCoordinateConfig("re0", "re0", _problem(1.0, 6)),
        },
        descent_iterations=iters,
        name="async-ckpt",
    )
    return train, val, config


def _coordinate_arrays(model):
    out = {}
    for name, coord in model.coordinates.items():
        if hasattr(coord, "table"):
            out[name] = np.asarray(coord.table)
        else:
            out[name] = np.asarray(coord.coefficients.means)
    return out


# -- resolve gate ------------------------------------------------------------


def test_resolve_checkpoint_async(monkeypatch):
    assert resolve_checkpoint_async(None) is True  # default on
    assert resolve_checkpoint_async("off") is False
    assert resolve_checkpoint_async("on") is True
    assert resolve_checkpoint_async(False) is False
    monkeypatch.setenv("PHOTON_CHECKPOINT_ASYNC", "off")
    assert resolve_checkpoint_async(None) is False
    assert resolve_checkpoint_async("on") is True  # flag wins over env
    with pytest.raises(ValueError):
        resolve_checkpoint_async("maybe")


# -- publisher unit ----------------------------------------------------------


def test_publisher_failure_surfaces_on_next_submit():
    pub = AsyncPublisher(TelemetrySession("t"))

    def boom():
        raise RuntimeError("publish died")

    pub.submit(boom)
    with pytest.raises(RuntimeError, match="publish died"):
        pub.submit(lambda: None)
    # The failed slot was consumed: the replacement publish goes through.
    ran = []
    pub.submit(lambda: ran.append(1))
    pub.drain()
    assert ran == [1]


def test_publisher_drain_raises_tail_failure():
    pub = AsyncPublisher(TelemetrySession("t"))
    pub.submit(lambda: (_ for _ in ()).throw(RuntimeError("tail")))
    with pytest.raises(RuntimeError, match="tail"):
        pub.drain()
    # drain(reraise=False) never raises and clears the error.
    pub.submit(lambda: (_ for _ in ()).throw(RuntimeError("tail2")))
    pub.drain(reraise=False)
    pub.submit(lambda: None)
    pub.drain()


def test_publisher_bounded_depth_blocks_until_previous_lands():
    session = TelemetrySession("t")
    pub = AsyncPublisher(session)
    order = []

    def slow():
        time.sleep(0.15)
        order.append("first-done")

    pub.submit(slow)
    t0 = time.monotonic()
    pub.submit(lambda: order.append("second"))  # must wait for slow()
    waited = time.monotonic() - t0
    pub.drain()
    assert order == ["first-done", "second"]
    assert waited >= 0.1
    # The wait is visible as checkpoint.blocked_s.
    assert session.histogram("checkpoint.blocked_s").max >= 0.1


# -- kill windows (tentpole acceptance) --------------------------------------


@pytest.mark.parametrize("window", ["checkpoint:stage", "checkpoint:write"])
@pytest.mark.parametrize("mode", ["device", "host"])
def test_async_kill_windows_keep_previous_checkpoint_loadable(
    tmp_path, window, mode
):
    """A kill during the d2h-staging or torn-write window of an ASYNC
    publish leaves the previous checkpoint the loadable LATEST, and
    ``--resume latest`` parity with an uninterrupted fit is EXACT (0.0)."""
    train, val, config = _game_fixture()

    def fit(**kw):
        return GameEstimator(
            "logistic_regression", train, val, residual_mode=mode
        ).fit([config], checkpoint_async="on", **kw)[0]

    baseline = GameEstimator(
        "logistic_regression", train, val, residual_mode=mode
    ).fit([config])[0]

    ckpt = str(tmp_path / "ckpt")
    set_plan(FaultPlan.parse(f"{window}:iter=1"))
    with pytest.raises(InjectedKillError):
        fit(checkpoint_dir=ckpt)
    set_plan(None)

    # Iteration 0's checkpoint survived the kill and is the LATEST.
    chain = DescentCheckpointer(os.path.join(ckpt, "cfg-000"))
    latest = chain.latest_path()
    assert latest is not None and latest.endswith("ckpt-000000")
    state = DescentCheckpointer.load_path(latest)
    assert state.iteration == 0

    resumed = fit(checkpoint_dir=ckpt, resume="latest")
    assert baseline.metrics == resumed.metrics
    base_arrays = _coordinate_arrays(baseline.model)
    res_arrays = _coordinate_arrays(resumed.model)
    for name in base_arrays:
        np.testing.assert_array_equal(base_arrays[name], res_arrays[name])


def test_final_iteration_drains_before_fit_returns(tmp_path):
    """A completed fit's LAST checkpoint is published (not in flight) by
    the time fit() returns — the final-iteration drain."""
    train, val, config = _game_fixture()
    ckpt = str(tmp_path / "ckpt")
    GameEstimator("logistic_regression", train, val).fit(
        [config], checkpoint_dir=ckpt, checkpoint_async="on"
    )
    chain = DescentCheckpointer(os.path.join(ckpt, "cfg-000"))
    latest = chain.latest_path()
    assert latest is not None and latest.endswith(
        f"ckpt-{config.descent_iterations - 1:06d}"
    )
    DescentCheckpointer.load_path(latest)  # manifest-complete


def test_async_publish_telemetry(tmp_path):
    train, val, config = _game_fixture()
    session = TelemetrySession("t")
    GameEstimator(
        "logistic_regression", train, val, telemetry=session
    ).fit([config], checkpoint_dir=str(tmp_path / "c"), checkpoint_async="on")
    saves = session.counter("checkpoint.saves").value
    assert saves == config.descent_iterations
    assert session.histogram("checkpoint.publish_lag_s").count == saves
    assert session.histogram("checkpoint.blocked_s").count == saves
    # The publisher thread's spans land in the session's trace.
    assert sum(
        1 for sp in session.tracer.finished if sp.name == "checkpoint.publish"
    ) == saves


# -- one-sync-per-iteration (tentpole acceptance) ----------------------------


@pytest.mark.parametrize("mode", ["device", "host"])
def test_exactly_one_stats_sync_per_outer_iteration(mode):
    """``descent.host_syncs`` counts exactly one stats/quarantine drain per
    outer iteration — the per-coordinate train() stats sync is gone — and
    the drained stats still feed the re_solver telemetry."""
    train, val, config = _game_fixture(iters=3)
    session = TelemetrySession("t")
    GameEstimator(
        "logistic_regression", train, val, residual_mode=mode,
        telemetry=session,
    ).fit([config])
    counters = {
        (c["name"], tuple(sorted(c["labels"].items()))): c["value"]
        for c in session.registry.snapshot()["counters"]
    }
    assert counters[("descent.host_syncs", (("kind", "stats"),))] == 3
    # Deferred stats resolved at the boundary still record solver telemetry.
    assert counters[("re_solver.entities", (("coordinate", "re0"),))] > 0
    assert counters[("descent.iterations", ())] == 3


def test_deferred_stats_direct_caller_resolves_lazily():
    from photon_tpu.game.coordinate import RandomEffectCoordinate

    data, _ = make_game_dataset(20, 4, 5, 3, seed=3)
    coord = RandomEffectCoordinate(
        data, RandomEffectCoordinateConfig("re0", "re0", _problem(1.0, 5)),
        "logistic_regression",
    )
    model, stats = coord.train(np.zeros(data.num_examples, np.float32))
    assert isinstance(stats, DeferredSolveStats)
    # Dict-style access resolves on first touch (off the descent loop).
    assert stats["entities"] == coord.dataset.num_entities
    assert stats["quarantined"] == 0
    assert stats.get("converged") <= stats["entities"]
    assert "iterations_max" in stats
