"""Low-precision tables and tiles (ISSUE 17): bf16/int8 storage with f32
accumulation, per-codec parity bounds, and the machinery that keeps the
reduced tiers operationally identical to f32.

Contracts pinned here:

- quantization unit laws: int8 per-row absmax roundtrip error bound,
  exact zero rows, canonical fixed-point idempotence (re-encode of a
  decode is byte-identical — the property kill→resume digest compares
  rely on), bf16 truncation idempotence;
- serving parity per dtype vs the f32 HOST oracle (``GameModel.score``):
  request path, dataset path, cold entities, post-``swap_model`` — each
  within the codec's declared ``PARITY_TOL`` bound;
- recompile freedom per dtype: post-warmup traffic across buckets
  compiles NOTHING (the decode lives inside the warmed programs);
- ``swap_model`` preserves the storage tier: a refresh and a
  grow-in-place (within pre-provisioned capacity) keep the dtype with
  zero compiles, and a dtype-mismatched swap REFUSES;
- ``serving.table_bytes``: bf16 >= 1.9x and int8 >= 3.5x smaller than
  f32 at equal entity count (the ISSUE acceptance bars);
- tile-store codecs: lossy roundtrip within the metric bound, NaN/Inf
  payloads fall back to the lossless path bit-exactly, a corrupted int8
  SCALE ROW is refused at read (digest over the ENCODED payload — before
  a decode could silently rescale a whole row);
- spilled write-back + resume per codec: flushed lossy tiles re-attach
  exactly (memory == disk after the publish-time roundtrip), and a
  spilled fit's metrics track the host-resident streamed fit within the
  per-codec ``TILE_METRIC_TOL``;
- the estimator refuses a lossy ``tile_dtype`` without a spill dir, and
  unknown dtypes are rejected everywhere.
"""

from __future__ import annotations

import numpy as np
import pytest

from photon_tpu.core.objective import RegularizationContext
from photon_tpu.core.optimizers import OptimizerConfig
from photon_tpu.core.problem import ProblemConfig
from photon_tpu.data.synthetic import make_game_dataset
from photon_tpu.game.coordinate import (
    FixedEffectCoordinateConfig,
    RandomEffectCoordinateConfig,
)
from photon_tpu.game.data import split_game_dataset
from photon_tpu.game.estimator import (
    GameEstimator,
    GameOptimizationConfiguration,
)
from photon_tpu.game.lowp import (
    PARITY_TOL,
    TABLE_DTYPES,
    check_dtype,
    dequantize_int8_rows,
    encode_bf16,
    parity_tol_for,
    quantize_int8_canonical,
    quantize_int8_rows,
    tile_metric_tol_for,
)
from photon_tpu.game.model import (
    FixedEffectModel,
    GameModel,
    RandomEffectModel,
)
from photon_tpu.game.tile_store import (
    TILES,
    CorruptTileError,
    TileStore,
    codec_roundtrip,
)
from photon_tpu.game.tiles import (
    ChunkPlan,
    HostTileCache,
    SpilledResidualTable,
)
from photon_tpu.models.glm import Coefficients, model_for_task
from photon_tpu.serving import (
    GameScorer,
    ScoringRequest,
    build_requests,
    request_spec_for_dataset,
)
from photon_tpu.telemetry import TelemetrySession

LOSSY = ("bf16", "int8")

# random_dim 32: wide enough that int8's per-row scale column amortizes
# past the 3.5x acceptance bar (bytes ratio 4d/(d+4)).
RANDOM_DIM = 32


def _fixture(seed=3, n_entities=40, fixed_dim=6, random_dim=RANDOM_DIM):
    data, _ = make_game_dataset(
        n_entities, 4, fixed_dim, random_dim, seed=seed
    )
    rng = np.random.default_rng(seed)
    keys = np.unique(data.id_columns["re0"])
    model = GameModel(
        coordinates={
            "fixed": FixedEffectModel(
                model_for_task("logistic_regression", Coefficients(
                    rng.standard_normal(fixed_dim).astype(np.float32)
                )),
                "global",
            ),
            "per_entity": RandomEffectModel(
                table=rng.standard_normal(
                    (len(keys), random_dim)
                ).astype(np.float32),
                keys=keys, entity_column="re0", shard_name="re0",
                task_type="logistic_regression",
            ),
        },
        task_type="logistic_regression",
    )
    return model, data


def _counter_total(session, name, **labels):
    total = 0
    for m in session.registry.snapshot()["counters"]:
        if m["name"] != name:
            continue
        if labels and any(
            str(m["labels"].get(k)) != str(v) for k, v in labels.items()
        ):
            continue
        total += m["value"]
    return total


@pytest.fixture(scope="module")
def served_tiers():
    """One warmed scorer per storage dtype over the SAME model/data (the
    f32 entry doubles as the table-bytes denominator)."""
    model, data = _fixture()
    out = {}
    for dtype in TABLE_DTYPES:
        session = TelemetrySession(f"test-lowp-{dtype}")
        scorer = GameScorer(
            model, request_spec=request_spec_for_dataset(model, data),
            max_batch=64, telemetry=session, table_dtype=dtype,
        ).warmup()
        out[dtype] = (scorer, session)
    return model, data, out


# -- quantization unit laws --------------------------------------------------

def test_int8_roundtrip_error_bound_and_zero_rows():
    rng = np.random.default_rng(0)
    arr = (rng.standard_normal((50, 16)) * 10.0 **
           rng.integers(-3, 3, (50, 1))).astype(np.float32)
    arr[7] = 0.0  # an exactly-zero row (a cold/unused entity)
    q, scale = quantize_int8_rows(arr)
    assert q.dtype == np.int8 and scale.dtype == np.float32
    back = dequantize_int8_rows(q, scale)
    # Symmetric absmax: per-row error <= half a quantization step (the
    # 0.51 absorbs the f32 rounding of the scale itself).
    step = np.abs(arr).max(axis=-1, keepdims=True) / 127.0
    assert np.all(np.abs(back - arr) <= 0.51 * step)
    # Zero rows decode EXACTLY zero (scale 0, not a 0/0 NaN).
    assert scale[7] == 0.0
    np.testing.assert_array_equal(back[7], np.zeros(16, np.float32))


def test_int8_canonical_is_a_fixed_point():
    """Re-encoding a decode must be byte-identical — the digest-over-
    encoded-payload resume compare depends on it."""
    rng = np.random.default_rng(1)
    arr = rng.standard_normal((40, 12)).astype(np.float32)
    q, scale, converged = quantize_int8_canonical(arr)
    assert converged
    back = dequantize_int8_rows(q, scale)
    q2, scale2, converged2 = quantize_int8_canonical(back)
    assert converged2
    assert q2.tobytes() == q.tobytes()
    assert scale2.tobytes() == scale.tobytes()


def test_bf16_truncation_idempotent():
    rng = np.random.default_rng(2)
    arr = rng.standard_normal((33, 9)).astype(np.float32)
    once = codec_roundtrip(arr, "bf16")
    assert once.dtype == np.float32
    assert np.abs(once - arr).max() <= 2.0 ** -6 * np.abs(arr).max()
    np.testing.assert_array_equal(codec_roundtrip(once, "bf16"), once)
    assert encode_bf16(once).tobytes() == encode_bf16(arr).tobytes()


def test_check_dtype_rejects_unknown():
    assert check_dtype(None) == "f32"
    with pytest.raises(ValueError, match="fp8"):
        check_dtype("fp8")
    with pytest.raises(ValueError):
        GameScorer(_fixture()[0], table_dtype="f16")


# -- serving parity per dtype (request / dataset / cold / post-swap) ---------

@pytest.mark.parametrize("dtype", LOSSY)
def test_request_path_parity_per_dtype(served_tiers, dtype):
    model, data, tiers = served_tiers
    scorer, _ = tiers[dtype]
    want = model.score(data)  # f32 host oracle
    tol = parity_tol_for(dtype)
    pos = 0
    sizes = [1, 3, 17, 64]
    for req, size in zip(build_requests(data, model, sizes), sizes):
        rows = np.arange(pos, pos + size) % data.num_examples
        got = scorer.score_batch(req)
        assert np.abs(got - want[rows]).max() <= tol
        pos = (pos + size) % data.num_examples


@pytest.mark.parametrize("dtype", LOSSY)
def test_dataset_path_parity_per_dtype(served_tiers, dtype):
    model, data, tiers = served_tiers
    scorer, _ = tiers[dtype]
    got = scorer.score_dataset(data)
    assert np.abs(got - model.score(data)).max() <= parity_tol_for(dtype)


@pytest.mark.parametrize("dtype", LOSSY)
def test_cold_entities_fall_back_per_dtype(served_tiers, dtype):
    """Unknown keys score fixed-effect-only through the ZERO gather row —
    which every codec must decode to exactly zero (int8: scale-row 0)."""
    model, data, tiers = served_tiers
    scorer, session = tiers[dtype]
    before = _counter_total(session, "serving.cold_entities")
    x_fixed = data.shards["global"].x[:3]
    x_rand = data.shards["re0"].x[:3]
    req = ScoringRequest(
        features={"global": x_fixed, "re0": x_rand},
        entity_ids={"re0": np.array([10 ** 9, 10 ** 9 + 1, 10 ** 9 + 2])},
    )
    got = scorer.score_batch(req)
    fixed_only = x_fixed @ np.asarray(
        model.coordinates["fixed"].coefficients.means
    )
    # The cold fallback is EXACT per dtype (zero decodes to zero), so the
    # f32 tolerance applies to every tier.
    np.testing.assert_allclose(got, fixed_only, rtol=1e-5, atol=1e-5)
    assert _counter_total(session, "serving.cold_entities") == before + 3


@pytest.mark.parametrize("dtype", TABLE_DTYPES)
def test_recompile_free_post_warmup_per_dtype(served_tiers, dtype):
    model, data, tiers = served_tiers
    scorer, _ = tiers[dtype]
    warm = scorer.compilations
    rng = np.random.default_rng(4)
    sizes = rng.integers(1, 65, size=20).tolist()
    for req in build_requests(data, model, sizes):
        scorer.score_batch(req)
    assert scorer.compilations == warm


def test_table_bytes_reduction_bars(served_tiers):
    """The ISSUE 17 acceptance bars at equal entity count: bf16 >= 1.9x,
    int8 >= 3.5x smaller gather tables than f32."""
    _, _, tiers = served_tiers
    bytes_for = {}
    for dtype, (_, session) in tiers.items():
        bytes_for[dtype] = session.registry.gauge(
            "serving.table_bytes", dtype=dtype
        ).value
    assert bytes_for["f32"] / bytes_for["bf16"] >= 1.9
    assert bytes_for["f32"] / bytes_for["int8"] >= 3.5


# -- hot swap: dtype preserved, growth in place, mismatch refused ------------

def _perturbed(model, seed, extra_entities=0):
    """A refreshed model: same shapes (plus optionally grown vocabulary),
    different values — what a continual-training cycle publishes."""
    rng = np.random.default_rng(seed)
    re = model.coordinates["per_entity"]
    keys = np.asarray(re.keys)
    table = np.asarray(re.table) + 0.1 * rng.standard_normal(
        (len(keys), re.table.shape[1])
    ).astype(np.float32)
    if extra_entities:
        new_keys = np.arange(
            keys.max() + 1, keys.max() + 1 + extra_entities
        ).astype(keys.dtype)
        keys = np.concatenate([keys, new_keys])
        table = np.concatenate([
            table,
            rng.standard_normal(
                (extra_entities, table.shape[1])
            ).astype(np.float32),
        ])
    return GameModel(
        coordinates={
            "fixed": model.coordinates["fixed"],
            "per_entity": RandomEffectModel(
                table=table.astype(np.float32), keys=keys,
                entity_column=re.entity_column,
                shard_name=re.shard_name, task_type=re.task_type,
            ),
        },
        task_type=model.task_type,
    )


@pytest.mark.parametrize("dtype", LOSSY)
def test_swap_model_preserves_dtype_and_parity(dtype):
    model, data = _fixture(seed=9)
    session = TelemetrySession(f"test-swap-{dtype}")
    scorer = GameScorer(
        model, request_spec=request_spec_for_dataset(model, data),
        max_batch=64, telemetry=session, table_dtype=dtype,
    ).warmup()
    warm = scorer.compilations
    new_model = _perturbed(model, seed=10)
    scorer.swap_model(new_model)
    assert scorer.table_dtype == dtype
    assert scorer.compilations == warm  # swap never recompiles
    got = scorer.score_dataset(data)
    assert np.abs(got - new_model.score(data)).max() <= parity_tol_for(dtype)


@pytest.mark.parametrize("dtype", LOSSY)
def test_grow_in_place_preserves_dtype(dtype):
    """Vocabulary growth within pre-provisioned capacity hot-swaps in
    place: the new rows land in the headroom UNDER THE SAME CODEC (int8:
    their scale rows too), with zero compiles."""
    model, data = _fixture(seed=12)
    scorer = GameScorer(
        model, request_spec=request_spec_for_dataset(model, data),
        max_batch=64, table_dtype=dtype, table_capacity_factor=2,
    ).warmup()
    warm = scorer.compilations
    grown = _perturbed(model, seed=13, extra_entities=8)
    scorer.swap_model(grown)
    assert scorer.table_dtype == dtype
    assert scorer.compilations == warm
    # Score rows carrying the NEW entities' ids: served through the grown
    # rows, within the codec bound of the host oracle.
    re = grown.coordinates["per_entity"]
    new_keys = np.asarray(re.keys)[-8:]
    x_fixed = data.shards["global"].x[:8]
    x_rand = data.shards["re0"].x[:8]
    req = ScoringRequest(
        features={"global": x_fixed, "re0": x_rand},
        entity_ids={"re0": new_keys},
    )
    got = scorer.score_batch(req)
    fixed_w = np.asarray(grown.coordinates["fixed"].coefficients.means)
    table = np.asarray(re.table)
    want = x_fixed @ fixed_w + np.einsum(
        "rd,rd->r", x_rand, table[-8:]
    )
    assert np.abs(got - want).max() <= parity_tol_for(dtype)


def test_swap_model_dtype_mismatch_refuses():
    model, data = _fixture(seed=14)
    scorer = GameScorer(
        model, request_spec=request_spec_for_dataset(model, data),
        max_batch=64, table_dtype="bf16",
    ).warmup()
    with pytest.raises(ValueError, match="bf16"):
        scorer.swap_model(_perturbed(model, seed=15), table_dtype="f32")
    # The refused swap left the served tables untouched.
    got = scorer.score_dataset(data)
    assert np.abs(got - model.score(data)).max() <= parity_tol_for("bf16")


# -- tile-store codecs --------------------------------------------------------

@pytest.mark.parametrize("dtype", LOSSY)
def test_tile_store_lossy_roundtrip(tmp_path, dtype):
    rng = np.random.default_rng(5)
    tile = (rng.standard_normal((3, 41)) * 10.0 **
            rng.integers(-2, 3, (3, 1))).astype(np.float32)
    store = TileStore(str(tmp_path), tile_dtype=dtype)
    store.write(TILES, 0, {"tile": tile},
                codecs=store.lossy_codecs(("tile",)))
    arrays, _ = store.read(TILES, 0)
    got = arrays["tile"]
    assert got.dtype == np.float32
    np.testing.assert_array_equal(got, codec_roundtrip(tile, dtype))
    # Re-publishing the DECODE is a fixed point: byte-identical payload.
    store.write(TILES, 1, {"tile": got},
                codecs=store.lossy_codecs(("tile",)))
    again, _ = store.read(TILES, 1)
    np.testing.assert_array_equal(again["tile"], got)


@pytest.mark.parametrize("dtype", LOSSY)
def test_tile_store_nan_inf_falls_back_lossless(tmp_path, dtype):
    tile = np.array([[1.0, np.nan, 3.0], [np.inf, 5.0, -np.inf]],
                    np.float32)
    store = TileStore(str(tmp_path), tile_dtype=dtype)
    store.write(TILES, 0, {"tile": tile},
                codecs=store.lossy_codecs(("tile",)))
    arrays, _ = store.read(TILES, 0)
    # Non-finite payloads must come back BIT-exact (lossless fallback).
    np.testing.assert_array_equal(arrays["tile"], tile)


def test_corrupt_scale_row_refused_at_read(tmp_path):
    """A flipped bit in the int8 SCALE ROW region is caught by the
    digest over the ENCODED payload — before a decode could silently
    rescale a whole row of 41 values."""
    import json as _json
    import struct

    rng = np.random.default_rng(6)
    tile = rng.standard_normal((3, 41)).astype(np.float32)
    # compress=False keeps the payload at encoding "raw", so the flipped
    # offset lands in the scale bytes themselves (a corrupt COMPRESSED
    # stream would fail earlier, in zlib).
    store = TileStore(str(tmp_path), tile_dtype="int8", compress=False)
    store.write(TILES, 0, {"tile": tile},
                codecs=store.lossy_codecs(("tile",)))
    path = store.path(TILES, 0)
    blob = bytearray(open(path, "rb").read())
    (hlen,) = struct.unpack("<Q", bytes(blob[8:16]))
    header = _json.loads(bytes(blob[16:16 + hlen]))
    entry = next(e for e in header["arrays"] if e["name"] == "tile")
    assert entry["codec"] == "int8"
    # The int8 payload leads with the f32 scale rows: offset + 2 lands
    # inside the first scale value.
    pos = 16 + hlen + entry["offset"] + 2
    blob[pos] ^= 0xFF
    with open(path, "wb") as f:
        f.write(bytes(blob))
    with pytest.raises(CorruptTileError):
        store.read(TILES, 0)


@pytest.mark.parametrize("dtype", LOSSY)
def test_spilled_writeback_resume_per_codec(tmp_path, dtype):
    """Flush → re-attach under a lossy codec: the publish-time roundtrip
    makes memory == disk, so a fresh table adopts every tile (digests
    over the encoded payload compare exact) and serves identical values."""
    rng = np.random.default_rng(7)
    n = 101
    base = rng.standard_normal(n).astype(np.float32)
    plan = ChunkPlan(n, 17)
    names = ["a", "b"]
    store = TileStore(str(tmp_path), tile_dtype=dtype)
    spilled = SpilledResidualTable(
        base, names, plan, store, HostTileCache()
    )
    for name in names:
        spilled.update(name, rng.standard_normal(n).astype(np.float32))
    assert spilled.flush() == plan.num_chunks
    attached = SpilledResidualTable(
        base, names, plan, store, HostTileCache()
    )
    assert attached.attach_resume() == []
    assert attached.tile_digests() == spilled.tile_digests()
    for name in names:
        np.testing.assert_array_equal(
            attached.scores_for(name), spilled.scores_for(name)
        )
    np.testing.assert_array_equal(
        attached.composite_full(), spilled.composite_full()
    )


# -- spilled fit parity per codec --------------------------------------------

CHUNK = 37


def _problem(lam):
    return ProblemConfig(
        regularization=RegularizationContext("l2", lam),
        optimizer_config=OptimizerConfig(
            max_iterations=80, tolerance=1e-11, gradient_tolerance=1e-8,
        ),
    )


def _config():
    return GameOptimizationConfiguration(
        coordinates={
            "fixed": FixedEffectCoordinateConfig("global", _problem(1.0)),
            "re0": RandomEffectCoordinateConfig("re0", "re0", _problem(1.0)),
        },
        descent_iterations=2,
        name="lowp",
    )


@pytest.fixture(scope="module")
def fit_data():
    data, _ = make_game_dataset(100, 5, 6, 3, seed=0, n_random_coords=1)
    return split_game_dataset(data, 0.25, seed=1)


@pytest.fixture(scope="module")
def host_streamed_fit(fit_data):
    train, val = fit_data
    return GameEstimator(
        "linear_regression", train, validation_data=val,
        stream_chunks=CHUNK,
    ).fit([_config()])[0]


@pytest.mark.parametrize("dtype", LOSSY)
def test_spilled_fit_metric_parity_per_codec(
    tmp_path, fit_data, host_streamed_fit, dtype
):
    train, val = fit_data
    result = GameEstimator(
        "linear_regression", train, validation_data=val,
        stream_chunks=CHUNK, spill_dir=str(tmp_path), tile_dtype=dtype,
    ).fit([_config()])[0]
    tol = tile_metric_tol_for(dtype)
    for name, value in host_streamed_fit.metrics.items():
        assert abs(value - result.metrics[name]) <= tol, (
            f"{name}: {value} vs {result.metrics[name]} (bound {tol})"
        )


def test_tile_dtype_requires_spill_dir(fit_data):
    train, _ = fit_data
    with pytest.raises(ValueError, match="spill_dir"):
        GameEstimator(
            "linear_regression", train, stream_chunks=CHUNK,
            tile_dtype="bf16",
        )
    with pytest.raises(ValueError, match="tile dtype"):
        GameEstimator(
            "linear_regression", train, stream_chunks=CHUNK,
            tile_dtype="int4",
        )


# -- solver polish (ISSUE 17 satellite: the PR 8 stopping trick grafted) -----

def test_lbfgs_polish_tightens_past_line_search_floor():
    """The guarded full-step polish drives the final gradient well past
    where f32 function differences round to zero (~1e-4 basin)."""
    import jax
    import jax.numpy as jnp

    from photon_tpu.core.optimizers.lbfgs import lbfgs

    rng = np.random.default_rng(0)
    n, d = 200, 12
    X = jnp.asarray(rng.standard_normal((n, d)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 2, n), jnp.float32)

    def fun(w):
        z = X @ w
        f = jnp.mean(jnp.logaddexp(0.0, z) - y * z) + 0.01 * jnp.sum(w * w)
        g = X.T @ (jax.nn.sigmoid(z) - y) / n + 0.02 * w
        return f, g

    r = jax.jit(lambda w0: lbfgs(fun, w0, OptimizerConfig()))(jnp.zeros(d))
    assert bool(r.converged)
    assert float(r.grad_norm) < 1e-5
    assert np.all(np.isfinite(np.asarray(r.w)))


def test_owlqn_polish_keeps_exact_zeros():
    """Polish runs through the orthant machinery: coordinates the loop
    zeroed stay EXACTLY zero while the pseudo-gradient tightens."""
    import jax
    import jax.numpy as jnp

    from photon_tpu.core.optimizers.owlqn import owlqn

    rng = np.random.default_rng(1)
    n, d = 200, 12
    X = jnp.asarray(rng.standard_normal((n, d)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 2, n), jnp.float32)

    def fun(w):
        z = X @ w
        f = jnp.mean(jnp.logaddexp(0.0, z) - y * z)
        g = X.T @ (jax.nn.sigmoid(z) - y) / n
        return f, g

    r = jax.jit(
        lambda w0: owlqn(fun, w0, OptimizerConfig(), l1_weight=0.05)
    )(jnp.zeros(d))
    w = np.asarray(r.w)
    assert np.all(np.isfinite(w))
    assert (w == 0.0).sum() > 0  # L1 sparsity survived the polish
    assert float(r.grad_norm) < 1e-5


@pytest.mark.parametrize("tol", list(PARITY_TOL.items()))
def test_parity_tol_registry_consistent(tol):
    dtype, bound = tol
    assert parity_tol_for(dtype) == bound
    assert tile_metric_tol_for(dtype) > 0
