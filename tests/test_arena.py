"""Multi-model serving arena (photon_tpu/serving/arena, ISSUE 18): N
tenant models in ONE gather-table allocation behind ONE compiled bucket
ladder, model-id request routing, traffic splits, and per-tenant
admission isolation.

The contracts pinned here:

- the compiled-program count is independent of model count (model
  identity is a per-request offset vector, never a program key), and a
  mixed-tenant micro-batch scores in one dispatch with per-row parity
  against each tenant's host oracle;
- arena bytes stay within 1.15x the sum of the tenants' solo
  single-model tables (shared allocation, not duplication);
- onboard/retire/refresh under live traffic are slice publications:
  zero dropped requests, zero recompiles while reserve capacity lasts,
  a ``layout_version`` bump only when the arena actually grows;
- a dtype-mismatched slice publish is refused (the storage decode is
  baked into the shared ladder);
- requests route by ``ScoringRequest.model`` end to end: wire
  roundtrip (scalar and per-row), coalescing (all-same scalars stay
  scalar, mixes widen to per-row arrays), slicing;
- seeded traffic splits are deterministic hash-of-user assignments, and
  the split arm rides ``TimedRequest.arm`` / ``request.model``;
- per-tenant admission budgets isolate a storming tenant: the victim
  tenant's shed rate and tail stay at its solo baseline (ISSUE 18
  satellite);
- subprocess children host the same multi-model arena from per-tenant
  artifacts, swap one tenant's slice over the wire, and their span
  timestamps are de-skewed by the ping-measured clock offset (ISSUE 18
  satellite).
"""

from __future__ import annotations

import threading
import time

import numpy as np
import pytest

from photon_tpu.data.synthetic import make_game_dataset
from photon_tpu.game.model import (
    FixedEffectModel,
    GameModel,
    RandomEffectModel,
)
from photon_tpu.models.glm import Coefficients, model_for_task
from photon_tpu.serving import (
    AdmissionPolicy,
    RequestShedError,
    ScoringRequest,
    ServingFleet,
    TrafficSpec,
    build_requests,
    generate_traffic,
    host_score_request,
    request_spec_for_dataset,
    run_closed_loop_outcomes,
)
from photon_tpu.serving.arena import MultiModelScorer
from photon_tpu.serving.scorer import (
    GameScorer,
    concat_requests,
    slice_request,
)
from photon_tpu.serving.traffic import split_arm_for
from photon_tpu.serving.transport import pack_request, unpack_request
from photon_tpu.telemetry import TelemetrySession


def _fixture(seed=3, n_entities=40, fixed_dim=6, random_dim=4):
    data, _ = make_game_dataset(
        n_entities, 4, fixed_dim, random_dim, seed=seed
    )
    rng = np.random.default_rng(seed)
    keys = np.unique(data.id_columns["re0"])
    model = GameModel(
        coordinates={
            "fixed": FixedEffectModel(
                model_for_task("logistic_regression", Coefficients(
                    rng.standard_normal(fixed_dim).astype(np.float32)
                )),
                "global",
            ),
            "per_entity": RandomEffectModel(
                table=rng.standard_normal(
                    (len(keys), random_dim)
                ).astype(np.float32),
                keys=keys, entity_column="re0", shard_name="re0",
                task_type="logistic_regression",
            ),
        },
        task_type="logistic_regression",
    )
    return model, data


def _retabled(model: GameModel, seed: int) -> GameModel:
    """Same coordinate structure/vocabulary, freshly seeded tables — a
    distinct tenant the arena hosts next to ``model``."""
    rng = np.random.default_rng(seed)
    fixed = model.coordinates["fixed"]
    per_entity = model.coordinates["per_entity"]
    dim = np.asarray(fixed.coefficients.means).shape[0]
    return GameModel(
        coordinates={
            "fixed": FixedEffectModel(
                model_for_task(model.task_type, Coefficients(
                    rng.standard_normal(dim).astype(np.float32)
                )),
                fixed.shard_name,
            ),
            "per_entity": RandomEffectModel(
                table=rng.standard_normal(
                    (per_entity.num_entities, per_entity.dim)
                ).astype(np.float32),
                keys=per_entity.keys,
                entity_column=per_entity.entity_column,
                shard_name=per_entity.shard_name,
                task_type=model.task_type,
            ),
        },
        task_type=model.task_type,
    )


def _tenants(model: GameModel, n: int) -> dict:
    return {
        f"m{i}": (model if i == 0 else _retabled(model, seed=100 + i))
        for i in range(n)
    }


def _counter_total(session, name, **labels):
    total = 0
    for m in session.registry.snapshot()["counters"]:
        if m["name"] != name:
            continue
        if labels and any(
            str(m["labels"].get(k)) != str(v) for k, v in labels.items()
        ):
            continue
        total += m["value"]
    return total


def _compile_listener():
    import jax.monitoring
    from jax._src import monitoring as monitoring_src

    events = []

    def listener(event, **kwargs):
        if "compile" in event:
            events.append(event)

    def attach():
        jax.monitoring.register_event_listener(listener)

    def detach():
        monitoring_src._unregister_event_listener_by_callback(listener)

    return events, attach, detach


# -- arena scorer: shared ladder + parity ------------------------------------

def test_eight_models_one_ladder_mixed_parity():
    """ISSUE 18 acceptance: 8 tenants share one compiled ladder (program
    count == a solo scorer's), every tenant scores at its own host
    oracle, a coalesced mixed-tenant batch resolves per row, and the
    whole mixed serve triggers ZERO post-warmup compilations."""
    model, data = _fixture(seed=3)
    models = _tenants(model, 8)
    spec = request_spec_for_dataset(model, data)
    solo = GameScorer(model, request_spec=spec, max_batch=16).warmup()
    scorer = MultiModelScorer(
        models, request_spec=spec, max_batch=16
    ).warmup()
    assert scorer.compilations == solo.compilations
    events, attach, detach = _compile_listener()
    import dataclasses as dc

    reqs = build_requests(data, model, [1, 5, 16, 8])
    attach()
    try:
        for mid, m in models.items():
            for req in reqs:
                got = scorer.score_batch(dc.replace(req, model=mid))
                np.testing.assert_allclose(
                    got, host_score_request(m, req), rtol=1e-4, atol=1e-4
                )
        # A coalesced mixed-tenant batch: per-row ids, one dispatch.
        mixed_ids = np.asarray(
            [f"m{i % 8}" for i in range(reqs[2].num_rows)], dtype=object
        )
        got = scorer.score_batch(dc.replace(reqs[2], model=mixed_ids))
        for mid in set(mixed_ids):
            rows = mixed_ids == mid
            np.testing.assert_allclose(
                got[rows],
                host_score_request(models[mid], reqs[2])[rows],
                rtol=1e-4, atol=1e-4,
            )
        # No model id → the default tenant.
        np.testing.assert_allclose(
            scorer.score_batch(reqs[0]),
            host_score_request(models["m0"], reqs[0]),
            rtol=1e-4, atol=1e-4,
        )
    finally:
        detach()
    assert events == []


def test_arena_bytes_bounded_by_solo_sum():
    model, data = _fixture(seed=5)
    models = _tenants(model, 8)
    spec = request_spec_for_dataset(model, data)
    import jax

    solo = GameScorer(model, request_spec=spec, max_batch=16).warmup()
    solo_bytes = 0
    for m in models.values():
        solo.swap_model(m)
        solo_bytes += sum(
            leaf.nbytes for leaf in jax.tree_util.tree_leaves(solo._tables)
        )
    scorer = MultiModelScorer(models, request_spec=spec, max_batch=16)
    assert scorer.arena.arena_bytes() <= 1.15 * solo_bytes


def test_unhosted_model_refused():
    model, data = _fixture(seed=7)
    scorer = MultiModelScorer(
        _tenants(model, 2),
        request_spec=request_spec_for_dataset(model, data), max_batch=16,
    ).warmup()
    (req,) = build_requests(data, model, [4])
    import dataclasses as dc

    with pytest.raises(KeyError, match="ghost"):
        scorer.score_batch(dc.replace(req, model="ghost"))
    # Per-row arrays routing to an unhosted id refuse too.
    ids = np.asarray(["m0", "ghost", "m1", "m0"], dtype=object)
    with pytest.raises(KeyError, match="ghost"):
        scorer.score_batch(dc.replace(req, model=ids))


# -- model lifecycle under live state ----------------------------------------

def test_onboard_retire_refresh_without_recompiles():
    """Reserve-rows headroom makes onboard/retire/refresh pure slice
    publications: zero compile events, ``layout_version`` unchanged; the
    retired tenant's id is refused afterwards."""
    model, data = _fixture(seed=9)
    models = _tenants(model, 3)
    spec = request_spec_for_dataset(model, data)
    scorer = MultiModelScorer(
        models, request_spec=spec, max_batch=16, reserve_rows=256,
    ).warmup()
    import dataclasses as dc

    (req,) = build_requests(data, model, [6])
    # Warm the slice-scatter program shapes once (a publish compiles its
    # scatter on first use; after that every same-shaped publish reuses
    # it — the contract under test).
    scorer.swap_model(models["m1"], model_id="m1")
    version0 = scorer.arena.layout_version
    events, attach, detach = _compile_listener()
    newcomer = _retabled(model, seed=201)
    refreshed = _retabled(model, seed=202)
    attach()
    try:
        scorer.add_model("m9", newcomer)
        np.testing.assert_allclose(
            scorer.score_batch(dc.replace(req, model="m9")),
            host_score_request(newcomer, req), rtol=1e-4, atol=1e-4,
        )
        scorer.swap_model(refreshed, model_id="m2")
        np.testing.assert_allclose(
            scorer.score_batch(dc.replace(req, model="m2")),
            host_score_request(refreshed, req), rtol=1e-4, atol=1e-4,
        )
        scorer.retire_model("m9")
        with pytest.raises(KeyError, match="m9"):
            scorer.score_batch(dc.replace(req, model="m9"))
        # Untouched tenants still serve their own tables.
        np.testing.assert_allclose(
            scorer.score_batch(dc.replace(req, model="m0")),
            host_score_request(models["m0"], req), rtol=1e-4, atol=1e-4,
        )
    finally:
        detach()
    assert events == []
    assert scorer.arena.layout_version == version0


def test_arena_growth_bumps_layout_and_keeps_parity():
    """Onboarding past free capacity grows the arena (amortized
    doubling): ``layout_version`` bumps, every hosted tenant still
    scores at its oracle afterwards."""
    model, data = _fixture(seed=11)
    models = _tenants(model, 2)
    spec = request_spec_for_dataset(model, data)
    scorer = MultiModelScorer(
        models, request_spec=spec, max_batch=16, reserve_rows=0,
    ).warmup()
    version0 = scorer.arena.layout_version
    added = {}
    for i in range(4):
        added[f"g{i}"] = _retabled(model, seed=300 + i)
        scorer.add_model(f"g{i}", added[f"g{i}"])
    assert scorer.arena.layout_version > version0
    import dataclasses as dc

    (req,) = build_requests(data, model, [8])
    for mid, m in {**models, **added}.items():
        np.testing.assert_allclose(
            scorer.score_batch(dc.replace(req, model=mid)),
            host_score_request(m, req), rtol=1e-4, atol=1e-4,
        )


def test_retire_last_model_refused():
    model, data = _fixture(seed=13)
    scorer = MultiModelScorer(
        {"only": model},
        request_spec=request_spec_for_dataset(model, data), max_batch=16,
    )
    with pytest.raises(ValueError, match="last hosted"):
        scorer.retire_model("only")


def test_dtype_mismatched_slice_publish_refused():
    """The storage decode is baked into the shared ladder: one tenant
    cannot publish a slice at a different table dtype."""
    model, data = _fixture(seed=15)
    scorer = MultiModelScorer(
        _tenants(model, 2),
        request_spec=request_spec_for_dataset(model, data),
        max_batch=16, table_dtype="bf16",
    )
    with pytest.raises(ValueError, match="bf16"):
        scorer.swap_model(
            _retabled(model, seed=401), model_id="m1", table_dtype="f32"
        )
    # Matching dtype (or unspecified) publishes fine.
    scorer.swap_model(
        _retabled(model, seed=402), model_id="m1", table_dtype="bf16"
    )


# -- request routing: wire, coalescing, slicing ------------------------------

def test_model_routing_survives_wire_and_coalescing():
    model, data = _fixture(seed=17)
    reqs = build_requests(data, model, [3, 2, 4])
    import dataclasses as dc

    a = dc.replace(reqs[0], model="tenant-a")
    b = dc.replace(reqs[1], model="tenant-b")
    c = reqs[2]  # unrouted

    # Wire: a scalar id rides the header; a per-row array rides as data.
    got, _ = unpack_request(pack_request(a))
    assert got.model == "tenant-a"
    per_row = dc.replace(
        reqs[2], model=np.asarray(["x", "y", "x", "y"], dtype=object)
    )
    got, _ = unpack_request(pack_request(per_row))
    np.testing.assert_array_equal(
        np.asarray(got.model, dtype=object),
        np.asarray(per_row.model, dtype=object),
    )
    got, _ = unpack_request(pack_request(c))
    assert got.model is None

    # Coalescing: all-same scalars stay scalar; a mix (including
    # unrouted rows) widens to a per-row object array.
    same = concat_requests([a, dc.replace(reqs[1], model="tenant-a")])
    assert same.model == "tenant-a"
    mixed = concat_requests([a, b, c])
    assert not isinstance(mixed.model, str)
    np.testing.assert_array_equal(
        np.asarray(mixed.model, dtype=object),
        np.asarray(
            ["tenant-a"] * 3 + ["tenant-b"] * 2 + [None] * 4, dtype=object
        ),
    )
    # Slicing a coalesced batch keeps each row's id.
    window = slice_request(mixed, 2, 6)
    np.testing.assert_array_equal(
        np.asarray(window.model, dtype=object),
        np.asarray(["tenant-a", "tenant-b", "tenant-b", None],
                   dtype=object),
    )
    assert slice_request(a, 0, 2).model == "tenant-a"


# -- traffic splits ----------------------------------------------------------

def test_split_arms_deterministic_and_weighted():
    splits = {"control": 0.5, "treat": 0.5}
    arms = [split_arm_for(7, user, splits) for user in range(2000)]
    # Deterministic: the same (seed, user) always lands the same arm.
    assert arms == [split_arm_for(7, user, splits) for user in range(2000)]
    # A different seed reshuffles the assignment.
    assert arms != [split_arm_for(8, user, splits) for user in range(2000)]
    frac = arms.count("treat") / len(arms)
    assert 0.44 < frac < 0.56
    # Weights steer the allocation.
    skew = [
        split_arm_for(7, user, {"a": 0.9, "b": 0.1})
        for user in range(2000)
    ]
    assert skew.count("a") > 1600


def test_generated_traffic_stamps_split_arms():
    model, data = _fixture(seed=19)
    spec = TrafficSpec(
        requests=60, mean_rows=4, max_rows=16, popularity="powerlaw",
        seed=5, splits={"m0": 0.5, "m1": 0.5},
    )
    t1 = generate_traffic(data, model, spec)
    t2 = generate_traffic(data, model, spec)
    arms1 = [item.arm for item in t1.items]
    assert arms1 == [item.arm for item in t2.items]
    assert set(arms1) == {"m0", "m1"}
    for item in t1.items:
        assert item.request.model == item.arm
    # Splits leave the request stream itself untouched (PR 9 seeded
    # byte-exactness): same spec without splits, same rows per request.
    plain = generate_traffic(
        data, model,
        TrafficSpec(requests=60, mean_rows=4, max_rows=16,
                    popularity="powerlaw", seed=5),
    )
    assert [i.request.num_rows for i in t1.items] == [
        i.request.num_rows for i in plain.items
    ]


# -- fleet: mixed traffic, lifecycle under load, isolation -------------------

def _multi_fleet(models, data, session, replicas=1, **kwargs):
    first = next(iter(models.values()))
    return ServingFleet(
        None, models=models, replicas=replicas,
        request_spec=request_spec_for_dataset(first, data),
        max_batch=16, max_delay_s=0.001, telemetry=session, **kwargs,
    ).warmup()


def test_fleet_serves_mixed_split_traffic_with_onboard_mid_stream():
    """ISSUE 18 acceptance: a fleet hosting N tenants serves mixed
    split-arm traffic; onboarding a new tenant mid-traffic drops ZERO
    requests, and the newcomer serves immediately after."""
    model, data = _fixture(seed=21)
    models = _tenants(model, 4)
    session = TelemetrySession("test-arena-fleet")
    fleet = _multi_fleet(models, data, session, replicas=2,
                         reserve_rows=256)
    try:
        traffic = generate_traffic(data, model, TrafficSpec(
            requests=80, mean_rows=4, max_rows=16, popularity="powerlaw",
            seed=2, splits={mid: 0.25 for mid in models},
        ))
        newcomer = _retabled(model, seed=500)
        onboarded = threading.Event()

        def onboard_mid_stream():
            time.sleep(0.01)
            fleet.add_model("late", newcomer)
            onboarded.set()

        t = threading.Thread(target=onboard_mid_stream)
        t.start()
        outcomes, _ = run_closed_loop_outcomes(
            lambda tid: (lambda item: fleet.score(item.request)),
            traffic.items, clients=4,
        )
        t.join(timeout=30)
        assert onboarded.is_set()
        assert all(o.status == "ok" for o in outcomes)
        for out in outcomes:
            np.testing.assert_allclose(
                out.scores,
                host_score_request(models[out.item.arm],
                                   out.item.request),
                rtol=1e-4, atol=1e-4,
            )
        (req,) = build_requests(data, model, [5])
        np.testing.assert_allclose(
            fleet.score(req, model="late"),
            host_score_request(newcomer, req), rtol=1e-4, atol=1e-4,
        )
        fleet.retire_model("late")
        assert "late" not in fleet.models
    finally:
        fleet.close()


def test_per_tenant_rollout_swaps_one_slice():
    """fleet.rollout(model_id=...) canaries ONE tenant's slice: the
    target serves the new tables afterwards, other tenants are
    untouched, and nothing recompiles."""
    model, data = _fixture(seed=25)
    models = _tenants(model, 3)
    session = TelemetrySession("test-arena-rollout")
    fleet = _multi_fleet(models, data, session, replicas=2,
                         reserve_rows=256)
    try:
        reqs = build_requests(data, model, [4, 4])
        # Warm the publish path's scatter shapes before listening.
        fleet.rollout(_retabled(model, seed=601), model_id="m1",
                      probe_requests=reqs)
        events, attach, detach = _compile_listener()
        new_m1 = _retabled(model, seed=602)
        attach()
        try:
            fleet.rollout(new_m1, model_id="m1", probe_requests=reqs)
        finally:
            detach()
        assert events == []
        (req,) = build_requests(data, model, [6])
        np.testing.assert_allclose(
            fleet.score(req, model="m1"),
            host_score_request(new_m1, req), rtol=1e-4, atol=1e-4,
        )
        np.testing.assert_allclose(
            fleet.score(req, model="m0"),
            host_score_request(models["m0"], req), rtol=1e-4, atol=1e-4,
        )
        assert fleet.models["m1"] is new_m1
    finally:
        fleet.close()


def test_tenant_budget_isolates_storm():
    """ISSUE 18 satellite: tenant A's storm burns A's OWN admission
    budget (shed ``tenant_budget``); tenant B replaying steady traffic
    keeps a ZERO shed rate — its solo baseline — and a bounded tail."""
    model, data = _fixture(seed=27)
    models = {"a": model, "b": _retabled(model, seed=701)}
    session = TelemetrySession("test-tenant-budget")
    fleet = _multi_fleet(
        models, data, session, replicas=1,
        admission=AdmissionPolicy(tenant_queue_rows=32),
    )
    try:
        b_requests = build_requests(data, model, [4] * 30)
        want_b = [host_score_request(models["b"], r) for r in b_requests]

        def replay_b():
            lat = []
            for req, want in zip(b_requests, want_b):
                t0 = time.monotonic()
                got = fleet.score(req, model="b")
                lat.append(time.monotonic() - t0)
                np.testing.assert_allclose(got, want, rtol=1e-4,
                                           atol=1e-4)
            return float(np.percentile(lat, 99))

        p99_solo = replay_b()

        a_requests = build_requests(data, model, [8] * 300)
        a_state = {"shed": 0, "futs": []}

        def storm_a():
            for req in a_requests:
                try:
                    a_state["futs"].append(fleet.submit(req, model="a"))
                except RequestShedError as e:
                    assert e.reason == "tenant_budget"
                    a_state["shed"] += 1

        storm = threading.Thread(target=storm_a)
        storm.start()
        p99_storm = replay_b()  # B's shed rate stays 0: every score ok
        storm.join(timeout=60)
        for fut in a_state["futs"]:
            fut.result(timeout=60)
        assert a_state["shed"] > 0
        # The storm burned the TENANT gate, not the global queue.
        assert _counter_total(
            session, "serving.shed", reason="tenant_budget"
        ) == a_state["shed"]
        assert _counter_total(
            session, "serving.shed", reason="queue_full"
        ) == 0
        # B's tail under the storm stays within its solo baseline's
        # envelope (the budget caps how many of A's rows can queue
        # ahead of B; generous floor absorbs 1-core scheduler noise).
        assert p99_storm <= max(8 * p99_solo, 1.0)
    finally:
        fleet.close()


# -- subprocess children: per-tenant artifacts + clock de-skew ---------------

def test_subprocess_multimodel_swap_and_clock_offset():
    """Subprocess children boot the SAME arena from per-tenant
    artifacts: per-tenant parity over the wire, a one-tenant slice swap
    via the control frame, and the child's ping-measured clock offset
    lands on the replica (span de-skew input, ISSUE 18 satellite)."""
    model, data = _fixture(seed=31)
    models = {"a": model, "b": _retabled(model, seed=801)}
    session = TelemetrySession("test-arena-subprocess")
    fleet = _multi_fleet(models, data, session, replicas=1,
                         backend="subprocess", reserve_rows=256)
    try:
        reqs = build_requests(data, model, [3, 8])
        for mid, m in models.items():
            for req in reqs:
                np.testing.assert_allclose(
                    fleet.score(req, model=mid),
                    host_score_request(m, req), rtol=1e-4, atol=1e-4,
                )
        r0 = fleet.replicas[0]
        pong = r0.ping(30.0)
        assert pong["kind"] == "pong"
        # Loopback, same host clock: the EWMA offset is measured and
        # small (it exists to de-skew cross-machine span timestamps).
        assert abs(r0.scorer.clock_offset_s) < 0.5
        new_b = _retabled(model, seed=802)
        fleet.rollout(new_b, model_id="b", probe_requests=reqs)
        np.testing.assert_allclose(
            fleet.score(reqs[0], model="b"),
            host_score_request(new_b, reqs[0]), rtol=1e-4, atol=1e-4,
        )
        np.testing.assert_allclose(
            fleet.score(reqs[0], model="a"),
            host_score_request(model, reqs[0]), rtol=1e-4, atol=1e-4,
        )
    finally:
        fleet.close()


def test_shift_span_times_de_skews_child_spans():
    from photon_tpu.telemetry.distributed import shift_span_times

    spans = [
        {"name": "score", "start": 100.5, "duration_s": 0.25,
         "events": [{"t": 100.6, "msg": "batch"}]},
        {"name": "noise", "events": None},
    ]
    out = shift_span_times(spans, 2.0)
    assert out[0]["start"] == pytest.approx(98.5)
    assert out[0]["events"][0]["t"] == pytest.approx(98.6)
    assert out[0]["duration_s"] == 0.25  # durations are monotonic-local
    # Zero offset is the identity (no copy, no mutation needed).
    again = [{"start": 5.0, "events": [{"t": 5.5}]}]
    assert shift_span_times(again, 0.0)[0]["start"] == 5.0
