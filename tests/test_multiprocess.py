"""Multi-process runtime: 2 local CPU processes must compute the same
distributed objective as one process (VERDICT r2 item 4; SURVEY.md §2.6).

Each subprocess joins via ``jax.distributed.initialize`` (the drivers'
``--coordinator/--process-id/--num-processes`` path), contributes its local
rows through ``make_global_batch``, and evaluates the sharded
value+gradient over the 2-device global mesh; both the psum-ed value and
gradient must match a single-process evaluation over the full batch.
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# One worker covers BOTH the sharded-objective check and the row-split
# entity-solve check: jax import + distributed init dominate worker wall
# time on this box, so the two checks share one process pair (suite-time
# budget, VERDICT r3 item 4).
WORKER = r"""
import json, os, sys
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
sys.path.insert(0, sys.argv[1])
coordinator, pid, out_path = sys.argv[2], int(sys.argv[3]), sys.argv[4]
import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(
    coordinator_address=coordinator, num_processes=2, process_id=pid
)
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from photon_tpu.core.objective import GlmObjective, RegularizationContext
from photon_tpu.data.batch import SparseBatch, attach_feature_major
from photon_tpu.data.streaming import make_global_batch
from photon_tpu.parallel.distributed import DistributedGlmObjective

# Part 1: sharded objective. Deterministic dataset; each process
# contributes its half as local rows.
n, k, d = 256, 6, 48
rng = np.random.default_rng(0)
ids = rng.integers(0, d, size=(n, k), dtype=np.int32)
vals = rng.standard_normal((n, k)).astype(np.float32)
label = (rng.random(n) < 0.5).astype(np.float32)
weight = rng.uniform(0.5, 2.0, n).astype(np.float32)
lo, hi = pid * (n // 2), (pid + 1) * (n // 2)
local = SparseBatch(
    jnp.asarray(ids[lo:hi]), jnp.asarray(vals[lo:hi]),
    jnp.asarray(label[lo:hi]), jnp.zeros(n // 2, jnp.float32),
    jnp.asarray(weight[lo:hi]),
)
local = attach_feature_major(local)

assert jax.process_count() == 2 and len(jax.devices()) == 2
mesh = Mesh(np.asarray(jax.devices()), ("data",))
batch = make_global_batch(local, mesh)
assert batch.fm is not None

obj = GlmObjective.create("logistic", RegularizationContext("l2", 0.7))
dist = DistributedGlmObjective(obj, mesh)
w = jnp.asarray(np.random.default_rng(1).standard_normal(d), jnp.float32) * 0.1
v, g = dist.value_and_grad(w, batch)
hv = dist.hessian_vector(
    w, jnp.asarray(np.random.default_rng(2).standard_normal(d), jnp.float32),
    batch,
)

# Part 1b (round 5): multi-process SHARDED FAST KERNELS — each process
# builds the xchg aux for its local block with globally-agreed geometry
# (the allgather inside make_global_batch), and the sharded objective
# must produce the same numbers the fm path above did.
_prev_env = {
    k: os.environ.get(k)
    for k in ("PHOTON_SPARSE_GRAD", "PHOTON_XCHG_REDUCE",
              "PHOTON_ROUTE_CACHE")
}
os.environ["PHOTON_SPARSE_GRAD"] = "xchg"
os.environ["PHOTON_XCHG_REDUCE"] = "cumsum"
os.environ["PHOTON_ROUTE_CACHE"] = "0"
local_x = SparseBatch(
    jnp.asarray(ids[lo:hi]), jnp.asarray(vals[lo:hi]),
    jnp.asarray(label[lo:hi]), jnp.zeros(n // 2, jnp.float32),
    jnp.asarray(weight[lo:hi]),
)
batch_x = make_global_batch(local_x, mesh, aligned_dim=d)
assert batch_x.xchg is not None, "multi-process xchg aux missing"
v_x, g_x = dist.value_and_grad(w, batch_x)
# Restore the pre-part-1b environment so part 2 exercises the same
# (auto, default-reduce, cached-routes) dispatch it did before round 5.
for _k, _v in _prev_env.items():
    if _v is None:
        os.environ.pop(_k, None)
    else:
        os.environ[_k] = _v

# Part 2: row-split entity solves. THIS process holds rows
# [pid*R/2, (pid+1)*R/2) of EVERY entity — the row-split multi-host
# placement (no shuffle).
from photon_tpu.core.optimizers import OptimizerConfig
from photon_tpu.core.problem import ProblemConfig
from photon_tpu.parallel.distributed import solve_entities_row_split
from photon_tpu.parallel.mesh import to_host

E, R, rk, rd = 5, 16, 3, 10
rng = np.random.default_rng(0)
rids = rng.integers(1, rd, (E, R, rk)).astype(np.int32)
rvals = rng.standard_normal((E, R, rk)).astype(np.float32)
rlabel = (rng.random((E, R)) < 0.5).astype(np.float32)
rweight = rng.uniform(0.5, 2.0, (E, R)).astype(np.float32)
rlo, rhi = pid * R // 2, (pid + 1) * R // 2

def row_sharded(a):
    return jax.make_array_from_process_local_data(
        NamedSharding(mesh, P(None, "data", *([None] * (a.ndim - 2)))),
        a[:, rlo:rhi],
    )
rbatch = SparseBatch(
    row_sharded(rids), row_sharded(rvals), row_sharded(rlabel),
    row_sharded(np.zeros((E, R), np.float32)), row_sharded(rweight),
)
reg = RegularizationContext("l2", 0.8)
cfg = ProblemConfig(optimizer="lbfgs", regularization=reg,
                    optimizer_config=OptimizerConfig(max_iterations=12))
robj = GlmObjective.create("logistic", reg)
coeffs, res = solve_entities_row_split(
    robj, cfg, rbatch, jnp.zeros((E, rd), jnp.float32), mesh
)
with open(out_path, "w") as f:
    json.dump({
        "value": float(v),
        "grad": np.asarray(g).tolist(),
        "hv": np.asarray(hv).tolist(),
        "xchg_value": float(v_x),
        "xchg_grad": np.asarray(g_x).tolist(),
        "rs_means": to_host(coeffs.means).tolist(),
        "rs_value": to_host(res.value).tolist(),
    }, f)
"""


def _worker_env() -> dict:
    """Worker subprocess environment: strip the parent's XLA_/JAX_ device
    forcing (each worker sets its own) but keep the shared compilation
    cache so workers load, not recompile."""
    return {
        k: v for k, v in os.environ.items()
        if not k.startswith(("XLA_", "JAX_"))
        or k.startswith("JAX_PERSISTENT_CACHE")
        or k == "JAX_COMPILATION_CACHE_DIR"
    }


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# Error signatures of a platform that cannot run 2-process JAX at all (e.g.
# a jaxlib whose CPU client lacks cross-process collectives): the suite must
# SKIP these tests with a reason, not report code failures.  The canonical
# tuple lives in bench.py (its worker runner re-raises on the same
# signatures) so the skip logic and the bench stay in lockstep.
from bench import MP_UNSUPPORTED_MARKERS  # noqa: E402

# A coordinator port lost to the free-port race (another process bound it
# between _free_port() and the workers' bind): retry with a fresh port.
_PORT_COLLISION_MARKERS = ("Address already in use", "address in use")


def skip_if_mp_unsupported(err: str) -> None:
    """Skip (with the signature as reason) when worker output shows this
    platform cannot spawn multi-process JAX."""
    for marker in MP_UNSUPPORTED_MARKERS:
        if marker in err:
            pytest.skip(
                f"platform cannot run multi-process JAX: {marker!r}"
            )


def run_worker_pair(cmds_for, timeout=300, what="multi-process worker"):
    """Launch the 2-process worker pair ``cmds_for(coordinator)``; on a
    coordinator-port collision retry once with a freshly allocated port,
    and on the no-multi-process-JAX signatures skip instead of failing."""
    for attempt in (0, 1):
        coordinator = f"127.0.0.1:{_free_port()}"
        env = _worker_env()
        procs = [
            subprocess.Popen(
                cmd, env=env, stdout=subprocess.PIPE,
                stderr=subprocess.PIPE, text=True,
            )
            for cmd in cmds_for(coordinator)
        ]
        errs = []
        try:
            for p in procs:
                _, err = p.communicate(timeout=timeout)
                errs.append(err)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
                q.wait()
            pytest.fail(f"{what} timed out (distributed hang)")
        if all(p.returncode == 0 for p in procs):
            return
        joined = "\n".join(errs)
        skip_if_mp_unsupported(joined)
        if attempt == 0 and any(m in joined for m in _PORT_COLLISION_MARKERS):
            continue
        for p, err in zip(procs, errs):
            assert p.returncode == 0, f"{what} failed:\n{err[-2000:]}"


@pytest.fixture(scope="module")
def merged_worker_results(tmp_path_factory):
    """Run the merged 2-process worker pair once for the module; both the
    objective test and the row-split test assert against its outputs."""
    tmp_path = tmp_path_factory.mktemp("mp_worker")
    worker = tmp_path / "worker.py"
    worker.write_text(WORKER)
    outs = [str(tmp_path / f"out{i}.json") for i in range(2)]
    run_worker_pair(lambda coordinator: [
        [sys.executable, str(worker), REPO, coordinator, str(i), outs[i]]
        for i in range(2)
    ])
    return [json.load(open(o)) for o in outs]


def test_two_process_objective_matches_single(merged_worker_results):
    results = merged_worker_results
    # Both processes see the identical replicated (value, grad).
    assert results[0]["value"] == pytest.approx(results[1]["value"], rel=1e-6)
    np.testing.assert_allclose(results[0]["grad"], results[1]["grad"], rtol=1e-5)

    # Single-process reference over the full batch.
    import jax
    import jax.numpy as jnp

    from photon_tpu.core.objective import GlmObjective, RegularizationContext
    from photon_tpu.data.batch import SparseBatch

    n, k, d = 256, 6, 48
    rng = np.random.default_rng(0)
    ids = rng.integers(0, d, size=(n, k), dtype=np.int32)
    vals = rng.standard_normal((n, k)).astype(np.float32)
    label = (rng.random(n) < 0.5).astype(np.float32)
    weight = rng.uniform(0.5, 2.0, n).astype(np.float32)
    batch = SparseBatch(
        jnp.asarray(ids), jnp.asarray(vals), jnp.asarray(label),
        jnp.zeros(n, jnp.float32), jnp.asarray(weight),
    )
    obj = GlmObjective.create("logistic", RegularizationContext("l2", 0.7))
    w = jnp.asarray(np.random.default_rng(1).standard_normal(d), jnp.float32) * 0.1
    v_ref, g_ref = jax.value_and_grad(obj.value)(w, batch)
    hv_ref = jax.jvp(
        lambda u: jax.grad(obj.value)(u, batch),
        (w,),
        (jnp.asarray(np.random.default_rng(2).standard_normal(d), jnp.float32),),
    )[1]
    assert results[0]["value"] == pytest.approx(float(v_ref), rel=1e-5)
    np.testing.assert_allclose(results[0]["grad"], np.asarray(g_ref),
                               rtol=2e-4, atol=1e-5)
    np.testing.assert_allclose(results[0]["hv"], np.asarray(hv_ref),
                               rtol=2e-4, atol=1e-5)
    # Round 5: the multi-process SHARDED XCHG path (per-process aux with
    # globally-agreed geometry) must match the same reference.
    assert results[0]["xchg_value"] == pytest.approx(float(v_ref), rel=1e-5)
    np.testing.assert_allclose(results[0]["xchg_grad"], np.asarray(g_ref),
                               rtol=2e-4, atol=1e-4)
    assert results[0]["xchg_value"] == pytest.approx(
        results[1]["xchg_value"], rel=1e-6
    )


STREAM_WORKER = r"""
import json, os, sys
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
# Simulate an operator who left kernel selection on auto: the driver's
# distributed init must pin it (to fm) identically on every rank.
os.environ["PHOTON_SPARSE_GRAD"] = "auto"
sys.path.insert(0, sys.argv[1])
coordinator, pid, input_dir, out_dir = (
    sys.argv[2], int(sys.argv[3]), sys.argv[4], sys.argv[5]
)
from photon_tpu.drivers import train

train.run(train.build_parser().parse_args([
    "--backend", "cpu",
    "--coordinator", coordinator, "--process-id", str(pid),
    "--num-processes", "2",
    "--input", input_dir, "--task", "logistic_regression",
    "--stream", "--reg-weights", "1.0", "--max-iterations", "6",
    "--output-dir", out_dir,
]))
# Every rank (not just the writing rank 0) records the kernel it resolved:
# maybe_init_distributed must have pinned auto -> fm so shards never mix
# reduction orders (VERDICT r3 weak 2).
os.makedirs(out_dir, exist_ok=True)
with open(os.path.join(out_dir, "kernel.json"), "w") as f:
    json.dump({"kernel": os.environ.get("PHOTON_SPARSE_GRAD", "auto")}, f)
"""


def test_two_process_streaming_driver_matches_single(tmp_path):
    """The --stream driver under --coordinator: per-shard streamed gradients
    all-reduce across processes, so the fitted model must match a
    single-process run over all files (the treeAggregate-across-hosts
    analog)."""
    rng = np.random.default_rng(3)
    n_per, k, d = 60, 5, 30
    input_dir = tmp_path / "data"
    input_dir.mkdir()
    w_true = rng.standard_normal(d)
    for fi in range(4):
        with open(input_dir / f"part-{fi}.libsvm", "w") as f:
            for _ in range(n_per):
                fid = np.sort(
                    rng.choice(np.arange(1, d + 1), size=k, replace=False)
                )
                xv = rng.standard_normal(k)
                m = float(w_true[fid - 1] @ xv)
                y = 1 if rng.random() < 1 / (1 + np.exp(-m)) else -1
                f.write(f"{y} " + " ".join(
                    f"{j}:{v:.5f}" for j, v in zip(fid, xv)) + "\n")

    from photon_tpu.drivers import train

    single_out = str(tmp_path / "single")
    train.run(train.build_parser().parse_args([
        "--backend", "cpu", "--input", str(input_dir),
        "--task", "logistic_regression", "--stream",
        "--reg-weights", "1.0", "--max-iterations", "6",
        "--output-dir", single_out,
    ]))

    worker = tmp_path / "stream_worker.py"
    worker.write_text(STREAM_WORKER)
    outs = [str(tmp_path / f"mp{i}") for i in range(2)]
    run_worker_pair(lambda coordinator: [
        [sys.executable, str(worker), REPO, coordinator, str(i),
         str(input_dir), outs[i]]
        for i in range(2)
    ], timeout=240, what="streaming worker")

    def final_value(out):
        with open(os.path.join(out, "training_summary.json")) as f:
            return json.load(f)["sweep"][0]["final_value"]

    # Identical global objective -> identical optimum (up to solver noise).
    # Only rank 0 writes outputs (the reference's driver-writes semantics);
    # rank 1 exiting cleanly above is its assertion.
    assert final_value(outs[0]) == pytest.approx(
        final_value(single_out), rel=1e-4
    )
    assert not os.path.exists(os.path.join(outs[1], "training_summary.json"))

    # Kernel pinning (VERDICT r3 weak 2): both ranks started on "auto" and
    # must have resolved the SAME pinned kernel (the autodiff default —
    # measured fastest on real TPU, KERNEL_NOTES.md round-4 table) — never
    # a per-rank measurement that could mix reduction orders across shards.
    kernels = [
        json.load(open(os.path.join(o, "kernel.json")))["kernel"] for o in outs
    ]
    assert kernels == ["autodiff", "autodiff"], kernels


GAME_WORKER = r"""
import json, os, sys
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
sys.path.insert(0, sys.argv[1])
coordinator, pid, out_dir = sys.argv[2], int(sys.argv[3]), sys.argv[4]
extra = sys.argv[5:]
from photon_tpu.drivers import train_game

summary = train_game.run(train_game.build_parser().parse_args([
    "--backend", "cpu",
    "--coordinator", coordinator, "--process-id", str(pid),
    "--num-processes", "2",
    "--input", "synthetic-game:32:4:8:4:1:7",
    "--coordinate", "fixed:type=fixed,shard=global,max_iters=6",
    "--coordinate", "per_user:type=random,shard=re0,entity=re0,max_iters=5",
    "--descent-iterations", "1",
    "--validation-split", "0.25",
    "--output-dir", out_dir,
] + extra))
if pid == 0:
    with open(os.path.join(out_dir, "mp_metrics.json"), "w") as f:
        json.dump(summary["best_metrics"], f)
"""


def test_two_process_game_driver_matches_single(tmp_path):
    """Full GAME training over a 2-process global mesh: fixed effect
    data-sharded with psum, random effect entity-sharded, rank-0-only
    writes — must reproduce the single-process metrics.  (Row-split across
    real processes is covered by test_two_process_row_split_matches_single;
    carrying it here too tripled this test's compile load.)"""
    from photon_tpu.drivers import train_game

    argv = [
        "--backend", "cpu",
        "--input", "synthetic-game:32:4:8:4:1:7",
        "--coordinate", "fixed:type=fixed,shard=global,max_iters=6",
        "--coordinate", "per_user:type=random,shard=re0,entity=re0,max_iters=5",
        "--descent-iterations", "1",
        "--validation-split", "0.25",
    ]
    single = train_game.run(train_game.build_parser().parse_args(
        argv + ["--output-dir", str(tmp_path / "single")]))

    worker = tmp_path / "game_worker.py"
    worker.write_text(GAME_WORKER)
    outs = [str(tmp_path / f"mp{i}") for i in range(2)]
    run_worker_pair(lambda coordinator: [
        [sys.executable, str(worker), REPO, coordinator, str(i), outs[i]]
        for i in range(2)
    ], what="GAME worker")

    mp_metrics = json.load(open(os.path.join(outs[0], "mp_metrics.json")))
    assert os.path.isdir(os.path.join(outs[0], "best_model"))
    for name, value in single["best_metrics"].items():
        assert mp_metrics[name] == pytest.approx(value, rel=2e-3), (
            name, mp_metrics[name], value
        )


def test_two_process_device_residuals_match_single(tmp_path):
    """EXPLICIT ``--residuals device --validation-pipeline device`` under a
    2-process global mesh: the sharded score tables (training residuals AND
    validation) run as SPMD programs over globally-sharded rows, so the
    device engine no longer falls back to host multi-process — metrics must
    reproduce a single-process device-mode run."""
    from photon_tpu.drivers import train_game

    flags = ["--residuals", "device", "--validation-pipeline", "device"]
    argv = [
        "--backend", "cpu",
        "--input", "synthetic-game:32:4:8:4:1:7",
        "--coordinate", "fixed:type=fixed,shard=global,max_iters=6",
        "--coordinate", "per_user:type=random,shard=re0,entity=re0,max_iters=5",
        "--descent-iterations", "1",
        "--validation-split", "0.25",
    ] + flags
    single = train_game.run(train_game.build_parser().parse_args(
        argv + ["--output-dir", str(tmp_path / "single")]))

    worker = tmp_path / "game_worker.py"
    worker.write_text(GAME_WORKER)
    outs = [str(tmp_path / f"mp{i}") for i in range(2)]
    run_worker_pair(lambda coordinator: [
        [sys.executable, str(worker), REPO, coordinator, str(i), outs[i]]
        + flags
        for i in range(2)
    ], what="GAME device-residual worker")

    mp_metrics = json.load(open(os.path.join(outs[0], "mp_metrics.json")))
    for name, value in single["best_metrics"].items():
        assert mp_metrics[name] == pytest.approx(value, rel=2e-3), (
            name, mp_metrics[name], value
        )




def test_two_process_checkpoint_resumes_on_one_process(tmp_path):
    """Elastic resume, the real multi-controller leg: a checkpoint WRITTEN
    by a 2-process run (rank 0 writes, globally-sharded score tables)
    resumes on ONE process — a different process AND device count — and
    continues training to the single-process run's metrics.  Skips with a
    reason on jaxlibs without cross-process CPU collectives
    (MP_UNSUPPORTED_MARKERS), like every multi-process test."""
    from photon_tpu.drivers import train_game

    ckpt = str(tmp_path / "ckpt")
    worker = tmp_path / "game_worker.py"
    worker.write_text(GAME_WORKER)
    outs = [str(tmp_path / f"mp{i}") for i in range(2)]
    # The 2-proc pair trains ONE outer iteration with checkpointing on.
    run_worker_pair(lambda coordinator: [
        [sys.executable, str(worker), REPO, coordinator, str(i), outs[i],
         "--checkpoint-dir", ckpt]
        for i in range(2)
    ], what="GAME checkpoint worker")
    from photon_tpu.fault.checkpoint import has_published_checkpoint

    assert has_published_checkpoint(ckpt)

    argv = [
        "--backend", "cpu",
        "--input", "synthetic-game:32:4:8:4:1:7",
        "--coordinate", "fixed:type=fixed,shard=global,max_iters=6",
        "--coordinate", "per_user:type=random,shard=re0,entity=re0,max_iters=5",
        "--validation-split", "0.25",
    ]
    # Resume single-process with a RAISED iteration budget: iteration 0 is
    # restored from the 2-proc snapshot, iteration 1 trains locally.
    resumed = train_game.run(train_game.build_parser().parse_args(
        argv + ["--descent-iterations", "2",
                "--checkpoint-dir", ckpt, "--resume", "latest",
                "--output-dir", str(tmp_path / "resumed")]))
    single = train_game.run(train_game.build_parser().parse_args(
        argv + ["--descent-iterations", "2",
                "--output-dir", str(tmp_path / "single")]))
    for name, value in single["best_metrics"].items():
        assert resumed["best_metrics"][name] == pytest.approx(
            value, rel=2e-3
        ), (name, resumed["best_metrics"][name], value)
    history = resumed["sweep"][0]["history"]
    assert [h["iteration"] for h in history] == [0, 1]


def test_two_process_row_split_matches_single(merged_worker_results):
    """Row-split entity solves across 2 REAL processes (each holding half of
    every entity's rows) must match a single-process co-located solve — the
    multi-host shuffle-free random-effect path end-to-end.  (Runs inside the
    shared merged worker pair; see merged_worker_results.)"""
    results = merged_worker_results
    np.testing.assert_allclose(results[0]["rs_means"], results[1]["rs_means"],
                               rtol=1e-6)
    np.testing.assert_allclose(results[0]["rs_value"], results[1]["rs_value"],
                               rtol=1e-6)

    # Single-process co-located reference on the same data.
    import jax
    import jax.numpy as jnp

    from photon_tpu.core.objective import GlmObjective, RegularizationContext
    from photon_tpu.core.optimizers import OptimizerConfig
    from photon_tpu.core.problem import GlmOptimizationProblem, ProblemConfig
    from photon_tpu.data.batch import SparseBatch

    E, R, k, d = 5, 16, 3, 10  # must match the worker's Part-2 shapes
    rng = np.random.default_rng(0)
    batch = SparseBatch(
        jnp.asarray(rng.integers(1, d, (E, R, k)).astype(np.int32)),
        jnp.asarray(rng.standard_normal((E, R, k)).astype(np.float32)),
        jnp.asarray((rng.random((E, R)) < 0.5).astype(np.float32)),
        jnp.zeros((E, R), jnp.float32),
        jnp.asarray(rng.uniform(0.5, 2.0, (E, R)).astype(np.float32)),
    )
    reg = RegularizationContext("l2", 0.8)
    cfg = ProblemConfig(optimizer="lbfgs", regularization=reg,
                        optimizer_config=OptimizerConfig(max_iterations=12))
    obj = GlmObjective.create("logistic", reg)
    ref_coeffs, _ = GlmOptimizationProblem(obj, cfg).solver(vmapped=True)(
        obj, batch, jnp.zeros((E, d), jnp.float32)
    )
    np.testing.assert_allclose(
        results[0]["rs_means"], np.asarray(ref_coeffs.means),
        rtol=2e-2, atol=2e-3,
    )
