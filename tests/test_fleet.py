"""Fleet serving (photon_tpu/serving fleet tier, ISSUE 12): socket
transport, replicated scorers behind the router, deadline-aware admission
control, traffic generation, canary rollout, replica-death rerouting.

The contracts pinned here:

- wire roundtrip: a request (dense + sparse + string/int keys + offset +
  deadline) survives pack→unpack bit-exactly; responses carry scores,
  sheds, and errors as typed frames;
- TCP serving parity: scores over the loopback ingest equal the host
  oracle; an injected ``transport:read`` fault is retried (reconnect +
  resend) to a correct response;
- overload: past-saturation offered load sheds deterministically
  (``serving.shed`` counted, every future resolves, admitted p99 bounded,
  ZERO jax compilations after warmup — the recompile-freedom contract
  holds under overload);
- cold-start storm: a burst of unknown entities rides the zero-row
  fallback (fixed-effect-only scores, ``serving.cold_entities`` counted,
  no recompiles);
- replica death: a ``serve:replica_kill`` mid-stream reroutes in-flight
  work with no lost or duplicated responses;
- canary rollout: one replica first, mirrored-traffic parity probe, then
  the rest — responses are always exactly ONE model's scores; a probe
  failure rolls the canary back; a canary killed mid-probe fails over to
  the next replica;
- the "Serving fleet" telemetry report section renders per-replica
  QPS/depth, the shed breakdown, and the rollout timeline.
"""

from __future__ import annotations

import numpy as np
import pytest

from photon_tpu.data.synthetic import make_game_dataset
from photon_tpu.fault.injection import FaultPlan, set_plan
from photon_tpu.game.model import (
    FixedEffectModel,
    GameModel,
    RandomEffectModel,
)
from photon_tpu.models.glm import Coefficients, model_for_task
from photon_tpu.serving import (
    AdmissionPolicy,
    RequestShedError,
    RolloutParityError,
    ScoringClient,
    ScoringRequest,
    ServingFleet,
    TrafficSpec,
    build_requests,
    generate_traffic,
    host_score_request,
    request_spec_for_dataset,
    run_closed_loop_outcomes,
)
from photon_tpu.serving.transport import (
    pack_request,
    pack_scores,
    pack_shed,
    unpack_request,
    unpack_response,
)
from photon_tpu.telemetry import TelemetrySession


@pytest.fixture(autouse=True)
def _no_fault_plan():
    yield
    set_plan(None)


def _fixture(seed=3, n_entities=40, fixed_dim=6, random_dim=4):
    data, _ = make_game_dataset(
        n_entities, 4, fixed_dim, random_dim, seed=seed
    )
    rng = np.random.default_rng(seed)
    keys = np.unique(data.id_columns["re0"])
    model = GameModel(
        coordinates={
            "fixed": FixedEffectModel(
                model_for_task("logistic_regression", Coefficients(
                    rng.standard_normal(fixed_dim).astype(np.float32)
                )),
                "global",
            ),
            "per_entity": RandomEffectModel(
                table=rng.standard_normal(
                    (len(keys), random_dim)
                ).astype(np.float32),
                keys=keys, entity_column="re0", shard_name="re0",
                task_type="logistic_regression",
            ),
        },
        task_type="logistic_regression",
    )
    return model, data


def _retrained(model: GameModel, seed: int) -> GameModel:
    rng = np.random.default_rng(seed)
    fixed = model.coordinates["fixed"]
    per_entity = model.coordinates["per_entity"]
    means = np.asarray(fixed.coefficients.means)
    return GameModel(
        coordinates={
            "fixed": FixedEffectModel(
                model_for_task(model.task_type, Coefficients(
                    (means + rng.standard_normal(means.shape)).astype(
                        np.float32
                    )
                )),
                fixed.shard_name,
            ),
            "per_entity": RandomEffectModel(
                table=rng.standard_normal(
                    (per_entity.num_entities, per_entity.dim)
                ).astype(np.float32),
                keys=per_entity.keys,
                entity_column=per_entity.entity_column,
                shard_name=per_entity.shard_name,
                task_type=model.task_type,
            ),
        },
        task_type=model.task_type,
    )


def _counter_total(session, name, **labels):
    total = 0
    for m in session.registry.snapshot()["counters"]:
        if m["name"] != name:
            continue
        if labels and any(
            str(m["labels"].get(k)) != str(v) for k, v in labels.items()
        ):
            continue
        total += m["value"]
    return total


def _fleet(model, data, session, replicas=2, max_batch=16, **kwargs):
    return ServingFleet(
        model, replicas=replicas,
        request_spec=request_spec_for_dataset(model, data),
        max_batch=max_batch, max_delay_s=0.001, telemetry=session,
        **kwargs,
    ).warmup()


# -- wire format -------------------------------------------------------------

def test_transport_request_roundtrip():
    req = ScoringRequest(
        features={
            "dense": np.arange(12, dtype=np.float32).reshape(3, 4),
            "sparse": (
                np.arange(6, dtype=np.int32).reshape(3, 2),
                np.linspace(0, 1, 6, dtype=np.float32).reshape(3, 2),
            ),
        },
        entity_ids={
            "user": np.asarray([7, 9, 11], np.int64),
            "item": np.asarray(["a-1", "bb-22", "ccc-333"]),
        },
        offset=np.asarray([0.5, -1.0, 2.0], np.float32),
    )
    got, deadline = unpack_request(pack_request(req, deadline_s=0.025))
    assert abs(deadline - 0.025) < 1e-12
    np.testing.assert_array_equal(got.features["dense"],
                                  req.features["dense"])
    np.testing.assert_array_equal(got.features["sparse"][0],
                                  req.features["sparse"][0])
    np.testing.assert_array_equal(got.features["sparse"][1],
                                  req.features["sparse"][1])
    np.testing.assert_array_equal(got.entity_ids["user"],
                                  req.entity_ids["user"])
    np.testing.assert_array_equal(got.entity_ids["item"],
                                  req.entity_ids["item"])
    np.testing.assert_array_equal(got.offset, req.offset)
    assert got.entity_ids["item"].dtype == req.entity_ids["item"].dtype
    # No deadline → None on the other side.
    _, none_deadline = unpack_request(pack_request(req))
    assert none_deadline is None


def test_transport_response_roundtrips():
    scores = np.linspace(-2, 2, 7, dtype=np.float32)
    np.testing.assert_array_equal(
        unpack_response(pack_scores(scores)), scores
    )
    with pytest.raises(RequestShedError, match="queue projection") as e:
        unpack_response(pack_shed("overload", "queue projection blown"))
    assert e.value.reason == "overload"
    from photon_tpu.serving.transport import TransportError

    with pytest.raises(TransportError, match="boom"):
        unpack_response(
            __import__(
                "photon_tpu.serving.transport", fromlist=["pack_error"]
            ).pack_error("boom")
        )


# -- TCP serving -------------------------------------------------------------

def test_fleet_serves_over_tcp_matching_host_oracle():
    model, data = _fixture(seed=5)
    session = TelemetrySession("test-fleet-tcp")
    with _fleet(model, data, session, replicas=1) as fleet:
        server = fleet.serve()
        with ScoringClient(server.address, telemetry=session) as client:
            for req in build_requests(data, model, [1, 5, 16]):
                got = client.score(req, deadline_s=10.0)
                np.testing.assert_allclose(
                    got, host_score_request(model, req),
                    rtol=1e-4, atol=1e-4,
                )
    assert _counter_total(session, "serving.transport_connections") >= 1
    assert _counter_total(
        session, "serving.transport_bytes", direction="in"
    ) > 0


def test_transport_read_fault_retried_to_clean_response(monkeypatch):
    monkeypatch.setenv("PHOTON_IO_RETRY_BASE_S", "0")
    model, data = _fixture(seed=7)
    session = TelemetrySession("test-transport-fault")
    with _fleet(model, data, session, replicas=1) as fleet:
        server = fleet.serve()
        (req,) = build_requests(data, model, [6])
        set_plan(FaultPlan.parse("transport:read:times=2"))
        with ScoringClient(server.address, telemetry=session) as client:
            got = client.score(req)
        set_plan(None)
        np.testing.assert_allclose(
            got, host_score_request(model, req), rtol=1e-4, atol=1e-4
        )
    assert _counter_total(
        session, "io.retries", site="transport:read"
    ) >= 1


# -- router dispatch + admission ---------------------------------------------

def test_router_dispatches_across_replicas():
    model, data = _fixture(seed=9)
    session = TelemetrySession("test-dispatch")
    with _fleet(model, data, session, replicas=2) as fleet:
        outcomes, _ = run_closed_loop_outcomes(
            lambda tid: (
                lambda item: fleet.score(item.request)
            ),
            generate_traffic(
                data, model,
                TrafficSpec(requests=40, mean_rows=4, max_rows=16, seed=0),
            ).items,
            clients=4,
        )
    assert all(o.status == "ok" for o in outcomes)
    # Queue-depth-aware dispatch actually spread load: both replicas saw
    # traffic (40 requests, 4 concurrent clients, 1ms windows).
    assert _counter_total(
        session, "serving.replica_requests", replica="r0"
    ) > 0
    assert _counter_total(
        session, "serving.replica_requests", replica="r1"
    ) > 0
    assert _counter_total(session, "serving.admitted") == 40


def test_overload_sheds_deterministically_without_recompiles():
    """ISSUE 12 satellite: offered load past saturation sheds (counted,
    every future resolves, admitted p99 bounded) and the whole episode
    triggers ZERO jax compilations after warmup."""
    import jax.monitoring
    from jax._src import monitoring as monitoring_src

    model, data = _fixture(seed=11)
    session = TelemetrySession("test-overload")
    compile_events = []

    def listener(event, **kwargs):
        if "compile" in event:
            compile_events.append(event)

    import time

    with _fleet(
        model, data, session, replicas=2,
        admission=AdmissionPolicy(max_queue_rows=64),
    ) as fleet:
        requests = build_requests(data, model, [4] * 150)
        want = model.score(data)
        jax.monitoring.register_event_listener(listener)
        try:
            # A single-thread flood far past the drain rate: the 64-row
            # depth cap must start shedding while every admitted request
            # still completes with its OWN rows' scores.
            admitted, sheds = [], 0
            latencies = []
            pos = 0
            for req in requests:
                rows = np.arange(pos, pos + 4) % data.num_examples
                pos = (pos + 4) % data.num_examples
                t0 = time.monotonic()
                try:
                    fut = fleet.submit(req)
                except RequestShedError as e:
                    assert e.reason in ("queue_full", "overload")
                    sheds += 1
                    continue
                fut.add_done_callback(
                    lambda f, t0=t0: latencies.append(
                        time.monotonic() - t0
                    )
                )
                admitted.append((fut, rows))
            results = [
                (f.result(timeout=60), rows) for f, rows in admitted
            ]
            # Deterministic deadline shed: a zero budget can never admit.
            with pytest.raises(RequestShedError) as shed_info:
                fleet.submit(requests[0], deadline_s=0.0)
        finally:
            monitoring_src._unregister_event_listener_by_callback(listener)
        assert shed_info.value.reason == "deadline"
        for got, rows in results:
            np.testing.assert_allclose(
                got, want[rows], rtol=1e-4, atol=1e-4
            )
    assert sheds > 0
    assert len(results) > 0
    assert len(results) + sheds == len(requests)
    assert _counter_total(session, "serving.shed") == sheds + 1
    # Every admitted request resolved, no recompiles, and the depth cap
    # keeps the admitted tail bounded (64 queued rows at CPU-fixture pace
    # drain in well under a second; 5s is the no-unbounded-queue pin).
    assert compile_events == []
    assert len(latencies) == len(results)
    assert float(np.percentile(latencies, 99)) < 5.0


def test_deadline_shed_and_hit_accounting():
    model, data = _fixture(seed=13)
    session = TelemetrySession("test-deadline")
    with _fleet(model, data, session, replicas=1) as fleet:
        (req,) = build_requests(data, model, [4])
        # Generous budget: admitted and met.
        got = fleet.score(req, deadline_s=30.0)
        np.testing.assert_allclose(
            got, host_score_request(model, req), rtol=1e-4, atol=1e-4
        )
        with pytest.raises(RequestShedError):
            fleet.submit(req, deadline_s=0.0)
    assert _counter_total(session, "serving.admitted") == 1
    assert _counter_total(session, "serving.shed", reason="deadline") == 1


def test_cold_start_storm_rides_zero_row_fallback():
    """ISSUE 12 satellite: a burst of unknown entities gets fixed-effect-
    only scores through the (movable) zero row, counted as cold — and
    never recompiles."""
    import jax.monitoring
    from jax._src import monitoring as monitoring_src

    model, data = _fixture(seed=17)
    session = TelemetrySession("test-storm")
    traffic = generate_traffic(
        data, model,
        TrafficSpec(requests=30, mean_rows=4, max_rows=16,
                    storm_frac=0.3, storm_at=0.5, seed=3),
    )
    storm_items = [t for t in traffic.items if t.kind == "storm"]
    assert len(storm_items) == 9
    compile_events = []

    def listener(event, **kwargs):
        if "compile" in event:
            compile_events.append(event)

    with _fleet(model, data, session, replicas=2) as fleet:
        jax.monitoring.register_event_listener(listener)
        try:
            outcomes, _ = run_closed_loop_outcomes(
                lambda tid: (lambda item: fleet.score(item.request)),
                traffic.items, clients=3,
            )
        finally:
            monitoring_src._unregister_event_listener_by_callback(listener)
    assert all(o.status == "ok" for o in outcomes)
    for out in outcomes:
        np.testing.assert_allclose(
            out.scores, host_score_request(model, out.item.request),
            rtol=1e-4, atol=1e-4,
        )
    storm_rows = sum(t.request.num_rows for t in storm_items)
    assert _counter_total(session, "serving.cold_entities") == storm_rows
    assert compile_events == []


# -- replica death -----------------------------------------------------------

def test_replica_kill_mid_stream_reroutes_without_loss():
    """ISSUE 12 acceptance: a replica killed mid-replay reroutes its
    in-flight work — every submitted request resolves exactly once with
    its own correct scores (none lost, none duplicated), the death is
    counted, and the survivor serves the rest."""
    model, data = _fixture(seed=19)
    session = TelemetrySession("test-kill")
    with _fleet(model, data, session, replicas=2) as fleet:
        requests = build_requests(data, model, [4] * 30)
        set_plan(FaultPlan.parse("serve:replica_kill:replica=r0:times=1"))
        futures = [fleet.submit(r) for r in requests]
        results = [f.result(timeout=60) for f in futures]
        set_plan(None)
        want = model.score(data)
        pos = 0
        for got in results:
            rows = np.arange(pos, pos + 4) % data.num_examples
            np.testing.assert_allclose(
                got, want[rows], rtol=1e-4, atol=1e-4
            )
            pos = (pos + 4) % data.num_examples
        assert not fleet.replicas[0].alive
        assert fleet.replicas[1].alive
        # Post-kill traffic keeps serving through the survivor.
        np.testing.assert_allclose(
            fleet.score(requests[0]), want[np.arange(4)],
            rtol=1e-4, atol=1e-4,
        )
    assert _counter_total(
        session, "serving.replica_deaths", replica="r0"
    ) == 1
    assert _counter_total(session, "serving.rerouted") >= 1


def test_all_replicas_dead_sheds_no_replica():
    model, data = _fixture(seed=23)
    session = TelemetrySession("test-all-dead")
    with _fleet(model, data, session, replicas=1) as fleet:
        (req,) = build_requests(data, model, [4])
        set_plan(FaultPlan.parse("serve:replica_kill:times=1"))
        fut = fleet.submit(req)
        from photon_tpu.serving import NoHealthyReplicaError

        with pytest.raises(NoHealthyReplicaError):
            fut.result(timeout=30)
        set_plan(None)
        with pytest.raises(RequestShedError) as e:
            fleet.submit(req)
        assert e.value.reason == "no_replica"


# -- canary rollout ----------------------------------------------------------

def test_rollout_canary_probe_then_promote_under_load():
    """ISSUE 12 acceptance: a canary rollout completes under load with
    zero mixed-model responses — every response is wholly one model's
    scores, the stream's tail serves the new model, and nothing
    recompiles (same-layout swap, capacity-headroom tables)."""
    import jax.monitoring
    from jax._src import monitoring as monitoring_src

    model, data = _fixture(seed=29)
    retrained = _retrained(model, seed=31)
    session = TelemetrySession("test-rollout")
    want_old = model.score(data)
    want_new = retrained.score(data)
    requests = build_requests(data, model, [8] * 40)
    windows = [np.arange(i * 8, (i + 1) * 8) % data.num_examples
               for i in range(40)]
    compile_events = []

    def listener(event, **kwargs):
        if "compile" in event:
            compile_events.append(event)

    with _fleet(model, data, session, replicas=2, max_batch=32) as fleet:
        compiled = fleet.compilations
        jax.monitoring.register_event_listener(listener)
        try:
            futures = []
            for i, req in enumerate(requests):
                if i == 20:
                    fleet.rollout(retrained)
                futures.append(fleet.submit(req))
            results = [f.result(timeout=60) for f in futures]
        finally:
            monitoring_src._unregister_event_listener_by_callback(listener)
        assert fleet.compilations == compiled
    for rows, got in zip(windows, results):
        ok_old = np.allclose(got, want_old[rows], rtol=1e-4, atol=1e-4)
        ok_new = np.allclose(got, want_new[rows], rtol=1e-4, atol=1e-4)
        assert ok_old or ok_new, "response matches neither model"
    assert np.allclose(
        results[-1], want_new[windows[-1]], rtol=1e-4, atol=1e-4
    )
    assert compile_events == []
    assert _counter_total(session, "serving.rollouts") == 1
    assert _counter_total(session, "serving.swaps") == 2  # canary + promote
    # Timeline gauges: canary then probe_ok then promoted.
    steps = {
        (m["labels"]["replica"], m["labels"]["phase"]): m["value"]
        for m in session.registry.snapshot()["gauges"]
        if m["name"] == "serving.rollout_step"
    }
    phases = [p for (_, p), _v in sorted(steps.items(), key=lambda kv: kv[1])]
    assert phases == ["canary", "probe_ok", "promoted"]


def test_rollout_aborts_and_rolls_back_on_parity_failure():
    model, data = _fixture(seed=37)
    retrained = _retrained(model, seed=41)
    session = TelemetrySession("test-rollout-abort")
    with _fleet(model, data, session, replicas=2) as fleet:
        probes = build_requests(data, model, [4, 4])
        bad_oracle = lambda req: np.full(  # noqa: E731 — tiny test stub
            req.num_rows, 1e6, np.float32
        )
        with pytest.raises(RolloutParityError, match="parity probe"):
            fleet.router.rollout(
                retrained, probe_requests=probes, probe_oracle=bad_oracle
            )
        # Canary rolled back: the WHOLE fleet still serves the old model.
        want_old = model.score(data)
        for _ in range(4):
            got = fleet.score(probes[0])
            np.testing.assert_allclose(
                got, want_old[np.arange(4)], rtol=1e-4, atol=1e-4
            )
    steps = {
        m["labels"]["phase"]
        for m in session.registry.snapshot()["gauges"]
        if m["name"] == "serving.rollout_step"
    }
    assert "rolled_back" in steps
    assert _counter_total(session, "serving.rollouts") == 0


def test_rollout_survives_canary_kill_mid_probe():
    """Mid-rollout kill (README failure-matrix row): the canary dies while
    its parity probe runs; the rollout fails over to the next healthy
    replica and completes — the fleet ends up serving the new model."""
    model, data = _fixture(seed=43)
    retrained = _retrained(model, seed=47)
    session = TelemetrySession("test-rollout-kill")
    with _fleet(model, data, session, replicas=2) as fleet:
        probes = build_requests(data, model, [4, 4])
        set_plan(FaultPlan.parse("serve:replica_kill:replica=r0:times=1"))
        fleet.rollout(retrained, probe_requests=probes)
        set_plan(None)
        assert not fleet.replicas[0].alive
        assert fleet.replicas[1].alive
        want_new = retrained.score(data)
        np.testing.assert_allclose(
            fleet.score(probes[0]), want_new[np.arange(4)],
            rtol=1e-4, atol=1e-4,
        )
    steps = {
        (m["labels"]["replica"], m["labels"]["phase"])
        for m in session.registry.snapshot()["gauges"]
        if m["name"] == "serving.rollout_step"
    }
    assert ("r0", "died") in steps
    assert ("r1", "probe_ok") in steps
    assert _counter_total(
        session, "serving.replica_deaths", replica="r0"
    ) == 1


def test_rollout_rolls_back_on_non_parity_probe_failure():
    """A probe failure that is NOT a parity disagreement (here: the oracle
    itself raising) must also roll the canary back — the fleet may never
    be left split across two models by an escaping probe error."""
    model, data = _fixture(seed=59)
    retrained = _retrained(model, seed=61)
    session = TelemetrySession("test-rollout-probe-err")
    with _fleet(model, data, session, replicas=2) as fleet:
        probes = build_requests(data, model, [4, 4])

        def broken_oracle(req):
            raise RuntimeError("oracle exploded")

        with pytest.raises(RuntimeError, match="oracle exploded"):
            fleet.router.rollout(
                retrained, probe_requests=probes, probe_oracle=broken_oracle
            )
        # Canary rolled back: the WHOLE fleet still serves the old model.
        want_old = model.score(data)
        for _ in range(4):
            np.testing.assert_allclose(
                fleet.score(probes[0]), want_old[np.arange(4)],
                rtol=1e-4, atol=1e-4,
            )
    steps = {
        m["labels"]["phase"]
        for m in session.registry.snapshot()["gauges"]
        if m["name"] == "serving.rollout_step"
    }
    assert "rolled_back" in steps
    assert _counter_total(session, "serving.rollouts") == 0


def test_rollout_promote_failure_marks_replica_dead():
    """A replica whose swap fails AT PROMOTE (after the canary probe
    passed) is marked dead — it must not keep serving the old model
    behind a fleet that promoted — and the rollout still completes."""
    model, data = _fixture(seed=67)
    retrained = _retrained(model, seed=71)
    session = TelemetrySession("test-promote-fail")
    with _fleet(model, data, session, replicas=2) as fleet:
        probes = build_requests(data, model, [4, 4])

        def refuse(_model):
            raise RuntimeError("device fell over at promote")

        fleet.replicas[1].scorer.swap_model = refuse
        fleet.rollout(retrained, probe_requests=probes)
        assert fleet.replicas[0].alive
        assert not fleet.replicas[1].alive
        want_new = retrained.score(data)
        np.testing.assert_allclose(
            fleet.score(probes[0]), want_new[np.arange(4)],
            rtol=1e-4, atol=1e-4,
        )
    steps = {
        (m["labels"]["replica"], m["labels"]["phase"])
        for m in session.registry.snapshot()["gauges"]
        if m["name"] == "serving.rollout_step"
    }
    assert ("r1", "died") in steps
    assert _counter_total(session, "serving.rollouts") == 1
    assert _counter_total(
        session, "serving.replica_deaths", replica="r1"
    ) == 1


def test_submit_after_close_sheds_closed_without_phantom_death():
    """A submit racing (or following) shutdown sheds ``closed`` — it must
    not funnel the closing batcher's error into the replica-death path and
    record phantom deaths/reroutes in the run report."""
    model, data = _fixture(seed=73)
    session = TelemetrySession("test-closed-shed")
    fleet = _fleet(model, data, session, replicas=2)
    (req,) = build_requests(data, model, [4])
    fleet.score(req)  # healthy while open
    fleet.close()
    with pytest.raises(RequestShedError) as e:
        fleet.submit(req)
    assert e.value.reason == "closed"
    assert all(r.alive for r in fleet.replicas)
    assert _counter_total(session, "serving.replica_deaths") == 0
    assert _counter_total(session, "serving.rerouted") == 0
    assert _counter_total(session, "serving.shed", reason="closed") == 1


# -- fault-site registry (ISSUE 12 satellite) --------------------------------

def test_new_fault_sites_registered_with_correct_semantics():
    """`serve:replica_kill` / `transport:read` ride the KNOWN_FAULT_SITES
    registry (the scan tests in test_fault_sites.py enforce docs +
    coverage); here their SEMANTICS are pinned: replica_kill is a KILL
    (InjectedKillError, replica-targetable), transport:read a retriable
    IO fault."""
    from photon_tpu.fault.injection import (
        KNOWN_FAULT_SITES,
        InjectedIOError,
        InjectedKillError,
        fault_point,
    )

    assert "serve:replica_kill" in KNOWN_FAULT_SITES
    assert "transport:read" in KNOWN_FAULT_SITES
    set_plan(FaultPlan.parse("serve:replica_kill:times=1"))
    with pytest.raises(InjectedKillError):
        fault_point("serve:replica_kill", replica="rX")
    set_plan(FaultPlan.parse("transport:read:times=1"))
    with pytest.raises(InjectedIOError):
        fault_point("transport:read")
    # Replica targeting: a rule scoped to r1 never fires on r0.
    set_plan(FaultPlan.parse("serve:replica_kill:replica=r1:times=1"))
    fault_point("serve:replica_kill", replica="r0")  # must not raise
    with pytest.raises(InjectedKillError):
        fault_point("serve:replica_kill", replica="r1")
    set_plan(None)


# -- traffic generator -------------------------------------------------------

def test_traffic_generator_is_deterministic():
    model, data = _fixture(seed=49)
    spec = TrafficSpec(requests=50, mean_rows=5, max_rows=16, alpha=1.2,
                       storm_frac=0.1, target_qps=500.0,
                       deadline_ms=20.0, seed=7)
    a = generate_traffic(data, model, spec)
    b = generate_traffic(data, model, spec)
    assert a.duration_s == b.duration_s
    for x, y in zip(a.items, b.items):
        assert x.at_s == y.at_s and x.kind == y.kind
        assert x.deadline_s == y.deadline_s == 0.02
        np.testing.assert_array_equal(
            x.request.entity_ids["re0"], y.request.entity_ids["re0"]
        )
        np.testing.assert_array_equal(
            x.request.features["global"], y.request.features["global"]
        )
    # Arrival times are a non-decreasing schedule over the target span.
    at = [t.at_s for t in a.items]
    assert all(s <= t for s, t in zip(at, at[1:]))
    assert a.duration_s == pytest.approx(50 / 500.0)


def test_powerlaw_popularity_skews_entity_traffic():
    model, data = _fixture(seed=53, n_entities=60)
    traffic = generate_traffic(
        data, model,
        TrafficSpec(requests=300, mean_rows=4, max_rows=16,
                    alpha=1.4, seed=11),
    )
    # Count requests per (single) entity: each powerlaw request samples
    # rows of ONE entity.
    per_entity: dict = {}
    for item in traffic.items:
        keys = np.unique(item.request.entity_ids["re0"])
        assert len(keys) == 1  # one user per request
        per_entity[keys[0]] = per_entity.get(keys[0], 0) + 1
    counts = sorted(per_entity.values(), reverse=True)
    # The hottest entity dominates far beyond the uniform share.
    assert counts[0] >= 3 * (300 / 60)


def test_geometric_traffic_matches_pr9_stream():
    """Bench continuity: ``popularity='geometric'`` reproduces the PR 9
    seeded stream (request_sizes + consecutive row windows) exactly."""
    from photon_tpu.drivers.serve_game import request_sizes

    model, data = _fixture(seed=59)
    traffic = generate_traffic(
        data, model,
        TrafficSpec(requests=20, mean_rows=8, max_rows=32,
                    popularity="geometric", seed=4),
    )
    sizes = request_sizes(20, 8.0, 32, seed=4)
    legacy = build_requests(data, model, sizes)
    assert len(traffic.items) == len(legacy)
    for item, old in zip(traffic.items, legacy):
        np.testing.assert_array_equal(
            item.request.features["global"], old.features["global"]
        )
        np.testing.assert_array_equal(
            item.request.entity_ids["re0"], old.entity_ids["re0"]
        )


# -- report renderer ---------------------------------------------------------

def test_report_renders_serving_fleet_section():
    """ISSUE 12 satellite: the telemetry report grows a "Serving fleet"
    section — per-replica table, shed breakdown, deadline hit rate,
    rollout timeline."""
    from photon_tpu.telemetry.report import render_markdown

    model, data = _fixture(seed=61)
    session = TelemetrySession("test-fleet-report")
    with _fleet(model, data, session, replicas=2) as fleet:
        requests = build_requests(data, model, [4] * 10)
        for req in requests:
            fleet.score(req, deadline_s=30.0)
        with pytest.raises(RequestShedError):
            fleet.submit(requests[0], deadline_s=0.0)
        fleet.rollout(_retrained(model, seed=67), probe_requests=requests[:1])
    report = {
        "driver": "test", "run_id": "x", "status": "ok",
        "metrics": session.registry.snapshot(),
    }
    md = render_markdown(report)
    assert "## Serving fleet" in md
    assert "| r0 |" in md and "| r1 |" in md
    assert "**shed**" in md and "deadline=1" in md
    assert "**deadline hit rate**" in md
    assert "**rollout timeline**" in md
    assert "canary" in md and "promoted" in md
    # A fleet-less report renders no fleet section.
    assert "## Serving fleet" not in render_markdown(
        {"driver": "t", "metrics": {"counters": [], "gauges": [],
                                    "histograms": []}}
    )


# -- driver ------------------------------------------------------------------

def test_serve_game_fleet_driver_end_to_end(tmp_path):
    """serve_game with replicas + tcp transport + powerlaw traffic +
    deadline: summary carries the fleet fields, scores parity-check
    against each request's host oracle, the run report renders the
    Serving fleet section."""
    import json

    from photon_tpu.drivers import serve_game
    from photon_tpu.game.model_io import save_game_model

    model, data = _fixture(seed=71)
    _, imaps = make_game_dataset(40, 4, 6, 4, seed=71)
    save_game_model(str(tmp_path / "model"), model, imaps)
    out = tmp_path / "served"
    summary = serve_game.run(serve_game.build_parser().parse_args([
        "--backend", "cpu",
        "--model", str(tmp_path / "model"),
        "--input", "synthetic-game:40:4:6:4:1:71",
        "--requests", "30",
        "--clients", "3",
        "--replicas", "2",
        "--transport", "tcp",
        "--traffic", "powerlaw",
        "--storm-frac", "0.1",
        "--deadline-ms", "2000",
        "--max-batch", "32",
        "--max-delay-ms", "1",
        "--supervise",
        "--output-dir", str(out),
    ]))
    assert summary["requests"] == 30
    assert summary["replicas"] == 2
    assert summary["replica_backend"] == "thread"
    assert summary["supervised"] is True
    # A healthy supervised run: nothing died, nothing resurrected.
    assert summary["replica_deaths"] == 0
    assert summary["resurrections"] == 0
    assert summary["transport"] == "tcp"
    assert summary["traffic"] == "powerlaw"
    assert summary["served"] + summary["shed"] == 30
    assert summary["served"] > 0
    assert summary["cold_entities"] > 0  # the storm rode the fallback
    scores = np.loadtxt(str(out / "scores.txt"))
    assert len(scores) == summary["rows"]
    with open(out / "telemetry" / "run_report.json") as f:
        report = json.load(f)
    names = {m["name"] for m in report["metrics"]["counters"]}
    assert {"serving.admitted", "serving.replica_requests",
            "serving.transport_connections"} <= names
    from photon_tpu.telemetry.report import render_markdown

    md = render_markdown(report)
    assert "## Serving fleet" in md
    assert "## Online serving" in md
