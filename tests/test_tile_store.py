"""Disk-backed tile store (ISSUE 11): part-file container, compression
codec, digest refusal, atomic publish, guarded/retried IO, the LRU host
cache, and the spilled chunk source.

Contracts pinned here:

- part-file roundtrips are BIT-exact (raw and compressed), so spilled and
  host-resident streamed runs cannot diverge;
- a corrupted on-disk tile is refused via digest at read
  (:class:`CorruptTileError`, not retried);
- a torn write (kill mid-publish) leaves the previous part file intact and
  readable;
- ``tile:read`` / ``tile:write`` injected faults retry to a clean result
  (``io.retries{site}`` counted) and exhaust to the real error;
- the LRU host cache respects its byte budget (evictions counted,
  ``tiles.host_cache_bytes`` gauge-asserted), single-flights concurrent
  loads, and serves prefetched entries as disk-tier overlap;
- ``spill_dataset`` + :class:`SpilledChunkSource` reproduce the resident
  chunk slices exactly, and a foreign/stale spill dir is reset.
"""

from __future__ import annotations

import math
import os
import threading

import numpy as np
import pytest

from photon_tpu.data.synthetic import make_game_dataset
from photon_tpu.fault.injection import FaultPlan, set_plan
from photon_tpu.game.tile_store import (
    FEATURES,
    TILES,
    CorruptTileError,
    TileStore,
    _decode,
    _encode,
    compress_enabled,
)
from photon_tpu.game.tiles import (
    ChunkPlan,
    HostTileCache,
    NeumaierAccumulator,
    ResidentChunkSource,
    SpilledChunkSource,
    SpilledResidualTable,
    TiledResidualTable,
    spill_dataset,
)
from photon_tpu.telemetry import TelemetrySession


@pytest.fixture(autouse=True)
def _fast_retries(monkeypatch):
    monkeypatch.setenv("PHOTON_IO_RETRY_BASE_S", "0")


def _counters(session):
    snap = session.registry.snapshot()
    return {
        (m["name"], tuple(sorted(m["labels"].items()))): m["value"]
        for m in snap["counters"]
    }


# -- codec -------------------------------------------------------------------

def test_codec_roundtrip_bit_exact():
    rng = np.random.default_rng(0)
    cases = [
        rng.standard_normal((3, 41)).astype(np.float32),
        (rng.random(100) * 1000).astype(np.int32),
        np.arange(17, dtype=np.int64),
        rng.standard_normal(5).astype(np.float64),
        np.frombuffer(b"photon", dtype=np.uint8),
        np.array([], dtype=np.float32),
        np.array([np.nan, np.inf, -0.0, 1e-38], dtype=np.float32),
    ]
    for arr in cases:
        for compress in (False, True):
            buf, encoding = _encode(arr, compress)
            back = _decode(buf, arr.dtype, arr.shape, encoding)
            assert back.dtype == arr.dtype
            # Bit-exact, not just value-equal (NaN payloads included).
            assert arr.tobytes() == back.tobytes(), (arr.dtype, compress)


def test_store_roundtrips_extension_dtypes(tmp_path):
    """`--dtype bfloat16` feature shards must survive the spill: the
    dtype is stored by NAME (``dtype.str`` of an ml_dtypes extension
    dtype is an opaque void that reconstructs as a JAX-rejected array —
    code-review finding, reproduced live before the fix)."""
    import jax.numpy as jnp
    import ml_dtypes

    arr = np.arange(6, dtype=ml_dtypes.bfloat16).reshape(2, 3)
    for compress in (False, True):
        store = TileStore(
            str(tmp_path / f"c{int(compress)}"), compress=compress
        )
        store.write(FEATURES, 0, {"x": arr})
        back, _ = store.read(FEATURES, 0)
        assert back["x"].dtype == arr.dtype
        assert arr.tobytes() == back["x"].tobytes()
        assert jnp.asarray(back["x"]).dtype == jnp.bfloat16


def test_codec_compresses_smooth_streams():
    # A smooth ramp (the delta+shuffle sweet spot) must actually shrink.
    arr = np.linspace(0, 1, 4096, dtype=np.float32)
    buf, encoding = _encode(arr, True)
    assert encoding == "dsz"
    assert len(buf) < arr.nbytes
    assert np.array_equal(_decode(buf, arr.dtype, arr.shape, encoding), arr)


def test_compress_env_gate(monkeypatch):
    monkeypatch.delenv("PHOTON_TILE_COMPRESS", raising=False)
    assert compress_enabled() is False
    monkeypatch.setenv("PHOTON_TILE_COMPRESS", "1")
    assert compress_enabled() is True
    assert compress_enabled(False) is False  # explicit override wins
    monkeypatch.setenv("PHOTON_TILE_COMPRESS", "off")
    assert compress_enabled() is False


# -- part files --------------------------------------------------------------

def test_store_roundtrip_and_accounting(tmp_path):
    session = TelemetrySession("t-store")
    store = TileStore(str(tmp_path), telemetry=session)
    tile = np.arange(12, dtype=np.float32).reshape(3, 4)
    store.write(TILES, 0, {"tile": tile}, meta={"tile_digest": "abc"})
    arrays, meta = store.read(TILES, 0)
    np.testing.assert_array_equal(arrays["tile"], tile)
    assert meta["tile_digest"] == "abc"
    assert store.read_meta(TILES, 0) == meta
    assert store.has(TILES, 0) and not store.has(TILES, 1)
    assert store.disk_bytes > 0
    gauges = {
        m["name"]: m["value"]
        for m in session.registry.snapshot()["gauges"]
    }
    assert gauges["tiles.disk_bytes"] == store.disk_bytes
    store.delete(TILES, 0)
    assert store.disk_bytes == 0
    # A re-opened store recovers its accounting from the directory.
    store.write(TILES, 1, {"a": tile})
    reopened = TileStore(str(tmp_path))
    assert reopened.disk_bytes == store.disk_bytes > 0


def test_store_compressed_roundtrip(tmp_path, monkeypatch):
    monkeypatch.setenv("PHOTON_TILE_COMPRESS", "1")
    store = TileStore(str(tmp_path))
    assert store.compress
    rng = np.random.default_rng(1)
    arrays = {
        "tile": rng.standard_normal((2, 57)).astype(np.float32),
        "ids": np.sort(rng.integers(0, 100, (57, 4))).astype(np.int32),
    }
    store.write(TILES, 3, arrays)
    back, _ = store.read(TILES, 3)
    for name, arr in arrays.items():
        assert arr.tobytes() == back[name].tobytes()


def test_corrupted_tile_refused_via_digest(tmp_path):
    store = TileStore(str(tmp_path))
    store.write(TILES, 0, {"tile": np.ones(64, np.float32)})
    path = store.path(TILES, 0)
    blob = bytearray(open(path, "rb").read())
    blob[-3] ^= 0xFF  # flip a payload byte
    with open(path, "wb") as f:
        f.write(blob)
    with pytest.raises(CorruptTileError, match="digest mismatch"):
        store.read(TILES, 0)
    # Structural corruption (torn header) is refused too.
    with open(path, "wb") as f:
        f.write(b"garbage!")
    with pytest.raises(CorruptTileError):
        store.read(TILES, 0)


def test_corrupted_compressed_payload_refused(tmp_path, monkeypatch):
    """Corruption in a COMPRESSED payload surfaces as CorruptTileError
    too (zlib.decompress failure, not a raw zlib.error escaping), same
    contract as the raw path's digest mismatch."""
    monkeypatch.setenv("PHOTON_TILE_COMPRESS", "1")
    store = TileStore(str(tmp_path))
    ids = np.sort(
        np.random.default_rng(2).integers(0, 100, (257, 4))
    ).astype(np.int32)
    store.write(TILES, 0, {"ids": ids})
    path = store.path(TILES, 0)
    blob = bytearray(open(path, "rb").read())
    assert b'"dsz"' in blob  # the payload really is compressed
    blob[-9] ^= 0xFF  # flip a compressed-payload byte
    with open(path, "wb") as f:
        f.write(blob)
    with pytest.raises(CorruptTileError):
        store.read(TILES, 0)


def test_corruption_is_not_retried(tmp_path):
    session = TelemetrySession("t-corrupt")
    store = TileStore(str(tmp_path), telemetry=session)
    store.write(TILES, 0, {"tile": np.ones(8, np.float32)})
    path = store.path(TILES, 0)
    blob = bytearray(open(path, "rb").read())
    blob[-1] ^= 0x01
    with open(path, "wb") as f:
        f.write(blob)
    with pytest.raises(CorruptTileError):
        store.read(TILES, 0)
    # Bit-rot is not transient: the retry budget must not be spent on it.
    assert (("io.retries", (("site", "tile:read"),))) not in _counters(
        session
    )


def test_torn_publish_keeps_previous_tile(tmp_path, monkeypatch):
    """A kill inside the publish window (after the temp write, during the
    rename) leaves the PREVIOUS part file intact — the atomic-rename
    contract on the tile write-back path."""
    store = TileStore(str(tmp_path))
    old = np.full(16, 7.0, np.float32)
    store.write(TILES, 0, {"tile": old})

    real_replace = os.replace
    calls = {"n": 0}

    def torn_replace(src, dst):
        if dst.endswith("tile-000000.pt"):
            calls["n"] += 1
            raise KeyboardInterrupt("simulated kill mid-publish")
        return real_replace(src, dst)

    monkeypatch.setattr(os, "replace", torn_replace)
    with pytest.raises(KeyboardInterrupt):
        store.write(TILES, 0, {"tile": np.zeros(16, np.float32)})
    assert calls["n"] == 1
    monkeypatch.setattr(os, "replace", real_replace)
    arrays, _ = store.read(TILES, 0)
    np.testing.assert_array_equal(arrays["tile"], old)
    # No temp debris is ever READ: only *.pt part files count.
    assert store.has(TILES, 0)


def test_injected_tile_faults_retry_to_clean(tmp_path, monkeypatch):
    monkeypatch.setenv("PHOTON_IO_RETRIES", "8")
    session = TelemetrySession("t-faults")
    store = TileStore(str(tmp_path), telemetry=session)
    tile = np.arange(32, dtype=np.float32)
    set_plan(FaultPlan.parse("tile:write:p=0.5,tile:read:p=0.5", seed=3))
    try:
        for k in range(8):
            store.write(TILES, k, {"tile": tile + k})
        for k in range(8):
            arrays, _ = store.read(TILES, k)
            np.testing.assert_array_equal(arrays["tile"], tile + k)
    finally:
        set_plan(None)
    counters = _counters(session)
    retries = sum(
        v for (name, labels), v in counters.items() if name == "io.retries"
    )
    assert retries > 0


def test_injected_tile_fault_exhaustion_raises(tmp_path, monkeypatch):
    monkeypatch.setenv("PHOTON_IO_RETRIES", "2")
    store = TileStore(str(tmp_path))
    store.write(TILES, 0, {"tile": np.ones(4, np.float32)})
    set_plan(FaultPlan.parse("tile:read:p=1.0", seed=0))
    try:
        with pytest.raises(OSError):
            store.read(TILES, 0)
    finally:
        set_plan(None)


# -- LRU host cache ----------------------------------------------------------

def test_cache_hits_misses_and_lru_eviction():
    session = TelemetrySession("t-cache")
    one_kb = np.zeros(256, np.float32)  # 1024 bytes
    cache = HostTileCache(max_bytes=3 * 1024, telemetry=session)
    for k in range(3):
        cache.get(("feat", k), lambda: one_kb)
    assert cache.nbytes == 3 * 1024
    cache.get(("feat", 0), lambda: one_kb)  # refresh 0: now 1 is LRU
    cache.get(("feat", 3), lambda: one_kb)  # evicts 1
    counters = _counters(session)
    assert counters[("tiles.cache_misses", ())] == 4
    assert counters[("tiles.cache_evictions", ())] == 1
    assert counters[("tiles.cache_hits", ())] == 1
    # The evicted key misses again; the refreshed key still hits.
    seen = []
    cache.get(("feat", 1), lambda: seen.append(1) or one_kb)
    cache.get(("feat", 0), lambda: seen.append(0) or one_kb)
    assert seen == [1]
    gauges = {
        m["name"]: m["value"]
        for m in session.registry.snapshot()["gauges"]
    }
    assert 0 < gauges["tiles.host_cache_bytes"] <= 3 * 1024


def test_cache_single_flight_under_concurrency():
    cache = HostTileCache()
    loads = []
    gate = threading.Event()

    def loader():
        gate.wait(2)
        loads.append(1)
        return np.zeros(4, np.float32)

    results = []

    def worker():
        results.append(cache.get(("feat", 0), loader)[0])

    threads = [threading.Thread(target=worker) for _ in range(4)]
    for t in threads:
        t.start()
    gate.set()
    for t in threads:
        t.join()
    assert len(loads) == 1  # one disk read shared by all four
    assert len(results) == 4


def test_cache_prefetch_counts_hidden_overlap():
    session = TelemetrySession("t-prefetch")
    cache = HostTileCache(telemetry=session)
    import time as _time

    def slow_loader():
        _time.sleep(0.01)
        return np.zeros(4, np.float32)

    cache.prefetch(("feat", 9), slow_loader)
    deadline = _time.monotonic() + 2.0
    while ("feat", 9) not in cache._entries:
        assert _time.monotonic() < deadline, "prefetch never landed"
        _time.sleep(0.002)
    value, hidden = cache.get(("feat", 9), slow_loader)
    assert hidden >= 0.01  # the prefetched read's hidden seconds
    _, hidden2 = cache.get(("feat", 9), slow_loader)
    assert hidden2 == 0.0  # only the FIRST consumption reports it


def test_cache_budget_validation():
    with pytest.raises(ValueError):
        HostTileCache(max_bytes=0)


# -- spilled dataset + chunk source ------------------------------------------

@pytest.fixture(scope="module")
def spill_fixture(tmp_path_factory):
    data, _ = make_game_dataset(60, 4, 6, 3, seed=0, n_random_coords=1)
    plan = ChunkPlan(data.num_examples, 23)
    root = str(tmp_path_factory.mktemp("store"))
    store = TileStore(root)
    assert spill_dataset(store, data, plan) == plan.num_chunks
    return data, plan, store


def test_spilled_chunks_match_resident_slices(spill_fixture):
    data, plan, store = spill_fixture
    src = SpilledChunkSource(store, plan, HostTileCache())
    resident = ResidentChunkSource(data, plan)
    for k in range(plan.num_chunks):
        a, b = src.chunk(k), resident.chunk(k)
        np.testing.assert_array_equal(a.label, b.label)
        np.testing.assert_array_equal(a.weight, b.weight)
        np.testing.assert_array_equal(a.offset, b.offset)
        for name in data.shards:
            sa, sb = a.shard(name), b.shard(name)
            if hasattr(sa, "x"):
                np.testing.assert_array_equal(sa.x, sb.x)
            else:
                np.testing.assert_array_equal(sa.ids, sb.ids)
                np.testing.assert_array_equal(sa.vals, sb.vals)
                assert sa.dim_ == sb.dim_


def test_spill_is_idempotent_and_resets_on_foreign_data(spill_fixture):
    data, plan, store = spill_fixture
    assert spill_dataset(store, data, plan) == 0  # already published
    # A different chunking is a DIFFERENT layout: full re-spill.
    other_plan = ChunkPlan(data.num_examples, 31)
    assert spill_dataset(store, data, other_plan) == other_plan.num_chunks
    # Restore the fixture layout for later tests.
    assert spill_dataset(store, data, plan) == plan.num_chunks


def test_spilled_table_matches_host_resident_bitwise(tmp_path):
    rng = np.random.default_rng(0)
    n = 101
    base = rng.standard_normal(n).astype(np.float32)
    plan = ChunkPlan(n, 17)
    names = ["a", "b", "c"]
    store = TileStore(str(tmp_path))
    spilled = SpilledResidualTable(
        base, names, plan, store, HostTileCache()
    )
    resident = TiledResidualTable(base, names, plan)
    for name in names:
        scores = rng.standard_normal(n).astype(np.float32) * 10
        spilled.update(name, scores)
        resident.update(name, scores)
    for name in names:
        np.testing.assert_array_equal(
            spilled.offsets_full(name), resident.offsets_full(name)
        )
        np.testing.assert_array_equal(
            spilled.scores_for(name), resident.scores_for(name)
        )
    np.testing.assert_array_equal(
        spilled.composite_full(), resident.composite_full()
    )
    assert spilled.tile_digests() == resident.tile_digests()
    assert spilled.snapshot_rows() == {}  # referenced, not re-saved
    # Write-back batching (ISSUE 17): the three per-coordinate updates
    # of each tile coalesce into ONE store publish at flush time.
    assert spilled.flush() == plan.num_chunks
    assert spilled.flush() == 0  # idempotent: nothing left dirty
    # A second table attaches to the published tiles exactly.
    attached = SpilledResidualTable(
        base, names, plan, store, HostTileCache()
    )
    assert attached.attach_resume() == []
    assert attached.tile_digests() == resident.tile_digests()
    np.testing.assert_array_equal(
        attached.offsets_full("b"), resident.offsets_full("b")
    )
    # reset_store drops back to the implicit zero state.
    attached.reset_store()
    assert attached.attach_resume() == list(range(plan.num_chunks))
    np.testing.assert_array_equal(
        attached.scores_for("a"), np.zeros(n, np.float32)
    )


# -- compensated accumulator (ISSUE 11 satellite) ----------------------------

def test_neumaier_accumulator_matches_fsum():
    rng = np.random.default_rng(0)
    values = (rng.standard_normal(500) * 10.0 ** rng.integers(
        -6, 7, 500
    )).astype(np.float64)
    grads = rng.standard_normal((500, 3)) * values[:, None]
    acc = NeumaierAccumulator(3)
    for v, g in zip(values, grads):
        acc.add(float(v), g)
    assert acc.value == pytest.approx(math.fsum(values), abs=0.0, rel=1e-15)
    for j in range(3):
        want = math.fsum(grads[:, j])
        assert acc.grad[j] == pytest.approx(want, abs=1e-12 * max(
            1.0, abs(want)
        ))
