"""Device-resident residual engine (game/residuals.py): parity with the
seed's host float64 path, donation safety, and mode resolution.

The ISSUE-2 acceptance bar: the device path's validation metrics must pin to
the host-path reference within 1e-4 on a synthetic GAME fit, and donated
score-table buffers must never be read after donation (scores reproducible
across two identical runs).
"""

from __future__ import annotations

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from photon_tpu.core.objective import RegularizationContext  # noqa: E402
from photon_tpu.core.optimizers import OptimizerConfig  # noqa: E402
from photon_tpu.core.problem import ProblemConfig  # noqa: E402
from photon_tpu.data.synthetic import make_game_dataset  # noqa: E402
from photon_tpu.game.coordinate import (  # noqa: E402
    FixedEffectCoordinateConfig,
    RandomEffectCoordinateConfig,
)
from photon_tpu.game.data import split_game_dataset  # noqa: E402
from photon_tpu.game.estimator import (  # noqa: E402
    GameEstimator,
    GameOptimizationConfiguration,
)
from photon_tpu.game.residuals import (  # noqa: E402
    HostResiduals,
    ResidualEngine,
    resolve_residual_mode,
)
from photon_tpu.telemetry import TelemetrySession  # noqa: E402


# ---------------------------------------------------------------------------
# Engine-level parity and donation safety
# ---------------------------------------------------------------------------


def _random_scores(n: int, n_coords: int, seed: int = 0) -> list:
    rng = np.random.default_rng(seed)
    # Spread magnitudes so compensated summation has real work to do.
    return [
        (rng.standard_normal(n) * 10.0 ** (i - 1)).astype(np.float32)
        for i in range(n_coords)
    ]


def test_engine_offsets_match_host_reference():
    n, names = 257, ["a", "b", "c", "d"]
    rng = np.random.default_rng(3)
    base = rng.standard_normal(n).astype(np.float32)
    scores = _random_scores(n, len(names), seed=4)

    engine = ResidualEngine(base, names=names)
    host = HostResiduals(base)
    for name, s in zip(names, scores):
        engine.update(name, jnp.asarray(s))
        host.update(name, s)

    for name in names:
        dev = np.asarray(engine.offsets_for(name))
        ref = host.offsets_for(name)
        np.testing.assert_allclose(dev, ref, rtol=0, atol=1e-4)


def test_engine_partial_updates_exclude_own_row():
    n = 64
    base = np.zeros(n, np.float32)
    engine = ResidualEngine(base, names=["x", "y"])
    sx = np.full(n, 2.0, np.float32)
    engine.update("x", jnp.asarray(sx))
    # y's offsets see x's scores; x's offsets see only zeros (y unset).
    np.testing.assert_allclose(np.asarray(engine.offsets_for("y")), sx)
    np.testing.assert_allclose(
        np.asarray(engine.offsets_for("x")), np.zeros(n, np.float32)
    )


def test_engine_update_rejects_bad_shape_and_duplicate_names():
    engine = ResidualEngine(np.zeros(8, np.float32), names=["a"])
    with pytest.raises(ValueError, match="shape"):
        engine.update("a", jnp.zeros(9, jnp.float32))
    with pytest.raises(ValueError, match="duplicate"):
        ResidualEngine(np.zeros(8, np.float32), names=["a", "a"])
    with pytest.raises(ValueError, match="at least one"):
        ResidualEngine(np.zeros(8, np.float32), names=[])


def test_donation_safety_two_runs_identical():
    """Updates donate the score table; a second identical run must produce
    bit-identical offsets (any use-after-donate would corrupt or raise)."""
    n, names = 513, ["f", "r0", "r1"]
    base = np.linspace(-1, 1, n).astype(np.float32)
    score_seq = [_random_scores(n, len(names), seed=s) for s in (7, 8, 9)]

    def run() -> list:
        engine = ResidualEngine(base, names=names)
        outs = []
        for scores in score_seq:  # three "descent iterations"
            for name, s in zip(names, scores):
                outs.append(np.asarray(engine.offsets_for(name)).copy())
                engine.update(name, jnp.asarray(s))
        outs.append(np.asarray(engine.scores_for("r1")).copy())
        return outs

    first, second = run(), run()
    assert len(first) == len(second)
    for a, b in zip(first, second):
        np.testing.assert_array_equal(a, b)


def test_engine_compensated_sum_beats_naive_f32():
    """The Neumaier total must recover a small signal buried under large
    cancelling rows — the regime where naive f32 accumulation loses the
    parity the host float64 path provides."""
    n = 128
    big = np.full(n, 3e7, np.float32)
    small = np.full(n, 0.5, np.float32)
    engine = ResidualEngine(
        np.zeros(n, np.float32), names=["big", "neg", "small", "probe"]
    )
    engine.update("big", jnp.asarray(big))
    engine.update("neg", jnp.asarray(-big))
    engine.update("small", jnp.asarray(small))
    # Σ other = big - big + small: exact answer 0.5 everywhere.
    out = np.asarray(engine.offsets_for("probe"))
    np.testing.assert_allclose(out, small, rtol=0, atol=1e-6)


def test_engine_counts_one_upload_and_tracks_updates():
    session = TelemetrySession("test-residuals")
    base = np.zeros(100, np.float32)
    engine = ResidualEngine(base, names=["a", "b"], telemetry=session)
    engine.update("a", jnp.ones(100, jnp.float32))
    engine.offsets_for("b")
    h2d = session.counter(
        "descent.host_transfer_bytes", direction="h2d", path="residuals"
    ).value
    assert h2d == base.nbytes  # the one-time base upload; device rows free
    assert session.counter("residuals.updates", coordinate="a").value == 1


# ---------------------------------------------------------------------------
# Mode resolution
# ---------------------------------------------------------------------------


def test_resolve_residual_mode(monkeypatch):
    monkeypatch.delenv("PHOTON_RESIDUALS", raising=False)
    assert resolve_residual_mode() == "device"
    assert resolve_residual_mode("host") == "host"
    monkeypatch.setenv("PHOTON_RESIDUALS", "host")
    assert resolve_residual_mode() == "host"
    # Explicit argument wins over the env var.
    assert resolve_residual_mode("device") == "device"
    monkeypatch.setenv("PHOTON_RESIDUALS", "nonsense")
    with pytest.raises(ValueError, match="residual mode"):
        resolve_residual_mode()


def test_resolve_residual_mode_multiprocess(monkeypatch):
    """The sharded engine is multi-controller safe: ``auto`` stays
    ``device`` under multi-process runs (the PR-2 single-controller engine
    used to fall back to host there) and an explicit ``device`` request is
    legal."""
    import photon_tpu.game.residuals as residuals_mod

    monkeypatch.delenv("PHOTON_RESIDUALS", raising=False)
    monkeypatch.setattr(residuals_mod.jax, "process_count", lambda: 2)
    assert resolve_residual_mode() == "device"
    assert resolve_residual_mode("auto") == "device"
    assert resolve_residual_mode("host") == "host"
    assert resolve_residual_mode("device") == "device"


def test_resolve_validation_mode(monkeypatch):
    """``auto`` follows the residual mode; explicit flag / env override."""
    from photon_tpu.game.residuals import resolve_validation_mode

    monkeypatch.delenv("PHOTON_VALIDATION", raising=False)
    assert resolve_validation_mode() == "device"
    assert resolve_validation_mode(residual_mode="host") == "host"
    assert resolve_validation_mode("device", residual_mode="host") == "device"
    assert resolve_validation_mode("host", residual_mode="device") == "host"
    monkeypatch.setenv("PHOTON_VALIDATION", "host")
    assert resolve_validation_mode(residual_mode="device") == "host"
    # Explicit argument wins over the env var.
    assert resolve_validation_mode("device", residual_mode="host") == "device"
    monkeypatch.setenv("PHOTON_VALIDATION", "nonsense")
    with pytest.raises(ValueError, match="validation mode"):
        resolve_validation_mode()


# ---------------------------------------------------------------------------
# End-to-end parity on a synthetic GAME fit
# ---------------------------------------------------------------------------


def _fit_metrics(mode: str) -> dict:
    data, _ = make_game_dataset(30, 10, 6, 4, seed=11, n_random_coords=2)
    train, val = split_game_dataset(data, 0.25)

    def problem(lam: float, max_iters: int) -> ProblemConfig:
        return ProblemConfig(
            regularization=RegularizationContext("l2", lam),
            optimizer_config=OptimizerConfig(max_iterations=max_iters),
        )

    config = GameOptimizationConfiguration(
        coordinates={
            "fixed": FixedEffectCoordinateConfig("global", problem(0.01, 40)),
            "re0": RandomEffectCoordinateConfig("re0", "re0", problem(1.0, 20)),
            "re1": RandomEffectCoordinateConfig("re1", "re1", problem(1.0, 20)),
        },
        descent_iterations=2,
    )
    estimator = GameEstimator(
        "logistic_regression", train, val, residual_mode=mode
    )
    return estimator.fit([config])[0].metrics


def test_score_device_foreign_model_uses_model_layout():
    """score_device must honor the MODEL's shard/entity layout: a foreign
    warm start (different shard_name/entity_column than the coordinate's
    config) falls back to the model's own host scoring path instead of
    silently scoring against the coordinate's cached device features."""
    import dataclasses

    from photon_tpu.game.coordinate import build_coordinate

    data, _ = make_game_dataset(20, 6, 6, 4, seed=5, n_random_coords=2)
    coord = build_coordinate(
        data,
        RandomEffectCoordinateConfig(
            "re0", "re0",
            ProblemConfig(
                regularization=RegularizationContext("l2", 1.0),
                optimizer_config=OptimizerConfig(max_iterations=5),
            ),
        ),
        "logistic_regression",
    )
    model, _ = coord.train(np.zeros(data.num_examples, np.float32))
    np.testing.assert_allclose(
        np.asarray(coord.score_device(model)), model.score(data), atol=1e-5
    )
    foreign = dataclasses.replace(model, shard_name="re1", entity_column="re1")
    np.testing.assert_allclose(
        np.asarray(coord.score_device(foreign)), foreign.score(data),
        atol=1e-5,
    )


def test_game_fit_device_matches_host_within_1e4():
    host = _fit_metrics("host")
    device = _fit_metrics("device")
    assert host and device
    for name, ref in host.items():
        assert abs(device[name] - ref) < 1e-4, (name, device[name], ref)
