"""End-to-end driver tests on tiny fixtures — the reference's full-driver
integration tests (SURVEY.md §4): train → files exist → metrics pass
thresholds → score round-trip."""

import json
import os

import numpy as np
import pytest

from photon_tpu.data.synthetic import make_glm_data, write_libsvm
from photon_tpu.drivers import score as score_driver
from photon_tpu.drivers import train as train_driver


@pytest.fixture(scope="module")
def libsvm_files(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("libsvm")
    batch, _ = make_glm_data(400, 13, task="logistic_regression", seed=1)
    x = np.asarray(batch.x)[:, :-1]  # drop intercept column; driver re-adds
    y = np.asarray(batch.label)
    train_p, val_p = str(tmp / "train.libsvm"), str(tmp / "val.libsvm")
    write_libsvm(train_p, x[:300], y[:300])
    write_libsvm(val_p, x[300:], y[300:])
    return train_p, val_p


def test_train_driver_end_to_end(libsvm_files, tmp_path):
    train_p, val_p = libsvm_files
    out = str(tmp_path / "out")
    summary = train_driver.run(train_driver.build_parser().parse_args([
        "--input", train_p, "--validation-input", val_p,
        "--task", "logistic_regression", "--optimizer", "lbfgs",
        "--reg-type", "l2", "--reg-weights", "0.1,1.0,10.0",
        "--output-dir", out, "--backend", "cpu",
        "--save-all-models", "--variance-computation", "simple",
    ]))
    assert os.path.exists(os.path.join(out, "best_model.avro"))
    assert os.path.exists(os.path.join(out, "feature_index.json"))
    assert os.path.exists(os.path.join(out, "model_lambda_0.1.avro"))
    with open(os.path.join(out, "training_summary.json")) as f:
        persisted = json.load(f)
    assert persisted["best_lambda"] == summary["best_lambda"]
    # Model should beat chance comfortably on separable-ish synthetic data.
    aucs = [e["metrics"]["AUC"] for e in summary["sweep"]]
    assert max(aucs) > 0.7
    # Lambda sweep must actually produce different models.
    assert len({e["final_value"] for e in summary["sweep"]}) == 3
    # Per-lambda diagnostic report artifacts (the reference's deprecated
    # diagnostic reports — SURVEY.md §3.2; VERDICT r3 item 8).
    for lam in ("0.1", "1", "10"):
        path = os.path.join(out, "diagnostics", f"report_lambda_{lam}.json")
        assert os.path.exists(path), path
        with open(path) as f:
            report = json.load(f)
        assert report["convergence_trace"], "trace must be recorded"
        assert report["coefficients"]["dim"] == 13  # 12 features + intercept
        assert "AUC" in report["metrics"]
    assert os.path.exists(os.path.join(out, "diagnostics", "report.md"))


def test_train_score_round_trip(libsvm_files, tmp_path):
    train_p, val_p = libsvm_files
    out = str(tmp_path / "out")
    train_driver.run(train_driver.build_parser().parse_args([
        "--input", train_p, "--task", "logistic_regression",
        "--reg-weights", "1.0", "--output-dir", out, "--backend", "cpu",
    ]))
    score_out = str(tmp_path / "scores")
    result = score_driver.run(score_driver.build_parser().parse_args([
        "--input", val_p, "--model", os.path.join(out, "best_model.avro"),
        "--output-dir", score_out, "--backend", "cpu",
        "--evaluators", "AUC,LOGISTIC_LOSS",
    ]))
    assert result["num_scored"] == 100
    assert result["metrics"]["AUC"] > 0.7
    scores = np.loadtxt(os.path.join(score_out, "scores.txt"))
    assert scores.shape == (100,)


def test_train_driver_owlqn_sparsifies(tmp_path):
    out = str(tmp_path / "out")
    summary = train_driver.run(train_driver.build_parser().parse_args([
        "--input", "synthetic:linear_regression:300:10:3",
        "--task", "linear_regression", "--optimizer", "owlqn",
        "--reg-type", "elastic_net", "--reg-weights", "30.0",
        "--output-dir", out, "--backend", "cpu", "--model-format", "json",
    ]))
    with open(os.path.join(out, "best_model.json")) as f:
        record = json.load(f)
    # Sparse storage: OWL-QN must have zeroed some coefficients, and zeros
    # are dropped on save (10 features + intercept, minus exact zeros).
    assert len(record["means"]) < 11
    assert summary["sweep"][0]["convergence_reason"] in (
        "FUNCTION_VALUES_TOLERANCE", "GRADIENT_TOLERANCE", "MAX_ITERATIONS",
        "OBJECTIVE_NOT_IMPROVING",
    )


def test_train_driver_tron_poisson(tmp_path):
    out = str(tmp_path / "out")
    summary = train_driver.run(train_driver.build_parser().parse_args([
        "--input", "synthetic:poisson_regression:300:8:4:77",
        "--validation-input", "synthetic:poisson_regression:300:8:5:77",
        "--task", "poisson_regression", "--optimizer", "tron",
        "--reg-type", "l2", "--reg-weights", "1.0",
        "--output-dir", out, "--backend", "cpu",
    ]))
    # Poisson loss on validation should beat the intercept-only baseline.
    assert summary["sweep"][0]["metrics"]["POISSON_LOSS"] < 2.0


def test_score_no_intercept_model(tmp_path):
    # The score driver must take intercept presence from the index map, not
    # the CLI flag: a model trained with --no-intercept scored with default
    # flags would otherwise shift feature ids (review finding).
    batch, _ = make_glm_data(300, 12, task="logistic_regression", seed=3,
                             intercept=False)
    x, y = np.asarray(batch.x), np.asarray(batch.label)
    train_p = str(tmp_path / "train.libsvm")
    write_libsvm(train_p, x, y)
    out = str(tmp_path / "out")
    train_driver.run(train_driver.build_parser().parse_args([
        "--input", train_p, "--task", "logistic_regression",
        "--reg-weights", "1.0", "--output-dir", out, "--backend", "cpu",
        "--no-intercept",
    ]))
    score_out = str(tmp_path / "scores")
    result = score_driver.run(score_driver.build_parser().parse_args([
        "--input", train_p, "--model", os.path.join(out, "best_model.avro"),
        "--output-dir", score_out, "--backend", "cpu", "--evaluators", "AUC",
    ]))
    # With the flag mistakenly trusted, ids shift and AUC collapses.
    assert result["metrics"]["AUC"] > 0.7


def test_score_rejects_sharded_evaluators_before_scoring(tmp_path):
    batch, _ = make_glm_data(100, 8, task="logistic_regression", seed=4)
    x, y = np.asarray(batch.x)[:, :-1], np.asarray(batch.label)
    train_p = str(tmp_path / "train.libsvm")
    write_libsvm(train_p, x, y)
    out = str(tmp_path / "out")
    train_driver.run(train_driver.build_parser().parse_args([
        "--input", train_p, "--task", "logistic_regression",
        "--reg-weights", "1.0", "--output-dir", out, "--backend", "cpu",
    ]))
    score_out = str(tmp_path / "scores")
    with pytest.raises(ValueError, match="entity ids"):
        score_driver.run(score_driver.build_parser().parse_args([
            "--input", train_p, "--model", os.path.join(out, "best_model.avro"),
            "--output-dir", score_out, "--backend", "cpu",
            "--evaluators", "SHARDED_AUC:user",
        ]))
    # The guard must fire before any scoring output is written.
    assert not os.path.exists(os.path.join(score_out, "scores.txt"))


def test_a1a_fixture_anchor(tmp_path):
    """The committed a1a-statistics fixture is a determinism anchor: a
    regression in loss/optimizer/data plumbing moves its held-out AUC
    (BASELINE.md round-3 table)."""
    from photon_tpu.data.fixtures import a1a_fixture_paths
    from photon_tpu.drivers import train

    train_path, test_path = a1a_fixture_paths()
    summary = train.run(train.build_parser().parse_args([
        "--backend", "cpu",
        "--input", train_path, "--validation-input", test_path,
        "--task", "logistic_regression", "--optimizer", "lbfgs",
        "--reg-type", "l2", "--reg-weights", "1.0",
        "--max-iterations", "100",
        "--output-dir", str(tmp_path / "out"),
    ]))
    auc = summary["sweep"][0]["metrics"]["AUC"]
    assert 0.80 < auc < 0.87, f"a1a fixture AUC anchor moved: {auc}"


@pytest.mark.parametrize("forward", [False, True])
def test_train_driver_pallas_kernel_a1a(tmp_path, monkeypatch, forward):
    """PHOTON_SPARSE_GRAD=pallas trains a1a end-to-end through the
    slab-aligned Mosaic kernel (interpret mode on CPU) and reaches the same
    AUC band as the fm path (VERDICT r3 item 2 'done' criterion).  With
    PHOTON_SPARSE_MARGIN=pallas the margins also route through the
    transposed layout (full fwd+bwd Pallas sparse pipeline)."""
    from photon_tpu.data.fixtures import a1a_fixture_paths

    monkeypatch.setenv("PHOTON_SPARSE_GRAD", "pallas")
    if forward:
        monkeypatch.setenv("PHOTON_SPARSE_MARGIN", "pallas")
    else:
        # An ambient PHOTON_SPARSE_MARGIN would silently collapse both
        # params onto the same path.
        monkeypatch.delenv("PHOTON_SPARSE_MARGIN", raising=False)
    train_path, test_path = a1a_fixture_paths()
    summary = train_driver.run(train_driver.build_parser().parse_args([
        "--backend", "cpu",
        "--input", train_path, "--validation-input", test_path,
        "--task", "logistic_regression", "--optimizer", "lbfgs",
        "--reg-type", "l2", "--reg-weights", "1.0",
        "--max-iterations", "25",
        "--output-dir", str(tmp_path / "out"),
    ]))
    auc = summary["sweep"][0]["metrics"]["AUC"]
    assert 0.80 < auc < 0.87, f"pallas-path a1a AUC out of band: {auc}"


def test_score_stream_matches_whole(libsvm_files, tmp_path):
    """score --stream over part files == whole-set scoring, exactly."""
    train_p, val_p = libsvm_files
    out = str(tmp_path / "model")
    train_driver.run(train_driver.build_parser().parse_args([
        "--input", train_p, "--task", "logistic_regression",
        "--reg-weights", "1.0", "--max-iterations", "30",
        "--output-dir", out, "--backend", "cpu",
    ]))

    # Split the validation file into 3 uneven parts.
    lines = open(val_p).read().splitlines(keepends=True)
    parts = tmp_path / "parts"
    parts.mkdir()
    cuts = [0, 13, 60, len(lines)]
    for pi in range(3):
        with open(parts / f"part-{pi}.libsvm", "w") as f:
            f.writelines(lines[cuts[pi]:cuts[pi + 1]])

    common_args = [
        "--model", os.path.join(out, "best_model.avro"),
        "--backend", "cpu",
        "--evaluators", "AUC",
    ]
    whole = score_driver.run(score_driver.build_parser().parse_args(
        common_args + ["--input", val_p,
                       "--output-dir", str(tmp_path / "w")]))
    streamed = score_driver.run(score_driver.build_parser().parse_args(
        common_args + ["--input", str(parts / "*.libsvm"), "--stream",
                       "--output-dir", str(tmp_path / "s")]))
    assert streamed["streamed"] and streamed["num_scored"] == whole["num_scored"]
    sw = np.loadtxt(tmp_path / "w" / "scores.txt")
    ss = np.loadtxt(tmp_path / "s" / "scores.txt")
    np.testing.assert_array_equal(sw, ss)
    assert streamed["metrics"]["AUC"] == pytest.approx(
        whole["metrics"]["AUC"], rel=1e-9
    )


def test_sweep_warm_start_reduces_iterations(libsvm_files, tmp_path):
    """The regularization path warm start must land on the same optima with
    fewer total iterations than cold starts."""
    train_p, _ = libsvm_files
    totals, finals = {}, {}
    for mode, flag in (("warm", "--sweep-warm-start"),
                       ("cold", "--no-sweep-warm-start")):
        out = str(tmp_path / mode)
        summary = train_driver.run(train_driver.build_parser().parse_args([
            "--input", train_p, "--task", "logistic_regression",
            "--reg-weights", "10,3,1,0.3", "--max-iterations", "200",
            flag, "--output-dir", out, "--backend", "cpu",
        ]))
        totals[mode] = sum(e["iterations"] for e in summary["sweep"])
        finals[mode] = [e["final_value"] for e in summary["sweep"]]
    np.testing.assert_allclose(finals["warm"], finals["cold"], rtol=1e-4)
    assert totals["warm"] < totals["cold"], totals


def test_real_data_dir_hooks(tmp_path, monkeypatch):
    """PHOTON_REAL_DATA_DIR switches fixtures to operator-provided real
    datasets (VERDICT r3 item 9 infrastructure): a1a paths resolve to the
    verbatim files, and MovieLens-1M .dat files parse into the GAME layout
    (label = rating >= 4, genre indicator shards)."""
    from photon_tpu.data.fixtures import a1a_fixture_paths, movielens_dataset

    # Without the env (or with files missing), fixtures back everything.
    monkeypatch.delenv("PHOTON_REAL_DATA_DIR", raising=False)
    tr, te = a1a_fixture_paths()
    assert tr.endswith("a1a.libsvm")
    monkeypatch.setenv("PHOTON_REAL_DATA_DIR", str(tmp_path))
    tr2, _ = a1a_fixture_paths()
    assert tr2.endswith("a1a.libsvm"), "missing real files must fall back"

    # Drop in miniature verbatim-format real files.
    (tmp_path / "a1a").write_text("-1 3:1 11:1\n+1 5:1 77:1\n")
    (tmp_path / "a1a.t").write_text("+1 4:1\n")
    ml = tmp_path / "ml-1m"
    ml.mkdir()
    (ml / "movies.dat").write_text(
        "1::Toy Story (1995)::Animation|Children's|Comedy\n"
        "2::Jumanji (1995)::Adventure|Children's|Fantasy\n",
        encoding="latin-1",
    )
    (ml / "ratings.dat").write_text(
        "1::1::5::978300760\n1::2::3::978302109\n2::1::4::978301968\n",
        encoding="latin-1",
    )

    tr3, te3 = a1a_fixture_paths()
    assert tr3 == str(tmp_path / "a1a") and te3 == str(tmp_path / "a1a.t")

    data, maps = movielens_dataset()
    assert data.num_examples == 3
    np.testing.assert_array_equal(data.label, [1.0, 0.0, 1.0])
    np.testing.assert_array_equal(data.id_columns["userId"], [1, 1, 2])
    x = data.shard("global").x
    assert x.shape == (3, 19)  # 18 genres + intercept
    # Row 0 rates movie 1: Animation + Children's + Comedy set.
    assert x[0].sum() == 4.0 and x[0, -1] == 1.0
    assert maps["per_user"].intercept_id is not None
