"""Hyperparameter search tests (reference: photon-lib hyperparameter/ —
GaussianProcessSearch with Matérn-5/2 + EI, RandomSearch; SURVEY.md §2.1)."""

import numpy as np
import pytest

from photon_tpu.hyperparameter import (
    GaussianProcessSearch,
    RandomSearch,
    SearchDimension,
    SearchSpace,
)
from photon_tpu.hyperparameter.search import (
    _expected_improvement,
    _gp_posterior,
    _matern52,
)

import jax.numpy as jnp


def test_dimension_unit_round_trip():
    d = SearchDimension("lam", 1e-4, 1e2, log_scale=True)
    for v in (1e-4, 1e-2, 1.0, 1e2):
        assert np.isclose(d.from_unit(d.to_unit(v)), v, rtol=1e-12)
    lin = SearchDimension("x", -2.0, 4.0)
    assert np.isclose(lin.to_unit(1.0), 0.5)
    with pytest.raises(ValueError):
        SearchDimension("bad", 1.0, 1.0)
    with pytest.raises(ValueError):
        SearchDimension("bad", 0.0, 1.0, log_scale=True)


def test_matern_kernel_properties():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.random((12, 3)))
    k = np.asarray(_matern52(x, x, jnp.asarray(0.5), jnp.asarray(1.0)))
    # Symmetric, unit diagonal, PSD.
    np.testing.assert_allclose(k, k.T, atol=1e-12)
    np.testing.assert_allclose(np.diag(k), 1.0, atol=1e-6)
    assert np.linalg.eigvalsh(k).min() > -1e-8


def test_gp_posterior_interpolates_observations():
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.random((8, 1)))
    y = jnp.sin(4.0 * x[:, 0])
    mean, std = _gp_posterior(
        x, y, x, jnp.asarray(0.5), jnp.asarray(1.0), jnp.asarray(1e-8)
    )
    np.testing.assert_allclose(np.asarray(mean), np.asarray(y), atol=1e-3)
    assert np.all(np.asarray(std) < 1e-2)


def test_expected_improvement_nonnegative_and_monotone():
    mean = jnp.asarray([0.0, 0.5, 2.0])
    std = jnp.asarray([1.0, 1.0, 1.0])
    ei = np.asarray(_expected_improvement(mean, std, jnp.asarray(1.0)))
    assert np.all(ei >= 0)
    assert ei[0] > ei[1] > ei[2]  # lower predicted mean -> more improvement


def quadratic_1d(params):
    x = params["x"]
    return (x - 0.62) ** 2


def test_random_search_reproducible_and_improves():
    space = SearchSpace([SearchDimension("x", 0.0, 1.0)])
    s1 = RandomSearch(space, quadratic_1d, seed=7)
    s2 = RandomSearch(space, quadratic_1d, seed=7)
    best1, best2 = s1.find(20), s2.find(20)
    assert best1.params == best2.params
    assert best1.value < 0.05


def test_gp_search_beats_random_on_smooth_objective():
    space = SearchSpace([SearchDimension("x", 0.0, 1.0)])
    gp = GaussianProcessSearch(space, quadratic_1d, seed=11, num_seed_trials=3)
    best = gp.find(12)
    # Matches/beats random search's accuracy with the same budget.
    assert best.value < 1e-2
    # Trials after seeding concentrate near the optimum.
    late = [abs(r.params["x"] - 0.62) for r in gp.history[6:]]
    assert min(late) < 0.05


def test_gp_search_maximize_direction():
    space = SearchSpace([SearchDimension("x", 0.0, 1.0)])
    gp = GaussianProcessSearch(
        space, lambda p: -((p["x"] - 0.3) ** 2), maximize=True, seed=5
    )
    best = gp.find(12)
    assert abs(best.params["x"] - 0.3) < 0.1


def test_gp_search_2d_log_dim():
    space = SearchSpace([
        SearchDimension("lam1", 1e-3, 1e3, log_scale=True),
        SearchDimension("lam2", 1e-3, 1e3, log_scale=True),
    ])

    def objective(p):
        # Minimum at lam1=1, lam2=10 in log space.
        return (np.log10(p["lam1"]) - 0.0) ** 2 + (np.log10(p["lam2"]) - 1.0) ** 2

    best = GaussianProcessSearch(space, objective, seed=3).find(18)
    assert best.value < 0.5


def test_train_game_driver_bayesian_tuning(tmp_path):
    from photon_tpu.drivers import train_game

    out = str(tmp_path / "out")
    summary = train_game.run(train_game.build_parser().parse_args([
        "--backend", "cpu",
        "--input", "synthetic-game:32:4:8:4:1:9",
        "--coordinate", "fixed:type=fixed,shard=global,max_iters=8",
        "--coordinate", "per_user:type=random,shard=re0,entity=re0,max_iters=5",
        "--validation-split", "0.25",
        "--tuning", "bayesian",
        "--tuning-iterations", "3",
        "--tuning-range", "0.01:100",
        "--output-dir", out,
    ]))
    assert len(summary["sweep"]) == 3
    assert summary["best_metrics"]["AUC"] > 0.55
