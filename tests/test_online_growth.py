"""In-place device-data growth for EXISTING entities (ISSUE 15 blocker
fix): per-bin row-capacity headroom writes, entity migration past
exhausted capacity, absent-row masks, atomicity, and the capacity-headroom
accounting gauges — in isolation from the online service."""

from __future__ import annotations

import numpy as np
import pytest

from photon_tpu.core.objective import RegularizationContext
from photon_tpu.core.optimizers import OptimizerConfig
from photon_tpu.core.problem import ProblemConfig
from photon_tpu.data.synthetic import make_game_data
from photon_tpu.game.coordinate import (
    FixedEffectCoordinateConfig,
    RandomEffectCoordinate,
    RandomEffectCoordinateConfig,
    RandomEffectDeviceData,
)
from photon_tpu.game.data import DenseShard, GameDataset
from photon_tpu.game.estimator import (
    GameEstimator,
    GameOptimizationConfiguration,
)
from photon_tpu.telemetry import TelemetrySession


def _problem(max_iterations=30):
    return ProblemConfig(
        regularization=RegularizationContext("l2", 1.0),
        optimizer_config=OptimizerConfig(max_iterations=max_iterations),
    )


def _config(**kw):
    return RandomEffectCoordinateConfig("pe", "uid", _problem(), **kw)


def _dataset(n_entities, seed, keep=None, fixed=False):
    raw = make_game_data(
        n_entities, 4, 5, 4, seed=seed,
        n_random_coords=1,
    )
    ids = raw["entity_ids"]["re0"]
    sel = slice(None) if keep is None else keep(ids)
    shards = {"pe": DenseShard(raw["x_random"]["re0"][sel])}
    if fixed:
        shards["global"] = DenseShard(raw["x_fixed"][sel])
    return GameDataset.create(
        raw["label"][sel], shards, id_columns={"uid": ids[sel]}
    )


def _grown(base, seed, existing_below=10, new_from=35, n_source=40):
    """Append rows for EXISTING entities (< existing_below) AND NEW
    entities (>= new_from) onto ``base``."""
    raw = make_game_data(n_source, 3, 5, 4, seed=seed, n_random_coords=1)
    ids = raw["entity_ids"]["re0"]
    keep = (ids < existing_below) | (ids >= new_from)
    shards = {"pe": DenseShard(np.concatenate([
        base.shards["pe"].x, raw["x_random"]["re0"][keep]
    ]))}
    if "global" in base.shards:
        shards["global"] = DenseShard(np.concatenate([
            base.shards["global"].x, raw["x_fixed"][keep]
        ]))
    return GameDataset.create(
        np.concatenate([base.label, raw["label"][keep]]),
        shards,
        id_columns={"uid": np.concatenate([base.id_columns["uid"],
                                           ids[keep]])},
    )


def _train(data, config, dd=None):
    coord = RandomEffectCoordinate(
        data, config, "logistic_regression", device_data=dd
    )
    model, stats = coord.train(np.zeros(data.num_examples, np.float32))
    return model, stats


# ---------------------------------------------------------------------------
# Device-data level: grown-in-place fit == full rebuild
# ---------------------------------------------------------------------------


def test_grow_existing_rows_matches_full_rebuild():
    """The blocker fix: appended rows for EXISTING entities scatter into
    the owning bins' row-capacity headroom — and the resulting fit matches
    a full rebuild of the device data ≤1e-5."""
    base = _dataset(30, seed=11)
    grown = _grown(base, seed=12)
    config = _config()
    session = TelemetrySession("t-grow")
    dd = RandomEffectDeviceData(base, config)
    n_bins = len(dd.buckets)
    dd.onboard(grown, telemetry=session)
    model, stats = _train(grown, config, dd)
    rebuilt, _ = _train(grown, config)
    np.testing.assert_array_equal(model.keys, rebuilt.keys)
    np.testing.assert_allclose(
        np.asarray(model.table), np.asarray(rebuilt.table),
        atol=1e-5, rtol=0,
    )
    assert stats["entities"] == dd.dataset.num_entities
    # Growth telemetry: existing-entity rows landed IN PLACE (the base
    # fixture's bins have pow2 headroom) and the new entities appended.
    counters = {
        (m["name"], (m.get("labels") or {}).get("column")): m["value"]
        for m in session.registry.snapshot()["counters"]
    }
    assert counters.get(("onboard.rows_in_place", "uid"), 0) > 0
    assert counters.get(("onboard.entities_new", "uid"), 0) > 0
    # Layout EXTENDED (appended bins for new/migrated entities), never
    # rebuilt from scratch.
    assert len(dd.buckets) >= n_bins


def test_repeated_growth_matches_full_rebuild():
    """Two successive onboards onto the SAME layout (steady-state online
    ingest) still match a from-scratch rebuild."""
    base = _dataset(30, seed=21)
    config = _config()
    dd = RandomEffectDeviceData(base, config)
    g1 = _grown(base, seed=22)
    dd.onboard(g1)
    g2 = _grown(g1, seed=23, existing_below=15, new_from=38, n_source=45)
    dd.onboard(g2)
    model, _ = _train(g2, config, dd)
    rebuilt, _ = _train(g2, config)
    np.testing.assert_allclose(
        np.asarray(model.table), np.asarray(rebuilt.table),
        atol=1e-5, rtol=0,
    )


def test_migration_when_bin_capacity_exhausted():
    """An entity whose appended rows exceed its bin's row capacity
    migrates to an appended bin at the next power of two; its old slot is
    neutralized (dummy index, zero weights) and the fit still matches a
    rebuild."""
    base = _dataset(20, seed=31)
    config = _config()
    dd = RandomEffectDeviceData(base, config)
    # One entity gets a LOT of new rows — guaranteed past any bin's
    # capacity in this fixture.
    rng = np.random.default_rng(7)
    n_new = 64
    grown = GameDataset.create(
        np.concatenate([base.label, (rng.random(n_new) < 0.5).astype(
            np.float32)]),
        {"pe": DenseShard(np.concatenate([
            base.shards["pe"].x,
            rng.normal(size=(n_new, 4)).astype(np.float32),
        ]))},
        id_columns={"uid": np.concatenate([
            base.id_columns["uid"],
            np.full(n_new, base.id_columns["uid"][0], np.int64),
        ])},
    )
    session = TelemetrySession("t-migrate")
    dd.onboard(grown, telemetry=session)
    counters = {
        m["name"]: m["value"]
        for m in session.registry.snapshot()["counters"]
    }
    assert counters.get("onboard.entities_migrated", 0) == 1
    assert counters.get("onboard.rows_migrated", 0) == n_new
    # The migrated entity appears in exactly ONE live slot.
    e = int(np.searchsorted(dd.dataset.keys, base.id_columns["uid"][0]))
    live_slots = sum(
        int((b.entity_index == e).sum()) for b in dd.buckets
    )
    assert live_slots == 1
    model, _ = _train(grown, config, dd)
    rebuilt, _ = _train(grown, config)
    np.testing.assert_allclose(
        np.asarray(model.table), np.asarray(rebuilt.table),
        atol=1e-5, rtol=0,
    )


def test_projected_config_grows_via_migration():
    """Per-bin projections (index_map) cannot accept in-place rows (the
    new rows would invalidate the bucket's feature transform): existing-
    entity growth routes through migration and still matches a rebuild."""
    base = _dataset(25, seed=41)
    grown = _grown(base, seed=42, existing_below=8, new_from=100)
    config = _config(projection="index_map")
    session = TelemetrySession("t-proj")
    dd = RandomEffectDeviceData(base, config)
    dd.onboard(grown, telemetry=session)
    counters = {
        m["name"]: m["value"]
        for m in session.registry.snapshot()["counters"]
    }
    assert counters.get("onboard.rows_in_place", 0) == 0
    assert counters.get("onboard.entities_migrated", 0) > 0
    model, _ = _train(grown, config, dd)
    rebuilt, _ = _train(grown, config)
    np.testing.assert_allclose(
        np.asarray(model.table), np.asarray(rebuilt.table),
        atol=1e-5, rtol=0,
    )


def test_active_row_cap_growth_stays_unbiased_and_finite():
    """Entities pushed past ``active_row_cap`` migrate with a per-entity
    seeded re-subsample and the cap's weight correction; the fit stays
    finite and covers every entity."""
    base = _dataset(25, seed=51)
    grown = _grown(base, seed=52, existing_below=8, new_from=100)
    config = _config(active_row_cap=4)
    dd = RandomEffectDeviceData(base, config)
    dd.onboard(grown)
    model, stats = _train(grown, config, dd)
    assert np.isfinite(np.asarray(model.table)).all()
    assert stats["entities"] == dd.dataset.num_entities
    # Unbiasedness accounting: a capped entity's kept rows carry the
    # count/cap correction.
    e = int(np.searchsorted(dd.dataset.keys, 0))
    total = int((dd.dataset.entity_idx_per_row == e).sum())
    if total > 4:
        for b in dd.buckets:
            slot = np.nonzero(b.entity_index == e)[0]
            if len(slot):
                w = b.row_weight[slot[0]]
                np.testing.assert_allclose(
                    w[w > 0], total / 4.0, rtol=1e-6
                )


def test_absent_rows_join_no_entity():
    """Rows masked absent (the online ingest's missing-id fill) keep
    per-row entity index -1 and no bin membership."""
    base = _dataset(20, seed=61)
    grown = _grown(base, seed=62)
    n_tail = grown.num_examples - base.num_examples
    dd = RandomEffectDeviceData(base, _config())
    dd.onboard(grown, absent_tail=np.ones(n_tail, bool))
    assert dd.dataset.num_entities == 20
    assert (dd.dataset.entity_idx_per_row[base.num_examples:] == -1).all()
    # Fit unchanged vs the base layout (the absent rows are invisible).
    model, _ = _train(grown, _config(), dd)
    base_model, _ = _train(base, _config())
    np.testing.assert_allclose(
        np.asarray(model.table), np.asarray(base_model.table),
        atol=1e-6, rtol=0,
    )


def test_capacity_headroom_gauges():
    """The onboard publishes per-bin capacity/live/headroom gauges — the
    accounting that says how much room the next append has."""
    base = _dataset(20, seed=71)
    grown = _grown(base, seed=72)
    session = TelemetrySession("t-headroom")
    dd = RandomEffectDeviceData(base, _config())
    dd.onboard(grown, telemetry=session)
    gauges = {
        (m["name"], (m.get("labels") or {}).get("bin")): m["value"]
        for m in session.registry.snapshot()["gauges"]
        if m["name"].startswith("onboard.bin_")
    }
    assert gauges, "no headroom gauges published"
    for i, st in enumerate(dd.bin_stats):
        cells = st["capacity"] * st["total_entities"]
        assert gauges[("onboard.bin_row_capacity", str(i))] == cells
        assert gauges[("onboard.bin_rows_live", str(i))] == st["live_rows"]
        assert gauges[("onboard.bin_row_headroom", str(i))] == (
            cells - st["live_rows"]
        )
        # Live rows actually live in the blocks (the gauge is honest).
        n_e = dd.dataset.num_entities
        live = sum(
            int((b.row_weight[b.entity_index < n_e] > 0).sum())
            for j, b in enumerate(dd.buckets) if j == i
        )
        assert live == st["live_rows"]


# ---------------------------------------------------------------------------
# Estimator level
# ---------------------------------------------------------------------------


def test_estimator_growth_matches_fresh_estimator():
    """Estimator-level: onboard (grown in place) + warm-started fit ==
    fresh estimator on the merged data + the same warm start, ≤1e-5 —
    with ZERO random-layout rebuilds counted."""
    from photon_tpu.game.model import GameModel

    base = _dataset(30, seed=81, fixed=True)
    grown = _grown(base, seed=82)
    config = GameOptimizationConfiguration(
        coordinates={
            "fixed": FixedEffectCoordinateConfig("global", _problem()),
            "per_entity": _config(),
        },
        descent_iterations=2,
    )
    session = TelemetrySession("t-est-grow")
    estimator = GameEstimator("logistic_regression", base,
                              telemetry=session)
    first = estimator.fit([config])[0]
    estimator.onboard_training_data(grown)
    dd = estimator._device_data_cache[
        config.coordinates["per_entity"].data_key
    ]
    warm = GameModel(
        {
            "fixed": first.model.coordinate("fixed"),
            "per_entity": first.model.coordinate("per_entity")
            .with_entities(dd.dataset.keys),
        },
        "logistic_regression",
    )
    second = estimator.fit([config], initial_model=warm)[0]
    fresh = GameEstimator("logistic_regression", grown).fit(
        [config], initial_model=warm
    )[0]
    for name in config.coordinates:
        got, want = second.model.coordinate(name), fresh.model.coordinate(name)
        got_t = getattr(got, "table", None)
        if got_t is None:
            got_t = got.coefficients.means
            want_t = want.coefficients.means
        else:
            want_t = want.table
        np.testing.assert_allclose(
            np.asarray(got_t), np.asarray(want_t), atol=1e-5, rtol=0
        )
    counters = [
        (m["name"], (m.get("labels") or {}).get("kind"), m["value"])
        for m in session.registry.snapshot()["counters"]
        if m["name"] == "estimator.device_data_rebuilds"
    ]
    assert not any(kind == "random" for _, kind, _ in counters)
    assert any(kind == "fixed" for _, kind, _ in counters)


def test_estimator_growth_is_atomic_on_rejected_batch():
    """Bin-migration atomicity: a batch one coordinate must reject (its
    feature shard is missing from the grown data) mutates NOTHING — the
    other coordinate's layout is not grown first."""
    raw = make_game_data(20, 4, 5, 4, seed=5, n_random_coords=2)
    base = GameDataset.create(
        raw["label"],
        {"re0": DenseShard(raw["x_random"]["re0"]),
         "re1": DenseShard(raw["x_random"]["re1"])},
        id_columns={"re0": raw["entity_ids"]["re0"],
                    "re1": raw["entity_ids"]["re1"]},
    )
    n_new = 6
    # Grown data LACKS re1's shard: the per-item layout must reject.
    grown = GameDataset.create(
        np.concatenate([base.label, base.label[:n_new]]),
        {"re0": DenseShard(np.concatenate([
            base.shards["re0"].x, base.shards["re0"].x[:n_new]
        ]))},
        id_columns={
            name: np.concatenate([col, col[:n_new]])
            for name, col in base.id_columns.items()
        },
    )
    config = GameOptimizationConfiguration(
        coordinates={
            "per_user": RandomEffectCoordinateConfig(
                "re0", "re0", _problem(5)
            ),
            "per_item": RandomEffectCoordinateConfig(
                "re1", "re1", _problem(5)
            ),
        },
        descent_iterations=1,
    )
    estimator = GameEstimator("logistic_regression", base)
    estimator.fit([config])
    with pytest.raises(KeyError, match="re1"):
        estimator.onboard_training_data(grown)
    for dd in estimator._device_data_cache.values():
        assert dd.dataset.num_entities == 20
        assert len(dd.dataset.entity_idx_per_row) == base.num_examples
    assert estimator.training_data is base
    estimator.fit([config])


def test_onboard_still_rejects_shrunk_data_and_bad_mask():
    base = _dataset(20, seed=91)
    dd = RandomEffectDeviceData(base, _config())
    from photon_tpu.game.data import take_rows

    with pytest.raises(ValueError, match="GROWN"):
        dd.onboard(take_rows(base, np.arange(base.num_examples - 5)))
    grown = _grown(base, seed=92)
    with pytest.raises(ValueError, match="absent_tail"):
        dd.onboard(grown, absent_tail=np.ones(3, bool))
    # Nothing mutated by the rejections.
    assert dd.dataset.num_entities == 20
    assert len(dd.dataset.entity_idx_per_row) == base.num_examples


def test_onboard_rejects_layout_kind_mismatch_before_mutating():
    """A dense appended shard over a sparse-built layout (or vice versa)
    is refused in check_onboard — BEFORE any remap/write — instead of
    crashing mid-apply with a half-mutated layout."""
    from photon_tpu.game.data import SparseShard

    rng = np.random.default_rng(5)
    n = 40
    sparse = SparseShard(
        rng.integers(0, 6, (n, 3)).astype(np.int32),
        rng.standard_normal((n, 3)).astype(np.float32),
        6,
    )
    base = GameDataset.create(
        (rng.random(n) < 0.5).astype(np.float32),
        {"pe": sparse},
        id_columns={"uid": np.repeat(np.arange(10, dtype=np.int64), 4)},
    )
    cfg = RandomEffectCoordinateConfig("pe", "uid", _problem())
    dd = RandomEffectDeviceData(base, cfg)
    keys_before = dd.dataset.keys
    grown = GameDataset.create(
        np.concatenate([base.label, base.label[:4]]),
        {"pe": DenseShard(np.zeros((n + 4, 6), np.float32))},  # DENSE
        id_columns={"uid": np.concatenate([
            base.id_columns["uid"],
            np.arange(100, 104, dtype=np.int64),
        ])},
    )
    with pytest.raises(ValueError, match="dense"):
        dd.onboard(grown)
    # Nothing mutated: same vocabulary object, same per-row map length.
    assert dd.dataset.keys is keys_before
    assert len(dd.dataset.entity_idx_per_row) == base.num_examples


def test_fixed_batch_row_capacity_zero_recompiles_across_refresh():
    """ISSUE 18 satellite: the fixed-effect training batch carries
    row-capacity headroom (weight-0 pad rows, amortized doubling), so an
    online refresh whose grown row count still fits the capacity rebuilds
    the batch at the SAME padded shape — the solve programs compiled
    against it stay hot (ZERO compile events on the refreshed train) —
    and the pad rows are exact (the padded fit matches an unpadded one).

    Pinned at the COORDINATE level: the descent loop's residual/validation
    engines are sized off the true row count by design (their elementwise
    kernels recompile cheaply per refresh); the expensive artifact this
    satellite protects is the fixed-effect BATCH and its solve."""
    import jax.monitoring
    from jax._src import monitoring as monitoring_src

    from photon_tpu.game.coordinate import (
        FixedEffectCoordinate,
        FixedEffectDeviceData,
    )
    from photon_tpu.utils import pow2_at_least

    base = _dataset(30, seed=71, fixed=True)
    g1 = _grown(base, seed=72)
    g2 = _grown(g1, seed=73)
    cfg = FixedEffectCoordinateConfig("global", _problem())
    cap = max(pow2_at_least(g1.num_examples), 2 * base.num_examples)
    assert g2.num_examples <= cap  # the refresh lands inside the headroom

    def train_at(data, row_capacity):
        dd = FixedEffectDeviceData(data, cfg, row_capacity=row_capacity)
        coord = FixedEffectCoordinate(
            data, cfg, "logistic_regression", device_data=dd
        )
        model, _ = coord.train(np.zeros(data.num_examples, np.float32))
        return dd, model

    dd1, _ = train_at(g1, cap)
    assert dd1.batch.num_examples == cap
    assert dd1.unpadded_n == g1.num_examples

    events = []

    def listener(event, **kwargs):
        if "compile" in event:
            events.append(event)

    jax.monitoring.register_event_listener(listener)
    try:
        # The refresh: MORE rows, SAME capacity — same batch shape, so
        # the rebuilt batch replays entirely against compiled programs.
        dd2, padded = train_at(g2, cap)
    finally:
        monitoring_src._unregister_event_listener_by_callback(listener)
    assert events == []
    assert dd2.batch.num_examples == cap
    assert dd2.unpadded_n == g2.num_examples

    # Pad rows are weight-0 and therefore EXACT: the capacity-padded fit
    # equals the unpadded fit on the same data.
    _, unpadded = train_at(g2, None)
    np.testing.assert_allclose(
        np.asarray(padded.coefficients.means),
        np.asarray(unpadded.coefficients.means),
        atol=1e-5, rtol=0,
    )


def test_estimator_fixed_row_capacity_amortized_doubling():
    """The estimator's capacity policy: the FIRST build is exact (no
    padding — existing single-fit flows see unchanged shapes); the first
    growth sets an amortized-doubled capacity; a later onboard that fits
    rebuilds at the SAME capacity (the coordinate-level zero-recompile
    contract above is what that buys)."""
    base = _dataset(30, seed=71, fixed=True)
    g1 = _grown(base, seed=72)
    g2 = _grown(g1, seed=73)
    config = GameOptimizationConfiguration(
        coordinates={
            "fixed": FixedEffectCoordinateConfig("global", _problem()),
        },
        descent_iterations=1,
    )
    estimator = GameEstimator("logistic_regression", base)
    estimator.fit([config])
    fixed_key = config.coordinates["fixed"].data_key
    assert estimator._fixed_row_capacity == {}  # no growth yet: exact
    batch0 = estimator._device_data_cache[fixed_key].batch
    assert batch0.num_examples == base.num_examples

    estimator.onboard_training_data(g1)
    estimator.fit([config])  # pays the ONE growth rebuild, sets capacity
    cap1 = estimator._fixed_row_capacity[fixed_key]
    dd1 = estimator._device_data_cache[fixed_key]
    assert cap1 >= g1.num_examples
    assert dd1.batch.num_examples == cap1
    assert dd1.unpadded_n == g1.num_examples
    assert g2.num_examples <= cap1

    estimator.onboard_training_data(g2)
    estimator.fit([config])
    assert estimator._fixed_row_capacity[fixed_key] == cap1
    dd2 = estimator._device_data_cache[fixed_key]
    assert dd2.batch.num_examples == cap1  # SAME padded shape
    assert dd2.unpadded_n == g2.num_examples
