"""Row-split random-effect solves: entities' rows sharded across the mesh.

The reference co-locates each entity's rows via shuffle before solving
(RandomEffectDatasetPartitioner); the row-split path solves each entity
EXACTLY while its rows stay where they were read, psum-ing per-entity data
terms across the mesh axis (parallel/distributed.RowSplitGlmObjective —
README §scale-out).  These tests pin exactness against co-located solves.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import Mesh

from photon_tpu.core.objective import GlmObjective, RegularizationContext
from photon_tpu.core.optimizers import OptimizerConfig
from photon_tpu.core.problem import GlmOptimizationProblem, ProblemConfig
from photon_tpu.data.batch import SparseBatch
from photon_tpu.parallel.distributed import solve_entities_row_split
from photon_tpu.parallel.mesh import DATA_AXIS


def _entity_batches(n_entities=6, rows=32, k=4, d=16, seed=0):
    """[E, R, ...] per-entity padded batches with ragged real row counts."""
    rng = np.random.default_rng(seed)
    ids = rng.integers(1, d, size=(n_entities, rows, k), dtype=np.int32)
    vals = rng.standard_normal((n_entities, rows, k)).astype(np.float32)
    label = (rng.random((n_entities, rows)) < 0.5).astype(np.float32)
    # Ragged: entity e has 8*(e%3+1) real rows; the rest are weight-0 pads
    # scattered ACROSS the row axis so every mesh shard sees some padding.
    weight = np.zeros((n_entities, rows), np.float32)
    for e in range(n_entities):
        real = 8 * (e % 3 + 1)
        keep = rng.choice(rows, size=real, replace=False)
        weight[e, keep] = rng.uniform(0.5, 2.0, real).astype(np.float32)
    return SparseBatch(
        jnp.asarray(ids), jnp.asarray(vals), jnp.asarray(label),
        jnp.zeros((n_entities, rows), jnp.float32), jnp.asarray(weight),
    )


@pytest.mark.parametrize("optimizer,reg_type", [
    ("lbfgs", "l2"), ("tron", "l2"), ("owlqn", "l1"),
])
def test_row_split_matches_colocated(optimizer, reg_type):
    batches = _entity_batches()
    d = 16
    reg = RegularizationContext(reg_type, 0.7)
    cfg = ProblemConfig(optimizer=optimizer, regularization=reg,
                        optimizer_config=OptimizerConfig(max_iterations=15))
    obj = GlmObjective.create("logistic", reg)
    w0s = jnp.zeros((batches.ids.shape[0], d), jnp.float32)

    # Co-located reference: plain vmapped solve, all rows on one device.
    ref_coeffs, ref_res = GlmOptimizationProblem(obj, cfg).solver(vmapped=True)(
        obj, batches, w0s
    )

    # Row-split: the SAME entities with rows sharded over all 8 devices.
    mesh = Mesh(np.asarray(jax.devices()), (DATA_AXIS,))
    split_coeffs, split_res = solve_entities_row_split(
        obj, cfg, batches, w0s, mesh
    )

    # psum reduction order differs from the co-located row-sum order;
    # optimizer trajectories amplify the f32 noise over iterations, so the
    # comparison is solver-trajectory-tolerance, not bitwise.
    np.testing.assert_allclose(
        np.asarray(split_coeffs.means), np.asarray(ref_coeffs.means),
        rtol=2e-2, atol=2e-3,
    )
    # Convergence FLAGS can flip near thresholds (TRON's accept/reject is a
    # hard comparison on psum-order-sensitive f32 values); what must agree
    # is the achieved objective.
    np.testing.assert_allclose(
        np.asarray(split_res.value), np.asarray(ref_res.value),
        rtol=1e-4, atol=1e-5,
    )


@pytest.mark.parametrize("variance,n_entities,seed,rtol", [
    ("simple", 6, 3, 5e-4),   # 1/diag(H): psum-ed Hessian diagonal
    ("full", 4, 7, 2e-3),     # diag(H^-1): psum-ed dense Hessian, Cholesky
])
def test_row_split_variance_matches(variance, n_entities, seed, rtol):
    """Variance computation under row-split must match co-located solves."""
    batches = _entity_batches(n_entities=n_entities, seed=seed)
    d = 16
    reg = RegularizationContext("l2", 1.0)
    cfg = ProblemConfig(optimizer="lbfgs", regularization=reg,
                        optimizer_config=OptimizerConfig(max_iterations=12),
                        variance_computation=variance)
    obj = GlmObjective.create("logistic", reg)
    w0s = jnp.zeros((n_entities, d), jnp.float32)
    ref_coeffs, _ = GlmOptimizationProblem(obj, cfg).solver(vmapped=True)(
        obj, batches, w0s
    )
    mesh = Mesh(np.asarray(jax.devices()), (DATA_AXIS,))
    split_coeffs, _ = solve_entities_row_split(obj, cfg, batches, w0s, mesh)
    np.testing.assert_allclose(
        np.asarray(split_coeffs.variances), np.asarray(ref_coeffs.variances),
        rtol=rtol, atol=1e-6,
    )


def test_row_split_rejects_indivisible_rows():
    batches = _entity_batches(rows=30)  # 30 % 8 != 0
    reg = RegularizationContext("l2", 1.0)
    cfg = ProblemConfig(optimizer="lbfgs", regularization=reg)
    obj = GlmObjective.create("logistic", reg)
    mesh = Mesh(np.asarray(jax.devices()), (DATA_AXIS,))
    with pytest.raises(ValueError, match="divisible by the mesh axis"):
        solve_entities_row_split(
            obj, cfg, batches, jnp.zeros((6, 16), jnp.float32), mesh
        )


def test_random_effect_coordinate_row_split_matches_entity_sharded():
    """RandomEffectCoordinate(row_split=True) must reproduce the default
    entity-sharded coordinate's model on the same mesh."""
    from jax.sharding import Mesh as _Mesh

    from photon_tpu.data.synthetic import make_game_dataset
    from photon_tpu.game.coordinate import (
        RandomEffectCoordinate,
        RandomEffectCoordinateConfig,
    )

    data, _ = make_game_dataset(12, 10, 8, 6, seed=5)
    cfg = ProblemConfig(optimizer="lbfgs",
                        regularization=RegularizationContext("l2", 1.0),
                        optimizer_config=OptimizerConfig(max_iterations=12))
    mesh = _Mesh(np.asarray(jax.devices()), (DATA_AXIS,))
    offsets = np.zeros(data.num_examples, np.float32)

    base = RandomEffectCoordinate(
        data,
        RandomEffectCoordinateConfig("re0", "re0", cfg),
        "logistic_regression",
        mesh=mesh,
    )
    model_base, _ = base.train(offsets)

    split = RandomEffectCoordinate(
        data,
        RandomEffectCoordinateConfig("re0", "re0", cfg, row_split=True),
        "logistic_regression",
        mesh=mesh,
    )
    model_split, stats = split.train(offsets)

    np.testing.assert_array_equal(model_base.keys, model_split.keys)
    np.testing.assert_allclose(
        np.asarray(model_split.table), np.asarray(model_base.table),
        rtol=2e-2, atol=2e-3,
    )
    assert stats["entities"] == 12


def test_train_game_driver_row_split_spec(tmp_path):
    """End-to-end: the row_split=true coordinate spec trains and scores."""
    import os

    from photon_tpu.drivers import train_game

    summary = train_game.run(train_game.build_parser().parse_args([
        "--backend", "cpu",
        "--input", "synthetic-game:16:8:8:4:1:9",
        "--coordinate", "fixed:type=fixed,shard=global,max_iters=8",
        "--coordinate",
        "pu:type=random,shard=re0,entity=re0,max_iters=6,row_split=true",
        "--descent-iterations", "1",
        "--validation-split", "0.25",
        "--output-dir", str(tmp_path / "out"),
    ]))
    assert summary["best_metrics"]["AUC"] > 0.5
    assert os.path.isdir(str(tmp_path / "out" / "best_model"))
