"""Fused Pallas sparse value-and-gradient kernel tests (interpret mode on
CPU; the kernel itself targets TPU — photon_tpu.ops.pallas_sparse).

Exactness contract: the fused kernel must match jax.value_and_grad of the
XLA objective to float32 tolerance for every loss."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from photon_tpu.core.losses import get_loss
from photon_tpu.core.objective import GlmObjective, RegularizationContext
from photon_tpu.data.batch import SparseBatch
from photon_tpu.ops.pallas_sparse import fused_value_and_grad


def _batch(n=700, k=6, d=128, seed=0, poisson=False):
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, d, size=(n, k)).astype(np.int32)
    vals = rng.standard_normal((n, k)).astype(np.float32)
    if poisson:
        label = rng.poisson(1.5, size=n).astype(np.float32)
    else:
        label = (rng.random(n) < 0.5).astype(np.float32)
    offset = (rng.standard_normal(n) * 0.1).astype(np.float32)
    weight = (rng.random(n) + 0.5).astype(np.float32)
    w = (rng.standard_normal(d) * 0.2).astype(np.float32)
    return w, SparseBatch(
        jnp.asarray(ids), jnp.asarray(vals), jnp.asarray(label),
        jnp.asarray(offset), jnp.asarray(weight),
    )


@pytest.mark.parametrize(
    "loss_name", ["logistic", "squared", "poisson", "smoothed_hinge"]
)
def test_fused_matches_xla_per_loss(loss_name):
    w, batch = _batch(poisson=loss_name == "poisson", seed=hash(loss_name) % 100)
    v, g = fused_value_and_grad(
        get_loss(loss_name), jnp.asarray(w), batch.ids, batch.vals,
        batch.label, batch.offset, batch.weight, block_rows=256,
    )
    obj = GlmObjective.create(loss_name)
    v2, g2 = obj.value_and_grad(jnp.asarray(w), batch)
    np.testing.assert_allclose(float(v), float(v2), rtol=2e-5)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g2), rtol=1e-3, atol=1e-4)


def test_fused_handles_row_padding():
    """n not a multiple of block_rows: padded rows must contribute nothing."""
    w, batch = _batch(n=130, k=4, d=64, seed=3)
    v, g = fused_value_and_grad(
        get_loss("logistic"), jnp.asarray(w), batch.ids, batch.vals,
        batch.label, batch.offset, batch.weight, block_rows=64,
    )
    v2, g2 = GlmObjective.create("logistic").value_and_grad(jnp.asarray(w), batch)
    np.testing.assert_allclose(float(v), float(v2), rtol=2e-5)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g2), rtol=1e-3, atol=1e-4)


def test_fused_empty_batch():
    w = jnp.zeros(16, jnp.float32)
    v, g = fused_value_and_grad(
        get_loss("logistic"), w,
        jnp.zeros((0, 3), jnp.int32), jnp.zeros((0, 3), jnp.float32),
        jnp.zeros(0, jnp.float32), jnp.zeros(0, jnp.float32),
        jnp.zeros(0, jnp.float32),
    )
    assert float(v) == 0.0
    np.testing.assert_array_equal(np.asarray(g), 0.0)


def test_fused_single_block_and_tiny():
    w, batch = _batch(n=3, k=2, d=16, seed=5)
    v, g = fused_value_and_grad(
        get_loss("squared"), jnp.asarray(w), batch.ids, batch.vals,
        batch.label, batch.offset, batch.weight,
    )
    v2, g2 = GlmObjective.create("squared").value_and_grad(jnp.asarray(w), batch)
    np.testing.assert_allclose(float(v), float(v2), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g2), rtol=1e-4, atol=1e-5)


def test_objective_routes_through_pallas_when_enabled(monkeypatch):
    """PHOTON_TPU_PALLAS=1 routes GlmObjective.value_and_grad through the
    fused kernel with identical results incl. the analytic L2 term
    (in-process: these calls are eager, so the flag is re-read per call)."""
    import jax.numpy as jnp

    from photon_tpu.core.objective import GlmObjective, RegularizationContext
    from photon_tpu.data.batch import SparseBatch

    rng = np.random.default_rng(0)
    n, k, d = 300, 5, 64
    batch = SparseBatch(
        jnp.asarray(rng.integers(0, d, (n, k)).astype(np.int32)),
        jnp.asarray(rng.standard_normal((n, k)).astype(np.float32)),
        jnp.asarray((rng.random(n) < 0.5).astype(np.float32)),
        jnp.zeros(n, jnp.float32), jnp.ones(n, jnp.float32))
    w = jnp.asarray(rng.standard_normal(d).astype(np.float32) * 0.1)
    obj = GlmObjective.create("logistic", RegularizationContext("l2", 2.0))
    monkeypatch.setenv("PHOTON_TPU_PALLAS", "1")
    v1, g1 = obj.value_and_grad(w, batch)
    monkeypatch.setenv("PHOTON_TPU_PALLAS", "0")
    v2, g2 = obj.value_and_grad(w, batch)
    np.testing.assert_allclose(float(v1), float(v2), rtol=2e-5)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), rtol=1e-3,
                               atol=1e-4)


def test_full_lbfgs_fit_under_pallas_flag(monkeypatch):
    """An entire L-BFGS fit with the fused kernel converges to the same
    model as the XLA path.  The solver is a module-level lru_cached jit in
    which pallas_enabled() runs at TRACE time, so both the solver cache and
    the jit executable cache must be dropped between flag flips — otherwise
    the second run replays the first compiled program and the comparison is
    vacuous (review r4)."""
    from photon_tpu.core import problem as problem_mod
    from photon_tpu.core.objective import GlmObjective, RegularizationContext
    from photon_tpu.core.optimizers import OptimizerConfig
    from photon_tpu.core.problem import GlmOptimizationProblem, ProblemConfig
    from photon_tpu.data.batch import SparseBatch

    rng = np.random.default_rng(1)
    n, k, d = 800, 6, 64
    ids = rng.integers(1, d, (n, k)).astype(np.int32)
    vals = rng.standard_normal((n, k)).astype(np.float32)
    w_true = rng.standard_normal(d).astype(np.float32) * 0.3
    m = (w_true[ids] * vals).sum(1)
    y = (rng.random(n) < 1 / (1 + np.exp(-m))).astype(np.float32)
    batch = SparseBatch(jnp.asarray(ids), jnp.asarray(vals), jnp.asarray(y),
                        jnp.zeros(n, jnp.float32), jnp.ones(n, jnp.float32))
    obj = GlmObjective.create("logistic", RegularizationContext("l2", 1.0))
    problem = GlmOptimizationProblem(obj, ProblemConfig(
        optimizer_config=OptimizerConfig(max_iterations=25)))
    from photon_tpu.ops import pallas_sparse

    # Pre-warm the capability cache: kernel_supported's eager probe calls
    # .lower() on the module-global fused_value_and_grad, so it must run
    # BEFORE the spy replaces that attribute (the spy has no .lower and
    # would fail the probe, silently disabling the very routing under test).
    assert pallas_sparse.kernel_supported(obj.loss, k, d)

    values = {}
    routed = {}
    orig = pallas_sparse.fused_value_and_grad
    for flag in ("1", "0"):
        monkeypatch.setenv("PHOTON_TPU_PALLAS", flag)
        problem_mod._cached_solver.cache_clear()
        jax.clear_caches()
        calls: list = []
        monkeypatch.setattr(
            pallas_sparse, "fused_value_and_grad",
            lambda *a, **kw: (calls.append(1), orig(*a, **kw))[1],
        )
        _, res = problem.run(batch, jnp.zeros(d, jnp.float32))
        values[flag] = float(res.value)
        routed[flag] = bool(calls)
    assert routed == {"1": True, "0": False}, routed
    np.testing.assert_allclose(values["1"], values["0"], rtol=1e-4)
