"""The static-sparsity fast gradient path (FeatureMajorAux) must match the
autodiff reference exactly (up to float32 reduction order).

The fast path replaces XLA's unsorted scatter-add (sort + segmented reduce
per evaluation) with a host-pre-sorted ``segment_sum(indices_are_sorted=
True)`` — VERDICT r2 item 1; the reference's ValueAndGradientAggregator /
HessianVectorAggregator hot loop (SURVEY.md §3.4).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from photon_tpu.core.objective import GlmObjective, RegularizationContext
from photon_tpu.data.batch import SparseBatch, attach_feature_major


def _random_batch(n, k, d, seed=0, zipf=False, with_pads=True):
    rng = np.random.default_rng(seed)
    if zipf:
        # Power-law feature frequencies — the realistic sparse-GLM regime.
        ids = (rng.zipf(1.3, size=(n, k)) - 1) % d
        ids = ids.astype(np.int32)
    else:
        ids = rng.integers(0, d, size=(n, k), dtype=np.int32)
    vals = rng.standard_normal((n, k)).astype(np.float32)
    if with_pads:
        # Zero out a random suffix of some rows (the padding convention).
        cut = rng.integers(1, k + 1, size=n)
        mask = np.arange(k)[None, :] < cut[:, None]
        vals = np.where(mask, vals, 0.0).astype(np.float32)
        ids = np.where(mask, ids, 0).astype(np.int32)
    label = (rng.random(n) < 0.5).astype(np.float32)
    offset = rng.standard_normal(n).astype(np.float32) * 0.1
    weight = rng.uniform(0.5, 2.0, n).astype(np.float32)
    return SparseBatch(
        ids=jnp.asarray(ids), vals=jnp.asarray(vals), label=jnp.asarray(label),
        offset=jnp.asarray(offset), weight=jnp.asarray(weight),
    )


@pytest.mark.parametrize("loss", ["logistic", "squared", "poisson"])
@pytest.mark.parametrize("zipf", [False, True])
def test_fast_value_and_grad_matches_autodiff(loss, zipf):
    n, k, d = 512, 8, 64
    batch = _random_batch(n, k, d, seed=1, zipf=zipf)
    fast = attach_feature_major(batch)
    obj = GlmObjective.create(loss, RegularizationContext("l2", 0.7))
    w = jnp.asarray(np.random.default_rng(2).standard_normal(d), jnp.float32) * 0.1

    v_ref, g_ref = jax.value_and_grad(obj.value)(w, batch)
    v_fast, g_fast = obj.value_and_grad(w, fast)
    np.testing.assert_allclose(v_fast, v_ref, rtol=1e-5)
    np.testing.assert_allclose(g_fast, g_ref, rtol=2e-4, atol=1e-5)
    # And under jit (the optimizer always calls it jitted).
    v_j, g_j = jax.jit(obj.value_and_grad)(w, fast)
    np.testing.assert_allclose(g_j, g_ref, rtol=2e-4, atol=1e-5)


def test_fast_hessian_vector_matches_jvp():
    n, k, d = 256, 6, 48
    batch = _random_batch(n, k, d, seed=3)
    fast = attach_feature_major(batch)
    obj = GlmObjective.create("logistic", RegularizationContext("l2", 0.3))
    rng = np.random.default_rng(4)
    w = jnp.asarray(rng.standard_normal(d), jnp.float32) * 0.1
    v = jnp.asarray(rng.standard_normal(d), jnp.float32)

    hv_ref = jax.jvp(lambda u: jax.grad(obj.value)(u, batch), (w,), (v,))[1]
    hv_fast = obj.hessian_vector(w, v, fast)
    np.testing.assert_allclose(hv_fast, hv_ref, rtol=2e-4, atol=1e-5)


def test_multi_block_single_device():
    """S > 1 on one device: block-local rows offset to global rows."""
    n, k, d = 256, 4, 32
    batch = _random_batch(n, k, d, seed=5)
    obj = GlmObjective.create("logistic")
    w = jnp.asarray(np.random.default_rng(6).standard_normal(d), jnp.float32) * 0.1
    _, g_ref = jax.value_and_grad(obj.value)(w, batch)
    for shards in (1, 4):
        fast = attach_feature_major(batch, shards=shards)
        assert fast.fm.ids.shape[0] == shards
        _, g = obj.value_and_grad(w, fast)
        np.testing.assert_allclose(g, g_ref, rtol=2e-4, atol=1e-5)


def test_fm_ids_sorted_and_pads_harmless():
    batch = _random_batch(64, 4, 16, seed=7)
    fast = attach_feature_major(batch, shards=2)
    ids = np.asarray(fast.fm.ids)
    assert (np.diff(ids, axis=1) >= 0).all(), "ids must be sorted within blocks"
    # Pad entries carry val 0 -> removing them changes nothing.
    obj = GlmObjective.create("squared")
    w = jnp.ones(16, jnp.float32)
    _, g = obj.value_and_grad(w, fast)
    assert np.isfinite(np.asarray(g)).all()


def test_attach_feature_major_validation():
    batch = _random_batch(10, 3, 8, seed=8)
    with pytest.raises(ValueError, match="divisible"):
        attach_feature_major(batch, shards=3)


def test_distributed_fast_path_matches_single_device():
    from jax.sharding import Mesh
    from photon_tpu.parallel.distributed import DistributedGlmObjective
    from photon_tpu.parallel.mesh import create_mesh, shard_batch

    n, k, d = 512, 8, 64
    batch = _random_batch(n, k, d, seed=9)
    obj = GlmObjective.create("logistic", RegularizationContext("l2", 0.5))
    w = jnp.asarray(np.random.default_rng(10).standard_normal(d), jnp.float32) * 0.1
    v_ref, g_ref = jax.value_and_grad(obj.value)(w, batch)

    mesh = create_mesh(8)
    sharded = shard_batch(batch, mesh)  # attaches per-shard fm
    assert sharded.fm is not None and sharded.fm.ids.shape[0] == 8
    dist = DistributedGlmObjective(obj, mesh)
    v, g = dist.value_and_grad(w, sharded)
    np.testing.assert_allclose(v, v_ref, rtol=1e-5)
    np.testing.assert_allclose(g, g_ref, rtol=2e-4, atol=1e-5)

    rng = np.random.default_rng(11)
    vec = jnp.asarray(rng.standard_normal(d), jnp.float32)
    hv_ref = jax.jvp(lambda u: jax.grad(obj.value)(u, batch), (w,), (vec,))[1]
    hv = dist.hessian_vector(w, vec, sharded)
    np.testing.assert_allclose(hv, hv_ref, rtol=2e-4, atol=1e-5)


def test_sparse_grad_kernel_selection(monkeypatch):
    """ops/sparse_grad_select: env overrides force the path; auto measures
    once per (backend, size bucket) and caches."""
    import photon_tpu.core.objective as obj_mod
    import photon_tpu.ops.sparse_grad_select as sel

    # Drop the probe floor so this tiny problem exercises the measured
    # path (production small shapes short-circuit to autodiff).
    monkeypatch.setenv("PHOTON_SPARSE_PROBE_FLOOR", "0")
    n, k, d = 256, 4, 64
    batch = attach_feature_major(_random_batch(n, k, d, seed=20))
    obj = GlmObjective.create("logistic")
    w = jnp.zeros(d, jnp.float32)

    calls = []
    real = obj_mod._fm_segment_grad

    def spy(per_row, fm, dim):
        calls.append(dim)
        return real(per_row, fm, dim)

    monkeypatch.setattr(obj_mod, "_fm_segment_grad", spy)
    monkeypatch.setenv("PHOTON_SPARSE_GRAD", "autodiff")
    obj.value_and_grad(w, batch)
    assert not calls, "autodiff override must bypass the fm kernel"
    monkeypatch.setenv("PHOTON_SPARSE_GRAD", "fm")
    obj.value_and_grad(w, batch)
    assert calls, "fm override must route through the fm kernel"

    monkeypatch.setenv("PHOTON_SPARSE_GRAD", "auto")
    sel._CACHE.clear()
    decision = sel.fm_path_wins(n * k, d, n)
    assert isinstance(decision, bool)
    assert sel._CACHE, "auto mode must cache the measurement"
    # Same bucket -> no re-measure (cache key count stable).
    before = dict(sel._CACHE)
    sel.fm_path_wins(n * k, d, n)
    assert sel._CACHE == before


def test_fast_path_under_normalization_matches_autodiff():
    """g = F (X^T dz - s * sum(dz)): the fm path must stay exact under
    in-objective normalization (it used to fall back to autodiff)."""
    from photon_tpu.core.normalization import NormalizationContext
    from photon_tpu.core.stats import BasicStatisticalSummary

    n, k, d = 384, 6, 40
    batch = _random_batch(n, k, d, seed=31)
    fast = attach_feature_major(batch)
    summary = BasicStatisticalSummary.from_batch(batch, d)
    for kind in ("scale_with_standard_deviation", "standardization"):
        norm = NormalizationContext.build(kind, summary, intercept_id=0)
        obj = GlmObjective.create(
            "logistic", RegularizationContext("l2", 0.4), normalization=norm
        )
        w = jnp.asarray(
            np.random.default_rng(32).standard_normal(d), jnp.float32) * 0.1
        v_ref, g_ref = jax.value_and_grad(obj.value)(w, batch)
        v_fast, g_fast = obj.value_and_grad(w, fast)
        np.testing.assert_allclose(v_fast, v_ref, rtol=1e-5)
        np.testing.assert_allclose(g_fast, g_ref, rtol=2e-4, atol=1e-5)
        hv_ref = jax.jvp(
            lambda u: jax.grad(obj.value)(u, batch), (w,),
            (jnp.asarray(np.random.default_rng(33).standard_normal(d),
                         jnp.float32),),
        )[1]
        hv = obj.hessian_vector(
            w, jnp.asarray(np.random.default_rng(33).standard_normal(d),
                           jnp.float32), fast)
        np.testing.assert_allclose(hv, hv_ref, rtol=2e-4, atol=1e-5)


def test_distributed_fast_path_under_normalization():
    """Per-shard normalization correction (shifts * local sum(dz)) must psum
    to the global correction — 8-device mesh vs single-device, normalized."""
    from photon_tpu.core.normalization import NormalizationContext
    from photon_tpu.core.stats import BasicStatisticalSummary
    from photon_tpu.parallel.distributed import DistributedGlmObjective
    from photon_tpu.parallel.mesh import create_mesh, shard_batch

    n, k, d = 512, 8, 64
    batch = _random_batch(n, k, d, seed=41)
    summary = BasicStatisticalSummary.from_batch(batch, d)
    norm = NormalizationContext.build("standardization", summary, intercept_id=0)
    obj = GlmObjective.create(
        "logistic", RegularizationContext("l2", 0.5), normalization=norm
    )
    w = jnp.asarray(np.random.default_rng(42).standard_normal(d), jnp.float32) * 0.1
    v_ref, g_ref = jax.value_and_grad(obj.value)(w, batch)

    mesh = create_mesh(8)
    dist = DistributedGlmObjective(obj, mesh)
    v, g = dist.value_and_grad(w, shard_batch(batch, mesh))
    np.testing.assert_allclose(v, v_ref, rtol=1e-5)
    np.testing.assert_allclose(g, g_ref, rtol=2e-4, atol=1e-5)


@pytest.mark.parametrize("loss", ["logistic", "poisson"])
@pytest.mark.parametrize("zipf", [False, True])
def test_pallas_kernel_matches_autodiff(monkeypatch, loss, zipf):
    """PHOTON_SPARSE_GRAD=pallas routes value+grad AND Hv through the
    slab-aligned Mosaic kernel (interpret mode on CPU) — must match the
    autodiff reference like the fm path does (VERDICT r3 item 2)."""
    n, k, d = 256, 6, 48
    batch = _random_batch(n, k, d, seed=50, zipf=zipf)
    fast = attach_feature_major(batch, aligned_dim=d)
    assert fast.al is not None
    obj = GlmObjective.create(loss, RegularizationContext("l2", 0.6))
    rng = np.random.default_rng(51)
    w = jnp.asarray(rng.standard_normal(d), jnp.float32) * 0.1

    monkeypatch.setenv("PHOTON_SPARSE_GRAD", "pallas")
    assert obj._sparse_kernel(fast, d) == "pallas"
    v_ref, g_ref = jax.value_and_grad(obj.value)(w, batch)
    v_p, g_p = obj.value_and_grad(w, fast)
    np.testing.assert_allclose(v_p, v_ref, rtol=1e-5)
    np.testing.assert_allclose(g_p, g_ref, rtol=2e-4, atol=1e-5)
    # Under jit (optimizers always call it jitted).
    v_j, g_j = jax.jit(obj.value_and_grad)(w, fast)
    np.testing.assert_allclose(g_j, g_ref, rtol=2e-4, atol=1e-5)

    vec = jnp.asarray(rng.standard_normal(d), jnp.float32)
    hv_ref = jax.jvp(lambda u: jax.grad(obj.value)(u, batch), (w,), (vec,))[1]
    hv = obj.hessian_vector(w, vec, fast)
    np.testing.assert_allclose(hv, hv_ref, rtol=2e-4, atol=1e-5)


def test_pallas_kernel_under_normalization(monkeypatch):
    """The normalization algebra (g = F (X^T dz - s Σ dz)) is shared with
    the fm path, so the pallas kernel must stay exact under it too."""
    from photon_tpu.core.normalization import NormalizationContext
    from photon_tpu.core.stats import BasicStatisticalSummary

    n, k, d = 192, 5, 40
    batch = _random_batch(n, k, d, seed=60)
    fast = attach_feature_major(batch, aligned_dim=d)
    summary = BasicStatisticalSummary.from_batch(batch, d)
    norm = NormalizationContext.build("standardization", summary, intercept_id=0)
    obj = GlmObjective.create(
        "logistic", RegularizationContext("l2", 0.4), normalization=norm
    )
    w = jnp.asarray(np.random.default_rng(61).standard_normal(d), jnp.float32) * 0.1
    monkeypatch.setenv("PHOTON_SPARSE_GRAD", "pallas")
    v_ref, g_ref = jax.value_and_grad(obj.value)(w, batch)
    v_p, g_p = obj.value_and_grad(w, fast)
    np.testing.assert_allclose(v_p, v_ref, rtol=1e-5)
    np.testing.assert_allclose(g_p, g_ref, rtol=2e-4, atol=1e-5)


@pytest.mark.parametrize("zipf", [False, True])
def test_pallas_forward_margins_via_transposed_layout(monkeypatch, zipf):
    """aligned_forward=True builds the row-dictionary layout; the pallas
    path then computes margins AND Hv products through the same
    position-reduce kernel (KERNEL_NOTES option (a)) — must match the
    autodiff reference, incl. under normalization."""
    from photon_tpu.core.normalization import NormalizationContext
    from photon_tpu.core.stats import BasicStatisticalSummary
    from photon_tpu.ops.pallas_gather import aligned_segment_grad

    n, k, d = 320, 7, 56
    batch = _random_batch(n, k, d, seed=80, zipf=zipf)
    fast = attach_feature_major(batch, aligned_dim=d, aligned_forward=True)
    assert fast.al is not None and fast.al_t is not None
    rng = np.random.default_rng(81)
    w = jnp.asarray(rng.standard_normal(d), jnp.float32) * 0.1

    # Raw margins through the transposed layout == row-major gather.
    from photon_tpu.data.batch import margins as rowmajor_margins

    z_t = aligned_segment_grad(w, fast.al_t, n, interpret=True) + batch.offset
    np.testing.assert_allclose(
        np.asarray(z_t), np.asarray(rowmajor_margins(w, batch)),
        rtol=2e-4, atol=1e-5,
    )

    monkeypatch.setenv("PHOTON_SPARSE_GRAD", "pallas")
    obj = GlmObjective.create("logistic", RegularizationContext("l2", 0.5))
    v_ref, g_ref = jax.value_and_grad(obj.value)(w, batch)
    v_p, g_p = obj.value_and_grad(w, fast)
    np.testing.assert_allclose(v_p, v_ref, rtol=1e-5)
    np.testing.assert_allclose(g_p, g_ref, rtol=2e-4, atol=1e-5)
    vec = jnp.asarray(rng.standard_normal(d), jnp.float32)
    hv_ref = jax.jvp(lambda u: jax.grad(obj.value)(u, batch), (w,), (vec,))[1]
    np.testing.assert_allclose(
        obj.hessian_vector(w, vec, fast), hv_ref, rtol=2e-4, atol=1e-5
    )

    # Under normalization (the shifted-margin correction rides along).
    summary = BasicStatisticalSummary.from_batch(batch, d)
    norm = NormalizationContext.build("standardization", summary, intercept_id=0)
    obj_n = GlmObjective.create(
        "logistic", RegularizationContext("l2", 0.5), normalization=norm
    )
    v_ref, g_ref = jax.value_and_grad(obj_n.value)(w, batch)
    v_p, g_p = obj_n.value_and_grad(w, fast)
    np.testing.assert_allclose(v_p, v_ref, rtol=1e-5)
    np.testing.assert_allclose(g_p, g_ref, rtol=2e-4, atol=1e-5)


def test_pallas_kernel_normalized_hessian_vector(monkeypatch):
    """Normalized Hv falls back to jvp-of-grad; pallas_call has no JVP
    rule, so the inner grad must re-route to the (differentiable) fm
    layout — TRON + normalization + pallas used to crash at trace time."""
    from photon_tpu.core.normalization import NormalizationContext
    from photon_tpu.core.stats import BasicStatisticalSummary

    n, k, d = 128, 4, 24
    batch = _random_batch(n, k, d, seed=65)
    fast = attach_feature_major(batch, aligned_dim=d)
    summary = BasicStatisticalSummary.from_batch(batch, d)
    norm = NormalizationContext.build("standardization", summary, intercept_id=0)
    obj = GlmObjective.create(
        "logistic", RegularizationContext("l2", 0.3), normalization=norm
    )
    rng = np.random.default_rng(66)
    w = jnp.asarray(rng.standard_normal(d), jnp.float32) * 0.1
    vec = jnp.asarray(rng.standard_normal(d), jnp.float32)
    monkeypatch.setenv("PHOTON_SPARSE_GRAD", "pallas")
    hv = obj.hessian_vector(w, vec, fast)
    hv_ref = jax.jvp(lambda u: jax.grad(obj.value)(u, batch), (w,), (vec,))[1]
    np.testing.assert_allclose(hv, hv_ref, rtol=2e-4, atol=1e-5)


def test_select_kernel_availability_fallbacks(monkeypatch):
    """select_kernel honors layout availability: pallas needs the aligned
    layout, fm needs the feature-major aux; on CPU auto never picks pallas
    (Mosaic eligibility gate)."""
    import photon_tpu.ops.sparse_grad_select as sel

    monkeypatch.setenv("PHOTON_SPARSE_GRAD", "pallas")
    assert sel.select_kernel(1024, 64, 256, has_fm=True, has_aligned=False) == "fm"
    assert sel.select_kernel(1024, 64, 256, has_fm=False, has_aligned=False) == "autodiff"
    assert sel.select_kernel(1024, 64, 256, has_fm=False, has_aligned=True) == "pallas"
    monkeypatch.setenv("PHOTON_SPARSE_GRAD", "auto")
    # Drop the floor so the 1024-entry call reaches the MEASURED path —
    # the pallas-exclusion assertion is about the probe, not the floor.
    monkeypatch.setenv("PHOTON_SPARSE_PROBE_FLOOR", "0")
    sel._CACHE.clear()
    choice = sel.select_kernel(1024, 64, 256, has_fm=True, has_aligned=True)
    assert choice in ("fm", "autodiff"), "CPU auto must exclude pallas"
    # aligned_layout_wanted: forced pallas -> build; auto on CPU -> don't.
    monkeypatch.setenv("PHOTON_SPARSE_GRAD", "pallas")
    assert sel.aligned_layout_wanted()
    monkeypatch.setenv("PHOTON_SPARSE_GRAD", "auto")
    assert not sel.aligned_layout_wanted()
    monkeypatch.setenv("PHOTON_SPARSE_GRAD", "fm")
    assert not sel.aligned_layout_wanted()


def test_measure_correctness_gate_excludes_bad_pallas(monkeypatch):
    """A Mosaic kernel that miscompiles on the live backend must be
    DISQUALIFIED by the probe's on-device correctness gate, never timed
    into production eligibility; a correct kernel passes the gate."""
    import numpy as np

    import photon_tpu.ops.pallas_gather as pg
    import photon_tpu.ops.sparse_grad_select as sel

    real = pg.aligned_segment_grad

    def garbage(per_row, al, dim, interpret=None):
        return real(per_row, al, dim, interpret=True) + 1.0  # wrong output

    def correct(per_row, al, dim, interpret=None):
        return real(per_row, al, dim, interpret=True)  # CPU-safe, right math

    monkeypatch.setattr(pg, "aligned_segment_grad", garbage)
    choice = sel._measure(1 << 12, 256, 256, with_pallas=True)
    assert choice in ("fm", "autodiff"), "garbage pallas must be excluded"

    monkeypatch.setattr(pg, "aligned_segment_grad", correct)
    choice2 = sel._measure(1 << 12, 256, 256, with_pallas=True)
    assert choice2 in ("fm", "autodiff", "pallas")  # gate passed; timing decides


def test_probe_cap_env_override(monkeypatch):
    """The selection probe's size cap is env-tunable (bench.py raises it to
    probe at the true headline shape); garbage values fall back to the
    default instead of crashing training."""
    import photon_tpu.ops.sparse_grad_select as sel

    monkeypatch.delenv("PHOTON_SPARSE_PROBE_MAX_ENTRIES", raising=False)
    assert sel._probe_cap() == sel._PROBE_MAX_ENTRIES
    monkeypatch.setenv("PHOTON_SPARSE_PROBE_MAX_ENTRIES", "4096")
    assert sel._probe_cap() == 4096
    monkeypatch.setenv("PHOTON_SPARSE_PROBE_MAX_ENTRIES", "not-a-number")
    assert sel._probe_cap() == sel._PROBE_MAX_ENTRIES
    # 0 would divide-by-zero in the ceil; negatives would uncap the probe.
    monkeypatch.setenv("PHOTON_SPARSE_PROBE_MAX_ENTRIES", "0")
    assert sel._probe_cap() == sel._PROBE_MAX_ENTRIES
    monkeypatch.setenv("PHOTON_SPARSE_PROBE_MAX_ENTRIES", "-5")
    assert sel._probe_cap() == sel._PROBE_MAX_ENTRIES


def test_probe_floor_skips_measurement_for_small_problems(monkeypatch):
    """Below the probe floor auto mode returns autodiff WITHOUT running the
    eager measurement (GAME runs hit many small shape buckets; a probe per
    bucket costs more than any kernel difference repays)."""
    import photon_tpu.ops.sparse_grad_select as sel

    monkeypatch.setenv("PHOTON_SPARSE_GRAD", "auto")

    def boom(*a, **k):
        raise AssertionError("probe must not run below the floor")

    monkeypatch.setattr(sel, "_measure", boom)
    sel._CACHE.clear()
    assert sel.select_kernel(1 << 10, 64, 256, has_fm=True) == "autodiff"
    # The cache stays empty on the floor path — if the floor were removed,
    # boom would fire into select_kernel's failure fallback, which ALSO
    # returns autodiff but caches it; the cache is the discriminator.
    assert not sel._CACHE, "below the floor the probe path must not engage"
    # At/above the floor the measurement DOES run (here: boom fires, and
    # select_kernel's failure fallback also resolves to autodiff — assert
    # via the cache to distinguish the probed path from the floor path).
    monkeypatch.setenv("PHOTON_SPARSE_PROBE_FLOOR", "512")
    sel._CACHE.clear()
    assert sel.select_kernel(1 << 10, 64, 256, has_fm=True) == "autodiff"
    assert sel._CACHE, "above the floor the probe path must engage"
    sel._CACHE.clear()


def test_aligned_layout_survives_astype_and_pad_strip(monkeypatch):
    """batch_astype converts al.vals in place; pad_batch strips al (it is
    row-structure-dependent) so shard_batch rebuilds per-shard fm only."""
    from photon_tpu.data.batch import batch_astype, pad_batch

    batch = _random_batch(64, 4, 32, seed=70)
    fast = attach_feature_major(batch, aligned_dim=32)
    bf16 = batch_astype(fast, jnp.bfloat16)
    assert bf16.al is not None and bf16.al.vals.dtype == jnp.bfloat16
    obj = GlmObjective.create("logistic")
    w = jnp.asarray(np.random.default_rng(71).standard_normal(32), jnp.float32) * 0.1
    monkeypatch.setenv("PHOTON_SPARSE_GRAD", "pallas")
    _, g_ref = jax.value_and_grad(obj.value)(w, batch)
    _, g_bf = obj.value_and_grad(w, bf16)
    np.testing.assert_allclose(g_bf, g_ref, rtol=0.02, atol=0.02)
    padded = pad_batch(fast, 80)
    assert padded.al is None and padded.fm is None


def test_fast_path_matches_autodiff_across_random_configs():
    """Property-style sweep over (n, k, d) configs — incl. degenerate k=1,
    tiny d, n=1 — with round-robin losses, random l2, and a multi-block
    feature-major layout (shards=2) whenever n is even: the fm fast path
    must agree with the autodiff reference at several random points."""
    rng = np.random.default_rng(2024)
    # Each (n, k, d) is a distinct compile; the fixed list carries the edge
    # cases, so two random draws suffice (suite-time budget, VERDICT r3
    # item 4).
    configs = [(1, 1, 2), (3, 1, 2), (2, 5, 3), (17, 3, 9)] + [
        (int(rng.integers(2, 200)), int(rng.integers(1, 9)),
         int(rng.integers(2, 64)))
        for _ in range(2)
    ]
    for i, (n, k, d) in enumerate(configs):
        loss = ("logistic", "squared", "poisson")[i % 3]
        l2 = float(rng.uniform(0, 2))
        batch = _random_batch(n, k, d, seed=i, zipf=bool(i % 2))
        fast = attach_feature_major(batch, shards=2 if n % 2 == 0 else 1)
        obj = GlmObjective.create(loss, RegularizationContext("l2", l2))
        for trial in range(2):
            w = jnp.asarray(
                rng.standard_normal(d).astype(np.float32) * 0.5
            )
            v_ref, g_ref = jax.value_and_grad(obj.value)(w, batch)
            v_fm, g_fm = obj.value_and_grad(w, fast)
            np.testing.assert_allclose(
                float(v_fm), float(v_ref), rtol=2e-5,
                err_msg=f"cfg {n},{k},{d} {loss} l2={l2}",
            )
            np.testing.assert_allclose(
                np.asarray(g_fm), np.asarray(g_ref), rtol=2e-4, atol=2e-5,
                err_msg=f"cfg {n},{k},{d} {loss} l2={l2}",
            )


def test_selection_probe_measures_under_enclosing_trace(monkeypatch):
    """The auto-selection probe usually first fires while an ENCLOSING
    jit (optimizer while_loop, streamed chunk program) is being traced;
    under omnistaging its host synchronizations would raise and the
    blanket except would silently pin "autodiff" forever.  The
    ensure_compile_time_eval escape hatch must let the real measurement
    complete there (round-5 fix — the failure was latent in every
    jitted auto-mode path)."""
    import jax
    import jax.numpy as jnp

    import photon_tpu.ops.sparse_grad_select as sg

    saved = dict(sg._CACHE)
    sg._CACHE.clear()
    calls = []
    real = sg._measure

    def spy(*args, **kw):
        out = real(*args, **kw)
        calls.append(out)
        return out

    monkeypatch.setattr(sg, "_measure", spy)
    monkeypatch.setenv("PHOTON_SPARSE_GRAD", "auto")
    monkeypatch.setenv("PHOTON_SPARSE_PROBE_FLOOR", "1")
    try:
        def f(x):
            choice = sg.select_kernel(4096, 512, 256, has_fm=True)
            assert choice in ("fm", "autodiff")
            return x * 2.0

        jax.jit(f)(jnp.ones(2))
        assert calls, (
            "the probe must have completed a real measurement under the "
            "trace, not fallen into the except-Exception autodiff pin"
        )
    finally:
        sg._CACHE.clear()
        sg._CACHE.update(saved)
