"""Streamed fast-kernel layouts (VERDICT r5 item 3): chunks re-parsed
per pass carry cached aligned/xchg aux, route to the fast kernels, and
produce the same numbers as the plain autodiff streamed pass."""

import os

import numpy as np
import pytest

import jax.numpy as jnp

from photon_tpu.core.objective import GlmObjective, RegularizationContext
from photon_tpu.data.streaming import LibsvmFileSource, StreamingObjective

D_RAW = 96  # feature dim before the intercept column


def _write_files(tmp_path, n_files=3, rows=64, k=6, seed=0):
    rng = np.random.default_rng(seed)
    files = []
    for fi in range(n_files):
        path = tmp_path / f"part-{fi:03d}.libsvm"
        with open(path, "w") as f:
            # Last file shorter: exercises the unequal-chunk geometry.
            n = rows if fi < n_files - 1 else rows // 2
            for _ in range(n):
                ids = np.sort(rng.choice(
                    np.arange(1, D_RAW + 1), size=k, replace=False
                ))
                vals = rng.standard_normal(k)
                y = 1 if rng.random() < 0.5 else -1
                f.write(f"{y} " + " ".join(
                    f"{j}:{v:.5f}" for j, v in zip(ids, vals)
                ) + "\n")
        files.append(str(path))
    return files


def _streamed_vg(files, w):
    source = LibsvmFileSource(files, intercept=True)
    obj = StreamingObjective(
        GlmObjective.create("logistic", RegularizationContext("l2", 0.5)),
        source.chunk_iter_factory,
    )
    v, g = obj.value_and_grad(w)
    return float(v), np.asarray(g), source.dim


@pytest.mark.parametrize("kernel,reduce_mode", [
    ("fm", None),
    ("pallas", None),
    ("xchg", "cumsum"),
    ("xchg", "aligned"),
])
def test_streamed_kernel_matches_autodiff(tmp_path, monkeypatch, kernel,
                                          reduce_mode):
    files = _write_files(tmp_path)
    monkeypatch.setenv("PHOTON_SPARSE_GRAD", "autodiff")
    dim_probe = LibsvmFileSource(files, intercept=True).dim
    w = jnp.asarray(
        np.random.default_rng(1).standard_normal(dim_probe)
        .astype(np.float32) * 0.1
    )
    v_ref, g_ref, _ = _streamed_vg(files, w)

    monkeypatch.setenv("PHOTON_SPARSE_GRAD", kernel)
    if reduce_mode is not None:
        monkeypatch.setenv("PHOTON_XCHG_REDUCE", reduce_mode)
    monkeypatch.setenv(
        "PHOTON_STREAM_LAYOUT_CACHE", str(tmp_path / "cache")
    )
    v, g, _ = _streamed_vg(files, w)
    np.testing.assert_allclose(v, v_ref, rtol=2e-5)
    scale = max(float(np.abs(g_ref).max()), 1.0)
    np.testing.assert_allclose(g, g_ref, rtol=2e-4, atol=2e-4 * scale)


def test_stream_layout_cache_hit_skips_build(tmp_path, monkeypatch):
    """Second pass (and a fresh source, as after a restart) must load
    the cached aux instead of rebuilding."""
    import photon_tpu.data.stream_layouts as sl

    files = _write_files(tmp_path)
    monkeypatch.setenv("PHOTON_SPARSE_GRAD", "xchg")
    monkeypatch.setenv("PHOTON_XCHG_REDUCE", "cumsum")
    monkeypatch.setenv(
        "PHOTON_STREAM_LAYOUT_CACHE", str(tmp_path / "cache")
    )
    dim_probe = LibsvmFileSource(files, intercept=True).dim
    w = jnp.zeros(dim_probe, jnp.float32)
    builds = []
    real_build = sl._build_aux

    def counting_build(*args, **kw):
        builds.append(1)
        return real_build(*args, **kw)

    monkeypatch.setattr(sl, "_build_aux", counting_build)
    v1, g1, _ = _streamed_vg(files, w)
    assert len(builds) == len(files)  # one build per file, first pass
    v2, g2, _ = _streamed_vg(files, w)  # fresh source = restart
    assert len(builds) == len(files)  # all cache hits
    assert v1 == v2
    np.testing.assert_array_equal(g1, g2)


def test_stream_kernel_follows_forced_sparse_grad(monkeypatch):
    from photon_tpu.data.stream_layouts import stream_kernel

    monkeypatch.delenv("PHOTON_STREAM_KERNEL", raising=False)
    monkeypatch.setenv("PHOTON_SPARSE_GRAD", "xchg")
    assert stream_kernel() == "xchg"
    monkeypatch.setenv("PHOTON_SPARSE_GRAD", "auto")
    assert stream_kernel() == "autodiff"
    monkeypatch.setenv("PHOTON_STREAM_KERNEL", "pallas")
    assert stream_kernel() == "pallas"


def test_stream_cache_invalidated_by_file_change(tmp_path, monkeypatch):
    """Rewriting a part file (new size/mtime) must miss the cache and
    rebuild, not serve the stale aux."""
    import photon_tpu.data.stream_layouts as sl

    files = _write_files(tmp_path, n_files=1, rows=32)
    monkeypatch.setenv("PHOTON_SPARSE_GRAD", "xchg")
    monkeypatch.setenv("PHOTON_XCHG_REDUCE", "cumsum")
    monkeypatch.setenv(
        "PHOTON_STREAM_LAYOUT_CACHE", str(tmp_path / "cache")
    )
    dim_probe = LibsvmFileSource(files, intercept=True).dim
    w = jnp.zeros(dim_probe, jnp.float32)
    builds = []
    real_build = sl._build_aux

    def counting_build(*args, **kw):
        builds.append(1)
        return real_build(*args, **kw)

    monkeypatch.setattr(sl, "_build_aux", counting_build)
    _streamed_vg(files, w)
    assert len(builds) == 1
    # Rewrite with different content (more rows -> different size).
    _write_files(tmp_path, n_files=1, rows=48, seed=9)
    monkeypatch.setenv("PHOTON_SPARSE_GRAD", "autodiff")
    dim2 = LibsvmFileSource(files, intercept=True).dim
    monkeypatch.setenv("PHOTON_SPARSE_GRAD", "xchg")
    v_new, g_new, _ = _streamed_vg(files, jnp.zeros(dim2, jnp.float32))
    assert len(builds) == 2  # rebuilt for the new file identity
    monkeypatch.setenv("PHOTON_SPARSE_GRAD", "autodiff")
    v_ref, g_ref, _ = _streamed_vg(files, jnp.zeros(dim2, jnp.float32))
    np.testing.assert_allclose(v_new, v_ref, rtol=2e-5)
    np.testing.assert_allclose(g_new, g_ref, rtol=2e-4, atol=1e-4)
