"""FULL variance computation tests (reference: VarianceComputationType
NONE/SIMPLE/FULL — SURVEY.md §2.2 'L2 + variance')."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from photon_tpu.core.normalization import NormalizationContext
from photon_tpu.core.objective import GlmObjective, RegularizationContext
from photon_tpu.core.optimizers import OptimizerConfig
from photon_tpu.core.problem import GlmOptimizationProblem, ProblemConfig
from photon_tpu.data.batch import SparseBatch, dense_batch
from photon_tpu.data.synthetic import make_glm_data


def _sparse(n=300, k=4, d=24, seed=0):
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, d, size=(n, k)).astype(np.int32)
    vals = rng.standard_normal((n, k)).astype(np.float32)
    y = (rng.random(n) < 0.5).astype(np.float32)
    return SparseBatch(
        jnp.asarray(ids), jnp.asarray(vals), jnp.asarray(y),
        jnp.zeros(n, jnp.float32), jnp.ones(n, jnp.float32),
    )


@pytest.mark.parametrize("kind", ["dense", "sparse"])
def test_hessian_matrix_matches_autodiff(kind):
    obj = GlmObjective.create("logistic", RegularizationContext("l2", 0.7))
    if kind == "dense":
        batch, _ = make_glm_data(200, 12, seed=1)
        d = 12
    else:
        batch = _sparse(d=16)
        d = 16
    w = jnp.asarray(np.random.default_rng(2).standard_normal(d) * 0.3, jnp.float32)
    h = obj.hessian_matrix(w, batch)
    h_ref = jax.hessian(obj.value)(w, batch)
    np.testing.assert_allclose(np.asarray(h), np.asarray(h_ref), rtol=1e-3, atol=1e-3)


def test_hessian_matrix_under_normalization():
    batch, _ = make_glm_data(150, 8, seed=3)
    from photon_tpu.core.stats import BasicStatisticalSummary

    summary = BasicStatisticalSummary.from_batch(batch, 8)
    norm = NormalizationContext.build(
        "standardization", summary, intercept_id=7
    )
    obj = GlmObjective.create("logistic", RegularizationContext("l2", 0.5),
                              normalization=norm)
    w = jnp.asarray(np.random.default_rng(4).standard_normal(8) * 0.2, jnp.float32)
    h = obj.hessian_matrix(w, batch)
    h_ref = jax.hessian(obj.value)(w, batch)
    np.testing.assert_allclose(np.asarray(h), np.asarray(h_ref), rtol=1e-3, atol=1e-3)


def test_full_variance_is_diag_of_inverse_hessian():
    batch = _sparse(d=20, seed=5)
    obj = GlmObjective.create("logistic", RegularizationContext("l2", 1.0))
    problem = GlmOptimizationProblem(
        obj,
        ProblemConfig(
            optimizer_config=OptimizerConfig(max_iterations=30),
            regularization=RegularizationContext("l2", 1.0),
            variance_computation="full",
        ),
    )
    coeffs, result = problem.run(batch, jnp.zeros(20, jnp.float32))
    assert coeffs.variances is not None
    h = np.asarray(obj.hessian_matrix(coeffs.means, batch))
    expected = np.diag(np.linalg.inv(h))
    np.testing.assert_allclose(
        np.asarray(coeffs.variances), expected, rtol=1e-3, atol=1e-5
    )
    # FULL >= off-diagonal-blind SIMPLE is not guaranteed, but both must be
    # positive and finite.
    assert np.all(np.asarray(coeffs.variances) > 0)


def test_full_variance_distributed_matches_single():
    from photon_tpu.parallel import DistributedGlmObjective, create_mesh, shard_batch

    batch = _sparse(n=320, d=16, seed=6)
    obj = GlmObjective.create("logistic", RegularizationContext("l2", 1.0))
    mesh = create_mesh()
    sharded = shard_batch(batch, mesh)
    dobj = DistributedGlmObjective(obj, mesh)
    w = jnp.asarray(np.random.default_rng(7).standard_normal(16) * 0.2, jnp.float32)
    np.testing.assert_allclose(
        np.asarray(dobj.hessian_matrix(w, sharded)),
        np.asarray(obj.hessian_matrix(w, batch)),
        rtol=1e-4, atol=1e-4,
    )


def test_chunked_hessian_matrix_matches():
    from photon_tpu.data.streaming import ChunkedGlmObjective, chunk_batch

    batch = _sparse(n=300, d=16, seed=8)
    obj = GlmObjective.create("logistic", RegularizationContext("l2", 0.3))
    cobj = ChunkedGlmObjective(obj)
    chunks = chunk_batch(batch, 64)
    w = jnp.asarray(np.random.default_rng(9).standard_normal(16) * 0.2, jnp.float32)
    np.testing.assert_allclose(
        np.asarray(cobj.hessian_matrix(w, chunks)),
        np.asarray(obj.hessian_matrix(w, batch)),
        rtol=1e-4, atol=1e-4,
    )


def test_game_random_effect_full_variance():
    """Per-entity FULL variances through the vmapped solver."""
    from photon_tpu.data.synthetic import make_game_dataset
    from photon_tpu.game.coordinate import (
        RandomEffectCoordinate,
        RandomEffectCoordinateConfig,
    )

    data, _ = make_game_dataset(15, 3, 6, 4, seed=2)
    config = RandomEffectCoordinateConfig(
        shard_name="re0",
        entity_column="re0",
        problem=ProblemConfig(
            regularization=RegularizationContext("l2", 1.0),
            optimizer_config=OptimizerConfig(max_iterations=15),
            variance_computation="full",
        ),
    )
    coord = RandomEffectCoordinate(data, config, "logistic_regression")
    model, stats = coord.train(np.zeros(data.num_examples, np.float32))
    assert model.variances is not None
    v = np.asarray(model.variances)
    assert np.all(np.isfinite(v)) and np.all(v >= 0)
    # Entities with data have strictly positive variances (l2 bounds them).
    assert v.max() > 0


# ---------------------------------------------------------------------------
# Matrix-free FULL variance (large-d guard: core/variance.py, VERDICT r2 #7)
# ---------------------------------------------------------------------------


def test_cg_solve_matches_direct():
    from photon_tpu.core.variance import cg_solve

    rng = np.random.default_rng(0)
    a = rng.standard_normal((24, 24)).astype(np.float32)
    h = a @ a.T + 24 * np.eye(24, dtype=np.float32)
    b = rng.standard_normal(24).astype(np.float32)
    x = np.asarray(cg_solve(lambda v: jnp.asarray(h) @ v, jnp.asarray(b)))
    np.testing.assert_allclose(h @ x, b, rtol=1e-3, atol=1e-4)


def test_hutchinson_exact_for_orthogonal_features():
    # Each example touches exactly one feature -> H is diagonal, and the
    # Rademacher estimator is exact for ANY probe (z_j^2 = 1).
    from photon_tpu.core.variance import hutchinson_diag_inverse

    d, per = 16, 8
    rng = np.random.default_rng(1)
    ids = np.repeat(np.arange(d, dtype=np.int32), per)[:, None]
    vals = rng.uniform(0.5, 2.0, (d * per, 1)).astype(np.float32)
    batch = SparseBatch(
        jnp.asarray(ids), jnp.asarray(vals),
        jnp.asarray((rng.random(d * per) < 0.5).astype(np.float32)),
        jnp.zeros(d * per, jnp.float32), jnp.ones(d * per, jnp.float32),
    )
    obj = GlmObjective.create("logistic", RegularizationContext("l2", 0.5))
    w = jnp.asarray(rng.standard_normal(d), jnp.float32) * 0.1
    est = np.asarray(hutchinson_diag_inverse(
        lambda v: obj.hessian_vector(w, v, batch), dim=d, num_probes=2
    ))
    h = np.asarray(obj.hessian_matrix(w, batch))
    assert np.abs(h - np.diag(np.diag(h))).max() < 1e-5  # H really is diagonal
    np.testing.assert_allclose(est, 1.0 / np.diag(h), rtol=1e-3)


def test_full_variance_routes_matrix_free_above_threshold(monkeypatch):
    import photon_tpu.core.variance as variance_mod

    # Force the CG path at a tiny dim and compare against the dense answer
    # on a diagonal-Hessian problem (where the estimator is exact).
    monkeypatch.setattr(variance_mod, "FULL_DENSE_MAX_DIM", 4)
    d, per = 12, 6
    rng = np.random.default_rng(2)
    ids = np.repeat(np.arange(d, dtype=np.int32), per)[:, None]
    vals = rng.uniform(0.5, 2.0, (d * per, 1)).astype(np.float32)
    batch = SparseBatch(
        jnp.asarray(ids), jnp.asarray(vals),
        jnp.asarray((rng.random(d * per) < 0.5).astype(np.float32)),
        jnp.zeros(d * per, jnp.float32), jnp.ones(d * per, jnp.float32),
    )
    obj = GlmObjective.create("logistic", RegularizationContext("l2", 1.0))
    prob = GlmOptimizationProblem(
        obj, ProblemConfig(variance_computation="full")
    )
    coeffs, _ = prob.run(batch, dim=d)
    assert coeffs.variances is not None
    h = np.asarray(obj.hessian_matrix(jnp.asarray(coeffs.means), batch))
    np.testing.assert_allclose(
        np.asarray(coeffs.variances), 1.0 / np.diag(h), rtol=1e-3
    )
