"""Telemetry subsystem: registry, spans, run reports, driver integration."""

import json
import os
import threading

import numpy as np
import pytest

from photon_tpu.telemetry import (
    NULL_SESSION,
    MetricsRegistry,
    TelemetrySession,
    Tracer,
    telemetry_enabled,
)
from photon_tpu.telemetry.report import (
    render_markdown,
    resolve_report_path,
)
from photon_tpu.telemetry import report as report_cli


# ---------------------------------------------------------------- registry


def test_counter_gauge_histogram_basics():
    reg = MetricsRegistry()
    reg.counter("c").inc()
    reg.counter("c").inc(2.5)
    assert reg.counter("c").value == 3.5
    with pytest.raises(ValueError):
        reg.counter("c").inc(-1)

    assert reg.gauge("g").value is None
    reg.gauge("g").set(7)
    reg.gauge("g").set(5)
    assert reg.gauge("g").value == 5.0

    h = reg.histogram("h")
    for v in (1.0, 2.0, 3.0, 4.0):
        h.observe(v)
    s = h.summary()
    assert s["count"] == 4 and s["sum"] == 10.0
    assert s["min"] == 1.0 and s["max"] == 4.0 and s["mean"] == 2.5


def test_labels_create_distinct_series_and_kind_conflicts_raise():
    reg = MetricsRegistry()
    reg.counter("solves", lam="0.1").inc()
    reg.counter("solves", lam="1").inc(2)
    assert reg.counter("solves", lam="0.1").value == 1
    assert reg.counter("solves", lam="1").value == 2
    # Same (name, labels) under a different kind is a registration bug.
    with pytest.raises(TypeError):
        reg.gauge("solves", lam="0.1")
    # Label VALUES are stringified, so 1 and "1" are the same series.
    reg.counter("solves", lam=1).inc()
    assert reg.counter("solves", lam="1").value == 3


def test_histogram_reservoir_bounded_and_percentiles_sane():
    reg = MetricsRegistry()
    h = reg.histogram("big")
    n = 10_000
    for i in range(n):
        h.observe(float(i))
    assert h.count == n and h.sum == sum(range(n))
    assert len(h._kept) <= 256 + 1
    # Kept samples sweep the sequence evenly -> percentiles land close.
    assert abs(h.percentile(50) - n / 2) < n * 0.05
    assert h.percentile(0) == 0.0
    assert h.summary()["p99"] > n * 0.9


def test_snapshot_is_sorted_and_json_ready():
    reg = MetricsRegistry()
    reg.counter("b").inc()
    reg.counter("a", x="2").inc()
    reg.counter("a", x="1").inc()
    reg.gauge("g").set(1.5)
    reg.histogram("h").observe(3)
    snap = reg.snapshot()
    names = [(e["name"], e["labels"]) for e in snap["counters"]]
    assert names == [("a", {"x": "1"}), ("a", {"x": "2"}), ("b", {})]
    json.dumps(snap)  # must serialize


def test_prometheus_exposition():
    reg = MetricsRegistry()
    reg.counter("optimizer.solves", lam="0.1").inc(3)
    reg.gauge("train.best_lambda").set(0.1)
    reg.gauge("unset")  # never set -> omitted
    reg.histogram("solve_seconds").observe(2.0)
    text = reg.to_prometheus()
    assert 'optimizer_solves{lam="0.1"} 3' in text
    assert "train_best_lambda 0.1" in text
    assert "unset" not in text
    assert 'solve_seconds{quantile="0.5"} 2' in text
    assert "solve_seconds_count 1" in text


def test_registry_thread_safety():
    reg = MetricsRegistry()

    def work():
        for _ in range(1000):
            reg.counter("n").inc()
            reg.histogram("h").observe(1.0)

    threads = [threading.Thread(target=work) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert reg.counter("n").value == 4000
    assert reg.histogram("h").count == 4000


# ----------------------------------------------------------------- tracing


def test_span_nesting_and_attributes():
    tracer = Tracer()
    with tracer.span("outer", kind="test") as outer:
        with tracer.span("inner") as inner:
            assert tracer.current_span() is inner
            inner.set_attribute("rows", 10)
        assert tracer.current_span() is outer
    assert tracer.current_span() is None
    spans = tracer.export()
    # Children finish first.
    assert [s["name"] for s in spans] == ["inner", "outer"]
    by_name = {s["name"]: s for s in spans}
    assert by_name["inner"]["parent_id"] == by_name["outer"]["span_id"]
    assert by_name["outer"]["parent_id"] is None
    assert by_name["inner"]["attributes"]["rows"] == 10
    assert by_name["outer"]["attributes"]["kind"] == "test"
    assert all(s["duration_s"] >= 0 for s in spans)


def test_span_error_status_recorded_and_reraised():
    tracer = Tracer()
    with pytest.raises(RuntimeError, match="boom"):
        with tracer.span("failing"):
            raise RuntimeError("boom")
    (span,) = tracer.export()
    assert span["status"] == "error"
    assert "RuntimeError: boom" in span["error"]
    assert span["duration_s"] is not None


def test_spans_on_worker_threads_are_roots():
    tracer = Tracer()

    def work():
        with tracer.span("worker"):
            pass

    with tracer.span("main"):
        t = threading.Thread(target=work)
        t.start()
        t.join()
    worker = next(s for s in tracer.export() if s["name"] == "worker")
    assert worker["parent_id"] is None  # not a child of "main"
    assert worker["thread"] != "MainThread"


def test_phase_totals_and_jsonl(tmp_path):
    tracer = Tracer()
    for _ in range(3):
        with tracer.span("phase-a"):
            pass
    with tracer.span("phase-b"):
        pass
    totals = tracer.phase_totals()
    assert set(totals) == {"phase-a", "phase-b"}
    path = str(tmp_path / "spans.jsonl")
    tracer.write_jsonl(path)
    lines = [json.loads(ln) for ln in open(path)]
    assert len(lines) == 4


# ----------------------------------------------------------------- session


def test_disabled_session_is_a_full_noop(tmp_path):
    session = TelemetrySession("test", enabled=False)
    session.counter("c").inc()
    session.gauge("g").set(1)
    session.histogram("h").observe(1)
    with session.span("phase") as sp:
        sp.set_attribute("k", "v")
    assert session.finalize(str(tmp_path)) is None
    assert not os.path.exists(str(tmp_path / "telemetry"))
    # The shared NULL_SESSION behaves identically (library default arg).
    with NULL_SESSION.span("x") as sp:
        sp.set_attribute("a", 1)


def test_session_finalize_writes_artifacts(tmp_path):
    session = TelemetrySession("unittest")
    session.counter("rows").inc(5)
    with session.span("load"):
        pass
    report = session.finalize(str(tmp_path), extra={"note": "hi"})
    assert report["status"] == "success"
    assert report["driver"] == "unittest"
    assert report["extra"] == {"note": "hi"}
    tdir = tmp_path / "telemetry"
    with open(tdir / "run_report.json") as f:
        persisted = json.load(f)
    assert persisted["metrics"]["counters"][0]["value"] == 5
    assert [s["name"] for s in persisted["spans"]] == ["load"]
    assert (tdir / "spans.jsonl").exists()
    # Finalize is idempotent: the error path after a success write is a no-op.
    again = session.finalize(str(tmp_path), status="error", error="nope")
    assert again["status"] == "success"


def test_finalize_survives_non_json_attributes(tmp_path):
    """Telemetry must never crash the run it observes: non-JSON span
    attributes (numpy scalars etc.) degrade to strings at write time."""
    session = TelemetrySession("hardening")
    with session.span("phase") as sp:
        sp.set_attribute("np_scalar", np.float32(1.5))
        sp.set_attribute("array", np.arange(3))
    report = session.finalize(str(tmp_path))
    assert report["status"] == "success"
    persisted = json.load(open(tmp_path / "telemetry" / "run_report.json"))
    assert persisted["spans"][0]["attributes"]["np_scalar"] == "1.5"


def test_finalize_never_raises_on_unwritable_dir(tmp_path):
    """A telemetry write failure must not crash an otherwise-successful
    run — and on the driver error path must not replace the real
    exception with a telemetry traceback."""
    # Output dir nested under a regular FILE: makedirs fails regardless of
    # uid (chmod-based denial is a no-op when the suite runs as root).
    blocker = tmp_path / "blocker"
    blocker.write_text("")
    target = blocker / "out"
    session = TelemetrySession("hardening")
    session.counter("c").inc()
    report = session.finalize(str(target))  # must not raise
    assert report is not None and report["status"] == "success"
    assert not os.path.exists(str(target))


def test_session_write_gate_skips_files(tmp_path):
    session = TelemetrySession("rank1")
    session.write = False  # non-primary rank
    report = session.finalize(str(tmp_path))
    assert report is not None  # report still built...
    assert not os.path.exists(str(tmp_path / "telemetry"))  # ...nothing written


def test_env_var_gate(monkeypatch):
    assert telemetry_enabled(None) is True
    assert telemetry_enabled(False) is False
    monkeypatch.setenv("PHOTON_TELEMETRY", "off")
    assert telemetry_enabled(None) is False
    assert telemetry_enabled(True) is False  # env wins over the flag
    monkeypatch.setenv("PHOTON_TELEMETRY", "on")
    assert telemetry_enabled(None) is True


def test_logger_timed_feeds_tracer():
    from photon_tpu.utils import PhotonLogger

    logger = PhotonLogger("photon_tpu.test_telemetry")
    session = TelemetrySession("logger-test")
    session.attach(logger)
    with logger.timed("outer-phase"):
        with logger.timed("inner-phase"):
            pass
    assert "outer-phase" in logger.phase_times  # legacy dict still fed
    spans = {s["name"]: s for s in session.tracer.export()}
    assert spans["inner-phase"]["parent_id"] == spans["outer-phase"]["span_id"]


# ----------------------------------------------------- optimizer recording


def test_tracker_record_to():
    from photon_tpu.core.optimizers import OptimizationStatesTracker
    from photon_tpu.core.optimizers.base import OptimizerResult

    result = OptimizerResult(
        w=np.zeros(3, np.float32),
        value=np.float32(1.5),
        grad_norm=np.float32(0.01),
        iterations=np.int32(4),
        converged=np.bool_(True),
        reason=np.int32(2),  # FUNCTION_VALUES_TOLERANCE
        history_value=np.array([3.0, 2.0, 1.8, 1.6, 1.5, 0.0], np.float32),
        history_grad_norm=np.array([1.0, 0.5, 0.1, 0.05, 0.01, 0.0], np.float32),
        history_valid=np.array([1, 1, 1, 1, 1, 0], bool),
    )
    tracker = OptimizationStatesTracker(result, wall_time_s=0.25)
    reg = MetricsRegistry()
    tracker.record_to(reg, lam=0.5)
    assert reg.counter("optimizer.solves", lam="0.5").value == 1
    assert reg.counter("optimizer.iterations", lam="0.5").value == 4
    assert reg.counter("optimizer.converged_solves", lam="0.5").value == 1
    assert reg.counter(
        "optimizer.stop_reason", lam="0.5",
        reason="FUNCTION_VALUES_TOLERANCE",
    ).value == 1
    assert reg.histogram("optimizer.solve_seconds", lam="0.5").count == 1
    assert reg.gauge("optimizer.final_value", lam="0.5").value == pytest.approx(1.5)


# ----------------------------------------------------------------- reports


def test_render_markdown_and_cli(tmp_path, capsys):
    session = TelemetrySession("render-test")
    session.counter("rows", kind="train").inc(7)
    session.histogram("seconds").observe(0.5)
    with session.span("load"):
        with session.span("parse"):
            pass
    session.finalize(str(tmp_path))
    text = render_markdown(
        json.load(open(tmp_path / "telemetry" / "run_report.json"))
    )
    assert "# Run report: render-test" in text
    assert "| rows | kind=train | 7 |" in text
    assert "- load:" in text and "  - parse:" in text  # tree indentation

    # CLI: a driver output dir resolves to its nested run_report.json.
    assert resolve_report_path(str(tmp_path)).endswith(
        os.path.join("telemetry", "run_report.json")
    )
    out_md = str(tmp_path / "report.md")
    report_cli.main([str(tmp_path), "-o", out_md])
    assert "# Run report: render-test" in open(out_md).read()
    report_cli.main([str(tmp_path)])
    assert "# Run report: render-test" in capsys.readouterr().out


def test_render_markdown_checkpoint_pipeline_section(tmp_path):
    """Publisher lag/blocked histograms and io-pool gauges surface as their
    own section (ISSUE 5 satellite); absent metrics -> absent section."""
    session = TelemetrySession("pipeline-test")
    session.counter("checkpoint.saves").inc(3)
    session.histogram("checkpoint.write_seconds").observe(0.01)
    session.histogram("checkpoint.blocked_s").observe(0.0)
    session.histogram("checkpoint.publish_lag_s").observe(0.2)
    session.gauge("io_pool.workers").set(4)
    session.gauge("io_pool.in_flight_peak").set(8)
    session.finalize(str(tmp_path))
    text = render_markdown(
        json.load(open(tmp_path / "telemetry" / "run_report.json"))
    )
    assert "## Checkpoint pipeline" in text
    assert "**saves**: 3" in text
    assert "checkpoint.publish_lag_s" in text
    assert "## Host-IO pool" in text
    assert "**io_pool.in_flight_peak**: 8" in text

    plain = TelemetrySession("no-pipeline")
    plain.counter("rows").inc()
    plain.finalize(str(tmp_path / "plain"))
    text2 = render_markdown(
        json.load(open(tmp_path / "plain" / "telemetry" / "run_report.json"))
    )
    assert "## Checkpoint pipeline" not in text2
    assert "## Host-IO pool" not in text2


def test_render_markdown_streaming_tiers_section(tmp_path):
    """The stream.*/tiles.* row block (ISSUE 11): per-tier stall/overlap
    table + the host-cache/disk-store shape of a spilled run; absent
    metrics -> absent section."""
    session = TelemetrySession("ooc-test")
    session.counter("stream.chunks").inc(24)
    session.counter("stream.stall_s", tier="h2d").inc(0.25)
    session.counter("stream.stall_s", tier="disk").inc(1.5)
    session.counter("stream.prefetch_overlap_s", tier="h2d").inc(0.75)
    session.counter("stream.prefetch_overlap_s", tier="disk").inc(2.0)
    session.counter("tiles.cache_hits").inc(90)
    session.counter("tiles.cache_misses").inc(10)
    session.counter("tiles.cache_evictions").inc(4)
    session.gauge("tiles.host_cache_bytes").set(8192)
    session.gauge("tiles.disk_bytes").set(1 << 20)
    session.finalize(str(tmp_path))
    text = render_markdown(
        json.load(open(tmp_path / "telemetry" / "run_report.json"))
    )
    assert "## Streaming tiers" in text
    assert "**chunks delivered**: 24" in text
    assert "| disk | 1.5 | 2 |" in text
    assert "| h2d | 0.25 | 0.75 |" in text
    assert "**tiles.cache_evictions**: 4" in text
    assert "**tiles.host_cache_bytes**: 8192" in text
    assert "**tiles.disk_bytes**:" in text

    plain = TelemetrySession("no-stream")
    plain.counter("rows").inc()
    plain.finalize(str(tmp_path / "plain"))
    text2 = render_markdown(
        json.load(open(tmp_path / "plain" / "telemetry" / "run_report.json"))
    )
    assert "## Streaming tiers" not in text2


def test_render_markdown_serving_section(tmp_path):
    """The serving.* row block (ISSUE 9 satellite): request/batch counters,
    the coalescing and host-syncs-per-batch ratios, latency distributions;
    absent metrics -> absent section."""
    session = TelemetrySession("serving-test")
    session.counter("serving.requests").inc(40)
    session.counter("serving.batches", bucket=8).inc(6)
    session.counter("serving.batches", bucket=64).inc(4)
    session.counter("serving.rows").inc(320)
    session.counter("serving.host_syncs").inc(10)
    session.counter("serving.cold_entities", coordinate="per_user").inc(3)
    session.counter("serving.compilations").inc(5)
    session.gauge("serving.qps").set(1234.5)
    session.histogram("serving.request_latency_s").observe(0.002)
    session.histogram("serving.padded_fraction").observe(0.25)
    session.finalize(str(tmp_path))
    text = render_markdown(
        json.load(open(tmp_path / "telemetry" / "run_report.json"))
    )
    assert "## Online serving" in text
    assert "| serving.requests | 40 |" in text
    assert "| serving.batches | 10 |" in text  # summed over bucket labels
    assert "| requests per batch (coalescing) | 4 |" in text
    assert "| serving.host_syncs per batch | 1 |" in text
    assert "| serving.cold_entities | 3 |" in text
    assert "| serving.qps | 1234.5 |" in text
    assert "serving.request_latency_s" in text
    assert "serving.padded_fraction" in text

    plain = TelemetrySession("no-serving")
    plain.counter("rows").inc()
    plain.finalize(str(tmp_path / "plain"))
    text2 = render_markdown(
        json.load(open(tmp_path / "plain" / "telemetry" / "run_report.json"))
    )
    assert "## Online serving" not in text2


def test_render_markdown_online_section(tmp_path):
    """The online.* row block (ISSUE 15 satellite): ingest/refresh/lock
    counters, the in-place growth split, the refresh-latency distribution,
    and the per-bin capacity-headroom table; absent metrics -> absent
    section."""
    session = TelemetrySession("online-test")
    session.counter("online.refreshes").inc(3)
    session.counter("online.batches_ingested").inc(4)
    session.counter("online.rows_ingested").inc(500)
    session.counter("online.coordinates_refreshed").inc(7)
    session.counter("online.coordinates_locked").inc(2)
    session.counter("online.publishes").inc(3)
    session.counter("onboard.rows_in_place", column="userId").inc(420)
    session.counter("onboard.rows_migrated", column="userId").inc(60)
    session.counter("onboard.entities_migrated", column="userId").inc(2)
    session.counter("onboard.entities_new", column="userId").inc(9)
    session.gauge("online.staleness_s").set(0.0)
    session.gauge("onboard.bin_row_capacity", column="userId", bin=0).set(64)
    session.gauge("onboard.bin_rows_live", column="userId", bin=0).set(50)
    session.gauge("onboard.bin_row_headroom", column="userId", bin=0).set(14)
    session.histogram("online.refresh_latency_s").observe(1.5)
    session.finalize(str(tmp_path))
    text = render_markdown(
        json.load(open(tmp_path / "telemetry" / "run_report.json"))
    )
    assert "## Online learning" in text
    assert "| online.refreshes | 3 |" in text
    assert "| online.rows_ingested | 500 |" in text
    assert "| online.coordinates_refreshed | 7 |" in text
    assert "| online.coordinates_locked | 2 |" in text
    assert "| onboard.rows_in_place | 420 |" in text
    assert "| onboard.entities_migrated | 2 |" in text
    assert "online.refresh_latency_s" in text
    assert "| userId | 0 | 64 | 50 | 14 |" in text

    plain = TelemetrySession("no-online")
    plain.counter("rows").inc()
    plain.finalize(str(tmp_path / "plain"))
    text2 = render_markdown(
        json.load(open(tmp_path / "plain" / "telemetry" / "run_report.json"))
    )
    assert "## Online learning" not in text2


# ------------------------------------------------------ driver integration


@pytest.fixture(scope="module")
def trained_run(tmp_path_factory):
    from photon_tpu.drivers import train as train_driver

    out = str(tmp_path_factory.mktemp("telem_train") / "out")
    summary = train_driver.run(train_driver.build_parser().parse_args([
        "--input", "synthetic:logistic_regression:200:8:0",
        "--validation-input", "synthetic:logistic_regression:100:8:1:0",
        "--reg-weights", "0.5,2.0", "--max-iterations", "10",
        "--output-dir", out, "--backend", "cpu",
    ]))
    return out, summary


def test_train_driver_writes_run_report(trained_run):
    out, _ = trained_run
    with open(os.path.join(out, "telemetry", "run_report.json")) as f:
        report = json.load(f)
    assert report["status"] == "success" and report["error"] is None
    assert report["driver"] == "train"
    counters = {
        (e["name"], tuple(sorted(e["labels"].items()))): e["value"]
        for e in report["metrics"]["counters"]
    }
    # One solve per lambda, recorded by the optimizer tracker.
    assert counters[("optimizer.solves", (("lam", "0.5"), ("optimizer", "lbfgs")))] == 1
    assert counters[("optimizer.solves", (("lam", "2"), ("optimizer", "lbfgs")))] == 1
    assert counters[("train.sweep_entries", ())] == 2
    span_names = {s["name"] for s in report["spans"]}
    assert {"load-data", "train-lambda-0.5", "train-lambda-2.0",
            "save-models"} <= span_names
    assert report["environment"]["jax"]["backend"] == "cpu"
    # Spans mirror the logger's phase-times dict.
    assert set(report["phase_totals"]) == span_names


def test_train_summary_stays_telemetry_free(trained_run):
    """training_summary.json must stay byte-stable across identical runs
    (the determinism contract) — all wall-clock telemetry lives in the
    separate telemetry/ artifacts."""
    out, summary = trained_run
    assert "telemetry" not in summary
    with open(os.path.join(out, "training_summary.json")) as f:
        assert "run_id" not in json.load(f)


def test_no_telemetry_flag_writes_nothing(tmp_path):
    from photon_tpu.drivers import train as train_driver

    out = str(tmp_path / "out")
    train_driver.run(train_driver.build_parser().parse_args([
        "--input", "synthetic:logistic_regression:100:6:0",
        "--reg-weights", "1.0", "--max-iterations", "5",
        "--output-dir", out, "--backend", "cpu", "--no-telemetry",
    ]))
    assert os.path.exists(os.path.join(out, "best_model.avro"))
    assert not os.path.exists(os.path.join(out, "telemetry"))


def test_failed_run_leaves_error_report(tmp_path):
    from photon_tpu.drivers import train as train_driver

    out = str(tmp_path / "out")
    with pytest.raises(FileNotFoundError):
        train_driver.run(train_driver.build_parser().parse_args([
            "--input", str(tmp_path / "does-not-exist.libsvm"),
            "--output-dir", out, "--backend", "cpu",
        ]))
    with open(os.path.join(out, "telemetry", "run_report.json")) as f:
        report = json.load(f)
    assert report["status"] == "error"
    assert "FileNotFoundError" in report["error"]


def test_multiprocess_prebody_failure_writes_rank0_only(tmp_path):
    """A distributed run that dies before the driver body learns its rank
    from jax.process_index() (bad input path on every rank) must not have
    N processes writing the same run_report.json: telemetry_run gates the
    error-path write on the operator-declared --process-id."""
    import argparse

    from photon_tpu.drivers.common import telemetry_run
    from photon_tpu.utils import PhotonLogger

    def attempt(outdir, **distributed):
        args = argparse.Namespace(
            telemetry=True, output_dir=str(outdir), **distributed
        )
        logger = PhotonLogger("photon_tpu.test_telemetry")
        with pytest.raises(RuntimeError):
            with telemetry_run(args, "train", logger):
                raise RuntimeError("pre-body failure")
        return os.path.exists(
            os.path.join(str(outdir), "telemetry", "run_report.json")
        )

    assert attempt(tmp_path / "rank1", coordinator="h:1", process_id=1,
                   num_processes=2) is False
    assert attempt(tmp_path / "rank0", coordinator="h:1", process_id=0,
                   num_processes=2) is True
    assert attempt(tmp_path / "single") is True  # no --coordinator: write


def test_stream_score_parts_keeps_one_span(tmp_path):
    """Streamed scoring exists for beyond-host-memory part layouts, so it
    must not retain one Span per part file: the loop gets a single
    stream-score span (per-chunk timing lives in the bounded stream.*
    histograms), while the per-file phase logs/phase_times stay."""
    from types import SimpleNamespace

    from photon_tpu.drivers.common import stream_score_parts
    from photon_tpu.utils import PhotonLogger

    parts = tmp_path / "parts"
    parts.mkdir()
    for i in range(3):
        (parts / f"part-{i:05d}").write_text("x\n")

    logger = PhotonLogger("photon_tpu.test_telemetry")
    session = TelemetrySession("stream-test")
    session.attach(logger)
    chunk = SimpleNamespace(num_examples=2)
    n = stream_score_parts(
        str(parts),
        lambda path: chunk,
        lambda c: (np.zeros(2), np.zeros(2), c.num_examples),
        str(tmp_path / "scores.txt"),
        logger, telemetry=session,
    )
    assert n == 6
    names = [s["name"] for s in session.tracer.export()]
    assert names == ["stream-score"]  # one span total, not one per file
    assert session.registry.histogram("stream.chunk_seconds").count == 3
    # The per-file phase timing still reaches the legacy phase_times dict.
    assert sum(1 for k in logger.phase_times if k.startswith("score-")) == 3


def test_game_driver_telemetry(tmp_path):
    from photon_tpu.drivers import train_game

    out = str(tmp_path / "out")
    train_game.run(train_game.build_parser().parse_args([
        "--input", "synthetic-game:12:4:6:3:1:5",
        "--validation-split", "0.25",
        "--coordinate", "fixed:type=fixed,shard=global,max_iters=5",
        "--coordinate", "per0:type=random,shard=re0,entity=re0,max_iters=3",
        "--descent-iterations", "2",
        "--output-dir", out, "--backend", "cpu",
    ]))
    with open(os.path.join(out, "telemetry", "run_report.json")) as f:
        report = json.load(f)
    assert report["status"] == "success"
    counters = {
        (e["name"], tuple(sorted(e["labels"].items()))): e["value"]
        for e in report["metrics"]["counters"]
    }
    assert counters[("descent.iterations", ())] == 2
    assert counters[("descent.coordinate_updates", (("coordinate", "fixed"),))] == 2
    assert counters[("estimator.configurations", ())] == 1
    # Fixed effect records through the tracker, random through entity stats.
    assert counters[("optimizer.solves", (("coordinate", "fixed"),))] == 2
    assert counters[("re_solver.entities", (("coordinate", "per0"),))] > 0
    span_names = [s["name"] for s in report["spans"]]
    assert span_names.count("descent.iteration") == 2
    assert "estimator.fit" in span_names
    # The descent iteration span carries the validation metrics.
    iter_spans = [s for s in report["spans"] if s["name"] == "descent.iteration"]
    assert any("metrics" in s.get("attributes", {}) for s in iter_spans)
    gauges = {e["name"] for e in report["metrics"]["gauges"]}
    assert "descent.validation_metric" in gauges
