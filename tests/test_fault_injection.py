"""Fault-tolerance tests: fault plan, retry/backoff, atomic checkpoints,
kill-and-resume parity, and quarantine-based graceful degradation.

The acceptance bar (ISSUE 4): a fit killed after outer iteration k and
resumed from its checkpoint matches an uninterrupted fit to <= 1e-6 (host
AND device residual modes); injected IO faults and a NaN bucket solve
complete the sweep via retry/quarantine with telemetry evidence; checkpoint
writes are atomic (no torn manifest after a kill during write)."""

import json
import os

import numpy as np
import pytest

from photon_tpu.core.objective import RegularizationContext
from photon_tpu.core.optimizers import OptimizerConfig
from photon_tpu.core.problem import ProblemConfig
from photon_tpu.data.synthetic import make_game_dataset
from photon_tpu.fault import (
    QuarantineBudgetError,
    RetryPolicy,
    retry_call,
    verify_manifest,
)
from photon_tpu.fault.checkpoint import DescentCheckpointer
from photon_tpu.fault.injection import (
    FaultPlan,
    InjectedIOError,
    InjectedKillError,
    set_plan,
)
from photon_tpu.game.coordinate import (
    FixedEffectCoordinateConfig,
    RandomEffectCoordinateConfig,
)
from photon_tpu.game.data import split_game_dataset
from photon_tpu.game.estimator import GameEstimator, GameOptimizationConfiguration
from photon_tpu.telemetry import TelemetrySession


@pytest.fixture(autouse=True)
def _fault_hygiene(monkeypatch):
    """No test leaks a fault plan or pays real backoff sleeps."""
    monkeypatch.setenv("PHOTON_IO_RETRY_BASE_S", "0")
    set_plan(None)
    yield
    set_plan(None)


def _problem(lam: float, iters: int) -> ProblemConfig:
    return ProblemConfig(
        regularization=RegularizationContext("l2", lam),
        optimizer_config=OptimizerConfig(max_iterations=iters),
    )


def _game_fixture(seed: int = 7):
    data, _ = make_game_dataset(40, 5, 6, 3, seed=seed)
    train, val = split_game_dataset(data, 0.25)
    config = GameOptimizationConfiguration(
        coordinates={
            "fixed": FixedEffectCoordinateConfig("global", _problem(0.01, 8)),
            "re0": RandomEffectCoordinateConfig("re0", "re0", _problem(1.0, 6)),
        },
        descent_iterations=3,
        name="ckpt-test",
    )
    return train, val, config


def _coordinate_arrays(model):
    out = {}
    for name, coord in model.coordinates.items():
        if hasattr(coord, "table"):
            out[name] = np.asarray(coord.table)
        else:
            out[name] = np.asarray(coord.coefficients.means)
    return out


# -- fault plan --------------------------------------------------------------


def test_fault_plan_parse_and_determinism():
    spec = "io:read:p=0.3,descent:kill:iter=2,solve:nan:coord=re0"
    plan = FaultPlan.parse(spec, seed=5)
    assert [r.site for r in plan.rules] == ["io:read", "descent:kill", "solve:nan"]

    # Probabilistic rules fire at the same call positions for the same seed.
    def fire_pattern():
        p = FaultPlan.parse(spec, seed=5)
        return [p.consume("io:read") is not None for _ in range(50)]

    a, b = fire_pattern(), fire_pattern()
    assert a == b
    assert any(a) and not all(a)

    # Deterministic rules: kill only at its iteration, once by default.
    kill = FaultPlan.parse("descent:kill:iter=2", seed=0)
    assert kill.consume("descent:kill", iteration=1) is None
    assert kill.consume("descent:kill", iteration=2) is not None
    assert kill.consume("descent:kill", iteration=2) is None  # times=1

    # nan rule is addressed by coordinate name.
    nan = FaultPlan.parse("solve:nan:coord=re0", seed=0)
    assert nan.consume("solve:nan", coordinate="fixed") is None
    assert nan.consume("solve:nan", coordinate="re0") is not None
    assert nan.consume("solve:nan", coordinate="re0") is None

    with pytest.raises(ValueError):
        FaultPlan.parse("justonetoken")
    with pytest.raises(ValueError):
        FaultPlan.parse("io:read:oops")


def test_retry_call_recovers_counts_and_raises():
    session = TelemetrySession("t")
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise InjectedIOError("transient")
        return "ok"

    sleeps = []
    out = retry_call(
        flaky, site="unit", telemetry=session,
        policy=RetryPolicy(attempts=5, base_delay_s=0.01),
        sleep=sleeps.append,
    )
    assert out == "ok" and calls["n"] == 3
    counters = {
        (c["name"], c["labels"].get("site")): c["value"]
        for c in session.registry.snapshot()["counters"]
    }
    assert counters[("io.retries", "unit")] == 2
    # Exponential and capped backoff.
    assert len(sleeps) == 2 and sleeps[1] > sleeps[0]

    # Exhausted attempts re-raise the real error.
    with pytest.raises(InjectedIOError):
        retry_call(
            lambda: (_ for _ in ()).throw(InjectedIOError("always")),
            site="unit", policy=RetryPolicy(attempts=2, base_delay_s=0.0),
            sleep=lambda s: None,
        )


# -- IO fault injection + retry ---------------------------------------------


def test_injected_read_faults_retry_to_success(tmp_path):
    from photon_tpu.data.game_io import read_game_avro, write_game_avro

    data, index_maps = make_game_dataset(20, 3, 5, 3, seed=1)
    path = str(tmp_path / "train.avro")
    write_game_avro(path, data, index_maps)
    bags = {name: name for name in data.shards}

    clean, _ = read_game_avro(path, bags, ["re0"])

    session = TelemetrySession("t")
    set_plan(FaultPlan.parse("io:read:p=0.5", seed=3))
    faulted, _ = read_game_avro(path, bags, ["re0"], telemetry=session)
    set_plan(None)

    np.testing.assert_array_equal(clean.label, faulted.label)
    np.testing.assert_array_equal(
        clean.shards["global"].vals, faulted.shards["global"].vals
    )
    retries = [
        c for c in session.registry.snapshot()["counters"]
        if c["name"] == "io.retries"
    ]
    assert sum(c["value"] for c in retries) > 0


def test_streaming_chunk_read_retries(tmp_path, monkeypatch):
    from photon_tpu.data.streaming import LibsvmFileSource

    # p=0.5 per attempt exhausts the default 5-attempt budget ~3% of the
    # time per file; a deeper budget keeps the (seeded, deterministic)
    # test on the recovery path it exists to exercise.
    monkeypatch.setenv("PHOTON_IO_RETRIES", "12")

    paths = []
    for i in range(3):
        p = tmp_path / f"part-{i}.txt"
        p.write_text("".join(
            f"{(r + i) % 2} 1:{0.5 + r} 2:{1.0 + i}\n" for r in range(4)
        ))
        paths.append(str(p))

    clean = [np.asarray(c.label) for c in
             LibsvmFileSource(paths).chunk_iter_factory()]

    session = TelemetrySession("t")
    set_plan(FaultPlan.parse("io:read:p=0.5", seed=11))
    source = LibsvmFileSource(paths, telemetry=session)
    faulted = [np.asarray(c.label) for c in source.chunk_iter_factory()]
    set_plan(None)

    assert len(clean) == len(faulted)
    for a, b in zip(clean, faulted):
        np.testing.assert_array_equal(a, b)
    retries = [
        c for c in session.registry.snapshot()["counters"]
        if c["name"] == "io.retries"
    ]
    assert sum(c["value"] for c in retries) > 0


# -- kill-and-resume parity --------------------------------------------------


@pytest.mark.parametrize("mode", ["device", "host"])
def test_kill_and_resume_matches_uninterrupted(tmp_path, mode):
    train, val, config = _game_fixture()

    def fit(**kw):
        return GameEstimator(
            "logistic_regression", train, val, residual_mode=mode
        ).fit([config], **kw)[0]

    baseline = fit()

    ckpt = str(tmp_path / "ckpt")
    set_plan(FaultPlan.parse("descent:kill:iter=2"))
    with pytest.raises(InjectedKillError):
        fit(checkpoint_dir=ckpt)
    set_plan(None)

    resumed = fit(checkpoint_dir=ckpt, resume="auto")

    for k, v in baseline.metrics.items():
        assert abs(v - resumed.metrics[k]) <= 1e-6
    base_arrays = _coordinate_arrays(baseline.model)
    res_arrays = _coordinate_arrays(resumed.model)
    for name in base_arrays:
        np.testing.assert_allclose(
            base_arrays[name], res_arrays[name], atol=1e-6, rtol=0
        )
    # History covers ALL iterations (pre-kill ones restored from snapshot).
    assert [h["iteration"] for h in resumed.descent.history] == [0, 1, 2]


def test_resume_rejects_mismatched_configuration(tmp_path):
    train, val, config = _game_fixture()
    ckpt = str(tmp_path / "ckpt")
    GameEstimator("logistic_regression", train, val).fit(
        [config], checkpoint_dir=ckpt
    )
    # A different coordinate set must be refused even though the checkpoint
    # is COMPLETE (the completed short-circuit must not bypass the check).
    other = GameOptimizationConfiguration(
        coordinates={
            "fixed": FixedEffectCoordinateConfig("global", _problem(0.01, 8)),
        },
        descent_iterations=3,
        name="other",
    )
    from photon_tpu.fault.checkpoint import CheckpointError

    with pytest.raises(CheckpointError):
        GameEstimator("logistic_regression", train, val).fit(
            [other], checkpoint_dir=ckpt, resume="auto"
        )


def test_resume_rejects_different_regularization(tmp_path):
    train, val, config = _game_fixture()
    ckpt = str(tmp_path / "ckpt")
    GameEstimator("logistic_regression", train, val).fit(
        [config], checkpoint_dir=ckpt
    )
    # Same coordinates, different reg weight: a different sweep point must
    # not adopt this checkpoint (the config-key fingerprint component).
    other = GameOptimizationConfiguration(
        coordinates={
            "fixed": FixedEffectCoordinateConfig("global", _problem(0.01, 8)),
            "re0": RandomEffectCoordinateConfig(
                "re0", "re0", _problem(100.0, 6)
            ),
        },
        descent_iterations=3,
        name="other-lambda",
    )
    from photon_tpu.fault.checkpoint import CheckpointError

    with pytest.raises(CheckpointError):
        GameEstimator("logistic_regression", train, val).fit(
            [other], checkpoint_dir=ckpt, resume="auto"
        )


def test_resume_with_raised_iterations_runs_the_extra_passes(tmp_path):
    train, val, config = _game_fixture()
    ckpt = str(tmp_path / "ckpt")
    GameEstimator("logistic_regression", train, val).fit(
        [config], checkpoint_dir=ckpt
    )
    import dataclasses

    longer = dataclasses.replace(config, descent_iterations=4)
    result = GameEstimator("logistic_regression", train, val).fit(
        [longer], checkpoint_dir=ckpt, resume="auto"
    )[0]
    # The completed 3-iteration checkpoint resumes and runs iteration 3.
    assert [h["iteration"] for h in result.descent.history] == [0, 1, 2, 3]


def test_resume_latest_requires_checkpoint(tmp_path):
    train, val, config = _game_fixture()
    est = GameEstimator("logistic_regression", train, val)
    from photon_tpu.fault.checkpoint import CheckpointError

    with pytest.raises(CheckpointError):
        est.fit([config], checkpoint_dir=str(tmp_path / "none"), resume="latest")


def test_driver_resume_latest_rejects_unpublished_debris(tmp_path):
    # A run killed before its first checkpoint publish leaves only hidden
    # .tmp-* debris: --resume latest must refuse, not silently retrain.
    from photon_tpu.drivers import train_game

    debris = tmp_path / "ckpt" / "000-x" / "cfg-000" / ".tmp-ckpt-000000-1"
    debris.mkdir(parents=True)
    args = train_game.build_parser().parse_args(
        _driver_args(tmp_path, "out", [
            "--checkpoint-dir", str(tmp_path / "ckpt"), "--resume", "latest",
        ])
    )
    with pytest.raises(ValueError, match="no published checkpoint"):
        train_game.run(args)


def test_completed_config_restores_without_refit(tmp_path):
    train, val, config = _game_fixture()
    ckpt = str(tmp_path / "ckpt")
    session = TelemetrySession("t")
    est = GameEstimator("logistic_regression", train, val, telemetry=session)
    first = est.fit([config], checkpoint_dir=ckpt)[0]

    second = est.fit([config], checkpoint_dir=ckpt, resume="auto")[0]
    counters = {
        c["name"]: c["value"] for c in session.registry.snapshot()["counters"]
        if c["name"].startswith("estimator.")
    }
    assert counters.get("estimator.configurations_resumed") == 1
    assert counters.get("estimator.configurations") == 1  # only the first ran
    assert second.metrics == first.metrics
    a, b = _coordinate_arrays(first.model), _coordinate_arrays(second.model)
    for name in a:
        np.testing.assert_array_equal(a[name], b[name])


# -- checkpoint atomicity ----------------------------------------------------


def test_checkpoint_survives_kill_during_write(tmp_path):
    train, val, config = _game_fixture()
    ckpt = str(tmp_path / "ckpt")
    est = GameEstimator("logistic_regression", train, val)
    est.fit([config], checkpoint_dir=ckpt)

    cfg_dir = os.path.join(ckpt, "cfg-000")
    checkpointer = DescentCheckpointer(cfg_dir)
    before = checkpointer.latest_path()
    assert before is not None
    verify_manifest(before)
    state_before = DescentCheckpointer.load_path(before)

    # Kill the NEXT run inside the checkpoint write (payload written,
    # manifest not): the published chain must be untouched.
    set_plan(FaultPlan.parse("checkpoint:write:times=1"))
    with pytest.raises(InjectedKillError):
        est.fit([config], checkpoint_dir=ckpt)
    set_plan(None)

    after = checkpointer.latest_path()
    assert after == before
    verify_manifest(after)  # no torn manifest
    state_after = DescentCheckpointer.load_path(after)
    assert state_after.iteration == state_before.iteration
    # No half-written visible checkpoint dirs left behind.
    visible = [
        n for n in os.listdir(cfg_dir)
        if n.startswith("ckpt-") and not n.startswith(".")
    ]
    for name in visible:
        verify_manifest(os.path.join(cfg_dir, name))


def test_manifest_detects_corruption(tmp_path):
    train, val, config = _game_fixture()
    ckpt = str(tmp_path / "ckpt")
    GameEstimator("logistic_regression", train, val).fit(
        [config], checkpoint_dir=ckpt
    )
    path = DescentCheckpointer(os.path.join(ckpt, "cfg-000")).latest_path()
    state_file = os.path.join(path, "state.json")
    with open(state_file, "a") as f:
        f.write(" ")
    from photon_tpu.fault import CorruptArtifactError

    with pytest.raises(CorruptArtifactError):
        DescentCheckpointer.load_path(path)


# -- quarantine --------------------------------------------------------------


def test_nan_bucket_solve_quarantined_and_sweep_completes():
    train, val, config = _game_fixture()
    session = TelemetrySession("t")
    set_plan(FaultPlan.parse("solve:nan:coord=re0"))
    result = GameEstimator(
        "logistic_regression", train, val, telemetry=session
    ).fit([config], max_quarantined=10)[0]
    set_plan(None)

    quarantined = [
        c for c in session.registry.snapshot()["counters"]
        if c["name"] == "descent.quarantined"
    ]
    assert sum(c["value"] for c in quarantined) > 0
    assert all(np.isfinite(v) for v in result.metrics.values())
    for arr in _coordinate_arrays(result.model).values():
        assert np.isfinite(arr).all()


def test_nonfinite_initial_model_quarantined_at_seed():
    import dataclasses as dc

    import jax.numpy as jnp

    train, val, config = _game_fixture()
    fitted = GameEstimator("logistic_regression", train, val).fit([config])[0]
    re0 = fitted.model.coordinates["re0"]
    corrupted = dc.replace(
        re0, table=jnp.asarray(np.asarray(re0.table)).at[0].set(jnp.nan)
    )
    from photon_tpu.game.model import GameModel

    bad_initial = GameModel(
        {**fitted.model.coordinates, "re0": corrupted}, "logistic_regression"
    )
    session = TelemetrySession("t")
    result = GameEstimator(
        "logistic_regression", train, val, telemetry=session
    ).fit([config], initial_model=bad_initial, max_quarantined=10)[0]
    # The rejection is attributed to the SEEDING (not iteration 0's trained
    # iterate), the run completes, and the final model is finite.
    seed_q = [
        c for c in session.registry.snapshot()["counters"]
        if c["name"] == "descent.quarantined"
        and c["labels"].get("stage") == "seed"
    ]
    assert sum(c["value"] for c in seed_q) == 1
    for arr in _coordinate_arrays(result.model).values():
        assert np.isfinite(arr).all()
    assert all(np.isfinite(v) for v in result.metrics.values())


def test_quarantine_budget_exceeded_fails_loudly():
    train, val, config = _game_fixture()
    set_plan(FaultPlan.parse("solve:nan:coord=re0"))
    with pytest.raises(QuarantineBudgetError):
        GameEstimator("logistic_regression", train, val).fit(
            [config], max_quarantined=0
        )


def test_score_table_guard_rejects_nonfinite_row():
    from photon_tpu.game.residuals import ResidualEngine

    session = TelemetrySession("t")
    engine = ResidualEngine(
        np.zeros(8, np.float32), names=["a", "b"], telemetry=session
    )
    good = np.linspace(0.0, 1.0, 8).astype(np.float32)
    engine.update("a", good)
    poisoned = good.copy()
    poisoned[3] = np.nan
    engine.update("b", poisoned)
    assert engine.poll_quarantined() == ["b"]
    # b's row kept its previous (zero) iterate; totals stay finite.
    np.testing.assert_allclose(np.asarray(engine.scores_for("b")), 0.0)
    np.testing.assert_allclose(
        np.asarray(engine.offsets_for("b")), good, atol=1e-7
    )


# -- failed-run telemetry (satellite: error report mid-descent) --------------


def _driver_args(tmp_path, out_name, extra=()):
    return [
        "--backend", "cpu",
        "--input", "synthetic-game:30:4:6:3",
        "--task", "logistic_regression",
        "--coordinate", "fixed:type=fixed,shard=global,max_iters=6",
        "--coordinate", "re0:type=random,shard=re0,entity=re0,max_iters=5",
        "--descent-iterations", "2",
        "--validation-split", "0.25",
        "--output-dir", str(tmp_path / out_name),
        *extra,
    ]


def test_mid_descent_kill_leaves_error_run_report(tmp_path):
    from photon_tpu.drivers import train_game

    args = train_game.build_parser().parse_args(
        _driver_args(tmp_path, "killed", [
            "--faults", "descent:kill:iter=1",
            "--checkpoint-dir", str(tmp_path / "ckpt"),
        ])
    )
    with pytest.raises(InjectedKillError):
        train_game.run(args)

    # The --faults plan is scoped to the run: telemetry_run cleared it even
    # though the run died, so a later in-process run is not injected.
    from photon_tpu.fault.injection import active_plan

    assert active_plan() is None

    report_path = tmp_path / "killed" / "telemetry" / "run_report.json"
    with open(report_path) as f:
        report = json.load(f)
    assert report["status"] == "error"
    assert "InjectedKillError" in report["error"]
    # Partial span tree: iteration 0 ran to completion before the kill.
    span_names = [s["name"] for s in report["spans"]]
    assert "descent.iteration" in span_names
    assert "descent.checkpoint.save" in span_names


def test_driver_kill_resume_roundtrip_matches_uninterrupted(tmp_path):
    from photon_tpu.drivers import train_game

    baseline = train_game.run(
        train_game.build_parser().parse_args(_driver_args(tmp_path, "base"))
    )

    ckpt = str(tmp_path / "ckpt2")
    with pytest.raises(InjectedKillError):
        train_game.run(train_game.build_parser().parse_args(
            _driver_args(tmp_path, "killed2", [
                "--faults", "descent:kill:iter=1",
                "--checkpoint-dir", ckpt,
            ])
        ))
    set_plan(None)  # the driver installed the plan process-wide
    resumed = train_game.run(train_game.build_parser().parse_args(
        _driver_args(tmp_path, "resumed", [
            "--checkpoint-dir", ckpt, "--resume", "latest",
        ])
    ))
    for k, v in baseline["best_metrics"].items():
        assert abs(v - resumed["best_metrics"][k]) <= 1e-6


# -- streamed GLM: kill -> resume through the driver -------------------------


def _stream_files(tmp_path, n_files=2, rows=80, d=12):
    from photon_tpu.data.synthetic import make_glm_data, write_libsvm

    paths = []
    for i in range(n_files):
        b, _ = make_glm_data(rows, d, seed=11 + i, weight_seed=7)
        p = str(tmp_path / f"part-{i}.libsvm")
        write_libsvm(p, np.asarray(b.x)[:, :-1], np.asarray(b.label))
        paths.append(p)
    return str(tmp_path / "part-*.libsvm")


def test_streamed_driver_kill_resume_roundtrip(tmp_path):
    from photon_tpu.drivers import train

    glob_spec = _stream_files(tmp_path)

    def stream_args(out, extra=()):
        return train.build_parser().parse_args([
            "--backend", "cpu", "--stream", "--input", glob_spec,
            "--task", "logistic_regression", "--reg-weights", "0.5,2.0",
            "--max-iterations", "12",
            "--output-dir", str(tmp_path / out), *extra,
        ])

    baseline = train.run(stream_args("base"))

    ckpt = str(tmp_path / "ckpt")
    with pytest.raises(InjectedKillError):
        train.run(stream_args("killed", [
            "--checkpoint-dir", ckpt, "--faults", "stream:kill:iter=4",
        ]))
    set_plan(None)  # the driver installed the plan process-wide

    resumed = train.run(stream_args("resumed", [
        "--checkpoint-dir", ckpt, "--resume", "latest",
    ]))
    # Optimizer trajectories are EXACTLY the uninterrupted ones — for the
    # completed weight (rebuilt from its final snapshot) and the
    # interrupted one (continued mid-fit) alike.
    for ea, eb in zip(baseline["sweep"], resumed["sweep"]):
        assert ea["final_value"] == eb["final_value"]
        assert ea["iterations"] == eb["iterations"]
        assert ea["convergence_reason"] == eb["convergence_reason"]


def test_streamed_resume_latest_requires_published_checkpoint(tmp_path):
    from photon_tpu.drivers import train

    glob_spec = _stream_files(tmp_path)
    args = train.build_parser().parse_args([
        "--backend", "cpu", "--stream", "--input", glob_spec,
        "--max-iterations", "4",
        "--output-dir", str(tmp_path / "out"),
        "--checkpoint-dir", str(tmp_path / "empty"), "--resume", "latest",
    ])
    with pytest.raises(ValueError, match="no published checkpoint"):
        train.run(args)


def test_resident_driver_resume_flag_validation(tmp_path):
    # The resident path now supports checkpoints (see test_elastic.py for
    # the resume behavior) but keeps the same flag strictness as --stream.
    from photon_tpu.drivers import train

    base = [
        "--backend", "cpu",
        "--input", "synthetic:logistic_regression:100:10:3:5",
        "--output-dir", str(tmp_path / "out"),
    ]
    with pytest.raises(ValueError, match="--resume needs --checkpoint-dir"):
        train.run(train.build_parser().parse_args(base + ["--resume", "auto"]))
    with pytest.raises(ValueError, match="no published checkpoint"):
        train.run(train.build_parser().parse_args(base + [
            "--checkpoint-dir", str(tmp_path / "empty"), "--resume", "latest",
        ]))


# -- atomic model export -----------------------------------------------------


def test_save_game_model_atomic_under_injected_failure(tmp_path):
    from photon_tpu.game.model_io import load_game_model, save_game_model

    train, val, config = _game_fixture()
    result = GameEstimator("logistic_regression", train, val).fit([config])[0]
    _, index_maps = make_game_dataset(40, 5, 6, 3, seed=7)

    target = str(tmp_path / "model")
    save_game_model(target, result.model, index_maps)
    loaded_before, _ = load_game_model(target)

    # A failure mid-export (coordinate files written, metadata not) must
    # leave the published directory untouched.
    set_plan(FaultPlan.parse("io:write:times=1"))
    with pytest.raises(InjectedIOError):
        save_game_model(target, result.model, index_maps)
    set_plan(None)

    loaded_after, _ = load_game_model(target)  # still complete + loadable
    assert sorted(loaded_after.coordinates) == sorted(loaded_before.coordinates)
    assert not [
        n for n in os.listdir(tmp_path) if n.startswith(".tmp-")
    ]  # no visible debris outside the target's parent bookkeeping
