"""Distributed-vs-local cross-checks — the reference's key test trick
(SURVEY.md §4): the same objective computed distributed and single-node must
agree to tight tolerance.  Here: 8-virtual-device mesh vs 1 device."""

import jax
import jax.numpy as jnp
import numpy as np

from photon_tpu.core.objective import GlmObjective, RegularizationContext
from photon_tpu.core.optimizers import OptimizerConfig, lbfgs, tron
from photon_tpu.data.batch import dense_batch, sparse_batch_from_rows
from photon_tpu.parallel import DistributedGlmObjective, create_mesh, shard_batch

DIM = 16
N = 100  # not a multiple of 8: exercises zero-weight padding


def _data(seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(N, DIM)).astype(np.float32)
    y = (rng.random(N) < 0.5).astype(np.float32)
    offset = (rng.normal(size=N) * 0.1).astype(np.float32)
    weight = rng.uniform(0.5, 2.0, N).astype(np.float32)
    return x, y, offset, weight


def test_mesh_has_8_devices():
    assert len(jax.devices()) == 8


def test_distributed_value_grad_matches_local():
    x, y, offset, weight = _data()
    local = dense_batch(x, y, offset, weight)
    obj = GlmObjective.create("logistic", RegularizationContext("l2", 0.7))
    mesh = create_mesh()
    dist = DistributedGlmObjective(obj, mesh)
    sharded = shard_batch(local, mesh)
    rng = np.random.default_rng(1)
    w = jnp.asarray(rng.normal(size=DIM).astype(np.float32))
    v_l, g_l = obj.value_and_grad(w, local)
    v_d, g_d = dist.value_and_grad(w, sharded)
    np.testing.assert_allclose(v_l, v_d, rtol=1e-5)
    np.testing.assert_allclose(g_l, g_d, rtol=1e-4, atol=1e-5)


def test_distributed_hvp_and_diag_match_local():
    x, y, offset, weight = _data(2)
    local = dense_batch(x, y, offset, weight)
    obj = GlmObjective.create("poisson", RegularizationContext("l2", 0.3))
    mesh = create_mesh()
    dist = DistributedGlmObjective(obj, mesh)
    sharded = shard_batch(local, mesh)
    rng = np.random.default_rng(3)
    w = jnp.asarray((rng.normal(size=DIM) * 0.1).astype(np.float32))
    v = jnp.asarray(rng.normal(size=DIM).astype(np.float32))
    np.testing.assert_allclose(
        obj.hessian_vector(w, v, local), dist.hessian_vector(w, v, sharded),
        rtol=1e-4, atol=1e-4,
    )
    np.testing.assert_allclose(
        obj.hessian_diagonal(w, local), dist.hessian_diagonal(w, sharded),
        rtol=1e-4, atol=1e-4,
    )


def test_sparse_distributed_matches_local():
    x, y, offset, weight = _data(4)
    rows = []
    for i in range(N):
        ids = np.nonzero(x[i] * (np.arange(DIM) % 3 == i % 3))[0].astype(np.int32)
        rows.append((ids, x[i][ids].astype(np.float32)))
    local = sparse_batch_from_rows(rows, y, offset, weight)
    obj = GlmObjective.create("logistic", RegularizationContext("l2", 0.5))
    mesh = create_mesh()
    dist = DistributedGlmObjective(obj, mesh)
    sharded = shard_batch(local, mesh)
    rng = np.random.default_rng(5)
    w = jnp.asarray(rng.normal(size=DIM).astype(np.float32))
    v_l, g_l = obj.value_and_grad(w, local)
    v_d, g_d = dist.value_and_grad(w, sharded)
    np.testing.assert_allclose(v_l, v_d, rtol=1e-5)
    np.testing.assert_allclose(g_l, g_d, rtol=1e-4, atol=1e-5)


def test_distributed_lbfgs_matches_single_device():
    """Full optimizer run: same data, 1 device vs 8-device mesh — the
    TPU analog of the reference's Spark-local distributed tests."""
    x, y, offset, weight = _data(6)
    local = dense_batch(x, y, offset, weight)
    obj = GlmObjective.create("logistic", RegularizationContext("l2", 1.0))
    cfg = OptimizerConfig(max_iterations=100)
    res_local = lbfgs(jax.jit(lambda w: obj.value_and_grad(w, local)),
                      jnp.zeros(DIM), cfg)

    mesh = create_mesh()
    dist = DistributedGlmObjective(obj, mesh)
    sharded = shard_batch(local, mesh)
    res_dist = lbfgs(jax.jit(dist.bind(sharded)), jnp.zeros(DIM), cfg)
    np.testing.assert_allclose(res_local.value, res_dist.value, rtol=1e-5)
    np.testing.assert_allclose(res_local.w, res_dist.w, rtol=1e-3, atol=1e-4)


def test_distributed_tron_matches_single_device():
    x, y, offset, weight = _data(7)
    local = dense_batch(x, y, offset, weight)
    obj = GlmObjective.create("logistic", RegularizationContext("l2", 0.5))
    cfg = OptimizerConfig(max_iterations=50)
    res_local = tron(
        jax.jit(lambda w: obj.value_and_grad(w, local)), jnp.zeros(DIM), cfg,
        hvp=lambda w, v: obj.hessian_vector(w, v, local),
    )
    mesh = create_mesh()
    dist = DistributedGlmObjective(obj, mesh)
    sharded = shard_batch(local, mesh)
    res_dist = tron(jax.jit(dist.bind(sharded)), jnp.zeros(DIM), cfg,
                    hvp=dist.bind_hvp(sharded))
    np.testing.assert_allclose(res_local.value, res_dist.value, rtol=1e-5)
    np.testing.assert_allclose(res_local.w, res_dist.w, rtol=1e-3, atol=1e-4)
