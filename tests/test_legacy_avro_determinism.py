"""Legacy-driver Avro input (VERDICT r2 item 9) and the SURVEY §5
same-seed -> same-result determinism guarantee."""

import json
import os

import numpy as np
import pytest

from photon_tpu.drivers import train


def _write_glm_avro(path, n=300, d=12, seed=5, w=None):
    from photon_tpu.data.game_io import write_game_avro
    from photon_tpu.data.index_map import IndexMap, feature_key
    from photon_tpu.game.data import DenseShard, GameDataset

    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, d)).astype(np.float32)
    if w is None:
        w = rng.standard_normal(d)
    label = (rng.random(n) < 1 / (1 + np.exp(-(x @ w)))).astype(np.float32)
    x_i = np.concatenate([x, np.ones((n, 1), np.float32)], axis=1)
    data = GameDataset(
        shards={"global": DenseShard(x_i)},
        label=label,
        offset=np.zeros(n, np.float32),
        weight=np.ones(n, np.float32),
        id_columns={},
    )
    maps = {"global": IndexMap.build(
        [feature_key(f"f{i}") for i in range(d)], intercept=True
    )}
    write_game_avro(path, data, maps, feature_bags={"global": "features"})
    return data


def test_legacy_driver_trains_from_avro(tmp_path):
    """--input *.avro through the legacy driver (the reference's
    AvroDataReader feeds its legacy Driver too — SURVEY.md §2.3)."""
    train_avro = str(tmp_path / "train.avro")
    val_avro = str(tmp_path / "val.avro")
    w_true = np.random.default_rng(99).standard_normal(12)
    _write_glm_avro(train_avro, seed=5, w=w_true)
    _write_glm_avro(val_avro, n=200, seed=6, w=w_true)

    out = str(tmp_path / "out")
    summary = train.run(train.build_parser().parse_args([
        "--backend", "cpu",
        "--input", train_avro,
        "--validation-input", val_avro,
        "--task", "logistic_regression",
        "--reg-weights", "1.0", "--max-iterations", "50",
        "--output-dir", out,
    ]))
    assert os.path.exists(os.path.join(out, "best_model.avro"))
    # Same ground-truth model in train and val -> far better than chance.
    assert summary["sweep"][0]["metrics"]["AUC"] > 0.8


def test_avro_validation_requires_index_map():
    with pytest.raises(ValueError, match="training index map"):
        from photon_tpu.drivers import common

        common.load_validation("whatever.avro", 10, True)


def _model_records(out_dir):
    # Avro containers embed a random sync marker, so compare parsed records
    # (exact float equality included), not raw bytes.
    from photon_tpu.data.avro_codec import read_container

    _, recs = read_container(os.path.join(out_dir, "best_model.avro"))
    return recs


def test_same_seed_same_result_full_driver_run(tmp_path):
    """SURVEY.md §5: JAX's functional model makes runs reproducible — two
    identical driver invocations must produce byte-identical models and
    identical summaries (modulo wall-clock fields)."""
    argvs = [
        "--backend", "cpu",
        "--input", "synthetic:logistic_regression:256:16:3",
        "--validation-input", "synthetic:logistic_regression:128:16:4:3",
        "--task", "logistic_regression",
        "--reg-weights", "0.5,2.0", "--max-iterations", "15",
        "--variance-computation", "simple",
    ]
    outs = []
    for run_i in range(2):
        out = str(tmp_path / f"run{run_i}")
        summary = train.run(train.build_parser().parse_args(
            argvs + ["--output-dir", out]))
        summary.pop("phase_times", None)
        for entry in summary["sweep"]:
            entry.pop("wall_time_s", None)
        outs.append((out, json.dumps(summary, sort_keys=True)))
    assert _model_records(outs[0][0]) == _model_records(outs[1][0]), (
        "model records differ across identical runs"
    )
    assert outs[0][1] == outs[1][1], "summaries differ across identical runs"


def test_same_seed_same_result_game(tmp_path):
    from photon_tpu.drivers import train_game

    argv = [
        "--backend", "cpu",
        "--input", "synthetic-game:20:4:8:4:1:5",
        "--coordinate", "fixed:type=fixed,shard=global,max_iters=6",
        "--coordinate", "per_user:type=random,shard=re0,entity=re0,max_iters=4",
        "--descent-iterations", "1",
        "--validation-split", "0.25",
    ]
    metrics = []
    for run_i in range(2):
        s = train_game.run(train_game.build_parser().parse_args(
            argv + ["--output-dir", str(tmp_path / f"g{run_i}")]))
        metrics.append(s["best_metrics"])
    assert metrics[0] == metrics[1], f"GAME metrics differ: {metrics}"
