"""Optimizer convergence tests vs closed form / scipy / sklearn-free checks
(the reference tests optimizers on closed-form problems — SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import scipy.optimize

from photon_tpu.core.objective import GlmObjective, RegularizationContext
from photon_tpu.core.optimizers import (
    OptimizationStatesTracker,
    OptimizerConfig,
    lbfgs,
    owlqn,
    tron,
)
from photon_tpu.data.batch import dense_batch

CFG = OptimizerConfig(max_iterations=200, tolerance=1e-10, gradient_tolerance=1e-7)


def _quadratic(A, b):
    def fun(w):
        v = 0.5 * w @ A @ w - b @ w
        return v, A @ w - b
    return fun


def test_lbfgs_quadratic_exact():
    rng = np.random.default_rng(0)
    m = rng.normal(size=(8, 8))
    A = jnp.asarray((m @ m.T + 8 * np.eye(8)).astype(np.float32))
    b = jnp.asarray(rng.normal(size=8).astype(np.float32))
    res = lbfgs(_quadratic(A, b), jnp.zeros(8), CFG)
    w_star = np.linalg.solve(np.asarray(A), np.asarray(b))
    np.testing.assert_allclose(res.w, w_star, rtol=1e-3, atol=1e-4)
    assert bool(res.converged)


def test_tron_quadratic_exact():
    rng = np.random.default_rng(1)
    m = rng.normal(size=(8, 8))
    A = jnp.asarray((m @ m.T + 8 * np.eye(8)).astype(np.float32))
    b = jnp.asarray(rng.normal(size=8).astype(np.float32))
    res = tron(_quadratic(A, b), jnp.zeros(8), CFG, hvp=lambda w, v: A @ v)
    w_star = np.linalg.solve(np.asarray(A), np.asarray(b))
    np.testing.assert_allclose(res.w, w_star, rtol=1e-3, atol=1e-4)


def _logistic_problem(seed=0, n=200, d=10, l2=1.0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, d)).astype(np.float32)
    w_true = rng.normal(size=d).astype(np.float32)
    p = 1.0 / (1.0 + np.exp(-(x @ w_true)))
    y = (rng.random(n) < p).astype(np.float32)
    batch = dense_batch(x, y)
    obj = GlmObjective.create("logistic", RegularizationContext("l2", l2))
    return obj, batch, x, y


def _scipy_reference(obj, batch, d):
    def f(w):
        return float(obj.value(jnp.asarray(w, jnp.float32), batch))

    def g(w):
        return np.asarray(
            obj.grad(jnp.asarray(w, jnp.float32), batch), dtype=np.float64
        )

    out = scipy.optimize.minimize(f, np.zeros(d), jac=g, method="L-BFGS-B",
                                  options={"maxiter": 500, "ftol": 1e-12})
    return out


@pytest.mark.parametrize("opt_name", ["lbfgs", "tron"])
def test_logistic_matches_scipy(opt_name):
    obj, batch, _, _ = _logistic_problem()
    fun = jax.jit(lambda w: obj.value_and_grad(w, batch))
    if opt_name == "lbfgs":
        res = lbfgs(fun, jnp.zeros(10), CFG)
    else:
        res = tron(fun, jnp.zeros(10), CFG,
                   hvp=lambda w, v: obj.hessian_vector(w, v, batch))
    ref = _scipy_reference(obj, batch, 10)
    assert float(res.value) <= ref.fun * (1 + 1e-5) + 1e-5
    np.testing.assert_allclose(res.w, ref.x, rtol=2e-2, atol=2e-3)


def test_poisson_tron_converges():
    rng = np.random.default_rng(3)
    n, d = 300, 8
    x = (rng.normal(size=(n, d)) * 0.3).astype(np.float32)
    w_true = (rng.normal(size=d) * 0.5).astype(np.float32)
    lam = np.exp(x @ w_true)
    y = rng.poisson(lam).astype(np.float32)
    batch = dense_batch(x, y)
    obj = GlmObjective.create("poisson", RegularizationContext("l2", 0.5))
    fun = jax.jit(lambda w: obj.value_and_grad(w, batch))
    res = tron(fun, jnp.zeros(d), CFG,
               hvp=lambda w, v: obj.hessian_vector(w, v, batch))
    assert float(res.grad_norm) < 1e-3 * max(1.0, float(res.value))
    # Recovered weights correlate with the truth.
    corr = np.corrcoef(np.asarray(res.w), w_true)[0, 1]
    assert corr > 0.9


def test_owlqn_lasso_sparsity_and_value():
    # Lasso linear regression: compare objective value against scipy on the
    # smooth-reformulated problem (split w = p - n, p,n >= 0).
    rng = np.random.default_rng(4)
    n, d = 120, 15
    x = rng.normal(size=(n, d)).astype(np.float32)
    w_true = np.zeros(d, np.float32)
    w_true[:3] = [2.0, -3.0, 1.5]
    y = (x @ w_true + 0.01 * rng.normal(size=n)).astype(np.float32)
    batch = dense_batch(x, y)
    l1 = 25.0
    obj = GlmObjective.create("squared")
    fun = jax.jit(lambda w: obj.value_and_grad(w, batch))
    res = owlqn(fun, jnp.zeros(d), CFG, l1_weight=l1)

    # scipy reference via positive/negative split (bounded L-BFGS-B).
    def f_split(z):
        w = z[:d] - z[d:]
        wj = jnp.asarray(w, jnp.float32)
        return float(obj.value(wj, batch)) + l1 * float(np.sum(z))

    def g_split(z):
        w = jnp.asarray(z[:d] - z[d:], jnp.float32)
        g = np.asarray(obj.grad(w, batch), np.float64)
        return np.concatenate([g + l1, -g + l1])

    ref = scipy.optimize.minimize(
        f_split, np.zeros(2 * d), jac=g_split, method="L-BFGS-B",
        bounds=[(0, None)] * (2 * d), options={"maxiter": 1000, "ftol": 1e-14},
    )
    assert float(res.value) <= ref.fun * (1 + 1e-4) + 1e-4
    # True zeros should be recovered as exact zeros (orthant projection).
    w = np.asarray(res.w)
    assert np.sum(np.abs(w[3:]) == 0.0) >= d - 3 - 2


def test_owlqn_elastic_net_linear():
    # Elastic net = L2 in objective + L1 in OWL-QN (bench config 2 shape).
    rng = np.random.default_rng(5)
    n, d = 100, 10
    x = rng.normal(size=(n, d)).astype(np.float32)
    w_true = np.zeros(d, np.float32)
    w_true[:2] = [1.0, -2.0]
    y = (x @ w_true).astype(np.float32)
    batch = dense_batch(x, y)
    reg = RegularizationContext("elastic_net", 10.0, alpha=0.5)
    obj = GlmObjective.create("squared", reg)
    fun = jax.jit(lambda w: obj.value_and_grad(w, batch))
    res = owlqn(fun, jnp.zeros(d), CFG, l1_weight=reg.l1_weight)
    assert bool(res.converged)
    w = np.asarray(res.w)
    assert abs(w[0]) > 0.5 and w[1] < -1.0
    assert np.all(np.abs(w[2:]) < 0.05)


def test_owlqn_zero_l1_matches_lbfgs():
    obj, batch, _, _ = _logistic_problem(6)
    fun = jax.jit(lambda w: obj.value_and_grad(w, batch))
    r1 = lbfgs(fun, jnp.zeros(10), CFG)
    r2 = owlqn(fun, jnp.zeros(10), CFG, l1_weight=0.0)
    np.testing.assert_allclose(r1.value, r2.value, rtol=1e-5)


def test_states_tracker():
    obj, batch, _, _ = _logistic_problem(7)
    fun = jax.jit(lambda w: obj.value_and_grad(w, batch))
    res = lbfgs(fun, jnp.zeros(10), OptimizerConfig(max_iterations=50))
    tracker = OptimizationStatesTracker(res)
    assert tracker.iterations >= 1
    assert len(tracker.values) == tracker.iterations + 1
    # Monotone decrease for a convex problem with Armijo line search.
    assert np.all(np.diff(tracker.values) <= 1e-6)
    assert tracker.convergence_reason in (
        "FUNCTION_VALUES_TOLERANCE", "GRADIENT_TOLERANCE", "MAX_ITERATIONS",
        "OBJECTIVE_NOT_IMPROVING",
    )


def test_vmapped_lbfgs_matches_sequential():
    # The property GAME's random effects depend on: vmapping the optimizer
    # over a batch of problems gives the same result as solving sequentially.
    rng = np.random.default_rng(8)
    B, n, d = 5, 40, 6
    xs = rng.normal(size=(B, n, d)).astype(np.float32)
    ys = (rng.random((B, n)) < 0.5).astype(np.float32)
    obj = GlmObjective.create("logistic", RegularizationContext("l2", 1.0))

    def solve(x, y):
        batch = dense_batch(x, y)
        return lbfgs(lambda w: obj.value_and_grad(w, batch), jnp.zeros(d),
                     OptimizerConfig(max_iterations=100)).w

    seq = np.stack([np.asarray(solve(xs[i], ys[i])) for i in range(B)])

    def solve_traced(x, y):
        from photon_tpu.data.batch import DenseBatch
        batch = DenseBatch(
            x=x, label=y, offset=jnp.zeros(n), weight=jnp.ones(n)
        )
        return lbfgs(lambda w: obj.value_and_grad(w, batch), jnp.zeros(d),
                     OptimizerConfig(max_iterations=100)).w

    batched = jax.jit(jax.vmap(solve_traced))(jnp.asarray(xs), jnp.asarray(ys))
    np.testing.assert_allclose(batched, seq, rtol=1e-3, atol=5e-4)


@pytest.mark.parametrize("opt_name", ["tron"])
def test_vmapped_tron_matches_sequential(opt_name):
    rng = np.random.default_rng(9)
    B, n, d = 4, 30, 5
    xs = rng.normal(size=(B, n, d)).astype(np.float32)
    ys = (rng.random((B, n)) < 0.5).astype(np.float32)
    obj = GlmObjective.create("logistic", RegularizationContext("l2", 0.5))

    def solve(x, y):
        from photon_tpu.data.batch import DenseBatch
        batch = DenseBatch(x=x, label=y, offset=jnp.zeros(n), weight=jnp.ones(n))
        return tron(
            lambda w: obj.value_and_grad(w, batch), jnp.zeros(d),
            OptimizerConfig(max_iterations=50),
            hvp=lambda w, v: obj.hessian_vector(w, v, batch),
        ).w

    seq = np.stack([np.asarray(jax.jit(solve)(jnp.asarray(xs[i]), jnp.asarray(ys[i])))
                    for i in range(B)])
    batched = jax.jit(jax.vmap(solve))(jnp.asarray(xs), jnp.asarray(ys))
    np.testing.assert_allclose(batched, seq, rtol=1e-3, atol=5e-4)
