"""On-device validation pipeline (ISSUE 3): incremental validation scoring
(`ValidationEngine` + `DeviceScoringCache`), device metric parity with the
host evaluators, the one-host-sync-per-iteration telemetry contract, and the
device warm-start alignment/restriction paths.
"""

from __future__ import annotations

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from photon_tpu.core.objective import RegularizationContext  # noqa: E402
from photon_tpu.core.optimizers import OptimizerConfig  # noqa: E402
from photon_tpu.core.problem import ProblemConfig  # noqa: E402
from photon_tpu.data.synthetic import make_game_dataset  # noqa: E402
from photon_tpu.evaluation import metrics as M  # noqa: E402
from photon_tpu.evaluation.evaluators import (  # noqa: E402
    MultiEvaluator,
    get_evaluator,
)
from photon_tpu.game.coordinate import (  # noqa: E402
    FixedEffectCoordinateConfig,
    RandomEffectCoordinateConfig,
    build_coordinate,
)
from photon_tpu.game.data import split_game_dataset  # noqa: E402
from photon_tpu.game.estimator import (  # noqa: E402
    GameEstimator,
    GameOptimizationConfiguration,
)
from photon_tpu.game.model import DeviceScoringCache  # noqa: E402
from photon_tpu.game.residuals import ValidationEngine  # noqa: E402
from photon_tpu.telemetry import TelemetrySession  # noqa: E402


def _problem(lam: float, max_iters: int) -> ProblemConfig:
    return ProblemConfig(
        regularization=RegularizationContext("l2", lam),
        optimizer_config=OptimizerConfig(max_iterations=max_iters),
    )


def _config(iters: int = 2) -> GameOptimizationConfiguration:
    return GameOptimizationConfiguration(
        coordinates={
            "fixed": FixedEffectCoordinateConfig("global", _problem(0.01, 40)),
            "re0": RandomEffectCoordinateConfig("re0", "re0", _problem(1.0, 20)),
            "re1": RandomEffectCoordinateConfig("re1", "re1", _problem(1.0, 20)),
        },
        descent_iterations=iters,
    )


def _evaluators() -> MultiEvaluator:
    return MultiEvaluator([
        get_evaluator("auc"),
        get_evaluator("logistic_loss"),
        get_evaluator("sharded_auc:re0"),
        get_evaluator("sharded_precision@3:re0"),
    ])


# ---------------------------------------------------------------------------
# Engine-level incremental re-scoring
# ---------------------------------------------------------------------------


def test_validation_engine_incremental_rescore_matches_full():
    """After updating ONLY one coordinate's row, the composite must equal a
    fresh engine's composite over the same final rows — incremental
    re-scoring may never drift from full re-scoring."""
    n, names = 129, ["a", "b", "c"]
    rng = np.random.default_rng(0)
    base = rng.standard_normal(n).astype(np.float32)
    rows = {m: rng.standard_normal(n).astype(np.float32) for m in names}

    engine = ValidationEngine(base, names=names)
    for m in names:
        engine.update(m, jnp.asarray(rows[m]))
    before = np.asarray(engine.composite()).copy()
    np.testing.assert_allclose(
        before, base + sum(rows.values()), rtol=0, atol=1e-5
    )

    rows["b"] = rng.standard_normal(n).astype(np.float32)
    engine.update("b", jnp.asarray(rows["b"]))  # only 'b' re-scored

    fresh = ValidationEngine(base, names=names)
    for m in names:
        fresh.update(m, jnp.asarray(rows[m]))
    np.testing.assert_array_equal(
        np.asarray(engine.composite()), np.asarray(fresh.composite())
    )


# ---------------------------------------------------------------------------
# Device-vs-host metric parity on identical scores
# ---------------------------------------------------------------------------


def test_device_metrics_match_host_within_1e6():
    """Every evaluator must agree between its host path (numpy ids) and its
    device path (entity codes + jitted kernels) to 1e-6 on the SAME scores
    — ties, weight-0 rows, and single-class entities included."""
    rng = np.random.default_rng(1)
    n, n_entities = 1500, 40
    # Two-decimal scores force real tie groups through the AUC kernel.
    scores = np.round(rng.standard_normal(n), 2).astype(np.float32)
    labels = (rng.random(n) < 0.35).astype(np.float32)
    weights = np.where(
        rng.random(n) < 0.1, 0.0, rng.uniform(0.5, 2.0, n)
    ).astype(np.float32)
    ids = rng.integers(0, n_entities, n)
    # Entity 7: single-class (sharded AUC must skip it on both paths).
    labels[ids == 7] = 1.0
    uniq, codes = np.unique(ids, return_inverse=True)

    for ev in _evaluators().evaluators:
        host_ids = ids if ev.entity_column is not None else None
        host = ev.evaluate(scores, labels, weights, host_ids)
        dev_ids = (
            (jnp.asarray(codes.astype(np.int32)), len(uniq) + 1)
            if ev.entity_column is not None else None
        )
        dev = ev.evaluate(
            jnp.asarray(scores), jnp.asarray(labels), jnp.asarray(weights),
            dev_ids,
        )
        assert abs(host - dev) < 1e-6, (ev.name, host, dev)


def test_sharded_metric_device_nan_when_no_valid_group():
    out = M.sharded_metric_device(
        "auc",
        jnp.asarray(np.zeros(8, np.float32)),
        jnp.asarray(np.ones(8, np.float32)),  # single class everywhere
        jnp.asarray(np.zeros(8, np.int32)),
        2,
    )
    assert np.isnan(float(out))


# ---------------------------------------------------------------------------
# End-to-end: device validation pipeline on a real fit
# ---------------------------------------------------------------------------


def _fit(validation_mode: str, iters: int = 2, telemetry=None,
         initial_model=None, locked=()):
    data, _ = make_game_dataset(30, 10, 6, 4, seed=11, n_random_coords=2)
    train, val = split_game_dataset(data, 0.25)
    estimator = GameEstimator(
        "logistic_regression", train, val, evaluators=_evaluators(),
        residual_mode="device", validation_mode=validation_mode,
        telemetry=telemetry,
    )
    result = estimator.fit(
        [_config(iters)], initial_model=initial_model,
        locked_coordinates=locked,
    )[0]
    return result, val


def test_game_fit_device_validation_matches_host():
    host, _ = _fit("host")
    device, _ = _fit("device")
    assert host.metrics and device.metrics
    for name, ref in host.metrics.items():
        # Composite scores differ at f32-rounding level between the host
        # float64 accumulate and the compensated device table; the metric
        # gap that rounding can produce is bounded well below 1e-5.
        assert abs(device.metrics[name] - ref) < 1e-5, (
            name, device.metrics[name], ref
        )


def test_device_validation_one_host_sync_per_iteration():
    """The acceptance bar: with device validation, the ONLY d2h traffic on
    the validation path is the per-metric scalars — 4 bytes x metrics x
    iterations — and the h2d upload is one-time (does not scale with
    iterations)."""
    n_metrics = len(_evaluators().evaluators)

    sessions = {}
    for iters in (1, 3):
        session = TelemetrySession(f"val-sync-{iters}")
        _fit("device", iters=iters, telemetry=session)
        sessions[iters] = session
        d2h = session.counter(
            "descent.host_transfer_bytes", direction="d2h", path="validation"
        ).value
        assert d2h == 4 * n_metrics * iters, (iters, d2h)

    # One-time upload: tripling the iterations must not grow h2d traffic.
    h2d = {
        iters: s.counter(
            "descent.host_transfer_bytes", direction="h2d", path="validation"
        ).value
        for iters, s in sessions.items()
    }
    assert h2d[1] > 0
    assert h2d[3] == h2d[1], h2d

    # Residency gauges exported.
    assert sessions[3].gauge("validation.device_bytes").value > 0
    assert sessions[3].gauge("validation.scoring_cache_bytes").value > 0


def test_validation_score_reuse_counts_locked_rows():
    """A locked coordinate is never re-scored: its validation rows are
    reused every iteration, and the counter proves it."""
    warm, _ = _fit("device", iters=1)
    session = TelemetrySession("val-reuse")
    _, val = _fit(
        "device", iters=2, telemetry=session,
        initial_model=warm.model, locked=["re1"],
    )
    reuse = session.counter("validation.score_reuse").value
    assert reuse == 2 * val.num_examples, (reuse, val.num_examples)


def test_host_validation_mode_never_builds_device_cache():
    data, _ = make_game_dataset(20, 6, 6, 4, seed=5, n_random_coords=1)
    train, val = split_game_dataset(data, 0.25)
    estimator = GameEstimator(
        "logistic_regression", train, val,
        residual_mode="host", validation_mode="auto",
    )
    estimator.fit([GameOptimizationConfiguration(
        coordinates={
            "fixed": FixedEffectCoordinateConfig("global", _problem(0.01, 10)),
            "re0": RandomEffectCoordinateConfig("re0", "re0", _problem(1.0, 8)),
        },
        descent_iterations=1,
    )])
    assert estimator._validation_cache is None


# ---------------------------------------------------------------------------
# DeviceScoringCache
# ---------------------------------------------------------------------------


def test_scoring_cache_scores_match_host_model_scores():
    data, _ = make_game_dataset(25, 8, 6, 4, seed=3, n_random_coords=2)
    train, val = split_game_dataset(data, 0.3)
    result, _ = _fit("host", iters=1)
    # Build the cache over THIS val split and compare per-coordinate
    # margins against each model's host scoring path.
    cache = DeviceScoringCache(val)
    fit_model = GameEstimator(
        "logistic_regression", train, val, residual_mode="device",
    ).fit([_config(1)])[0].model
    for name, model in fit_model.coordinates.items():
        dev = np.asarray(cache.score(model))[: cache.n]
        np.testing.assert_allclose(dev, model.score(val), rtol=0, atol=1e-5)


def test_scoring_cache_entity_index_caches_same_run_keys():
    data, _ = make_game_dataset(15, 6, 6, 4, seed=9, n_random_coords=1)
    cache = DeviceScoringCache(data)
    keys = np.unique(data.id_columns["re0"])
    a = cache.entity_index("re0", keys)
    b = cache.entity_index("re0", keys)  # identity hit — same device array
    assert a is b
    # A foreign (subset) vocabulary rebuilds the index with -1 for unseen.
    foreign = keys[:-1]
    c = np.asarray(cache.entity_index("re0", foreign))[: cache.n]
    from photon_tpu.game.data import entity_index_for

    np.testing.assert_array_equal(
        c, entity_index_for(data.id_columns["re0"], foreign)
    )
    # Replacing the cached per-column index must not leak residency:
    # device_bytes tracks LIVE bytes, so alternating vocabularies holds it
    # constant after the first replacement.
    stable = cache.device_bytes
    cache.entity_index("re0", keys)
    cache.entity_index("re0", foreign)
    assert cache.device_bytes == stable


# ---------------------------------------------------------------------------
# Device warm-start alignment + projection restriction
# ---------------------------------------------------------------------------


def test_initial_table_same_keys_stays_device_and_matches_host_align():
    data, _ = make_game_dataset(20, 6, 6, 4, seed=5, n_random_coords=1)
    coord = build_coordinate(
        data,
        RandomEffectCoordinateConfig("re0", "re0", _problem(1.0, 5)),
        "logistic_regression",
    )
    model, _ = coord.train(np.zeros(data.num_examples, np.float32))
    assert model.keys is coord.dataset.keys  # the common warm-start case
    aligned = np.asarray(coord._initial_table(model))
    np.testing.assert_array_equal(aligned[:-1], np.asarray(model.table))
    assert not aligned[-1].any()

    # Foreign vocabulary (subset): the host key join must still align rows.
    import dataclasses

    foreign = dataclasses.replace(
        model, keys=model.keys[:-1], table=model.table[:-1]
    )
    aligned_f = np.asarray(coord._initial_table(foreign))
    np.testing.assert_allclose(
        aligned_f[: len(model.keys) - 1], np.asarray(model.table)[:-1]
    )
    assert not aligned_f[len(model.keys) - 1].any()  # unseen entity -> zero


def test_restrict_kernels_match_host_projection_restriction():
    from photon_tpu.game.coordinate import (
        _restrict_index_map,
        _restrict_random,
    )
    from photon_tpu.game.projection import (
        IndexMapBucketProjection,
        build_random_projection,
    )

    rng = np.random.default_rng(2)
    E, dim, p = 6, 12, 4
    table = rng.standard_normal((E, dim)).astype(np.float32)

    proj_ids = np.sort(
        rng.choice(dim, size=(E, p), replace=True), axis=1
    ).astype(np.int32)
    mask = (rng.random((E, p)) < 0.8).astype(np.float32)
    imap = IndexMapBucketProjection(proj_ids=proj_ids, mask=mask)
    np.testing.assert_allclose(
        np.asarray(_restrict_index_map(
            jnp.asarray(table), jnp.asarray(proj_ids), jnp.asarray(mask)
        )),
        imap.restrict_table(table),
        rtol=1e-6,
    )

    rproj = build_random_projection(dim, p, seed=0)
    col_norms = (rproj.matrix**2).sum(axis=0)
    inv = (1.0 / np.maximum(col_norms, 1e-12)).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(_restrict_random(
            jnp.asarray(table), jnp.asarray(rproj.matrix), jnp.asarray(inv)
        )),
        rproj.restrict_table(table),
        rtol=1e-5, atol=1e-6,
    )


def test_warm_start_projected_fit_still_converges():
    """End-to-end guard for the device restriction path: a projected
    random-effect coordinate warm-started from its own previous model must
    train without error and score close to the cold fit."""
    data, _ = make_game_dataset(20, 8, 6, 8, seed=7, n_random_coords=1)
    coord = build_coordinate(
        data,
        RandomEffectCoordinateConfig(
            "re0", "re0", _problem(1.0, 10),
            projection="random", projected_dim=4,
        ),
        "logistic_regression",
    )
    offsets = np.zeros(data.num_examples, np.float32)
    cold, _ = coord.train(offsets)
    warm, _ = coord.train(offsets, initial_model=cold)
    np.testing.assert_allclose(
        warm.score(data), cold.score(data), rtol=0, atol=5e-3
    )
