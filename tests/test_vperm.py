"""vperm engine: routed static permutations == numpy oracle.

The vperm pipeline (ops/vperm.py) is the round-4 exchange design:
chunk-fused micro-Clos pallas passes + XLA transposes + a lane-packed
middle stage.  These tests run the kernels in interpret mode on CPU
(the same kernel code lowers on TPU) over every structural case: single
chunk, padded single chunk, multi-chunk with the middle stage, padded
multi-chunk, and the argsort-based inverse.
"""

import numpy as np
import pytest

import jax

from photon_tpu.ops.vperm import (
    CS,
    VpermRoute,
    apply_vperm,
    apply_vperm_reference,
    invert_vperm,
    route_vperm,
)

INTERP = jax.default_backend() != "tpu"


def _check(n, seed):
    rng = np.random.default_rng(seed)
    perm = rng.permutation(n).astype(np.int64)
    x = rng.standard_normal(n).astype(np.float32)
    route = route_vperm(perm)
    got = np.asarray(apply_vperm(jax.numpy.asarray(x), route,
                                 interpret=INTERP))
    np.testing.assert_array_equal(got, apply_vperm_reference(x, perm))
    return route


def test_single_chunk_exact():
    route = _check(CS, seed=0)
    assert route.nc == 1


def test_single_chunk_padded():
    route = _check(CS - 12345, seed=1)
    assert route.nc == 1


def test_multi_chunk_exact():
    route = _check(2 * CS, seed=2)
    assert route.nc == 2


def test_multi_chunk_padded_to_pow2():
    # ceil(n/CS) == 3 pads to NC = 4 so the middle stage lane-packs.
    route = _check(3 * CS - 777, seed=3)
    assert route.nc == 4


def test_inverse_roundtrip():
    n = 2 * CS
    rng = np.random.default_rng(4)
    perm = rng.permutation(n).astype(np.int64)
    x = rng.standard_normal(n).astype(np.float32)
    route = route_vperm(perm)
    inv = invert_vperm(route)
    y = apply_vperm(jax.numpy.asarray(x), route, interpret=INTERP)
    back = np.asarray(apply_vperm(y, inv, interpret=INTERP))
    np.testing.assert_array_equal(back, x)
    # And the inverse alone equals the numpy inverse permutation.
    inv_perm = np.argsort(perm)
    got = np.asarray(apply_vperm(jax.numpy.asarray(x), inv,
                                 interpret=INTERP))
    np.testing.assert_array_equal(got, apply_vperm_reference(x, inv_perm))


def test_rejects_non_permutation():
    with pytest.raises(ValueError):
        route_vperm(np.array([0, 1, 1, 3], dtype=np.int64))


def test_rejects_oversize():
    from photon_tpu.ops.vperm import MAX_N

    with pytest.raises(ValueError):
        route_vperm(np.arange(MAX_N + 1, dtype=np.int64))
