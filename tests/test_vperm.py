"""vperm engine: routed static permutations == numpy oracle.

The vperm pipeline (ops/vperm.py) is the round-4 exchange design:
chunk-fused micro-Clos pallas passes + XLA transposes + a lane-packed
middle stage.  These tests run the kernels in interpret mode on CPU
(the same kernel code lowers on TPU) over every structural case: single
chunk, padded single chunk, multi-chunk with the middle stage, padded
multi-chunk, and the argsort-based inverse.
"""

import numpy as np
import pytest

import jax

from photon_tpu.ops.vperm import (
    CH_SMALL,
    LANES,
    VpermRoute,
    apply_vperm,
    apply_vperm_reference,
    full_bijection,
    invert_vperm,
    pick_geometry,
    route_vperm,
    route_vperm_full,
)

CS = CH_SMALL * LANES
INTERP = jax.default_backend() != "tpu"

# Tests below route permutations past the pure-Python edge-colorer's size
# cap (ops/clos.py): they need the native library, which the session-scoped
# conftest fixture builds once (and skips, with a reason, when no C++
# toolchain can build it).
needs_native_router = pytest.mark.usefixtures("native_router")


def _check(n, seed):
    rng = np.random.default_rng(seed)
    perm = rng.permutation(n).astype(np.int64)
    x = rng.standard_normal(n).astype(np.float32)
    route = route_vperm(perm)
    got = np.asarray(apply_vperm(jax.numpy.asarray(x), route,
                                 interpret=INTERP))
    np.testing.assert_array_equal(got, apply_vperm_reference(x, perm))
    return route


@needs_native_router
def test_single_chunk_exact():
    route = _check(CS, seed=0)
    assert route.nc == 1


@needs_native_router
def test_single_chunk_padded():
    route = _check(CS - 12345, seed=1)
    assert route.nc == 1


@needs_native_router
def test_multi_chunk_exact():
    route = _check(2 * CS, seed=2)
    assert route.nc == 2


@needs_native_router
def test_multi_chunk_padded_to_pow2():
    # ceil(n/CS) == 3 pads to NC = 4 so the middle stage lane-packs.
    route = _check(3 * CS - 777, seed=3)
    assert route.nc == 4


@needs_native_router
def test_inverse_roundtrip():
    n = 2 * CS
    rng = np.random.default_rng(4)
    perm = rng.permutation(n).astype(np.int64)
    x = rng.standard_normal(n).astype(np.float32)
    route = route_vperm(perm)
    inv = invert_vperm(route)
    y = apply_vperm(jax.numpy.asarray(x), route, interpret=INTERP)
    back = np.asarray(apply_vperm(y, inv, interpret=INTERP))
    np.testing.assert_array_equal(back, x)
    # And the inverse alone equals the numpy inverse permutation.
    inv_perm = np.argsort(perm)
    got = np.asarray(apply_vperm(jax.numpy.asarray(x), inv,
                                 interpret=INTERP))
    np.testing.assert_array_equal(got, apply_vperm_reference(x, inv_perm))


def test_rejects_non_permutation():
    with pytest.raises(ValueError):
        route_vperm(np.array([0, 1, 1, 3], dtype=np.int64))


def test_rejects_oversize():
    from photon_tpu.ops.vperm import MAX_N

    with pytest.raises(ValueError):
        pick_geometry(MAX_N + 1)


@needs_native_router
def test_rectangular_bijection_route():
    # n_in != n_out: a source stream routed into a longer destination
    # stream with pad destinations (dest_src < 0) carrying zeros — the
    # xchg shape (row-major entries -> padded layout slots).
    rng = np.random.default_rng(5)
    n_in, n_out = CS - 500, CS - 100
    dest_src = np.full(n_out, -1, np.int64)
    real_dests = rng.choice(n_out, size=n_in, replace=False)
    dest_src[real_dests] = rng.permutation(n_in)
    ch, nc = pick_geometry(max(n_in, n_out))
    total = nc * ch * LANES
    perm = full_bijection(dest_src, n_in, total)
    route = route_vperm_full(perm, n_in, n_out, ch)
    x = rng.standard_normal(n_in).astype(np.float32)
    got = np.asarray(apply_vperm(jax.numpy.asarray(x), route,
                                 interpret=INTERP))
    want = np.zeros(n_out, np.float32)
    want[real_dests] = x[dest_src[real_dests]]
    np.testing.assert_array_equal(got, want)
    # The inverse carries the destination stream back onto the sources.
    inv = invert_vperm(route)
    back = np.asarray(apply_vperm(jax.numpy.asarray(got), inv,
                                  interpret=INTERP))
    np.testing.assert_array_equal(back, x)


@needs_native_router
def test_cumsum_reduce_precision_under_cancellation(monkeypatch):
    """The compensated prefix sum must recover small per-feature sums
    buried under a large-magnitude running prefix — the failure mode of
    a plain f32 cumsum at production E (review finding, round 4)."""
    from photon_tpu.ops.pallas_gather import build_aligned_layout
    from photon_tpu.ops.vperm import build_xchg_sorted_route, xchg_segment_grad

    rng = np.random.default_rng(7)
    n, k, dim = 2048, 128, 1024
    ids = rng.integers(0, dim, size=(n, k)).astype(np.int32)
    # Alternating +/-1000 pairs per row cancel within each feature's
    # segment up to a tiny signal, while the running prefix sweeps
    # through magnitudes where the f32 ulp is ~0.03-16.
    base = np.tile([1000.0, -1000.0], k // 2)
    vals = (base[None, :] + rng.standard_normal((n, k)) * 1e-3).astype(
        np.float32
    )
    aux = build_xchg_sorted_route(ids, dim)
    per_row = np.ones(n, np.float32)
    got = np.asarray(xchg_segment_grad(
        jax.numpy.asarray(per_row), jax.numpy.asarray(vals),
        None, aux, dim, interpret=INTERP,
    ))
    want = np.zeros(dim, np.float64)
    np.add.at(want, ids.reshape(-1), vals.reshape(-1).astype(np.float64))
    np.testing.assert_allclose(got, want, atol=2e-2, rtol=1e-3)


@pytest.mark.parametrize("zipf", [False, True])
@needs_native_router
def test_balanced_route_multi_chunk_matches_oracle(zipf):
    """The coloring-free balanced exchange at NC > 1 (two chunk passes
    around one block transpose) must reproduce the oracle gradient."""
    from photon_tpu.ops.vperm import (
        BalancedRoute,
        XchgAux,
        build_balanced_sorted_route,
        xchg_segment_grad,
    )

    rng = np.random.default_rng(8)
    n, k, dim = 2048 * 3, 128, 4096  # e = 3*CS -> nc = 3
    if zipf:
        ranks = rng.zipf(1.2, size=(n, k)).astype(np.int64)
        ids = np.minimum(ranks - 1, dim - 1).astype(np.int32)
    else:
        ids = rng.integers(0, dim, size=(n, k)).astype(np.int32)
    vals = rng.standard_normal((n, k)).astype(np.float32)
    vals[rng.random((n, k)) < 0.1] = 0.0
    built = build_balanced_sorted_route(ids, dim)
    assert built is not None
    route, bounds = built
    assert isinstance(route, BalancedRoute) and route.nc > 1
    per_row = rng.standard_normal(n).astype(np.float32)
    got = np.asarray(xchg_segment_grad(
        jax.numpy.asarray(per_row), jax.numpy.asarray(vals),
        None, XchgAux(route=route, bounds=bounds), dim, interpret=INTERP,
    ))
    want = np.zeros(dim, np.float64)
    np.add.at(want, ids.reshape(-1),
              (per_row[:, None] * vals).reshape(-1).astype(np.float64))
    np.testing.assert_allclose(got, want.astype(np.float32), rtol=2e-4,
                               atol=5e-3)


@needs_native_router
def test_xchg_bf16_payload_close_to_f32(monkeypatch):
    """PHOTON_XCHG_DTYPE=bfloat16 rides the exchange at half width; the
    reduce stays f32, so gradients track the f32 path to bf16 product
    precision."""
    from photon_tpu.ops.vperm import build_xchg_sorted_route, xchg_segment_grad

    rng = np.random.default_rng(10)
    n, k, dim = 2048, 16, 512
    ids = rng.integers(0, dim, size=(n, k)).astype(np.int32)
    vals = rng.standard_normal((n, k)).astype(np.float32)
    aux = build_xchg_sorted_route(ids, dim)
    per_row = rng.standard_normal(n).astype(np.float32)
    args = (jax.numpy.asarray(per_row), jax.numpy.asarray(vals), None,
            aux, dim)
    g32 = np.asarray(xchg_segment_grad(*args, interpret=INTERP))
    monkeypatch.setenv("PHOTON_XCHG_DTYPE", "bfloat16")
    g16 = np.asarray(xchg_segment_grad(*args, interpret=INTERP))
    scale = np.abs(g32).max()
    np.testing.assert_allclose(g16, g32, atol=2e-2 * scale)
    assert not np.array_equal(g16, g32)  # the knob actually engaged


@pytest.mark.parametrize("k,n_off", [(32, 0), (32, -1), (6, 0)])
@needs_native_router
def test_fused_dz_expansion_matches_oracle(monkeypatch, k, n_off):
    """The stage-A fused dz expansion (k | 128) must reproduce the
    oracle; (32, -1) makes cs_real indivisible by k so the window
    row-rounding branch engages; k=6 pins the fallback (k_expand == 0
    -> legacy stream)."""
    from photon_tpu.ops.vperm import build_xchg_aux, xchg_segment_grad

    monkeypatch.setenv("PHOTON_XCHG_REDUCE", "cumsum")
    monkeypatch.setenv("PHOTON_ROUTE_CACHE", "0")
    rng = np.random.default_rng(11)
    n = (3 * CS) // k + n_off  # e spans 3 chunks -> nc > 1
    dim = 4096
    ids = rng.integers(0, dim, size=(n, k)).astype(np.int32)
    vals = rng.standard_normal((n, k)).astype(np.float32)
    vals[rng.random((n, k)) < 0.1] = 0.0
    aux = build_xchg_aux(None, ids, dim, vals=vals)
    assert aux.vals_dest is not None
    from photon_tpu.ops.vperm import BalancedRoute

    assert isinstance(aux.route, BalancedRoute)
    assert aux.route.k_expand == (k if 128 % k == 0 else 0)
    per_row = rng.standard_normal(n).astype(np.float32)
    got = np.asarray(xchg_segment_grad(
        jax.numpy.asarray(per_row), jax.numpy.asarray(vals),
        None, aux, dim, interpret=INTERP,
    ))
    want = np.zeros(dim, np.float64)
    np.add.at(want, ids.reshape(-1),
              (per_row[:, None] * vals).reshape(-1).astype(np.float64))
    np.testing.assert_allclose(got, want.astype(np.float32), rtol=2e-4,
                               atol=5e-3)


@needs_native_router
def test_balanced_aligned_route_multi_chunk(monkeypatch):
    """The balanced exchange into the ALIGNED slot stream (repack +
    position-reduce) must reproduce the oracle at NC > 1."""
    from photon_tpu.ops.pallas_gather import (
        build_aligned_layout,
        device_layout,
    )
    from photon_tpu.ops.vperm import (
        BalancedRoute,
        build_xchg_aux,
        xchg_segment_grad,
    )

    monkeypatch.setenv("PHOTON_XCHG_REDUCE", "aligned")
    monkeypatch.setenv("PHOTON_ROUTE_CACHE", "0")
    rng = np.random.default_rng(12)
    n, k, dim = (3 * CS) // 32, 32, 4096
    ids = rng.integers(0, dim, size=(n, k)).astype(np.int32)
    vals = rng.standard_normal((n, k)).astype(np.float32)
    vals[rng.random((n, k)) < 0.1] = 0.0
    layout = build_aligned_layout(ids, vals, dim)
    aux = build_xchg_aux(layout, ids, dim, vals=vals)
    assert isinstance(aux.route, BalancedRoute) and aux.route.nc > 1
    assert aux.bounds is None and aux.vals_dest is not None
    per_row = rng.standard_normal(n).astype(np.float32)
    got = np.asarray(xchg_segment_grad(
        jax.numpy.asarray(per_row), jax.numpy.asarray(vals),
        device_layout(layout), aux, dim, interpret=INTERP,
    ))
    want = np.zeros(dim, np.float64)
    np.add.at(want, ids.reshape(-1),
              (per_row[:, None] * vals).reshape(-1).astype(np.float64))
    np.testing.assert_allclose(got, want.astype(np.float32), rtol=2e-4,
                               atol=5e-3)


def test_route_cache_round_trip(monkeypatch, tmp_path):
    """Cached routes must deserialize to the same gradient as freshly
    built ones, and a vals-zero-pattern change must MISS in aligned
    mode (the layout drops val==0 entries, so the route differs)."""
    from photon_tpu.ops.pallas_gather import (
        build_aligned_layout,
        device_layout,
    )
    from photon_tpu.ops.vperm import build_xchg_aux, xchg_segment_grad

    rng = np.random.default_rng(9)
    n, k, dim = 1024, 8, 256
    ids = rng.integers(0, dim, size=(n, k)).astype(np.int32)
    vals = rng.standard_normal((n, k)).astype(np.float32)
    per_row = rng.standard_normal(n).astype(np.float32)
    monkeypatch.setenv("PHOTON_ROUTE_CACHE", str(tmp_path))

    for mode in ("aligned", "cumsum"):
        monkeypatch.setenv("PHOTON_XCHG_REDUCE", mode)
        layout = build_aligned_layout(ids, vals, dim)
        al = device_layout(layout)
        fresh = build_xchg_aux(layout, ids, dim)
        cached = build_xchg_aux(layout, ids, dim)
        g1 = np.asarray(xchg_segment_grad(
            jax.numpy.asarray(per_row), jax.numpy.asarray(vals),
            al, fresh, dim, interpret=INTERP,
        ))
        g2 = np.asarray(xchg_segment_grad(
            jax.numpy.asarray(per_row), jax.numpy.asarray(vals),
            al, cached, dim, interpret=INTERP,
        ))
        np.testing.assert_array_equal(g1, g2)

    # Aligned-mode key must include the layout: zeroing some vals drops
    # entries and must rebuild, not hit the stale route.
    monkeypatch.setenv("PHOTON_XCHG_REDUCE", "aligned")
    vals2 = vals.copy()
    vals2[rng.random((n, k)) < 0.3] = 0.0
    layout2 = build_aligned_layout(ids, vals2, dim)
    al2 = device_layout(layout2)
    aux2 = build_xchg_aux(layout2, ids, dim)
    g = np.asarray(xchg_segment_grad(
        jax.numpy.asarray(per_row), jax.numpy.asarray(vals2),
        al2, aux2, dim, interpret=INTERP,
    ))
    want = np.zeros(dim, np.float64)
    np.add.at(want, ids.reshape(-1),
              (per_row[:, None] * vals2).reshape(-1).astype(np.float64))
    np.testing.assert_allclose(g, want.astype(np.float32), rtol=2e-4,
                               atol=2e-4)


@needs_native_router
def test_xchg_segment_grad_matches_oracle():
    from photon_tpu.ops.pallas_gather import (
        build_aligned_layout,
        device_layout,
    )
    from photon_tpu.ops.vperm import build_xchg_route, xchg_segment_grad

    rng = np.random.default_rng(6)
    n, k, dim = 4096, 8, 512
    ids = rng.integers(0, dim, size=(n, k)).astype(np.int32)
    vals = rng.standard_normal((n, k)).astype(np.float32)
    vals[rng.random((n, k)) < 0.1] = 0.0  # row-major pads
    layout = build_aligned_layout(ids, vals, dim)
    route = build_xchg_route(layout, n, k)
    per_row = rng.standard_normal(n).astype(np.float32)

    got = np.asarray(xchg_segment_grad(
        jax.numpy.asarray(per_row), jax.numpy.asarray(vals),
        device_layout(layout), route, dim, interpret=INTERP,
    ))
    want = np.zeros(dim, np.float64)
    np.add.at(want, ids.reshape(-1),
              (per_row[:, None] * vals).reshape(-1).astype(np.float64))
    np.testing.assert_allclose(got, want.astype(np.float32), rtol=2e-5,
                               atol=2e-4)


@needs_native_router
def test_balanced_nc3_chunk_height_sublane_aligned(monkeypatch):
    """Non-power-of-two NC (e.g. 3) must still yield a chunk height that
    is a multiple of 8*nc: Mosaic's f32 sublane tile is 8, and a block
    height indivisible by it can be rejected at compile on TPU even
    though interpret mode accepts it (ADVICE r4)."""
    from photon_tpu.ops.vperm import BalancedRoute, build_xchg_aux

    monkeypatch.setenv("PHOTON_XCHG_REDUCE", "cumsum")
    monkeypatch.setenv("PHOTON_ROUTE_CACHE", "0")
    rng = np.random.default_rng(21)
    k, dim = 32, 4096
    n = (3 * CS) // k - 7  # needs 3 chunks -> nc == 3
    ids = rng.integers(0, dim, size=(n, k)).astype(np.int32)
    vals = rng.standard_normal((n, k)).astype(np.float32)
    aux = build_xchg_aux(None, ids, dim, vals=vals)
    assert isinstance(aux.route, BalancedRoute)
    assert aux.route.nc == 3
    assert aux.route.ch % (8 * aux.route.nc) == 0
    # The routed exchange must still reproduce the oracle at the padded
    # geometry.
    from photon_tpu.ops.vperm import xchg_segment_grad

    per_row = rng.standard_normal(n).astype(np.float32)
    got = np.asarray(xchg_segment_grad(
        jax.numpy.asarray(per_row), jax.numpy.asarray(vals),
        None, aux, dim, interpret=INTERP,
    ))
    want = np.zeros(dim, np.float64)
    np.add.at(want, ids.reshape(-1),
              (per_row[:, None] * vals).reshape(-1).astype(np.float64))
    np.testing.assert_allclose(got, want.astype(np.float32), rtol=2e-4,
                               atol=5e-3)


def test_baked_vals_guard_rejects_stale_stream(monkeypatch):
    """When the attach baked vals_dest, an eager call passing DIFFERENT
    values must raise instead of silently using the stale baked stream
    (ADVICE r4)."""
    from photon_tpu.ops.vperm import build_xchg_aux, xchg_segment_grad

    monkeypatch.setenv("PHOTON_XCHG_REDUCE", "cumsum")
    monkeypatch.setenv("PHOTON_ROUTE_CACHE", "0")
    rng = np.random.default_rng(22)
    n, k, dim = 2048, 8, 512
    ids = rng.integers(0, dim, size=(n, k)).astype(np.int32)
    vals = rng.standard_normal((n, k)).astype(np.float32)
    aux = build_xchg_aux(None, ids, dim, vals=vals)
    assert aux.vals_dest is not None and aux.vals_fp is not None
    per_row = rng.standard_normal(n).astype(np.float32)
    # Same values: fine.
    xchg_segment_grad(
        jax.numpy.asarray(per_row), jax.numpy.asarray(vals),
        None, aux, dim, interpret=INTERP,
    )
    # Re-weighted values: rejected.
    with pytest.raises(ValueError, match="BAKED"):
        xchg_segment_grad(
            jax.numpy.asarray(per_row), jax.numpy.asarray(3.0 * vals),
            None, aux, dim, interpret=INTERP,
        )


@needs_native_router
def test_threaded_chunk_colorings_match_serial(monkeypatch):
    """PHOTON_ROUTE_THREADS > 1 must produce a route with identical
    applied results to the serial build (the colorings are independent;
    this pins the thread-pool refactor)."""
    from photon_tpu.ops.vperm import apply_balanced, build_xchg_aux

    monkeypatch.setenv("PHOTON_XCHG_REDUCE", "cumsum")
    monkeypatch.setenv("PHOTON_ROUTE_CACHE", "0")
    rng = np.random.default_rng(31)
    n, k, dim = (2 * CS) // 32, 32, 2048
    ids = rng.integers(0, dim, size=(n, k)).astype(np.int32)
    vals = rng.standard_normal((n, k)).astype(np.float32)
    x = jax.numpy.asarray(
        rng.standard_normal(n * k).astype(np.float32)
    )
    monkeypatch.setenv("PHOTON_ROUTE_THREADS", "1")
    aux_s = build_xchg_aux(None, ids, dim, vals=vals)
    monkeypatch.setenv("PHOTON_ROUTE_THREADS", "4")
    aux_t = build_xchg_aux(None, ids, dim, vals=vals)
    got_s = np.asarray(apply_balanced(x, aux_s.route, interpret=INTERP))
    got_t = np.asarray(apply_balanced(x, aux_t.route, interpret=INTERP))
    np.testing.assert_array_equal(got_s, got_t)
