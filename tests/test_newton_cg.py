"""Matrix-free Newton-CG entity solver (ISSUE 14).

Parity strategy mirrors tests/test_batched_solve.py: at dims ≤ 64 the
Newton-CG route is pinned ≤1e-5 against the dense-Cholesky Newton route —
both polish on the f32 gradient's zero, so agreement is at the ground-truth
scale, means AND variances (the same ``_compute_variances`` formula).  At
high dim (d=256, where the dense route never ran) the pin is against an
f64 numpy Newton ground truth.  The memory claim — no ``[B, d, d]``
materialization, peak intermediate O(B·d) — is asserted structurally on
the traced program's jaxpr, platform-independent.
"""

import contextlib
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from photon_tpu.core.objective import GlmObjective, RegularizationContext
from photon_tpu.core.optimizers import (
    OptimizerConfig,
    get_optimizer,
    newton_cg,
)
from photon_tpu.core.problem import GlmOptimizationProblem, ProblemConfig
from photon_tpu.data.batch import DenseBatch, SparseBatch
from photon_tpu.game.batched_solve import (
    newton_cg_max_dim,
    solver_route,
)
from photon_tpu.game.coordinate import (
    RandomEffectCoordinate,
    RandomEffectCoordinateConfig,
)
from photon_tpu.game.data import DenseShard, GameDataset
from photon_tpu.telemetry import TelemetrySession

_ENV_KEYS = (
    "PHOTON_SOLVE_BINNING", "PHOTON_SOLVE_NEWTON", "PHOTON_SOLVE_NEWTON_CG",
    "PHOTON_NEWTON_MAX_DIM", "PHOTON_NEWTON_CG_MAX_DIM",
)


@contextlib.contextmanager
def _env(**kw):
    saved = {k: os.environ.get(k) for k in _ENV_KEYS}
    for k, v in kw.items():
        os.environ[k] = v
    try:
        yield
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


# Forces the Newton-CG route at EVERY dim (the dense-Newton window closes).
_FORCE_CG = {"PHOTON_SOLVE_NEWTON_CG": "on", "PHOTON_NEWTON_MAX_DIM": "0"}


def _dataset(n_entities=40, rows_mean=6, dim=4, seed=3):
    rng = np.random.default_rng(seed)
    counts = np.maximum(1, rng.geometric(1.0 / rows_mean, n_entities))
    n = int(counts.sum())
    ent = np.repeat(np.arange(n_entities, dtype=np.int64), counts)
    x = rng.standard_normal((n, dim)).astype(np.float32)
    x[:, -1] = 1.0
    w_true = (rng.standard_normal((n_entities, dim)) * 0.5).astype(np.float32)
    z = np.einsum("nd,nd->n", x, w_true[ent])
    y = (rng.random(n) < 1.0 / (1.0 + np.exp(-z))).astype(np.float32)
    return GameDataset.create(
        y, {"per_entity": DenseShard(x)}, id_columns={"userId": ent}
    )


def _problem(optimizer="lbfgs", variance="none", max_iterations=100):
    return ProblemConfig(
        optimizer=optimizer,
        regularization=RegularizationContext("l2", 1.0),
        optimizer_config=OptimizerConfig(
            max_iterations=max_iterations, tolerance=0.0,
            gradient_tolerance=1e-8,
        ),
        variance_computation=variance,
    )


def _config(problem=None, **kw):
    return RandomEffectCoordinateConfig(
        shard_name="per_entity", entity_column="userId",
        problem=problem or _problem(), **kw,
    )


def _train(data, config, task="logistic_regression", telemetry=None, **env):
    with _env(**env):
        coord = RandomEffectCoordinate(data, config, task)
        if telemetry is not None:
            coord.telemetry = telemetry
        routes = coord._bin_routes()
        model, stats = coord.train(np.zeros(data.num_examples, np.float32))
    return coord, model, stats, routes


# ---------------------------------------------------------------------------
# Route selection
# ---------------------------------------------------------------------------


def test_solver_route_newton_cg_selection():
    smooth = _problem()
    # The dense-Newton window is untouched; the CG window opens above it.
    assert solver_route(smooth, 64) == "newton"
    assert solver_route(smooth, 65) == "newton_cg"
    assert solver_route(smooth, 1024) == "newton_cg"
    assert solver_route(smooth, 1025) == "vmapped"
    assert newton_cg_max_dim() == 1024
    # row_split placement still wins.
    assert solver_route(smooth, 200, row_split=True) == "row_split"
    # L1 problems keep their orthant solver at every dim.
    l1 = ProblemConfig(
        optimizer="owlqn",
        regularization=RegularizationContext("l1", 0.5),
    )
    assert solver_route(l1, 200) == "vmapped"
    # The gate and the cap are env-tunable.
    with _env(PHOTON_SOLVE_NEWTON_CG="off"):
        assert solver_route(smooth, 200) == "vmapped"
    with _env(PHOTON_NEWTON_CG_MAX_DIM="128"):
        assert solver_route(smooth, 129) == "vmapped"
        assert solver_route(smooth, 128) == "newton_cg"
    # An explicitly requested newton_cg problem routes there at ANY dim.
    explicit = _problem(optimizer="newton_cg")
    assert solver_route(explicit, 8) == "newton_cg"
    assert solver_route(explicit, 5000) == "newton_cg"


def test_registry_exposes_newton_cg():
    from photon_tpu.core.optimizers.newton_cg import newton_cg as fn

    assert get_optimizer("newton_cg") is fn
    assert get_optimizer("newton-cg") is fn
    # ProblemConfig validates through the registry.
    assert _problem(optimizer="newton_cg").optimizer == "newton_cg"
    with pytest.raises(KeyError):
        get_optimizer("newton_gc")


# ---------------------------------------------------------------------------
# HVP machinery
# ---------------------------------------------------------------------------


def _fixed_batches(n=30, d=7, k=3, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, d)).astype(np.float32)
    y = (rng.random(n) < 0.5).astype(np.float32)
    offs = rng.standard_normal(n).astype(np.float32) * 0.1
    w8 = (0.5 + rng.random(n)).astype(np.float32)
    dense = DenseBatch(jnp.asarray(x), jnp.asarray(y), jnp.asarray(offs),
                       jnp.asarray(w8))
    ids = rng.integers(0, d, (n, k))
    vals = rng.standard_normal((n, k)).astype(np.float32)
    sparse = SparseBatch(jnp.asarray(ids), jnp.asarray(vals),
                         jnp.asarray(y), jnp.asarray(offs), jnp.asarray(w8))
    return dense, sparse


@pytest.mark.parametrize("task", [
    "logistic_regression", "linear_regression", "poisson_regression",
])
def test_hessian_vector_product_matches_dense_hessian(task):
    """The matrix-free ``Xᵀ(D·(X v)) + λ₂ v`` agrees with an explicit
    ``H @ v`` on dense AND sparse batches — the identity the whole CG
    route rests on."""
    rng = np.random.default_rng(1)
    obj = GlmObjective.create(task, RegularizationContext("l2", 0.7))
    for batch in _fixed_batches():
        d = 7
        w = jnp.asarray(rng.standard_normal(d).astype(np.float32) * 0.3)
        v = jnp.asarray(rng.standard_normal(d).astype(np.float32))
        hv = obj.hessian_vector_product(w, v, batch)
        want = obj.hessian_matrix(w, batch) @ v
        np.testing.assert_allclose(np.asarray(hv), np.asarray(want),
                                   atol=1e-4, rtol=1e-4)
        # The operator form reuses one precomputed D(w) across products.
        op = obj.hvp_operator(w, batch)
        np.testing.assert_allclose(np.asarray(op(v)), np.asarray(hv),
                                   atol=0, rtol=0)


def test_hvp_normalized_objective_falls_back_exactly():
    """Normalized objectives route through jvp-of-gradient (the fast
    algebra would be silently half-normalized) — still matrix-free, still
    exact vs the dense normalized Hessian."""
    from photon_tpu.core.normalization import NormalizationContext

    rng = np.random.default_rng(2)
    dense, _ = _fixed_batches()
    d = 7
    norm = NormalizationContext(
        factors=jnp.asarray(0.5 + rng.random(d).astype(np.float32)),
        shifts=jnp.asarray(rng.standard_normal(d).astype(np.float32) * 0.2),
    )
    obj = GlmObjective.create(
        "logistic_regression", RegularizationContext("l2", 0.3),
        normalization=norm,
    )
    w = jnp.asarray(rng.standard_normal(d).astype(np.float32) * 0.3)
    v = jnp.asarray(rng.standard_normal(d).astype(np.float32))
    hv = obj.hessian_vector_product(w, v, dense)
    want = obj.hessian_matrix(w, dense) @ v
    np.testing.assert_allclose(np.asarray(hv), np.asarray(want),
                               atol=1e-4, rtol=1e-4)


# ---------------------------------------------------------------------------
# Solver behavior
# ---------------------------------------------------------------------------


def test_negative_curvature_falls_back_to_steepest_descent():
    """On a concave objective every curvature probe is negative: CG must
    bail to the (preconditioned) steepest-descent direction and the Armijo
    search must still make damped, finite progress — never a NaN step."""
    def fun(w):
        v = -0.5 * jnp.dot(w, w)
        return v, -w

    w0 = jnp.asarray([1.0, -2.0, 0.5])
    cfg = OptimizerConfig(max_iterations=5, tolerance=0.0,
                          gradient_tolerance=1e-12)
    res = newton_cg(fun, w0, cfg)
    assert bool(jnp.all(jnp.isfinite(res.w)))
    assert float(res.value) < float(fun(w0)[0])  # descent happened
    assert not bool(res.converged)  # unbounded below: ran out of iters
    assert int(res.cg_iterations) >= 1


def test_newton_cg_core_matches_dense_newton_core():
    """Same fun, same config: the CG solver lands where the dense-Cholesky
    solver lands (both polish past the f32 value stall)."""
    from photon_tpu.core.optimizers import newton

    rng = np.random.default_rng(4)
    dense, _ = _fixed_batches(n=50)
    obj = GlmObjective.create(
        "logistic_regression", RegularizationContext("l2", 1.0)
    )
    fun = lambda w: obj.value_and_grad(w, dense)  # noqa: E731
    cfg = OptimizerConfig(max_iterations=100, tolerance=0.0,
                          gradient_tolerance=1e-8)
    w0 = jnp.zeros(7)
    res_cg = newton_cg(
        fun, w0, cfg,
        hvp_at=lambda w: obj.hvp_operator(w, dense),
        diag=lambda w: obj.hessian_diagonal(w, dense),
    )
    res_dn = newton(fun, w0, cfg, hess=lambda w: obj.hessian_matrix(w, dense))
    np.testing.assert_allclose(np.asarray(res_cg.w), np.asarray(res_dn.w),
                               atol=1e-5, rtol=0)
    assert bool(res_cg.converged)


# ---------------------------------------------------------------------------
# Route parity: CG vs dense Newton (dims <= 64), means AND variances
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("task", [
    "logistic_regression", "linear_regression", "poisson_regression",
])
@pytest.mark.parametrize("projection,kw", [
    ("none", {}),
    ("index_map", {}),
    ("random", {"projected_dim": 3}),
])
def test_cg_parity_vs_dense_newton(task, projection, kw):
    data = _dataset(dim=6)
    config = _config(_problem(variance="simple"), projection=projection, **kw)
    _, cg_model, _, cg_routes = _train(data, config, task, **_FORCE_CG)
    _, dn_model, _, dn_routes = _train(data, config, task)
    assert all(r == "newton_cg" for r in cg_routes), cg_routes
    assert all(r == "newton" for r in dn_routes), dn_routes
    np.testing.assert_allclose(
        np.asarray(cg_model.table), np.asarray(dn_model.table),
        atol=1e-5, rtol=0,
    )
    np.testing.assert_allclose(
        np.asarray(cg_model.variances), np.asarray(dn_model.variances),
        atol=1e-5, rtol=0,
    )


def test_cg_parity_full_variance():
    """FULL variances ride the same ``_compute_variances`` formula, so the
    CG route's diag(H⁻¹) matches the dense route's ≤1e-5 too."""
    data = _dataset()
    config = _config(_problem(variance="full"))
    _, cg_model, _, _ = _train(data, config, **_FORCE_CG)
    _, dn_model, _, _ = _train(data, config)
    np.testing.assert_allclose(
        np.asarray(cg_model.variances), np.asarray(dn_model.variances),
        atol=1e-5, rtol=0,
    )


def test_newton_cg_high_dim_matches_f64_ground_truth():
    """The lifted-ceiling accuracy claim: at d=256 — past anything the
    dense route ever solved — the CG path lands ≤1e-5 from the true
    optimum (f64 numpy Newton run to 1e-14)."""
    data = _dataset(n_entities=10, rows_mean=24, dim=256, seed=9)
    _, model, stats, routes = _train(data, _config(), **_FORCE_CG)
    assert all(r == "newton_cg" for r in routes)
    assert stats["cg_iters"] > 0
    table = np.asarray(model.table)
    raw_x = data.shards["per_entity"].x.astype(np.float64)
    ids = data.id_columns["userId"]
    for e in range(model.num_entities):
        rows = ids == model.keys[e]
        xe = raw_x[rows]
        ye = data.label[rows].astype(np.float64)
        w = np.zeros(256)
        for _ in range(200):
            p = 1.0 / (1.0 + np.exp(-(xe @ w)))
            g = xe.T @ (p - ye) + w
            h = (xe * (p * (1 - p))[:, None]).T @ xe + np.eye(256)
            step = np.linalg.solve(h, -g)
            w += step
            if np.abs(step).max() < 1e-14:
                break
        np.testing.assert_allclose(table[e], w, atol=1e-5, rtol=0)


def test_nan_quarantine_preserved_through_newton_cg_route():
    from photon_tpu.fault.injection import FaultPlan, set_plan

    data = _dataset()
    with _env(**_FORCE_CG):
        coord = RandomEffectCoordinate(
            data, _config(), "logistic_regression"
        )
        assert all(r == "newton_cg" for r in coord._bin_routes())
        coord.fault_name = "re0"
        set_plan(FaultPlan.parse("solve:nan:coord=re0"))
        try:
            model, stats = coord.train(
                np.zeros(data.num_examples, np.float32)
            )
        finally:
            set_plan(None)
    table = np.asarray(model.table)
    assert np.isfinite(table).all()
    assert stats["quarantined"] == 1
    poisoned = int(coord.device_data.device_buckets[0]["entity_index"][0])
    assert np.all(table[poisoned] == 0.0)
    assert np.abs(table).sum() > 0
    assert stats["converged"] <= stats["entities"] - 1


# ---------------------------------------------------------------------------
# The memory claim: no [B, d, d] ever materializes
# ---------------------------------------------------------------------------


def _max_intermediate_elems(jaxpr) -> int:
    """Largest array any equation of ``jaxpr`` (recursively, through
    scan/while/cond sub-jaxprs) produces, in elements."""
    def sub_jaxprs(p):
        out = []
        if hasattr(p, "jaxpr"):  # ClosedJaxpr
            out.append(p.jaxpr)
        elif hasattr(p, "eqns"):  # Jaxpr
            out.append(p)
        elif isinstance(p, (list, tuple)):
            for q in p:
                out.extend(sub_jaxprs(q))
        return out

    best = 0
    for eqn in jaxpr.eqns:
        for v in eqn.outvars:
            shape = getattr(v.aval, "shape", ())
            best = max(best, int(np.prod(shape, dtype=np.int64)))
        for p in eqn.params.values():
            for sub in sub_jaxprs(p):
                best = max(best, _max_intermediate_elems(sub))
    return best


def test_newton_cg_never_materializes_dense_hessians():
    """ISSUE 14 acceptance: the traced Newton-CG program contains NO
    ``[B, d, d]`` intermediate — its peak array is O(B·d·R) (the batch
    itself) — while the dense-Newton program provably does."""
    import functools

    from photon_tpu.game.batched_solve import (
        _run_newton_cg_fit,
        _run_newton_fit,
    )

    rng = np.random.default_rng(6)
    B, R, d = 24, 4, 96
    obj = GlmObjective.create(
        "logistic_regression", RegularizationContext("l2", 1.0)
    )
    batch = DenseBatch(
        jnp.asarray(rng.standard_normal((B, R, d)).astype(np.float32)),
        jnp.asarray((rng.random((B, R)) < 0.5).astype(np.float32)),
        jnp.zeros((B, R), jnp.float32),
        jnp.ones((B, R), jnp.float32),
    )
    w0 = jnp.zeros((B, d), jnp.float32)
    cfg = OptimizerConfig(max_iterations=50)

    def trace(run_fit):
        fn = jax.vmap(
            functools.partial(run_fit, cfg=cfg, variance="none"),
            in_axes=(None, 0, 0),
        )
        return jax.make_jaxpr(fn)(obj, batch, w0).jaxpr

    cg_peak = _max_intermediate_elems(trace(_run_newton_cg_fit))
    dense_peak = _max_intermediate_elems(trace(_run_newton_fit))
    # The dense route materializes the [B, d, d] block ...
    assert dense_peak >= B * d * d
    # ... the CG route's peak stays O(B·d): bounded by the batch features
    # plus a few coefficient-sized vectors per lane, nowhere near B·d·d.
    assert cg_peak <= max(B * R * d, 8 * B * d)
    assert cg_peak * 4 <= B * d * d


# ---------------------------------------------------------------------------
# Telemetry: cg_iters histogram + routed-entities counter
# ---------------------------------------------------------------------------


def test_cg_iters_flow_into_stats_and_histogram():
    data = _dataset(dim=6)
    session = TelemetrySession("t-cg-iters")
    _, _, stats, routes = _train(
        data, _config(), telemetry=session, **_FORCE_CG
    )
    assert all(r == "newton_cg" for r in routes)
    resolved = stats.resolve()
    assert resolved["cg_iters"] > 0
    assert resolved["entities"] == 40
    # Every entity went through a CG bin here, so the mean denominator
    # (cg_entities — CG-routed entities only, the mixed-route guard)
    # equals the coordinate's entity count.
    assert resolved["cg_entities"] == 40
    # The descent boundary drain records the per-CG-entity mean into the
    # solves.cg_iters histogram.
    from photon_tpu.game.descent import _record_coordinate_info

    _record_coordinate_info(session, "per_entity", resolved)
    snap = session.registry.snapshot()
    hists = [h for h in snap["histograms"] if h["name"] == "solves.cg_iters"]
    assert len(hists) == 1
    want_mean = resolved["cg_iters"] / resolved["cg_entities"]
    assert hists[0]["count"] == 1
    assert abs(hists[0]["mean"] - want_mean) < 1e-9
    # A mixed-route stats dict must NOT dilute the mean with non-CG
    # entities: the denominator is the CG bins' own count.
    mixed = TelemetrySession("t-cg-iters-mixed")
    _record_coordinate_info(
        mixed, "mixed",
        {"entities": 1000, "converged": 1000, "iterations_max": 5,
         "quarantined": 0, "cg_iters": 500, "cg_entities": 10},
    )
    hist = [h for h in mixed.registry.snapshot()["histograms"]
            if h["name"] == "solves.cg_iters"][0]
    assert abs(hist["mean"] - 50.0) < 1e-9
    # Non-CG routes contribute no observation.
    _, _, dn_stats, _ = _train(data, _config())
    assert dn_stats["cg_iters"] == 0 and dn_stats["cg_entities"] == 0


def test_routed_entities_counter_per_route():
    """ISSUE 14 satellite: ``solves.routed{route}`` counts the live
    entities each route received — a downgraded bin is visible, not
    inferred."""
    data = _dataset(dim=6)
    session = TelemetrySession("t-routed")
    _train(data, _config(), telemetry=session, **_FORCE_CG)

    def routed(session, route):
        return sum(
            c["value"] for c in session.registry.snapshot()["counters"]
            if c["name"] == "solves.routed"
            and c["labels"]["route"] == route
        )

    assert routed(session, "newton_cg") == 40
    assert routed(session, "vmapped") == 0
    # The downgrade case: over-cap dims fall back to vmapped, and the
    # counter says so.
    session2 = TelemetrySession("t-routed-2")
    _train(
        data, _config(), telemetry=session2,
        PHOTON_SOLVE_NEWTON="off", PHOTON_SOLVE_NEWTON_CG="off",
    )
    assert routed(session2, "vmapped") == 40
    assert routed(session2, "newton_cg") == 0


# ---------------------------------------------------------------------------
# Explicit newton_cg as a first-class optimizer (fixed effects too)
# ---------------------------------------------------------------------------


def test_explicit_newton_cg_problem_solves_fixed_effect():
    dense, _ = _fixed_batches(n=60)
    obj = GlmObjective.create(
        "logistic_regression", RegularizationContext("l2", 1.0)
    )
    cfg = _problem(optimizer="newton_cg")
    problem = GlmOptimizationProblem(obj, cfg)
    coefficients, result = problem.run(dense, dim=7)
    base = GlmOptimizationProblem(obj, _problem())
    want, _ = base.run(dense, dim=7)
    # Cross-solver agreement at the f32 floor; newton_cg itself converges.
    np.testing.assert_allclose(
        np.asarray(coefficients.means), np.asarray(want.means),
        atol=5e-3, rtol=0,
    )
    assert bool(result.converged)
    assert int(result.cg_iterations) > 0


# -- TRON through the precomputed-curvature operator (ISSUE 15 satellite) ----

def test_tron_hvp_operator_route_matches_per_call_hvp():
    """`tron(hvp_at=...)` (the hvp_operator closure — margins/D(w) once
    per outer iteration) matches the legacy per-call `hvp` route and the
    derived jvp-of-grad default ≤1e-6, directly and through the cached
    GAME solver path."""
    import jax.numpy as jnp

    from photon_tpu.core.optimizers.tron import tron
    from photon_tpu.core.problem import (
        GlmOptimizationProblem,
        hvp_at_for,
    )
    from photon_tpu.data.synthetic import make_glm_data

    batch, _ = make_glm_data(300, 10, task="logistic_regression", seed=9)
    objective = GlmObjective.create(
        "logistic_regression", RegularizationContext("l2", 0.5)
    )
    fun = lambda w: objective.value_and_grad(w, batch)  # noqa: E731
    w0 = jnp.zeros(10)
    cfg = OptimizerConfig(max_iterations=30)
    legacy = tron(
        fun, w0, cfg,
        hvp=lambda w, v: objective.hessian_vector(w, v, batch),
    )
    operator = tron(fun, w0, cfg, hvp_at=hvp_at_for(objective, batch))
    derived = tron(fun, w0, cfg)
    assert float(jnp.abs(legacy.w - operator.w).max()) <= 1e-6
    assert float(jnp.abs(legacy.w - derived.w).max()) <= 1e-6
    # The problem route (what GAME coordinates run) wires hvp_at now.
    problem = GlmOptimizationProblem(
        objective,
        ProblemConfig(optimizer="tron", optimizer_config=cfg),
    )
    coefficients, _result = problem.run(batch, None, dim=10)
    assert float(jnp.abs(coefficients.means - operator.w).max()) <= 1e-6


def test_tron_vmapped_entity_route_unchanged():
    """The vmapped per-entity TRON route (GAME random effects): the
    operator wiring (`hvp_at`, what `_run_fit` now passes) produces the
    same per-lane solutions as the legacy per-call `hvp` wiring under the
    same vmap — the rewire changes where the curvature is built, not what
    any entity converges to."""
    import jax
    import jax.numpy as jnp

    from photon_tpu.core.optimizers.tron import tron
    from photon_tpu.core.problem import cached_solver, hvp_at_for
    from photon_tpu.data.batch import DenseBatch
    from photon_tpu.data.synthetic import make_glm_data

    objective = GlmObjective.create(
        "logistic_regression", RegularizationContext("l2", 1.0)
    )
    cfg = OptimizerConfig(max_iterations=25)
    batches = []
    for seed in range(4):
        b, _ = make_glm_data(16, 6, task="logistic_regression", seed=seed)
        batches.append(b)
    stacked = DenseBatch(
        jnp.stack([b.x for b in batches]),
        jnp.stack([b.label for b in batches]),
        jnp.stack([b.offset for b in batches]),
        jnp.stack([b.weight for b in batches]),
    )
    w0 = jnp.zeros((4, 6))

    def legacy_lane(batch, w):
        fun = lambda u: objective.value_and_grad(u, batch)  # noqa: E731
        return tron(
            fun, w, cfg,
            hvp=lambda ww, v: objective.hessian_vector(ww, v, batch),
        ).w

    def operator_lane(batch, w):
        fun = lambda u: objective.value_and_grad(u, batch)  # noqa: E731
        return tron(fun, w, cfg, hvp_at=hvp_at_for(objective, batch)).w

    legacy = jax.jit(jax.vmap(legacy_lane))(stacked, w0)
    operator = jax.jit(jax.vmap(operator_lane))(stacked, w0)
    assert float(jnp.abs(legacy - operator).max()) <= 1e-6
    # And the cached GAME solver route (the production wiring) matches.
    solver = cached_solver("tron", cfg, "none", vmapped=True)
    coeff, _ = solver(objective, stacked, w0)
    assert float(jnp.abs(coeff.means - operator).max()) <= 1e-6
