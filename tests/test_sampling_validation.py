"""Down-sampler and data-validation tests (reference: photon-lib sampling/,
photon-client DataValidators — SURVEY.md §2.1, §2.3)."""

import numpy as np
import pytest

from photon_tpu.data.sampling import (
    BinaryClassificationDownSampler,
    DefaultDownSampler,
    down_sampler_for_task,
    get_down_sampler,
)
from photon_tpu.data.validation import (
    DataValidationError,
    apply_validation,
    validate_columns,
    validate_game_dataset,
)


def test_default_down_sampler_unbiased_weight_sum():
    rng = np.random.default_rng(0)
    n = 20000
    label = (rng.random(n) < 0.5).astype(np.float32)
    weight = np.ones(n, np.float32)
    rows, corrected = DefaultDownSampler(0.2).down_sample(label, weight, seed=1)
    assert 0.15 * n < len(rows) < 0.25 * n
    # Corrected weight total is an unbiased estimate of the original total.
    assert abs(corrected.sum() - n) / n < 0.05


def test_binary_down_sampler_keeps_positives():
    rng = np.random.default_rng(2)
    n = 10000
    label = (rng.random(n) < 0.05).astype(np.float32)  # 5% positives
    weight = np.full(n, 2.0, np.float32)
    rows, corrected = BinaryClassificationDownSampler(0.1).down_sample(
        label, weight, seed=3
    )
    kept_labels = label[rows]
    assert kept_labels.sum() == label.sum()  # every positive survives
    # Positive weights untouched; negative weights scaled by 1/rate.
    assert np.all(corrected[kept_labels > 0.5] == 2.0)
    assert np.all(corrected[kept_labels <= 0.5] == 20.0)
    # Weighted negative mass is approximately preserved.
    neg_mass = corrected[kept_labels <= 0.5].sum()
    assert abs(neg_mass - 2.0 * (n - label.sum())) / (2.0 * n) < 0.12


def test_sampler_registry_and_task_default():
    assert isinstance(get_down_sampler("binary", 0.5), BinaryClassificationDownSampler)
    assert isinstance(
        down_sampler_for_task("logistic_regression", 0.5),
        BinaryClassificationDownSampler,
    )
    assert isinstance(
        down_sampler_for_task("poisson_regression", 0.5), DefaultDownSampler
    )
    with pytest.raises(KeyError):
        get_down_sampler("nope", 0.5)
    with pytest.raises(ValueError):
        DefaultDownSampler(0.0)


def test_rate_one_is_identity():
    label = np.asarray([0.0, 1.0, 0.0])
    weight = np.asarray([1.0, 2.0, 3.0], np.float32)
    rows, corrected = BinaryClassificationDownSampler(1.0).down_sample(label, weight)
    np.testing.assert_array_equal(rows, [0, 1, 2])
    np.testing.assert_allclose(corrected, weight)


def test_validate_columns_catches_each_issue():
    label = np.asarray([0.0, 1.0, np.nan, 2.0])
    weight = np.asarray([1.0, 0.0, 1.0, -1.0])
    offset = np.asarray([0.0, np.inf, 0.0, 0.0])
    issues = validate_columns(label, weight, offset, "logistic_regression")
    checks = {i.check for i in issues}
    assert checks == {
        "non_finite_label", "non_binary_label", "invalid_weight",
        "non_finite_offset",
    }
    # Poisson: negative labels flagged, 2.0 fine.
    issues = validate_columns(
        np.asarray([0.0, 2.0, -1.0]), None, None, "poisson_regression"
    )
    assert [i.check for i in issues] == ["negative_label"]


def test_validate_game_dataset_and_modes():
    from photon_tpu.data.synthetic import make_game_dataset

    data, _ = make_game_dataset(10, 3, 5, 3, seed=0)
    assert validate_game_dataset(data, "logistic_regression") == []

    bad = data.shards["global"].x.copy()
    bad[0, 0] = np.nan
    data2 = type(data)(
        label=data.label, offset=data.offset, weight=data.weight,
        shards={**data.shards, "global": type(data.shards["global"])(bad)},
        id_columns=data.id_columns,
    )
    issues = validate_game_dataset(data2, "logistic_regression")
    assert issues and issues[0].check.startswith("non_finite_features")
    with pytest.raises(DataValidationError):
        apply_validation(issues, "error")
    apply_validation(issues, "warn")  # logs only
    apply_validation(issues, "off")
    with pytest.raises(ValueError):
        apply_validation(issues, "bogus")


def test_train_driver_rejects_bad_labels(tmp_path):
    from photon_tpu.drivers import train

    libsvm = tmp_path / "bad.libsvm"
    libsvm.write_text("nan 1:1.0\n0 2:2.0\n1 1:0.5\n")
    with pytest.raises(DataValidationError):
        train.run(train.build_parser().parse_args([
            "--backend", "cpu",
            "--input", str(libsvm),
            "--task", "linear_regression",
            "--max-iterations", "2",
            "--output-dir", str(tmp_path / "out"),
        ]))


def test_fixed_coordinate_binary_downsampler():
    """Fixed-effect coordinate with binary down-sampling still trains."""
    from photon_tpu.core.optimizers import OptimizerConfig
    from photon_tpu.core.problem import ProblemConfig
    from photon_tpu.data.synthetic import make_game_dataset
    from photon_tpu.game.coordinate import FixedEffectCoordinateConfig
    from photon_tpu.game.estimator import GameEstimator, GameOptimizationConfiguration

    data, _ = make_game_dataset(40, 6, 6, 3, seed=1)
    config = GameOptimizationConfiguration(
        coordinates={
            "fixed": FixedEffectCoordinateConfig(
                "global",
                ProblemConfig(optimizer_config=OptimizerConfig(max_iterations=10)),
                downsampling_rate=0.5,
                downsampler="binary",
            ),
        },
    )
    result = GameEstimator("logistic_regression", data).fit([config])[0]
    table = result.model.coordinates["fixed"].coefficients.means
    assert np.all(np.isfinite(np.asarray(table)))
