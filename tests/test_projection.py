"""Feature-projection tests (reference: photon-api data/projectors —
IndexMapProjection, RandomProjection, ProjectionMatrix; SURVEY.md §2.2)."""

import numpy as np
import pytest

from photon_tpu.game.data import DenseShard, SparseShard, build_random_effect_dataset
from photon_tpu.game.projection import (
    build_index_map_projection,
    build_random_projection,
)


def _sparse_bucket():
    """One bucket: 4 entities x 2 rows, global dim 32, few active features."""
    from photon_tpu.game.data import EntityBucket

    rng = np.random.default_rng(0)
    ids = np.zeros((4, 2, 3), np.int32)
    vals = np.zeros((4, 2, 3), np.float32)
    for e in range(4):
        active = rng.choice(np.arange(1, 32), size=4, replace=False)
        for r in range(2):
            chosen = rng.choice(active, size=3, replace=False)
            ids[e, r] = np.sort(chosen)
            vals[e, r] = rng.standard_normal(3)
    return EntityBucket(
        row_capacity=2,
        entity_index=np.arange(4, dtype=np.int32),
        row_index=np.zeros((4, 2), np.int64),
        row_weight=np.ones((4, 2), np.float32),
        label=np.zeros((4, 2), np.float32),
        features=SparseShard(ids, vals, 32),
    )


def test_index_map_projection_sparse_margins_exact():
    bucket = _sparse_bucket()
    proj = build_index_map_projection(bucket)
    assert proj is not None
    assert proj.projected_dim < 32
    local = proj.project(bucket.features)
    # Any global coefficient vector restricted per entity gives identical
    # margins on the local ids/vals.
    rng = np.random.default_rng(1)
    w = rng.standard_normal(32).astype(np.float32)
    table = np.tile(w, (4, 1))
    w_local = proj.restrict_table(table)  # [4, p]
    ids, vals = bucket.features.ids, bucket.features.vals
    for e in range(4):
        for r in range(2):
            global_margin = (w[ids[e, r]] * vals[e, r]).sum()
            local_margin = (w_local[e][local.ids[e, r]] * local.vals[e, r]).sum()
            np.testing.assert_allclose(local_margin, global_margin, rtol=1e-5)


def test_index_map_projection_dense_and_no_savings():
    # Dense [E, R, d] with few active columns.
    x = np.zeros((3, 2, 16), np.float32)
    x[0, :, 2] = 1.0
    x[1, :, [5, 7]] = 2.0
    x[2, 0, 11] = 3.0
    from photon_tpu.game.data import EntityBucket

    bucket = EntityBucket(
        row_capacity=2,
        entity_index=np.arange(3, dtype=np.int32),
        row_index=np.zeros((3, 2), np.int64),
        row_weight=np.ones((3, 2), np.float32),
        label=np.zeros((3, 2), np.float32),
        features=DenseShard(x),
    )
    proj = build_index_map_projection(bucket)
    assert proj is not None and proj.projected_dim == 2
    local = proj.project(bucket.features)
    w = np.arange(16, dtype=np.float32)
    w_local = proj.restrict_table(np.tile(w, (3, 1)))
    np.testing.assert_allclose(
        np.einsum("erd,ed->er", x, np.tile(w, (3, 1))),
        np.einsum("erp,ep->er", local.x, w_local),
        rtol=1e-5,
    )
    # Dense bucket with every column active -> no savings -> None.
    full = DenseShard(np.ones((2, 2, 4), np.float32))
    bucket_full = EntityBucket(
        row_capacity=2,
        entity_index=np.arange(2, dtype=np.int32),
        row_index=np.zeros((2, 2), np.int64),
        row_weight=np.ones((2, 2), np.float32),
        label=np.zeros((2, 2), np.float32),
        features=full,
    )
    assert build_index_map_projection(bucket_full) is None


def test_random_projection_lift_preserves_margins():
    rng = np.random.default_rng(2)
    dim, p = 64, 16
    proj = build_random_projection(dim, p, seed=3)
    assert proj.matrix.shape == (dim, p)
    x = rng.standard_normal((5, 3, dim)).astype(np.float32)
    local = proj.project(DenseShard(x))
    assert local.x.shape == (5, 3, p)
    w_local = rng.standard_normal((5, p)).astype(np.float32)
    lifted = proj.lift(w_local)  # [5, dim]
    # (R^T x)^T w_local == x^T (R w_local) exactly.
    np.testing.assert_allclose(
        np.einsum("erp,ep->er", local.x, w_local),
        np.einsum("erd,ed->er", x, lifted),
        rtol=1e-4, atol=1e-4,
    )
    with pytest.raises(ValueError):
        build_random_projection(8, 8)


def test_random_projection_restrict_inverts_lift():
    """restrict(lift(w)) ≈ w — warm starts across descent iterations must
    not be rescaled (a raw Rᵀ pullback would inflate them by ~dim/p)."""
    rng = np.random.default_rng(4)
    dim, p = 512, 32
    proj = build_random_projection(dim, p, seed=1)
    w = rng.standard_normal((6, p)).astype(np.float32)
    back = proj.restrict_table(np.asarray(proj.lift(w)))
    ratio = np.linalg.norm(back) / np.linalg.norm(w)
    assert 0.7 < ratio < 1.4
    # Norm preservation in expectation: E[||Rᵀx||²] = ||x||².
    x = rng.standard_normal((200, dim)).astype(np.float32)
    from photon_tpu.game.data import DenseShard as DS

    projected = proj.project(DS(x[:, None, :])).x[:, 0, :]
    norm_ratio = (projected**2).sum() / (x**2).sum()
    assert 0.8 < norm_ratio < 1.2


def test_random_projection_sparse_matches_dense():
    proj = build_random_projection(32, 8, seed=0)
    bucket = _sparse_bucket()
    sp = proj.project(bucket.features)
    # Densify the sparse rows and project to compare.
    ids, vals = bucket.features.ids, bucket.features.vals
    dense = np.zeros((4, 2, 32), np.float32)
    for e in range(4):
        for r in range(2):
            np.add.at(dense[e, r], ids[e, r], vals[e, r])
    np.testing.assert_allclose(
        sp.x, proj.project(DenseShard(dense)).x, rtol=1e-4, atol=1e-5
    )


def _game_sparse_data(seed=0):
    """GAME-style dataset with a SPARSE random-effect shard."""
    rng = np.random.default_rng(seed)
    n_entities, rows_mean, dim = 20, 4, 64
    counts = np.maximum(1, rng.geometric(1.0 / rows_mean, n_entities))
    n = int(counts.sum())
    entity = np.repeat(np.arange(n_entities), counts)
    k = 4
    ids = np.zeros((n, k), np.int32)
    vals = np.zeros((n, k), np.float32)
    w_true = rng.standard_normal((n_entities, dim)).astype(np.float32) * 0.5
    z = np.zeros(n, np.float32)
    for i in range(n):
        active = rng.choice(dim, size=k, replace=False)
        ids[i] = np.sort(active)
        vals[i] = rng.standard_normal(k)
        z[i] = (w_true[entity[i], ids[i]] * vals[i]).sum()
    y = (rng.random(n) < 1.0 / (1.0 + np.exp(-z))).astype(np.float32)
    from photon_tpu.game.data import GameDataset

    return GameDataset.create(
        y, {"re": SparseShard(ids, vals, dim)}, id_columns={"re": entity}
    )


def _train_re(data, **config_kw):
    from photon_tpu.core.objective import RegularizationContext
    from photon_tpu.core.optimizers import OptimizerConfig
    from photon_tpu.core.problem import ProblemConfig
    from photon_tpu.game.coordinate import (
        RandomEffectCoordinate,
        RandomEffectCoordinateConfig,
    )

    config = RandomEffectCoordinateConfig(
        shard_name="re",
        entity_column="re",
        problem=ProblemConfig(
            regularization=RegularizationContext("l2", 1.0),
            optimizer_config=OptimizerConfig(max_iterations=25),
        ),
        **config_kw,
    )
    coord = RandomEffectCoordinate(data, config, "logistic_regression")
    model, stats = coord.train(np.zeros(data.num_examples, np.float32))
    return model, stats, coord


def test_index_map_projected_solve_matches_unprojected():
    """The projection is exact: projected and unprojected coordinate solves
    must land on the same model (same objective, same optimizer)."""
    data = _game_sparse_data()
    model_plain, _, coord = _train_re(data)
    model_proj, stats, _ = _train_re(data, projection="index_map")
    assert stats["entities"] == model_proj.num_entities
    np.testing.assert_allclose(
        np.asarray(model_proj.table), np.asarray(model_plain.table),
        rtol=2e-3, atol=2e-4,
    )
    # Scores agree too.
    np.testing.assert_allclose(
        model_proj.score(data), model_plain.score(data), rtol=1e-3, atol=1e-3
    )


def test_random_projected_solve_trains_and_scores():
    """Random projection is lossy but must train finite and score sanely."""
    data = _game_sparse_data(seed=1)
    model, stats, _ = _train_re(data, projection="random", projected_dim=16)
    table = np.asarray(model.table)
    assert np.all(np.isfinite(table))
    assert stats["converged"] > 0
    # Lifted-model scores correlate with the labels' direction.
    scores = model.score(data)
    assert np.isfinite(scores).all()


def test_projection_with_active_row_cap_and_vocab():
    data = _game_sparse_data(seed=2)
    ds = build_random_effect_dataset(
        data, "re", "re", active_row_cap=4
    )
    for bucket in ds.buckets:
        proj = build_index_map_projection(bucket)
        if proj is not None:
            assert proj.proj_ids.shape[0] == bucket.num_entities
