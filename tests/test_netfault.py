"""Partition-tolerant fleet supervision (ISSUE 19): lease/generation
membership, deterministic network fault injection, zero-downtime rebuild.

The contracts pinned here:

- every fault-shim primitive (drop, duplicate, delay, reorder, throttle,
  partition-then-heal) preserves response-SET equality with a clean run —
  the seq/resend exchange loses nothing and double-serves nothing;
- a dropped control/data connection inside the lease window is a
  tolerated miss: the replica rejoins SILENTLY on reconnect (zero
  declared deaths, ``serving.replica_reconnects`` counts the rejoin);
- a partition that outlives the lease declares death with cause
  ``"lease"`` — and only then;
- a zombie replica (generation ratcheted past the parent's) is fenced:
  its answers raise :class:`ReplicaDeadError` and count
  ``serving.fenced_responses{reason=stale_gen}``, never reach a caller;
- duplicated frames are fenced by seq (``reason=stale_seq``) — exactly
  once survives;
- an injected child clock skew is measured off the ping RTT
  (``clock_offset_s``) and child span timestamps are shifted back onto
  the parent's clock before trace merge;
- capacity-exceeding growth triggers the zero-downtime background
  rebuild: replacement at doubled capacity, canary parity gate, atomic
  generation-bumped cutover; a canary failure aborts with the fleet
  untouched.
"""

from __future__ import annotations

import threading
import time
import types

import numpy as np
import pytest

from photon_tpu.data.synthetic import make_game_dataset
from photon_tpu.game.model import (
    FixedEffectModel,
    GameModel,
    RandomEffectModel,
)
from photon_tpu.models.glm import Coefficients, model_for_task
from photon_tpu.serving import (
    ReplicaDeadError,
    ServingFleet,
    SupervisorPolicy,
    build_requests,
    host_score_request,
    request_spec_for_dataset,
)
from photon_tpu.serving.fleet import ReplicaRebuildError, is_capacity_refusal
from photon_tpu.serving.netfault import (
    LinkRule,
    NetFaultPlan,
    partition,
    set_net_plan,
)
from photon_tpu.serving.supervisor import ReplicaSupervisor
from photon_tpu.telemetry import TelemetrySession
from photon_tpu.telemetry.distributed import (
    TraceContext,
    attach_trace,
    new_trace_id,
    shift_span_times,
)


@pytest.fixture(autouse=True)
def _clean_net_plan():
    set_net_plan(None)
    yield
    set_net_plan(None)


def _fixture(seed=3, n_entities=40, fixed_dim=6, random_dim=4):
    data, _ = make_game_dataset(
        n_entities, 4, fixed_dim, random_dim, seed=seed
    )
    rng = np.random.default_rng(seed)
    keys = np.unique(data.id_columns["re0"])
    model = GameModel(
        coordinates={
            "fixed": FixedEffectModel(
                model_for_task("logistic_regression", Coefficients(
                    rng.standard_normal(fixed_dim).astype(np.float32)
                )),
                "global",
            ),
            "per_entity": RandomEffectModel(
                table=rng.standard_normal(
                    (len(keys), random_dim)
                ).astype(np.float32),
                keys=keys, entity_column="re0", shard_name="re0",
                task_type="logistic_regression",
            ),
        },
        task_type="logistic_regression",
    )
    return model, data


def _counter(session, name, **labels):
    return sum(
        m["value"] for m in session.registry.snapshot()["counters"]
        if m["name"] == name
        and all(str(m["labels"].get(k)) == str(v) for k, v in labels.items())
    )


def _rewire(fleet):
    """Force every replica's next exchange through a silent reconnect —
    the redial passes ``maybe_shim``, so a just-installed (or just
    cleared) fault plan takes effect on a LIVE fleet."""
    for replica in fleet.replicas:
        for chan in ("_data", "_ctrl"):
            try:
                getattr(replica.scorer, chan).close()
            except OSError:
                pass


def _grown(model, extra=None):
    """The capacity-crossing model: the per-entity vocabulary grown past
    the factor-1 headroom (capacity = factor * (num_entities + 1))."""
    pe = model.coordinates["per_entity"]
    ks = np.asarray(pe.keys)
    n_new = extra if extra is not None else len(ks) + 4
    new = ks.max() + np.arange(1, n_new, dtype=ks.dtype)
    grown_pe = pe.with_entities(np.unique(np.concatenate([ks, new])))
    return GameModel(
        coordinates={"fixed": model.coordinates["fixed"],
                     "per_entity": grown_pe},
        task_type=model.task_type,
    )


@pytest.fixture(scope="module")
def rig():
    """One subprocess-backed replica shared across the protocol tests
    (child spawn is the expensive part; every test restores the clean
    state it found — plan cleared, generation re-synced)."""
    set_net_plan(None)
    model, data = _fixture(seed=3)
    session = TelemetrySession("netfault-rig")
    fleet = ServingFleet(
        model, replicas=1, backend="subprocess",
        request_spec=request_spec_for_dataset(model, data),
        max_batch=16, max_delay_s=0.001, telemetry=session,
    ).warmup()
    # Short per-attempt silence: black-holed frames resend quickly, so
    # the faulted cells finish in test time.
    fleet.replicas[0].scorer.exchange_timeout_s = 0.25
    requests = build_requests(data, model, [4, 9, 2, 7, 3, 5])
    clean = [
        np.asarray(fleet.score(r), np.float64)  # host-side test oracle
        for r in requests
    ]
    yield types.SimpleNamespace(
        model=model, data=data, session=session, fleet=fleet,
        requests=requests, clean=clean,
    )
    set_net_plan(None)
    fleet.close()


# -- deterministic fault injection (satellite: property-style replay) ---------

def test_every_fault_primitive_preserves_response_set(rig):
    """Seeded property test: the same traffic trace replayed through
    every FaultPlan primitive yields the exact response set of the clean
    run — zero lost futures, zero double-served rows, no corruption."""
    cells = {
        "drop": NetFaultPlan(
            [LinkRule(link="r0:data", direction="both", drop_p=0.3)], seed=5
        ),
        "duplicate": NetFaultPlan(
            [LinkRule(link="r0:data", direction="both", dup_p=1.0)], seed=6
        ),
        "delay": NetFaultPlan(
            [LinkRule(link="r0:data", direction="both", delay_s=0.02)], seed=7
        ),
        "reorder": NetFaultPlan(
            [LinkRule(link="r0:data", direction="both",
                      dup_p=0.5, reorder_p=0.7)], seed=8
        ),
        "throttle": NetFaultPlan(
            [LinkRule(link="r0:data", direction="both",
                      rate_bytes_per_s=2e6)], seed=9
        ),
        "partition_heal": NetFaultPlan(
            [partition("r0:data", 0.0, 0.6)], seed=10
        ),
    }
    expect_events = {
        "drop": "dropped", "duplicate": "duplicated",
        "reorder": "reordered", "throttle": "throttled",
        "partition_heal": "partitioned",
    }
    for name, plan in cells.items():
        set_net_plan(plan)
        _rewire(rig.fleet)
        got = [np.asarray(rig.fleet.score(r), np.float64)
               for r in rig.requests]
        for g, c in zip(got, rig.clean):
            np.testing.assert_allclose(g, c, rtol=0, atol=1e-9,
                                       err_msg=f"cell {name}")
        if name in expect_events:
            assert plan.total(expect_events[name]) > 0, (
                f"cell {name} never exercised its fault: {plan.counters}"
            )
    set_net_plan(None)
    _rewire(rig.fleet)
    # The faulted cells resent black-holed frames and fenced the stale
    # replies those resends raced — the exactly-once machinery actually
    # ran; it did not just get lucky with a quiet wire.
    assert _counter(rig.session, "serving.exchange_resends") > 0
    assert _counter(rig.session, "serving.fenced_responses",
                    reason="stale_seq") > 0
    assert _counter(rig.session, "serving.replica_deaths") == 0


def test_dropped_connection_rejoins_silently_within_lease(rig):
    """A dropped connection is NOT a death: the next exchange redials,
    the replica rejoins silently, and the only trace is the
    ``serving.replica_reconnects`` counter."""
    before = _counter(rig.session, "serving.replica_reconnects")
    _rewire(rig.fleet)
    got = np.asarray(rig.fleet.score(rig.requests[0]), np.float64)
    np.testing.assert_allclose(got, rig.clean[0], rtol=0, atol=1e-9)
    pong = rig.fleet.replicas[0].ping(10.0)
    assert pong.get("kind") == "pong"
    assert _counter(rig.session, "serving.replica_reconnects") > before
    assert _counter(rig.session, "serving.replica_deaths") == 0


def test_generation_fence_rejects_zombie_replica(rig):
    """A replica whose child has ratcheted PAST the parent's generation
    (the parent is the zombie: a newer incarnation owns the id) must not
    serve — its answers raise and are counted, never returned."""
    r0 = rig.fleet.replicas[0]
    before = _counter(rig.session, "serving.fenced_responses",
                      reason="stale_gen")
    # Ratchet the child three generations ahead (what a rebuilt/cutover
    # sibling's frames do), then score from the stale parent handle.
    r0.scorer.ping(10.0, gen=r0.generation + 3)
    with pytest.raises(ReplicaDeadError):
        r0.scorer.score_batch(rig.requests[0])
    assert _counter(rig.session, "serving.fenced_responses",
                    reason="stale_gen") > before
    # Re-sync the handle onto the current generation: service resumes.
    r0.generation += 3
    r0.scorer.generation = r0.generation
    got = np.asarray(r0.scorer.score_batch(rig.requests[0]), np.float64)
    np.testing.assert_allclose(got, rig.clean[0], rtol=0, atol=1e-9)


def test_partition_heals_within_lease_without_false_death(rig):
    """The tier-1 chaos smoke (one matrix cell): a transient partition
    shorter than the lease produces probe MISSES, never a declaration —
    and service resumes through the healed link with zero resurrections
    (there was nothing to resurrect)."""
    sup = rig.fleet.supervise(
        SupervisorPolicy(probe_interval_s=10.0, probe_deadline_s=0.3,
                         hang_timeout_s=1e9, lease_s=30.0,
                         respawn_base_s=0.0, respawn_jitter=0.0),
        start=False,
    )
    sup.check_once()  # healthy pass establishes + renews the lease
    misses0 = _counter(rig.session, "serving.lease_probe_misses")
    plan = NetFaultPlan([partition("r0:*", 0.0, 0.6)], seed=21)
    set_net_plan(plan)
    _rewire(rig.fleet)
    sup.check_once()  # ping blocks probe_deadline_s, then misses
    assert rig.fleet.replicas[0].alive, "declared dead inside the lease"
    assert _counter(rig.session, "serving.lease_probe_misses") > misses0
    assert _counter(rig.session, "serving.replica_deaths") == 0
    time.sleep(0.45)  # the partition window closes (0.3s already spent)
    sup.check_once()  # renewal through the healed link
    assert rig.fleet.replicas[0].alive
    assert _counter(rig.session, "serving.replica_deaths") == 0
    assert _counter(rig.session, "serving.replica_resurrections") == 0
    set_net_plan(None)
    _rewire(rig.fleet)
    got = np.asarray(rig.fleet.score(rig.requests[1]), np.float64)
    np.testing.assert_allclose(got, rig.clean[1], rtol=0, atol=1e-9)


def test_skewed_child_clock_measured_and_spans_deskewed(rig):
    """A child whose self-reported clock runs 30s ahead (injected via the
    fault shim's skew rewrite) is measured off the ping RTT midpoint, and
    its span timestamps land back on the parent's clock before merge."""
    r0 = rig.fleet.replicas[0]
    plan = NetFaultPlan(
        [LinkRule(link="r0:*", direction="recv", skew_s=30.0)], seed=11
    )
    set_net_plan(plan)
    _rewire(rig.fleet)
    # The offset is an EWMA that earlier (unskewed) pings seeded near 0;
    # enough renewals converge it onto the injected skew.
    for _ in range(15):
        r0.ping(10.0)
    assert plan.total("skewed") >= 15
    assert 25.0 < r0.scorer.clock_offset_s < 35.0
    # A traced request's child span crosses the same skewed link; the
    # replica's span delivery subtracts the measured offset.
    collected = []
    r0.span_sink = collected.extend
    try:
        req = build_requests(rig.data, rig.model, [4])[0]
        attach_trace(req, TraceContext(new_trace_id(), "aaaa0001", True))
        r0.scorer.score_batch(req)
        spans = collected + r0.pull_spans(10.0)
    finally:
        r0.span_sink = None
    assert spans, "traced request produced no child spans"
    now = time.time()
    for span in spans:
        assert abs(float(span["start"]) - now) < 15.0, (
            f"span still on the skewed clock: {span['start']} vs {now}"
        )
    set_net_plan(None)
    _rewire(rig.fleet)


def test_shift_span_times_shifts_starts_and_events_only():
    spans = [{
        "start": 130.0, "duration_s": 0.5, "name": "score",
        "events": [{"t": 130.2, "name": "batch"}],
    }]
    shifted = shift_span_times(spans, 30.0)
    assert shifted[0]["start"] == pytest.approx(100.0)
    assert shifted[0]["events"][0]["t"] == pytest.approx(100.2)
    assert shifted[0]["duration_s"] == 0.5  # durations are clock-free
    assert shift_span_times(spans, 0.0) is spans  # no-op fast path


# -- lease expiry --------------------------------------------------------------

def test_partition_past_lease_declares_death_with_cause_lease():
    """Only lease EXPIRY declares: under a permanent partition the
    supervisor tolerates misses while the lease runs, then declares with
    cause ``"lease"`` — driven by a fake clock, so the verdict is exact,
    not timing-dependent."""
    model, data = _fixture(seed=5)
    session = TelemetrySession("netfault-lease")
    fleet = ServingFleet(
        model, replicas=1, backend="subprocess",
        request_spec=request_spec_for_dataset(model, data),
        max_batch=16, max_delay_s=0.001, telemetry=session,
    ).warmup()
    clock = types.SimpleNamespace(t=1000.0)
    try:
        sup = ReplicaSupervisor(
            fleet,
            SupervisorPolicy(probe_interval_s=10.0, probe_deadline_s=0.3,
                             hang_timeout_s=1e9, lease_s=5.0,
                             resurrect=False),
            telemetry=session, clock=lambda: clock.t,
        )
        r0 = fleet.replicas[0]
        sup.check_once()  # healthy: lease established and renewed
        set_net_plan(NetFaultPlan([partition("r0:*", 0.0, None)], seed=1))
        _rewire(fleet)
        clock.t += 1.0
        sup.check_once()
        assert r0.alive, "declared dead inside the lease window"
        assert _counter(session, "serving.lease_probe_misses",
                        replica="r0") >= 1
        clock.t += 10.0  # past the 5s lease
        sup.check_once()
        assert not r0.alive, "lease expiry did not declare"
        assert _counter(session, "serving.replica_deaths",
                        cause="lease") == 1
        # No false-positive resurrection: supervision was detect-only.
        assert _counter(session, "serving.replica_resurrections") == 0
    finally:
        set_net_plan(None)
        fleet.close()


# -- zero-downtime background rebuild ------------------------------------------

def test_rollout_with_rebuild_crosses_capacity_boundary():
    """Growth past the serving tables' headroom refuses the in-place
    swap (``is_capacity_refusal``) and falls through to the background
    rebuild: doubled capacity, canary parity gate, atomic cutover — and
    the grown vocabulary serves correctly afterwards."""
    model, data = _fixture(seed=3)
    session = TelemetrySession("netfault-rebuild")
    fleet = ServingFleet(
        model, replicas=2,
        request_spec=request_spec_for_dataset(model, data),
        max_batch=16, max_delay_s=0.001, telemetry=session,
        table_capacity_factor=1,
    ).warmup()
    try:
        requests = build_requests(data, model, [4, 9, 2])
        for r in requests:
            fleet.score(r)
        grown = _grown(model)
        rebuilt = fleet.rollout_with_rebuild(
            grown, probe_requests=requests[:2]
        )
        assert rebuilt, "capacity-crossing growth did not rebuild"
        for r in requests:
            got = np.asarray(fleet.score(r), np.float64)
            want = host_score_request(grown, r)
            assert np.abs(got - want).max() < 1e-3
        current, version = fleet.current_model()
        assert current is grown
        assert _counter(session, "serving.fleet_rebuilds") == 1
        assert _counter(session, "serving.replica_rebuilds") == 2
        # The SAME model fits now: the next rollout is the in-place path.
        assert fleet.rollout_with_rebuild(grown) is False
        assert _counter(session, "serving.fleet_rebuilds") == 1
        assert fleet.current_model()[1] > version  # rollouts stay monotonic
    finally:
        fleet.close()


def test_rebuild_canary_failure_restores_fleet():
    """A replacement that fails its canary parity gate is retired and the
    rebuild aborts with :class:`ReplicaRebuildError` — the fleet keeps
    serving the OLD model, fully healthy."""
    model, data = _fixture(seed=3)
    session = TelemetrySession("netfault-canary")
    fleet = ServingFleet(
        model, replicas=2,
        request_spec=request_spec_for_dataset(model, data),
        max_batch=16, max_delay_s=0.001, telemetry=session,
        table_capacity_factor=1,
    ).warmup()
    try:
        requests = build_requests(data, model, [4, 9, 2])
        clean = [np.asarray(fleet.score(r), np.float64) for r in requests]
        grown = _grown(model)
        with pytest.raises(ReplicaRebuildError):
            # parity_tol=-1.0: an impossible gate — every canary fails.
            fleet.rebuild(grown, parity_tol=-1.0,
                          probe_requests=requests[:2])
        current, _ = fleet.current_model()
        assert current is model, "aborted rebuild left the grown model"
        for r, c in zip(requests, clean):
            got = np.asarray(fleet.score(r), np.float64)
            np.testing.assert_allclose(got, c, rtol=0, atol=1e-9)
        assert all(r.alive for r in fleet.replicas)
        assert _counter(session, "serving.fleet_rebuilds") == 0
    finally:
        fleet.close()


def test_capacity_refusal_detector_matches_both_refusal_sites():
    refusals = (
        RuntimeError("grown vocabulary requires a new GameScorer"),
        ValueError("capacity growth is a layout-shape change — rebuild "
                   "the scorer instead of hot-swapping"),
    )
    for exc in refusals:
        assert is_capacity_refusal(exc)
    wrapped = RuntimeError("swap failed")
    wrapped.__cause__ = refusals[0]
    assert is_capacity_refusal(wrapped)
    assert not is_capacity_refusal(RuntimeError("unrelated failure"))


def test_subprocess_rebuild_replaces_child_under_live_traffic():
    """The subprocess flavor: the replacement is a fresh CHILD PROCESS at
    doubled capacity, born into generation+1; cutover retires the old
    child and live traffic sees zero sheds and zero lost futures."""
    model, data = _fixture(seed=7)
    session = TelemetrySession("netfault-subproc-rebuild")
    fleet = ServingFleet(
        model, replicas=1, backend="subprocess",
        request_spec=request_spec_for_dataset(model, data),
        max_batch=16, max_delay_s=0.001, telemetry=session,
        table_capacity_factor=1,
    ).warmup()
    try:
        requests = build_requests(data, model, [4, 9, 2, 7])
        for r in requests:
            fleet.score(r)
        grown = _grown(model)
        old_pid = fleet.replicas[0].child_pid
        old_gen = fleet.replicas[0].generation
        errors, stop = [], threading.Event()

        def client():
            while not stop.is_set():
                try:
                    fleet.score(requests[0])
                except Exception as e:  # noqa: BLE001 — audited below
                    errors.append(e)
                time.sleep(0.02)

        t = threading.Thread(target=client)
        t.start()
        try:
            rebuilt = fleet.rollout_with_rebuild(
                grown, probe_requests=requests[:2]
            )
        finally:
            stop.set()
            t.join()
        assert rebuilt
        assert not errors, f"live traffic failed during rebuild: {errors}"
        assert fleet.replicas[0].child_pid != old_pid
        assert fleet.replicas[0].generation == old_gen + 1
        for r in requests:
            got = np.asarray(fleet.score(r), np.float64)
            want = host_score_request(grown, r)
            assert np.abs(got - want).max() < 1e-3
        # The retired child's generation is fenced out by construction:
        # the router's cutover bumped the stamp the child echoes.
        pong = fleet.replicas[0].ping(10.0)
        assert pong.get("gen") == fleet.replicas[0].generation
    finally:
        fleet.close()
