"""bfloat16 feature-value storage (TPU-first option; reference has no analog
— Breeze vectors are f64).  Arithmetic stays float32 via promotion; only the
stored value stream shrinks, so results must track f32 to bf16 precision.
"""

import numpy as np
import jax.numpy as jnp

from photon_tpu.core.objective import GlmObjective, RegularizationContext
from photon_tpu.data.batch import (
    SparseBatch,
    attach_feature_major,
    batch_astype,
    dense_batch,
)


def _batch(n=512, k=6, d=48, seed=0):
    rng = np.random.default_rng(seed)
    ids = rng.integers(1, d, size=(n, k), dtype=np.int32)
    vals = rng.standard_normal((n, k)).astype(np.float32)
    label = (rng.random(n) < 0.5).astype(np.float32)
    return attach_feature_major(SparseBatch(
        jnp.asarray(ids), jnp.asarray(vals), jnp.asarray(label),
        jnp.zeros(n, jnp.float32), jnp.ones(n, jnp.float32),
    ))


def test_bf16_value_and_grad_tracks_f32():
    batch = _batch()
    b16 = batch_astype(batch, jnp.bfloat16)
    assert b16.vals.dtype == jnp.bfloat16 and b16.fm.vals.dtype == jnp.bfloat16
    assert b16.label.dtype == jnp.float32  # only the value stream converts
    obj = GlmObjective.create("logistic", RegularizationContext("l2", 1.0))
    w = jnp.asarray(np.random.default_rng(1).standard_normal(48), jnp.float32) * 0.3
    v32, g32 = obj.value_and_grad(w, batch)
    v16, g16 = obj.value_and_grad(w, b16)
    assert v16.dtype == jnp.float32 and g16.dtype == jnp.float32
    np.testing.assert_allclose(float(v16), float(v32), rtol=2e-2)
    np.testing.assert_allclose(np.asarray(g16), np.asarray(g32),
                               rtol=5e-2, atol=2e-2)


def test_bf16_dense_batch():
    rng = np.random.default_rng(2)
    x = rng.standard_normal((128, 16)).astype(np.float32)
    y = (rng.random(128) < 0.5).astype(np.float32)
    b = dense_batch(x, y)
    b16 = batch_astype(b, jnp.bfloat16)
    assert b16.x.dtype == jnp.bfloat16
    obj = GlmObjective.create("logistic")
    w = jnp.asarray(rng.standard_normal(16), jnp.float32) * 0.2
    v32, _ = obj.value_and_grad(w, b)
    v16, _ = obj.value_and_grad(w, b16)
    np.testing.assert_allclose(float(v16), float(v32), rtol=2e-2)


def test_driver_dtype_flag(tmp_path):
    """--dtype bfloat16 trains end-to-end and lands near the f32 model."""
    from photon_tpu.drivers import train

    rng = np.random.default_rng(3)
    n, d = 600, 24
    w_true = rng.standard_normal(d)
    path = tmp_path / "t.libsvm"
    with open(path, "w") as f:
        for _ in range(n):
            fid = np.sort(rng.choice(np.arange(1, d + 1), 5, replace=False))
            xv = rng.standard_normal(5)
            y = 1 if rng.random() < 1 / (1 + np.exp(-float(w_true[fid - 1] @ xv))) else -1
            f.write(f"{y} " + " ".join(f"{j}:{v:.5f}" for j, v in zip(fid, xv)) + "\n")

    outs = {}
    for dtype in ("float32", "bfloat16"):
        out = tmp_path / dtype
        summary = train.run(train.build_parser().parse_args([
            "--backend", "cpu", "--input", str(path),
            "--task", "logistic_regression", "--reg-weights", "1.0",
            "--max-iterations", "40", "--dtype", dtype,
            "--output-dir", str(out),
        ]))
        outs[dtype] = summary["sweep"][0]["final_value"]
    np.testing.assert_allclose(outs["bfloat16"], outs["float32"], rtol=2e-2)


def test_game_driver_dtype_flag(tmp_path):
    """train_game --dtype bfloat16 trains end-to-end near the f32 metrics
    (validation stays f32, so AUC differences are model-quality only)."""
    import os

    from photon_tpu.drivers import train_game

    aucs = {}
    for dtype in ("float32", "bfloat16"):
        out = tmp_path / dtype
        summary = train_game.run(train_game.build_parser().parse_args([
            "--backend", "cpu",
            "--input", "synthetic-game:16:6:8:4:1:4",
            "--coordinate", "fixed:type=fixed,shard=global,max_iters=5",
            "--coordinate", "pu:type=random,shard=re0,entity=re0,max_iters=4",
            "--descent-iterations", "1",
            "--validation-split", "0.25",
            "--dtype", dtype,
            "--output-dir", str(out),
        ]))
        aucs[dtype] = summary["best_metrics"]["AUC"]
        assert os.path.isdir(str(out / "best_model"))
    assert abs(aucs["bfloat16"] - aucs["float32"]) < 0.05, aucs
