"""LIBSVM reader, index maps, Avro codec, model save/load round trips
(the reference's IO + index-map unit tests — SURVEY.md §4)."""

import io
import os

import jax.numpy as jnp
import numpy as np
import pytest

from photon_tpu.data import avro_codec
from photon_tpu.data.index_map import DELIMITER, IndexMap, feature_key
from photon_tpu.data.libsvm import parse_libsvm, to_sparse_batch
from photon_tpu.data.model_io import load_glm_model, save_glm_model
from photon_tpu.models.glm import Coefficients, LogisticRegressionModel

LIBSVM_SAMPLE = b"""\
+1 3:1 11:0.5 14:-2
-1 1:2.5 19:1 39:1  # trailing comment
+1 5:1
-1 2:1 3:0.5
"""


def test_parse_libsvm(tmp_path):
    p = tmp_path / "sample.libsvm"
    p.write_bytes(LIBSVM_SAMPLE)
    data = parse_libsvm(str(p))
    assert data.num_examples == 4
    assert data.dim == 39  # max 1-based id 39 -> 0-based 38 -> dim 39
    np.testing.assert_allclose(data.labels, [1, -1, 1, -1])
    ids0, vals0 = data.rows[0]
    np.testing.assert_array_equal(ids0, [2, 10, 13])
    np.testing.assert_allclose(vals0, [1.0, 0.5, -2.0])


def test_to_sparse_batch_intercept(tmp_path):
    p = tmp_path / "sample.libsvm"
    p.write_bytes(LIBSVM_SAMPLE)
    data = parse_libsvm(str(p))
    batch, dim = to_sparse_batch(data, intercept=True)
    assert dim == 40
    # Labels normalized to {0,1}.
    np.testing.assert_allclose(np.asarray(batch.label), [1, 0, 1, 0])
    # Intercept id = 39 present in every row.
    assert all(39 in set(np.asarray(batch.ids[i])) for i in range(4))
    # Margin with w = e_intercept is 1 for every row.
    from photon_tpu.data.batch import margins

    w = jnp.zeros(40).at[39].set(1.0)
    np.testing.assert_allclose(np.asarray(margins(w, batch)), np.ones(4))


def test_index_map_roundtrip(tmp_path):
    keys = [feature_key("age"), feature_key("cat", "dog"), feature_key("z", "1")]
    imap = IndexMap.build(keys + keys, intercept=True)  # dedup preserved order
    assert len(imap) == 4
    assert imap.intercept_id == 3
    assert imap.get_id(feature_key("cat", "dog")) == 1
    assert imap.get_id("missing") == -1
    path = str(tmp_path / "imap.json")
    imap.save(path)
    loaded = IndexMap.load(path)
    assert list(loaded.keys()) == list(imap.keys())
    assert loaded.intercept_id == 3


def test_avro_codec_primitives_roundtrip():
    schema = {
        "type": "record",
        "name": "T",
        "fields": [
            {"name": "s", "type": "string"},
            {"name": "i", "type": "long"},
            {"name": "d", "type": "double"},
            {"name": "u", "type": ["null", "string"]},
            {"name": "arr", "type": {"type": "array", "items": "double"}},
        ],
    }
    rec = {"s": "héllo", "i": -12345678901, "d": 3.25, "u": None, "arr": [1.0, -2.5]}
    buf = io.BytesIO()
    avro_codec.write_datum(buf, rec, schema)
    buf.seek(0)
    assert avro_codec.read_datum(buf, schema) == rec


def test_avro_container_roundtrip(tmp_path):
    schema = {
        "type": "record",
        "name": "Row",
        "fields": [{"name": "x", "type": "long"}],
    }
    path = str(tmp_path / "rows.avro")
    records = [{"x": i} for i in range(100)]
    avro_codec.write_container(path, schema, records)
    schema2, records2 = avro_codec.read_container(path)
    assert records2 == records
    assert schema2["name"] == "Row"


@pytest.mark.parametrize("fmt", ["avro", "json"])
def test_model_save_load_roundtrip(tmp_path, fmt):
    keys = [feature_key(f"f{i}") for i in range(5)]
    imap = IndexMap.build(keys, intercept=True)
    means = jnp.asarray([0.5, 0.0, -1.5, 2.0, 0.0, 0.25])  # two exact zeros
    variances = jnp.asarray([0.1, 0.2, 0.3, 0.4, 0.5, 0.6])
    model = LogisticRegressionModel(Coefficients(means, variances))
    path = str(tmp_path / f"model.{fmt}")
    save_glm_model(path, model, imap, fmt=fmt)
    loaded = load_glm_model(path, imap)
    assert loaded.task_type == "logistic_regression"
    np.testing.assert_allclose(np.asarray(loaded.coefficients.means), means, rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(loaded.coefficients.variances), variances, rtol=1e-6
    )


def test_model_load_with_rebuilt_index_map(tmp_path):
    # Feature-key join: a permuted/extended index map must still place
    # coefficients at the right features (the reference's portability
    # property for name/term-keyed models).
    keys = [feature_key(n) for n in ("a", "b", "c")]
    imap = IndexMap.build(keys, intercept=True)
    means = jnp.asarray([1.0, 2.0, 3.0, 0.5])
    model = LogisticRegressionModel(Coefficients(means))
    path = str(tmp_path / "m.avro")
    save_glm_model(path, model, imap)
    imap2 = IndexMap.build([feature_key(n) for n in ("c", "x", "a", "b")], intercept=True)
    loaded = load_glm_model(path, imap2)
    got = np.asarray(loaded.coefficients.means)
    assert got[imap2.get_id(feature_key("a"))] == 1.0
    assert got[imap2.get_id(feature_key("b"))] == 2.0
    assert got[imap2.get_id(feature_key("c"))] == 3.0
    assert got[imap2.get_id(feature_key("x"))] == 0.0
    assert got[imap2.intercept_id] == 0.5


def test_avro_by_name_reference_with_empty_defining_array():
    # A named record referenced by name in a later field must decode even
    # when the defining array is empty (named types are registered by a
    # schema walk, not lazily at first write).
    schema = {
        "type": "record",
        "name": "M",
        "fields": [
            {"name": "means", "type": {"type": "array", "items": {
                "type": "record", "name": "NTV",
                "fields": [{"name": "v", "type": "double"}],
            }}},
            {"name": "variances", "type": ["null", {"type": "array", "items": "NTV"}]},
        ],
    }
    rec = {"means": [], "variances": [{"v": 1.5}]}
    buf = io.BytesIO()
    avro_codec.write_datum(buf, rec, schema)
    buf.seek(0)
    assert avro_codec.read_datum(buf, schema) == rec


def test_iter_container_matches_read_container(tmp_path):
    """The lazy reader must yield exactly read_container's records — the
    streamed GAME ingestion (game_io.read_game_avro) is built on it."""
    schema = {
        "type": "record", "name": "R",
        "fields": [
            {"name": "x", "type": "double"},
            {"name": "s", "type": "string"},
        ],
    }
    records = [{"x": float(i) / 3.0, "s": f"r{i}"} for i in range(257)]
    path = str(tmp_path / "r.avro")
    avro_codec.write_container(path, schema, records)
    assert list(avro_codec.iter_container(path)) == records
    _, eager = avro_codec.read_container(path)
    assert eager == records


def test_read_game_avro_multi_file_matches_single(tmp_path):
    """Part-file input (the 1B-row layout) must produce the same dataset
    and vocabularies as one concatenated file."""
    from photon_tpu.data.fixtures import make_movielens_like
    from photon_tpu.data.game_io import read_game_avro, write_game_avro
    from photon_tpu.game.data import take_rows

    data, maps = make_movielens_like(n_users=24, n_items=18, mean_ratings=6)
    n = data.num_examples
    single = str(tmp_path / "all.avro")
    write_game_avro(single, data, maps)
    parts_dir = tmp_path / "parts"
    parts_dir.mkdir()
    third = n // 3
    for pi, (lo, hi) in enumerate([(0, third), (third, 2 * third), (2 * third, n)]):
        write_game_avro(
            str(parts_dir / f"part-{pi}.avro"),
            take_rows(data, np.arange(lo, hi)), maps,
        )

    bags = {name: name for name in data.shards}
    got_s, maps_s = read_game_avro(single, bags, list(data.id_columns))
    got_m, maps_m = read_game_avro(
        str(parts_dir / "*.avro"), bags, list(data.id_columns)
    )
    for name in bags:
        assert [maps_s[name].get_key(i) for i in range(len(maps_s[name]))] == \
            [maps_m[name].get_key(i) for i in range(len(maps_m[name]))]
        np.testing.assert_array_equal(
            got_s.shards[name].ids, got_m.shards[name].ids
        )
        np.testing.assert_array_equal(
            got_s.shards[name].vals, got_m.shards[name].vals
        )
    np.testing.assert_array_equal(got_s.label, got_m.label)
    np.testing.assert_array_equal(got_s.weight, got_m.weight)
    for col in data.id_columns:
        np.testing.assert_array_equal(
            got_s.id_columns[col], got_m.id_columns[col]
        )
