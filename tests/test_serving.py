"""Online GAME scoring service (photon_tpu/serving): device-resident
tables, recompile-free bucketed micro-batching, async batcher, drivers.

The contracts pinned here:

- parity: the serving gather-table path scores exactly what the host
  ``GameModel.score`` oracle scores (requests, whole datasets, both mesh
  shapes);
- recompile freedom: after :meth:`GameScorer.warmup`, 50 batches of varied
  sizes spanning BOTH padded buckets trigger ZERO jax compilations (jax
  monitoring listener + the scorer's own compile counter) and exactly one
  host sync per batch (``serving.host_syncs``);
- cold entities: unknown keys fall back to fixed-effect-only scores through
  the zero gather row and are counted;
- the batcher coalesces under max-delay/max-batch, preserves per-request
  result slices, and surfaces scorer failures through futures;
- the batched model-export d2h (ONE ``jax.device_get`` for all coordinate
  tables, ``descent.host_transfer_bytes{path=export}``);
- the batch ``score_game`` route shares the scorer with serving.
"""

from __future__ import annotations

import numpy as np
import pytest

from photon_tpu.data.synthetic import make_game_dataset
from photon_tpu.game.model import (
    FixedEffectModel,
    GameModel,
    RandomEffectModel,
)
from photon_tpu.models.glm import Coefficients, model_for_task
from photon_tpu.serving import (
    GameScorer,
    RequestBatcher,
    ScoringRequest,
    build_requests,
    concat_requests,
    request_from_dataset,
    request_spec_for_dataset,
    run_closed_loop,
    slice_request,
)
from photon_tpu.telemetry import TelemetrySession


def _fixture(seed=3, n_entities=40, fixed_dim=6, random_dim=4):
    """Constructed (not fitted) GAME model + matching dataset: serving
    tests measure scoring, and a fit would slow every test for nothing."""
    data, _ = make_game_dataset(
        n_entities, 4, fixed_dim, random_dim, seed=seed
    )
    rng = np.random.default_rng(seed)
    keys = np.unique(data.id_columns["re0"])
    model = GameModel(
        coordinates={
            "fixed": FixedEffectModel(
                model_for_task("logistic_regression", Coefficients(
                    rng.standard_normal(fixed_dim).astype(np.float32)
                )),
                "global",
            ),
            "per_entity": RandomEffectModel(
                table=rng.standard_normal(
                    (len(keys), random_dim)
                ).astype(np.float32),
                keys=keys, entity_column="re0", shard_name="re0",
                task_type="logistic_regression",
            ),
        },
        task_type="logistic_regression",
    )
    return model, data


@pytest.fixture(scope="module")
def served():
    model, data = _fixture()
    session = TelemetrySession("test-serving")
    scorer = GameScorer(
        model, request_spec=request_spec_for_dataset(model, data),
        max_batch=64, telemetry=session,
    ).warmup()
    return model, data, scorer, session


def _counter_total(session, name, **labels):
    total = 0
    for m in session.registry.snapshot()["counters"]:
        if m["name"] != name:
            continue
        if labels and any(
            str(m["labels"].get(k)) != str(v) for k, v in labels.items()
        ):
            continue
        total += m["value"]
    return total


# -- scorer parity -----------------------------------------------------------

def test_request_scores_match_host_oracle(served):
    model, data, scorer, _ = served
    want = model.score(data)
    sizes = [1, 3, 17, 64, 64]
    pos = 0
    for req, size in zip(build_requests(data, model, sizes), sizes):
        rows = np.arange(pos, pos + size) % data.num_examples
        got = scorer.score_batch(req)
        np.testing.assert_allclose(got, want[rows], rtol=1e-4, atol=1e-4)
        pos = (pos + size) % data.num_examples


def test_score_dataset_matches_host_oracle(served):
    model, data, scorer, _ = served
    np.testing.assert_allclose(
        scorer.score_dataset(data), model.score(data), rtol=1e-4, atol=1e-4
    )


def test_scorer_under_mesh_matches_host_oracle():
    """Mesh parity, stress-looped: the replica-aliasing donation bug this
    pins (one replica's output clobbering a zero-copy-shared input buffer)
    corrupted only a FRACTION of batches — a single comparison passed most
    runs; thirty back-to-back batches fail reliably on regression."""
    from photon_tpu.parallel.mesh import create_mesh

    model, data = _fixture(seed=5)
    scorer = GameScorer(
        model, mesh=create_mesh(),
        request_spec=request_spec_for_dataset(model, data), max_batch=32,
    ).warmup()
    want = model.score(data)
    np.testing.assert_allclose(
        scorer.score_dataset(data), want, rtol=1e-4, atol=1e-4
    )
    rng = np.random.default_rng(1)
    sizes = rng.integers(1, 33, size=30).tolist()
    pos = 0
    for req, size in zip(build_requests(data, model, sizes), sizes):
        rows = np.arange(pos, pos + size) % data.num_examples
        np.testing.assert_allclose(
            scorer.score_batch(req), want[rows], rtol=1e-4, atol=1e-4
        )
        pos = (pos + size) % data.num_examples


def test_sparse_request_spec_roundtrip():
    """Avro-shaped input (padded-COO sparse shards) serves through the same
    scorer: spec carries the nonzero width, parity holds."""
    from photon_tpu.data.game_io import read_game_avro, write_game_avro

    model, data = _fixture(seed=11)
    import tempfile, os

    with tempfile.TemporaryDirectory() as tmp:
        path = os.path.join(tmp, "req.avro")
        _, imaps = make_game_dataset(40, 4, 6, 4, seed=11)
        write_game_avro(path, data, imaps)
        sparse_data, _ = read_game_avro(
            path, {n: n for n in data.shards}, ["re0"], index_maps=imaps
        )
    scorer = GameScorer(
        model, request_spec=request_spec_for_dataset(model, sparse_data),
        max_batch=32,
    ).warmup()
    got = scorer.score_dataset(sparse_data)
    want = model.score(sparse_data)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_oversize_batch_is_chunked(served):
    model, data, scorer, _ = served
    n = data.num_examples
    assert n > scorer.max_bucket
    req = request_from_dataset(data, model)
    np.testing.assert_allclose(
        scorer.score_batch(req), model.score(data), rtol=1e-4, atol=1e-4
    )


# -- cold entities -----------------------------------------------------------

def test_unknown_entities_fall_back_to_fixed_effect(served):
    model, data, scorer, session = served
    before = _counter_total(session, "serving.cold_entities")
    x_fixed = data.shards["global"].x[:3]
    x_rand = data.shards["re0"].x[:3]
    req = ScoringRequest(
        features={"global": x_fixed, "re0": x_rand},
        entity_ids={"re0": np.array([10 ** 9, 10 ** 9 + 1, 10 ** 9 + 2])},
    )
    got = scorer.score_batch(req)
    fixed_only = x_fixed @ np.asarray(
        model.coordinates["fixed"].coefficients.means
    )
    np.testing.assert_allclose(got, fixed_only, rtol=1e-5, atol=1e-5)
    assert _counter_total(session, "serving.cold_entities") == before + 3


def test_padding_rows_not_counted_cold(served):
    """A 3-row request pads to the 8-bucket with entity index -1; only the
    REAL unknown rows may count as cold."""
    model, data, scorer, session = served
    before = _counter_total(session, "serving.cold_entities")
    (req,) = build_requests(data, model, [3])
    scorer.score_batch(req)  # all known entities
    assert _counter_total(session, "serving.cold_entities") == before


# -- recompile freedom (the ISSUE acceptance contract) -----------------------

def test_no_recompiles_after_warmup_across_buckets(served):
    """50 post-warmup batches of varied sizes spanning both padded buckets:
    ZERO jax compilations and exactly one host sync per batch."""
    import jax.monitoring
    from jax._src import monitoring as monitoring_src

    model, data, scorer, session = served
    compile_events = []

    def listener(event, **kwargs):
        if "compile" in event:
            compile_events.append(event)

    rng = np.random.default_rng(0)
    sizes = rng.integers(1, scorer.max_bucket + 1, size=50).tolist()
    # Spanning "both padded buckets" must be true by construction, not by
    # RNG luck: force one batch into the smallest and one into the largest.
    sizes[0], sizes[-1] = 1, scorer.max_bucket
    requests = build_requests(data, model, sizes)
    compilations_before = scorer.compilations
    syncs_before = _counter_total(session, "serving.host_syncs")
    batches_before = _counter_total(session, "serving.batches")

    jax.monitoring.register_event_listener(listener)
    try:
        for req in requests:
            scorer.score_batch(req)
    finally:
        monitoring_src._unregister_event_listener_by_callback(listener)

    assert compile_events == []
    assert scorer.compilations == compilations_before
    assert _counter_total(session, "serving.compilations") == \
        compilations_before
    batches = _counter_total(session, "serving.batches") - batches_before
    assert batches == 50
    # serving.host_syncs <= 1 per batch (exactly 1 here).
    assert _counter_total(session, "serving.host_syncs") - syncs_before == 50
    # The varied sizes really did exercise more than one bucket.
    buckets_hit = {
        m["labels"]["bucket"]
        for m in session.registry.snapshot()["counters"]
        if m["name"] == "serving.batches"
    }
    assert len(buckets_hit) >= 2


def test_off_ladder_shape_raises_after_warmup(served):
    # A bucket no other test can have cached (score_dataset legitimately
    # adds the dataset's own pow2 shape to the compiled set).
    _, _, scorer, _ = served
    with pytest.raises(RuntimeError, match="never recompile"):
        scorer._program(scorer.max_bucket * 4096)


def test_warmup_compiles_whole_ladder():
    model, data = _fixture(seed=9)
    scorer = GameScorer(
        model, request_spec=request_spec_for_dataset(model, data),
        max_batch=64,
    )
    assert scorer.compilations == 0
    scorer.warmup()
    assert scorer.compilations == len(scorer.buckets)
    assert scorer.buckets == (8, 16, 32, 64)
    assert scorer.bucket_for(1) == 8
    assert scorer.bucket_for(9) == 16
    assert scorer.bucket_for(64) == 64
    with pytest.raises(ValueError, match="exceeds max bucket"):
        scorer.bucket_for(65)


# -- request plumbing --------------------------------------------------------

def test_request_validation_errors(served):
    model, data, scorer, _ = served
    (req,) = build_requests(data, model, [4])
    with pytest.raises(ValueError, match="missing shard"):
        scorer.score_batch(ScoringRequest(
            features={"global": req.features["global"]},
            entity_ids=req.entity_ids,
        ))
    with pytest.raises(ValueError, match="missing id column"):
        scorer.score_batch(ScoringRequest(
            features=req.features, entity_ids={},
        ))
    with pytest.raises(ValueError, match="want"):
        scorer.score_batch(ScoringRequest(
            features={"global": req.features["global"][:, :2],
                      "re0": req.features["re0"]},
            entity_ids=req.entity_ids,
        ))


def test_slice_and_concat_roundtrip(served):
    model, data, _, _ = served
    req = request_from_dataset(data, model)
    parts = [slice_request(req, 0, 10), slice_request(req, 10, req.num_rows)]
    merged = concat_requests(parts)
    assert merged.num_rows == req.num_rows
    np.testing.assert_array_equal(
        merged.features["global"], req.features["global"]
    )
    np.testing.assert_array_equal(
        merged.entity_ids["re0"], req.entity_ids["re0"]
    )
    np.testing.assert_array_equal(merged.offset, req.offset)


# -- batcher -----------------------------------------------------------------

def test_batcher_coalesces_and_preserves_request_slices(served):
    model, data, scorer, session = served
    want = model.score(data)
    sizes = [2] * 20
    requests = build_requests(data, model, sizes)
    batches_before = _counter_total(session, "serving.batches")
    with RequestBatcher(scorer, max_delay_s=0.05) as batcher:
        futures = [batcher.submit(r) for r in requests]
        results = [f.result(timeout=30) for f in futures]
    pos = 0
    for size, got in zip(sizes, results):
        rows = np.arange(pos, pos + size) % data.num_examples
        np.testing.assert_allclose(got, want[rows], rtol=1e-4, atol=1e-4)
        pos = (pos + size) % data.num_examples
    # 40 rows in 2-row requests under a generous window: far fewer
    # batches than requests (coalescing actually happened).
    batches = _counter_total(session, "serving.batches") - batches_before
    assert batches < len(requests)


def test_batcher_closed_loop_and_latency_telemetry(served):
    model, data, scorer, session = served
    requests = build_requests(data, model, [1, 5, 9, 30, 2, 7])
    with RequestBatcher(scorer, max_delay_s=0.001) as batcher:
        scores, latencies, wall = run_closed_loop(batcher, requests, clients=3)
    assert len(scores) == len(requests)
    assert all(lat is not None and lat >= 0 for lat in latencies)
    hist = next(
        h for h in session.registry.snapshot()["histograms"]
        if h["name"] == "serving.request_latency_s"
    )
    assert hist["count"] >= len(requests)
    assert hist["p99"] is not None


def test_batcher_surfaces_scorer_failure(served):
    model, data, scorer, _ = served
    (good,) = build_requests(data, model, [4])
    bad = ScoringRequest(
        features={"global": good.features["global"]},  # missing re0 shard
        entity_ids=good.entity_ids,
    )
    with RequestBatcher(scorer, max_delay_s=0.001) as batcher:
        fut = batcher.submit(bad)
        with pytest.raises(ValueError, match="missing shard"):
            fut.result(timeout=30)
        # The batcher thread survives a failed batch.
        ok = batcher.submit(good).result(timeout=30)
    assert ok.shape == (4,)
    with pytest.raises(RuntimeError, match="closed"):
        batcher.submit(good)


def test_batcher_respects_max_batch_rows(served):
    model, data, scorer, session = served
    sizes = [30, 30, 30]  # 90 rows > max_batch 60 -> at least two batches
    requests = build_requests(data, model, sizes)
    batches_before = _counter_total(session, "serving.batches")
    with RequestBatcher(scorer, max_batch=60, max_delay_s=0.2) as batcher:
        futures = [batcher.submit(r) for r in requests]
        for f in futures:
            f.result(timeout=30)
    assert _counter_total(session, "serving.batches") - batches_before >= 2


# -- batched export d2h (satellite) ------------------------------------------

def test_save_game_model_single_batched_device_get(tmp_path, monkeypatch):
    import jax

    from photon_tpu.game.model_io import load_game_model, save_game_model

    model, data = _fixture(seed=13)
    _, imaps = make_game_dataset(40, 4, 6, 4, seed=13)
    session = TelemetrySession("test-export")
    calls = []
    real = jax.device_get

    def counting(x):
        calls.append(1)
        return real(x)

    # _fetch_model_tables resolves jax.device_get at call time, so the
    # global patch counts the export's d2h dispatches.
    monkeypatch.setattr(jax, "device_get", counting)
    save_game_model(str(tmp_path / "m"), model, imaps, telemetry=session)
    assert len(calls) == 1  # ONE d2h for every coordinate's tables
    moved = _counter_total(
        session, "descent.host_transfer_bytes", direction="d2h", path="export"
    )
    table = model.coordinates["per_entity"].table
    fixed = model.coordinates["fixed"].coefficients.means
    assert moved == table.nbytes + np.asarray(fixed).nbytes
    loaded, _ = load_game_model(str(tmp_path / "m"))
    np.testing.assert_allclose(
        loaded.score(data), model.score(data), rtol=1e-5, atol=1e-5
    )


# -- drivers -----------------------------------------------------------------

def test_score_game_batch_routes_through_scorer(tmp_path, monkeypatch):
    """The non-streamed batch driver scores through the serving gather
    tables; the host escape hatch reproduces the old path and both agree."""
    from photon_tpu.drivers import score_game
    from photon_tpu.game.model_io import save_game_model

    model, data = _fixture(seed=17)
    _, imaps = make_game_dataset(40, 4, 6, 4, seed=17)
    save_game_model(str(tmp_path / "model"), model, imaps)

    def run(outdir, env=None):
        if env:
            monkeypatch.setenv("PHOTON_BATCH_SCORER", env)
        else:
            monkeypatch.delenv("PHOTON_BATCH_SCORER", raising=False)
        score_game.run(score_game.build_parser().parse_args([
            "--backend", "cpu",
            "--input", "synthetic-game:40:4:6:4:1:17",
            "--model", str(tmp_path / "model"),
            "--output-dir", str(tmp_path / outdir),
        ]))
        return np.loadtxt(str(tmp_path / outdir / "scores.txt"))

    device = run("out-device")
    host = run("out-host", env="host")
    np.testing.assert_allclose(device, host, rtol=1e-4, atol=1e-4)


def test_serve_game_driver_end_to_end(tmp_path):
    from photon_tpu.drivers import serve_game
    from photon_tpu.game.model_io import save_game_model

    model, data = _fixture(seed=21)
    _, imaps = make_game_dataset(40, 4, 6, 4, seed=21)
    save_game_model(str(tmp_path / "model"), model, imaps)
    out = tmp_path / "served"
    summary = serve_game.run(serve_game.build_parser().parse_args([
        "--backend", "cpu",
        "--model", str(tmp_path / "model"),
        "--input", "synthetic-game:40:4:6:4:1:21",
        "--requests", "25",
        "--clients", "3",
        # The PR 9 stream (consecutive row windows), kept as --traffic
        # geometric for bench continuity: the scores.txt spot-check below
        # relies on request windows starting at row 0.
        "--traffic", "geometric",
        "--max-batch", "32",
        "--max-delay-ms", "1",
        "--output-dir", str(out),
    ]))
    assert summary["requests"] == 25
    assert summary["served"] == 25 and summary["shed"] == 0
    assert summary["qps"] > 0
    assert summary["latency_p99_ms"] >= summary["latency_p50_ms"]
    scores = np.loadtxt(str(out / "scores.txt"))
    assert len(scores) == summary["rows"]
    # Scores must be the model's (spot-check the first request window
    # against the host oracle; request windows start at row 0).
    want = model.score(data)
    np.testing.assert_allclose(
        scores[:10], want[:10], rtol=1e-4, atol=1e-4
    )
    # Run report carries the serving block.
    import json

    with open(out / "telemetry" / "run_report.json") as f:
        report = json.load(f)
    names = {m["name"] for m in report["metrics"]["counters"]}
    assert {"serving.requests", "serving.batches",
            "serving.host_syncs"} <= names
    from photon_tpu.telemetry.report import render_markdown

    md = render_markdown(report)
    assert "## Online serving" in md
    assert "serving.host_syncs per batch | 1 |" in md


# -- model hot-swap (ISSUE 10 satellite) -------------------------------------

def _retrained(model: GameModel, seed: int) -> GameModel:
    """A 'retrained' model: same coordinate layout and vocabularies,
    different coefficients — the production hot-swap shape."""
    rng = np.random.default_rng(seed)
    fixed = model.coordinates["fixed"]
    per_entity = model.coordinates["per_entity"]
    means = np.asarray(fixed.coefficients.means)
    return GameModel(
        coordinates={
            "fixed": FixedEffectModel(
                model_for_task(model.task_type, Coefficients(
                    (means + rng.standard_normal(means.shape)).astype(
                        np.float32
                    )
                )),
                fixed.shard_name,
            ),
            "per_entity": RandomEffectModel(
                table=rng.standard_normal(
                    (per_entity.num_entities, per_entity.dim)
                ).astype(np.float32),
                keys=per_entity.keys,
                entity_column=per_entity.entity_column,
                shard_name=per_entity.shard_name,
                task_type=model.task_type,
            ),
        },
        task_type=model.task_type,
    )


def test_swap_model_scores_new_model_without_recompiles():
    model, data = _fixture(seed=23)
    session = TelemetrySession("test-swap")
    scorer = GameScorer(
        model, request_spec=request_spec_for_dataset(model, data),
        max_batch=32, telemetry=session,
    ).warmup()
    compiled = scorer.compilations
    req = build_requests(data, model, [16])[0]
    np.testing.assert_allclose(
        scorer.score_batch(req), model.score(data)[:16],
        rtol=1e-4, atol=1e-4,
    )
    retrained = _retrained(model, seed=29)
    scorer.swap_model(retrained)
    # Zero recompiles, scores are the NEW model's, and the swap counted.
    np.testing.assert_allclose(
        scorer.score_batch(req), retrained.score(data)[:16],
        rtol=1e-4, atol=1e-4,
    )
    assert scorer.compilations == compiled
    assert _counter_total(session, "serving.swaps") == 1


def test_swap_model_mid_closed_loop_no_dropped_requests():
    """Swap while a closed-loop request stream is in flight: every request
    completes, every response matches the model that was live when its
    batch dispatched (old XOR new — never a mix), and scores before/after
    the swap pin both models."""
    model, data = _fixture(seed=31)
    session = TelemetrySession("test-swap-loop")
    scorer = GameScorer(
        model, request_spec=request_spec_for_dataset(model, data),
        max_batch=32, telemetry=session,
    ).warmup()
    retrained = _retrained(model, seed=37)
    want_old = model.score(data)
    want_new = retrained.score(data)
    requests = build_requests(data, model, [8] * 40)
    windows = [np.arange(i * 8, (i + 1) * 8) % data.num_examples
               for i in range(40)]
    batcher = RequestBatcher(scorer, max_batch=32, max_delay_s=0.001)
    swap_at = 20
    results = []
    with batcher:
        futures = []
        for i, req in enumerate(requests):
            if i == swap_at:
                scorer.swap_model(retrained)
            futures.append(batcher.submit(req))
        results = [f.result(timeout=30) for f in futures]
    assert len(results) == len(requests)
    for rows, got in zip(windows, results):
        # Every response is exactly ONE model's scores — old XOR new,
        # never a mix of the two tables/vocabularies.
        ok_old = np.allclose(got, want_old[rows], rtol=1e-4, atol=1e-4)
        ok_new = np.allclose(got, want_new[rows], rtol=1e-4, atol=1e-4)
        assert ok_old or ok_new, "response matches neither model"
    # The tail of the stream (submitted well after the swap) must be the
    # new model's scores.
    assert np.allclose(
        results[-1], want_new[windows[-1]], rtol=1e-4, atol=1e-4
    )
    assert _counter_total(session, "serving.swaps") == 1


def test_swap_model_grown_vocabulary_within_capacity():
    """Satellite (ISSUE 12): the serving tables carry amortized-doubling
    capacity headroom and a MOVABLE zero-row index, so a model whose grown
    vocabulary still fits the served capacity hot-swaps in place — zero
    recompiles, the new entity scores its own (non-zero) row, and it is no
    longer counted cold."""
    import dataclasses

    import jax.numpy as jnp

    model, data = _fixture(seed=41)
    session = TelemetrySession("test-grow-swap")
    scorer = GameScorer(
        model, request_spec=request_spec_for_dataset(model, data),
        max_batch=16, telemetry=session,
    ).warmup()
    compiled = scorer.compilations
    per_entity = model.coordinates["per_entity"]
    new_key = np.asarray([10_000], per_entity.keys.dtype)
    grown = per_entity.with_entities(
        np.unique(np.concatenate([per_entity.keys, new_key]))
    )
    # Give the onboarded entity a real (non-zero) coefficient row so its
    # served margin is distinguishable from the cold fallback.
    new_idx = int(np.searchsorted(grown.keys, new_key[0]))
    new_row = np.arange(1, grown.dim + 1, dtype=np.float32)
    grown = dataclasses.replace(
        grown, table=jnp.asarray(grown.table).at[new_idx].set(new_row)
    )
    bigger = GameModel(
        coordinates={**model.coordinates, "per_entity": grown},
        task_type=model.task_type,
    )
    scorer.swap_model(bigger)

    x_fixed = data.shards["global"].x[:2]
    x_rand = data.shards["re0"].x[:2]
    cold_before = _counter_total(session, "serving.cold_entities")
    got = scorer.score_batch(ScoringRequest(
        features={"global": x_fixed, "re0": x_rand},
        entity_ids={"re0": np.asarray(
            [10_000, 999_999], per_entity.keys.dtype
        )},
    ))
    fixed_only = x_fixed @ np.asarray(
        model.coordinates["fixed"].coefficients.means
    )
    np.testing.assert_allclose(
        got, fixed_only + np.array([x_rand[0] @ new_row, 0.0]),
        rtol=1e-4, atol=1e-4,
    )
    # The grown entity is served (not cold); the truly unknown one still
    # rides the (moved) zero row and counts.
    assert _counter_total(session, "serving.cold_entities") == \
        cold_before + 1
    assert scorer.compilations == compiled
    assert _counter_total(session, "serving.swaps") == 1


def test_swap_model_rejects_layout_changes():
    model, data = _fixture(seed=41)
    scorer = GameScorer(
        model, request_spec=request_spec_for_dataset(model, data),
        max_batch=16,
    ).warmup()
    per_entity = model.coordinates["per_entity"]
    # Growth PAST the table capacity is a layout-shape change: the compiled
    # programs' gather-table shape would have to grow — refuse (rebuild).
    capacity = 1
    while capacity < per_entity.num_entities + 1:
        capacity *= 2
    extra = np.arange(
        20_000, 20_000 + capacity, dtype=per_entity.keys.dtype
    )
    grown = per_entity.with_entities(
        np.unique(np.concatenate([per_entity.keys, extra]))
    )
    bigger = GameModel(
        coordinates={**model.coordinates, "per_entity": grown},
        task_type=model.task_type,
    )
    with pytest.raises(ValueError, match="layout-shape change"):
        scorer.swap_model(bigger)
    # A changed coordinate SET refuses too (plan mismatch).
    with pytest.raises(ValueError, match="swap_model"):
        scorer.swap_model(GameModel(
            coordinates={"fixed": model.coordinates["fixed"]},
            task_type=model.task_type,
        ))
