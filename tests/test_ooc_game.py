"""Out-of-core GAME training (ISSUE 10): tiled score tables, the
double-buffered chunk streamer, the streamed epoch-style descent.

Contracts pinned here:

- per-chunk Neumaier partials reduce to the resident engine's global
  total (chunking never changes an offset or composite value);
- streamed-vs-resident fit parity ≤ 1e-4 against BOTH residual modes
  (linear task; the logistic fixture sits at the chunked-accumulation
  solver floor and gets its own documented bound);
- chunk-boundary edge cases: a partial last chunk, an exactly-divisible
  plan, and the single-chunk degenerate plan all converge to the same fit;
- mid-epoch ``descent:kill`` → ``--resume auto`` reproduces the
  uninterrupted streamed fit EXACTLY (chunk cursor + tile digests);
- device residency stays inside the chunk window
  (``residuals.device_bytes`` = streamer in-flight peak ≤ (prefetch+1) ×
  chunk bytes) and the prefetch telemetry records real overlap;
- the driver's ``--stream-chunks`` / ``--max-resident-mb`` auto-enable;
- the first-hit foreign-vocabulary warm-start join prefetches on the io
  pool (satellite).
"""

from __future__ import annotations

import os

import numpy as np
import pytest

from photon_tpu.core.objective import RegularizationContext
from photon_tpu.core.optimizers import OptimizerConfig
from photon_tpu.core.problem import ProblemConfig
from photon_tpu.data.synthetic import make_game_dataset
from photon_tpu.game.coordinate import (
    FixedEffectCoordinateConfig,
    RandomEffectCoordinateConfig,
)
from photon_tpu.game.data import split_game_dataset
from photon_tpu.game.estimator import (
    GameEstimator,
    GameOptimizationConfiguration,
)
from photon_tpu.game.tiles import (
    PREFETCH_DEPTH,
    ChunkPlan,
    ChunkStreamer,
    TiledResidualTable,
    chunk_rows_for_budget,
    per_row_bytes,
    resident_bytes_estimate,
)
from photon_tpu.telemetry import TelemetrySession

CHUNK = 37  # deliberately not a divisor of the row count: partial last chunk


def _problem(lam, max_iters=80):
    # Tight tolerances: parity tests compare two solver implementations
    # (jitted vs streamed-host-loop L-BFGS) at their common optimum — the
    # tighter both converge, the tighter they agree.
    return ProblemConfig(
        regularization=RegularizationContext("l2", lam),
        optimizer_config=OptimizerConfig(
            max_iterations=max_iters, tolerance=1e-11,
            gradient_tolerance=1e-8,
        ),
    )


def _config(iters=2):
    return GameOptimizationConfiguration(
        coordinates={
            "fixed": FixedEffectCoordinateConfig("global", _problem(1.0)),
            "re0": RandomEffectCoordinateConfig("re0", "re0", _problem(1.0)),
        },
        descent_iterations=iters,
        name="ooc",
    )


@pytest.fixture(scope="module")
def game_data():
    data, _ = make_game_dataset(100, 5, 6, 3, seed=0, n_random_coords=1)
    return split_game_dataset(data, 0.25, seed=1)


@pytest.fixture(scope="module")
def fits(game_data):
    """One linear-task fit per mode (device / host / streamed), shared by
    the parity tests."""
    train, val = game_data
    out = {}
    for mode, kwargs in (
        ("device", {"residual_mode": "device"}),
        ("host", {"residual_mode": "host"}),
        ("stream", {"stream_chunks": CHUNK}),
    ):
        out[mode] = GameEstimator(
            "linear_regression", train, validation_data=val, **kwargs
        ).fit([_config()])[0]
    return out


# -- chunk plan + tiled-table unit contracts ---------------------------------

def test_chunk_plan_partial_and_degenerate():
    plan = ChunkPlan(100, 37)
    assert plan.num_chunks == 3
    assert [plan.bounds(k) for k in range(3)] == [(0, 37), (37, 74), (74, 100)]
    assert plan.rows(2) == 26  # partial last chunk
    exact = ChunkPlan(100, 25)
    assert exact.num_chunks == 4 and exact.rows(3) == 25
    one = ChunkPlan(100, 1000)  # single-chunk degenerate
    assert one.num_chunks == 1 and one.bounds(0) == (0, 100)
    with pytest.raises(IndexError):
        plan.bounds(3)
    with pytest.raises(ValueError):
        ChunkPlan(10, 0)


def test_budget_helpers(game_data):
    train, _ = game_data
    rb = per_row_bytes(train)
    n = train.num_examples
    assert rb > 0
    # Feature blocks ×2 (training + scoring cache) + the two [C, n] score
    # tables at the given coordinate count.
    assert resident_bytes_estimate(train) == 2 * rb * n + 2 * 2 * n * 4
    assert resident_bytes_estimate(train, n_coordinates=3) == (
        2 * rb * n + 2 * 3 * n * 4
    )
    rows = chunk_rows_for_budget(train, 0.01)
    # The in-flight window — (prefetch + 1) chunks — fits the budget.
    assert (PREFETCH_DEPTH + 1) * rows * rb <= 0.01 * (1 << 20) or rows == 1
    assert chunk_rows_for_budget(train, 1e9) == train.num_examples
    with pytest.raises(ValueError):
        chunk_rows_for_budget(train, 0)


def test_tiled_partials_match_unchunked_totals():
    """The per-chunk Neumaier partials concatenate to the SAME offsets and
    composite a single-chunk (resident-equivalent) table produces — the
    chunk partition is numerically invisible."""
    rng = np.random.default_rng(0)
    n = 101
    base = rng.standard_normal(n).astype(np.float32)
    scores = {
        "a": rng.standard_normal(n).astype(np.float32) * 100,
        "b": rng.standard_normal(n).astype(np.float32),
        "c": rng.standard_normal(n).astype(np.float32) * 0.01,
    }
    tiled = TiledResidualTable(base, ["a", "b", "c"], ChunkPlan(n, 17))
    whole = TiledResidualTable(base, ["a", "b", "c"], ChunkPlan(n, n))
    for name, s in scores.items():
        tiled.update(name, s)
        whole.update(name, s)
    for name in scores:
        np.testing.assert_array_equal(
            tiled.offsets_full(name), whole.offsets_full(name)
        )
        np.testing.assert_array_equal(
            np.concatenate([
                tiled.offsets_chunk(name, k)
                for k in range(tiled.num_chunks)
            ]),
            whole.offsets_full(name),
        )
    np.testing.assert_array_equal(
        tiled.composite_full(), whole.composite_full()
    )
    # The compensated total carries ~f64 precision for the f32 rows.
    want = base.astype(np.float64) + sum(
        s.astype(np.float64) for s in scores.values()
    )
    np.testing.assert_allclose(
        tiled.composite_full(), want, rtol=1e-6, atol=1e-5
    )


def test_tiled_table_guard_and_snapshot_roundtrip():
    base = np.zeros(10, np.float32)
    table = TiledResidualTable(base, ["a", "b"], ChunkPlan(10, 4))
    good = np.arange(10, dtype=np.float32)
    table.update("a", good)
    bad = good.copy()
    bad[3] = np.nan
    table.update("b", bad)
    assert table.poll_quarantined() == ["b"]
    # Rejected row kept its previous (zero) state.
    np.testing.assert_array_equal(table.scores_for("b"), np.zeros(10))
    snap = table.snapshot_rows()
    restored = TiledResidualTable(base, ["a", "b"], ChunkPlan(10, 4))
    restored.load_rows(snap)
    np.testing.assert_array_equal(restored.scores_for("a"), good)
    assert restored.tile_digests() == table.tile_digests()
    # A changed tile changes its chunk's digest (and only its chunk's).
    table.update("a", good + 1)
    assert table.tile_digests() != restored.tile_digests()


def test_chunk_streamer_orders_and_measures():
    session = TelemetrySession("t-streamer")
    streamer = ChunkStreamer(session, prefetch=2)
    import time as _time

    def load(k):
        _time.sleep(0.002)
        return np.full(8, k, np.float32)

    out = list(streamer.stream(load, 7))
    assert [int(a[0]) for a in out] == list(range(7))
    snap = session.registry.snapshot()
    counters = {m["name"]: m["value"] for m in snap["counters"]}
    assert counters["stream.chunks"] == 7
    assert counters["stream.stall_s"] >= 0
    # With 2 workers prefetching 2ms loads, SOME load time hides behind
    # the consumer.
    assert counters["stream.prefetch_overlap_s"] > 0
    assert streamer.peak_in_flight_bytes >= 32


# -- streamed-vs-resident fit parity -----------------------------------------

def test_streamed_fit_matches_resident_both_modes(fits, game_data):
    """The ISSUE 10 acceptance bar: streamed GAME ≤ 1e-4 from the resident
    fit, against BOTH residual modes — on validation metrics and RMS score
    parity.  Worst-case single-row |Δscore| sits at the floor set by
    comparing two L-BFGS implementations (jitted whole-batch vs streamed
    host-loop) stopping on the f32 value plateau (~2e-4 here; see ROADMAP
    'Out-of-core GAME' edge (d)) and is pinned at 5e-4 so a real
    regression — wrong offsets, corrupted tiles — still fails loudly."""
    _, val = game_data
    stream = fits["stream"].model.score(val)
    for mode in ("device", "host"):
        resident = fits[mode].model.score(val)
        diff = resident - stream
        assert float(np.sqrt(np.mean(diff * diff))) <= 1e-4, mode
        assert np.abs(diff).max() <= 5e-4, mode
        for name, value in fits[mode].metrics.items():
            assert abs(value - fits["stream"].metrics[name]) <= 1e-4, (
                mode, name,
            )


def test_streamed_logistic_fit_tracks_resident(game_data):
    """Logistic parity sits at the chunked-accumulation solver floor
    (~2–5e-4 on this fixture — see ROADMAP 'Out-of-core GAME' edge (d));
    pin it under a documented looser bound so a real regression (wrong
    offsets, broken tiles) still fails loudly."""
    train, val = game_data
    config = _config()
    resident = GameEstimator(
        "logistic_regression", train, validation_data=val,
        residual_mode="device",
    ).fit([config])[0]
    streamed = GameEstimator(
        "logistic_regression", train, validation_data=val,
        stream_chunks=CHUNK,
    ).fit([config])[0]
    diff = np.abs(
        resident.model.score(val) - streamed.model.score(val)
    ).max()
    assert diff <= 2e-3, diff


def test_single_chunk_and_divisible_plans_match_partial_chunk_fit(game_data):
    """Chunk-boundary edges: the single-chunk degenerate plan and an
    exactly-divisible plan produce the same streamed fit as the
    partial-last-chunk plan up to the chunk-accumulation floor (for the
    linear task the per-chunk sums re-associate only across chunk
    boundaries)."""
    train, val = game_data
    config = _config()

    def fit(chunk_rows):
        return GameEstimator(
            "linear_regression", train, validation_data=val,
            stream_chunks=chunk_rows,
        ).fit([config])[0].model.score(val)

    partial = fit(CHUNK)                      # 37 ∤ n: partial last chunk
    single = fit(train.num_examples + 10)     # one chunk == resident shape
    divisible = fit(25)
    assert np.abs(partial - single).max() <= 1e-4
    assert np.abs(partial - divisible).max() <= 1e-4


# -- mid-epoch kill -> resume ------------------------------------------------

def test_mid_epoch_kill_then_resume_exact(game_data, tmp_path):
    from photon_tpu.fault.injection import (
        FaultPlan,
        InjectedKillError,
        set_plan,
    )

    train, val = game_data
    config = _config(iters=2)

    def estimator():
        return GameEstimator(
            "linear_regression", train, validation_data=val,
            stream_chunks=CHUNK,
        )

    baseline = estimator().fit([config])[0]
    ck = str(tmp_path / "ck")
    # Kill MID-EPOCH: before coordinate re0 of iteration 1 — the fixed
    # effect of iteration 1 has already trained and checkpointed.
    set_plan(FaultPlan.parse("descent:kill:iter=1:coord=re0"))
    try:
        with pytest.raises(InjectedKillError):
            estimator().fit([config], checkpoint_dir=ck, resume="auto")
    finally:
        set_plan(None)
    # The published chain holds a MID-EPOCH snapshot: cursor > 0, tile
    # digests stamped.
    from photon_tpu.fault.checkpoint import DescentCheckpointer

    ckpt = DescentCheckpointer(os.path.join(ck, "cfg-000"))
    state = ckpt.load("latest")
    assert state.stream is not None
    assert state.stream["cursor"] == 1
    assert state.stream["chunk_rows"] == CHUNK
    assert len(state.stream["tile_digests"]) == ChunkPlan(
        train.num_examples, CHUNK
    ).num_chunks
    assert not state.completed

    resumed = estimator().fit([config], checkpoint_dir=ck, resume="auto")[0]
    np.testing.assert_array_equal(
        baseline.model.score(val), resumed.model.score(val)
    )
    assert baseline.metrics == resumed.metrics
    np.testing.assert_array_equal(
        baseline.model.score(train), resumed.model.score(train)
    )


def test_stream_checkpoint_refuses_other_chunk_size(game_data, tmp_path):
    """chunk_rows is part of the streamed fingerprint: a checkpoint written
    under one chunk size cannot silently resume under another (the
    accumulation order would change)."""
    from photon_tpu.fault.checkpoint import CheckpointError
    from photon_tpu.fault.injection import (
        FaultPlan,
        InjectedKillError,
        set_plan,
    )

    train, val = game_data
    config = _config(iters=2)
    ck = str(tmp_path / "ck")
    set_plan(FaultPlan.parse("descent:kill:iter=1"))
    try:
        with pytest.raises(InjectedKillError):
            GameEstimator(
                "linear_regression", train, validation_data=val,
                stream_chunks=CHUNK,
            ).fit([config], checkpoint_dir=ck, resume="auto")
    finally:
        set_plan(None)
    with pytest.raises(CheckpointError, match="fingerprint"):
        GameEstimator(
            "linear_regression", train, validation_data=val,
            stream_chunks=CHUNK + 5,
        ).fit([config], checkpoint_dir=ck, resume="auto")


# -- device-residency bound + telemetry --------------------------------------

def test_streamed_device_bytes_bounded_by_chunk_window(game_data):
    train, val = game_data
    session = TelemetrySession("t-ooc")
    estimator = GameEstimator(
        "linear_regression", train, validation_data=val,
        stream_chunks=CHUNK, telemetry=session,
    )
    estimator.fit([_config()])
    snap = session.registry.snapshot()
    gauges = {
        m["name"]: m["value"] for m in snap["gauges"] if not m["labels"]
    }
    counters = {
        m["name"]: m["value"] for m in snap["counters"] if not m["labels"]
    }
    assert counters["stream.chunks"] > 0
    assert "stream.stall_s" in counters
    assert "stream.prefetch_overlap_s" in counters
    # The acceptance bound: peak in-flight device residency stays inside
    # the (prefetch + 1)-chunk window of the budget.  Entity sub-blocks
    # are sized by the same budget, so the whole streamed fit obeys it.
    bound = (PREFETCH_DEPTH + 1) * CHUNK * per_row_bytes(train)
    assert 0 < gauges["residuals.device_bytes"] <= bound
    assert estimator._streamer.peak_in_flight_bytes == (
        gauges["residuals.device_bytes"]
    )


# -- estimator / coordinate gates --------------------------------------------

def test_stream_mode_gates(game_data):
    train, val = game_data
    with pytest.raises(ValueError, match="stream_chunks"):
        GameEstimator("linear_regression", train, stream_chunks=-1)
    with pytest.raises(ValueError, match="stream_chunks"):
        GameEstimator("linear_regression", train, stream_chunks=0)
    # An explicitly requested resident engine must not be silently
    # replaced by the tiled tables.
    with pytest.raises(ValueError, match="residual"):
        GameEstimator(
            "linear_regression", train, residual_mode="host",
            stream_chunks=CHUNK,
        )
    # Unsupported resident-only features fail loudly at build time.
    cases = [
        ({"fixed": FixedEffectCoordinateConfig(
            "global", _problem(0.1), downsampling_rate=0.5)},
         "downsampling"),
        ({"fixed": FixedEffectCoordinateConfig(
            "global", ProblemConfig(
                optimizer="tron",
                regularization=RegularizationContext("l2", 0.1)))},
         "lbfgs"),
        ({"re0": RandomEffectCoordinateConfig(
            "re0", "re0", _problem(1.0), projection="random",
            projected_dim=2)},
         "projection"),
    ]
    for coords, match in cases:
        est = GameEstimator(
            "linear_regression", train, validation_data=val,
            stream_chunks=CHUNK,
        )
        with pytest.raises(ValueError, match=match):
            est.fit([GameOptimizationConfiguration(
                coordinates=coords, descent_iterations=1, name="bad"
            )])


# -- driver integration ------------------------------------------------------

def test_train_game_stream_chunks_driver(tmp_path):
    from photon_tpu.drivers import train_game

    out = tmp_path / "out"
    summary = train_game.run(train_game.build_parser().parse_args([
        "--input", "synthetic-game:60:4:6:3",
        "--task", "linear_regression",
        "--coordinate", "fixed:type=fixed,shard=global,max_iters=25",
        "--coordinate", "re0:type=random,shard=re0,entity=re0,max_iters=25",
        "--descent-iterations", "1",
        "--validation-split", "0.25",
        "--stream-chunks", "53",
        "--output-dir", str(out),
    ]))
    assert summary["best_metrics"]
    assert (out / "best_model").is_dir()


def test_train_game_max_resident_mb_auto_enables(tmp_path):
    """A budget the dataset exceeds auto-enables streaming with a fitted
    chunk size; a generous budget keeps the resident path."""
    import json

    from photon_tpu.drivers import train_game

    def run(budget_mb, out):
        return train_game.run(train_game.build_parser().parse_args([
            "--input", "synthetic-game:60:4:6:3",
            "--task", "linear_regression",
            "--coordinate", "fixed:type=fixed,shard=global,max_iters=25",
            "--coordinate",
            "re0:type=random,shard=re0,entity=re0,max_iters=25",
            "--descent-iterations", "1",
            "--validation-split", "0.25",
            "--max-resident-mb", str(budget_mb),
            "--output-dir", str(out),
        ]))

    run(0.01, tmp_path / "small")  # far under the resident estimate
    with open(
        tmp_path / "small" / "telemetry" / "run_report.json"
    ) as f:
        report = json.load(f)
    gauges = {m["name"]: m["value"] for m in report["metrics"]["gauges"]}
    assert gauges["stream.chunk_rows"] >= 1
    counters = {m["name"] for m in report["metrics"]["counters"]}
    assert "stream.chunks" in counters

    run(10_000, tmp_path / "big")  # generous budget: resident path
    with open(tmp_path / "big" / "telemetry" / "run_report.json") as f:
        report = json.load(f)
    gauges = {m["name"]: m["value"] for m in report["metrics"]["gauges"]}
    assert "stream.chunk_rows" not in gauges


# -- warm-start join prefetch (satellite) ------------------------------------

def test_warm_join_prefetch_overlaps_and_matches(game_data):
    from photon_tpu.game.coordinate import (
        RandomEffectCoordinate,
        _align_foreign_table,
        prefetch_warm_joins,
    )
    from photon_tpu.game.model import GameModel, RandomEffectModel

    train, _ = game_data
    coord = RandomEffectCoordinate(
        train, RandomEffectCoordinateConfig("re0", "re0", _problem(1.0)),
        "linear_regression",
    )
    coord.telemetry = TelemetrySession("t-warmjoin")
    # A FOREIGN vocabulary: the run's keys plus one unseen entity, as a
    # fresh array object (identity check must miss).
    foreign_keys = np.unique(np.concatenate(
        [coord.dataset.keys, np.asarray(["zzz-unseen"])]
    ))
    rng = np.random.default_rng(0)
    foreign = RandomEffectModel(
        table=rng.standard_normal(
            (len(foreign_keys), coord.dim)
        ).astype(np.float32),
        keys=foreign_keys, entity_column="re0", shard_name="re0",
        task_type="linear_regression",
    )
    # Un-prefetched reference result first, on a twin coordinate.
    twin = RandomEffectCoordinate(
        train, RandomEffectCoordinateConfig("re0", "re0", _problem(1.0)),
        "linear_regression",
    )
    want = _align_foreign_table(twin, foreign)

    scheduled = prefetch_warm_joins(
        {"re0": coord},
        GameModel({"re0": foreign}, "linear_regression"),
        telemetry=coord.telemetry,
    )
    assert scheduled == 1
    from concurrent.futures import Future

    cached = coord.device_data._warm_join_cache[id(foreign.keys)]
    assert isinstance(cached[1], Future)
    got = _align_foreign_table(coord, foreign)
    np.testing.assert_array_equal(got, want)
    # The future resolved into the cache; a second align is a pure hit.
    cached = coord.device_data._warm_join_cache[id(foreign.keys)]
    assert isinstance(cached[1], np.ndarray)
    # Same-vocabulary models schedule nothing.
    own = RandomEffectModel(
        table=np.zeros((coord.dataset.num_entities, coord.dim), np.float32),
        keys=coord.dataset.keys, entity_column="re0", shard_name="re0",
        task_type="linear_regression",
    )
    assert prefetch_warm_joins(
        {"re0": coord}, GameModel({"re0": own}, "linear_regression")
    ) == 0


def test_mid_epoch_checkpoint_carries_solve_quarantine(game_data, tmp_path):
    """A checkpointed streamed run resolves each coordinate's solve stats
    BEFORE its mid-epoch snapshot, so solve-stage quarantines survive a
    kill+resume that skips past the coordinate (code-review finding: the
    deferred-drain count must not be lost to the cursor)."""
    from photon_tpu.fault.checkpoint import DescentCheckpointer
    from photon_tpu.fault.injection import FaultPlan, set_plan

    train, val = game_data
    config = _config(iters=1)
    ck = str(tmp_path / "ck")
    set_plan(FaultPlan.parse("solve:nan:coord=re0"))
    try:
        GameEstimator(
            "linear_regression", train, validation_data=val,
            stream_chunks=CHUNK,
        ).fit([config], checkpoint_dir=ck, resume="auto")
    finally:
        set_plan(None)
    state = DescentCheckpointer(os.path.join(ck, "cfg-000")).load("latest")
    assert state.quarantined >= 1
