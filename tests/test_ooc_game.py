"""Out-of-core GAME training (ISSUE 10): tiled score tables, the
double-buffered chunk streamer, the streamed epoch-style descent.

Contracts pinned here:

- per-chunk Neumaier partials reduce to the resident engine's global
  total (chunking never changes an offset or composite value);
- streamed-vs-resident fit parity ≤ 1e-4 against BOTH residual modes
  (linear task; the logistic fixture sits at the chunked-accumulation
  solver floor and gets its own documented bound);
- chunk-boundary edge cases: a partial last chunk, an exactly-divisible
  plan, and the single-chunk degenerate plan all converge to the same fit;
- mid-epoch ``descent:kill`` → ``--resume auto`` reproduces the
  uninterrupted streamed fit EXACTLY (chunk cursor + tile digests);
- device residency stays inside the chunk window
  (``residuals.device_bytes`` = streamer in-flight peak ≤ (prefetch+1) ×
  chunk bytes) and the prefetch telemetry records real overlap;
- the driver's ``--stream-chunks`` / ``--max-resident-mb`` auto-enable;
- the first-hit foreign-vocabulary warm-start join prefetches on the io
  pool (satellite).
"""

from __future__ import annotations

import os

import numpy as np
import pytest

from photon_tpu.core.objective import RegularizationContext
from photon_tpu.core.optimizers import OptimizerConfig
from photon_tpu.core.problem import ProblemConfig
from photon_tpu.data.synthetic import make_game_dataset
from photon_tpu.game.coordinate import (
    FixedEffectCoordinateConfig,
    RandomEffectCoordinateConfig,
)
from photon_tpu.game.data import split_game_dataset
from photon_tpu.game.estimator import (
    GameEstimator,
    GameOptimizationConfiguration,
)
from photon_tpu.game.tiles import (
    PREFETCH_DEPTH,
    ChunkPlan,
    ChunkStreamer,
    TiledResidualTable,
    chunk_rows_for_budget,
    per_row_bytes,
    resident_bytes_estimate,
)
from photon_tpu.telemetry import TelemetrySession

CHUNK = 37  # deliberately not a divisor of the row count: partial last chunk


def _problem(lam, max_iters=80):
    # Tight tolerances: parity tests compare two solver implementations
    # (jitted vs streamed-host-loop L-BFGS) at their common optimum — the
    # tighter both converge, the tighter they agree.
    return ProblemConfig(
        regularization=RegularizationContext("l2", lam),
        optimizer_config=OptimizerConfig(
            max_iterations=max_iters, tolerance=1e-11,
            gradient_tolerance=1e-8,
        ),
    )


def _config(iters=2):
    return GameOptimizationConfiguration(
        coordinates={
            "fixed": FixedEffectCoordinateConfig("global", _problem(1.0)),
            "re0": RandomEffectCoordinateConfig("re0", "re0", _problem(1.0)),
        },
        descent_iterations=iters,
        name="ooc",
    )


@pytest.fixture(scope="module")
def game_data():
    data, _ = make_game_dataset(100, 5, 6, 3, seed=0, n_random_coords=1)
    return split_game_dataset(data, 0.25, seed=1)


@pytest.fixture(scope="module")
def fits(game_data):
    """One linear-task fit per mode (device / host / streamed), shared by
    the parity tests."""
    train, val = game_data
    out = {}
    for mode, kwargs in (
        ("device", {"residual_mode": "device"}),
        ("host", {"residual_mode": "host"}),
        ("stream", {"stream_chunks": CHUNK}),
    ):
        out[mode] = GameEstimator(
            "linear_regression", train, validation_data=val, **kwargs
        ).fit([_config()])[0]
    return out


# -- chunk plan + tiled-table unit contracts ---------------------------------

def test_chunk_plan_partial_and_degenerate():
    plan = ChunkPlan(100, 37)
    assert plan.num_chunks == 3
    assert [plan.bounds(k) for k in range(3)] == [(0, 37), (37, 74), (74, 100)]
    assert plan.rows(2) == 26  # partial last chunk
    exact = ChunkPlan(100, 25)
    assert exact.num_chunks == 4 and exact.rows(3) == 25
    one = ChunkPlan(100, 1000)  # single-chunk degenerate
    assert one.num_chunks == 1 and one.bounds(0) == (0, 100)
    with pytest.raises(IndexError):
        plan.bounds(3)
    with pytest.raises(ValueError):
        ChunkPlan(10, 0)


def test_budget_helpers(game_data):
    train, _ = game_data
    rb = per_row_bytes(train)
    n = train.num_examples
    assert rb > 0
    # Feature blocks ×2 (training + scoring cache) + the two [C, n] score
    # tables at the given coordinate count.
    assert resident_bytes_estimate(train) == 2 * rb * n + 2 * 2 * n * 4
    assert resident_bytes_estimate(train, n_coordinates=3) == (
        2 * rb * n + 2 * 3 * n * 4
    )
    rows = chunk_rows_for_budget(train, 0.01)
    # The in-flight window — (prefetch + 1) chunks — fits the budget.
    assert (PREFETCH_DEPTH + 1) * rows * rb <= 0.01 * (1 << 20) or rows == 1
    assert chunk_rows_for_budget(train, 1e9) == train.num_examples
    with pytest.raises(ValueError):
        chunk_rows_for_budget(train, 0)


def test_tiled_partials_match_unchunked_totals():
    """The per-chunk Neumaier partials concatenate to the SAME offsets and
    composite a single-chunk (resident-equivalent) table produces — the
    chunk partition is numerically invisible."""
    rng = np.random.default_rng(0)
    n = 101
    base = rng.standard_normal(n).astype(np.float32)
    scores = {
        "a": rng.standard_normal(n).astype(np.float32) * 100,
        "b": rng.standard_normal(n).astype(np.float32),
        "c": rng.standard_normal(n).astype(np.float32) * 0.01,
    }
    tiled = TiledResidualTable(base, ["a", "b", "c"], ChunkPlan(n, 17))
    whole = TiledResidualTable(base, ["a", "b", "c"], ChunkPlan(n, n))
    for name, s in scores.items():
        tiled.update(name, s)
        whole.update(name, s)
    for name in scores:
        np.testing.assert_array_equal(
            tiled.offsets_full(name), whole.offsets_full(name)
        )
        np.testing.assert_array_equal(
            np.concatenate([
                tiled.offsets_chunk(name, k)
                for k in range(tiled.num_chunks)
            ]),
            whole.offsets_full(name),
        )
    np.testing.assert_array_equal(
        tiled.composite_full(), whole.composite_full()
    )
    # The compensated total carries ~f64 precision for the f32 rows.
    want = base.astype(np.float64) + sum(
        s.astype(np.float64) for s in scores.values()
    )
    np.testing.assert_allclose(
        tiled.composite_full(), want, rtol=1e-6, atol=1e-5
    )


def test_tiled_table_guard_and_snapshot_roundtrip():
    base = np.zeros(10, np.float32)
    table = TiledResidualTable(base, ["a", "b"], ChunkPlan(10, 4))
    good = np.arange(10, dtype=np.float32)
    table.update("a", good)
    bad = good.copy()
    bad[3] = np.nan
    table.update("b", bad)
    assert table.poll_quarantined() == ["b"]
    # Rejected row kept its previous (zero) state.
    np.testing.assert_array_equal(table.scores_for("b"), np.zeros(10))
    snap = table.snapshot_rows()
    restored = TiledResidualTable(base, ["a", "b"], ChunkPlan(10, 4))
    restored.load_rows(snap)
    np.testing.assert_array_equal(restored.scores_for("a"), good)
    assert restored.tile_digests() == table.tile_digests()
    # A changed tile changes its chunk's digest (and only its chunk's).
    table.update("a", good + 1)
    assert table.tile_digests() != restored.tile_digests()


def test_chunk_streamer_orders_and_measures():
    session = TelemetrySession("t-streamer")
    streamer = ChunkStreamer(session, prefetch=2)
    import time as _time

    def load(k):
        _time.sleep(0.002)
        return np.full(8, k, np.float32)

    out = list(streamer.stream(load, 7))
    assert [int(a[0]) for a in out] == list(range(7))
    snap = session.registry.snapshot()
    counters = {m["name"]: m["value"] for m in snap["counters"]}
    assert counters["stream.chunks"] == 7
    assert counters["stream.stall_s"] >= 0
    # With 2 workers prefetching 2ms loads, SOME load time hides behind
    # the consumer.
    assert counters["stream.prefetch_overlap_s"] > 0
    assert streamer.peak_in_flight_bytes >= 32


# -- streamed-vs-resident fit parity -----------------------------------------

def test_streamed_fit_matches_resident_both_modes(fits, game_data):
    """The ISSUE 10 acceptance bar: streamed GAME ≤ 1e-4 from the resident
    fit, against BOTH residual modes — on validation metrics and RMS score
    parity.  Worst-case single-row |Δscore| sits at the floor set by
    comparing two L-BFGS implementations (jitted whole-batch vs streamed
    host-loop) stopping on the f32 value plateau (~2e-4 here; see ROADMAP
    'Out-of-core GAME' edge (d)) and is pinned at 5e-4 so a real
    regression — wrong offsets, corrupted tiles — still fails loudly."""
    _, val = game_data
    stream = fits["stream"].model.score(val)
    for mode in ("device", "host"):
        resident = fits[mode].model.score(val)
        diff = resident - stream
        assert float(np.sqrt(np.mean(diff * diff))) <= 1e-4, mode
        assert np.abs(diff).max() <= 5e-4, mode
        for name, value in fits[mode].metrics.items():
            assert abs(value - fits["stream"].metrics[name]) <= 1e-4, (
                mode, name,
            )


def test_streamed_logistic_fit_tracks_resident(game_data):
    """Logistic parity now sits at the TWO-SOLVER f32 plateau floor
    (~4–6e-4 on this fixture): the ISSUE 11 Neumaier-compensated f64
    cross-chunk value+grad accumulator removed the chunk-count drift the
    ROADMAP flagged (the streamed fit is now identical across chunk
    sizes), so the pin tightens 2e-3 → 1e-3; the remainder is the two
    L-BFGS implementations stopping on the f32 value plateau, not the
    chunked accumulation."""
    train, val = game_data
    config = _config()
    resident = GameEstimator(
        "logistic_regression", train, validation_data=val,
        residual_mode="device",
    ).fit([config])[0]
    streamed = GameEstimator(
        "logistic_regression", train, validation_data=val,
        stream_chunks=CHUNK,
    ).fit([config])[0]
    diff = np.abs(
        resident.model.score(val) - streamed.model.score(val)
    ).max()
    assert diff <= 1e-3, diff


def test_single_chunk_and_divisible_plans_match_partial_chunk_fit(game_data):
    """Chunk-boundary edges: the single-chunk degenerate plan and an
    exactly-divisible plan produce the same streamed fit as the
    partial-last-chunk plan up to the chunk-accumulation floor (for the
    linear task the per-chunk sums re-associate only across chunk
    boundaries)."""
    train, val = game_data
    config = _config()

    def fit(chunk_rows):
        return GameEstimator(
            "linear_regression", train, validation_data=val,
            stream_chunks=chunk_rows,
        ).fit([config])[0].model.score(val)

    partial = fit(CHUNK)                      # 37 ∤ n: partial last chunk
    single = fit(train.num_examples + 10)     # one chunk == resident shape
    divisible = fit(25)
    assert np.abs(partial - single).max() <= 1e-4
    assert np.abs(partial - divisible).max() <= 1e-4


# -- mid-epoch kill -> resume ------------------------------------------------

def test_mid_epoch_kill_then_resume_exact(game_data, tmp_path):
    from photon_tpu.fault.injection import (
        FaultPlan,
        InjectedKillError,
        set_plan,
    )

    train, val = game_data
    config = _config(iters=2)

    def estimator():
        return GameEstimator(
            "linear_regression", train, validation_data=val,
            stream_chunks=CHUNK,
        )

    baseline = estimator().fit([config])[0]
    ck = str(tmp_path / "ck")
    # Kill MID-EPOCH: before coordinate re0 of iteration 1 — the fixed
    # effect of iteration 1 has already trained and checkpointed.
    set_plan(FaultPlan.parse("descent:kill:iter=1:coord=re0"))
    try:
        with pytest.raises(InjectedKillError):
            estimator().fit([config], checkpoint_dir=ck, resume="auto")
    finally:
        set_plan(None)
    # The published chain holds a MID-EPOCH snapshot: cursor > 0, tile
    # digests stamped.
    from photon_tpu.fault.checkpoint import DescentCheckpointer

    ckpt = DescentCheckpointer(os.path.join(ck, "cfg-000"))
    state = ckpt.load("latest")
    assert state.stream is not None
    assert state.stream["cursor"] == 1
    assert state.stream["chunk_rows"] == CHUNK
    assert len(state.stream["tile_digests"]) == ChunkPlan(
        train.num_examples, CHUNK
    ).num_chunks
    assert not state.completed

    resumed = estimator().fit([config], checkpoint_dir=ck, resume="auto")[0]
    np.testing.assert_array_equal(
        baseline.model.score(val), resumed.model.score(val)
    )
    assert baseline.metrics == resumed.metrics
    np.testing.assert_array_equal(
        baseline.model.score(train), resumed.model.score(train)
    )


def test_stream_checkpoint_refuses_other_chunk_size(game_data, tmp_path):
    """chunk_rows is part of the streamed fingerprint: a checkpoint written
    under one chunk size cannot silently resume under another (the
    accumulation order would change)."""
    from photon_tpu.fault.checkpoint import CheckpointError
    from photon_tpu.fault.injection import (
        FaultPlan,
        InjectedKillError,
        set_plan,
    )

    train, val = game_data
    config = _config(iters=2)
    ck = str(tmp_path / "ck")
    set_plan(FaultPlan.parse("descent:kill:iter=1"))
    try:
        with pytest.raises(InjectedKillError):
            GameEstimator(
                "linear_regression", train, validation_data=val,
                stream_chunks=CHUNK,
            ).fit([config], checkpoint_dir=ck, resume="auto")
    finally:
        set_plan(None)
    with pytest.raises(CheckpointError, match="fingerprint"):
        GameEstimator(
            "linear_regression", train, validation_data=val,
            stream_chunks=CHUNK + 5,
        ).fit([config], checkpoint_dir=ck, resume="auto")


# -- device-residency bound + telemetry --------------------------------------

def test_streamed_device_bytes_bounded_by_chunk_window(game_data):
    train, val = game_data
    session = TelemetrySession("t-ooc")
    estimator = GameEstimator(
        "linear_regression", train, validation_data=val,
        stream_chunks=CHUNK, telemetry=session,
    )
    estimator.fit([_config()])
    snap = session.registry.snapshot()
    gauges = {
        m["name"]: m["value"] for m in snap["gauges"] if not m["labels"]
    }
    counters = {
        m["name"]: m["value"] for m in snap["counters"] if not m["labels"]
    }
    tiered = {
        (m["name"], m["labels"].get("tier")): m["value"]
        for m in snap["counters"] if "tier" in m["labels"]
    }
    assert counters["stream.chunks"] > 0
    assert ("stream.stall_s", "h2d") in tiered
    assert ("stream.prefetch_overlap_s", "h2d") in tiered
    # The acceptance bound: peak in-flight device residency stays inside
    # the (prefetch + 1)-chunk window of the budget.  Entity sub-blocks
    # are sized by the same budget, so the whole streamed fit obeys it.
    bound = (PREFETCH_DEPTH + 1) * CHUNK * per_row_bytes(train)
    assert 0 < gauges["residuals.device_bytes"] <= bound
    assert estimator._streamer.peak_in_flight_bytes == (
        gauges["residuals.device_bytes"]
    )


# -- estimator / coordinate gates --------------------------------------------

def test_stream_mode_gates(game_data):
    train, val = game_data
    with pytest.raises(ValueError, match="stream_chunks"):
        GameEstimator("linear_regression", train, stream_chunks=-1)
    with pytest.raises(ValueError, match="stream_chunks"):
        GameEstimator("linear_regression", train, stream_chunks=0)
    # An explicitly requested resident engine must not be silently
    # replaced by the tiled tables.
    with pytest.raises(ValueError, match="residual"):
        GameEstimator(
            "linear_regression", train, residual_mode="host",
            stream_chunks=CHUNK,
        )
    # Unsupported resident-only features fail loudly at build time.
    cases = [
        ({"fixed": FixedEffectCoordinateConfig(
            "global", _problem(0.1), downsampling_rate=0.5)},
         "downsampling"),
        ({"fixed": FixedEffectCoordinateConfig(
            "global", ProblemConfig(
                optimizer="tron",
                regularization=RegularizationContext("l2", 0.1)))},
         "lbfgs"),
        ({"re0": RandomEffectCoordinateConfig(
            "re0", "re0", _problem(1.0), projection="random",
            projected_dim=2)},
         "projection"),
    ]
    for coords, match in cases:
        est = GameEstimator(
            "linear_regression", train, validation_data=val,
            stream_chunks=CHUNK,
        )
        with pytest.raises(ValueError, match=match):
            est.fit([GameOptimizationConfiguration(
                coordinates=coords, descent_iterations=1, name="bad"
            )])


# -- driver integration ------------------------------------------------------

def test_train_game_stream_chunks_driver(tmp_path):
    from photon_tpu.drivers import train_game

    out = tmp_path / "out"
    summary = train_game.run(train_game.build_parser().parse_args([
        "--input", "synthetic-game:60:4:6:3",
        "--task", "linear_regression",
        "--coordinate", "fixed:type=fixed,shard=global,max_iters=25",
        "--coordinate", "re0:type=random,shard=re0,entity=re0,max_iters=25",
        "--descent-iterations", "1",
        "--validation-split", "0.25",
        "--stream-chunks", "53",
        "--output-dir", str(out),
    ]))
    assert summary["best_metrics"]
    assert (out / "best_model").is_dir()


def test_train_game_max_resident_mb_auto_enables(tmp_path):
    """A budget the dataset exceeds auto-enables streaming with a fitted
    chunk size; a generous budget keeps the resident path."""
    import json

    from photon_tpu.drivers import train_game

    def run(budget_mb, out):
        return train_game.run(train_game.build_parser().parse_args([
            "--input", "synthetic-game:60:4:6:3",
            "--task", "linear_regression",
            "--coordinate", "fixed:type=fixed,shard=global,max_iters=25",
            "--coordinate",
            "re0:type=random,shard=re0,entity=re0,max_iters=25",
            "--descent-iterations", "1",
            "--validation-split", "0.25",
            "--max-resident-mb", str(budget_mb),
            "--output-dir", str(out),
        ]))

    run(0.01, tmp_path / "small")  # far under the resident estimate
    with open(
        tmp_path / "small" / "telemetry" / "run_report.json"
    ) as f:
        report = json.load(f)
    gauges = {m["name"]: m["value"] for m in report["metrics"]["gauges"]}
    assert gauges["stream.chunk_rows"] >= 1
    counters = {m["name"] for m in report["metrics"]["counters"]}
    assert "stream.chunks" in counters

    run(10_000, tmp_path / "big")  # generous budget: resident path
    with open(tmp_path / "big" / "telemetry" / "run_report.json") as f:
        report = json.load(f)
    gauges = {m["name"]: m["value"] for m in report["metrics"]["gauges"]}
    assert "stream.chunk_rows" not in gauges


# -- warm-start join prefetch (satellite) ------------------------------------

def test_warm_join_prefetch_overlaps_and_matches(game_data):
    from photon_tpu.game.coordinate import (
        RandomEffectCoordinate,
        _align_foreign_table,
        prefetch_warm_joins,
    )
    from photon_tpu.game.model import GameModel, RandomEffectModel

    train, _ = game_data
    coord = RandomEffectCoordinate(
        train, RandomEffectCoordinateConfig("re0", "re0", _problem(1.0)),
        "linear_regression",
    )
    coord.telemetry = TelemetrySession("t-warmjoin")
    # A FOREIGN vocabulary: the run's keys plus one unseen entity, as a
    # fresh array object (identity check must miss).
    foreign_keys = np.unique(np.concatenate(
        [coord.dataset.keys, np.asarray(["zzz-unseen"])]
    ))
    rng = np.random.default_rng(0)
    foreign = RandomEffectModel(
        table=rng.standard_normal(
            (len(foreign_keys), coord.dim)
        ).astype(np.float32),
        keys=foreign_keys, entity_column="re0", shard_name="re0",
        task_type="linear_regression",
    )
    # Un-prefetched reference result first, on a twin coordinate.
    twin = RandomEffectCoordinate(
        train, RandomEffectCoordinateConfig("re0", "re0", _problem(1.0)),
        "linear_regression",
    )
    want = _align_foreign_table(twin, foreign)

    scheduled = prefetch_warm_joins(
        {"re0": coord},
        GameModel({"re0": foreign}, "linear_regression"),
        telemetry=coord.telemetry,
    )
    assert scheduled == 1
    from concurrent.futures import Future

    cached = coord.device_data._warm_join_cache[id(foreign.keys)]
    assert isinstance(cached[1], Future)
    got = _align_foreign_table(coord, foreign)
    np.testing.assert_array_equal(got, want)
    # The future resolved into the cache; a second align is a pure hit.
    cached = coord.device_data._warm_join_cache[id(foreign.keys)]
    assert isinstance(cached[1], np.ndarray)
    # Same-vocabulary models schedule nothing.
    own = RandomEffectModel(
        table=np.zeros((coord.dataset.num_entities, coord.dim), np.float32),
        keys=coord.dataset.keys, entity_column="re0", shard_name="re0",
        task_type="linear_regression",
    )
    assert prefetch_warm_joins(
        {"re0": coord}, GameModel({"re0": own}, "linear_regression")
    ) == 0


# -- disk-backed tile store (ISSUE 11) ---------------------------------------

def _spilled_estimator(train, val, spill_dir, **kwargs):
    return GameEstimator(
        "linear_regression", train, validation_data=val,
        stream_chunks=CHUNK, spill_dir=str(spill_dir), **kwargs,
    )


@pytest.fixture(scope="module")
def spilled_fit(game_data, tmp_path_factory):
    """One spilled fit under a host budget of ~1.5 feature chunks: big
    enough that no single entry exceeds the budget (the gauge bound is
    strict), small enough that streaming all chunks + tiles MUST evict."""
    train, val = game_data
    spill_dir = tmp_path_factory.mktemp("tile_store")
    session = TelemetrySession("t-spilled-fit")
    budget_bytes = int(1.5 * CHUNK * per_row_bytes(train))
    result = _spilled_estimator(
        train, val, spill_dir, max_host_mb=budget_bytes / (1 << 20),
        telemetry=session,
    ).fit([_config()])[0]
    return result, session, spill_dir, budget_bytes


def test_spilled_fit_matches_host_resident_streamed_bitwise(
    spilled_fit, fits, game_data
):
    """The ISSUE 11 acceptance bar: a spilled streamed fit is
    BIT-IDENTICAL to the host-resident streamed fit — the disk roundtrip
    and the cache/eviction churn change nothing."""
    train, val = game_data
    result, _, _, _ = spilled_fit
    host = fits["stream"]
    for name, host_model in host.model.coordinates.items():
        sp_model = result.model.coordinates[name]
        if hasattr(host_model, "table"):
            assert np.array_equal(
                np.asarray(host_model.table), np.asarray(sp_model.table)
            ), name
        else:
            assert np.array_equal(
                np.asarray(host_model.model.coefficients.means),
                np.asarray(sp_model.model.coefficients.means),
            ), name
    np.testing.assert_array_equal(
        host.model.score(val), result.model.score(val)
    )
    for name, value in host.metrics.items():
        assert abs(value - result.metrics[name]) <= 1e-6, name


def test_spilled_tiles_on_disk_match_recomputation(
    spilled_fit, game_data
):
    """The PUBLISHED tiles equal a bit-exact recomputation from the final
    models (write-through write-back worked; roundtrip lossless)."""
    from photon_tpu.game.tile_store import TileStore
    from photon_tpu.game.tiles import RESIDUAL_TILE_KIND as TILES
    from photon_tpu.game.tiles import score_model_chunks

    train, _ = game_data
    result, _, spill_dir, _ = spilled_fit
    plan = ChunkPlan(train.num_examples, CHUNK)
    store = TileStore(str(spill_dir))
    last = result.descent.last_model.coordinates
    names = list(last)
    oracle = ChunkStreamer()
    rows = {
        name: score_model_chunks(last[name], train, plan, oracle)
        for name in names
    }
    for k in range(plan.num_chunks):
        arrays, meta = store.read(TILES, k)
        lo, hi = plan.bounds(k)
        want = np.stack([rows[name][lo:hi] for name in names])
        assert np.array_equal(arrays["tile"], want), k
        assert len(meta["tile_digest"]) == 16


def test_spilled_eviction_respects_host_budget(spilled_fit):
    """The host budget is ~1.5 feature chunks while the full tile+feature
    set spans 3 chunks: eviction MUST fire, and the cache gauge must end
    inside the budget (every entry is smaller than the budget, so the
    oversized-entry allowance never applies)."""
    _, session, _, budget_bytes = spilled_fit
    snap = session.registry.snapshot()
    counters = {
        m["name"]: m["value"] for m in snap["counters"] if not m["labels"]
    }
    gauges = {
        m["name"]: m["value"] for m in snap["gauges"] if not m["labels"]
    }
    assert counters["tiles.cache_evictions"] > 0
    assert counters["tiles.cache_misses"] > 0
    assert 0 < gauges["tiles.host_cache_bytes"] <= budget_bytes
    assert gauges["tiles.disk_bytes"] > 0
    # Per-tier stalls measured on BOTH edges.
    tiered = {
        (m["name"], m["labels"].get("tier")): m["value"]
        for m in snap["counters"] if "tier" in m["labels"]
    }
    assert ("stream.stall_s", "disk") in tiered
    assert ("stream.stall_s", "h2d") in tiered


def test_spilled_mid_epoch_kill_then_resume_exact(game_data, tmp_path):
    """Mid-epoch kill→resume with SPILLED tiles: the checkpoint carries
    digests only (rows empty — on-disk tiles referenced, not re-saved)
    and the resumed fit is exact."""
    from photon_tpu.fault.checkpoint import DescentCheckpointer
    from photon_tpu.fault.injection import (
        FaultPlan,
        InjectedKillError,
        set_plan,
    )

    train, val = game_data
    config = _config(iters=2)
    spill_dir = tmp_path / "store"
    baseline = _spilled_estimator(train, val, spill_dir).fit([config])[0]
    ck = str(tmp_path / "ck")
    set_plan(FaultPlan.parse("descent:kill:iter=1:coord=re0"))
    try:
        with pytest.raises(InjectedKillError):
            _spilled_estimator(train, val, spill_dir).fit(
                [config], checkpoint_dir=ck, resume="auto"
            )
    finally:
        set_plan(None)
    state = DescentCheckpointer(os.path.join(ck, "cfg-000")).load("latest")
    assert state.stream["cursor"] == 1
    assert state.stream["spilled"] is True
    assert state.residual_rows == {}  # referenced, not re-saved
    assert len(state.stream["tile_digests"]) == ChunkPlan(
        train.num_examples, CHUNK
    ).num_chunks
    resumed = _spilled_estimator(train, val, spill_dir).fit(
        [config], checkpoint_dir=ck, resume="auto"
    )[0]
    np.testing.assert_array_equal(
        baseline.model.score(val), resumed.model.score(val)
    )
    np.testing.assert_array_equal(
        baseline.model.score(train), resumed.model.score(train)
    )
    assert baseline.metrics == resumed.metrics


def test_spilled_resume_with_corrupt_tile_refused(game_data, tmp_path):
    """A corrupted on-disk tile is refused via digest at read during
    resume — never silently adopted."""
    from photon_tpu.fault.injection import (
        FaultPlan,
        InjectedKillError,
        set_plan,
    )
    from photon_tpu.game.tile_store import CorruptTileError, TileStore
    from photon_tpu.game.tiles import RESIDUAL_TILE_KIND as TILES

    train, val = game_data
    config = _config(iters=2)
    spill_dir = tmp_path / "store"
    ck = str(tmp_path / "ck")
    set_plan(FaultPlan.parse("descent:kill:iter=1:coord=re0"))
    try:
        with pytest.raises(InjectedKillError):
            _spilled_estimator(train, val, spill_dir).fit(
                [config], checkpoint_dir=ck, resume="auto"
            )
    finally:
        set_plan(None)
    store = TileStore(str(spill_dir))
    path = store.path(TILES, 0)
    blob = bytearray(open(path, "rb").read())
    blob[-5] ^= 0xFF
    with open(path, "wb") as f:
        f.write(blob)
    with pytest.raises(CorruptTileError):
        _spilled_estimator(train, val, spill_dir).fit(
            [config], checkpoint_dir=ck, resume="auto"
        )


def test_spilled_resume_rebuilds_stale_tiles(game_data, tmp_path):
    """A STALE (valid but torn-sequence) on-disk tile set is rebuilt
    deterministically from the checkpointed models: resume stays exact
    even after the store lost a write-back."""
    from photon_tpu.fault.injection import (
        FaultPlan,
        InjectedKillError,
        set_plan,
    )
    from photon_tpu.game.tile_store import TileStore
    from photon_tpu.game.tiles import RESIDUAL_TILE_KIND as TILES

    train, val = game_data
    config = _config(iters=2)
    spill_dir = tmp_path / "store"
    baseline = _spilled_estimator(train, val, spill_dir).fit([config])[0]
    ck = str(tmp_path / "ck")
    set_plan(FaultPlan.parse("descent:kill:iter=1:coord=re0"))
    try:
        with pytest.raises(InjectedKillError):
            _spilled_estimator(train, val, spill_dir).fit(
                [config], checkpoint_dir=ck, resume="auto"
            )
    finally:
        set_plan(None)
    # Simulate a torn update sequence: drop one published tile (a VALID
    # store state that no longer matches the checkpoint digests).
    TileStore(str(spill_dir)).delete(TILES, 1)
    session = TelemetrySession("t-rebuild")
    resumed = _spilled_estimator(
        train, val, spill_dir, telemetry=session
    ).fit([config], checkpoint_dir=ck, resume="auto")[0]
    counters = {
        m["name"]: m["value"]
        for m in session.registry.snapshot()["counters"]
        if not m["labels"]
    }
    assert counters.get("tiles.rebuilt", 0) == 1
    np.testing.assert_array_equal(
        baseline.model.score(val), resumed.model.score(val)
    )
    assert baseline.metrics == resumed.metrics


def test_spilled_fit_with_injected_tile_read_faults(
    game_data, tmp_path, monkeypatch
):
    """Transient ``tile:read`` faults during a spilled fit are retried to
    a clean, bit-identical run (the retry/backoff triangle on the disk
    edge)."""
    from photon_tpu.fault.injection import FaultPlan, set_plan

    monkeypatch.setenv("PHOTON_IO_RETRY_BASE_S", "0")
    monkeypatch.setenv("PHOTON_IO_RETRIES", "8")
    train, val = game_data
    config = _config(iters=1)
    clean = _spilled_estimator(train, val, tmp_path / "clean").fit(
        [config]
    )[0]
    session = TelemetrySession("t-tilefaults")
    set_plan(FaultPlan.parse("tile:read:p=0.5", seed=7))
    try:
        faulted = _spilled_estimator(
            train, val, tmp_path / "faulted", telemetry=session
        ).fit([config])[0]
    finally:
        set_plan(None)
    np.testing.assert_array_equal(
        clean.model.score(val), faulted.model.score(val)
    )
    counters = {
        (m["name"], tuple(sorted(m["labels"].items()))): m["value"]
        for m in session.registry.snapshot()["counters"]
    }
    assert counters.get(("io.retries", (("site", "tile:read"),)), 0) > 0


def test_spilled_fit_with_compression_bit_identical(
    game_data, tmp_path, monkeypatch
):
    """`PHOTON_TILE_COMPRESS=1` (delta + byte-shuffle + zlib) trades CPU
    for disk bandwidth without touching a single bit of the result."""
    monkeypatch.setenv("PHOTON_TILE_COMPRESS", "1")
    train, val = game_data
    config = _config(iters=1)
    host = GameEstimator(
        "linear_regression", train, validation_data=val,
        stream_chunks=CHUNK,
    ).fit([config])[0]
    compressed = _spilled_estimator(train, val, tmp_path / "store").fit(
        [config]
    )[0]
    np.testing.assert_array_equal(
        host.model.score(val), compressed.model.score(val)
    )
    from photon_tpu.game.tile_store import TileStore

    assert TileStore(str(tmp_path / "store")).compress


def test_spill_estimator_gates(game_data):
    train, val = game_data
    with pytest.raises(ValueError, match="spill_dir"):
        GameEstimator("linear_regression", train, spill_dir="/tmp/x")
    with pytest.raises(ValueError, match="max_host_mb"):
        GameEstimator(
            "linear_regression", train, stream_chunks=CHUNK,
            spill_dir="/tmp/x", max_host_mb=0,
        )
    with pytest.raises(ValueError, match="spill_dir"):
        GameEstimator(
            "linear_regression", train, stream_chunks=CHUNK,
            max_host_mb=1.0,
        )


def test_train_game_max_host_mb_auto_enables_spilling(tmp_path):
    """ISSUE 11 satellite: the auto-enable gate folds the HOST estimate
    in — a dataset past ``--max-host-mb`` auto-enables streaming AND the
    disk-backed tile store instead of OOM-ing the host cache."""
    import json

    from photon_tpu.drivers import train_game

    out = tmp_path / "out"
    train_game.run(train_game.build_parser().parse_args([
        "--input", "synthetic-game:60:4:6:3",
        "--task", "linear_regression",
        "--coordinate", "fixed:type=fixed,shard=global,max_iters=25",
        "--coordinate", "re0:type=random,shard=re0,entity=re0,max_iters=25",
        "--descent-iterations", "1",
        "--validation-split", "0.25",
        "--max-host-mb", "0.001",
        "--output-dir", str(out),
    ]))
    assert (out / "tile_store").is_dir()
    with open(out / "telemetry" / "run_report.json") as f:
        report = json.load(f)
    gauges = {m["name"]: m["value"] for m in report["metrics"]["gauges"]}
    assert gauges["stream.spilled"] == 1
    assert gauges["stream.chunk_rows"] >= 1
    assert gauges["stream.host_estimate_bytes"] > 0.001 * (1 << 20)
    assert gauges["tiles.disk_bytes"] > 0
    # A generous host budget keeps the non-spilled path.
    out2 = tmp_path / "out2"
    train_game.run(train_game.build_parser().parse_args([
        "--input", "synthetic-game:60:4:6:3",
        "--task", "linear_regression",
        "--coordinate", "fixed:type=fixed,shard=global,max_iters=25",
        "--coordinate", "re0:type=random,shard=re0,entity=re0,max_iters=25",
        "--descent-iterations", "1",
        "--validation-split", "0.25",
        "--stream-chunks", "53",
        "--max-host-mb", "10000",
        "--output-dir", str(out2),
    ]))
    assert not (out2 / "tile_store").exists()
    with open(out2 / "telemetry" / "run_report.json") as f:
        report = json.load(f)
    gauges = {m["name"]: m["value"] for m in report["metrics"]["gauges"]}
    assert "stream.spilled" not in gauges


def test_train_game_spill_dir_requires_streaming(tmp_path):
    from photon_tpu.drivers import train_game

    with pytest.raises(ValueError, match="streamed mode"):
        train_game.run(train_game.build_parser().parse_args([
            "--input", "synthetic-game:60:4:6:3",
            "--task", "linear_regression",
            "--coordinate", "fixed:type=fixed,shard=global,max_iters=25",
            "--descent-iterations", "1",
            "--spill-dir", str(tmp_path / "store"),
            "--output-dir", str(tmp_path / "out"),
        ]))


def test_mid_epoch_checkpoint_carries_solve_quarantine(game_data, tmp_path):
    """A checkpointed streamed run resolves each coordinate's solve stats
    BEFORE its mid-epoch snapshot, so solve-stage quarantines survive a
    kill+resume that skips past the coordinate (code-review finding: the
    deferred-drain count must not be lost to the cursor)."""
    from photon_tpu.fault.checkpoint import DescentCheckpointer
    from photon_tpu.fault.injection import FaultPlan, set_plan

    train, val = game_data
    config = _config(iters=1)
    ck = str(tmp_path / "ck")
    set_plan(FaultPlan.parse("solve:nan:coord=re0"))
    try:
        GameEstimator(
            "linear_regression", train, validation_data=val,
            stream_chunks=CHUNK,
        ).fit([config], checkpoint_dir=ck, resume="auto")
    finally:
        set_plan(None)
    state = DescentCheckpointer(os.path.join(ck, "cfg-000")).load("latest")
    assert state.quarantined >= 1
