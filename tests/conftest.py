"""Test configuration: force an 8-device CPU platform.

Mirrors the reference's test strategy (SURVEY.md §4): the reference tests
"distributed" code paths with local-mode Spark in one JVM; we test sharded
code paths with 8 virtual CPU devices in one process
(``--xla_force_host_platform_device_count=8``).  Must run before jax import.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# The environment may pin jax_platforms to a TPU-tunnel platform ("axon")
# whose client init needs real hardware; tests run CPU-only.  The env var is
# overridden by site customization, so set the config directly post-import.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", False)
