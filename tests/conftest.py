"""Test configuration: force an 8-device CPU platform.

Mirrors the reference's test strategy (SURVEY.md §4): the reference tests
"distributed" code paths with local-mode Spark in one JVM; we test sharded
code paths with 8 virtual CPU devices in one process
(``--xla_force_host_platform_device_count=8``).  Must run before jax import.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# The environment may pin jax_platforms to a TPU-tunnel platform ("axon")
# whose client init needs real hardware; tests run CPU-only.  The env var is
# overridden by site customization, so set the config directly post-import.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", False)

# Persistent compilation cache: the suite is dominated by XLA compiles of
# optimizer while_loops and GAME programs that are identical run-to-run.
# The cache dir is repo-local (gitignored) so repeated suite runs in one
# workspace — including the driver's — hit warm.
#
# The cache is KEYED by jaxlib version + a digest of the photon_tpu
# sources: stale cached programs from an older repo revision once
# segfaulted runs when a donated-buffer program's aliasing metadata no
# longer matched the cache entry loaded for it.  A source or jaxlib change
# now lands in a FRESH cache subdirectory (stale siblings are pruned), so
# that class of corruption cannot recur; unchanged sources keep hitting
# the warm cache.  JAX_TEST_COMPILATION_CACHE overrides the location
# verbatim (no keying) for operators managing their own cache.


def _repo_state_digest() -> str:
    import hashlib

    root = os.path.abspath(
        os.path.join(os.path.dirname(__file__), os.pardir, "photon_tpu")
    )
    h = hashlib.sha256()
    h.update(jax.__version__.encode())
    try:
        import jaxlib

        h.update(jaxlib.__version__.encode())
    except Exception:
        pass
    for dirpath, dirnames, filenames in sorted(os.walk(root)):
        dirnames.sort()
        for name in sorted(filenames):
            if not name.endswith(".py"):
                continue
            path = os.path.join(dirpath, name)
            h.update(os.path.relpath(path, root).encode())
            with open(path, "rb") as f:
                h.update(f.read())
    return h.hexdigest()[:12]


_cache_override = os.environ.get("JAX_TEST_COMPILATION_CACHE")
if _cache_override:
    _cache_dir = os.path.abspath(_cache_override)
else:
    _cache_root = os.path.abspath(
        os.path.join(os.path.dirname(__file__), os.pardir, ".jax_test_cache")
    )
    _cache_key = _repo_state_digest()
    _cache_dir = os.path.join(_cache_root, _cache_key)
    # Prune stale entries (old keyed subdirs AND pre-keying flat cache
    # files) so the workspace cache never grows one dead copy per source
    # change — and a stale program can never be picked up again.
    if os.path.isdir(_cache_root):
        import shutil

        for entry in os.listdir(_cache_root):
            if entry != _cache_key:
                full = os.path.join(_cache_root, entry)
                try:
                    shutil.rmtree(full) if os.path.isdir(full) else os.remove(full)
                except OSError:
                    pass
jax.config.update("jax_compilation_cache_dir", _cache_dir)
# Threshold 0: the suite compiles hundreds of SMALL programs (0.05-0.2s
# each) across ~220 tests; caching them all is worth far more than the
# cache-dir inode count it costs.
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
# Also export as env vars so worker SUBPROCESSES spawned by tests (the
# multi-process suite) share the cache.
os.environ["JAX_COMPILATION_CACHE_DIR"] = _cache_dir
os.environ["JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"] = "0.0"
os.environ["JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES"] = "-1"

# Pin the feature-major gradient kernel: correctness tests must exercise the
# production fm path even on platforms where the runtime autotuner
# (ops/sparse_grad_select) would prefer the autodiff scatter; the selection
# logic itself is tested explicitly with env overrides.
os.environ.setdefault("PHOTON_SPARSE_GRAD", "fm")

# The vperm route disk cache must NOT serve tests: a stale cached route
# would mask builder regressions (tests would validate deserialization,
# not construction).  The cache itself is covered by a dedicated test
# with an explicit tmp-dir override.
os.environ.setdefault("PHOTON_ROUTE_CACHE", "0")

# Hermetic fixtures: an operator's ambient PHOTON_REAL_DATA_DIR would
# silently redirect the a1a/MovieLens anchor tests to real data, whose
# metrics fall outside the fixture-calibrated bands.  Tests that cover the
# hook set the variable themselves via monkeypatch.
os.environ.pop("PHOTON_REAL_DATA_DIR", None)

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: excluded from the tier-1 run (`-m 'not slow'`); full CLI "
        "subprocess drives and other minute-scale checks",
    )


@pytest.fixture(scope="session")
def native_router():
    """The native ``_photon_native.so``, building it once per session.

    ``build.get_lib`` caches both on disk (the compiled .so survives across
    sessions) and in process (a failed build costs one attempt), so this
    fixture is effectively free after the first use.  Tests whose routes
    exceed the pure-Python edge-colorer's size cap (ops/clos.py) depend on
    it; when no working C++ toolchain is present they skip with a reason
    instead of erroring out of ``route_permutation``.
    """
    from photon_tpu.native import build

    lib = build.get_lib()
    if lib is None:
        pytest.skip(
            "native _photon_native.so unavailable (no working C++ toolchain "
            "to build clos_edge_color; routes over the Python fallback cap "
            "cannot be colored)"
        )
    return lib


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_per_module():
    """Bound the CPU client's accumulated compiled-executable state.

    A single-shot full-suite run compiles hundreds of XLA programs into one
    process; past ~200 tests the CPU backend segfaults inside a fresh
    compile (observed twice, deterministically, at the same test — any
    subset of the suite passes).  Dropping the in-memory executable caches
    at module boundaries keeps the client small; re-runs of shared programs
    reload from the persistent disk cache configured above, so the time
    cost is deserialization, not recompilation.
    """
    yield
    jax.clear_caches()
