"""Host-IO thread pool (utils/io_pool): ordered delivery, bounded window,
exception propagation — and the pooled read paths (native Avro, streamed
chunks) must be byte-identical to their sequential reads.
"""

import time

import numpy as np
import pytest


def test_map_ordered_preserves_order_and_window():
    from photon_tpu.utils.io_pool import map_ordered

    started = []

    def work(i):
        started.append(i)
        time.sleep(0.002 * (7 - i % 8))  # later items often finish first
        return i * i

    # The real memory bound is SUBMITTED-but-unconsumed results, not
    # concurrently-running workers: submission only advances between
    # yields, so started <= consumed + window must hold at every step
    # (deleting the window logic would submit all 40 upfront).
    out = []
    for r in map_ordered(work, range(40), workers=4, window=6):
        out.append(r)
        time.sleep(0.004)  # slow consumer: unbounded submission would race
        # ahead (workers churn the whole input while we sleep)
        assert len(started) <= len(out) + 6, (
            f"window exceeded: {len(started)} started, {len(out)} consumed"
        )
    assert out == [i * i for i in range(40)]


def test_map_ordered_sequential_fallback_and_errors():
    from photon_tpu.utils.io_pool import map_ordered

    # workers=1: plain lazy map, no threads.
    seen = []

    def trace(i):
        seen.append(i)
        return i

    it = map_ordered(trace, [1, 2, 3], workers=1)
    assert next(it) == 1 and seen == [1], "workers=1 must stay lazy"

    # An exception surfaces at its in-order position, same as sequential.
    def boom(i):
        if i == 3:
            raise ValueError("file 3 is corrupt")
        return i

    out = []
    with pytest.raises(ValueError, match="file 3"):
        for r in map_ordered(boom, range(6), workers=3):
            out.append(r)
    assert out == [0, 1, 2], "items before the failure must still deliver"


def test_map_ordered_abandon_cancels_pending():
    from photon_tpu.utils.io_pool import map_ordered

    started = []

    def work(i):
        started.append(i)
        time.sleep(0.005)
        return i

    it = map_ordered(work, range(100), workers=2, window=3)
    assert next(it) == 0
    it.close()  # abandoning must not run all 100 items
    time.sleep(0.05)
    assert len(started) <= 10, f"abandoned iterator kept working: {started}"


def test_io_threads_env(monkeypatch):
    from photon_tpu.utils import io_pool

    monkeypatch.setenv("PHOTON_IO_THREADS", "3")
    assert io_pool.io_threads() == 3
    monkeypatch.setenv("PHOTON_IO_THREADS", "0")
    assert io_pool.io_threads() >= 1  # falls back to cpu-count heuristic
    monkeypatch.setenv("PHOTON_IO_THREADS", "junk")
    assert io_pool.io_threads() >= 1


def test_pooled_avro_read_matches_sequential(tmp_path, monkeypatch):
    """read_game_avro over multiple part files: PHOTON_IO_THREADS=4 must be
    byte-identical to the sequential read (vocab order included)."""
    from photon_tpu.data.fixtures import make_movielens_like
    from photon_tpu.data.game_io import read_game_avro, write_game_avro
    from photon_tpu.game.data import take_rows

    data, maps = make_movielens_like(n_users=40, n_items=30, mean_ratings=6)
    d = tmp_path / "parts"
    d.mkdir()
    # Split rows across 4 part files.
    n = data.num_examples
    for pi in range(4):
        lo, hi = pi * n // 4, (pi + 1) * n // 4
        write_game_avro(
            str(d / f"part-{pi:04d}.avro"),
            take_rows(data, np.arange(lo, hi)), maps,
        )

    bags = {"global": "global", "per_user": "per_user"}
    cols = ["userId", "itemId"]
    monkeypatch.setenv("PHOTON_IO_THREADS", "1")
    ds_seq, maps_seq = read_game_avro(str(d), bags, cols)
    monkeypatch.setenv("PHOTON_IO_THREADS", "4")
    ds_par, maps_par = read_game_avro(str(d), bags, cols)

    np.testing.assert_array_equal(ds_seq.label, ds_par.label)
    np.testing.assert_array_equal(ds_seq.offset, ds_par.offset)
    np.testing.assert_array_equal(ds_seq.weight, ds_par.weight)
    for c in cols:
        assert list(ds_seq.id_columns[c]) == list(ds_par.id_columns[c])
    for s in bags:
        assert list(maps_seq[s].keys()) == list(maps_par[s].keys())
        np.testing.assert_array_equal(ds_seq.shard(s).ids, ds_par.shard(s).ids)
        np.testing.assert_array_equal(ds_seq.shard(s).vals, ds_par.shard(s).vals)


def test_pooled_stream_chunks_matches_sequential(tmp_path, monkeypatch):
    """Streamed objective over part files: pooled chunk loading gives the
    same value+gradient as single-threaded prefetch."""
    import jax.numpy as jnp

    from photon_tpu.core.objective import GlmObjective, RegularizationContext
    from photon_tpu.data.streaming import LibsvmFileSource, StreamingObjective
    from photon_tpu.data.synthetic import make_glm_data, write_libsvm

    files = []
    for i in range(5):
        batch, _ = make_glm_data(60, 16, task="logistic_regression", seed=i)
        p = str(tmp_path / f"part-{i}.libsvm")
        write_libsvm(p, np.asarray(batch.x), np.asarray(batch.label))
        files.append(p)

    def run():
        source = LibsvmFileSource(files, intercept=True)
        obj = StreamingObjective(
            GlmObjective.create("logistic", RegularizationContext("l2", 0.5)),
            source.chunk_iter_factory,
        )
        w = jnp.zeros(source.dim, jnp.float32)
        v, g = obj.value_and_grad(w)
        return float(v), np.asarray(g)

    monkeypatch.setenv("PHOTON_IO_THREADS", "1")
    v1, g1 = run()
    monkeypatch.setenv("PHOTON_IO_THREADS", "4")
    v4, g4 = run()
    assert v1 == v4
    np.testing.assert_array_equal(g1, g4)


def test_map_ordered_telemetry_gauges():
    """With a telemetry session the pool exports its live shape: configured
    workers, current in-flight, and the in-flight high-water mark (ISSUE 5
    satellite: io_pool gauges in run reports)."""
    from photon_tpu.telemetry import TelemetrySession
    from photon_tpu.utils.io_pool import map_ordered

    session = TelemetrySession("t")
    out = list(map_ordered(
        lambda i: i + 1, range(20), workers=4, window=6, telemetry=session,
    ))
    assert out == list(range(1, 21))
    assert session.gauge("io_pool.workers").value == 4
    peak = session.gauge("io_pool.in_flight_peak").value
    assert 1 <= peak <= 6
    # After the last harvest the window is drained.
    assert session.gauge("io_pool.in_flight").value == 0

    # Sequential fallback (workers=1) never touches the pool gauges.
    seq = TelemetrySession("t2")
    list(map_ordered(lambda i: i, range(3), workers=1, telemetry=seq))
    assert seq.gauge("io_pool.in_flight_peak").value is None
