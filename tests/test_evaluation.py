"""Evaluator checks vs hand-computed values and sklearn-free references."""

import numpy as np
import pytest

from photon_tpu.evaluation import get_evaluator
from photon_tpu.evaluation.metrics import (
    area_under_roc_curve,
    precision_at_k,
    rmse,
    sharded_metric,
)


def _auc_bruteforce(scores, labels, weights=None):
    w = np.ones_like(scores) if weights is None else weights
    num = den = 0.0
    for i in range(len(scores)):
        for j in range(len(scores)):
            if labels[i] == 1 and labels[j] == 0:
                pair_w = w[i] * w[j]
                den += pair_w
                if scores[i] > scores[j]:
                    num += pair_w
                elif scores[i] == scores[j]:
                    num += 0.5 * pair_w
    return num / den


def test_auc_matches_bruteforce():
    rng = np.random.default_rng(0)
    scores = rng.normal(size=60).astype(np.float32)
    scores[::7] = scores[3]  # inject ties
    labels = (rng.random(60) < 0.4).astype(np.float32)
    got = float(area_under_roc_curve(scores, labels))
    np.testing.assert_allclose(got, _auc_bruteforce(scores, labels), rtol=1e-5)


def test_auc_weighted_and_padded():
    rng = np.random.default_rng(1)
    scores = rng.normal(size=40).astype(np.float32)
    labels = (rng.random(40) < 0.5).astype(np.float32)
    weights = rng.uniform(0.5, 2.0, 40).astype(np.float32)
    weights[30:] = 0.0  # padded rows must be invisible
    got = float(area_under_roc_curve(scores, labels, weights))
    want = _auc_bruteforce(scores[:30], labels[:30], weights[:30])
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_auc_perfect_and_random():
    scores = np.array([0.1, 0.2, 0.8, 0.9], np.float32)
    labels = np.array([0, 0, 1, 1], np.float32)
    assert float(area_under_roc_curve(scores, labels)) == 1.0
    assert float(area_under_roc_curve(scores, 1 - labels)) == 0.0


def test_rmse():
    s = np.array([1.0, 2.0, 3.0], np.float32)
    l = np.array([1.0, 1.0, 1.0], np.float32)
    np.testing.assert_allclose(float(rmse(s, l)), np.sqrt(5.0 / 3.0), rtol=1e-6)


def test_precision_at_k():
    scores = np.array([0.9, 0.8, 0.7, 0.1], np.float32)
    labels = np.array([1, 0, 1, 1], np.float32)
    np.testing.assert_allclose(float(precision_at_k(scores, labels, k=2)), 0.5)
    np.testing.assert_allclose(float(precision_at_k(scores, labels, k=3)), 2 / 3)


def test_sharded_auc_skips_single_class_groups():
    scores = np.array([0.9, 0.1, 0.8, 0.2, 0.5, 0.6], np.float32)
    labels = np.array([1, 0, 1, 0, 1, 1], np.float32)
    groups = np.array([0, 0, 1, 1, 2, 2])
    got = sharded_metric(
        area_under_roc_curve, scores, labels, groups, require_both_classes=True
    )
    np.testing.assert_allclose(got, 1.0)  # groups 0,1 perfect; group 2 skipped


def test_evaluator_registry_and_direction():
    auc = get_evaluator("AUC")
    assert auc.maximize and auc.better_than(0.9, 0.8)
    rmse_ev = get_evaluator("rmse")
    assert not rmse_ev.maximize and rmse_ev.better_than(0.1, 0.2)
    p5 = get_evaluator("precision@5")
    assert p5.name == "PRECISION@5"
    sauc = get_evaluator("sharded_auc:userId")
    assert sauc.entity_column == "userId"
    with pytest.raises(KeyError):
        get_evaluator("f1")  # not in the reference's evaluator set


def test_sharded_evaluator_end_to_end():
    ev = get_evaluator("sharded_auc:user")
    scores = np.array([0.9, 0.1, 0.2, 0.8], np.float32)
    labels = np.array([1, 0, 0, 1], np.float32)
    ids = np.array([7, 7, 9, 9])
    assert ev.evaluate(scores, labels, entity_ids=ids) == 1.0
