"""Probe 2: verify the shuffle-pipeline primitives with scalar outputs.

(a) in-kernel [128,128] transpose throughput
(b) deep sublane gather: v-loop of take_along_axis+select over a 16-vreg block
(c) P1 skeleton: gather + multiply + transpose + regrouped write
All timed programs reduce outputs to a scalar inside jit (tunnel-safe).
"""

from __future__ import annotations

import sys
import time

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

S, L = 128, 128
N_TILES = 2048  # 134 MB of f32


def tm(fn, *args, reps=10):
    fj = jax.jit(fn)
    out = fj(*args)
    np.asarray(out)
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fj(*args)
    np.asarray(out)
    return (time.perf_counter() - t0) / reps


def bench(name, kernel, inputs, n_in_blocks=1):
    try:
        f = pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((N_TILES * S, L), jnp.float32),
            grid=(N_TILES,),
            in_specs=[pl.BlockSpec((S, L), lambda i: (i, 0))
                      for _ in range(n_in_blocks)],
            out_specs=pl.BlockSpec((S, L), lambda i: (i, 0)),
        )
        t = tm(lambda *a: jnp.sum(f(*a)), *inputs)
        n = N_TILES * S * L
        print(f"{name:40s} {t*1e3:8.2f} ms  {n/t/1e9:7.2f} Gelem/s")
    except Exception as ex:  # noqa: BLE001
        print(f"{name:40s} FAILED: {type(ex).__name__}: {str(ex)[:160]}")


def main():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((N_TILES * S, L)).astype(np.float32))

    def k_copy(x_ref, o_ref):
        o_ref[...] = x_ref[...]
    bench("copy", k_copy, (x,))

    def k_t(x_ref, o_ref):
        o_ref[...] = x_ref[...].T
    bench("transpose 128x128", k_t, (x,))

    # deep sublane gather: each output vreg gathers from 16 source vregs
    # via hi/lo decomposition (the P3 assemble pattern)
    hi = rng.integers(0, 16, size=(N_TILES * S, L), dtype=np.int32)
    lo = rng.integers(0, 8, size=(N_TILES * S, L), dtype=np.int32)
    hi_j, lo_j = jnp.asarray(hi), jnp.asarray(lo)

    def k_deep(x_ref, hi_ref, lo_ref, o_ref):
        for ov in range(16):
            sl = slice(ov * 8, (ov + 1) * 8)
            h = hi_ref[sl, :]
            l = lo_ref[sl, :]
            acc = jnp.zeros((8, L), jnp.float32)
            for v in range(16):
                src = x_ref[v * 8:(v + 1) * 8, :]
                acc = jnp.where(h == v, jnp.take_along_axis(src, l, axis=0), acc)
            o_ref[sl, :] = acc
    bench("deep gather 128-deep (16x ta+sel)", k_deep, (x, hi_j, lo_j), 3)

    # P1 skeleton: 8-deep gather + mul + transpose
    idx8 = jnp.asarray(rng.integers(0, 8, size=(N_TILES * S, L), dtype=np.int32))

    def k_p1(x_ref, i_ref, o_ref):
        w = x_ref[0:8, :]
        out = jnp.zeros((S, L), jnp.float32)
        for v in range(16):
            sl = slice(v * 8, (v + 1) * 8)
            out = out.at[sl, :].set(
                jnp.take_along_axis(w, i_ref[sl, :], axis=0) * x_ref[sl, :])
        o_ref[...] = out.T
    bench("gather8+mul+transpose (P1 skel)", k_p1, (x, idx8), 2)

    # XLA big transpose for comparison
    x4 = x.reshape(N_TILES, S // 8, 8, L)
    t = tm(lambda a: jnp.sum(jnp.transpose(a, (1, 0, 2, 3))), x4)
    print(f"{'XLA transpose [2048,16,8,128]->(1,0,..)':40s} {t*1e3:8.2f} ms")


if __name__ == "__main__":
    main()
