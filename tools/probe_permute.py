"""Primitive probes for the static-permutation ("benes") sparse-grad design.

Every kernel in production (autodiff scatter / fm segment-sum / pallas
aligned reduce) bottlenecks on ONE pathology measured in the round-4
hardware window: XLA lowers random E-element gathers and scatters on TPU
essentially serially (~0.1% of HBM roofline at the baseline shape).  The
candidate fix is to eliminate random access entirely: the row-order ->
feature-order exchange is a STATIC permutation, and a static permutation
can be decomposed into hardware-friendly primitives.  This probe times
each candidate building block on the live backend so the design choice is
measurement-driven (KERNEL_NOTES.md round-4 verdict 3):

  a. baseline: full-array XLA gather x[perm]                (the pathology)
  b. XLA 2-D transpose at the exchange shape               (Clos middle stage)
  c. in-kernel jnp.take_along_axis along lanes (Mosaic
     dynamic-gather lowering, if supported)                 (would collapse
                                                            the whole network
                                                            to one pass)
  d. Pallas masked-XOR-swap stage built from pltpu.roll     (Benes stage)
  e. windowed one-hot matmul segment-sum (MXU)              (sorted-side
                                                            reduce/gather)
  f. jnp.repeat monotonic expand w[f] by static counts      (forward side)
  g. XLA sort-by-key at E (dynamic-permutation alternative)
  h. within-row take_along_axis at the stage shape          (one Clos stage
                                                            as XLA sees it)
  i. full 3-stage Clos apply (P1.T.P2.T.P3)                 (the complete
                                                            XLA-only benes
                                                            permute —
                                                            ops/clos.py)

Timing methodology matches tools/microbench2.py: jit once, warm up, then
median of reps with a scalar reduction brought host-side so the timed
window contains no host copies of the payload.
"""

import argparse

import numpy as np

from probe_common import CHAIN, timed as _time  # noqa: F401 (cpu guard)

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def probe_gather_baseline(E):
    perm = np.random.permutation(E).astype(np.int32)
    x = jnp.asarray(np.random.rand(E).astype(np.float32))
    permd = jnp.asarray(perm)

    @jax.jit
    def f(x, p):
        y = x
        for _ in range(CHAIN):
            y = y[p]  # output feeds the next gather: no step can be elided
        return y.sum()

    t = _time(f, x, permd) / CHAIN
    print(f"a. XLA random gather     E={E:>10,}  {t*1e3:8.2f} ms  "
          f"{E/t/1e6:10.1f} Melem/s  {E*4/t/1e9:7.2f} GB/s")
    return t


def probe_transpose(E):
    # Exchange shape for the Clos middle stage: [A, B] -> [B, A].
    A = 8192
    B = E // A
    x = jnp.asarray(np.random.rand(A, B).astype(np.float32))

    @jax.jit
    def f(x):
        y = x
        for i in range(CHAIN):
            # *1.0000001 keeps each stage a distinct computation (T.T would
            # fold to identity); the multiply fuses into the transpose
            # write.  The barrier stops XLA from treating the transpose as
            # a free layout change absorbed by a layout-agnostic consumer.
            y = jax.lax.optimization_barrier(y.T) * jnp.float32(1.0000001)
        return y.sum()

    t = _time(f, x) / CHAIN
    print(f"b. XLA transpose [{A}x{B}]      {t*1e3:8.2f} ms  "
          f"{A*B*4/t/1e9:7.2f} GB/s")
    return t


def probe_lane_gather_kernel(E):
    # Per-sublane arbitrary lane gather inside a Pallas kernel.  If Mosaic
    # lowers take_along_axis on the lane axis, a static tile-local
    # permutation is ONE vector op per tile and the Benes network is
    # unnecessary.
    TILE = (512, 128)
    n_tiles = E // (TILE[0] * TILE[1])
    E = n_tiles * TILE[0] * TILE[1]  # actual processed count

    def kernel(x_ref, idx_ref, o_ref):
        o_ref[...] = jnp.take_along_axis(x_ref[...], idx_ref[...], axis=1)

    xh = np.random.rand(n_tiles * TILE[0], 128).astype(np.float32)
    x = jnp.asarray(xh)
    idx = jnp.asarray(
        np.argsort(np.random.rand(n_tiles * TILE[0], 128), axis=1).astype(
            np.int32
        )
    )

    try:
        f = pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
            grid=(n_tiles,),
            in_specs=[
                pl.BlockSpec(TILE, lambda i: (i, 0)),
                pl.BlockSpec(TILE, lambda i: (i, 0)),
            ],
            out_specs=pl.BlockSpec(TILE, lambda i: (i, 0)),
        )
        def chained(x, idx):
            y = x
            for _ in range(CHAIN):
                y = f(y, idx)
            return y.sum()

        g = jax.jit(chained)
        # Correctness first: the permuted rows must sum to the same total.
        total = float(g(x, idx))
        np.testing.assert_allclose(
            total, float(xh.astype(np.float64).sum()), rtol=1e-3
        )
        t = _time(g, x, idx) / CHAIN
        print(f"c. pallas lane-gather    E={E:>10,}  {t*1e3:8.2f} ms  "
              f"{E/t/1e6:10.1f} Melem/s  {E*4/t/1e9:7.2f} GB/s")
        return t
    except Exception as e:  # noqa: BLE001 - probe must report, not crash
        print(f"c. pallas lane-gather    UNSUPPORTED: {type(e).__name__}: "
              f"{str(e)[:120]}")
        return None


def probe_benes_stage(E):
    # One masked XOR-swap stage (stride 32 within lanes) via two rolls and
    # a select, which is the per-stage cost of a lane-level Benes network.
    # Stride 32 keeps the two rolls distinct expressions (at stride 64 the
    # +s and -s rolls coincide and CSE would time half a real stage).
    TILE = (512, 128)
    n_tiles = E // (TILE[0] * TILE[1])
    E = n_tiles * TILE[0] * TILE[1]  # actual processed count

    def kernel(x_ref, m_ref, o_ref):
        x = x_ref[...]
        up = pltpu.roll(x, 32, axis=1)
        dn = pltpu.roll(x, 128 - 32, axis=1)  # roll is cyclic: -s == size-s
        m = m_ref[...]
        lane = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
        swapped = jnp.where((lane // 32) % 2 == 0, up, dn)
        o_ref[...] = jnp.where(m > 0, swapped, x)

    x = jnp.arange(E, dtype=jnp.float32).reshape(n_tiles * TILE[0], 128)
    m = jnp.asarray(
        (np.random.rand(n_tiles * TILE[0], 128) < 0.5).astype(np.float32)
    )

    try:
        f = pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
            grid=(n_tiles,),
            in_specs=[
                pl.BlockSpec(TILE, lambda i: (i, 0)),
                pl.BlockSpec(TILE, lambda i: (i, 0)),
            ],
            out_specs=pl.BlockSpec(TILE, lambda i: (i, 0)),
        )
        def chained(x, m):
            y = x
            for _ in range(CHAIN):
                y = f(y, m)
            return y.sum()

        g = jax.jit(chained)
        t = _time(g, x, m) / CHAIN
        print(f"d. benes swap stage      E={E:>10,}  {t*1e3:8.2f} ms  "
              f"{E/t/1e6:10.1f} Melem/s  (x19 stages ~ "
              f"{19*t*1e3:6.1f} ms/full-perm upper bound)")
        return t
    except Exception as e:  # noqa: BLE001
        print(f"d. benes swap stage      UNSUPPORTED: {type(e).__name__}: "
              f"{str(e)[:120]}")
        return None


def probe_onehot_segsum(E):
    # Sorted-side segment-sum as a windowed one-hot MXU matmul: tiles of
    # sorted entries whose feature ids span a 128-wide window; the reduce
    # is onehot[T,128]^T @ pv[T] accumulated per window.
    T = 2048  # entries per tile
    GROUP = 128  # tiles whose one-hot materializes at once (134 MB f32)
    n_groups = E // (T * GROUP)
    n_tiles = n_groups * GROUP
    E = n_tiles * T  # actual processed count
    # Synthetic sorted ids: each tile covers its own 128-window densely.
    local = np.sort(np.random.randint(0, 128, size=(n_tiles, T))).astype(
        np.int32
    )
    pv = jnp.asarray(
        np.random.rand(n_tiles, T).astype(np.float32).reshape(
            n_groups, GROUP, T
        )
    )
    idx = jnp.asarray(local.reshape(n_groups, GROUP, T))

    @jax.jit
    def f(pv, idx):
        # lax.map over groups bounds the materialized one-hot to
        # GROUP*T*128*4 bytes; a single whole-E one-hot would exceed the
        # 16 GB HBM of the target chip at the default entry count.
        def group(args):
            pv_g, idx_g = args
            onehot = (
                idx_g[..., None] == jnp.arange(128)[None, None, :]
            ).astype(jnp.float32)
            return jnp.einsum("nt,ntw->nw", pv_g, onehot).sum()

        s = jnp.float32(0.0)
        for _ in range(CHAIN):
            # Chain through the scalar: each pass's input is perturbed by
            # the previous pass's result, so no pass can be elided.  The
            # perturbing broadcast-add is stream-speed (noise next to the
            # matmul passes being timed).
            s = jax.lax.map(group, (pv + s * 1e-30, idx)).sum()
        return s

    t = _time(f, pv, idx) / CHAIN
    print(f"e. onehot segsum (MXU)   E={E:>10,}  {t*1e3:8.2f} ms  "
          f"{E/t/1e6:10.1f} Melem/s")
    return t


def probe_repeat_expand(E, d=262144):
    # Forward-side monotonic expand: w[f] repeated by static per-feature
    # counts (ids sorted by feature).  Implemented as the standard
    # cumsum-searchsorted-free gather on a SORTED index vector so XLA can
    # see monotonicity.
    per = max(1, E // d)
    E = per * d  # actual processed count
    sorted_feat = jnp.asarray(np.repeat(np.arange(d), per).astype(np.int32))
    w = jnp.asarray(np.random.rand(d).astype(np.float32))

    @jax.jit
    def f(w, f_sorted):
        s = jnp.float32(0.0)
        for _ in range(CHAIN):
            s = (w + s * 1e-30)[f_sorted].sum()  # scalar-chained: see _time
        return s

    t = _time(f, w, sorted_feat) / CHAIN
    print(f"f. monotonic gather w[f] E={E:>10,}  {t*1e3:8.2f} ms  "
          f"{E/t/1e6:10.1f} Melem/s")
    return t


def probe_rowwise_gather(E):
    # One Clos stage as XLA sees it: within-row gather on the [A, B] grid
    # with a DIFFERENT random perm per row.  Random per-row indices are
    # timing-equivalent to real routed stages, so no router is needed.
    A = 8192
    B = E // A
    E = A * B
    x = jnp.asarray(np.random.rand(A, B).astype(np.float32))
    idx = jnp.asarray(
        np.argsort(np.random.rand(A, B), axis=1).astype(np.int32)
    )

    @jax.jit
    def f(x, idx):
        y = x
        for _ in range(CHAIN):
            y = jnp.take_along_axis(y, idx, axis=1)
        return y.sum()

    t = _time(f, x, idx) / CHAIN
    print(f"h. row-wise gather [{A}x{B}]  {t*1e3:8.2f} ms  "
          f"{E/t/1e6:10.1f} Melem/s  {E*4/t/1e9:7.2f} GB/s")
    return t


def probe_clos_composite(E):
    # Full 3-stage Clos apply (P1, T, P2, T, P3) with random per-row
    # perms; upper-bounds the XLA-only benes permute cost per direction.
    A = 8192
    B = E // A
    E = A * B
    x = jnp.asarray(np.random.rand(A, B).astype(np.float32))
    rng = np.random.default_rng(0)
    p1 = jnp.asarray(np.argsort(rng.random((A, B)), axis=1).astype(np.int32))
    p2 = jnp.asarray(np.argsort(rng.random((B, A)), axis=1).astype(np.int32))
    p3 = jnp.asarray(np.argsort(rng.random((A, B)), axis=1).astype(np.int32))

    @jax.jit
    def f(x, p1, p2, p3):
        g = x
        for _ in range(CHAIN):
            g = jnp.take_along_axis(g, p1, axis=1)
            g = g.T
            g = jnp.take_along_axis(g, p2, axis=1)
            g = g.T
            g = jnp.take_along_axis(g, p3, axis=1)
        return g.sum()

    t = _time(f, x, p1, p2, p3) / CHAIN
    print(f"i. clos 3-stage apply    E={E:>10,}  {t*1e3:8.2f} ms  "
          f"{E/t/1e6:10.1f} Melem/s  (vs probe a = the op it replaces)")
    return t


def probe_sort(E):
    k = jnp.asarray(np.random.randint(0, E, size=E).astype(np.int32))
    v = jnp.arange(E, dtype=jnp.float32)

    @jax.jit
    def f(k, v):
        for _ in range(CHAIN):
            k, v = jax.lax.sort([k, v], num_keys=1)
            # Re-randomize keys from the sorted values (cheap elementwise
            # hash) so every chained sort does full work on unsorted keys.
            vb = jax.lax.bitcast_convert_type(v, jnp.int32)
            k = (vb * jnp.int32(-1640531527)) ^ k
        return v.sum()

    t = _time(f, k, v) / CHAIN
    print(f"g. XLA sort-by-key       E={E:>10,}  {t*1e3:8.2f} ms  "
          f"{E/t/1e6:10.1f} Melem/s")
    return t


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--entries", type=int, default=1 << 25)
    args = ap.parse_args()
    E = args.entries
    print(f"backend={jax.default_backend()} devices={jax.devices()} E={E:,}")
    # Each probe is individually guarded: a mid-run failure (OOM, tunnel
    # drop, unsupported lowering) must not cost the remaining rows —
    # partial output is still evidence.
    for probe in (
        probe_gather_baseline,
        probe_transpose,
        probe_lane_gather_kernel,
        probe_benes_stage,
        probe_onehot_segsum,
        probe_repeat_expand,
        probe_sort,
        probe_rowwise_gather,
        probe_clos_composite,
    ):
        try:
            probe(E)
        except Exception as e:  # noqa: BLE001 - report and continue
            print(f"{probe.__name__} FAILED: {type(e).__name__}: "
                  f"{str(e)[:160]}")


if __name__ == "__main__":
    main()
