#!/usr/bin/env bash
# Poll the TPU tunnel; the moment it answers, run the evidence pack.
# Round-4 windows lasted 8-13 minutes and arrived unannounced — an
# unattended watcher is the only way not to miss one.  Probe is a
# bounded subprocess (the axon backend init HANGS, not errors, when the
# tunnel is down).  Exits after one successful pack so the operator (or
# agent) is notified exactly once per window.
set -u
cd "$(dirname "$0")/.."
INTERVAL="${1:-300}"
while true; do
    rm -f "${TMPDIR:-/tmp}/photon_bench_backend_probe.json"
    if timeout 120 python -c "
import jax
assert jax.default_backend() in ('tpu', 'axon'), jax.default_backend()
print('tpu up')
" >/dev/null 2>&1; then
        echo "$(date -u +%H:%M:%S) tunnel up — running pack"
        bash tools/tpu_day.sh
        exit 0
    fi
    echo "$(date -u +%H:%M:%S) tunnel down; sleeping ${INTERVAL}s"
    sleep "$INTERVAL"
done
