#!/usr/bin/env bash
# Poll the TPU tunnel; the moment it answers, run the evidence pack.
# Round-4 windows lasted 8-13 minutes and arrived unannounced — an
# unattended watcher is the only way not to miss one.  Probe is a
# bounded subprocess (the axon backend init HANGS, not errors, when the
# tunnel is down).
#
# After a pack completes the PREVIOUS pack's outputs are archived to
# tools/tpu_day_out_<utc-stamp>/ and the watcher keeps watching — a
# round can catch several windows (round 4 saw three) without the
# second pack clobbering the first window's evidence.  Pass a second
# argument "once" for the old exit-after-one-pack behavior.
set -u
cd "$(dirname "$0")/.."
INTERVAL="${1:-300}"
MODE="${2:-loop}"
while true; do
    rm -f "${TMPDIR:-/tmp}/photon_bench_backend_probe.json"
    if timeout 120 python -c "
import jax
assert jax.default_backend() in ('tpu', 'axon'), jax.default_backend()
print('tpu up')
" >/dev/null 2>&1; then
        echo "$(date -u +%H:%M:%S) tunnel up — running pack"
        if bash tools/tpu_day.sh; then
            echo "$(date -u +%H:%M:%S) pack finished"
            if [ "$MODE" = "once" ]; then
                exit 0
            fi
            # Archive only COMPLETED packs: an aborted pack (backend
            # gate failed mid-window) leaves a stub that must not be
            # stamped as window evidence — the next attempt overwrites
            # it in place instead.
            if [ -d tools/tpu_day_out ]; then
                stamp=$(date -u +%m%d_%H%M%S)
                mv tools/tpu_day_out "tools/tpu_day_out_${stamp}"
                echo "$(date -u +%H:%M:%S) archived pack to" \
                     "tpu_day_out_${stamp}; watching for the next window"
            fi
        else
            echo "$(date -u +%H:%M:%S) pack aborted (backend gate or" \
                 "mid-run failure); will retry on the next probe"
        fi
    else
        echo "$(date -u +%H:%M:%S) tunnel down; sleeping ${INTERVAL}s"
    fi
    sleep "$INTERVAL"
done
