"""Route-build scaling measurement (VERDICT r4 missing-weak #4 / r5
task: 'measure and bound route-build scaling').

Times ``build_xchg_aux`` (the production exchange-route build) across
entry counts; prints one JSON line per (E, mode) so the cost model in
KERNEL_NOTES.md can carry numbers.  The PHASE attribution in that table
(~60% native edge-coloring, ~20% argsorts at E=2^23) came from cProfile
— reproduce it with:

    python -c "import cProfile, pstats; \
      cProfile.run('...build_xchg_aux(...)', 'out'); \
      pstats.Stats('out').sort_stats('cumulative').print_stats(14)"

(the colorings are the independent per-chunk `_edge_color_native`
calls, parallelizable via PHOTON_ROUTE_THREADS).

Run: python tools/probe_route_scaling.py [max_log2_e]
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("PHOTON_ROUTE_CACHE", "0")

import numpy as np


def main() -> None:
    import jax

    jax.config.update("jax_platforms", "cpu")
    from photon_tpu.ops.vperm import build_xchg_aux

    max_log2 = int(sys.argv[1]) if len(sys.argv) > 1 else 25
    k = 32
    threads = os.environ.get("PHOTON_ROUTE_THREADS", "(default)")
    for log2e in range(22, max_log2 + 1):
        e = 1 << log2e
        n = e // k
        rng = np.random.default_rng(0)
        ids = rng.integers(1, 1 << 18, size=(n, k), dtype=np.int32)
        vals = rng.standard_normal((n, k)).astype(np.float32)
        for mode in ("cumsum",):
            os.environ["PHOTON_XCHG_REDUCE"] = mode
            t0 = time.perf_counter()
            aux = build_xchg_aux(None, ids, 1 << 18, vals=vals)
            wall = time.perf_counter() - t0
            kind = type(aux.route).__name__
            print(json.dumps({
                "e": e, "log2e": log2e, "mode": mode, "kind": kind,
                "nc": aux.route.nc, "ch": aux.route.ch,
                "build_seconds": round(wall, 2),
                "us_per_entry": round(1e6 * wall / e, 3),
                "threads": threads,
            }), flush=True)
            del aux


if __name__ == "__main__":
    main()
