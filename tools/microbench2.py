"""Re-run primitive benchmarks with scalar-reduced outputs.

The axon-tunneled TPU platform makes device->host copies of large outputs
dominate wall time (a 134MB fetch costs ~700ms), so every timed program here
reduces its result to a scalar INSIDE jit; only 4 bytes cross the tunnel.
"""

from __future__ import annotations

import sys
import time

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp
import numpy as np


def tm(fn, *args, reps=10):
    fj = jax.jit(fn)
    out = fj(*args)
    np.asarray(out)
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fj(*args)
    np.asarray(out)
    return (time.perf_counter() - t0) / reps


def main():
    n, k, d = 1 << 20, 32, 1 << 18
    e = n * k
    rng = np.random.default_rng(0)
    ids = rng.integers(1, d, size=(n, k), dtype=np.int32)
    vals = rng.standard_normal((n, k)).astype(np.float32)
    w = jnp.asarray(rng.standard_normal(d).astype(np.float32))
    ids_j = jnp.asarray(ids)
    vals_j = jnp.asarray(vals)

    flat = ids.reshape(-1)
    order = np.argsort(flat, kind="stable").astype(np.int32)
    perm = jnp.asarray(order)
    qe = jnp.asarray(rng.standard_normal(e).astype(np.float32))
    u = jnp.asarray(rng.standard_normal(n).astype(np.float32))

    res = {}
    res["fused margins rowsum (fwd today)"] = timeit = tm(
        lambda w, i, v: jnp.sum((jnp.take(w, i, axis=0) * v).sum(axis=-1)),
        w, ids_j, vals_j)
    res["gather w[ids] + sum"] = tm(
        lambda w, i: jnp.sum(jnp.take(w, i.reshape(-1), axis=0)), w, ids_j)
    res["permute 33.5M + sum"] = tm(
        lambda q, p: jnp.sum(jnp.take(q, p, axis=0)), qe, perm)
    res["cumsum 33.5M + last"] = tm(lambda q: jnp.cumsum(q)[-1], qe)
    res["scatter-add 33.5M->d + sum"] = tm(
        lambda q, i: jnp.sum(jnp.zeros(d, jnp.float32).at[i.reshape(-1)].add(q)),
        qe, ids_j)
    res["u bcast [n,k] flat + sum"] = tm(
        lambda v, u: jnp.sum((v * u[:, None]).reshape(-1)), vals_j, u)

    try:
        from photon_tpu.ops.pallas_gather import (
            aligned_gather_products, build_aligned_layout)
        lay = build_aligned_layout(ids, vals, d)
        gmap = jnp.asarray(lay.group_of_tile)
        lo = jnp.asarray(lay.lo)
        lvals = jnp.asarray(lay.vals)
        t = tm(lambda w, g, l, v: jnp.sum(aligned_gather_products(w, g, l, v)),
               w, gmap, lo, lvals)
        res[f"pallas aligned gather+sum ({lay.padded_entries/1e6:.0f}M slots)"] = t
    except Exception as ex:  # noqa: BLE001
        print("pallas aligned gather FAILED:", str(ex)[:200])

    for name, t in res.items():
        print(f"{name:45s} {t*1e3:8.2f} ms   {e/t/1e9:7.2f} Gelem/s")


if __name__ == "__main__":
    main()
