"""Decision-grade micro-benchmarks of the sparse-GLM primitive ops.

The axon-tunneled TPU platform makes device->host copies of large outputs
dominate wall time (a 134MB fetch costs ~700ms), so every timed program here
reduces its result to a scalar INSIDE jit; only 4 bytes cross the tunnel.
Each row reports throughput against ITS OWN element count (a pallas row
processes padded slots, not raw entries).

Run on the real chip; record the table in photon_tpu/ops/KERNEL_NOTES.md —
it decides whether the crossing-stage kernels are worth building.
"""

from __future__ import annotations

import sys
import time

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp
import numpy as np


def tm(fn, *args, reps=10):
    fj = jax.jit(fn)
    out = fj(*args)
    np.asarray(out)
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fj(*args)
    np.asarray(out)
    return (time.perf_counter() - t0) / reps


def main():
    n, k, d = 1 << 20, 32, 1 << 18
    e = n * k
    rng = np.random.default_rng(0)
    ids = rng.integers(1, d, size=(n, k), dtype=np.int32)
    vals = rng.standard_normal((n, k)).astype(np.float32)
    w = jnp.asarray(rng.standard_normal(d).astype(np.float32))
    ids_j = jnp.asarray(ids)
    vals_j = jnp.asarray(vals)

    flat = ids.reshape(-1)
    order = np.argsort(flat, kind="stable").astype(np.int32)
    sorted_ids = jnp.asarray(flat[order])
    rows_sorted = jnp.asarray((order // k).astype(np.int32))
    perm = jnp.asarray(order)
    qe = jnp.asarray(rng.standard_normal(e).astype(np.float32))
    u = jnp.asarray(rng.standard_normal(n).astype(np.float32))

    res = {}  # name -> (seconds, element_count)
    res["fwd: gather w[ids] + rowsum margins"] = (tm(
        lambda w, i, v: jnp.sum((jnp.take(w, i, axis=0) * v).sum(axis=-1)),
        w, ids_j, vals_j), e)
    res["gather dz[rows] 33.5M from 4MB"] = (tm(
        lambda u, r: jnp.sum(jnp.take(u, r, axis=0)), u, rows_sorted), e)
    res["permute 33.5M from 134MB"] = (tm(
        lambda q, p: jnp.sum(jnp.take(q, p, axis=0)), qe, perm), e)
    res["cumsum 33.5M"] = (tm(lambda q: jnp.cumsum(q)[-1], qe), e)
    res["bwd today: scatter-add unsorted"] = (tm(
        lambda q, i: jnp.sum(jnp.zeros(d, jnp.float32).at[i.reshape(-1)].add(q)),
        qe, ids_j), e)
    res["bwd fast: segment_sum sorted"] = (tm(
        lambda q, i: jnp.sum(jax.ops.segment_sum(
            q, i, num_segments=d, indices_are_sorted=True)), qe, sorted_ids), e)
    # Lowering-diagnostic variants: if these differ materially from the rows
    # above, the bottleneck is XLA's choice of lowering, not the hardware.
    res["bwd alt: scatter-add 2D [n,k] ids"] = (tm(
        lambda v2, i2: jnp.sum(jnp.zeros(d, jnp.float32).at[i2].add(v2)),
        vals_j, ids_j), e)
    res["bwd alt: weighted bincount"] = (tm(
        lambda q, i: jnp.sum(jnp.bincount(i.reshape(-1), weights=q, length=d)),
        qe, ids_j), e)
    # Small-table gather: same 33.5M lookups, 1024-entry (4KB) table.  If
    # this is fast while the 4MB-table row is slow, gathers are cache/HBM
    # bound (layout fixes help); if both are slow, the lowering is serial
    # per element (only an in-kernel gather helps).
    small = jnp.asarray(rng.standard_normal(1024).astype(np.float32))
    rows_small = jnp.asarray(((order // k) % 1024).astype(np.int32))
    res["gather small-table 33.5M from 4KB"] = (tm(
        lambda t, r: jnp.sum(jnp.take(t, r, axis=0)), small, rows_small), e)

    al = al_t = None
    try:
        from photon_tpu.ops.pallas_gather import (
            aligned_gather_products, aligned_segment_grad,
            build_aligned_layout, device_layout)
        lay = build_aligned_layout(ids, vals, d)
        al = device_layout(lay)
        smap = jnp.asarray(lay.slab_of_tile)
        lo = jnp.asarray(lay.lo)
        lvals = jnp.asarray(lay.vals)
        dup = jnp.asarray(lay.dup_map)
        t = tm(lambda w, s, l, v: jnp.sum(aligned_gather_products(w, s, l, v)),
               jnp.take(w, dup, axis=0).reshape(-1, 128), smap, lo, lvals)
        res[f"pallas aligned gather (pad {lay.padding_factor:.2f}x)"] = (
            t, lay.padded_entries)
        res["dup-gather w[dup_map]"] = (tm(
            lambda w, m: jnp.sum(jnp.take(w, m, axis=0)), w, dup), dup.size)
        # The round-4 production gradient kernel: dz[rows] gather + Pallas
        # position reduce + dictionary segment-sum (vs "bwd fast" above,
        # whose segment-sum runs over all E entries).
        res["bwd pallas: aligned_segment_grad"] = (tm(
            lambda u: jnp.sum(aligned_segment_grad(u, al, d, interpret=False)),
            u), lay.padded_entries)
        # The transposed (row-dictionary) layout: same kernel runs the
        # FORWARD — margins as per-row sums (vs "fwd: gather+rowsum" above).
        from photon_tpu.ops.pallas_gather import build_row_aligned_layout

        lay_t = build_row_aligned_layout(ids, vals)
        al_t = device_layout(lay_t)
        res[f"fwd pallas: aligned margins (pad {lay_t.padding_factor:.2f}x)"] = (
            tm(lambda w: jnp.sum(aligned_segment_grad(w, al_t, n, interpret=False)),
               w), lay_t.padded_entries)
    except Exception as ex:  # noqa: BLE001
        print("pallas aligned kernels FAILED:", str(ex)[:200])

    # End-to-end: the three production value_and_grad paths (env-pinned so
    # the measured routing is the named one, not the auto measurement).
    import os

    from photon_tpu.core.objective import GlmObjective, RegularizationContext
    from photon_tpu.data.batch import SparseBatch, attach_feature_major

    batch = SparseBatch(ids_j, vals_j, jnp.asarray((rng.random(n) < 0.5).astype(np.float32)),
                        jnp.zeros(n, jnp.float32), jnp.ones(n, jnp.float32))
    obj = GlmObjective.create("logistic", RegularizationContext("l2", 1.0))
    prev = os.environ.get("PHOTON_SPARSE_GRAD")
    try:
        os.environ["PHOTON_SPARSE_GRAD"] = "autodiff"
        res["value_and_grad autodiff (r1 path)"] = (tm(
            lambda w: obj.value_and_grad(w, batch)[1].sum(), w), e)
        os.environ["PHOTON_SPARSE_GRAD"] = "fm"
        fast = attach_feature_major(batch)
        res["value_and_grad fast (fm path)"] = (tm(
            lambda w: obj.value_and_grad(w, fast)[1].sum(), w), e)
        if al is not None:
            os.environ["PHOTON_SPARSE_GRAD"] = "pallas"
            aligned = fast._replace(al=al)
            res["value_and_grad pallas bwd (r4)"] = (tm(
                lambda w: obj.value_and_grad(w, aligned)[1].sum(), w), e)
            if al_t is not None:
                aligned_fb = aligned._replace(al_t=al_t)
                res["value_and_grad pallas fwd+bwd (r4)"] = (tm(
                    lambda w: obj.value_and_grad(w, aligned_fb)[1].sum(), w), e)
    finally:
        if prev is None:
            os.environ.pop("PHOTON_SPARSE_GRAD", None)
        else:
            os.environ["PHOTON_SPARSE_GRAD"] = prev

    for name, (t, cnt) in res.items():
        print(f"{name:45s} {t*1e3:8.2f} ms   {cnt/t/1e9:7.2f} Gelem/s")


if __name__ == "__main__":
    main()
