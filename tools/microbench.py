"""Micro-benchmarks of the sparse-GLM primitive ops on the local accelerator.

Measures the candidate building blocks for the static-sparsity fast path
(VERDICT round-1 item #2): gathers, scatters, sorted segment sums, cumsum
tricks, and the Pallas aligned gather.  Run on the real chip to pick the
architecture; numbers land in photon_tpu/ops/KERNEL_NOTES.md.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np


def timeit(fn, *args, reps=10, warmup=2):
    fn_j = jax.jit(fn)
    for _ in range(warmup):
        out = fn_j(*args)
    np.asarray(jax.tree.leaves(out)[0])  # force full device sync via host copy
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn_j(*args)
    np.asarray(jax.tree.leaves(out)[0])
    return (time.perf_counter() - t0) / reps


def main():
    n, k, d = 1 << 20, 32, 1 << 18
    e = n * k  # 33.5M entries
    rng = np.random.default_rng(0)
    ids = rng.integers(1, d, size=(n, k), dtype=np.int32)
    vals = rng.standard_normal((n, k)).astype(np.float32)
    w = jnp.asarray(rng.standard_normal(d).astype(np.float32))
    ids_j = jnp.asarray(ids)
    vals_j = jnp.asarray(vals)

    flat = ids.reshape(-1)
    order = np.argsort(flat, kind="stable").astype(np.int32)
    sorted_ids = flat[order]
    perm = jnp.asarray(order)
    sorted_ids_j = jnp.asarray(sorted_ids)
    # segment boundaries: starts[f] = first entry index of feature f
    starts = np.searchsorted(sorted_ids, np.arange(d + 1)).astype(np.int32)
    starts_j = jnp.asarray(starts)
    u = jnp.asarray(rng.standard_normal(n).astype(np.float32))
    qe = jnp.asarray(rng.standard_normal(e).astype(np.float32))

    res = {}

    res["gather_w[ids] 33.5M from 1MB"] = timeit(
        lambda w, i: jnp.take(w, i, axis=0), w, ids_j)
    res["gather flat[perm] 33.5M from 134MB"] = timeit(
        lambda q, p: jnp.take(q, p, axis=0), qe, perm)
    res["scatter-add unsorted (grad today)"] = timeit(
        lambda q, i: jnp.zeros(d, jnp.float32).at[i.reshape(-1)].add(q), qe, ids_j)
    res["segment_sum sorted flag"] = timeit(
        lambda q, s: jax.ops.segment_sum(q, s, num_segments=d,
                                         indices_are_sorted=True),
        qe, sorted_ids_j)
    res["cumsum 33.5M + boundary diff"] = timeit(
        lambda q, st: jnp.diff(jnp.concatenate([jnp.zeros(1), jnp.cumsum(q)])[st]),
        qe, starts_j)
    # forward spread: w per entry in sorted order via diff/scatter-small/cumsum
    def spread(w, st):
        dw = jnp.diff(jnp.concatenate([jnp.zeros(1, w.dtype), w]))
        delta = jnp.zeros(e, w.dtype).at[st[:-1]].add(dw)
        return jnp.cumsum(delta)
    res["spread w->entries via cumsum"] = timeit(spread, w, starts_j)
    res["rowsum+loss elementwise"] = timeit(
        lambda v, i, u: (v * u[:, None]).sum(axis=1), vals_j, ids_j, u)
    res["u broadcast to entries [n,k]"] = timeit(
        lambda v, u: (v * u[:, None]).reshape(-1), vals_j, u)

    try:
        from photon_tpu.ops.pallas_gather import (
            aligned_gather_products, build_aligned_layout)
        lay = build_aligned_layout(ids, vals, d)
        gmap = jnp.asarray(lay.group_of_tile)
        lo = jnp.asarray(lay.lo)
        lvals = jnp.asarray(lay.vals)
        res[f"pallas aligned gather ({lay.padded_entries/1e6:.1f}M slots)"] = timeit(
            lambda w, g, lo, v: aligned_gather_products(w, g, lo, v),
            w, gmap, lo, lvals)
    except Exception as ex:  # noqa: BLE001
        res["pallas aligned gather"] = f"FAILED: {ex}"

    for name, t in res.items():
        if isinstance(t, str):
            print(f"{name:45s} {t}")
        else:
            print(f"{name:45s} {t*1e3:8.2f} ms   {e/t/1e9:7.2f} Gelem/s")


if __name__ == "__main__":
    main()
