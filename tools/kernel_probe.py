"""Probe Mosaic/v5e primitive throughput for the sparse fast-path design.

Each probe is a tiny Pallas kernel over ~134MB of f32 so the numbers expose
per-element op costs: copy (baseline), take_along_axis sublane gathers (8-deep
and 128-deep), in-kernel [128,128] transpose, lane roll+select, and a
masked-add accumulation loop.  Decides which router the crossing kernel uses.
"""

from __future__ import annotations

import sys
import time

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

S, L = 128, 128  # tile sublanes x lanes
N_TILES = 2048   # 2048 * 16K * 4B = 134 MB


def tm(fn, *args, reps=10):
    fj = jax.jit(fn)
    out = fj(*args)
    np.asarray(jax.tree.leaves(out)[0])
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fj(*args)
    np.asarray(jax.tree.leaves(out)[0])
    return (time.perf_counter() - t0) / reps


def run(name, kernel, extra_inputs=(), out_shape=None, interpret=False):
    x = jnp.asarray(
        np.random.default_rng(0).standard_normal((N_TILES * S, L)).astype(np.float32))
    nelem = x.size
    out_shape = out_shape or jax.ShapeDtypeStruct((N_TILES * S, L), jnp.float32)
    specs = [pl.BlockSpec((S, L), lambda i: (i, 0)) for _ in range(1 + len(extra_inputs))]
    try:
        f = pl.pallas_call(
            kernel,
            out_shape=out_shape,
            grid=(N_TILES,),
            in_specs=specs,
            out_specs=pl.BlockSpec((S, L), lambda i: (i, 0)),
            interpret=interpret,
        )
        t = tm(f, x, *extra_inputs)
        print(f"{name:42s} {t*1e3:8.2f} ms  {nelem/t/1e9:7.2f} Gelem/s")
    except Exception as ex:  # noqa: BLE001
        msg = str(ex).split(chr(10))[0][:120]
        print(f"{name:42s} FAILED: {type(ex).__name__}: {msg}")


def main():
    rng = np.random.default_rng(1)

    # baseline copy
    run("copy", lambda x_ref, o_ref: o_ref.__setitem__(..., x_ref[...]))

    # take_along_axis 8-deep per vreg (16 vregs per tile)
    idx8 = jnp.asarray(rng.integers(0, 8, size=(N_TILES * S, L), dtype=np.int32))
    def k_ta8(x_ref, i_ref, o_ref):
        for v in range(S // 8):
            sl = slice(v * 8, (v + 1) * 8)
            o_ref[sl, :] = jnp.take_along_axis(x_ref[sl, :], i_ref[sl, :], axis=0)
    run("take_along_axis 8-deep", k_ta8, (idx8,))

    # take_along_axis 128-deep over whole tile
    idx128 = jnp.asarray(rng.integers(0, S, size=(N_TILES * S, L), dtype=np.int32))
    def k_ta128(x_ref, i_ref, o_ref):
        o_ref[...] = jnp.take_along_axis(x_ref[...], i_ref[...], axis=0)
    run("take_along_axis 128-deep", k_ta128, (idx128,))

    # in-kernel transpose of the [128,128] tile
    def k_t(x_ref, o_ref):
        o_ref[...] = x_ref[...].T
    run("transpose 128x128", k_t)

    # lane roll + select, 16 radix-rolls per tile
    mask = jnp.asarray(rng.integers(0, 2, size=(N_TILES * S, L), dtype=np.int32))
    def k_roll(x_ref, m_ref, o_ref):
        x = x_ref[...]
        acc = jnp.zeros_like(x)
        m = m_ref[...]
        for g in range(16):
            acc = acc + jnp.where(m == (g % 2), pltpu.roll(x, g, 1), 0.0)
        o_ref[...] = acc
    run("lane roll+select x16", k_roll, (mask,))

    # masked-add: 8 select+adds per vreg into an [8,128] accumulator
    lo = jnp.asarray(rng.integers(0, 8, size=(N_TILES * S, L), dtype=np.int32))
    def k_acc(x_ref, lo_ref, o_ref):
        x = x_ref[...]
        lov = lo_ref[...]
        acc = jnp.zeros((8, L), jnp.float32)
        for v in range(S // 8):
            sl = slice(v * 8, (v + 1) * 8)
            xv = x[sl, :]
            lv = lov[sl, :]
            for t in range(8):
                acc = acc.at[t, :].add(jnp.sum(jnp.where(lv == t, xv, 0.0), axis=0))
        o_ref[...] = jnp.broadcast_to(acc, (S, L)).reshape(S, L)
    run("masked-add 8-way per vreg", k_acc, (lo,))

    # MXU routing: per-tile [128,128] @ [128,128] matmul
    p = jnp.asarray(rng.standard_normal((N_TILES * S, L)).astype(np.float32))
    def k_mm(x_ref, p_ref, o_ref):
        o_ref[...] = jnp.dot(x_ref[...], p_ref[...],
                             preferred_element_type=jnp.float32)
    run("matmul 128x128 per tile", k_mm, (p,))


if __name__ == "__main__":
    main()
