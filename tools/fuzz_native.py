"""Byte-mutation fuzz harness for the native host-side components.

Feeds mutated inputs to the three C++-backed readers — the LIBSVM parser,
the GAME Avro columnar decoder, and the mmap index store — in worker
SUBPROCESSES, so a segfault/abort in native code is observed as a worker
crash rather than killing the harness.  Graceful errors (ValueError /
OSError / clean parse) are the expected outcomes; any non-zero worker exit
is a finding and the offending input is preserved under /tmp.

Run: ``python tools/fuzz_native.py [mutants-per-component]`` (default 480;
the README's robustness claim was recorded at 800/480/480 clean).
"""

from __future__ import annotations

import os
import random
import shutil
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

BATCH = 80

LIBSVM_SEEDS = [
    "1 1:0.5 3:1.25 7:-2.5\n", "-1 2:1e-3 4:3.25\n", "0\n",
    "+1 5:+2.5 6:nan 8:inf\n", "1 9:0.1 # comment\n",
]

LIBSVM_WORKER = r'''
import sys
sys.path.insert(0, sys.argv[1])
from photon_tpu.native import libsvm_native
for path in sys.argv[2:]:
    try:
        libsvm_native.parse_file(path, False)
        print(path, "OK", flush=True)
    except ValueError:
        print(path, "VALERR", flush=True)
'''

AVRO_WORKER = r'''
import sys
sys.path.insert(0, sys.argv[1])
from photon_tpu.data.game_io import read_game_avro
bags = {"global": "global", "per_user": "per_user"}
for path in sys.argv[2:]:
    try:
        read_game_avro(path, bags, ["userId", "itemId"])
        print(path, "OK", flush=True)
    except Exception as ex:
        print(path, type(ex).__name__, flush=True)
'''

PIXS_WORKER = r'''
import sys
sys.path.insert(0, sys.argv[1])
from photon_tpu.data.index_map import OffHeapIndexMap
for path in sys.argv[2:]:
    try:
        m = OffHeapIndexMap.open(path)
        for probe in ("f3\x01t3", "zzz", "f1999\x01t4"):
            m.get_id(probe)
        for i in (0, 1, 1999, 2000):
            try: m.get_key(i)
            except (IndexError, OSError, ValueError, UnicodeDecodeError): pass
        print(path, "OK", flush=True)
    except (OSError, ValueError) as ex:
        print(path, type(ex).__name__, flush=True)
'''


def mutate(base: bytes, rng: random.Random) -> bytes:
    b = bytearray(base)
    for _ in range(rng.randint(1, 10)):
        if not b:
            break
        op, j = rng.random(), rng.randrange(len(b))
        if op < 0.5:
            b[j] = rng.randrange(256)
        elif op < 0.8:
            del b[j]
        else:
            b.insert(j, rng.randrange(256))
    if rng.random() < 0.25:
        b = b[: rng.randrange(len(b) + 1)]
    return bytes(b)


def run_component(name, worker, base_bytes, suffix, n_mutants, rng, td) -> int:
    crashes = 0
    done_mutants = 0
    batch_idx = 0
    while done_mutants < n_mutants:
        count = min(BATCH, n_mutants - done_mutants)
        paths = []
        for i in range(count):
            p = os.path.join(td, f"{name}_b{batch_idx}_m{i}{suffix}")
            with open(p, "wb") as f:
                f.write(mutate(base_bytes, rng))
            paths.append(p)
        out = subprocess.run(
            [sys.executable, "-c", worker, REPO] + paths,
            capture_output=True, text=True, timeout=600,
        )
        if out.returncode != 0:
            crashes += 1
            done = len(out.stdout.strip().splitlines())
            bad = paths[done] if done < len(paths) else None
            print(f"[{name}] CRASH rc={out.returncode} "
                  f"on {bad}: {out.stderr[-400:]}")
            if bad:
                kept = f"/tmp/fuzz_{name}_crash_{batch_idx}{suffix}"
                shutil.copy(bad, kept)
                print(f"[{name}] offending input kept at {kept}")
        done_mutants += count
        batch_idx += 1
    print(f"[{name}] {done_mutants} mutants, {crashes} crashing batches")
    return crashes


def main() -> int:
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 480
    rng = random.Random(0)
    import jax

    jax.config.update("jax_platforms", "cpu")
    from photon_tpu.data.fixtures import make_movielens_like
    from photon_tpu.data.game_io import write_game_avro
    from photon_tpu.data.index_map import OffHeapIndexMap, feature_key

    total = 0
    with tempfile.TemporaryDirectory() as td:
        svm_base = "".join(
            random.Random(1).choices(LIBSVM_SEEDS, k=40)
        ).encode()
        total += run_component(
            "libsvm", LIBSVM_WORKER, svm_base, ".libsvm", n, rng, td
        )

        avro_path = os.path.join(td, "base.avro")
        data, maps = make_movielens_like(
            n_users=12, n_items=10, mean_ratings=4
        )
        write_game_avro(avro_path, data, maps)
        total += run_component(
            "avro", AVRO_WORKER, open(avro_path, "rb").read(), ".avro",
            n, rng, td,
        )

        pixs_path = os.path.join(td, "base.pixs")
        keys = [feature_key(f"f{i}", f"t{i % 5}") for i in range(2000)]
        OffHeapIndexMap.build_file(pixs_path, keys, intercept=True).close()
        total += run_component(
            "pixs", PIXS_WORKER, open(pixs_path, "rb").read(), ".pixs",
            n, rng, td,
        )
    print(f"TOTAL crashing batches: {total}")
    return 1 if total else 0


if __name__ == "__main__":
    sys.exit(main())
