"""Probes for the fused chunk-local permutation kernel (the `xchg` plan).

KERNEL_NOTES.md (round-4 third window) reduces the sparse-GLM exchange
problem to one question: how fast can this chip run a STATIC permutation
of the E-element entry stream, given that the only fast data movers are
pallas lane-local gathers (3.4 Gelem/s), sublane-local gathers, XLA
strided transposes (14 GB/s), and sequential streams?  The planned
decomposition is chunk-Clos: arbitrary perm = chunk-local perm → (T ·
lane-perm · T) middle → chunk-local perm, with each chunk-local perm
itself a fused in-VMEM mixed-radix Benes.  These probes time the
candidate device pieces with the chained methodology
(tools/probe_permute.py 2026-07-31 note):

  a. tall-tile lane-gather (one stage at h=2048: refats the 9.9 ms/pass)
  b. in-kernel VMEM transpose [2048,128] -> [128,2048] (support + speed)
  c. fused 5-stage chunk kernel: lane-gather / transpose / lane-gather /
     transpose / lane-gather, all inside one pallas_call per [2048,128]
     chunk (the v2 fused chunk-perm; random per-stage routing is
     timing-equivalent to real routing)
  d. the middle-stage sandwich: XLA transpose + lane-gather pass + XLA
     transpose at the full-E shape
  e. sublane-gather stage (take_along_axis axis=0 within [8,128] groups)

Verdict rule: pipeline cost/direction ~= 2 x (c) + (d).  If that lands
under ~35 ms at E=2^25, the xchg kernel beats autodiff's 531 ms step by
enough to clear 10 steps/s end-to-end; between 35-120 ms it still beats
1.881 steps/s; above that the unfused v1 (13 HBM passes) is the only
win and is marginal.
"""

import argparse
import os
import sys

import numpy as np

from probe_common import CHAIN, LANES, timed as _time  # noqa: F401

# Repo root on the path: probe_scans times the PRODUCTION compensated
# scan from photon_tpu.ops.vperm, not a copy.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

CH = 2048  # chunk sublane-rows: chunk = [CH, 128] = 2^18 elements (1 MB)
INTERPRET = False  # --interpret: validate kernel logic off-TPU


def _pallas(*args, **kwargs):
    return pl.pallas_call(*args, interpret=INTERPRET, **kwargs)


def _rand_lane_idx(rows, rng):
    return jnp.asarray(
        np.argsort(rng.random((rows, LANES)), axis=1).astype(np.int32)
    )


def probe_tall_lane_gather(E):
    rng = np.random.default_rng(0)
    rows = E // LANES
    x = jnp.asarray(rng.random((rows, LANES)).astype(np.float32))
    idx = _rand_lane_idx(rows, rng)
    n_tiles = rows // CH

    def kernel(x_ref, i_ref, o_ref):
        o_ref[...] = jnp.take_along_axis(x_ref[...], i_ref[...], axis=1)

    f = _pallas(
        kernel,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((CH, LANES), lambda i: (i, 0)),
            pl.BlockSpec((CH, LANES), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((CH, LANES), lambda i: (i, 0)),
    )

    @jax.jit
    def g(x, idx):
        y = x
        for _ in range(CHAIN):
            y = f(y, idx)
        return y.sum()

    t = _time(g, x, idx) / CHAIN
    print(f"a. lane-gather h={CH}     E={E:>10,}  {t*1e3:8.2f} ms  "
          f"{E/t/1e6:9.1f} Melem/s")
    return t


def probe_vmem_transpose(E):
    rng = np.random.default_rng(1)
    rows = E // LANES
    n_tiles = rows // CH
    x = jnp.asarray(rng.random((rows, LANES)).astype(np.float32))

    def kernel(x_ref, o_ref):
        o_ref[...] = x_ref[...].T

    try:
        f = _pallas(
            kernel,
            out_shape=jax.ShapeDtypeStruct((n_tiles * LANES, CH), jnp.float32),
            grid=(n_tiles,),
            in_specs=[pl.BlockSpec((CH, LANES), lambda i: (i, 0))],
            out_specs=pl.BlockSpec((LANES, CH), lambda i: (i, 0)),
        )

        @jax.jit
        def g(x):
            y = x
            for _ in range(CHAIN // 2):
                z = f(y)  # [R,128] -> tiles of [128, CH]
                y = f(z.reshape(rows, LANES))  # keep shapes cycling
            return y.sum()

        t = _time(g, x) / CHAIN
        print(f"b. in-kernel transpose [{CH},128]  {t*1e3:8.2f} ms/pass  "
              f"{E/t/1e6:9.1f} Melem/s")
        return t
    except Exception as e:  # noqa: BLE001 - probe reports, never crashes
        print(f"b. in-kernel transpose   UNSUPPORTED: {type(e).__name__}: "
              f"{str(e)[:110]}")
        return None


def probe_fused_chunk(E):
    # 3 lane-gather stages + 2 in-VMEM transposes fused per chunk — the
    # v2 chunk-local Benes body.  Random per-stage routing times the same
    # as real routing (identical op sequence, data-independent).
    rng = np.random.default_rng(2)
    rows = E // LANES
    n_tiles = rows // CH
    x = jnp.asarray(rng.random((rows, LANES)).astype(np.float32))
    i1 = _rand_lane_idx(rows, rng)
    # Stage-2 indices live on the transposed [128, CH] view, one tile each.
    i2 = jnp.asarray(
        np.argsort(rng.random((n_tiles * LANES, CH)), axis=1).astype(np.int32)
    )
    i3 = _rand_lane_idx(rows, rng)

    def kernel(x_ref, i1_ref, i2_ref, i3_ref, o_ref):
        y = jnp.take_along_axis(x_ref[...], i1_ref[...], axis=1)
        y = y.T  # [128, CH] in VMEM
        y = jnp.take_along_axis(y, i2_ref[...], axis=1)
        y = y.T  # back to [CH, 128]
        o_ref[...] = jnp.take_along_axis(y, i3_ref[...], axis=1)

    try:
        f = _pallas(
            kernel,
            out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
            grid=(n_tiles,),
            in_specs=[
                pl.BlockSpec((CH, LANES), lambda i: (i, 0)),
                pl.BlockSpec((CH, LANES), lambda i: (i, 0)),
                pl.BlockSpec((LANES, CH), lambda i: (i, 0)),
                pl.BlockSpec((CH, LANES), lambda i: (i, 0)),
            ],
            out_specs=pl.BlockSpec((CH, LANES), lambda i: (i, 0)),
        )

        @jax.jit
        def g(x, i1, i2, i3):
            y = x
            for _ in range(CHAIN):
                y = f(y, i1, i2, i3)
            return y.sum()

        t = _time(g, x, i1, i2, i3) / CHAIN
        print(f"c. fused 5-stage chunk   E={E:>10,}  {t*1e3:8.2f} ms  "
              f"{E/t/1e6:9.1f} Melem/s  (chunk-local arbitrary perm, fused)")
        return t
    except Exception as e:  # noqa: BLE001
        print(f"c. fused 5-stage chunk   UNSUPPORTED: {type(e).__name__}: "
              f"{str(e)[:110]}")
        return None


def probe_middle_sandwich(E):
    # Middle macro-stage: XLA transpose, lane-gather pass, XLA transpose.
    rng = np.random.default_rng(3)
    rows = E // LANES  # [rows, 128] -> T -> [128, rows]
    n_tiles = rows // CH
    x = jnp.asarray(rng.random((rows, LANES)).astype(np.float32))
    # Indices must be PER-TILE (each [128, CH] tile gathers within its
    # own 2048-wide window), not global 0..rows-1 — out-of-tile indices
    # would clamp and time a degenerate gather.
    idx = jnp.asarray(
        np.argsort(rng.random((LANES, n_tiles, CH)), axis=-1)
        .reshape(LANES, rows)
        .astype(np.int32)
    )

    def kernel(x_ref, i_ref, o_ref):
        o_ref[...] = jnp.take_along_axis(x_ref[...], i_ref[...], axis=1)

    # Lane-gather on the transposed view: tiles of [128, CH] columns.
    f = _pallas(
        kernel,
        out_shape=jax.ShapeDtypeStruct((LANES, rows), jnp.float32),
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((LANES, CH), lambda i: (0, i)),
            pl.BlockSpec((LANES, CH), lambda i: (0, i)),
        ],
        out_specs=pl.BlockSpec((LANES, CH), lambda i: (0, i)),
    )

    @jax.jit
    def g(x, idx):
        y = x
        for _ in range(CHAIN):
            z = jax.lax.optimization_barrier(y.T)  # [128, rows]
            z = f(z, idx)
            y = jax.lax.optimization_barrier(z.T)  # [rows, 128]
        return y.sum()

    try:
        t = _time(g, x, idx) / CHAIN
        print(f"d. T+lane-gather+T middle E={E:>10,}  {t*1e3:8.2f} ms  "
              f"{E/t/1e6:9.1f} Melem/s")
        return t
    except Exception as e:  # noqa: BLE001
        print(f"d. middle sandwich       FAILED: {type(e).__name__}: "
              f"{str(e)[:110]}")
        return None


def probe_sublane_gather(E):
    # take_along_axis along sublanes within [8,128] groups (the radix-8
    # stage; production _gather_kernel already uses this lowering).
    rng = np.random.default_rng(4)
    rows = E // LANES
    n_tiles = rows // CH
    x = jnp.asarray(rng.random((rows, LANES)).astype(np.float32))
    idx = jnp.asarray(
        rng.integers(0, 8, size=(rows, LANES)).astype(np.int32)
    )

    def kernel(x_ref, i_ref, o_ref):
        for s in range(CH // 8):
            sl = slice(s * 8, (s + 1) * 8)
            o_ref[sl, :] = jnp.take_along_axis(
                x_ref[sl, :], i_ref[sl, :], axis=0
            )

    try:
        f = _pallas(
            kernel,
            out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
            grid=(n_tiles,),
            in_specs=[
                pl.BlockSpec((CH, LANES), lambda i: (i, 0)),
                pl.BlockSpec((CH, LANES), lambda i: (i, 0)),
            ],
            out_specs=pl.BlockSpec((CH, LANES), lambda i: (i, 0)),
        )

        @jax.jit
        def g(x, idx):
            y = x
            for _ in range(CHAIN):
                y = f(y, idx)
            return y.sum()

        t = _time(g, x, idx) / CHAIN
        print(f"e. sublane-gather (r8)   E={E:>10,}  {t*1e3:8.2f} ms  "
              f"{E/t/1e6:9.1f} Melem/s")
        return t
    except Exception as e:  # noqa: BLE001
        print(f"e. sublane-gather (r8)   UNSUPPORTED: {type(e).__name__}: "
              f"{str(e)[:110]}")
        return None


def probe_scans(E):
    # The cumsum-reduce's per-step scan: plain f32 cumsum vs the
    # compensated (hi, lo) two-sum associative scan ops/vperm.py uses.
    rng = np.random.default_rng(5)
    x0 = jnp.asarray(rng.standard_normal(E).astype(np.float32))

    @jax.jit
    def plain(x):
        y = x
        s = jnp.float32(0)
        for _ in range(CHAIN):
            ps = jnp.cumsum(y)
            s = s + ps[-1]
            y = jax.lax.optimization_barrier(y + s * 1e-30)
        return s

    t = _time(plain, x0) / CHAIN
    print(f"f. plain f32 cumsum      E={E:>10,}  {t*1e3:8.2f} ms  "
          f"{E/t/1e6:9.1f} Melem/s")

    from photon_tpu.ops.vperm import _compensated_cumsum

    @jax.jit
    def comp(x):
        y = x
        s = jnp.float32(0)
        for _ in range(CHAIN):
            hi, lo = _compensated_cumsum(y)
            s = s + hi[-1] + lo[-1]
            y = jax.lax.optimization_barrier(y + s * 1e-30)
        return s

    t = _time(comp, x0) / CHAIN
    print(f"g. compensated cumsum    E={E:>10,}  {t*1e3:8.2f} ms  "
          f"{E/t/1e6:9.1f} Melem/s")


def probe_inkernel_repeat(E):
    # Stage-A fusion candidate: expand dz inside the chunk kernel via
    # jnp.repeat along lanes ([CH, 128/k] -> [CH, 128], k=32).
    k = 32
    rng = np.random.default_rng(6)
    rows = E // LANES
    n_tiles = rows // CH
    x = jnp.asarray(rng.random((rows, LANES // k)).astype(np.float32))

    def kernel(x_ref, o_ref):
        o_ref[...] = jnp.repeat(x_ref[...], k, axis=1)

    try:
        f = _pallas(
            kernel,
            out_shape=jax.ShapeDtypeStruct((rows, LANES), jnp.float32),
            grid=(n_tiles,),
            in_specs=[pl.BlockSpec((CH, LANES // k), lambda i: (i, 0))],
            out_specs=pl.BlockSpec((CH, LANES), lambda i: (i, 0)),
        )

        @jax.jit
        def g(x):
            s = jnp.float32(0)
            y = x
            for _ in range(CHAIN):
                s = s + f(y).sum()
                y = jax.lax.optimization_barrier(y + s * 1e-30)
            return s

        t = _time(g, x) / CHAIN
        print(f"h. in-kernel lane repeat E={E:>10,}  {t*1e3:8.2f} ms  "
              f"{E/t/1e6:9.1f} Melem/s (out elems)")
        return t
    except Exception as e:  # noqa: BLE001
        print(f"h. in-kernel lane repeat UNSUPPORTED: {type(e).__name__}: "
              f"{str(e)[:110]}")
        return None


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--entries", type=int, default=1 << 25)
    ap.add_argument("--interpret", action="store_true",
                    help="run kernels in interpret mode (correctness "
                    "check off-TPU; timings meaningless)")
    args = ap.parse_args()
    global INTERPRET
    INTERPRET = args.interpret
    E = args.entries
    print(f"backend={jax.default_backend()} devices={jax.devices()} E={E:,}")
    for probe in (
        probe_fused_chunk,       # the decision-maker runs first
        probe_scans,             # the cumsum-reduce's dominant unknown
        probe_middle_sandwich,
        probe_tall_lane_gather,
        probe_vmem_transpose,
        probe_sublane_gather,
        probe_inkernel_repeat,
    ):
        try:
            probe(E)
        except Exception as e:  # noqa: BLE001
            print(f"{probe.__name__} FAILED: {type(e).__name__}: "
                  f"{str(e)[:160]}")


if __name__ == "__main__":
    main()
