"""Pallas grid-overhead probe: the same kernel at varying tile heights.

Motivation: every pallas measurement in the round-4 windows clusters
around 400-560 Melem/s (~2 GB/s) regardless of what the kernel computes
— the production aligned_reduce, probe_permute's lane-gather, swap-stage
and one-hot rows all hit the same plateau, while plain XLA elementwise
sustains ~180 GB/s on the same chip.  A per-element cost that does not
depend on the computation points at per-GRID-STEP overhead (dispatch /
semaphore / DMA setup per tile), not bandwidth.  This probe times a
minimal copy-scale kernel and the benes swap-stage kernel over a sweep
of tile heights at fixed total size: if time/element falls as tiles get
taller, the production kernels' tile of 128 sublanes is leaving an
order of magnitude on the table and `TILE_SUBLANES` should rise.

Methodology: chained calls (each step's input is the previous output)
inside one jit + a host-fetched scalar, per tools/probe_permute.py's
2026-07-31 note — bare block_until_ready timings are not decision-grade
under the tunneled backend.
"""

import argparse

import numpy as np

from probe_common import CHAIN, LANES, timed as _time  # noqa: F401

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def copy_kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...] * jnp.float32(1.0000001)


def swap_kernel(x_ref, o_ref):
    x = x_ref[...]
    up = pltpu.roll(x, 32, axis=1)
    dn = pltpu.roll(x, LANES - 32, axis=1)
    lane = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    o_ref[...] = jnp.where((lane // 32) % 2 == 0, up, dn)


def sweep(kernel, name, E):
    x0 = jnp.asarray(np.random.rand(E // LANES, LANES).astype(np.float32))
    for h in (8, 32, 128, 512, 2048, 8192):
        rows = E // LANES
        if rows % h:
            continue
        n_tiles = rows // h
        try:
            f = pl.pallas_call(
                kernel,
                out_shape=jax.ShapeDtypeStruct(x0.shape, x0.dtype),
                grid=(n_tiles,),
                in_specs=[pl.BlockSpec((h, LANES), lambda i: (i, 0))],
                out_specs=pl.BlockSpec((h, LANES), lambda i: (i, 0)),
            )

            @jax.jit
            def g(x, f=f):
                y = x
                for _ in range(CHAIN):
                    y = f(y)
                return y.sum()

            t = _time(g, x0) / CHAIN
            print(
                f"{name} h={h:<5} tiles={n_tiles:<6} {t*1e3:8.2f} ms  "
                f"{E/t/1e6:9.1f} Melem/s  {E*4*2/t/1e9:7.2f} GB/s r+w  "
                f"{t/n_tiles*1e6:7.1f} us/tile"
            )
        except Exception as e:  # noqa: BLE001 - probe reports, never crashes
            print(f"{name} h={h:<5} FAILED: {type(e).__name__}: {str(e)[:90]}")


def xla_baseline(E):
    x0 = jnp.asarray(np.random.rand(E // LANES, LANES).astype(np.float32))

    @jax.jit
    def g(x):
        y = x
        for _ in range(CHAIN):
            # Barrier per step: without it XLA fuses the chain into one
            # HBM pass (or folds to a single multiply) and /CHAIN
            # under-reports ~CHAIN-fold (probe_common methodology note).
            y = jax.lax.optimization_barrier(y * jnp.float32(1.0000001))
        return y.sum()

    t = _time(g, x0) / CHAIN
    print(f"xla elementwise baseline       {t*1e3:8.2f} ms  "
          f"{E/t/1e6:9.1f} Melem/s  {E*4*2/t/1e9:7.2f} GB/s r+w")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--entries", type=int, default=1 << 25)
    args = ap.parse_args()
    E = args.entries
    print(f"backend={jax.default_backend()} devices={jax.devices()} E={E:,}")
    xla_baseline(E)
    sweep(copy_kernel, "pallas copy", E)
    sweep(swap_kernel, "pallas swap", E)


if __name__ == "__main__":
    main()
