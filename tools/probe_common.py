"""Shared harness for the TPU primitive probes.

Methodology (decision-grade under the tunneled backend, per
ops/KERNEL_NOTES.md round-4 third window): bare ``block_until_ready``
timings of repeated identical calls are NOT trustworthy — an E-element
gather "ran" at 3× the HBM roofline.  Every probe therefore CHAINS its
op ``CHAIN`` times inside one jit with a data dependency per step (no
step can be cached or elided) and ``float()``-fetches the final scalar
host-side; report median wall time / CHAIN.

XLA fusion caveat: chains of fusible elementwise ops must insert
``jax.lax.optimization_barrier`` per step, or XLA collapses the chain
into one pass and the /CHAIN division under-reports ~CHAIN-fold.
Pallas calls and data-movement ops with distinct index operands are
opaque enough already.
"""

import os
import time

import numpy as np

import jax

# The axon site registration dials the TPU tunnel even when
# JAX_PLATFORMS=cpu is exported; the config update is the override that
# sticks (same guard as bench.py / tests/conftest.py).
if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
    jax.config.update("jax_platforms", "cpu")

CHAIN = 8
LANES = 128


def timed(fn, *args, reps=5):
    """Median wall seconds of ``fn(*args)`` with host-fetched result."""
    out = fn(*args)
    float(np.asarray(out).ravel()[0])
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn(*args)
        float(np.asarray(out).ravel()[0])
        ts.append(time.perf_counter() - t0)
    return float(np.median(ts))
