"""Summarize a tools/tpu_day_out/ evidence pack into a markdown table.

Run after a hardware window: parses every bench JSON line and probe
table in the pack, prints a KERNEL_NOTES-ready markdown summary plus
the raw probe rows, and flags files that errored or never produced a
metric (evidence of a mid-window tunnel drop or a lowering failure).
"""

import glob
import json
import os
import re
import sys


def main(out_dir="tools/tpu_day_out"):
    rows = []
    missing = []
    for path in sorted(glob.glob(os.path.join(out_dir, "*.txt"))):
        name = os.path.basename(path)
        text = open(path, errors="replace").read()
        metrics = re.findall(r'^\{"metric".*\}$', text, re.M)
        if metrics:
            for m in metrics:
                try:
                    d = json.loads(m)
                except json.JSONDecodeError:
                    continue
                det = d.get("detail", {})
                rows.append((
                    name, d.get("metric"), d.get("value"), d.get("unit"),
                    det.get("kernel"), det.get("platform"),
                    det.get("pct_hbm_roofline"),
                ))
        elif name.startswith(("02_", "03_", "04_", "06_", "09_")):
            tail = text.strip().splitlines()[-3:] if text.strip() else []
            missing.append((name, " | ".join(t[:90] for t in tail)))

    if rows:
        print("| file | metric | value | unit | kernel | platform | %roof |")
        print("|---|---|---|---|---|---|---|")
        for r in rows:
            print("| " + " | ".join(str(x) for x in r) + " |")
    for path in sorted(glob.glob(os.path.join(out_dir, "0[578]_*.txt"))):
        print(f"\n== {os.path.basename(path)} ==")
        for line in open(path, errors="replace").read().splitlines():
            if re.match(r"^[a-z]\. ", line) or line.startswith(
                ("backend=", "pallas ", "xla ")
            ):
                print(line)
    if missing:
        print("\nNO METRIC (drop / failure?):")
        for name, tail in missing:
            print(f"  {name}: {tail}")


if __name__ == "__main__":
    main(*sys.argv[1:])
