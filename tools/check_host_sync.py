#!/usr/bin/env python3
"""Guard against host-sync regressions in the GAME hot loop.

The device-resident score engines (``photon_tpu/game/residuals.py``) exist
so the descent loop's steady state never round-trips score data through the
host: the per-metric validation scalars are the ONE sanctioned sync per
outer iteration, and everything else stays on device (see the residuals
module docstring and README §"Device-resident residual engine").

This check greps the hot-loop modules for the calls that move device data
to host — ``np.asarray(``, ``jax.device_get(`` / ``.device_get(``,
``to_host(`` — and fails unless the call site is explicitly sanctioned
with a ``host-sync:`` marker comment on the same line or within the three
lines above it.  Adding a new host fetch to the hot loop therefore forces
a visible, reviewed annotation instead of silently reintroducing the
per-iteration transfer the engines removed.

Usage: ``python tools/check_host_sync.py [files...]`` (defaults to the
GAME hot-loop modules).  Exit code 0 = clean, 1 = unsanctioned syncs.
"""

from __future__ import annotations

import re
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent

# The GAME hot loop: the score engines, the descent loop that drives
# them, the coordinate train/score paths (whose per-train stats now stay
# on device — the descent boundary drain is the one sanctioned sync), and
# the checkpoint module (whose async staging pass is the one sanctioned
# off-hot-path fetch).  Legitimate host paths (the escape hatch, warm
# starts, model export) carry host-sync markers.
DEFAULT_FILES = (
    "photon_tpu/game/residuals.py",
    "photon_tpu/game/descent.py",
    "photon_tpu/game/coordinate.py",
    # The size-binned batched solve layer runs INSIDE the bin loop of
    # every RandomEffectCoordinate.train: a host fetch here would repeal
    # the one-sync-per-iteration contract for every random coordinate.
    "photon_tpu/game/batched_solve.py",
    # The matrix-free Newton-CG solver (ISSUE 14) is pure traced JAX — a
    # host fetch inside its outer/inner loops would not just break the
    # sync contract, it would break tracing; guarding it keeps a future
    # "quick debug print" from landing.
    "photon_tpu/core/optimizers/newton_cg.py",
    # The streamed (out-of-core) descent: score data moves host<->device
    # per CHUNK by design (that is the tier the data lives at), but every
    # such edge is a bulk streaming transfer carrying a marker — the only
    # blocking scalar sync allowed per outer iteration is the chunk-cursor
    # stats drain (descent.host_syncs), same contract as resident.
    "photon_tpu/game/tiles.py",
    "photon_tpu/game/stream_descent.py",
    # The disk tier of the out-of-core stream: pure host IO by design —
    # it must NEVER touch device data (a d2h inside a store read/write
    # would serialize the disk edge against the device stream it exists
    # to overlap).
    "photon_tpu/game/tile_store.py",
    "photon_tpu/fault/checkpoint.py",
    # The preemption/watchdog layers run ON the hot loop's thread (the
    # boundary checks) or beside it (the heartbeat thread): neither may
    # ever fetch device data — a watchdog that syncs would BE the stall.
    "photon_tpu/fault/preemption.py",
    "photon_tpu/fault/watchdog.py",
    # The online scoring service: every served batch is allowed exactly
    # ONE d2h (the response egress, serving.host_syncs) and the host work
    # at request ingest (staging/key-join on caller-owned numpy); both
    # carry markers.  Anything else in the serving hot path would add a
    # per-request round-trip the latency budget cannot absorb.
    "photon_tpu/serving/scorer.py",
    "photon_tpu/serving/batcher.py",
    # The fleet tier above the scorer: the router moves requests between
    # host queues (its only sanctioned fetches are the explicit parity
    # oracle), the transport is pure wire/host IO, and the fleet assembly
    # never touches device data at all.  A d2h anywhere here would add a
    # per-request round-trip the serving latency budget cannot absorb.
    "photon_tpu/serving/router.py",
    "photon_tpu/serving/transport.py",
    "photon_tpu/serving/fleet.py",
    # The self-healing tier (ISSUE 13): the supervisor is pure host-side
    # control whose only sanctioned fetches are the probe-oracle parity
    # comparisons; the subprocess-replica parent side is frames + numpy,
    # with the one sanctioned fetch at artifact publish (model tables to
    # host once per published version).
    "photon_tpu/serving/supervisor.py",
    "photon_tpu/serving/replica_proc.py",
    # The online-learning loop (ISSUE 15): ingest, delta, and the refresh
    # orchestration are pure host control — the sanctioned device edges
    # are inside the estimator/descent/serving layers it drives.  A d2h
    # here would serialize the refresh against the serving path it is
    # supposed to leave untouched.
    "photon_tpu/online/feed.py",
    "photon_tpu/online/delta.py",
    "photon_tpu/online/service.py",
    # The observability plane (ISSUE 16): tracing, live metrics, SLO
    # burn rates, and flight-recorder collection are pure host-side
    # bookkeeping over plain dicts — an observer that fetched device
    # data would BE the latency it exists to measure, and a d2h inside
    # the span/event path would charge every traced request for it.
    "photon_tpu/telemetry/distributed.py",
    "photon_tpu/telemetry/live.py",
    "photon_tpu/serving/observe.py",
    # Low-precision table/tile codecs (ISSUE 17): quantize/dequantize
    # and the parity-tolerance registry are host-side numpy over already
    # materialized arrays — the DEVICE decode lives in the scorer's
    # gather programs; a hidden d2h here would stall every tile publish.
    "photon_tpu/game/lowp.py",
    # Multi-model arena (ISSUE 18): slot allocation, gather-index
    # resolution, and slice publication are host bookkeeping; the one
    # device sync per scored batch lives in the scorer path and every
    # np.asarray site must carry its sanction — an extra d2h here would
    # tax EVERY tenant's request, not just one model's.
    "photon_tpu/serving/arena.py",
    # Partition-tolerant supervision (ISSUE 19): the lease ledger, the
    # seq/generation exchange, and the network-fault shim are pure host
    # wire/bookkeeping code — a d2h anywhere in them would put device
    # latency inside the lease/ping/fencing paths whose TIMING is the
    # contract under test.
    "photon_tpu/serving/netfault.py",
    "photon_tpu/serving/supervisor.py",
)

SYNC_PATTERN = re.compile(
    r"\bnp\.asarray\s*\(|jax\.device_get\s*\(|\bdevice_get\s*\(|\bto_host\s*\("
)
MARKER = "host-sync:"
# Lines above a call site that may carry the sanction marker.
MARKER_WINDOW = 3


def check_file(path: Path) -> list[tuple[int, str]]:
    """Unsanctioned sync call sites in ``path`` as (line_number, line)."""
    lines = path.read_text().splitlines()
    violations = []
    for i, line in enumerate(lines):
        stripped = line.strip()
        if stripped.startswith("#") or not SYNC_PATTERN.search(line):
            continue
        window = lines[max(0, i - MARKER_WINDOW): i + 1]
        if not any(MARKER in w for w in window):
            violations.append((i + 1, stripped))
    return violations


def main(argv: list[str]) -> int:
    files = [Path(a) for a in argv] if argv else [
        REPO / rel for rel in DEFAULT_FILES
    ]
    failed = False
    for path in files:
        for lineno, line in check_file(path):
            failed = True
            print(f"{path}:{lineno}: unsanctioned host sync: {line}")
    if failed:
        print(
            "\nThe GAME hot loop must not fetch device data to host outside "
            "the sanctioned sync points (the per-metric validation scalars "
            "and the explicit host escape hatches).  If this sync is "
            "intentional, annotate the call site with a `# host-sync: "
            "<why>` comment within the three lines above it; see the "
            "photon_tpu/game/residuals.py module docstring and the README "
            "residual-engine section for the residency contract."
        )
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
