"""Pre-build the auto-selection probe's exchange routes into the disk
cache, so a TPU-window auto-mode headline run (bench.py with
PHOTON_SPARSE_GRAD unset) spends its first trace compiling — not tens
of host-seconds edge-coloring.

Replicates ops/sparse_grad_select._measure's EXACT probe construction
(deterministic rng(0) ids at the bench's full probe cap) and calls the
same build_xchg_aux entry point, which content-hashes the inputs —
identical inputs on the TPU host therefore hit these cache files.  Run
from the repo root on the host that will serve the window (the cache
dir defaults to the same root the window's bench run resolves).

Usage: python tools/precache_probe_routes.py [log2_e] [mode ...]
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main() -> None:
    import jax

    jax.config.update("jax_platforms", "cpu")
    from photon_tpu.ops.pallas_gather import load_or_build_aligned_layout
    from photon_tpu.ops.vperm import build_xchg_aux

    log2_e = int(sys.argv[1]) if len(sys.argv) > 1 else 25
    modes = sys.argv[2:] or ["aligned", "cumsum"]
    # Mirror _measure: e entries over d features, k = e // n.
    e, d = 1 << log2_e, 1 << 18
    n = 1 << (log2_e - 5)  # bench headline rows scale: k = 32
    k = max(e // max(n, 1), 1)
    n_probe = e // k
    rng = np.random.default_rng(0)
    flat_ids = rng.integers(0, d, size=e, dtype=np.int32)
    vals = rng.standard_normal(e).astype(np.float32)
    ids2d = flat_ids[: n_probe * k].reshape(n_probe, k)
    vals2d = vals[: n_probe * k].reshape(n_probe, k)
    print(f"probe shape: e=2^{log2_e} d=2^18 n={n_probe} k={k}")
    layout = None
    for mode in modes:
        os.environ["PHOTON_XCHG_REDUCE"] = mode
        if mode != "cumsum" and layout is None:
            t0 = time.perf_counter()
            layout = load_or_build_aligned_layout(ids2d, vals2d, d)
            print(f"layout build: {time.perf_counter() - t0:.1f}s")
        t0 = time.perf_counter()
        build_xchg_aux(
            layout if mode != "cumsum" else None, ids2d, d, vals=vals2d
        )
        print(f"route ({mode}): {time.perf_counter() - t0:.1f}s "
              "(cached for the next run)")


if __name__ == "__main__":
    main()
