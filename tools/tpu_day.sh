#!/usr/bin/env bash
# TPU-day evidence pack: run the moment the tunneled chip answers.
#
# Produces, under tools/tpu_day_out/ (in RUN ORDER — unmeasured first,
# so a mid-window tunnel drop costs only re-confirmations):
#   00_probe.txt            backend probe (subprocess-guarded, bounded)
#   08_probe_blocklocal.txt vperm primitive lowering/timing (FIRST —
#                           validates the xchg kernel's Mosaic pieces)
#   09_headline_xchg_*.txt  the UNMEASURED vperm-exchange headline, then
#   09_headline_auto.txt    auto mode (correctness-gates xchg on-device)
#   07_probe_tiles.txt      pallas grid-overhead sweep (never completed)
#   05_probe_permute.txt    chained primitive table (re-confirmation)
#   01_microbench2.txt      primitive table (never completed on TPU)
#   02_headline_*.txt       per-kernel headline re-confirmations + bf16 +
#                           zipf + fused variants
#   03_configs.txt          bench configs 1-5 (quality anchors)
#   04_stream_scale.txt     streaming-ingestion proof
#
# Every step is individually timeout-bounded so a mid-run tunnel drop
# cannot hang the pack; partial output is still evidence.  Run from the
# repo root: bash tools/tpu_day.sh
set -u
cd "$(dirname "$0")/.."
OUT=tools/tpu_day_out
mkdir -p "$OUT"

# Fresh probe (bench.py caches a cpu-fallback verdict for 15 min; clear it).
rm -f "${TMPDIR:-/tmp}/photon_bench_backend_probe.json"
echo "== probe =="
# Gate on the resolved backend, not on output text: JAX's failure warnings
# mention "tpu" too, and a CPU-only pack must never masquerade as TPU
# evidence.
timeout 300 python -c "
import jax
print(jax.devices())
print('BACKEND=' + jax.default_backend())
" > "$OUT/00_probe.txt" 2>&1
if ! grep -q "^BACKEND=\(tpu\|axon\)" "$OUT/00_probe.txt"; then
    echo "no TPU backend resolved; pack aborted (see $OUT/00_probe.txt)"
    exit 1
fi

# PRIORITY ORDER (windows last ~8-13 minutes and drop mid-pack — both
# round-4 windows did): bank UNMEASURED things first, re-confirm known
# numbers later.  As of the 2026-07-31 window all three kernel headlines
# are banked on hardware (autodiff 1.881 / pallas 1.63 / fm 1.124); the
# unmeasured items are now (a) the static-permutation design's primitive
# table (probe_permute — decides the `benes` kernel design) and (b)
# microbench2's gather/scatter primitive rows (it has never completed on
# TPU; both windows dropped before it finished).
# Every run pins ALL PHOTON_* knobs it does not intend to vary, so an
# operator's ambient exports cannot contaminate the labeled files.
BASE="PHOTON_SPARSE_MARGIN= PHOTON_BENCH_DTYPE=float32 PHOTON_BENCH_SKEW=uniform PHOTON_BENCH_FUSED=0"

# Third-window (2026-07-31 03:14) banked: the benes headline (0.168
# steps/s, refuted) and the chained probe_permute table.  Remaining
# unmeasured items lead; everything below them is re-confirmation.

# Windows run 8-25 minutes: the xchg headlines are the round's decisive
# numbers, so a SHORT lowering probe gates them and everything else
# waits.  Routes for every xchg variant are pre-cached on this host
# (.photon_route_cache), so each headline run skips straight to compile
# + measure.

echo "== probe_blocklocal quick (vperm lowering gate) =="
if [ -f tools/probe_blocklocal.py ]; then
    timeout 420 python -u tools/probe_blocklocal.py \
        > "$OUT/08_probe_blocklocal.txt" 2>&1
fi

echo "== headline: xchg (UNMEASURED vperm-exchange kernel) =="
# The cumsum/balanced variant first: fewest passes, expected winner.
env $BASE PHOTON_SPARSE_GRAD=xchg PHOTON_XCHG_REDUCE=cumsum \
    timeout 900 python bench.py --headline-only \
    > "$OUT/09_headline_xchg_cumsum.txt" 2>&1
env $BASE PHOTON_SPARSE_GRAD=xchg PHOTON_XCHG_REDUCE=aligned \
    timeout 900 python bench.py --headline-only \
    > "$OUT/09_headline_xchg_aligned.txt" 2>&1
# Half-width exchange payload on the cumsum variant.
env $BASE PHOTON_SPARSE_GRAD=xchg PHOTON_XCHG_REDUCE=cumsum \
    PHOTON_XCHG_DTYPE=bfloat16 \
    timeout 900 python bench.py --headline-only \
    > "$OUT/09_headline_xchg_cumsum_bf16.txt" 2>&1
# Warm re-run of the cumsum variant (compile-cache hit check).
env $BASE PHOTON_SPARSE_GRAD=xchg PHOTON_XCHG_REDUCE=cumsum \
    timeout 900 python bench.py --headline-only \
    > "$OUT/09_headline_xchg_cumsum_warm.txt" 2>&1
# Auto mode with the xchg candidate: the selection probe correctness-
# gates the Mosaic kernels on-device before timing, so this run also
# validates xchg against the oracle at the true shape.
env $BASE timeout 1200 python bench.py --headline-only \
    > "$OUT/09_headline_auto.txt" 2>&1

echo "== probe_tiles (pallas grid-overhead sweep — never completed) =="
timeout 1200 python -u tools/probe_tiles.py > "$OUT/07_probe_tiles.txt" 2>&1

echo "== probe_permute (chained re-confirmation) =="
timeout 1200 python -u tools/probe_permute.py > "$OUT/05_probe_permute.txt" 2>&1

echo "== microbench2 (never completed on TPU) =="
timeout 900 python -u tools/microbench2.py > "$OUT/01_microbench2.txt" 2>&1

echo "== headline: per kernel (banked 2026-07-30/31 — re-confirmation) =="
for pass in cold warm; do
    env $BASE PHOTON_SPARSE_GRAD=pallas \
        timeout 900 python bench.py --headline-only \
        > "$OUT/02_headline_pallas_${pass}.txt" 2>&1
done
# Full-pallas pipeline (forward margins through the transposed layout).
env $BASE PHOTON_SPARSE_GRAD=pallas PHOTON_SPARSE_MARGIN=pallas \
    timeout 900 python bench.py --headline-only \
    > "$OUT/02_headline_pallas_fwd_warm.txt" 2>&1

for kernel in fm autodiff; do
    for pass in cold warm; do
        env $BASE PHOTON_SPARSE_GRAD=$kernel \
            timeout 900 python bench.py --headline-only \
            > "$OUT/02_headline_${kernel}_${pass}.txt" 2>&1
    done
done
# bf16 value storage delta on the autodiff kernel (the measured default).
env $BASE PHOTON_SPARSE_GRAD=autodiff PHOTON_BENCH_DTYPE=bfloat16 \
    timeout 900 python bench.py --headline-only \
    > "$OUT/02_headline_autodiff_bf16.txt" 2>&1
# Skewed-ids variant: the aligned layout's robustness case.
env $BASE PHOTON_SPARSE_GRAD=pallas PHOTON_BENCH_SKEW=zipf \
    timeout 900 python bench.py --headline-only \
    > "$OUT/02_headline_pallas_zipf_warm.txt" 2>&1
# Fused dispatch: all reps in one device program (lax.scan) — isolates the
# ~9 ms/call tunnel dispatch overhead from true device-side step time.
for kernel in autodiff pallas; do
    env $BASE PHOTON_SPARSE_GRAD=$kernel PHOTON_BENCH_FUSED=1 \
        timeout 900 python bench.py --headline-only \
        > "$OUT/02_headline_${kernel}_fused.txt" 2>&1
done

echo "== configs 1-5 =="
: > "$OUT/03_configs.txt"
for c in 1 2 3 4 5; do
    timeout 900 python bench.py --config "$c" >> "$OUT/03_configs.txt" 2>&1
done

echo "== stream-scale =="
timeout 3600 python bench.py --stream-scale > "$OUT/04_stream_scale.txt" 2>&1
# Streamed xchg (round-5: per-file cached layouts; first pass builds +
# caches the routes, the timed passes then measure the streamed kernel
# on-chip — the config-5 fast-kernel story's first hardware number).
env PHOTON_SPARSE_GRAD=xchg PHOTON_XCHG_REDUCE=cumsum \
    timeout 3600 python bench.py --stream-scale \
    > "$OUT/04_stream_scale_xchg.txt" 2>&1

echo "pack complete: $OUT/"
grep -h '"metric"' "$OUT"/09_headline_*.txt "$OUT"/02_headline_*.txt \
    "$OUT/03_configs.txt" "$OUT/04_stream_scale.txt" 2>/dev/null | tail -24
