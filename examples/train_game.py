"""GAME walkthrough: fixed effect + per-user random effect + scoring.

The analog of the reference's ``GameTrainingDriver`` -> ``GameScoringDriver``
workflow (SURVEY.md §3.1/§3.3) on a synthetic per-user dataset: a global
model captures population-level feature weights while each user's random
effect personalizes on top of the shared scores (offsets).
"""

import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))


def main() -> None:
    from photon_tpu.drivers import score_game, train_game

    tmp = tempfile.mkdtemp(prefix="photon_example_game_")
    out = os.path.join(tmp, "game")

    # synthetic-game:<entities>:<rows/entity>:<fixed dim>:<re dim>:<n re>:<seed>
    spec = "synthetic-game:64:30:32:8:1:3"
    summary = train_game.run(train_game.build_parser().parse_args([
        "--backend", os.environ.get("PHOTON_EXAMPLE_BACKEND", "tpu"),
        "--input", spec,
        "--coordinate", "fixed:type=fixed,shard=global,reg_weights=0.1+1,max_iters=25",
        "--coordinate", "per_user:type=random,shard=re0,entity=re0,reg_weights=1,max_iters=15",
        "--descent-iterations", "2",
        "--validation-split", "0.25",
        "--output-dir", out,
    ]))
    print("\nbest validation metrics:", summary["best_metrics"])

    score_out = os.path.join(tmp, "scores")
    score_game.run(score_game.build_parser().parse_args([
        "--input", spec,
        "--model", os.path.join(out, "best_model"),
        "--evaluators", "AUC",
        "--output-dir", score_out,
    ]))
    with open(os.path.join(score_out, "metrics.json")) as f:
        print("scoring round-trip metrics:", json.load(f))
    print(f"\nartifacts: {out}/best_model/ (per-coordinate name/term Avro), "
          f"{score_out}/scores.txt")


if __name__ == "__main__":
    main()
