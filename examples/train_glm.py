"""Legacy-driver walkthrough: logistic GLM, lambda sweep, AUC model selection.

The analog of the reference's ``Driver`` workflow (SURVEY.md §3.2): read ->
normalize -> sweep regularization weights -> validate each -> save best.
Generates a small synthetic LIBSVM dataset so the script is self-contained.
"""

import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

import numpy as np


def make_libsvm(path: str, n: int, w: np.ndarray, seed: int = 0) -> None:
    rng = np.random.default_rng(seed)
    d = len(w)
    with open(path, "w") as f:
        for _ in range(n):
            fid = np.sort(rng.choice(np.arange(1, d + 1), size=8, replace=False))
            xv = rng.standard_normal(8)
            margin = float(w[fid - 1] @ xv)
            y = 1 if rng.random() < 1.0 / (1.0 + np.exp(-margin)) else -1
            f.write(f"{y} " + " ".join(f"{j}:{v:.5f}" for j, v in zip(fid, xv)) + "\n")


def main() -> None:
    from photon_tpu.drivers import train

    tmp = tempfile.mkdtemp(prefix="photon_example_")
    train_path = os.path.join(tmp, "train.libsvm")
    val_path = os.path.join(tmp, "val.libsvm")
    # One ground-truth model generates BOTH splits (train/val must share it).
    w_true = np.random.default_rng(42).standard_normal(64)
    make_libsvm(train_path, 4000, w_true, seed=0)
    make_libsvm(val_path, 1000, w_true, seed=1)

    out = os.path.join(tmp, "model")
    train.run(train.build_parser().parse_args([
        "--backend", os.environ.get("PHOTON_EXAMPLE_BACKEND", "tpu"),
        "--input", train_path,
        "--validation-input", val_path,
        "--task", "logistic_regression",
        "--optimizer", "lbfgs",
        "--reg-type", "l2",
        "--reg-weights", "0.1,1,10",       # the sweep shares ONE compiled program
        "--evaluators", "AUC,LOGISTIC_LOSS",
        "--max-iterations", "80",
        "--output-dir", out,
    ]))

    with open(os.path.join(out, "training_summary.json")) as f:
        summary = json.load(f)
    print("\nsweep results:")
    for entry in summary["sweep"]:
        print(f"  lambda={entry['lambda']:<6g} iters={entry['iterations']:<3d} "
              f"AUC={entry['metrics'].get('AUC', float('nan')):.4f} "
              f"({entry['convergence_reason']})")
    print(f"\nartifacts in {out}: best_model.avro, feature_index.json, "
          f"training_summary.json (incl. per-iteration 'states' trace)")


if __name__ == "__main__":
    main()
